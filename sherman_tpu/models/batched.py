"""Batched device-side tree operations — the TPU-native hot path.

Where the reference hides per-op RDMA latency with 8 coroutines per thread
(``Tree.cpp:1059-1122``) and doorbell-coalesced verb chains
(``Operation.cpp:351-481``), the TPU build amortizes everything by *batching*:
one jitted SPMD step carries thousands of keys per node through a full
descent (one gathered page read per level, ``Tree.cpp:429-458`` hot loop) and,
for inserts, applies every non-split write in a single owner-side scatter.

Consistency model (stronger than the reference, by construction):

- A step's reads all see ONE snapshot of the pool (the functional array the
  step was called with), so torn pages cannot occur *within* a step — the
  front/rear version protocol (``Tree.h:199-210``) remains on the pages for
  cross-driver/host interleavings and protocol parity.
- All writes of a step become visible atomically at the step boundary; this
  IS the write+unlock doorbell guarantee (``Operation.cpp:351-380``).
- Intra-batch conflicts are linearized deterministically by stable request
  order (a serial order exists: the (source, slot) order), which replaces the reference's
  hierarchical local-lock hand-over (``Tree.cpp:1124-1173``): requests to the
  same leaf are *combined* in one step instead of queueing on a ticket lock.

Slow paths (leaf full -> split, locked page, routing overflow) fail fast with
a per-key status and are retried through the host ``Tree`` path, mirroring
how the reference falls out of its fast path into lock-and-split code
(``Tree.cpp:922-963``).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import (ConfigError, KeyRangeError, ProtocolError,
                                ShermanError, StateError)
from sherman_tpu.obs import device as DEV
from sherman_tpu.obs import recorder as FR
from sherman_tpu.obs import slo as SLO
from sherman_tpu.config import DSMConfig, TreeConfig
from sherman_tpu.models.btree import META_ADDR
from sherman_tpu.ops import bits, layout, pallas_page
from sherman_tpu.parallel import dsm as D
from sherman_tpu.parallel import transport
from sherman_tpu.parallel.mesh import AXIS
from sherman_tpu.utils import journal as J

# Per-key insert status codes (reply of one insert step).
ST_INVALID = 0      # inactive slot (padding)
ST_APPLIED = 1      # written in this step
ST_SUPERSEDED = 2   # an earlier-ordered same-key request won AND applied
                    # (final: the winner's write is a legal concurrent
                    # overwrite of this one; losers of a non-applying
                    # winner get ST_RETRY instead)
ST_FULL = 3         # leaf full -> host split path
ST_LOCKED = 4       # page lock held (host split in flight) -> retry
ST_RETRY = 5        # routing overflow / descent incomplete -> retry
ST_BAD = 6          # failed sanity checks (not a level-0 page / fence)
ST_NOT_FOUND = 7    # delete: key absent (final)
ST_LOCK_TIMEOUT = 8  # host-side terminal: the key's page lock was STILL
                     # held by a LIVE lease when the insert round budget
                     # ran out — the op is REJECTED with this typed
                     # status instead of spinning unboundedly in the
                     # host fallback (dead leases are revoked by the
                     # in-loop probes every tcfg.lock_retry_rounds
                     # blocked rounds; see _recover_wedged_locks)

_PW = C.PAGE_WORDS


class DegradedError(ShermanError, RuntimeError):
    """Typed write rejection: the engine is in read-only degraded mode.

    Raised by every mutating engine entry point after unrecoverable
    data-plane damage (scrub-detected corruption that quarantine could
    not contain, or a failed lock revocation).  Searches keep being
    served; the documented exit is ``utils.checkpoint.restore`` into a
    fresh cluster (see README "Robustness")."""

    def __init__(self, reason: str):
        super().__init__(
            "engine degraded (read-only): write rejected — " + reason
            + "; recover via utils.checkpoint.restore")
        self.reason = reason


# degraded-mode gauge + lock-timeout counter (data-plane failure story)
_OBS_DEGRADED = obs.gauge("engine.degraded")
_OBS_LOCK_TIMEOUTS = obs.counter("engine.lock_timeouts")


def _slo_observe(op_class: str, ops: int, t0: float | None) -> None:
    """Attribute one host-path batch wall to its SLO op class (the
    amortized per-op latency model: a client op's completion latency IS
    its batch's wall).  ``t0`` None = a retry/chunk frame whose parent
    (or whose own chunks) already account the ops."""
    if t0 is not None and ops:
        SLO.observe(op_class, int(ops), time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# Descent: batch of keys walks root -> leaf, one gathered read per level.
# ---------------------------------------------------------------------------

def descend_spmd(pool, counters, khi, klo, root, active, *, cfg: DSMConfig,
                 iters: int, axis_name: str = AXIS, start=None,
                 stop_level: int = 0):
    """Walk each active key from ``root`` to its ``stop_level`` page
    (default: the leaf, level 0, in fence).  ``stop_level=1`` is the
    parent-maintenance descent (internal_page_store's target,
    Tree.cpp:980-987).

    Runs inside shard_map; khi/klo are this node's [B] key shard.  ``iters``
    is a static trip count (tree height + sibling-chase budget).  ``start``
    optionally seeds per-key start addresses (the index-cache fast path);
    keys then only need the sibling-chase/leaf hops from there.

    Returns (counters, addr [B], page [B, PW], done [B]).  done=False keys
    exhausted the budget (capacity overflow or deep chase): retry.

    Perf note: the loop carries ONLY (addr, done) — the leaf page is
    re-gathered once after the loop.  Carrying the [B, PAGE_WORDS] page
    through the loop costs a full-batch select per iteration, which
    dominates step time at large B.
    """
    B = khi.shape[0]
    if start is None:
        start = jnp.broadcast_to(jnp.asarray(root, jnp.int32), (B,))
    addr = start
    done = ~active
    # Single-node + gather_impl="pallas": the level's gather + in-page
    # pick run FUSED in one kernel (the page is searched in VMEM while
    # the next rows stream in; no [B, PAGE_WORDS] intermediate lands in
    # HBM between them).  Multi-node descents keep the XLA elementwise
    # pick after the exchange; their owner-side page reads still go
    # through the pallas snapshot kernel inside read_pages_spmd.
    fused = cfg.machine_nr == 1 and pallas_page.use_pallas(cfg)

    def advance(addr, done, nreads):
        # exact read accounting (DSM.cpp:17-21 counter semantics): one
        # read op per page actually fetched — the rows still descending
        nreads = nreads + jnp.sum((~done).astype(jnp.uint32))
        if fused:
            nxt, at_leaf, _, ok, _, _, _ = pallas_page.descent_round(
                pool, addr, khi, klo, ~done, stop_level=stop_level)
        else:
            pages, ok = D.read_pages_spmd(pool, addr, cfg=cfg,
                                          axis_name=axis_name,
                                          active=~done)
            lvl = layout.h_level(pages)
            chase = layout.needs_sibling_chase(pages, khi, klo)
            at_leaf = (lvl == stop_level) & ~chase
            nxt = jnp.where(chase, layout.h_sibling(pages),
                            layout.internal_pick_child(pages, khi, klo))
        step_ok = ok & ~done
        new_addr = jnp.where(step_ok & ~at_leaf, nxt, addr)
        new_done = done | (step_ok & at_leaf)
        return new_addr, new_done, nreads

    nreads = jnp.uint32(0)
    if cfg.machine_nr == 1:
        # Dynamic early exit: no collectives in the body, so a data-dependent
        # while_loop is legal; a fresh index-cache start exits after ~1 hop.
        def cond(st):
            it, _, done, _ = st
            return (it < iters) & jnp.any(~done)

        def bodyw(st):
            it, addr, done, nreads = st
            addr, done, nreads = advance(addr, done, nreads)
            return it + 1, addr, done, nreads

        _, addr, done, nreads = lax.while_loop(
            cond, bodyw, (0, addr, done, nreads))
    else:
        # SPMD: every node must run the SAME trip count (the body carries
        # all_to_all exchanges) — but it need not be the static budget:
        # a psum of the pending count is identical on every node, so a
        # while_loop on it exits uniformly as soon as the whole mesh is
        # done (with router seeds that is typically round 1-2, not the
        # full height+chase budget).  Rows already done post inactive
        # requests — not counted as reads.
        def pend_of(done):
            return lax.psum(jnp.sum((~done).astype(jnp.int32)), axis_name)

        def cond(st):
            it, _, _, _, pend = st
            return (it < iters) & (pend > 0)

        def body(st):
            it, addr, done, nreads, _ = st
            addr, done, nreads = advance(addr, done, nreads)
            return it + 1, addr, done, nreads, pend_of(done)

        _, addr, done, nreads, _ = lax.while_loop(
            cond, body, (0, addr, done, nreads, pend_of(done)))

    # one final gather yields the leaf pages for the done keys
    page, ok_f = D.read_pages_spmd(pool, addr, cfg=cfg, axis_name=axis_name,
                                   active=done & active)
    nreads = nreads + jnp.sum((done & active).astype(jnp.uint32))
    done = done & active & ok_f
    counters = counters.at[D.CNT_READ_OPS].add(nreads)
    counters = counters.at[D.CNT_READ_PAGES].add(nreads)
    return counters, addr, page, done


def search_routed_spmd(pool, counters, khi, klo, root, active, start, *,
                       cfg: DSMConfig, iters: int,
                       axis_name: str = AXIS):
    """Cache-hit search: one full-batch leaf read, then a COMPACTED
    straggler loop (any mesh size).

    ``start`` is the per-key seed address from the host index-cache probe
    (router.host_start): with a warm cache ~90%+ of keys finish in round 1
    (their seed IS their leaf).  The stragglers (bucket-boundary sibling
    chases, stale entries) are compacted into a small fixed buffer so later
    rounds gather S rows instead of B — full-batch rounds are what make a
    naive descent loop pay the whole batch's bandwidth per level.

    Perf notes (measured on v5e): the page gather is per-row latency-bound
    (~20-25 ns/row regardless of row width), so the step does exactly ONE
    full-batch gather.  Round 1 is leaf-only — seeds always satisfy
    ``page.lowest <= key`` (router invariant: buckets are only ever
    remapped to right-siblings whose ``lowest`` is the split key, so a
    seed can never land right of the key's leaf), and non-leaf seeds
    (cold router) fall into the compacted loop, which runs the full
    descent logic on S rows only.
    """
    counters, done, addr, found, vhi, vlo = _routed_resolve(
        pool, counters, khi, klo, active, start, iters=iters, cfg=cfg,
        axis_name=axis_name)
    return counters, done, found, vhi, vlo


def _routed_resolve(pool, counters, khi, klo, active, start, *, iters: int,
                    cfg: DSMConfig, axis_name: str = AXIS):
    """Walk every active key from its cache seed to its leaf.

    Shared core of the routed search and mixed steps: round 1 + compacted
    straggler loop as described in :func:`search_routed_spmd`.  Returns
    (counters, done, addr, found, vhi, vlo): ``addr`` is the key's leaf
    page (for owner-side applies), found/vhi/vlo its lookup result.

    The stragglers are compacted ONCE after round 1 and the loop runs
    entirely in the compacted [S] space (the set only shrinks — a row
    that resolved in round 1 never becomes a straggler later), with a
    single scatter of results back to [B] after the loop.  The previous
    shape re-compacted and scattered [B]-wide EVERY round, which
    measured ~41 ms of the 68 ms step at 2 M rows — 60% of the read
    path spent resolving ~3% of rows.  Rows beyond the S-slot buffer
    (cold-router floods) stay not-done; callers retry them through the
    full-descent path, same contract as the round budget.

    Multi-node meshes run the SAME shape per node shard: pages come
    through the bucket-routed read exchange (``D.read_pages_spmd``) —
    round 1 at the full step capacity, the straggler loop at an
    S-capacity exchange so straggler cost scales with miss count, not
    batch width (the reference's cache-hit path is O(1) reads per op at
    any cluster size, ``IndexCache.h:134-184``) — and the loop exits on
    a psum'd pending count so every node leaves together.
    """
    B = khi.shape[0]
    P = pool.shape[0]
    N = cfg.machine_nr
    S = max(min(1024, B), B // 16)
    max_rounds = iters * 4
    # gather_impl="pallas" on one node: each round is ONE fused kernel
    # (page stream + in-VMEM search, ops/pallas_page.descent_round) —
    # bit-identical outputs to the gather + elementwise composition.
    fused = N == 1 and pallas_page.use_pallas(cfg)

    if N == 1:
        def read(addrs, act, loop: bool):
            page = bits.addr_page(addrs)
            ok = act & (page >= 0) & (page < P)
            return pool[jnp.clip(page, 0, P - 1)], ok
    else:
        loop_cfg = dataclasses.replace(cfg, step_capacity=S)

        def read(addrs, act, loop: bool):
            return D.read_pages_spmd(
                pool, addrs, cfg=loop_cfg if loop else cfg,
                axis_name=axis_name, active=act)

    def advance(pg, ok, kh, kl):
        lvl = layout.h_level(pg)
        chase = layout.needs_sibling_chase(pg, kh, kl)
        at_leaf = ok & (lvl == 0) & ~chase
        nxt = jnp.where(chase, layout.h_sibling(pg),
                        layout.internal_pick_child(pg, kh, kl))
        f, vh, vl, _ = layout.leaf_find_key(pg, kh, kl)
        return at_leaf, nxt, f, vh, vl

    # round 1: full batch from the cache-seeded start; leaf-only logic
    # (no internal_pick_child on the full batch — stragglers descend in
    # the compacted loop below)
    if fused:
        # when chase is set the kernel's next address IS the sibling
        nxt1, leaf1, chase, ok, f, vh, vl = pallas_page.descent_round(
            pool, start, khi, klo, active)
        at_leaf = ok & leaf1
        sib1 = nxt1
    else:
        pg, ok = read(start, active, False)
        # NO optimization_barrier here: materializing the [B, PW] round-1
        # gather costs ~+10 ms at 2 M rows vs letting XLA fuse it into the
        # chase/level/find consumers (measured; the opposite tradeoff from
        # the apply path's snapshot)
        chase = layout.needs_sibling_chase(pg, khi, klo)
        at_leaf = ok & (layout.h_level(pg) == 0) & ~chase
        f, vh, vl, _ = layout.leaf_find_key(pg, khi, klo)
        sib1 = layout.h_sibling(pg)
    hit = active & at_leaf
    done = ~active | at_leaf
    found = hit & f
    vhi = jnp.where(found, vh, 0)
    vlo = jnp.where(found, vl, 0)
    addr = jnp.where(ok & chase, sib1, start)

    # one-time compaction; fill rows (sidx == B) start done
    sidx = jnp.nonzero(~done, size=S, fill_value=B)[0].astype(jnp.int32)
    valid = sidx < B
    ci = jnp.clip(sidx, 0, B - 1)
    s_kh, s_kl = khi[ci], klo[ci]
    s_addr = addr[ci]
    s_done = ~valid
    s_f = jnp.zeros(S, bool)
    s_vh = jnp.zeros(S, jnp.int32)
    s_vl = jnp.zeros(S, jnp.int32)

    if N == 1:
        def pend_of(s_done):
            return jnp.sum((~s_done).astype(jnp.int32))
    else:
        # uniform exit: every node sees the same cluster-wide pending
        # count (the loop body carries all_to_all exchanges)
        def pend_of(s_done):
            return lax.psum(jnp.sum((~s_done).astype(jnp.int32)), axis_name)

    def cond(st):
        it, pend = st[0], st[-1]
        return (it < max_rounds) & (pend > 0)

    def body(st):
        it, s_done, s_addr, s_f, s_vh, s_vl, loop_reads, _ = st
        loop_reads = loop_reads + jnp.sum((~s_done).astype(jnp.uint32))
        if fused:
            nxt, leafb, _, ok, f, vh, vl = pallas_page.descent_round(
                pool, s_addr, s_kh, s_kl, ~s_done)
            at_leaf = ok & leafb
        else:
            pg, ok = read(s_addr, ~s_done, True)
            ok = ok & ~s_done
            at_leaf, nxt, f, vh, vl = advance(pg, ok, s_kh, s_kl)
        fin = ok & at_leaf
        s_f = jnp.where(fin, f, s_f)
        s_vh = jnp.where(fin & f, vh, s_vh)
        s_vl = jnp.where(fin & f, vl, s_vl)
        s_done = s_done | fin
        s_addr = jnp.where(ok & ~at_leaf, nxt, s_addr)
        return (it + 1, s_done, s_addr, s_f, s_vh, s_vl, loop_reads,
                pend_of(s_done))

    (_, s_done, s_addr, s_f, s_vh, s_vl, loop_reads, _) = lax.while_loop(
        cond, body,
        (1, s_done, s_addr, s_f, s_vh, s_vl, jnp.uint32(0),
         pend_of(s_done)))

    # single scatter of the compacted results back to [B]
    res = valid & s_done
    tgt = jnp.where(res, sidx, B)
    done = done.at[tgt].set(True, mode="drop")
    found = found.at[tgt].set(s_f, mode="drop")
    vhi = vhi.at[tgt].set(jnp.where(s_f, s_vh, 0), mode="drop")
    vlo = vlo.at[tgt].set(jnp.where(s_f, s_vl, 0), mode="drop")
    addr = addr.at[tgt].set(s_addr, mode="drop")

    # round-1 gather (one page per active key) + every straggler-loop row
    n_reads = jnp.sum(active.astype(jnp.uint32)) + loop_reads
    counters = counters.at[D.CNT_READ_OPS].add(n_reads)
    counters = counters.at[D.CNT_READ_PAGES].add(n_reads)
    done = done & active
    return counters, done, addr, found & done, vhi, vlo


def search_spmd(pool, counters, khi, klo, root, active, start=None, *,
                cfg: DSMConfig, iters: int,
                axis_name: str = AXIS):
    """Batched ``Tree::search`` (Tree.cpp:405-458): pure one-sided reads.

    With ``start`` (host index-cache seeds), descent starts at the seeded
    page — normally the leaf itself (cache-hit path, Tree.cpp:415-427).
    Returns (done, found, vhi, vlo) per key.
    """
    counters, _, page, done = descend_spmd(
        pool, counters, khi, klo, root, active, cfg=cfg, iters=iters,
        axis_name=axis_name, start=start)
    found, vhi, vlo, _ = layout.leaf_find_key(page, khi, klo)
    return counters, done, found & done, vhi, vlo


# ---------------------------------------------------------------------------
# Owner-side leaf apply: the write fast path.
# ---------------------------------------------------------------------------

def leaf_apply_spmd(pool, locks, counters, inc, fresh=None, *,
                    cfg: DSMConfig, update_only: bool = False,
                    combine: bool = False):
    """Apply routed insert requests to this node's leaf pages.

    inc: dict of [M] arrays — active, addr (leaf), khi, klo, vhi, vlo.
    fresh: optional [F] int32 pre-allocated LOCAL page addrs (0 = no
    grant) enabling device-side leaf splits.
    Returns (pool, counters, status [M]) — plus a split log dict when
    ``fresh`` is given.

    ``update_only`` (static) compiles the steady-state fast kernel:
    requests whose key is NOT already present report ST_FULL (escalate
    to the general kernel with grants) instead of inserting, which drops
    the insert-rank/split machinery and shrinks the write-back to the 3
    words an update actually changes (packed version pair, vhi, vlo) —
    the update-heavy YCSB shape runs ~20% faster.

    Mirrors ``leaf_page_store`` (Tree.cpp:828-921): in-place update of an
    existing key, or insert into a free slot, with the single-entry
    write-back (only the touched 5-word entry is written).  Same-key
    requests are deduped (stable request order: lowest (source, slot)
    wins) — the intra-step linearization that replaces local-lock
    hand-over.

    ``combine`` (static) is HOCL-style write combining (the reference's
    local-lock-table handover, Tree.cpp:218-239): the lock verdict is
    consulted ONCE per page group (the sort's outer key is the page)
    and handed to every row of the group, instead of one lock-word
    gather per row.  Bit-identical by construction — all rows of a page
    hash to ONE lock word (``bits.lock_index`` is per-addr), so the
    per-row verdicts inside a group were always uniform; the only
    observable deltas are the lock-consult count and the
    ``CNT_COMBINE_*`` counter slots.  Deletes
    (:func:`leaf_delete_apply_spmd`) stay uncombined: their per-row
    verdict feeds a row-compacted CAS path with no group structure to
    ride.

    Splits (Tree.cpp:922-963, TPU-shaped): the first overflowing insert
    winner of a page (its in-page rank equals the page's free-slot count)
    becomes the page's *splitter* and is granted a fresh page; the owner
    sorts the LEAF_CAP slots + pending entry, writes the upper half to the
    fresh right sibling and rewrites the left page with fences/sibling
    updated — the B-link makes the split correct before any parent knows
    (the log lets the host insert parent entries lazily, which is why
    splits don't need the recursive ascent on-device).  Every other write
    to a splitting page retries next step: the split rewrites the whole
    page from the pre-step snapshot, so co-applying would be lost.
    """
    M = inc["addr"].shape[0]
    P = pool.shape[0]
    L = locks.shape[0]
    act = inc["active"]
    khi, klo = inc["khi"], inc["klo"]
    page_idx = bits.addr_page(inc["addr"])
    safe_page = jnp.clip(page_idx, 0, P - 1)
    # ONE materialized snapshot gather: pg feeds many consumers (fences,
    # liveness, find, versions); the barrier stops XLA rematerializing
    # the gather into consumer fusions (net-neutral at the 131 K-page
    # scale, insurance at larger pools where a duplicated gather costs
    # the full per-row latency again).  Reusing the descent's round-1
    # pages here instead was measured SLOWER (+24 ms at 2 M rows — the
    # materialized [B, PW] hint buffer costs more than the re-gather).
    # gather_impl="pallas": the explicit-DMA snapshot kernel's output IS
    # the materialized buffer — no barrier needed.
    use_pk = pallas_page.use_pallas(cfg)
    if use_pk:
        pg = pallas_page.gather_pages(pool, safe_page)     # [M, PW] snapshot
    else:
        pg = lax.optimization_barrier(pool[safe_page])     # [M, PW] snapshot

    lock_idx = bits.lock_index(inc["addr"], cfg.locks_per_node)
    if not combine:
        locked = locks[jnp.clip(lock_idx, 0, L - 1)] != 0

    sane = act & (page_idx >= 0) & (page_idx < P) \
        & (layout.h_level(pg) == 0) & layout.in_fence(pg, khi, klo) \
        & layout.page_consistent(pg)
    # combined mode defers the lock verdict to the per-group consult
    # below (sane rows enter the sort; their page-group head decides)
    ok_req = sane if combine else (sane & ~locked)

    found, _, _, fslot = layout.leaf_find_key(pg, khi, klo)
    if update_only:
        assert fresh is None, "update_only excludes the split path"
        freec = jnp.zeros(M, jnp.int32)  # unused: no insert ranking
    else:
        free = ~layout.leaf_slot_used(pg)                  # [M, CAP]
        cumfree = jnp.cumsum(free.astype(jnp.int32), axis=-1)
        freec = cumfree[:, -1]                             # page free slots

    # --- dedupe + insert-rank in ONE sorted pass ---------------------------
    # A single multi-operand lax.sort (stable) groups requests by
    # (page, key) and carries the original index / found / free-count
    # along — measured 4x cheaper than lexsort + per-array permutation
    # gathers, and it subsumes the old second sort for insert ranks: the
    # sort's outer key IS the page, so a segmented count over the sorted
    # order ranks each fresh-insert winner within its page (the scan-based
    # segment base replaces an O(B log B) searchsorted).
    # Dedup winner = first row of its group = lowest original index.  A
    # superseded loser is final ONLY when its winner applied (the winner's
    # write is then a legal concurrent overwrite of the loser's value); a
    # loser whose winner went to the split path (ST_FULL) must retry — the
    # acked write would otherwise be observably absent.
    idx0 = jnp.arange(M, dtype=jnp.int32)
    pk = jnp.where(ok_req, page_idx, P)
    if combine:
        # -- HOCL-style handover: one lock consult per page group -----
        # The sort already groups rows by page; carry the lock index
        # along, consult the lock word only at each group's head, and
        # hand the verdict down the group with a position-encoded
        # running max (same encoding as the dedup-winner broadcast
        # below).  Locked groups' rows fall out of ``sok`` exactly as
        # the per-row gather would have dropped them — same page ⇒
        # same lock word ⇒ uniform verdict — so everything downstream
        # (dedup, ranks, splits, write-back, statuses) is unchanged.
        sp, skhi, sklo, sidx, sfound, sfreec, slidx = lax.sort(
            (pk, bits._ux(khi), bits._ux(klo), idx0, found, freec,
             lock_idx), num_keys=3)
        sok_all = sp < P
        page_head_all = jnp.concatenate(
            [sok_all[:1], (sp[1:] != sp[:-1]) & sok_all[1:]])
        head_lw = locks[jnp.where(page_head_all,
                                  jnp.clip(slidx, 0, L - 1), 0)]
        head_locked = page_head_all & (head_lw != 0)
        encL = lax.associative_scan(
            jnp.maximum,
            jnp.where(page_head_all,
                      idx0 * 2 + head_locked.astype(jnp.int32), -1))
        locked_s = sok_all & ((encL & 1) == 1)
        sok = sok_all & ~locked_s
        u32c = lambda m: jnp.sum(m.astype(jnp.uint32))
        counters = counters.at[D.CNT_COMBINE_GROUPS].add(
            u32c(page_head_all))
        counters = counters.at[D.CNT_COMBINE_SAVED].add(
            u32c(sok_all) - u32c(page_head_all))
    else:
        sp, skhi, sklo, sidx, sfound, sfreec = lax.sort(
            (pk, bits._ux(khi), bits._ux(klo), idx0, found, freec),
            num_keys=3)
        sok = sp < P
    same_prev = jnp.concatenate([
        jnp.zeros(1, bool),
        (sp[1:] == sp[:-1]) & (skhi[1:] == skhi[:-1]) & (sklo[1:] == sklo[:-1])
        & sok[1:],
    ])
    winner_s = sok & ~same_prev
    ESCALATE = M + M  # update_only's not-found code, above any rank/split
    if update_only:
        # winners apply iff their key exists; not-found winners escalate
        applied_s = winner_s & sfound
        ins_code_s = jnp.full(M, ESCALATE, jnp.int32)
    else:
        need_ins_s = winner_s & ~sfound
        # rank among the page's fresh inserts: cum at row minus cum at the
        # page segment's head (cum_excl is nondecreasing, so a running max
        # over head-masked values yields the latest head's base)
        page_head = jnp.concatenate([jnp.ones(1, bool), sp[1:] != sp[:-1]])
        cum = jnp.cumsum(need_ins_s.astype(jnp.int32))
        cum_excl = cum - need_ins_s
        base = lax.associative_scan(
            jnp.maximum, jnp.where(page_head, cum_excl, -1))
        rank_s = cum_excl - base
        # a winner applies if it updates, or its insert rank fits the
        # page's free slots
        applied_s = winner_s & (sfound | (rank_s < sfreec))
        ins_code_s = rank_s
    # propagate the head's verdict to its losers with a position-encoded
    # running max (groups are contiguous, heads are winners)
    enc = lax.associative_scan(
        jnp.maximum,
        jnp.where(winner_s, idx0 * 2 + applied_s.astype(jnp.int32), -1))
    grp_winner_applied = (enc & 1) == 1
    # sorted-space verdicts: -4 loser whose winner did not apply (retry),
    # -3 dropped, -2 superseded-final, -1 winner-found (update),
    # 0 <= r < SPLIT_CODE winner insert rank, SPLIT_CODE + f granted
    # splitter using fresh slot f, ESCALATE update_only's key-absent.
    # Ranks are strictly below M (at most M requests per page), so M is a
    # safe static boundary for any batch geometry.
    SPLIT_CODE = M
    code_s = jnp.where(
        ~sok, -3,
        jnp.where(~winner_s, jnp.where(grp_winner_applied, -2, -4),
                  jnp.where(sfound, -1, ins_code_s)))
    if fresh is not None:
        F = fresh.shape[0]
        # the page's FIRST overflowing insert (rank == free count) splits
        splitter_s = need_ins_s & (rank_s == sfreec)
        sf_idx = jnp.cumsum(splitter_s.astype(jnp.int32)) - 1
        grant = fresh[jnp.clip(sf_idx, 0, F - 1)]
        granted_s = splitter_s & (sf_idx < F) & (grant != 0)
        code_s = jnp.where(granted_s, SPLIT_CODE + sf_idx, code_s)
    # un-sort via a 2-operand key-value sort (sidx is a permutation of
    # [0, M)): ~1 ms at 2 M rows on v5e vs ~15 ms for the equivalent
    # full-width scatter
    if combine:
        # carry the group verdict back to row space for the status line
        _, code, locked_i = lax.sort(
            (sidx, code_s, locked_s.astype(jnp.int32)), num_keys=1)
        locked = locked_i != 0
    else:
        _, code = lax.sort((sidx, code_s), num_keys=1)
    winner_upd = code == -1
    superseded = code == -2
    loser_retry = code == -4

    if update_only:
        splitter = jnp.zeros(M, bool)
        suppressed = jnp.zeros(M, bool)
        full = code == ESCALATE      # ST_FULL -> caller escalates to the
        applied = winner_upd         # general kernel (grants + inserts)
        slot = fslot
    else:
        splitter = (code >= SPLIT_CODE) & (code < ESCALATE)
        winner_ins = (code >= 0) & ~splitter
        rank = jnp.where(winner_ins, code, 0)
        have_slot = freec >= (rank + 1)

        if fresh is not None:
            has_split = jnp.zeros(P + 1, bool).at[
                jnp.where(splitter, safe_page, P)].set(True, mode="drop")
            page_splitting = has_split[safe_page]
        else:
            page_splitting = jnp.zeros(M, bool)

        # On a splitting page, updates and fitting inserts (rank < free
        # count) STILL apply — the split consumes the post-apply page, so
        # nothing is lost and the page splits exactly full.  Only inserts
        # ranked past the free slots retry (they land in the halves next
        # round).  Without this, an append-shaped workload funnels into
        # the rightmost leaf at ONE key per step.
        suppressed = winner_ins & page_splitting & ~have_slot
        full = winner_ins & ~have_slot & ~page_splitting
        applied = winner_upd | (winner_ins & have_slot)

        target = (rank + 1)[:, None]
        islot = jnp.argmax(cumfree >= target, axis=-1)
        slot = jnp.where(found, fslot, islot)

    # --- single-entry write-back scatter -----------------------------------
    # one-hot extract of the slot's old packed version pair
    # (take_along_axis is slow on TPU)
    ver_blk = pg[:, C.L_VER_W:C.L_VER_W + C.LEAF_CAP]
    slot_oh = jnp.arange(C.LEAF_CAP)[None, :] == slot[:, None]
    old_fv = (jnp.sum(jnp.where(slot_oh, ver_blk, 0), axis=-1)
              >> 16) & C.ENTRY_VER_MASK
    new_ver = (old_fv + 1) & C.ENTRY_VER_MASK
    new_ver = jnp.where(new_ver == 0, 1, new_ver)
    new_pair = layout.ver_pack(new_ver)

    # ONE fused scatter pass of exactly the entry words that change — the
    # reference single-entry write-back (Tree.cpp:914-921) writes the
    # LeafEntry only: page front/rear versions move on STRUCTURAL
    # rewrites (splits, internal rebuilds), not per-entry updates, and
    # the entry's own fver/rver pair carries the write's visibility.
    # Scatter cost is ~13.5 ms per word lane at 2 M rows on v5e, so lane
    # count is the write path's #1 knob: the 16/16-packed version pair
    # makes updates touch 3 words (version pair + value); inserts also
    # write the 2 key words.
    if update_only:
        ent = jnp.stack([new_pair, inc["vhi"], inc["vlo"]],
                        axis=-1)                           # [M, 3]
        lanes = (C.L_VER_W, C.L_VHI_W, C.L_VLO_W)
    else:
        ent = jnp.stack([new_pair, khi, klo, inc["vhi"], inc["vlo"]],
                        axis=-1)                           # [M, 5]
        lanes = (C.L_VER_W, C.L_KHI_W, C.L_KLO_W, C.L_VHI_W, C.L_VLO_W)
    if use_pk:
        # all lanes ride ONE kernel pass (per-row doorbell batch of
        # single-word DMAs) instead of one full-batch scatter per lane
        pool = pallas_page.writeback(pool, safe_page, slot, applied,
                                     ent, lanes)
    else:
        # the twin the parity fuzz pins IS the served path
        pool = pallas_page.writeback_xla(pool, safe_page, slot, applied,
                                         ent, lanes)

    # --- device-side splits (consume the POST-apply page) ------------------
    if fresh is not None:
        pool, counters, log = _leaf_split_apply(
            pool, counters, inc, splitter, code - SPLIT_CODE, fresh,
            safe_page, cfg=cfg)

    # --- status ------------------------------------------------------------
    status = jnp.full(M, ST_INVALID, jnp.int32)
    status = jnp.where(act, ST_BAD, status)
    status = jnp.where(act & sane & locked, ST_LOCKED, status)
    status = jnp.where(loser_retry | suppressed, ST_RETRY, status)
    status = jnp.where(superseded, ST_SUPERSEDED, status)
    status = jnp.where(full, ST_FULL, status)
    status = jnp.where(applied | splitter, ST_APPLIED, status)

    u32 = lambda m: jnp.sum(m.astype(jnp.uint32))
    counters = counters.at[D.CNT_WRITE_OPS].add(u32(applied))
    counters = counters.at[D.CNT_WRITE_WORDS].add(
        u32(applied) * jnp.uint32(3 if update_only
                                  else C.LEAF_ENTRY_WORDS))
    if fresh is not None:
        return pool, counters, status, log
    return pool, counters, status


def _leaf_pages(blk_khi, blk_klo, blk_vhi, blk_vlo, blk_live, ver, low_hi,
                low_lo, high_hi, high_lo, sibling):
    """Assemble [R] whole leaf pages from [R, LEAF_CAP] field blocks +
    [R] header words — the ONE place that knows the leaf wire layout as
    full pages (shared by the device split kernel and the bulk-load
    builder).  Dead slots are zeroed; fver/rver carry the liveness."""
    R = blk_khi.shape[0]
    CAP = C.LEAF_CAP
    page = jnp.zeros((R, _PW), jnp.int32)
    page = page.at[:, C.W_FRONT_VER].set(ver)
    page = page.at[:, C.W_REAR_VER].set(ver)
    page = page.at[:, C.W_SIBLING].set(sibling)
    page = page.at[:, C.W_LOW_HI].set(low_hi)
    page = page.at[:, C.W_LOW_LO].set(low_lo)
    page = page.at[:, C.W_HIGH_HI].set(high_hi)
    page = page.at[:, C.W_HIGH_LO].set(high_lo)
    lv = blk_live.astype(jnp.int32) * jnp.int32(layout.ver_pack(1))
    page = page.at[:, C.L_VER_W:C.L_VER_W + CAP].set(lv)
    z = lambda b: jnp.where(blk_live, b, 0)
    page = page.at[:, C.L_KHI_W:C.L_KHI_W + CAP].set(z(blk_khi))
    page = page.at[:, C.L_KLO_W:C.L_KLO_W + CAP].set(z(blk_klo))
    page = page.at[:, C.L_VHI_W:C.L_VHI_W + CAP].set(z(blk_vhi))
    page = page.at[:, C.L_VLO_W:C.L_VLO_W + CAP].set(z(blk_vlo))
    return page


def _leaf_split_apply(pool, counters, inc, splitter, fidx, fresh,
                      safe_page, *, cfg: DSMConfig):
    """Execute granted leaf splits in a compacted [F] buffer.

    splitter/fidx select granted rows and their fresh-page slots.  Reads
    the POST-apply page from ``pool`` (this step's fitting inserts and
    updates already landed, so the page splits exactly full and nothing
    co-applied is lost), builds both halves as whole pages (a split is a
    full-page rewrite in the reference too, Tree.cpp:922-963), and
    returns a log for lazy parent insertion + index-cache refresh.
    """
    M = splitter.shape[0]
    P = pool.shape[0]
    F = fresh.shape[0]
    CAP = C.LEAF_CAP

    sidx2 = jnp.nonzero(splitter, size=F, fill_value=M)[0].astype(jnp.int32)
    valid = sidx2 < M
    ci = jnp.clip(sidx2, 0, M - 1)
    left_row = safe_page[ci]
    spg = pool[left_row]                           # [F, PW] POST-apply
    pkhi, pklo = inc["khi"][ci], inc["klo"][ci]
    pvhi, pvlo = inc["vhi"][ci], inc["vlo"][ci]
    new_addr = fresh[jnp.clip(fidx[ci], 0, F - 1)]
    right_row = jnp.clip(bits.addr_page(new_addr), 0, P - 1)
    valid = valid & (new_addr != 0)

    # sort the LEAF_CAP slots + pending entry by key; dead slots sort last
    sv = layout.leaf_slots_view(spg)
    live = jnp.concatenate(
        [layout.leaf_slot_used(spg), jnp.ones((F, 1), bool)], axis=1)
    cat = lambda blk, pend: jnp.concatenate([blk, pend[:, None]], axis=1)
    k_hi, k_lo = cat(sv["khi"], pkhi), cat(sv["klo"], pklo)
    v_hi, v_lo = cat(sv["vhi"], pvhi), cat(sv["vlo"], pvlo)
    inf = jnp.int32(0x7FFFFFFF)
    gkh_key = jnp.where(live, bits._ux(k_hi), inf)
    gkl_key = jnp.where(live, bits._ux(k_lo), inf)
    # dead slots sort last, so sorted column j is live iff j < n
    _, _, gkh, gkl, gvh, gvl = lax.sort(
        (gkh_key, gkl_key, k_hi, k_lo, v_hi, v_lo), num_keys=2,
        dimension=1)                               # [F, CAP+1] each

    n = jnp.sum(live, axis=1).astype(jnp.int32)    # live incl pending
    m = n // 2                                     # left keeps m entries
    cols = jnp.arange(CAP + 1, dtype=jnp.int32)[None, :]
    # split key = first right entry (one-hot: column == m)
    at_m = cols == m[:, None]
    skhi = jnp.sum(jnp.where(at_m, gkh, 0), axis=1)
    sklo = jnp.sum(jnp.where(at_m, gkl, 0), axis=1)

    colsC = jnp.arange(CAP, dtype=jnp.int32)[None, :]
    l_live = colsC < m[:, None]
    ridx = jnp.clip(m[:, None] + colsC, 0, CAP)
    r_live = colsC < (n - m)[:, None]
    take = lambda a: jnp.take_along_axis(a, ridx, axis=1)

    old_ver = spg[:, C.W_FRONT_VER]
    bumped = (old_ver + 1) & 0x7FFFFFFF
    lver = jnp.where(bumped == 0, 1, bumped)
    old_hhi, old_hlo = spg[:, C.W_HIGH_HI], spg[:, C.W_HIGH_LO]
    left = _leaf_pages(gkh[:, :CAP], gkl[:, :CAP], gvh[:, :CAP],
                       gvl[:, :CAP], l_live, lver, spg[:, C.W_LOW_HI],
                       spg[:, C.W_LOW_LO], skhi, sklo, new_addr)
    right = _leaf_pages(take(gkh), take(gkl), take(gvh), take(gvl), r_live,
                        jnp.ones(F, jnp.int32), skhi, sklo, old_hhi,
                        old_hlo, spg[:, C.W_SIBLING])

    # right page first in program order is irrelevant — both land at the
    # step boundary (the atomic-split guarantee, stronger than the
    # reference's ordered sibling-then-page writes)
    pool = pool.at[jnp.where(valid, right_row, P)].set(right, mode="drop")
    pool = pool.at[jnp.where(valid, left_row, P)].set(left, mode="drop")

    u32 = lambda x: jnp.sum(x.astype(jnp.uint32))
    counters = counters.at[D.CNT_WRITE_OPS].add(u32(valid) * jnp.uint32(2))
    counters = counters.at[D.CNT_WRITE_WORDS].add(
        u32(valid) * jnp.uint32(2 * _PW))

    log = {"valid": valid, "skhi": skhi, "sklo": sklo,
           "new_addr": jnp.where(valid, new_addr, 0),
           "old_hhi": old_hhi, "old_hlo": old_hlo}
    return pool, counters, log


def _resolve_leaves(pool, counters, khi, klo, root, active, start, *,
                    cfg: DSMConfig, iters: int, axis_name: str):
    """Walk every active key to its leaf, picking the best descent:
    cache-seeded compacted loop when seeds exist (any mesh size),
    generic full-batch descent otherwise.  -> (counters, done, addr,
    found, vhi, vlo); callers that only need addresses let XLA drop the
    lookup outputs.
    """
    if start is not None:
        return _routed_resolve(pool, counters, khi, klo, active, start,
                               iters=iters, cfg=cfg, axis_name=axis_name)
    counters, addr, page, done = descend_spmd(
        pool, counters, khi, klo, root, active, cfg=cfg, iters=iters,
        axis_name=axis_name, start=start)
    f, vh, vl, _ = layout.leaf_find_key(page, khi, klo)
    found = f & done
    return (counters, done, addr, found,
            jnp.where(found, vh, 0), jnp.where(found, vl, 0))


def _mark_dirty_pages(dirty, page_idx, active):
    """OR ``active`` rows' (owner-local) target pages into the dirty
    shard — the delta-checkpoint feed.  Marks the pages the apply MAY
    write (lock-blocked / deduped rows over-mark: a spare delta row,
    never a missed one)."""
    P = dirty.shape[0]
    rows = jnp.where(active & (page_idx >= 0) & (page_idx < P),
                     page_idx, P)
    return dirty.at[rows].set(True, mode="drop")


def _route_and_apply(pool, locks, counters, dirty, apply_fn, addr, eligible,
                     fields, *, cfg: DSMConfig, axis_name: str):
    """Ship ``eligible`` requests to their owner nodes and apply.

    Shared tail of the insert/delete/mixed steps: single-node applies
    directly; multi-node bucketizes by owner, all_to_all-exchanges the
    request fields, applies on the owner, and routes statuses back.
    ``fields`` are the per-request arrays ``apply_fn`` expects beyond
    active/addr.  Returns (pool, counters, dirty, status_raw [B], extra)
    where status_raw is the apply status for eligible routed rows and
    ST_RETRY for rows that missed the bucket capacity (full RDMA send
    queue moral equivalent) — callers mask inactive rows to ST_INVALID;
    ``dirty`` is the per-node dirty-page mask with this step's write
    targets marked (delta-checkpoint feed; ``None`` = untracked, passed
    through).  ``extra`` is the apply_fn's optional 4th output (e.g. the
    split log), which stays owner-node-local (no reply routing).
    """
    N, cap = cfg.machine_nr, cfg.step_capacity
    if N == 1:
        inc = {"active": eligible, "addr": addr, **fields}
        if dirty is not None:
            dirty = _mark_dirty_pages(dirty, bits.addr_page(addr), eligible)
        out = apply_fn(pool, locks, counters, inc, cfg=cfg)
        pool, counters, st = out[:3]
        extra = out[3] if len(out) > 3 else None
        return (pool, counters, dirty,
                jnp.where(eligible, st, ST_RETRY), extra)

    dest = bits.addr_node(addr)
    bucket_idx, routed = transport.bucketize(dest, eligible, N, cap)
    out_fields = {"active": eligible & routed, "addr": addr, **fields}
    out = {k: transport.scatter_to_buckets(v, bucket_idx, N * cap)
           for k, v in out_fields.items()}
    inc = transport.exchange(out, axis_name, impl=cfg.exchange_impl)
    if dirty is not None:
        dirty = _mark_dirty_pages(dirty, bits.addr_page(inc["addr"]),
                                  inc["active"])
    aout = apply_fn(pool, locks, counters, inc, cfg=cfg)
    pool, counters, st = aout[:3]
    extra = aout[3] if len(aout) > 3 else None
    rep = transport.exchange({"st": st}, axis_name,
                             impl=cfg.exchange_impl)
    safe_b = jnp.where(routed, bucket_idx, 0)
    return (pool, counters, dirty,
            jnp.where(eligible & routed, rep["st"][safe_b], ST_RETRY),
            extra)


def insert_step_spmd(pool, locks, counters, khi, klo, vhi, vlo, root,
                     active, start=None, fresh=None, *, cfg: DSMConfig,
                     iters: int, axis_name: str = AXIS,
                     update_only: bool = False, combine: bool = False,
                     dirty=None):
    """One batched insert step: descend + route to owners + leaf apply.

    With ``fresh`` (per-node pre-allocated pages), full leaves split
    owner-side and a split log is returned for lazy parent insertion.
    ``update_only`` compiles the steady-state kernel (see
    :func:`leaf_apply_spmd`).  Returns (pool, counters, status [B]) per
    this node's key shard — plus the log when ``fresh`` is given.

    ``dirty`` (keyword-only): the node's dirty-page mask shard; when
    given, target leaves and granted split pages mark it and it rides
    the return tuple after ``counters`` (the delta-checkpoint feed —
    the ENGINE passes it; raw harness compositions that leave it None
    are outside the durability contract).
    """
    # NOTE: threading the descent's round-1 pages into the apply (to skip
    # its snapshot gather) was measured SLOWER (+24 ms at 2 M rows):
    # materializing the [B, PW] round-1 pages costs more than the
    # duplicate gather, which XLA fuses into the apply's consumers.
    counters, done, addr, _, _, _ = _resolve_leaves(
        pool, counters, khi, klo, root, active, start, cfg=cfg,
        iters=iters, axis_name=axis_name)
    apply_fn = functools.partial(leaf_apply_spmd, fresh=fresh,
                                 update_only=update_only, combine=combine)
    if fresh is not None and dirty is not None:
        # granted split pages are written owner-side this step; marking
        # every OFFERED grant over-marks unconsumed ones (spare delta
        # rows, never a miss)
        dirty = _mark_dirty_pages(dirty, bits.addr_page(fresh), fresh != 0)
    pool, counters, dirty, status, log = _route_and_apply(
        pool, locks, counters, dirty, apply_fn, addr, done,
        {"khi": khi, "klo": klo, "vhi": vhi, "vlo": vlo},
        cfg=cfg, axis_name=axis_name)
    status = jnp.where(active, status, ST_INVALID)
    state = (pool, counters) if dirty is None else (pool, counters, dirty)
    if fresh is not None:
        return (*state, status, log)
    return (*state, status)


# ---------------------------------------------------------------------------
# Batched delete: descend + routed owner-side slot clear.
# ---------------------------------------------------------------------------

def leaf_delete_apply_spmd(pool, locks, counters, inc, *, cfg: DSMConfig):
    """Clear routed delete requests on this node's leaf pages.

    Mirrors ``Tree::del``'s leaf step (btree.py delete / reference
    ``Tree.cpp`` del path): zero the slot's fver/rver pair — the two-level
    version liveness rule makes the slot free.  Clearing is idempotent, so
    same-key duplicates need no dedup (they scatter identical zeros).
    Returns (pool, counters, status [M]).
    """
    M = inc["addr"].shape[0]
    P = pool.shape[0]
    L = locks.shape[0]
    act = inc["active"]
    khi, klo = inc["khi"], inc["klo"]
    page_idx = bits.addr_page(inc["addr"])
    safe_page = jnp.clip(page_idx, 0, P - 1)
    use_pk = pallas_page.use_pallas(cfg)
    if use_pk:
        pg = pallas_page.gather_pages(pool, safe_page)  # one gather
    else:
        pg = lax.optimization_barrier(pool[safe_page])  # one gather, many uses

    lock_idx = bits.lock_index(inc["addr"], cfg.locks_per_node)
    locked = locks[jnp.clip(lock_idx, 0, L - 1)] != 0

    sane = act & (page_idx >= 0) & (page_idx < P) \
        & (layout.h_level(pg) == 0) & layout.in_fence(pg, khi, klo) \
        & layout.page_consistent(pg)
    ok_req = sane & ~locked

    found, _, _, slot = layout.leaf_find_key(pg, khi, klo)
    applied = ok_req & found
    safe_slot = jnp.clip(slot, 0, C.LEAF_CAP - 1)

    # ONE scatter: zero the slot's packed version word — the slot becomes
    # free.  Like the insert write-back, page front/rear versions move
    # only on structural rewrites (reference parity: Tree::del writes the
    # entry, not the page header).
    wb = pallas_page.writeback if use_pk else pallas_page.writeback_xla
    pool = wb(pool, safe_page, safe_slot, applied,
              jnp.zeros((M, 1), jnp.int32), (C.L_VER_W,))

    status = jnp.full(M, ST_INVALID, jnp.int32)
    status = jnp.where(act, ST_BAD, status)
    status = jnp.where(act & sane & locked, ST_LOCKED, status)
    status = jnp.where(ok_req & ~found, ST_NOT_FOUND, status)
    status = jnp.where(applied, ST_APPLIED, status)

    u32 = lambda m: jnp.sum(m.astype(jnp.uint32))
    counters = counters.at[D.CNT_WRITE_OPS].add(u32(applied))
    # the slot's packed version word
    counters = counters.at[D.CNT_WRITE_WORDS].add(u32(applied))
    return pool, counters, status


def delete_step_spmd(pool, locks, counters, khi, klo, root, active,
                     start=None, *, cfg: DSMConfig, iters: int,
                     axis_name: str = AXIS, dirty=None):
    """One batched delete step: descend + route to owners + slot clear.

    Returns (pool, counters, status [B]) per this node's key shard —
    with ``dirty`` threaded after ``counters`` when given (see
    :func:`insert_step_spmd`).
    """
    counters, done, addr, _, _, _ = _resolve_leaves(
        pool, counters, khi, klo, root, active, start, cfg=cfg, iters=iters,
        axis_name=axis_name)
    pool, counters, dirty, status, _ = _route_and_apply(
        pool, locks, counters, dirty, leaf_delete_apply_spmd, addr, done,
        {"khi": khi, "klo": klo}, cfg=cfg, axis_name=axis_name)
    status = jnp.where(active, status, ST_INVALID)
    if dirty is None:
        return pool, counters, status
    return pool, counters, dirty, status


# ---------------------------------------------------------------------------
# Mixed step: searches and upserts share one descent (YCSB-A/B shape).
# ---------------------------------------------------------------------------

def mixed_step_spmd(pool, locks, counters, khi, klo, vhi, vlo, root,
                    active_r, active_w, start=None, *, cfg: DSMConfig,
                    iters: int, axis_name: str = AXIS,
                    write_lo: int | None = None,
                    update_only: bool = False, combine: bool = False,
                    dirty=None):
    """One fused step of searches (``active_r``) and upserts (``active_w``).

    The reference interleaves reads and writes per thread from one open
    loop (``benchmark.cpp:159-188``); the batched equivalent runs both
    workload classes through a SINGLE descent per step — a read costs the
    same whether its neighbor is a write.  Consistency: reads that resolve
    in this step see the pre-step pool snapshot, and writes apply at the
    step boundary — the serial order is (resolved reads) < (writes).
    Reads that overrun the descent budget (done_r False) are NOT part of
    this step's linearization: the caller retries them in a later step,
    where they may legally observe this step's writes (the same outcome
    as a reference thread whose read lost the race to a concurrent
    writer).

    Returns (pool, counters, status [B], done_r [B], found [B], vhi [B],
    vlo [B]); status is ST_* for write keys, done_r/found/v* cover
    reads.  With ``dirty`` given it rides after ``counters``, write
    targets marked (see :func:`insert_step_spmd`).

    ``write_lo`` (static): when the caller lays each node's shard out as
    ``[reads | writes]`` with writes in ``[write_lo:]``, the apply runs on
    that half-width slice only — the apply path (page snapshot gather,
    dedup sort, write-back scatter) costs per ROW regardless of activity,
    so applying over the full batch pays ~2x for a 50/50 mix.
    """
    active = active_r | active_w
    counters, done, addr, found, rvh, rvl = _resolve_leaves(
        pool, counters, khi, klo, root, active, start, cfg=cfg, iters=iters,
        axis_name=axis_name)

    done_r = done & active_r
    found = found & done_r
    rvh = jnp.where(found, rvh, 0)
    rvl = jnp.where(found, rvl, 0)

    if write_lo is None:
        w = slice(None)
        pad = 0
    else:
        w = slice(write_lo, None)
        pad = write_lo
    pool, counters, dirty, st_w, _ = _route_and_apply(
        pool, locks, counters, dirty,
        functools.partial(leaf_apply_spmd, update_only=update_only,
                          combine=combine),
        addr[w], (done & active_w)[w],
        {"khi": khi[w], "klo": klo[w], "vhi": vhi[w], "vlo": vlo[w]},
        cfg=cfg, axis_name=axis_name)
    if pad:
        st_w = jnp.concatenate(
            [jnp.full(pad, ST_INVALID, jnp.int32), st_w])
    status = jnp.where(active_w, st_w, ST_INVALID)
    if dirty is None:
        return pool, counters, status, done_r, found, rvh, rvl
    return pool, counters, dirty, status, done_r, found, rvh, rvl


# ---------------------------------------------------------------------------
# Host-facing engine: jit/shard_map wrappers + retry loop.
# ---------------------------------------------------------------------------

def _assert_replicated(multihost: bool, arrays, what: str) -> None:
    """Multihost divergence guard: all processes must drive identical
    request streams — mirrored allocators and collective step sequences
    depend on it.  Cheap digest allgather; raises loudly on skew."""
    if not multihost:
        return
    import zlib

    from jax.experimental import multihost_utils as mhu
    dig = 0
    for a in arrays:
        dig = zlib.crc32(np.ascontiguousarray(a).tobytes(), dig)
    digs = np.asarray(mhu.process_allgather(
        np.asarray([dig], np.uint32))).ravel()
    if not (digs == np.uint32(dig)).all():
        raise ProtocolError(
            f"multihost {what} diverged across processes: every process "
            "must drive identical request streams (replicated-driver SPMD)")


class BatchedEngine:
    """Compiled batched ops over a :class:`~sherman_tpu.models.btree.Tree`.

    The engine is the analogue of ``run_coroutine`` (Tree.cpp:1059-1122) ×
    doorbell batching: a fixed per-node batch shape keeps one compiled
    program per tree height.
    """

    def __init__(self, tree, batch_per_node: int = 1024,
                 tcfg: TreeConfig | None = None,
                 split_slots: int | None = None,
                 write_combine: bool | None = None):
        self.tree = tree
        self.dsm = tree.dsm
        self.cfg = tree.cfg
        self.tcfg = tcfg if tcfg is not None else TreeConfig()
        self.B = batch_per_node
        # HOCL-style write combining (leaf_apply_spmd's ``combine``
        # static): one lock consult per same-leaf write group.  None
        # (default) reads the SHERMAN_WRITE_COMBINE knob; explicit
        # True/False pins it for A/B drivers and tests.  Static per
        # engine — it selects which program the jit caches compile.
        self._write_combine = (C.write_combine() if write_combine is None
                               else bool(write_combine))
        # device-split grant slots per node per insert round; unused grants
        # are cached host-side and re-offered (free() is a no-op, so
        # abandoning them would leak pages every round).  The default
        # suits steady-state workloads; split-storm drivers (fresh-key
        # bulk insertion into a near-full tree) raise it so one round can
        # split tens of thousands of leaves (tools/insert_bench.py).
        self.split_slots = (min(256, batch_per_node) if split_slots is None
                            else min(split_slots, batch_per_node))
        # Mid-chunk parent-flush trigger: flush when the pending backlog
        # reaches this many entries (insert() always flushes at the end
        # regardless).  1 = every round (default, tightest chains); a
        # split-storm driver raises it to ~split_slots — the router's
        # note_split keeps descents short between flushes, and each flush
        # pass costs several host round trips (expensive over an access
        # tunnel).
        self.parent_flush_threshold = 1
        self._fresh_cache: dict[int, list[int]] = {}
        self._pending_parents: list[tuple[int, int]] = []
        # empty-leaf reclamation bookkeeping (reclaim_empty_leaves).
        # "parked" holds retired pages still referenced as some parent's
        # LEFTMOST child — they stay retired forever (self-healing via
        # their back-sibling) rather than risking a dangling reference
        # into a reused page; bounded at ~1/INTERNAL_CAP of reclaimable
        # leaves.
        self._reclaim_state: dict = {"round": 0, "quarantine": [],
                                     "pending_parent": [], "parked": set()}
        # reclaim mutates engine-local reclaim state and the allocator
        # free pools across many steps; it is a maintenance pass, not a
        # concurrent op — overlapping calls are a caller bug
        self._reclaim_mutex = threading.Lock()
        self._parent_descend_cache: dict = {}
        self.router = None
        # Optional hot-key tier (models/leaf_cache.py, attached by
        # attach_leaf_cache / the SHERMAN_LEAF_CACHE knob): a versioned
        # compute-side leaf/value cache probed in front of the descent
        # by search/search_combined/mixed; write entry points invalidate
        # it, degraded entry flushes it.  None (default) costs one
        # `is None` test per read batch.
        self.leaf_cache = None
        # Optional out-of-line value heap (models/value_heap.py,
        # attached by attach_value_heap): leaf values become versioned
        # slab handles resolved in the fused fan-out; journal replay
        # discovers it here.  None (default) = inline 64-bit values,
        # bit-identical to pre-heap builds.
        self.value_heap = None
        # Optional write-ahead op journal (utils/journal.py, attached by
        # the recovery plane): every engine write op appends ONE batch
        # record of its APPLIED rows before returning — the record is
        # durable before the caller sees the ack, so recovery = restore
        # chain + replay journal loses zero acknowledged ops (RPO 0).
        # None (default) costs one `is None` test per op.  Single-writer
        # contract: record order must match apply order, so journaled
        # engines are driven from one thread (the drill/serving shape).
        self.journal = None
        # Graceful degradation (data-plane failure story): once flipped,
        # every mutating entry point raises DegradedError (typed write
        # rejection) while searches keep serving; exit = checkpoint
        # restore into a fresh engine.  A fresh engine is healthy by
        # construction, so the gauge resets here.
        self._degraded_reason: str | None = None
        _OBS_DEGRADED.set(0)
        self._search_cache: dict = {}
        self._insert_cache: dict = {}
        self._delete_cache: dict = {}
        self._mixed_cache: dict = {}
        spec = jax.sharding.PartitionSpec(AXIS)
        self._spec = spec
        self._rep = jax.sharding.PartitionSpec()
        # Multihost = replicated-driver SPMD: every process must call the
        # engine with IDENTICAL request streams (multi-controller JAX runs
        # the same host program everywhere; host-API ops execute once via
        # cluster.host_dsm, and the device batch shards over the
        # process-spanning mesh).  _check_replicated enforces it.
        self._mh = self.dsm.multihost
        # Compiled-step launches mutate the same donated pool/locks/
        # counters handles as the host-API steps, so concurrent host
        # threads (Tree clients taking locks/splitting — the reference's
        # 26-thread axis, benchmark.cpp:285-287) would race the engine on
        # the handle swap: an engine step built from a pre-host-step pool
        # handle writes back a result that LOSES the host step wholesale.
        # Sharing the DSM's step mutex for the read-handles -> launch ->
        # write-handles window makes every step atomic at the handle
        # level; cross-step consistency is then the lock/version
        # protocol's job, exactly as in the reference.  Launch-only:
        # dispatch is async, so the mutex is held microseconds and never
        # across a host DSM op (threading.Lock is not reentrant).
        self._step_mutex = self.dsm._step_mutex
        # Write-combining observability: the device kernels accumulate
        # group/saved counts in the DSM counter slots (no per-step host
        # sync); this pull-time collector names them the combine.* way
        # the receipts and dashboards expect.  Registered only when the
        # knob is on, so combine-off scrapes are bit-identical to a
        # build without the subsystem.  Weakly bound like the dsm
        # collector.
        self._combine_steps = 0
        self._combine_rows = 0
        if self._write_combine:
            import weakref
            _dref = weakref.ref(self.dsm)
            _eref = weakref.ref(self)

            def _combine_collect():
                d = _dref()
                e = _eref()
                if d is None or e is None:
                    return {}
                snap = d.counter_snapshot()
                groups = snap["combine_groups"]
                saved = snap["combine_locks_saved"]
                return {"groups": groups, "locks_saved": saved,
                        "ops_combined": saved,
                        "steps": float(e._combine_steps),
                        "rows": float(e._combine_rows)}
            obs.register_collector("combine", _combine_collect)

    # -- degraded mode (read-only serving after unrecoverable damage) --------

    @property
    def degraded(self) -> bool:
        return self._degraded_reason is not None

    @property
    def degraded_reason(self) -> str | None:
        return self._degraded_reason

    def enter_degraded(self, reason: str) -> None:
        """Flip to read-only degraded serving: searches continue, writes
        raise :class:`DegradedError`.  Idempotent (the first reason
        wins — it names the root cause)."""
        if self._degraded_reason is None:
            self._degraded_reason = reason
            _OBS_DEGRADED.set(1)
            obs.counter("engine.degraded_entries").inc()
            # the hot-key tier must not serve answers certified against
            # a pool the engine no longer trusts — flush wholesale (the
            # cache is volatile by contract; see leaf_cache.py)
            if self.leaf_cache is not None:
                self.leaf_cache.flush()
            # black box: the transition is a flight event, and entering
            # degraded auto-dumps the bundle (env-gated, debounced) so
            # the postmortem starts from the moment the engine gave up
            FR.record_event("engine.degraded_enter", reason=reason)
            FR.auto_dump("degraded_entry")

    def _note_combine_step(self, rows: int) -> None:
        """Per-batch write-combining accounting (plain integer adds —
        SL006-registered: this runs inside the write wall).  The
        group/saved counts themselves accumulate in the DSM counter
        slots on device; this only tracks how many batches/rows went
        through the combined kernel."""
        self._combine_steps += 1
        self._combine_rows += rows

    def exit_degraded(self) -> None:
        """Clear degraded mode — only after the damage is actually gone
        (state restored or repaired and re-validated); the chaos drill
        is the reference sequence."""
        self._degraded_reason = None
        _OBS_DEGRADED.set(0)
        FR.record_event("engine.degraded_exit")

    def _require_writable(self) -> None:
        if self._degraded_reason is not None:
            FR.record_event("engine.typed_error", error="DegradedError",
                            reason=self._degraded_reason)
            FR.auto_dump("typed_error")
            raise DegradedError(self._degraded_reason)

    def attach_journal(self, journal) -> None:
        """Attach (or detach, with ``None``) the write-ahead op journal;
        see the ``journal`` attribute's contract in ``__init__``."""
        self.journal = journal

    def _journal_applied(self, kind: int, keys, values=None) -> None:
        if self.journal is None or keys.size == 0:
            return
        self.journal.append(kind, keys, values)

    def _iters(self) -> int:
        # STATIC descent budget: max height + chase slack.  Deliberately
        # NOT tied to the live root level — that would change the compiled
        # program shape on every root growth, and a recompile through the
        # remote-compile path costs ~minutes.  Single-node loops exit
        # early dynamically (while_loop), so the slack is free there; the
        # multi-node fori pays it only on CPU test meshes.
        return self.tcfg.max_level + self.tcfg.sibling_chase_budget

    def attach_router(self, log2_buckets: int | None = None,
                      scan: bool = True):
        """Create + seed the device index cache (see router.py).  Uses the
        bulk-load leaf directory when available; otherwise (a restored or
        host-built tree) enumerates the live leaves in one device step
        (``validate.leaf_directory``) so the router is warm AND correctly
        sized from the first batch.  ``scan=False`` forces the cold
        root-seeded table (refined only by split notifications).

        COLLECTIVE in multihost deployments when ``scan=True`` and no
        bulk-load directory exists: the leaf scan does a
        ``process_allgather``, so EVERY process must call attach_router
        with the same arguments at the same point (calling it on a subset,
        or conditionally, deadlocks).  ``scan=False`` is process-local and
        safe to call unilaterally."""
        from sherman_tpu.models.router import LeafRouter, default_log2_buckets
        leaf_dir = getattr(self.tree, "_bulk_leaf_dir", None)
        if leaf_dir is None and scan:
            from sherman_tpu.models.validate import leaf_directory
            leaf_dir = leaf_directory(self.tree)
        if log2_buckets is None:
            n_leaves = len(leaf_dir[0]) if leaf_dir else 1024
            log2_buckets = default_log2_buckets(n_leaves)
        r = LeafRouter(self.tree, log2_buckets)
        if leaf_dir is not None and len(leaf_dir[0]):
            r.seed_from_leaves(*leaf_dir)
        self.router = r
        return r

    def attach_leaf_cache(self, slots: int | None = None,
                          admit_every: int = 0):
        """Create + attach the hot-key tier (models/leaf_cache.py): a
        versioned compute-side leaf/value cache probed in front of the
        descent by every read entry point.  ``slots`` defaults to the
        ``SHERMAN_LEAF_CACHE`` knob (``config.leaf_cache_slots``;
        65536 when the knob only says "on"); ``admit_every`` > 0 arms
        frequency-based auto-admission every that-many observed read
        batches (0 = manual ``fill`` — the staged bench drivers prefill
        the analytically known hot set instead)."""
        from sherman_tpu.models.leaf_cache import LeafCache
        self.leaf_cache = LeafCache(self, slots=slots,
                                    admit_every=admit_every)
        return self.leaf_cache

    def attach_value_heap(self, **kw):
        """Create + attach the out-of-line value heap
        (models/value_heap.py) over this engine's DSM heap region
        (``DSMConfig.heap_pages_per_node`` / ``SHERMAN_VALUE_HEAP``):
        leaf values become versioned slab handles and
        ``put``/``get``/``remove``/``scan`` on the returned
        :class:`~sherman_tpu.models.value_heap.ValueHeap` serve
        variable-length payloads."""
        from sherman_tpu.models.value_heap import ValueHeap
        return ValueHeap(self, **kw)

    def detach_leaf_cache(self) -> None:
        """Drop the hot-key tier (reads go back to full descents).
        The ``cache.`` collector unregisters with it — a scrape must
        not keep publishing stats for a tier that no longer probes."""
        if self.leaf_cache is not None:
            obs.get_registry().unregister_collector("cache")
        self.leaf_cache = None

    def _get_search(self, iters: int, with_start: bool):
        key = (iters, with_start)
        fn = self._search_cache.get(key)
        if fn is None:
            spec, rep = self._spec, self._rep
            in_specs = [spec, spec, spec, spec, rep, spec]
            if with_start:
                in_specs.append(spec)
            if with_start:
                kernel = functools.partial(search_routed_spmd, cfg=self.cfg,
                                           iters=iters)
            else:
                kernel = functools.partial(search_spmd, cfg=self.cfg,
                                           iters=iters)
            sm = jax.shard_map(
                kernel,
                mesh=self.dsm.mesh,
                in_specs=tuple(in_specs),
                out_specs=(spec, spec, spec, spec, spec),
                check_vma=False)
            # compile-ledger wrap (obs/device.py): the WRAPPER is what
            # the cache holds, so program-identity pins keep holding
            fn = DEV.wrap_program("engine.search",
                                  jax.jit(sm, donate_argnums=C.donate_argnums(1)))
            self._search_cache[key] = fn
        return fn

    def _get_insert(self, iters: int, with_start: bool,
                    with_fresh: bool = True, update_only: bool = False):
        """Insert step.  ``with_fresh`` (static) enables the device-split
        path: a per-node fresh page array goes in and the split log comes
        out.  Rounds that offer NO grants (round 0's optimistic pass, the
        steady-state update benchmark) compile the leaner variant — the
        splitter ranking, split-page detection and split-apply machinery
        drop out of the program entirely (~30 ms/step at 2 M rows).
        ``update_only`` additionally compiles the 3-word write-back
        steady-state kernel (absent keys escalate, see leaf_apply_spmd).
        The engine's ``_write_combine`` (SHERMAN_WRITE_COMBINE) selects
        the HOCL-style group-lock-consult variant — part of the cache
        key so A/B drivers flipping it per engine never collide."""
        assert not (update_only and with_fresh)
        combine = self._write_combine
        key = (iters, with_start, with_fresh, update_only, combine)
        fn = self._insert_cache.get(key)
        if fn is None:
            spec, rep = self._spec, self._rep
            in_specs = [spec, spec, spec, spec, spec, spec, spec, spec,
                        rep, spec]
            if with_start:
                in_specs.append(spec)
            if with_fresh:
                in_specs.append(spec)  # fresh pages [N*F]
            log_spec = {k: spec for k in ("valid", "skhi", "sklo",
                                          "new_addr", "old_hhi",
                                          "old_hlo")}

            def kernel(pool, locks, counters, dirty, khi, klo, vhi, vlo,
                       root, active, *rest):
                start = rest[0] if with_start else None
                fresh = rest[-1] if with_fresh else None
                return insert_step_spmd(
                    pool, locks, counters, khi, klo, vhi, vlo,
                    root, active, start, fresh, cfg=self.cfg, iters=iters,
                    update_only=update_only, combine=combine, dirty=dirty)

            sm = jax.shard_map(
                kernel,
                mesh=self.dsm.mesh,
                in_specs=tuple(in_specs),
                out_specs=((spec, spec, spec, spec, log_spec) if with_fresh
                           else (spec, spec, spec, spec)),
                check_vma=False)
            fn = DEV.wrap_program(
                "engine.insert",
                jax.jit(sm, donate_argnums=C.donate_argnums(0, 2, 3)))
            self._insert_cache[key] = fn
        return fn

    def _get_delete(self, iters: int, with_start: bool):
        key = (iters, with_start)
        fn = self._delete_cache.get(key)
        if fn is None:
            spec, rep = self._spec, self._rep
            in_specs = [spec, spec, spec, spec, spec, spec, rep, spec]
            if with_start:
                in_specs.append(spec)

            def kernel(pool, locks, counters, dirty, khi, klo, root,
                       active, *rest):
                start = rest[0] if with_start else None
                return delete_step_spmd(
                    pool, locks, counters, khi, klo, root, active, start,
                    cfg=self.cfg, iters=iters, dirty=dirty)

            sm = jax.shard_map(
                kernel,
                mesh=self.dsm.mesh,
                in_specs=tuple(in_specs),
                out_specs=(spec, spec, spec, spec),
                check_vma=False)
            fn = DEV.wrap_program(
                "engine.delete",
                jax.jit(sm, donate_argnums=C.donate_argnums(0, 2, 3)))
            self._delete_cache[key] = fn
        return fn

    def _get_mixed(self, iters: int, with_start: bool,
                   write_lo: int | None = None,
                   update_only: bool = False):
        """``write_lo`` (static, per-node offset): callers that lay each
        node's shard out as [reads | writes] get the half-width apply
        (see mixed_step_spmd).  ``update_only``: the 4-word steady-state
        apply (absent keys escalate with ST_FULL).  ``_write_combine``
        selects the group-lock-consult apply, like ``_get_insert``."""
        combine = self._write_combine
        key = (iters, with_start, write_lo, update_only, combine)
        fn = self._mixed_cache.get(key)
        if fn is None:
            spec, rep = self._spec, self._rep
            in_specs = [spec, spec, spec, spec, spec, spec, spec, spec,
                        rep, spec, spec]
            if with_start:
                in_specs.append(spec)

            def kernel(pool, locks, counters, dirty, khi, klo, vhi, vlo,
                       root, active_r, active_w, *rest):
                start = rest[0] if with_start else None
                return mixed_step_spmd(
                    pool, locks, counters, khi, klo, vhi, vlo, root,
                    active_r, active_w, start, cfg=self.cfg, iters=iters,
                    write_lo=write_lo, update_only=update_only,
                    combine=combine, dirty=dirty)

            sm = jax.shard_map(
                kernel,
                mesh=self.dsm.mesh,
                in_specs=tuple(in_specs),
                out_specs=(spec, spec, spec, spec, spec, spec, spec, spec),
                check_vma=False)
            fn = DEV.wrap_program(
                "engine.mixed",
                jax.jit(sm, donate_argnums=C.donate_argnums(0, 2, 3)))
            self._mixed_cache[key] = fn
        return fn

    def mixed(self, keys, values, is_read):
        """One fused step of reads and upserts over one key batch.

        keys u64 [n], values u64 [n] (ignored where is_read), is_read
        bool [n].  Returns (out_values u64 [n], found bool [n] — read
        rows only, status int32 [n] — write rows only).  Writes that
        miss the fast path (ST_FULL / ST_RETRY / ST_LOCKED — splits in
        flight, chase-budget overruns on stale seeds) retry through
        :meth:`insert`, which owns the split/host fallbacks; their
        status is rewritten to the retry outcome.  Reads that overran
        the descent budget retry inline as a LATER step — per the
        mixed_step_spmd linearization rule they may observe this step's
        writes.  (The bench drivers bypass this wrapper and treat
        fast-path misses as open-loop misses.)
        """
        t_slo = time.perf_counter()
        keys = np.asarray(keys, np.uint64)
        if keys.size and (keys.min() < C.KEY_MIN or keys.max() > C.KEY_MAX):
            raise KeyRangeError("keys outside [KEY_MIN, KEY_MAX]")
        values = np.asarray(values, np.uint64)
        is_read = np.asarray(is_read, bool)
        if not bool(np.asarray(is_read).all()):
            self._require_writable()  # degraded mode: reads-only batches
        self._check_replicated(keys, values, is_read)
        n = keys.shape[0]
        total = self.cfg.machine_nr * self.B
        assert n <= total, "chunk the batch to machine_nr * B"
        khi, klo = bits.keys_to_pairs(keys)
        vhi, vlo = bits.keys_to_pairs(values)
        (khi, _), (klo, _) = self._pad(khi), self._pad(klo)
        (vhi, _), (vlo, _) = self._pad(vhi), self._pad(vlo)
        ar, _ = self._pad(is_read)   # pad rows are neither read nor write
        aw, _ = self._pad(~is_read)
        # hot-key tier: probe the READ rows only — hits see the same
        # pre-step snapshot the fused descent's reads see (the probe
        # runs before the step's writes apply), so the mixed
        # linearization (resolved reads < writes) is unchanged
        cache = self.leaf_cache
        c_hit = c_vhi = c_vlo = None
        if cache is not None and bool(is_read.any()):
            c_hit, c_vhi, c_vlo = cache.probe(khi, klo, ar)
            ar = ar & ~c_hit
        use_router = self.router is not None
        fn = self._get_mixed(self._iters(), use_router)
        if self._write_combine:
            self._note_combine_step(int(np.count_nonzero(~is_read)))
        # batch prep (router probe, host->device transfers) OUTSIDE the
        # step mutex — only the handle read -> launch -> handle write is
        # locked (see __init__); holding it across prep would stall
        # concurrent host clients for the whole transfer
        args = [self._shard(khi), self._shard(klo),
                self._shard(vhi), self._shard(vlo),
                np.int32(self.tree._root_addr),
                self._shard(ar), self._shard(aw)]
        if use_router:
            args.append(self._shard(self.router.host_start(khi, klo)))
        with obs.span("engine.mixed.descend_lock_apply", n=int(n)):
            with self._step_mutex:
                (self.dsm.pool, self.dsm.counters, self.dsm.dirty, status,
                 done_r, found, rvh, rvl) = fn(
                    self.dsm.pool, self.dsm.locks, self.dsm.counters,
                    self.dsm.dirty, *args)
            status, done_r, found, rvh, rvl = self._unshard(
                status, done_r, found, rvh, rvl)
        status = np.array(status[:n])  # writable: retry outcomes land here
        done_r = done_r[:n]
        found = np.array(found[:n])
        out_vals = np.array(bits.pairs_to_keys(rvh[:n], rvl[:n]))
        if c_hit is not None and c_hit[:n].any():
            # merge cache-served reads (probe active mask was the read
            # rows, so hits are read rows by construction)
            hits = c_hit[:n]
            done_r = np.array(done_r)
            done_r[hits] = True
            found[hits] = True
            out_vals[hits] = bits.pairs_to_keys(
                c_vhi[:n], c_vlo[:n])[hits]
        # journal the fast-path applied writes BEFORE the retry branch:
        # retried rows apply in later steps through insert() (which
        # journals its own record), so appending here keeps record order
        # == apply order even for same-key duplicates across the classes
        fast_app = ~is_read & (status == ST_APPLIED)
        self._journal_applied(J.J_UPSERT, keys[fast_app], values[fast_app])
        if cache is not None and bool((~is_read).any()):
            # write-path invalidation hook: these keys' entry versions
            # bump this step (conservative over the full write class — a
            # spare invalidation, never a missed one; retried writes go
            # through insert(), which invalidates its own keys)
            cache.invalidate_keys(keys[~is_read])
        miss_r = is_read & ~done_r
        if miss_r.any():
            v2, f2 = self.search(keys[miss_r])
            out_vals[miss_r], found[miss_r] = v2, f2
        miss_w = ~is_read & np.isin(status, (ST_FULL, ST_RETRY, ST_LOCKED))
        if miss_w.any():
            st = self.insert(keys[miss_w], values[miss_w])
            # The rewrite below depends on insert()'s postcondition: every
            # request ends APPLIED, SUPERSEDED by a same-batch duplicate,
            # applied through the host path, or REJECTED with the typed
            # ST_LOCK_TIMEOUT outcome (lock held by a live lease past the
            # bounded retry budget).  Assert it so a future relaxation of
            # that guarantee cannot silently turn these synthesized
            # statuses into lies.
            resolved = (st["applied"] + st["superseded"] + st["host_path"]
                        + st["lock_timeouts"])
            assert resolved == int(miss_w.sum()), (
                f"insert() postcondition broken: {st} resolved != "
                f"{int(miss_w.sum())} retried writes")
            # per-request outcomes match the fast path's dedup semantics:
            # the first-ordered request of a key applies, later duplicates
            # are superseded by it (insert linearizes them the same way);
            # lock-timeout keys carry the typed rejection through
            idx_w = np.nonzero(miss_w)[0]
            first = np.zeros(idx_w.shape[0], bool)
            first[np.unique(keys[idx_w], return_index=True)[1]] = True
            status[idx_w[first]] = ST_APPLIED
            status[idx_w[~first]] = ST_SUPERSEDED
            if st["lock_timeouts"]:
                to = np.isin(keys[idx_w],
                             np.asarray(st["lock_timeout_keys"], np.uint64))
                status[idx_w[to]] = ST_LOCK_TIMEOUT
        # the whole fused batch (incl. any retry sub-batches, which also
        # report under their own classes) is the mixed class's wall
        _slo_observe("mixed", n, t_slo)
        return out_vals, found, status

    # -- helpers -------------------------------------------------------------

    def _shard(self, x):
        """Global-shape host array -> node-sharded device array.  In
        multihost mode ``x`` is the full (replicated) batch; each process
        contributes its local node block."""
        if not self._mh:
            return jax.device_put(x, self.dsm.shard)
        from jax.experimental import multihost_utils as mhu
        per = x.shape[0] // self.cfg.machine_nr
        lo = self.dsm.local_nodes[0] * per
        hi = (self.dsm.local_nodes[-1] + 1) * per
        return mhu.host_local_array_to_global_array(
            np.ascontiguousarray(x[lo:hi]), self.dsm.mesh,
            jax.sharding.PartitionSpec(AXIS))

    def _unshard(self, *ys):
        """Node-sharded device arrays -> full host arrays on every process
        (multihost: local block + ONE tiled allgather for all arrays;
        block order asserted ascending by ReplicatedDSM).  Returns a
        single array for one input, else a tuple."""
        if not self._mh:
            out = tuple(np.asarray(y) for y in ys)
            return out[0] if len(ys) == 1 else out
        from jax.experimental import multihost_utils as mhu
        spec = jax.sharding.PartitionSpec(AXIS)
        locals_ = tuple(np.asarray(mhu.global_array_to_host_local_array(
            y, self.dsm.mesh, spec)) for y in ys)
        g = mhu.process_allgather(locals_, tiled=True)
        out = tuple(np.asarray(x) for x in g)
        return out[0] if len(ys) == 1 else out

    def _check_replicated(self, *arrays) -> None:
        _assert_replicated(self._mh, arrays, "engine drivers")

    def _pad(self, arr: np.ndarray, fill=0) -> tuple[np.ndarray, int]:
        total = self.cfg.machine_nr * self.B
        n = arr.shape[0]
        assert n <= total
        if n == total:
            return arr, n
        pad = np.full((total - n,) + arr.shape[1:], fill, arr.dtype)
        return np.concatenate([arr, pad]), n

    # -- public ops ----------------------------------------------------------

    def search(self, keys, _depth: int = 0,
               _checked: bool = False) -> tuple[np.ndarray, np.ndarray]:
        """Batched lookup.  keys: uint64 array [n] (n <= N*B per call is
        chunked automatically).  Returns (values uint64 [n], found bool [n]).
        """
        keys = np.asarray(keys, np.uint64)
        if keys.size and (keys.min() < C.KEY_MIN or keys.max() > C.KEY_MAX):
            raise KeyRangeError("keys outside [KEY_MIN, KEY_MAX]")
        if _depth == 0 and not _checked:
            self._check_replicated(keys)
        n = keys.shape[0]
        total = self.cfg.machine_nr * self.B
        if n > total:
            # chunks were digest-checked as one array; each still routes
            # like a fresh call (_depth=0)
            parts = [self.search(keys[i:i + total], _checked=True)
                     for i in range(0, n, total)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))

        # SLO accounting: one batch wall per top-level call (chunks and
        # straggler retries fold into their parent's wall; _depth > 0
        # frames never observe)
        t_slo = time.perf_counter() if _depth == 0 else None
        khi, klo = bits.keys_to_pairs(keys)
        (khi, _), (klo, _) = self._pad(khi), self._pad(klo)
        active, _ = self._pad(np.ones(n, bool))
        # hot-key tier: probe the leaf/value cache in front of the
        # descent — hits are pool-validated (bit-identical to a
        # descent, see leaf_cache.py) and drop out of the device batch,
        # so the existing search program serves the RESIDUAL active set
        cache = self.leaf_cache if _depth == 0 and n else None
        c_hit = c_vhi = c_vlo = None
        if cache is not None:
            cache.observe(keys)
            c_hit, c_vhi, c_vlo = cache.probe(khi, klo, active)
            active = active & ~c_hit
        # retries (depth > 0) bypass the index cache and descend from root
        use_router = self.router is not None and _depth == 0
        fn = self._get_search(self._iters(), use_router)
        args = [self._shard(khi), self._shard(klo),
                np.int32(self.tree._root_addr), self._shard(active)]
        if use_router:
            args.append(self._shard(self.router.host_start(khi, klo)))
        # span covers launch -> materialized replies (dispatch is async;
        # _unshard's host materialization is the real step drain)
        with obs.span("engine.search.descend", n=int(n)):
            with self._step_mutex:  # launch-only (prep above)
                self.dsm.counters, done, found, vhi, vlo = fn(
                    self.dsm.pool, self.dsm.counters, *args)
            done, found, vhi, vlo = self._unshard(done, found, vhi, vlo)
        done = done[:n]
        if c_hit is not None and c_hit[:n].any():
            # merge the cache hits back into the batch's answers (their
            # device rows were inactive — the residual descent never
            # touched them)
            hits = c_hit[:n]
            done = np.array(done)
            done[hits] = True
            found, vhi, vlo = (np.array(found), np.array(vhi),
                               np.array(vlo))
            found[:n][hits] = True  # found/v* keep the padded width
            vhi[:n][hits] = c_vhi[:n][hits]
            vlo[:n][hits] = c_vlo[:n][hits]
        if not done.all():
            assert _depth < 8, "search stragglers not converging"
            # stale cache / height growth / capacity overflow: refresh root,
            # full descent for the stragglers
            self.tree._refresh_root()
            vals = np.array(bits.pairs_to_keys(vhi[:n], vlo[:n]))
            fnd = np.array(found[:n])
            miss = ~done
            v2, f2 = self.search(keys[miss], _depth=_depth + 1)
            vals[miss], fnd[miss] = v2, f2
            _slo_observe("read", n, t_slo)
            return vals, fnd
        _slo_observe("read", n, t_slo)
        return bits.pairs_to_keys(vhi[:n], vlo[:n]), found[:n]

    def _get_search_fanout(self, iters: int):
        """Search over the unique-key set + packed IN-STEP fan-out of
        every client request's answer.

        TPU gathers are per-row latency-bound regardless of width, so the
        three answer lanes (found, vhi, vlo) pack into ONE [U, 4] table
        and fan out to the [B_client] request slots with a single
        take_along_axis — the client-ops throughput of a combined batch
        is then fully earned on device (nothing deferred to the host).
        Multi-node: the fan-out runs AFTER the reply exchange — each node
        all-gathers the [U, 4] answer table once, then its client slots
        take locally (``inv`` holds GLOBAL unique indices).  jit
        re-specializes per (unique-width, client-width) shape pair.
        """
        fn = self._search_cache.get(("fanout", iters))
        if fn is None:
            spec, rep = self._spec, self._rep
            N = self.cfg.machine_nr

            def kernel(pool, counters, khi, klo, root, active, start, inv):
                counters, done, found, vhi, vlo = search_routed_spmd(
                    pool, counters, khi, klo, root, active, start,
                    cfg=self.cfg, iters=iters)
                ans = jnp.stack([found.astype(jnp.int32), vhi, vlo,
                                 jnp.zeros_like(vhi)], axis=-1)  # [U_loc, 4]
                if N > 1:
                    ans = transport.gather_rows(ans, AXIS)      # [U, 4]
                safe = jnp.clip(inv, 0, ans.shape[0] - 1)
                out = jnp.take_along_axis(ans, safe[:, None], axis=0)
                return (counters, done, out[:, 0].astype(bool),
                        out[:, 1], out[:, 2])

            sm = jax.shard_map(
                kernel, mesh=self.dsm.mesh,
                in_specs=(spec, spec, spec, spec, rep, spec, spec, spec),
                out_specs=(spec, spec, spec, spec, spec), check_vma=False)
            fn = DEV.wrap_program(
                "engine.search_fanout",
                jax.jit(sm, donate_argnums=C.donate_argnums(1)))
            self._search_cache[("fanout", iters)] = fn
        return fn

    def search_combined(self, keys) -> tuple[np.ndarray, np.ndarray]:
        """Batched lookup with request combining: duplicate keys share one
        descent + page fetch; every request still gets its answer.

        The read-side symmetric of the insert step's same-key dedup (its
        intra-step linearization — see :func:`leaf_apply_spmd`): the
        device batch is the unique-key set.  With the router attached,
        the per-request answer fan-out runs ON DEVICE inside the same
        step (:meth:`_get_search_fanout`) on any mesh size — multi-node
        fans out after the reply exchange via an answer-table all-gather;
        without a router it is a host vectorized gather.  Semantically
        identical to :meth:`search` (combined duplicates read the same
        snapshot, a legal concurrent schedule); ~2-10x fewer device rows
        on zipf-skewed batches.  Returns (values uint64 [n], found [n]).
        """
        keys = np.asarray(keys, np.uint64)
        with obs.span("engine.search.combine", n=int(keys.size)):
            uk, inv = np.unique(keys, return_inverse=True)
        use_device = (self.router is not None
                      and 0 < uk.size <= self.B * self.cfg.machine_nr)
        if not use_device:
            # host fan-out: search() attributes the unique-set batch
            vals, found = self.search(uk)
            return vals[inv], found[inv]
        t_slo = time.perf_counter()
        if keys.size and (keys.min() < C.KEY_MIN or keys.max() > C.KEY_MAX):
            raise KeyRangeError("keys outside [KEY_MIN, KEY_MAX]")
        self._check_replicated(keys)
        khi, klo = bits.keys_to_pairs(uk)
        (khi, _), (klo, _) = self._pad(khi), self._pad(klo)
        active, _ = self._pad(np.ones(uk.size, bool))
        # hot-key tier: probe the unique set — cache hits leave the
        # device batch (smaller residual descent); their answers merge
        # back per CLIENT row below via the same inverse map the
        # fan-out uses.  The admission sketch sees the raw (duplicated)
        # key stream: frequency ranking needs the multiplicities.
        cache = self.leaf_cache if uk.size else None
        c_hit = c_vhi = c_vlo = None
        if cache is not None:
            cache.observe(keys)
            c_hit, c_vhi, c_vlo = cache.probe(khi, klo, active)
            active = active & ~c_hit
        # bucket the CLIENT width so varying request counts reuse one
        # compiled program per quantum (unique width is already fixed at
        # N*B); pad rows fan out slot 0 and are sliced off below.  The
        # quantum is a machine_nr multiple so the client array shards
        # evenly over the node mesh.
        n = keys.size
        quantum = 8192 * self.cfg.machine_nr
        n_pad = -(-n // quantum) * quantum
        inv_p = np.zeros(n_pad, np.int32)
        inv_p[:n] = inv.astype(np.int32)
        fn = self._get_search_fanout(self._iters())
        args = [self._shard(khi), self._shard(klo),
                np.int32(self.tree._root_addr), self._shard(active),
                self._shard(self.router.host_start(khi, klo)),
                self._shard(inv_p)]
        with obs.span("engine.search.descend", n=int(uk.size),
                      fanout=int(n)):
            with self._step_mutex:  # launch-only (prep above)
                self.dsm.counters, done, found, vhi, vlo = fn(
                    self.dsm.pool, self.dsm.counters, *args)
            done, found, vhi, vlo = self._unshard(done, found, vhi, vlo)
        hit_u = c_hit[:uk.size] if c_hit is not None else None
        done_u = np.asarray(done[:uk.size]) if hit_u is None \
            else (np.asarray(done[:uk.size]) | hit_u)
        if not bool(done_u.all()):
            # straggler rescue (stale seeds / growth): host fan-out path
            # (search() attributes the rescue batch to the read class)
            vals, fnd = self.search(uk)
            return vals[inv], fnd[inv]
        if hit_u is not None and hit_u.any():
            # cache hits' device fan-out rows carried an inactive unique
            # row — overwrite them client-side through the inverse map
            chit = hit_u[inv]
            found, vhi, vlo = (np.array(found), np.array(vhi),
                               np.array(vlo))
            found[:n][chit] = True
            vhi[:n][chit] = c_vhi[:uk.size][inv][chit]
            vlo[:n][chit] = c_vlo[:uk.size][inv][chit]
        _slo_observe("read", n, t_slo)
        return (bits.pairs_to_keys(vhi[:n], vlo[:n]), found[:n])

    def insert(self, keys, values, max_rounds: int | None = None) -> dict:
        """Batched upsert with host fallback for splits.

        Returns stats {applied, superseded, host_path, rounds, st_locked,
        lock_timeouts, lock_timeout_keys}: every request ends APPLIED,
        SUPERSEDED, applied through the host path, or — when its page
        lock stayed held by a live lease past the bounded retry budget —
        REJECTED with the typed ST_LOCK_TIMEOUT outcome (counted in
        lock_timeouts, keys listed in lock_timeout_keys).
        """
        self._require_writable()
        t_slo = time.perf_counter()
        if max_rounds is None:
            max_rounds = self.tcfg.insert_rounds
        keys = np.asarray(keys, np.uint64)
        if keys.size and (keys.min() < C.KEY_MIN or keys.max() > C.KEY_MAX):
            raise KeyRangeError("keys outside [KEY_MIN, KEY_MAX]")
        values = np.asarray(values, np.uint64)
        self._check_replicated(keys, values)
        n = keys.shape[0]
        total = self.cfg.machine_nr * self.B
        stats = {"applied": 0, "superseded": 0, "host_path": 0, "rounds": 0,
                 "st_locked": 0, "lock_timeouts": 0, "lock_timeout_keys": []}
        applied_rows = np.zeros(n, bool)
        for i in range(0, n, total):
            applied_rows[i:i + total] = self._insert_chunk(
                keys[i:i + total], values[i:i + total], max_rounds, stats)
        self.flush_parents()
        # ONE journal batch record of the rows that actually landed
        # (superseded duplicates carry the winner's value — excluded;
        # lock-timeout rejections never applied — excluded), durable
        # before the caller sees the stats ack
        self._journal_applied(J.J_UPSERT, keys[applied_rows],
                              values[applied_rows])
        if self.leaf_cache is not None and n:
            # write-path invalidation hook (entry versions bumped);
            # whole batch, conservatively — superseded duplicates share
            # their winner's key, rejected rows invalidate spare
            self.leaf_cache.invalidate_keys(keys)
        # the wall includes flush_parents + the durable journal append —
        # insert's ack latency, which is what an SLO target governs
        _slo_observe("insert", n, t_slo)
        return stats

    def _get_parent_descend(self, iters: int, stop_level: int = 1):
        key = (iters, stop_level)
        fn = self._parent_descend_cache.get(key)
        if fn is None:
            spec, rep = self._spec, self._rep
            sm = jax.shard_map(
                functools.partial(descend_spmd, cfg=self.cfg, iters=iters,
                                  stop_level=stop_level),
                mesh=self.dsm.mesh,
                in_specs=(spec, spec, spec, spec, rep, spec),
                out_specs=(spec, spec, spec, spec),
                check_vma=False)
            fn = DEV.wrap_program(
                "engine.parent_descend",
                jax.jit(sm, donate_argnums=C.donate_argnums(1)))
            self._parent_descend_cache[key] = fn
        return fn

    def _descend_to_level(self, keys: np.ndarray, level: int = 1):
        """Batched root -> level-``level`` descent.  -> (addrs [n],
        done [n])."""
        n = keys.shape[0]
        total = self.cfg.machine_nr * self.B
        if n > total:
            parts = [self._descend_to_level(keys[i:i + total], level)
                     for i in range(0, n, total)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        khi, klo = bits.keys_to_pairs(keys)
        (khi, _), (klo, _) = self._pad(khi), self._pad(klo)
        active, _ = self._pad(np.ones(n, bool))
        fn = self._get_parent_descend(self._iters(), level)
        args = [self._shard(khi), self._shard(klo),
                np.int32(self.tree._root_addr), self._shard(active)]
        with self._step_mutex:  # launch-only (prep above)
            self.dsm.counters, addr, _, done = fn(
                self.dsm.pool, self.dsm.counters, *args)
        addr, done = self._unshard(addr, done)
        return addr[:n], done[:n]

    def flush_parents(self) -> int:
        """Insert deferred parent entries for device-side splits — the
        internal_page_store ascent (Tree.cpp:980-987), BATCHED at every
        level: per pending level, one device descent to that level, one
        step that lock+reads every touched internal page (coalesced
        cas_read rows), a host-side sorted merge — overflowing pages
        split IN the batch (both halves coalesce into the write step;
        the promoted middle entries become next attempt's pending set,
        one level up) — and one step writing every rebuilt page together
        with all unlocks.  Root growth is the only per-key host-path
        remnant (once per tree level, not per entry).  Searches are
        correct without any of this — the B-link covers the new pages —
        it only trims sibling chases.  Returns the entries flushed."""
        import collections
        import os
        import time as _t
        dbg = os.environ.get("SHERMAN_DEBUG_INSERT")

        # atomic drain: swap the list out FIRST — building pend from the
        # live list and then reassigning [] would silently drop an entry
        # a concurrent writer appends between the two statements (reclaim
        # calls this from a maintenance thread)
        raw, self._pending_parents = self._pending_parents, []
        total = len(raw)
        if not total:
            return 0
        with obs.span("engine.insert.flush_parents", n=total):
            return self._flush_parents_drained(raw, total, dbg)

    def _flush_parents_drained(self, raw, total, dbg) -> int:
        import collections
        import time as _t

        # legacy 2-tuples target level 1
        pend = [t if len(t) == 3 else (t[0], t[1], 1) for t in raw]
        tree, dsm = self.tree, self.dsm
        for _attempt in range(12):
            if not pend:
                break
            if dbg:
                print(f"[flush] attempt {_attempt} pend={len(pend)} "
                      f"t={_t.time():.1f}", flush=True)
            tree._refresh_root()
            # entries above the current root grow the tree on the host
            # path (rare: once per new level)
            grow = [t for t in pend if t[2] > tree._root_level]
            pend = [t for t in pend if t[2] <= tree._root_level]
            for k, c, lv in grow:
                tree._insert_parent(int(k), int(c), int(lv), {})
            if not pend:
                continue

            next_pend = []
            for lv in sorted({t[2] for t in pend}):
                at_lv = [t for t in pend if t[2] == lv]
                keysu = np.array([k for k, _, _ in at_lv], np.uint64)
                t_d0 = _t.time()
                addrs, done = self._descend_to_level(keysu, lv)
                t_d1 = _t.time()

                # lock + read every unique target page in ONE step; two
                # pages hashing to one lock word -> second CAS loses ->
                # next attempt
                uaddr = [int(a) for a in np.unique(addrs[done])]
                rows = []
                for a in uaddr:
                    la = tree._lock_word_addr(a)
                    rows.append({"op": D.OP_CAS, "addr": la, "woff": 0,
                                 "arg0": 0, "arg1": tree.ctx.lease,
                                 "space": D.SPACE_LOCK})
                    rows.append({"op": D.OP_READ, "addr": a})
                rep = dsm._batch(rows)
                t_l1 = _t.time()
                pages, unlock_rows = {}, []
                for i, a in enumerate(uaddr):
                    if bool(rep.ok[2 * i]):
                        pages[a] = np.array(rep.data[2 * i + 1])
                        unlock_rows.append(tree._unlock_row(
                            tree._lock_word_addr(a)))

                group = collections.defaultdict(list)
                for (k, c, _), a, d in zip(at_lv, addrs, done):
                    if d and int(a) in pages:
                        group[int(a)].append((int(k), int(c)))
                    else:
                        next_pend.append((k, c, lv))

                write_rows, host_fb = [], []
                n_split = 0
                for a, ents_new in group.items():
                    pg = pages[a]
                    lo, hi = layout.np_lowest(pg), layout.np_highest(pg)
                    stay = [(k, c) for k, c in ents_new if lo <= k < hi]
                    next_pend += [(k, c, lv) for k, c in ents_new
                                  if not (lo <= k < hi)]  # fence moved
                    if not stay:
                        continue
                    ents = sorted(set(layout.np_internal_entries(pg)
                                      + stay))
                    if len(ents) <= C.INTERNAL_CAP:
                        newpg = layout.np_internal_rebuild(pg, ents, lv)
                        write_rows.append({"op": D.OP_WRITE, "addr": a,
                                           "woff": 0, "nw": C.PAGE_WORDS,
                                           "payload": newpg})
                        continue
                    if len(ents) > 2 * C.INTERNAL_CAP:
                        host_fb += stay  # needs >1 split (rare)
                        continue
                    # BATCHED internal split: the page is already locked,
                    # so split it HERE and coalesce both halves into the
                    # same write step (the old per-key fallback cost
                    # seconds of tunnel round trips per entry under a
                    # split storm — 398 fallbacks measured on one 131k-op
                    # chunk).  Mirrors Tree._insert_parent_inner
                    # (internal_page_store's split, Tree.cpp:980-987);
                    # the promoted middle entry joins next attempt's
                    # pending set one level up, flushed through this same
                    # batched path.
                    try:
                        sib_addr = tree.ctx.alloc.alloc()
                    except MemoryError:
                        host_fb += stay
                        continue
                    m = len(ents) // 2
                    up_key, up_child = ents[m]
                    old_high = layout.np_highest(pg)
                    old_sib = int(pg[C.W_SIBLING])
                    ver = ((int(pg[C.W_FRONT_VER]) + 1) & 0x7FFFFFFF) or 1
                    right = layout.np_empty_page(lv, up_key, old_high,
                                                 sibling=old_sib,
                                                 leftmost=up_child)
                    for i, (k2, c2) in enumerate(ents[m + 1:]):
                        layout.np_internal_set_entry(right, i, k2, c2)
                    right[C.W_NKEYS] = len(ents) - m - 1
                    left = layout.np_empty_page(
                        lv, lo, up_key, sibling=sib_addr,
                        leftmost=int(pg[C.W_LEFTMOST]), version=ver)
                    for i, (k2, c2) in enumerate(ents[:m]):
                        layout.np_internal_set_entry(left, i, k2, c2)
                    left[C.W_NKEYS] = m
                    write_rows.append({"op": D.OP_WRITE, "addr": sib_addr,
                                       "woff": 0, "nw": C.PAGE_WORDS,
                                       "payload": right})
                    write_rows.append({"op": D.OP_WRITE, "addr": a,
                                       "woff": 0, "nw": C.PAGE_WORDS,
                                       "payload": left})
                    next_pend.append((up_key, sib_addr, lv + 1))
                    n_split += 1
                t_m1 = _t.time()
                if write_rows or unlock_rows:
                    dsm.write_rows(write_rows + unlock_rows)
                if dbg:
                    print(f"[flush] lv={lv} wrote={len(write_rows)} "
                          f"splits={n_split} host_fb={len(host_fb)} "
                          f"descend={t_d1 - t_d0:.1f}s "
                          f"lock={t_l1 - t_d1:.1f}s merge={t_m1 - t_l1:.1f}s "
                          f"write={_t.time() - t_m1:.1f}s", flush=True)
                for k, c in host_fb:
                    tree._insert_parent(k, c, lv, {})
            pend = next_pend
        if dbg and pend:
            print(f"[flush] per-key fallback for {len(pend)}", flush=True)
        for k, c, lv in pend:
            tree._insert_parent(int(k), int(c), int(lv), {})
        return total

    def _fill_fresh(self, grant: bool) -> np.ndarray:
        """Per-node fresh-page grants for the next insert round ([N*F],
        0 = no grant).  Grants are node-local pages (a split's right
        sibling is written by the page's owner).  Unconsumed grants stay
        in the host cache for the next round."""
        N, F = self.cfg.machine_nr, self.split_slots
        arr = np.zeros(N * F, np.int32)
        if not grant:
            return arr
        for nd in range(N):
            lst = self._fresh_cache.setdefault(nd, [])
            while len(lst) < F:
                try:
                    lst.append(self.tree.ctx.alloc.alloc(node=nd))
                except (KeyError, MemoryError):
                    break  # node not local / partition exhausted
            arr[nd * F:nd * F + len(lst[:F])] = lst[:F]
        return arr

    def _drain_split_log(self, log, stats) -> None:
        """Apply a round's split log: reclaim unconsumed grants, refresh
        the index cache, and lazily insert the parent entries (the B-link
        already makes the split pages reachable — Tree.cpp:116-124's
        broadcast role, deferred)."""
        valid, new_addr, skhi, sklo, ohhi, ohlo = self._unshard(
            log["valid"], log["new_addr"], log["skhi"], log["sklo"],
            log["old_hhi"], log["old_hlo"])
        if not valid.any():
            return
        new_addr = new_addr[valid]
        sk = bits.pairs_to_keys(skhi[valid], sklo[valid])
        oh = bits.pairs_to_keys(ohhi[valid], ohlo[valid])
        consumed = set(int(a) for a in new_addr)
        for nd, lst in self._fresh_cache.items():
            self._fresh_cache[nd] = [a for a in lst if a not in consumed]
        stats["device_splits"] = stats.get("device_splits", 0) + len(sk)
        if self.router is not None:
            # one vectorized table update for the whole split log (the
            # per-split path costs seconds at storm volume)
            self.router.note_splits_batch(sk, new_addr, oh)
        for i in range(len(sk)):
            # parent entries are deferred (flush_parents): the B-link
            # keeps the tree correct meanwhile, and retries reach the new
            # pages through the refreshed router seeds
            self._pending_parents.append((int(sk[i]), int(new_addr[i])))

    def _insert_chunk(self, keys, values, max_rounds, stats):
        """-> applied [n] bool: rows whose OWN value landed in the pool
        (device fast path or host fallback) — the journal's record set.
        Superseded duplicates and lock-timeout rejections stay False."""
        import os
        import time as _t
        dbg = os.environ.get("SHERMAN_DEBUG_INSERT")
        n = keys.shape[0]
        applied_rows = np.zeros(n, bool)
        pending = np.ones(n, bool)
        # consecutive rounds each row spent blocked on a HELD page lock
        # (bounded lock retry: see the ST_LOCKED handling below)
        locked_rounds = np.zeros(n, np.int32)
        fresh_np = self._fill_fresh(False)  # round 0: optimistic, no splits
        # Progress-adaptive rounds: append-shaped workloads drain the
        # rightmost leaf at ~(free slots + 1) keys per round (the same
        # serialization the reference pays on the last leaf's lock), so a
        # fixed budget would spill long appends to the host path.  Keep
        # going while rounds make progress; stop after 2 stalled rounds.
        round_i, stalled = 0, 0
        router_usable = self.router is not None
        while round_i < max_rounds or (stalled < 2
                                       and round_i < max_rounds * 16):
            round_i += 1
            if dbg:
                print(f"[ins] round {round_i} pending={pending.sum()} "
                      f"t={_t.time():.1f}", flush=True)
            if not pending.any():
                return applied_rows
            n_before = int(pending.sum())
            stats["rounds"] += 1
            idx = np.nonzero(pending)[0]
            khi, klo = bits.keys_to_pairs(keys[idx])
            vhi, vlo = bits.keys_to_pairs(values[idx])
            (khi, _), (klo, _) = self._pad(khi), self._pad(klo)
            (vhi, _), (vlo, _) = self._pad(vhi), self._pad(vlo)
            active, _ = self._pad(np.ones(idx.shape[0], bool))
            # The router is CORRECT on every round (seeds never land right
            # of a key's leaf; note_split keeps it current), and retries
            # then land directly on freshly split leaves.  But seeds that
            # land far left of a key's leaf (a cold unseeded table deep in
            # a tall tree, or a coarse span right after _grow_span) can
            # cost sibling chases beyond the descent budget, and such
            # keys would retry FOREVER: once a round makes no progress,
            # LATCH off the router for the rest of the chunk and use root
            # descents (fence-guided, height-bounded) like search's
            # straggler retry.  (Sub-2^32 keyspaces used to be the main
            # trigger; they now bucket at full resolution — the latch
            # remains the generic no-progress backstop.  It also avoids
            # oscillating: resetting on progress would re-enable the same
            # seeds every other round.)  First fallback round pays a
            # one-time compile of the no-seed insert kernel; cached after.
            if stalled > 0:
                router_usable = False
            use_router = router_usable
            # the compiled program SHAPE must agree across processes:
            # fresh_np holds only this process's local-node grants, so a
            # per-process any() could diverge (one host exhausted, another
            # granted) and mismatched SPMD programs deadlock the mesh —
            # multihost always keeps the fixed with-fresh shape
            with_fresh = self._mh or bool(fresh_np.any())
            fn = self._get_insert(self._iters(), use_router, with_fresh)
            if self._write_combine:
                self._note_combine_step(int(np.count_nonzero(active)))
            args = [self._shard(khi), self._shard(klo),
                    self._shard(vhi), self._shard(vlo),
                    np.int32(self.tree._root_addr), self._shard(active)]
            if use_router:
                args.append(self._shard(self.router.host_start(khi, klo)))
            if with_fresh:
                args.append(self._shard(fresh_np))
            # one fused device round: descend + lock + leaf apply (+
            # splits); the span drains at the status materialization
            with obs.span("engine.insert.descend_lock_apply",
                          n=int(idx.shape[0]), round=round_i):
                with self._step_mutex:  # launch-only (prep above)
                    if with_fresh:
                        (self.dsm.pool, self.dsm.counters, self.dsm.dirty,
                         status, log) = fn(
                            self.dsm.pool, self.dsm.locks,
                            self.dsm.counters, self.dsm.dirty, *args)
                    else:
                        (self.dsm.pool, self.dsm.counters, self.dsm.dirty,
                         status) = fn(
                            self.dsm.pool, self.dsm.locks,
                            self.dsm.counters, self.dsm.dirty, *args)
                        log = None
                status = self._unshard(status)[:idx.shape[0]]
            if dbg:
                import collections as _c
                print(f"[ins] status {dict(_c.Counter(status.tolist()))} "
                      f"t={_t.time():.1f}", flush=True)
            # host-held page locks surface as ST_LOCKED retries (the
            # protocol linchpin under concurrent host writers); count them
            # so drivers/tests can assert the interleaving really happened
            stats["st_locked"] += int((status == ST_LOCKED).sum())
            if log is not None:
                with obs.span("engine.insert.split_drain"):
                    self._drain_split_log(log, stats)
            if len(self._pending_parents) >= self.parent_flush_threshold:
                # flush between rounds: parents keep descent paths short —
                # deferring across many split rounds can grow a B-link
                # chain past the static descent budget, spilling the batch
                # tail to the per-key host path.  (With a router attached,
                # note_split already retargets the affected buckets, so
                # storm drivers raise the threshold and flush per chunk.)
                self.flush_parents()

            stats["applied"] += int((status == ST_APPLIED).sum())
            stats["superseded"] += int((status == ST_SUPERSEDED).sum())
            applied_rows[idx[status == ST_APPLIED]] = True
            done = (status == ST_APPLIED) | (status == ST_SUPERSEDED)
            pending[idx[done]] = False

            # Bounded lock retry with backoff (data-plane failure story):
            # a row blocked on a HELD page lock for lock_retry_rounds
            # consecutive rounds triggers a lease probe — a DEAD holder
            # (client died mid-critical-section) is revoked and the row
            # retries fresh; a LIVE holder is normal contention and
            # keeps retrying (with host-side backoff) through the round
            # budget.  Rows still lock-blocked when the budget runs out
            # get the typed ST_LOCK_TIMEOUT rejection below instead of
            # the host path's unbounded spin.
            lr = status == ST_LOCKED
            locked_rounds[idx[lr]] += 1
            locked_rounds[idx[~lr]] = 0
            probe = np.zeros(n, bool)
            probe[idx] = lr & (locked_rounds[idx]
                               % self.tcfg.lock_retry_rounds == 0)
            if probe.any():
                live = self._recover_wedged_locks(keys[probe])
                # reset ONLY rows whose lock was dead (now revoked) or
                # already freed — a live-blocked row must keep its
                # counter so budget exhaustion still rejects it typed
                rows_p = np.nonzero(probe)[0]
                locked_rounds[rows_p[~live]] = 0
            if lr.any():
                # brief host-side backoff before re-spinning on held
                # locks (doubles per consecutive blocked round, capped)
                _t.sleep(min(2e-4 * (1 << min(int(locked_rounds.max()),
                                              6)), 2e-2))

            # ST_FULL keys retry with fresh-page grants: the next round
            # splits their leaves on-device.  ST_BAD shouldn't happen but
            # is retried via host for robustness.
            bad = status == ST_BAD
            for j in idx[bad]:
                self.tree.insert(int(keys[j]), int(values[j]))
                stats["host_path"] += 1
                applied_rows[j] = True
                pending[j] = False
            if bad.any():
                self.tree._refresh_root()
            # grant fresh pages whenever anything retries: suppressed
            # writers on a splitting page report ST_RETRY, and their next
            # round may need to split again — granting only on ST_FULL
            # would split every OTHER round
            fresh_np = self._fill_fresh(
                bool(((status == ST_FULL) | (status == ST_RETRY)).any()))
            stalled = stalled + 1 if int(pending.sum()) == n_before else 0
        # Round budget exhausted.  Rows that ended it still blocked on a
        # page lock held by a LIVE lease get the typed ST_LOCK_TIMEOUT
        # rejection: handing them to the host path would trade a bounded
        # budget for an unbounded spin on a holder that never drained
        # (dead leases were revoked by the probes above, and one final
        # probe here catches a holder that died after the last round).
        still = np.nonzero(pending)[0]
        blocked = still[locked_rounds[still] > 0]
        if blocked.size:
            live_mask = self._recover_wedged_locks(keys[blocked])
            to = blocked[live_mask]
            if to.size:
                stats["lock_timeouts"] += int(to.size)
                stats["lock_timeout_keys"] += [int(k) for k in keys[to]]
                pending[to] = False
                _OBS_LOCK_TIMEOUTS.inc(int(to.size))
        # anything still pending after max_rounds: host path
        for j in np.nonzero(pending)[0]:
            self.tree.insert(int(keys[j]), int(values[j]))
            stats["host_path"] += 1
            applied_rows[j] = True
        return applied_rows

    def _recover_wedged_locks(self, keys: np.ndarray) -> np.ndarray:
        """Lock-lease recovery for keys blocked on held page locks:
        resolve each key's leaf with one device descent, read the
        leaves' global lock words in one step, and revoke every holder
        whose lease is DEAD — delegated per word to
        ``Tree._try_revoke_lease``, the single revocation policy (lease
        decode, epoch-table liveness, masked CAS, lease.* counters).
        -> live_mask [bool, aligned with keys]: True where the lock is
        held by a LIVE lease (legit contention or a stuck-but-alive
        peer — never revoked here).  Rides ``host_dsm``, so it is
        collective-safe: in multihost mode every process calls with the
        identical replicated key set and the revocation executes once
        cluster-wide."""
        tree = self.tree
        keys = np.asarray(keys, np.uint64)
        addrs, done = self._descend_to_level(keys, 0)
        la_by_key = np.array(
            [tree._lock_word_addr(int(a)) if d else -1
             for a, d in zip(addrs, done)], np.int64)
        las = sorted({int(la) for la in la_by_key if la != -1})
        if not las:
            return np.zeros(keys.shape[0], bool)
        rep = self.dsm._batch(
            [{"op": D.OP_READ_WORD, "addr": la, "woff": 0,
              "space": D.SPACE_LOCK} for la in las])
        live_las = {la for la, w in zip(las, rep.old)
                    if int(w) != 0
                    and not tree._try_revoke_lease(la, int(w))}
        return np.array([int(la) in live_las for la in la_by_key])

    def reclaim_empty_leaves(self, quarantine_rounds: int = 2) -> dict:
        """Unlink EMPTY leaves from the B-link chain and recycle their
        pages — beyond-reference: ``free()`` is a no-op in the reference
        (``DSM.h:226``, ``LocalAllocator.h:45-47``), so delete/churn
        workloads leak the pool dry.  Single-process meshes only (a local
        maintenance pass; multihost reclamation would need a replicated
        drive and is out of scope).

        Protocol, per (left, empty) adjacent leaf pair:

        MULTIHOST: a replicated COLLECTIVE — every process must call it
        at the same point with the same ``quarantine_rounds`` (digest-
        checked).  The pass then runs the PARITY #7 pattern implicitly:
        the plan is deterministic host code over mirrored state (the
        chain scan and every lock/verify/write step ride the leader-
        posted ReplicatedDSM; the allocator free pools are mirrored
        directories), so all processes compute and apply the identical
        plan in lock-step.  Calling it on a subset of processes
        deadlocks the collective steps — same contract as flush_parents.

        1. one jitted pool scan finds candidates (``leaf_chain_info``):
           an ACTIVE leaf with zero live slots whose chain predecessor
           exists (the leftmost leaf is never reclaimed — bounded waste,
           it is the chain's sentinel);
        2. lock left+empty (global CAS words; a shared hash word locks
           once), re-verify under the locks (left.sibling == empty, still
           empty, fences abut), then ONE atomic step rewrites left's
           header (sibling/highest bypass the empty leaf, front/rear
           version bump — a structural rewrite) and RETIRES the empty
           leaf: ``highest := 0`` refuses reads and writes structurally
           (every fence check fails), and ``sibling := left`` sends stale
           readers BACK to the absorbing leaf, which now owns the range;
        3. the retired leaf's parent entry is removed (lock + rebuild,
           the flush_parents merge protocol) — required before reuse: a
           stale parent entry must keep resolving to the RETIRED page
           (which self-heals via its back-sibling), never to a reused
           one.  A retired page referenced as a parent's LEFTMOST child
           is PARKED instead (retired forever, never freed — repointing
           the leftmost would dangle once its target is itself reused;
           bounded at ~1/INTERNAL_CAP of reclaimable leaves).  Cleanup
           failures stay pending and retry on the next call; retired
           strays found by the scan (e.g. in-flight state lost at a
           checkpoint/restore boundary) re-enter this path, so reclaim
           is crash-recoverable;
        4. quarantine: cleaned pages return to their node's allocator
           free pool only after ``quarantine_rounds`` further calls — the
           grace period for concurrent host clients still holding
           pre-unlink addresses (steps are serialized, so in-flight
           device work cannot straddle the boundary; the window is host
           threads mid-descent).

        Returns {"unlinked", "freed", "quarantined", "candidates"}.
        """
        self._require_writable()  # reclaim rewrites pages: not degraded
        # replicated-collective contract (multihost): identical call
        # sites + identical args on every process, pinned by the same
        # digest check the other engine drivers use.  The engine-local
        # reclaim round counter rides the digest so a process that
        # skipped an earlier reclaim call fails loudly here instead of
        # desyncing the mirrored allocator pools; the deferred-parent
        # count rides it too so a process whose writer thread raced an
        # entry in fails HERE, not by desyncing the flush_parents
        # collective the drain below would run on a subset of processes.
        self._check_replicated(np.array(
            [quarantine_rounds, self._reclaim_state["round"],
             len(self._pending_parents)], np.uint64))
        if not self._reclaim_mutex.acquire(blocking=False):
            raise StateError(
                "reclaim_empty_leaves is not reentrant: another reclaim "
                "pass is already running on this engine")
        try:
            return self._reclaim_empty_leaves_locked(quarantine_rounds)
        finally:
            self._reclaim_mutex.release()

    def _reclaim_empty_leaves_locked(self, quarantine_rounds: int) -> dict:
        from sherman_tpu.models.validate import leaf_chain_info
        tree, dsm = self.tree, self.dsm
        # Drain deferred parent entries BEFORE scanning: a pending
        # (k -> c) entry not yet flushed leaves leaf c with no parent
        # entry to find, so parent removal would quarantine it while the
        # deferred flush still owes a parent entry pointing at it — the
        # flush would then alias a freed/reused page.
        if self._pending_parents:
            self.flush_parents()
        st = self._reclaim_state
        st["round"] += 1
        stats = {"unlinked": 0, "freed": 0, "candidates": 0,
                 "quarantined": len(st["quarantine"]),
                 "parked": len(st["parked"])}

        # Snapshot the released-page state BEFORE the scan: a page freed
        # at snapshot time is either still free at scan time (snapshot
        # covers it) or was popped and rewritten by a writer (the scan
        # then no longer sees it as retired).  Snapshotting AFTER the
        # scan would leave a window where a writer pops a scanned-
        # retired page out of the pool and the sweep double-frees it.
        released = set()
        for nd, d in self.tree.ctx.alloc._by_node.items():
            for p in d.allocator.free_pages_list:
                released.add((nd << C.ADDR_PAGE_BITS) | p)
        for lst in self._fresh_cache.values():
            for a in lst:
                released.add(int(a) & 0xFFFFFFFF)
        # the chain scan launches on the CURRENT pool handle: hold the
        # step mutex so a concurrent host writer's donated-buffer swap
        # cannot invalidate the handle between read and launch (the scan
        # materializes inside, so the mutex spans one kernel execution —
        # acceptable for a maintenance pass)
        with self._step_mutex:
            (addrs, lows, highs, sibs, n_live,
             retired_addrs, retired_lows) = leaf_chain_info(tree)
        tree._refresh_root()
        quarantined = {a for _, a in st["quarantine"]}
        # sweep retired strays: pages unlinked by a PREVIOUS incarnation
        # (in-flight quarantine/cleanup state is engine-local and not
        # checkpointed) re-enter the parent-cleanup -> quarantine path
        # here, so a restored cluster's reclaim calls recover them.
        # `known` MUST also cover pages already RELEASED — the pre-scan
        # `released` snapshot of the allocator free pools and cached
        # split grants — because a freed page still LOOKS retired until
        # its next write; sweeping one would double-free it into the
        # pool (the same page granted twice = silent aliasing).
        known = (quarantined | st["parked"] | released
                 | {e for e, _, _ in st["pending_parent"]})
        for ra, rl in zip(retired_addrs.tolist(), retired_lows.tolist()):
            if ra not in known:
                st["pending_parent"].append((int(ra), int(rl), 0))
        # adjacent pairs with chain continuity; greedy-alternate so a
        # pair's left member is never itself unlinked this round.  Pages
        # still owed a deferred parent entry (appended after the flush
        # above, e.g. by a concurrent writer's split log) are excluded:
        # their parent entry does not exist yet, so parent removal would
        # wrongly conclude they are unreferenced.
        pend_children = {int(t[1]) & 0xFFFFFFFF
                         for t in self._pending_parents}
        pairs = []
        taken = set()
        for i in range(1, addrs.size):
            L, E = int(addrs[i - 1]), int(addrs[i])
            if (n_live[i] == 0 and sibs[i - 1] == E and E not in taken
                    and L not in taken and E not in quarantined
                    and (E & 0xFFFFFFFF) not in pend_children
                    and E != tree._root_addr):
                pairs.append((L, E, int(lows[i]), int(highs[i])))
                taken.add(E)
                taken.add(L)
        stats["candidates"] = len(pairs)

        # Two host steps for ALL pairs (the flush_parents coalescing
        # pattern — per-pair round trips would cost seconds each over an
        # access tunnel): one step CAS-locks every pair's word(s) and
        # reads both pages; one step writes every verified unlink plus
        # every unlock.  Pairs sharing a lock word with an earlier pair
        # are deferred to the next call (CAS outcomes would be ambiguous
        # across pairs).
        seen_words: set = set()
        plan = []
        for L, E, e_low, e_high in pairs:
            la, ea = tree._lock_word_addr(L), tree._lock_word_addr(E)
            words = (la,) if la == ea else (la, ea)
            if any(w in seen_words for w in words):
                continue
            seen_words.update(words)
            plan.append((L, E, e_low, e_high, words))
        rows = []
        base = {}
        for L, E, e_low, e_high, words in plan:
            base[E] = len(rows)
            for w in words:
                rows.append({"op": D.OP_CAS, "addr": w, "woff": 0,
                             "arg0": 0, "arg1": tree.ctx.lease,
                             "space": D.SPACE_LOCK})
            rows.append({"op": D.OP_READ, "addr": L})
            rows.append({"op": D.OP_READ, "addr": E})
        rep = dsm._batch(rows) if rows else None
        w1 = lambda a, w, v: {"op": D.OP_WRITE, "addr": a, "woff": w,
                              "nw": 1, "payload": np.array([v], np.int32)}
        out_rows = []
        mapping: dict[int, int] = {}
        for L, E, e_low, e_high, words in plan:
            i0 = base[E]
            got = [bool(rep.ok[i0 + j]) for j in range(len(words))]
            held = [w for w, g in zip(words, got) if g]
            if not all(got):
                out_rows += [tree._unlock_row(w) for w in held]
                continue
            lpg = np.array(rep.data[i0 + len(words)])
            epg = np.array(rep.data[i0 + len(words) + 1])
            ok = (int(lpg[C.W_SIBLING]) & 0xFFFFFFFF) == (E & 0xFFFFFFFF) \
                and layout.np_highest(lpg) == e_low \
                and layout.np_lowest(epg) == e_low \
                and layout.np_highest(epg) == e_high \
                and not layout.np_leaf_entries(epg)
            if not ok:
                out_rows += [tree._unlock_row(w) for w in held]
                continue
            ver = ((int(lpg[C.W_FRONT_VER]) + 1) & 0x7FFFFFFF) or 1
            hh, hl = bits.key_to_pair(e_high)
            out_rows += [
                # left absorbs the range: highest/sibling bypass E
                w1(L, C.W_HIGH_HI, hh), w1(L, C.W_HIGH_LO, hl),
                w1(L, C.W_SIBLING, int(epg[C.W_SIBLING])),
                w1(L, C.W_FRONT_VER, ver), w1(L, C.W_REAR_VER, ver),
                # E retires: highest=0 refuses every fence check; sibling
                # points BACK at the absorber so stale readers self-heal
                w1(E, C.W_HIGH_HI, 0), w1(E, C.W_HIGH_LO, 0),
                w1(E, C.W_SIBLING, np.int32(np.uint32(L & 0xFFFFFFFF)
                                            .view(np.int32))),
            ] + [tree._unlock_row(w) for w in held]
            st["pending_parent"].append((E, e_low, L))
            mapping[E] = L
            stats["unlinked"] += 1
            if tree.index_cache is not None:
                tree.index_cache.invalidate(e_low)
        if out_rows:
            dsm._batch(out_rows)
        if mapping and self.router is not None:
            self.router.remap_addrs(mapping)
        if mapping and self.leaf_cache is not None:
            # reclaim rewrites the absorber's header and retires the
            # empty page for eventual reuse: drop every cached entry on
            # either side of each unlinked pair (the retired page holds
            # no live keys, but a later reuse must never meet a stale
            # cached position)
            self.leaf_cache.invalidate_pages(
                list(mapping.keys()) + list(mapping.values()))

        # parent-entry removal for unlinked pages (flush-style); only
        # cleaned pages advance to quarantine
        if st["pending_parent"]:
            st["pending_parent"] = self._remove_parent_entries(
                st["pending_parent"], st)

        # release quarantine
        ready = [(r, a) for r, a in st["quarantine"]
                 if st["round"] - r >= quarantine_rounds]
        st["quarantine"] = [(r, a) for r, a in st["quarantine"]
                            if st["round"] - r < quarantine_rounds]
        by_node: dict[int, list[int]] = {}
        for _, a in ready:
            by_node.setdefault(bits.addr_node(a), []).append(
                bits.addr_page(a))
        for nd, pgs in by_node.items():
            d = self.tree.ctx.alloc._by_node.get(nd)
            if d is None:
                # non-local node: keep quarantined rather than leak
                st["quarantine"].extend((st["round"], bits.make_addr(nd, p))
                                        for p in pgs)
                continue
            d.allocator.reclaim(pgs)
            stats["freed"] += len(pgs)
        stats["quarantined"] = len(st["quarantine"])
        stats["parked"] = len(st["parked"])
        return stats

    def _remove_parent_entries(self, pend, st) -> list:
        """Remove retired pages' parent entries (lock + rebuild, the
        flush_parents merge protocol).  Cleaned pages enter quarantine;
        failures stay pending for the next reclaim call."""
        tree, dsm = self.tree, self.dsm
        tree._refresh_root()
        if tree._root_level < 1:
            # root is a leaf: no parents exist; straight to quarantine
            for e, _k, _l in pend:
                st["quarantine"].append((st["round"], e))
            return []
        keysu = np.array([k for _, k, _ in pend], np.uint64)
        # descend by the retired page's OLD low fence: its parent entry
        # (if any) lives on that path's level-1 page
        paddrs, done = self._descend_to_level(keysu, 1)
        group: dict[int, list[tuple[int, int, int]]] = {}
        nxt: list = []
        for (e, k, ab), a, d_ok in zip(pend, paddrs, done):
            if d_ok:
                group.setdefault(int(a), []).append((e, k, ab))
            else:
                nxt.append((e, k, ab))
        # TWO host steps for ALL parents (the unlink stage's coalescing
        # pattern): one step CAS-locks + reads every grouped parent, one
        # step writes every rebuilt page together with all unlocks.
        # Per-parent round trips measured seconds EACH over an access
        # tunnel — a churn pass touching ~10^3 parents took tens of
        # minutes.  Parents sharing a lock word with an earlier parent
        # defer to the next call (CAS outcomes across same-word rows in
        # one step would be ambiguous).
        seen_words: set = set()
        plan = []
        for pa, items in group.items():
            la = tree._lock_word_addr(pa)
            if la in seen_words:
                nxt.extend(items)
                continue
            seen_words.add(la)
            plan.append((pa, la, items))
        rows = []
        for pa, la, _items in plan:
            rows.append({"op": D.OP_CAS, "addr": la, "woff": 0, "arg0": 0,
                         "arg1": tree.ctx.lease, "space": D.SPACE_LOCK})
            rows.append({"op": D.OP_READ, "addr": pa})
        rep = dsm._batch(rows) if rows else None
        out_rows = []
        decisions = []
        for i, (pa, la, items) in enumerate(plan):
            if not bool(rep.ok[2 * i]):
                nxt.extend(items)
                continue
            pg = np.array(rep.data[2 * i + 1])
            if int(pg[C.W_LEVEL]) != 1:
                # fence moved / wrong page: retry next round
                out_rows.append(tree._unlock_row(la))
                nxt.extend(items)
                continue
            # fence re-check UNDER the lock (the same guard flush_parents
            # applies at its merge step): a concurrent split of this
            # parent between the descent and the CAS moves entries >= the
            # split key to the right sibling.  An item whose key the
            # locked page no longer covers may have its entry alive over
            # there — concluding "entry absent, page unreferenced" from
            # THIS page would quarantine and reuse a page a live parent
            # entry still resolves to.  Uncovered items retry next round.
            lo, hi = layout.np_lowest(pg), layout.np_highest(pg)
            covered = [t for t in items if lo <= t[1] < hi]
            nxt.extend(t for t in items if not (lo <= t[1] < hi))
            if not covered:
                out_rows.append(tree._unlock_row(la))
                continue
            items = covered
            drop = {e & 0xFFFFFFFF for e, _, _ in items}
            ents = [(k, c) for k, c in layout.np_internal_entries(pg)
                    if (c & 0xFFFFFFFF) not in drop]
            kept = {c & 0xFFFFFFFF for _, c in ents}
            newpg = layout.np_internal_rebuild(pg, ents, 1)
            lm = int(pg[C.W_LEFTMOST]) & 0xFFFFFFFF
            out_rows.append({"op": D.OP_WRITE, "addr": pa, "woff": 0,
                             "nw": C.PAGE_WORDS, "payload": newpg})
            out_rows.append(tree._unlock_row(la))
            decisions.append((items, kept, lm))
        # quarantine/park decisions apply ONLY after the write batch
        # lands: if it raises, st is untouched and the caller's
        # pending_parent assignment never happens, so every item stays
        # pending and retries — a failed batch must never quarantine
        # (-> later free + reuse) a page whose parent entry survived
        # on-device.
        if out_rows:
            dsm._batch(out_rows)
        for items, kept, lm in decisions:
            for e, k, ab in items:
                eu = e & 0xFFFFFFFF
                if eu == lm:
                    # this parent's LEFTMOST child: the pointer cannot be
                    # dropped (the page has no left entry) and repointing
                    # it at the absorber would dangle once the absorber
                    # is itself reclaimed and reused.  PARK the page: it
                    # stays retired forever (reads/writes refuse via the
                    # zero fence; stale descents self-heal through its
                    # back-sibling) and is never freed.
                    st["parked"].add(e)
                elif eu in kept:  # entry elsewhere: retry
                    nxt.append((e, k, ab))
                else:
                    st["quarantine"].append((st["round"], e))
        return nxt

    def range_query(self, lo: int, hi: int) -> tuple[np.ndarray, np.ndarray]:
        """All (k, v) with lo <= k < hi, sorted.  See
        :meth:`range_query_many`."""
        return self.range_query_many([(lo, hi)])[0]

    def range_query_many(self, ranges) -> list[tuple[np.ndarray, np.ndarray]]:
        """Batched scans: ONE device gather prefetches the candidate
        leaves of EVERY range, then each range walks its chain over the
        shared prefetch.  The multi-scan analogue of the reference's
        kParaFetch window (Tree.cpp:501-522): where it pipelines 32
        fetches within one scan, the batched server amortizes the whole
        scan SET into one step.  ranges: iterable of (lo, hi); returns
        [(keys, vals)] per range, each sorted by key."""
        ranges = [(int(lo), int(hi)) for lo, hi in ranges]
        # replication guard: the chain walk issues a data-dependent number
        # of collective host reads — divergent bounds would desync them
        self._check_replicated(
            np.asarray([b for r in ranges for b in r], np.uint64))
        t_slo = time.perf_counter()
        out = range_query_many(self, ranges)
        # scans: one op per range (row counts vary per range; the SLO
        # unit is the client request, as for every other class)
        _slo_observe("scan", len(ranges), t_slo)
        return out

    def delete(self, keys, max_rounds: int | None = None) -> np.ndarray:
        """Batched delete (``Tree::del`` parity).  Returns found bool [n]
        (True where the key existed and was removed)."""
        self._require_writable()
        t_slo = time.perf_counter()
        if max_rounds is None:
            max_rounds = self.tcfg.insert_rounds
        keys = np.asarray(keys, np.uint64)
        if keys.size and (keys.min() < C.KEY_MIN or keys.max() > C.KEY_MAX):
            raise KeyRangeError("keys outside [KEY_MIN, KEY_MAX]")
        self._check_replicated(keys)
        n = keys.shape[0]
        total = self.cfg.machine_nr * self.B
        out = np.zeros(n, bool)
        for i in range(0, n, total):
            out[i:i + total] = self._delete_chunk(keys[i:i + total],
                                                  max_rounds)
        # journal the deletes that actually cleared a slot (not-found
        # rows are no-ops; replaying them would also be, but keeping the
        # record set == applied set keeps replay accounting exact)
        self._journal_applied(J.J_DELETE, keys[out])
        if self.leaf_cache is not None and n:
            self.leaf_cache.invalidate_keys(keys)
        _slo_observe("delete", n, t_slo)
        return out

    def _delete_chunk(self, keys, max_rounds) -> np.ndarray:
        n = keys.shape[0]
        found_out = np.zeros(n, bool)
        pending = np.ones(n, bool)
        for round_i in range(max_rounds):
            if not pending.any():
                return found_out
            idx = np.nonzero(pending)[0]
            khi, klo = bits.keys_to_pairs(keys[idx])
            (khi, _), (klo, _) = self._pad(khi), self._pad(klo)
            active, _ = self._pad(np.ones(idx.shape[0], bool))
            use_router = self.router is not None and round_i == 0
            fn = self._get_delete(self._iters(), use_router)
            args = [self._shard(khi), self._shard(klo),
                    np.int32(self.tree._root_addr), self._shard(active)]
            if use_router:
                args.append(self._shard(self.router.host_start(khi, klo)))
            with obs.span("engine.delete.descend_lock_apply",
                          n=int(idx.shape[0])):
                with self._step_mutex:  # launch-only (prep above)
                    (self.dsm.pool, self.dsm.counters, self.dsm.dirty,
                     status) = fn(
                        self.dsm.pool, self.dsm.locks, self.dsm.counters,
                        self.dsm.dirty, *args)
                status = self._unshard(status)[:idx.shape[0]]

            found_out[idx[status == ST_APPLIED]] = True
            done = (status == ST_APPLIED) | (status == ST_NOT_FOUND)
            pending[idx[done]] = False
            bad = status == ST_BAD
            for j in idx[bad]:
                found_out[j] = self.tree.delete(int(keys[j]))
                pending[j] = False
            if bad.any():
                self.tree._refresh_root()
        for j in np.nonzero(pending)[0]:
            found_out[j] = self.tree.delete(int(keys[j]))
        return found_out


# ---------------------------------------------------------------------------
# Range query: cache-seeded batched leaf fetch (Tree.cpp:461-522).
# ---------------------------------------------------------------------------

def _addr_rows(addrs: np.ndarray, pages_per_node: int) -> np.ndarray:
    """Packed addrs -> global pool row indices (host)."""
    a = np.asarray(addrs).astype(np.uint32).astype(np.uint64)
    return ((a >> C.ADDR_PAGE_BITS) * np.uint64(pages_per_node)
            + (a & np.uint64(C.ADDR_PAGE_MASK))).astype(np.int64)


@jax.jit
def _gather_rows(pool, rows):
    return pool[rows]


def range_query_many(eng: "BatchedEngine", ranges
                     ) -> list[tuple[np.ndarray, np.ndarray]]:
    """Batched scans: all (k, v) with lo <= k < hi per range, sorted.

    TPU-native shape of the reference's pipelined scan
    (``Tree.cpp:461-522``): the index cache (router table) yields the
    candidate leaf set of EVERY range in O(1); ONE device gather fetches
    the union of candidate pages (beating the reference's 32-deep fetch
    window, and amortizing the host<->device round trip over the whole
    scan set); each range then walks its B-link chain over the shared
    prefetch and only touches the DSM again for chain gaps (stale
    cache), mirroring the re-descend fallback.
    """
    tree = eng.tree
    cfg = eng.cfg
    # materialize + coerce: callers may pass generators or numpy scalars
    ranges = [(int(lo), int(hi)) for lo, hi in ranges]
    for lo, hi in ranges:
        assert C.KEY_MIN <= lo and hi <= C.KEY_POS_INF and lo < hi

    # -- candidate prefetch from the router table (union of all ranges) ----
    fetched: dict[int, np.ndarray] = {}
    if eng.router is not None and ranges:
        r = eng.router
        cand_parts = []
        with r._read_locked():
            for lo, hi in ranges:
                # clamp BOTH ends into the table: out-of-span ranges
                # (common with narrow-keyspace seeds) start from the last
                # bucket's seed instead of silently skipping the prefetch
                b_lo = min(r.nb - 1, lo >> r.shift)
                b_hi = min(r.nb - 1, max(0, (hi - 1) >> r.shift))
                cand_parts.append(r.table_np[b_lo:b_hi + 1])
        cand = np.unique(np.concatenate(cand_parts))
        if cand.size:
            if eng._mh:
                # replicated host reads (chunked collective steps)
                pages = tree.dsm.read_pages([int(a) for a in cand])
            else:
                rows = _addr_rows(cand, cfg.pages_per_node)
                with eng._step_mutex:  # pool handle read vs donating steps
                    got = _gather_rows(eng.dsm.pool, jnp.asarray(rows))
                pages = np.asarray(got)
            for a, p in zip(cand.tolist(), pages):
                if int(p[C.W_LEVEL]) == 0:   # stale entries may be internal
                    fetched[int(a) & 0xFFFFFFFF] = p

    # pages fetched during chain walks (router misses) join `extras` so
    # later ranges starting inside them skip the re-descend
    extras: dict[int, np.ndarray] = {}

    def get_page(addr: int) -> np.ndarray:
        p = fetched.get(addr & 0xFFFFFFFF)
        if p is None:
            p = tree.dsm.read_page(addr)
            fetched[addr & 0xFFFFFFFF] = p
            extras[addr & 0xFFFFFFFF] = p
        return p

    # sorted (lowest -> addr) index over the prefetch: start-leaf lookup
    # per range is a binary search, not a scan of every fetched page
    if fetched:
        f_addrs = np.fromiter(fetched.keys(), np.int64, len(fetched))
        f_lows = np.array([layout.np_lowest(fetched[int(a)])
                           for a in f_addrs], np.uint64)
        f_highs = np.array([layout.np_highest(fetched[int(a)])
                            for a in f_addrs], np.uint64)
        f_order = np.argsort(f_lows)
        f_addrs, f_lows, f_highs = (f_addrs[f_order], f_lows[f_order],
                                    f_highs[f_order])
    else:
        f_addrs = np.zeros(0, np.int64)
        f_lows = f_highs = np.zeros(0, np.uint64)

    out: list[tuple[np.ndarray, np.ndarray]] = []
    for lo, hi in ranges:
        # -- find the first leaf containing lo ------------------------------
        start = None
        i = int(np.searchsorted(f_lows, np.uint64(lo), side="right")) - 1
        if i >= 0 and lo < int(f_highs[i]):
            start = int(f_addrs[i])
        if start is None:
            for a, p in extras.items():   # walk-fetched pages, few
                if layout.np_lowest(p) <= lo < layout.np_highest(p):
                    start = a
                    break
        if start is None:
            start, _, _ = tree._descend(lo, 0)

        # -- walk the chain -------------------------------------------------
        addr = start
        chain_pages = []
        hops = 0
        while True:
            pg = get_page(addr)
            chain_pages.append(pg)
            if layout.np_highest(pg) >= hi:
                break
            sib = int(pg[C.W_SIBLING])
            if bits.addr_is_null(sib):
                break
            addr = sib
            hops += 1
            assert hops < cfg.machine_nr * cfg.pages_per_node, \
                "chain runaway"
        pages = np.stack(chain_pages)
        keys, vals, live = layout.np_leaf_entries_batch(pages)
        m = live & (keys >= np.uint64(lo)) & (keys < np.uint64(hi))
        out_k, out_v = keys[m], vals[m]
        order = np.argsort(out_k)
        out.append((out_k[order], out_v[order]))
    return out


# ---------------------------------------------------------------------------
# Bulk load: bottom-up tree construction (benchmark warmup path).
# ---------------------------------------------------------------------------

def _install_pages_impl(pool, rows, pages):
    return pool.at[rows].set(pages)


@functools.lru_cache(maxsize=None)
def _install_pages_jit():
    # jitted lazily so the donation decision (backend-gated — see
    # config.donate_argnums) never initializes the backend at import
    return jax.jit(_install_pages_impl,
                   donate_argnums=C.donate_argnums(0))


def _install_pages(pool, rows, pages):
    return _install_pages_jit()(pool, rows, pages)


@functools.lru_cache(maxsize=None)
def _build_install_leaves_jit():
    return jax.jit(_build_install_leaves_impl,
                   donate_argnums=C.donate_argnums(0),
                   static_argnames=("per_leaf",))


def _build_install_leaves(pool, rows, khi, klo, vhi, vlo, live,
                          lhi, llo, hhi, hlo, sib, *, per_leaf: int):
    return _build_install_leaves_jit()(
        pool, rows, khi, klo, vhi, vlo, live, lhi, llo, hhi, hlo, sib,
        per_leaf=per_leaf)


def _build_install_leaves_impl(pool, rows, khi, klo, vhi, vlo, live,
                               lhi, llo, hhi, hlo, sib, *, per_leaf: int):
    """Build all leaf pages ON DEVICE and scatter them into the pool.

    The leaf level is ~97% of a bulk load's bytes; building it device-side
    ships 4 words per entry (khi/klo/vhi/vlo) instead of whole 256-word
    pages — ~2.7x less host->device traffic — and the build itself is
    reshape/pad/concat work the VPU does in milliseconds.  Entries are
    packed sequentially ``per_leaf`` per page (sorted bulk keys), so the
    [L, CAP] field blocks are plain reshapes of the flat word arrays —
    no scatter until the final page install.

    rows: [L] pool row of each leaf; khi..vlo: [L*per_leaf] padded flat
    entry words; live: [L*per_leaf] int32 slot liveness; lhi..sib: [L]
    header words.
    """
    L = rows.shape[0]
    pad_cols = ((0, 0), (0, C.LEAF_CAP - per_leaf))

    def blk(x):
        return jnp.pad(x.reshape(L, per_leaf), pad_cols)

    page = _leaf_pages(blk(khi), blk(klo), blk(vhi), blk(vlo),
                       blk(live).astype(bool), jnp.ones(L, jnp.int32),
                       lhi, llo, hhi, hlo, sib)
    return pool.at[rows].set(page)

def bulk_load(tree, keys, values, fill: float | None = None) -> dict:
    """Build the tree bottom-up from unique sorted keys and install it.

    The host builds every page vectorized in numpy and writes the whole pool
    once — the analogue of the benchmark's warmup phase
    (``test/benchmark.cpp:114-120``) at TPU speed.  Returns stats.
    """
    cfg = tree.cfg
    if fill is None:
        fill = TreeConfig().bulk_fill
    # replicated-driver invariant: every process must bulk-load the
    # identical data (mirrored allocators depend on it)
    _assert_replicated(tree.dsm.multihost,
                       (np.asarray(keys, np.uint64),
                        np.asarray(values, np.uint64)), "bulk_load")
    # Guard: bulk load replaces the whole tree, so refuse to drop existing
    # data — the current tree must be an empty root leaf.
    tree._refresh_root()
    old_root = tree._root_addr
    old_pg = tree.dsm.read_page(old_root)
    if tree._root_level != 0 or layout.np_leaf_entries(old_pg):
        raise ConfigError("bulk_load requires an empty tree")

    keys = np.asarray(keys, np.uint64)
    values = np.asarray(values, np.uint64)
    order = np.argsort(keys, kind="stable")
    keys, values = keys[order], values[order]
    assert (np.diff(keys) > 0).all(), "bulk_load requires unique keys"
    n = keys.shape[0]

    per_leaf = max(1, min(C.LEAF_CAP, int(C.LEAF_CAP * fill)))
    n_leaves = max(1, -(-n // per_leaf))

    # multi-controller jit needs explicit (replicated) global arrays for
    # the non-sharded operands; single-process passes host arrays through
    if tree.dsm.multihost:
        rep_shard = jax.sharding.NamedSharding(
            tree.dsm.mesh, jax.sharding.PartitionSpec())
        mk = lambda x: jax.make_array_from_callback(
            x.shape, rep_shard, lambda idx: x[idx])
    else:
        mk = jnp.asarray

    # --- leaf level: built ON DEVICE (_build_install_leaves) ----------------
    alloc = tree.ctx.alloc
    leaf_addrs = alloc.alloc_many(n_leaves)
    total = n_leaves * per_leaf
    khi, klo = bits.keys_to_pairs(keys)
    vhi, vlo = bits.keys_to_pairs(values)
    pad = total - n
    flat = lambda x: mk(np.pad(x, (0, pad)))
    live = np.zeros(total, np.int32)
    live[:n] = 1

    # fences: lowest = first key of leaf (leaf 0: -inf); highest = next
    # leaf's first key (last: +inf); sibling links left->right
    first_keys = keys[::per_leaf][:n_leaves]
    lows = np.empty(n_leaves, np.uint64)
    lows[0] = C.KEY_NEG_INF
    lows[1:] = first_keys[1:]
    highs = np.empty(n_leaves, np.uint64)
    highs[:-1] = first_keys[1:]
    highs[-1] = C.KEY_POS_INF
    lhi, llo = bits.keys_to_pairs(lows)
    hhi, hlo = bits.keys_to_pairs(highs)
    sib = np.zeros(n_leaves, np.int32)
    sib[:-1] = leaf_addrs[1:].astype(np.int32)
    leaf_rows = _addr_rows(leaf_addrs, cfg.pages_per_node)
    tree.dsm.pool = _build_install_leaves(
        tree.dsm.pool, mk(leaf_rows), flat(khi), flat(klo), flat(vhi),
        flat(vlo), mk(live), mk(lhi), mk(llo), mk(hhi), mk(hlo), mk(sib),
        per_leaf=per_leaf)
    # direct installs bypass the step path: mark for delta checkpoints
    tree.dsm.mark_dirty_rows(leaf_rows)

    all_pages = []
    all_addrs = []
    stats = {"leaves": n_leaves, "internal": 0, "levels": 1}

    # --- internal levels ----------------------------------------------------
    level = 0
    child_addrs = leaf_addrs
    child_lows = lows
    while len(child_addrs) > 1:
        level += 1
        # children per internal page (incl leftmost): same fill slack as
        # leaves — packing internal pages to capacity would force an
        # internal split on the FIRST post-bulk leaf split under them
        fan = max(2, int(C.INTERNAL_CAP * fill))
        m = len(child_addrs)
        n_pages = -(-m // fan)
        addrs = alloc.alloc_many(n_pages)
        ipages = np.zeros((n_pages, _PW), np.int32)
        ipages[:, C.W_FRONT_VER] = 1
        ipages[:, C.W_REAR_VER] = 1
        ipages[:, C.W_LEVEL] = level

        pg_of = np.arange(m) // fan
        pos = np.arange(m) % fan
        # first child of each page -> leftmost; rest -> entries keyed by
        # the child's lowest fence
        is_first = pos == 0
        ipages[pg_of[is_first], C.W_LEFTMOST] = \
            child_addrs[is_first].astype(np.int32)
        ent = pos - 1
        ei = ~is_first
        eslot = ent[ei]
        ckhi, cklo = bits.keys_to_pairs(child_lows[ei])
        ipages[pg_of[ei], C.I_KHI_W + eslot] = ckhi
        ipages[pg_of[ei], C.I_KLO_W + eslot] = cklo
        ipages[pg_of[ei], C.I_PTR_W + eslot] = child_addrs[ei].astype(np.int32)
        counts = np.bincount(pg_of, minlength=n_pages) - 1
        ipages[:, C.W_NKEYS] = counts.astype(np.int32)

        pfirst = child_lows[::fan][:n_pages]
        plows = np.empty(n_pages, np.uint64)
        plows[0] = C.KEY_NEG_INF
        plows[1:] = pfirst[1:]
        phighs = np.empty(n_pages, np.uint64)
        phighs[:-1] = pfirst[1:]
        phighs[-1] = C.KEY_POS_INF
        lhi, llo = bits.keys_to_pairs(plows)
        hhi, hlo = bits.keys_to_pairs(phighs)
        ipages[:, C.W_LOW_HI], ipages[:, C.W_LOW_LO] = lhi, llo
        ipages[:, C.W_HIGH_HI], ipages[:, C.W_HIGH_LO] = hhi, hlo
        ipages[:-1, C.W_SIBLING] = addrs[1:].astype(np.int32)

        all_pages.append(ipages)
        all_addrs.append(addrs)
        stats["internal"] += n_pages
        stats["levels"] += 1
        child_addrs, child_lows = addrs, plows

    root_addr = int(child_addrs[0])
    root_level = level

    # --- install internal levels (the ~3% the host still builds) -----------
    if all_addrs:
        flat_addrs = np.concatenate(all_addrs)
        flat_pages = np.concatenate(all_pages, axis=0)
        rows = _addr_rows(flat_addrs, cfg.pages_per_node)
        tree.dsm.pool = _install_pages(tree.dsm.pool, mk(rows),
                                       mk(flat_pages))
        tree.dsm.mark_dirty_rows(rows)

    # Install root (bulk load is cluster-quiescent) and POISON the old root:
    # clients holding a stale root handle recover through the B-link chase
    # (btree.py's correctness invariant), so the old root must chase into the
    # new tree — set its highest fence to -inf (every key overshoots) and its
    # sibling to the new root.
    old_poison = old_pg.copy()
    old_poison[C.W_HIGH_HI] = 0
    old_poison[C.W_HIGH_LO] = 0
    old_poison[C.W_SIBLING] = root_addr
    tree.dsm.write_rows([
        {"op": D.OP_WRITE, "addr": old_root, "woff": 0,
         "nw": C.PAGE_WORDS, "payload": old_poison},
        {"op": D.OP_WRITE_WORD, "addr": META_ADDR,
         "woff": C.META_ROOT_ADDR_W, "arg1": root_addr},
    ])
    tree.cluster.broadcast_new_root(root_addr, root_level)
    tree._root_addr, tree._root_level = root_addr, root_level
    stats["root_level"] = root_level

    # leaf directory for index-cache seeding (router.seed_from_leaves)
    tree._bulk_leaf_dir = (leaf_addrs.copy(), lows.copy())
    if tree.router is not None:
        tree.router.seed_from_leaves(leaf_addrs, lows)
    return stats
