"""The distributed B+Tree — client logic over the one-sided DSM.

Mirrors the reference index (``include/Tree.h``, ``src/Tree.cpp``): a B-link
tree of 1 KB pages living in the cluster-wide pool, accessed purely with
one-sided reads, lock CAS, and coalesced write+unlock steps.  The memory
nodes' CPUs never run index code (only chunk MALLOC / NEW_ROOT, served by
:class:`~sherman_tpu.parallel.alloc.Directory`).

This module is the *host orchestration* path: correct for every operation
(including splits and deletes), used for control-plane work, slow paths and
as the executable spec for the batched device kernels
(:mod:`sherman_tpu.models.batched`).  Protocol parity notes:

- Locking: global lock word = CAS on the owner node's lock table at
  ``hash(page_addr) % locks_per_node`` (``Tree.cpp:702-707,832-842``), spin
  with a deadlock reporter (``Tree.cpp:219-227``).
- Write-back: a no-split insert writes ONE leaf entry + the unlock word in a
  single DSM step — the single-entry write-back + write+unlock doorbell
  coalescing (``Tree.cpp:914-921``, ``Operation.cpp:351-380``).  A split
  writes sibling page + old page + unlock in one step, which makes the split
  *atomically visible* (stronger than the reference's ordered writes).
- B-link: every page carries a sibling pointer and a [lowest, highest)
  fence; readers chase siblings on overshoot (``Tree.cpp:626-629,648-651``),
  so stale roots/parents never break correctness, only add hops.
- Root: packed addr + level in the reserved meta page (node 0, page 0),
  installed by CAS (``Tree.cpp:55``, root slot parity ``Tree.cpp:90-97``),
  broadcast via NEW_ROOT (``Tree.cpp:116-124``).

Reference options deliberately NOT carried over (``Common.h:19-23``):

- ``CONFIG_ENABLE_CRC`` (page checksum): guards against torn NIC reads.
  The DSM's step-atomic visibility makes a torn page *unobservable* —
  a read returns one pre-step snapshot — so the CRC's failure mode
  cannot occur; the front/rear page versions and per-entry version
  pairs are kept for protocol parity and cross-step interleavings.
  They now also EARN their keep: the online scrubber
  (``models/scrub.py``) treats a torn front/rear pair or a torn
  per-entry pair as corruption (unreachable by legal step-atomic
  writes) — the CRC's detection role, served by the version protocol,
  and provable end-to-end with chaos injection (``sherman_tpu/chaos``).
- ``CONFIG_ENABLE_EMBEDDING_LOCK`` (lock word inside the page): an
  alternative to the on-chip lock table.  The separate per-node lock
  space IS the on-chip table analogue and composes with coalesced
  cas_read/write+unlock chains; embedding would save nothing here
  (same step count) while costing a page word the SoA layout uses
  for entries.
"""

from __future__ import annotations

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ProtocolError
from sherman_tpu.cluster import ClientContext, Cluster
from sherman_tpu.ops import bits, layout
from sherman_tpu.parallel import dsm as D

META_ADDR = bits.make_addr(0, 0)
LOCK_SPIN_LIMIT = 1_000_000  # deadlock reporter threshold (Tree.cpp:219-227)
# Failed spins on a HELD lock before the spin loop consults the lease
# table about the holder (a host-local dict lookup, no extra DSM op) and
# revokes a dead owner's lock by masked CAS.  Small so a wedged lock
# (client died mid-critical-section) resolves in a handful of steps; the
# reporter threshold above still bounds the wait on a LIVE holder.
LEASE_PROBE_SPINS = 4

# Index-cache effectiveness counters (the reference counts cache
# hit/miss rates by hand in its benchmark threads; here they ride the
# process registry — lock-free increments on the descent path).
_OBS_CACHE_HITS = obs.counter("btree.cache_hits")
_OBS_CACHE_MISSES = obs.counter("btree.cache_misses")
_OBS_CACHE_INVALIDATIONS = obs.counter("btree.cache_invalidations")
_OBS_SIBLING_CHASES = obs.counter("btree.sibling_chases")
_OBS_ROOT_REFRESHES = obs.counter("btree.root_refreshes")

# Lock-lease recovery counters (data-plane failure story): revocations
# of dead holders' locks, lost revocation races (another client got
# there first, or the holder moved), and deadlock reports on live
# holders.
_OBS_LEASE_REVOKED = obs.counter("lease.revoked")
_OBS_LEASE_REVOKE_LOST = obs.counter("lease.revoke_lost")
_OBS_DEADLOCK_REPORTS = obs.counter("lease.deadlock_reports")


class Tree:
    # device index cache handle (models/router.py); attached by the
    # batched engine, notified on leaf splits
    router = None
    # host index cache handle (native.IndexCache); see enable_index_cache
    index_cache = None

    def __init__(self, cluster: Cluster, ctx: ClientContext | None = None):
        self.cluster = cluster
        # host-API handle: the raw DSM single-process, the replicated
        # leader-posted wrapper on a process-spanning mesh (host ops
        # execute once cluster-wide); device state passes through either
        self.dsm = cluster.host_dsm
        self.cfg = cluster.cfg
        # the Tree host path IS replicated control flow in multi-host
        # deployments (all its DSM ops ride cluster.host_dsm)
        self.ctx = (ctx if ctx is not None
                    else cluster.register_client(replicated=True))
        # hierarchical lock, local tier (shared per process via the
        # cluster; None when the native lib is unavailable)
        self._llocks = cluster.local_locks
        self._lheld: dict[int, int] = {}   # lock addr -> local table index
        self._lpass: dict[int, bool] = {}  # lock addr -> handover decision
        # Injectable deadlock-reporter threshold (Tree.cpp:219-227 kept
        # the 10^6 constant unreachable in tests; SHERMAN_LOCK_SPIN_LIMIT
        # or a direct attribute write makes the path testable and lets
        # latency-sensitive deployments bound the wait).
        import os
        self.lock_spin_limit = int(
            os.environ.get("SHERMAN_LOCK_SPIN_LIMIT", LOCK_SPIN_LIMIT))

        # Adopt an existing root if one is installed; otherwise construct an
        # empty root leaf and CAS-install it (one winner across the cluster,
        # Tree.cpp:48-55).  The pre-read avoids leaking a page per client
        # handle (free() is a no-op, faithful to the reference).
        existing = self.dsm.read_word(META_ADDR, C.META_ROOT_ADDR_W)
        if existing != 0:
            self._root_addr = existing
            self._root_level = int(self.dsm.read_page(existing)[C.W_LEVEL])
            return
        root = self.ctx.alloc.alloc()
        pg = layout.np_empty_page(level=0, lowest=C.KEY_NEG_INF,
                                  highest=C.KEY_POS_INF)
        self.dsm.write_page(root, pg)
        old, ok = self.dsm.cas(META_ADDR, C.META_ROOT_ADDR_W, 0, root)
        if ok:
            self.cluster.broadcast_new_root(root, 0)
            self._root_addr, self._root_level = root, 0
        else:
            self._root_addr = old
            self._root_level = int(self.dsm.read_page(old)[C.W_LEVEL])

    # -- root helpers --------------------------------------------------------
    # The root's level is read from the root page itself (W_LEVEL), so the
    # root install is a SINGLE atomic CAS on the meta addr word — a separate
    # meta level word could be observed stale by a concurrent root-grow and
    # let it install a second root that orphans the tree.

    def _refresh_root(self) -> None:
        self._root_addr = self.dsm.read_word(META_ADDR, C.META_ROOT_ADDR_W)
        self._root_level = int(
            self.dsm.read_page(self._root_addr)[C.W_LEVEL])

    # -- locking: hierarchical — node-local ticket tier with bounded
    #    hand-over in front of the global CAS word (Sherman technique #1,
    #    Tree.cpp:1124-1173 + 205-242).  Same-process clients queue on the
    #    native ticket lock; the holder hands the GLOBAL lock down the
    #    train (<= kMaxHandOverTime=8), so a train pays ONE remote CAS and
    #    ONE remote unlock.  (The batched device path replaces this with
    #    in-step request combining — contention there collapses within the
    #    step itself.) -------------------------------------------------------

    def _lock_word_addr(self, page_addr: int) -> int:
        node = bits.addr_node(page_addr)
        idx = bits.lock_index_host(page_addr, self.cfg.locks_per_node)
        return bits.make_addr(node, idx)

    def _acquire_local(self, la: int) -> bool:
        """Join the local ticket queue for lock word ``la``
        (acquire_local_lock, Tree.cpp:1125-1147); blocks until this
        client holds the local lock.  -> True when the GLOBAL lock was
        handed over with it (skip the remote CAS)."""
        if self._llocks is None:
            return False
        li = (bits.addr_node(la) * self.cfg.locks_per_node
              + bits.addr_page(la))
        self._lheld[la] = li
        return self._llocks.acquire(li)

    def _abort_local(self, la: int) -> None:
        """Drop the local ticket on a failed GLOBAL acquisition (deadlock
        reporter path): never hand over (we don't hold the global lock),
        and clear the held entry so other local clients don't spin on a
        leaked ticket forever."""
        li = self._lheld.pop(la, None)
        if li is not None:
            self._llocks.release(li, False)

    def _abort_held_local(self) -> None:
        """Exception cleanup: drop EVERY held local ticket (no hand-over).
        The global word may stay leaked — exactly the pre-local-tier
        failure mode, where contenders hit LOCK_SPIN_LIMIT and raise a
        diagnosable error instead of hard-spinning on a dead ticket."""
        for la in list(self._lheld):
            self._abort_local(la)
        self._lpass.clear()

    def _try_revoke_lease(self, la: int, observed: int) -> bool:
        """Lock-lease recovery (the FUSEE-style repairable-metadata
        shape): if the observed holder of lock word ``la`` is DEAD per
        the cluster's epoch table, revoke its lock with a masked CAS on
        the lease fields and return True (caller retries acquisition
        immediately).  A LIVE holder returns False — the caller keeps
        spinning toward the deadlock reporter.  Sound because DSM steps
        are atomic: a dead client's protected write either landed whole
        or not at all, so freeing its lock never exposes a torn page."""
        owner = bits.lease_owner(observed)
        if owner == 0:
            return True  # freed between CAS and probe: just retry
        if self.cluster.lease_is_live(owner, bits.lease_epoch(observed)):
            return False
        _, won = self.dsm.masked_cas(la, 0, observed, 0, bits.LEASE_MASK,
                                     space=D.SPACE_LOCK)
        (_OBS_LEASE_REVOKED if won else _OBS_LEASE_REVOKE_LOST).inc()
        if won:
            obs.record_event("lease.revoked", lock_word=int(la),
                             owner=int(owner),
                             epoch=int(bits.lease_epoch(observed)))
        return True  # lost race = someone else revoked/acquired: retry

    def _deadlock_report(self, la: int, old: int) -> RuntimeError:
        """The reporter (Tree.cpp:219-227), now lease-aware: names the
        lock word, the holder's tag/epoch, and whether its lease is
        live (a dead lease reaching here means revocation kept losing
        races — diagnosable, not silent)."""
        _OBS_DEADLOCK_REPORTS.inc()
        owner = bits.lease_owner(old)
        live = self.cluster.lease_is_live(owner, bits.lease_epoch(old))
        verdict = ("live lease; not revocable" if live
                   else "dead lease; revocation kept losing")
        return RuntimeError(
            f"possible deadlock on lock {la:#x}: holder tag {owner} "
            f"epoch {bits.lease_epoch(old)} ({verdict}) after "
            f"{self.lock_spin_limit} spins")

    def _lock(self, page_addr: int) -> int:
        la = self._lock_word_addr(page_addr)
        if self._acquire_local(la):
            return la
        spins = 0
        while True:
            old, ok = self.dsm.cas(la, 0, 0, self.ctx.lease,
                                   space=D.SPACE_LOCK)
            if ok:
                return la
            spins += 1
            if spins >= LEASE_PROBE_SPINS:
                self._try_revoke_lease(la, old)  # dead holder -> freed
            if spins > self.lock_spin_limit:
                self._abort_local(la)
                raise self._deadlock_report(la, old)

    def _lock_and_read(self, page_addr: int) -> tuple[int, np.ndarray]:
        """Acquire the page's global lock and fetch the page in ONE step —
        lock_and_read_page (Tree.cpp:300-308) over the coalesced
        rdmaCasRead chain (Operation.cpp:382-414).  The snapshot the step
        returns is valid under the lock because the previous holder's
        payload write and unlock landed together in one earlier step.
        On a local hand-over the global lock is already ours: a plain
        read suffices (the predecessor's write step landed before its
        release).  -> (lock_addr, page)."""
        la = self._lock_word_addr(page_addr)
        if self._acquire_local(la):
            return la, self.dsm.read_page(page_addr)
        spins = 0
        while True:
            old, ok, pg = self.dsm.cas_read(la, 0, 0, self.ctx.lease,
                                            page_addr)
            if ok:
                return la, pg
            spins += 1
            if spins >= LEASE_PROBE_SPINS:
                self._try_revoke_lease(la, old)  # dead holder -> freed
            if spins > self.lock_spin_limit:
                self._abort_local(la)
                raise self._deadlock_report(la, old)

    def _unlock_row(self, lock_addr: int) -> dict:
        """Raw global-unlock request row (no local tier involvement)."""
        return {"op": D.OP_WRITE_WORD, "addr": lock_addr, "woff": 0,
                "arg1": 0, "space": D.SPACE_LOCK}

    def _unlock_rows(self, lock_addr: int) -> list[dict]:
        """Unlock rows to coalesce into the protected write step — EMPTY
        when the global lock will be handed to a local waiter
        (can_hand_over, Tree.cpp:1149-1167), keeping the remote unlock
        off the wire for the train.  The decision is made before the
        step and is binding (waiters block; see locks.cc).  Callers MUST
        call :meth:`_release_local` after the step lands."""
        if lock_addr in self._lheld:
            pas = self._llocks.can_handover(self._lheld[lock_addr])
            self._lpass[lock_addr] = pas
            if pas:
                return []
        return [self._unlock_row(lock_addr)]

    def _release_local(self, lock_addr: int) -> None:
        """Release the local ticket AFTER the protected write step landed
        (releases_local_lock, Tree.cpp:1169-1173): the next local holder
        then reads post-step state.  Must follow every _unlock_rows."""
        li = self._lheld.pop(lock_addr, None)
        if li is None:
            return
        decided = self._lpass.pop(lock_addr, False)
        passed = self._llocks.release(li, decided)
        if decided and not passed:
            # A decided hand-over that did not pass means locks.cc broke
            # its contract (waiters block, so a True can_handover probe is
            # binding).  Repair the global word so the cluster stays
            # unwedged for diagnosis, then surface the protocol violation
            # instead of silently masking it.
            self.dsm.write_word(lock_addr, 0, 0, space=D.SPACE_LOCK)
            raise ProtocolError(
                f"local-lock hand-over invariant violated on {lock_addr:#x}"
                ": can_handover said True but release did not pass the "
                "lock (locks.cc contract breach)")

    def _unlock(self, lock_addr: int) -> None:
        rows = self._unlock_rows(lock_addr)
        if rows:
            self.dsm.write_rows(rows)
        self._release_local(lock_addr)

    def _write_and_unlock(self, rows: list[dict], lock_addr: int) -> None:
        """Protected-write epilogue, made structural: coalesce the global
        unlock into the payload step (or hand the lock down the local
        train), then release the local ticket AFTER the step lands and
        BEFORE any further lock acquisition (a parent's lock word may
        hash onto the same local ticket — self-deadlock otherwise)."""
        self.dsm.write_rows(rows + self._unlock_rows(lock_addr))
        self._release_local(lock_addr)

    # -- index cache (host tier) ---------------------------------------------

    def enable_index_cache(self, capacity: int = 1 << 16) -> None:
        """Attach the native compute-node IndexCache (IndexCache.h role):
        descents that hit jump straight to the leaf, skipping every
        internal level (Tree.cpp:415-427)."""
        from sherman_tpu import native
        self.index_cache = native.IndexCache(capacity)

    def _cache_level1(self, pg: np.ndarray, key: int) -> None:
        """Record the child range covering `key` from a level-1 page
        (add_to_cache on level-1 fetch, Tree.cpp:644-646).  Only the one
        range this miss actually needed — caching all ~fanout children per
        descent would pay O(fanout) cache maintenance on every miss."""
        lo = layout.np_lowest(pg)
        prev_key, prev_child = lo, int(pg[C.W_LEFTMOST])
        for k, child in layout.np_internal_entries(pg):
            if key < k:
                break
            prev_key, prev_child = k, child
        else:
            k = layout.np_highest(pg)
        self.index_cache.add(prev_key, k, prev_child)

    # -- descent -------------------------------------------------------------

    def _descend(self, key: int, stop_level: int = 0):
        """Walk root -> stop_level; -> (addr, page, path{level: addr}).

        The hot read loop (Tree.cpp:429-458): one one-sided page read per
        level, B-link sibling chase on overshoot.  With the index cache
        attached, a hit seeds the walk at the leaf (Tree.cpp:415-427); a
        stale hit invalidates and restarts from the root
        (Tree.cpp:430-443).
        """
        addr = self._root_addr
        from_cache = False
        if stop_level == 0 and self.index_cache is not None:
            hit = self.index_cache.lookup(key)
            if hit:
                addr, from_cache = hit, True
                _OBS_CACHE_HITS.inc()
            else:
                _OBS_CACHE_MISSES.inc()
        path: dict[int, int] = {}
        hops = 0
        while True:
            pg = self.dsm.read_page(addr)
            lvl = int(pg[C.W_LEVEL])
            if from_cache and (lvl != 0 or key < layout.np_lowest(pg)):
                # stale cache entry (page repurposed is impossible — pages
                # are never freed — but a non-leaf/fence miss means the
                # mapping is junk): drop it, restart uncached
                self.index_cache.invalidate(key)
                _OBS_CACHE_INVALIDATIONS.inc()
                addr, from_cache = self._root_addr, False
                continue
            if key >= layout.np_highest(pg):
                if from_cache:
                    # split moved the key right since caching: invalidate,
                    # then chase the sibling (cheaper than a full restart)
                    self.index_cache.invalidate(key)
                    _OBS_CACHE_INVALIDATIONS.inc()
                sib = int(pg[C.W_SIBLING])
                if bits.addr_is_null(sib):
                    # stale root cache (concurrent new root): refresh
                    self._refresh_root()
                    addr = self._root_addr
                    _OBS_ROOT_REFRESHES.inc()
                else:
                    addr = sib
                    _OBS_SIBLING_CHASES.inc()
                from_cache = False
                hops += 1
                assert hops < 1000, "sibling chase runaway"
                continue
            path[lvl] = addr
            if lvl == stop_level:
                return addr, pg, path
            if lvl == 1 and self.index_cache is not None:
                self._cache_level1(pg, key)
            addr = layout.np_pick_child(pg, key)

    # -- public API (Tree.h:45-63 surface) -----------------------------------

    def search(self, key: int) -> int | None:
        assert C.KEY_MIN <= key <= C.KEY_MAX
        _, pg, _ = self._descend(key, 0)
        _, val = layout.np_leaf_find(pg, key)
        return val

    def insert(self, key: int, value: int) -> None:
        assert C.KEY_MIN <= key <= C.KEY_MAX
        try:
            while True:
                addr, _, path = self._descend(key, 0)
                if self._leaf_store(addr, key, value, path):
                    return
        except BaseException:
            self._abort_held_local()
            raise

    def delete(self, key: int) -> bool:
        assert C.KEY_MIN <= key <= C.KEY_MAX
        try:
            return self._delete(key)
        except BaseException:
            self._abort_held_local()
            raise

    def _delete(self, key: int) -> bool:
        while True:
            addr, _, _ = self._descend(key, 0)
            la, pg = self._lock_and_read(addr)
            if not (layout.np_lowest(pg) <= key < layout.np_highest(pg)):
                self._unlock(la)
                continue  # concurrent split: re-descend
            slot, _ = layout.np_leaf_find(pg, key)
            if slot < 0:
                self._unlock(la)
                return False
            # clear the slot's packed version word: fver==rver==0 marks it
            # free (SoA layout: the five fields live in separate blocks,
            # but only the version pair decides liveness)
            wv = layout.leaf_slot_words(slot)[0]
            self._write_and_unlock([
                {"op": D.OP_WRITE, "addr": addr, "woff": wv, "nw": 1,
                 "payload": np.zeros(1, np.int32)},
            ], la)
            return True

    def range_query(self, lo: int, hi: int) -> dict[int, int]:
        """All (k, v) with lo <= k < hi (Tree.cpp:461-522)."""
        out: dict[int, int] = {}
        addr, pg, _ = self._descend(lo, 0)
        while True:
            for k, v, _ in layout.np_leaf_entries(pg):
                if lo <= k < hi:
                    out[k] = v
            if layout.np_highest(pg) >= hi:
                return out
            sib = int(pg[C.W_SIBLING])
            if bits.addr_is_null(sib):
                return out
            pg = self.dsm.read_page(sib)

    # -- write path ----------------------------------------------------------

    def _leaf_store(self, addr: int, key: int, value: int,
                    path: dict[int, int]) -> bool:
        """leaf_page_store (Tree.cpp:828-987).  True on success, False to
        re-descend (fence moved under us)."""
        la, pg = self._lock_and_read(addr)  # fused lock + fresh read
        if not (layout.np_lowest(pg) <= key < layout.np_highest(pg)):
            self._unlock(la)
            return False

        slot, _ = layout.np_leaf_find(pg, key)
        if slot < 0:
            slot = layout.np_leaf_free_slot(pg)
        if slot >= 0:
            # in-place update / free-slot insert: write ONE entry + unlock
            # in one step (single-entry write-back, Tree.cpp:914-921).
            words = layout.leaf_slot_words(slot)
            old_fv = (int(pg[words[0]]) >> 16) & C.ENTRY_VER_MASK
            ver = (old_fv + 1) & C.ENTRY_VER_MASK or 1
            khi_, klo_ = bits.key_to_pair(key)
            vhi_, vlo_ = bits.key_to_pair(value)
            vals = (layout.ver_pack_np(ver), khi_, klo_, vhi_, vlo_)
            rows = [
                {"op": D.OP_WRITE, "addr": addr, "woff": w, "nw": 1,
                 "payload": np.array([v], np.int32)}
                for w, v in zip(words, vals)
            ]
            self._write_and_unlock(rows, la)
            return True

        # Leaf full: split (Tree.cpp:922-963).
        ents = [(k, v) for k, v, _ in layout.np_leaf_entries(pg)]
        ents.append((key, value))
        ents.sort()
        m = len(ents) // 2
        split_key = ents[m][0]
        sib_addr = self.ctx.alloc.alloc()
        old_high = layout.np_highest(pg)
        old_sib = int(pg[C.W_SIBLING])
        ver = int(pg[C.W_FRONT_VER]) + 1

        right = layout.np_empty_page(0, split_key, old_high, sibling=old_sib,
                                     version=1)
        for i, (k, v) in enumerate(ents[m:]):
            layout.np_leaf_set_entry(right, i, k, v)
        left = layout.np_empty_page(0, layout.np_lowest(pg), split_key,
                                    sibling=sib_addr, version=ver)
        for i, (k, v) in enumerate(ents[:m]):
            layout.np_leaf_set_entry(left, i, k, v)

        # sibling + rebuilt page + unlock all in ONE step: atomic split.
        self._write_and_unlock([
            {"op": D.OP_WRITE, "addr": sib_addr, "woff": 0,
             "nw": C.PAGE_WORDS, "payload": right},
            {"op": D.OP_WRITE, "addr": addr, "woff": 0,
             "nw": C.PAGE_WORDS, "payload": left},
        ], la)
        if self.router is not None:
            self.router.note_split(split_key, sib_addr, old_high)
        self._insert_parent(split_key, sib_addr, 1, path)
        return True

    def _insert_parent(self, key: int, child: int, level: int,
                       path: dict[int, int]) -> None:
        """See :meth:`_insert_parent_inner`; wrapper drops held local
        tickets on exceptions (called directly by the engine's
        flush_parents outside insert()'s own cleanup scope)."""
        try:
            self._insert_parent_inner(key, child, level, path)
        except BaseException:
            self._abort_held_local()
            raise

    def _insert_parent_inner(self, key: int, child: int, level: int,
                             path: dict[int, int]) -> None:
        """internal_page_store + root growth (Tree.cpp:980-987,116-124).

        Root growth always anchors the new root's leftmost pointer at the
        CURRENT root: the old root is the leftmost page of its level (its
        ``lowest`` fence is -inf forever), so every page of that level is
        reachable from it via the B-link chain.  Anchoring at the split's
        left half instead would orphan everything left of an arbitrary
        split when parent insertions are deferred (device-split logs
        flush out of order)."""
        if self._root_level < level:
            self._refresh_root()
        if self._root_level < level:
            # Grow the tree: new root over the whole old-root level.
            new_root = self.ctx.alloc.alloc()
            pg = layout.np_empty_page(level, C.KEY_NEG_INF, C.KEY_POS_INF,
                                      leftmost=self._root_addr)
            layout.np_internal_set_entry(pg, 0, key, child)
            pg[C.W_NKEYS] = 1
            self.dsm.write_page(new_root, pg)
            old, ok = self.dsm.cas(META_ADDR, C.META_ROOT_ADDR_W,
                                   self._root_addr, new_root)
            if ok:
                self.cluster.broadcast_new_root(new_root, level)
                self._root_addr, self._root_level = new_root, level
                return
            # lost the race: fall through and insert into the real tree
            self._refresh_root()

        addr = path.get(level)
        if addr is None:
            addr, _, _ = self._descend(key, level)
        while True:
            la, pg = self._lock_and_read(addr)
            if key >= layout.np_highest(pg):
                self._unlock(la)
                sib = int(pg[C.W_SIBLING])
                if bits.addr_is_null(sib):
                    addr, _, _ = self._descend(key, level)
                else:
                    addr = sib
                continue
            break

        ents = layout.np_internal_entries(pg)
        ents.append((key, child))
        ents.sort()
        if len(ents) <= C.INTERNAL_CAP:
            newpg = layout.np_internal_rebuild(pg, ents, level)
            self._write_and_unlock([
                {"op": D.OP_WRITE, "addr": addr, "woff": 0,
                 "nw": C.PAGE_WORDS, "payload": newpg},
            ], la)
            return

        # Internal split: middle key moves up.
        m = len(ents) // 2
        up_key, up_child = ents[m]
        sib_addr = self.ctx.alloc.alloc()
        old_high = layout.np_highest(pg)
        old_sib = int(pg[C.W_SIBLING])
        ver = int(pg[C.W_FRONT_VER]) + 1

        right = layout.np_empty_page(level, up_key, old_high, sibling=old_sib,
                                     leftmost=up_child)
        for i, (k, c) in enumerate(ents[m + 1:]):
            layout.np_internal_set_entry(right, i, k, c)
        right[C.W_NKEYS] = len(ents) - m - 1
        left = layout.np_empty_page(level, layout.np_lowest(pg), up_key,
                                    sibling=sib_addr,
                                    leftmost=int(pg[C.W_LEFTMOST]),
                                    version=ver)
        for i, (k, c) in enumerate(ents[:m]):
            layout.np_internal_set_entry(left, i, k, c)
        left[C.W_NKEYS] = m

        self._write_and_unlock([
            {"op": D.OP_WRITE, "addr": sib_addr, "woff": 0,
             "nw": C.PAGE_WORDS, "payload": right},
            {"op": D.OP_WRITE, "addr": addr, "woff": 0,
             "nw": C.PAGE_WORDS, "payload": left},
        ], la)
        self._insert_parent(up_key, sib_addr, level + 1, path)

    def lock_bench(self, key: int, loops: int = 100) -> float:
        """Micro-bench hook (Tree.cpp:310-321): lock/unlock round trips on
        the global lock table word for ``key``; returns ns per round trip."""
        import time
        pa = bits.make_addr(0, key)
        t0 = time.perf_counter_ns()
        for _ in range(loops):
            la = self._lock(pa)
            self._unlock(la)
        return (time.perf_counter_ns() - t0) / max(loops, 1)

    # -- diagnostics (print_and_check_tree parity, Tree.cpp:151-203) ---------

    def check_structure(self) -> dict:
        """Walk the leftmost spine + leaf sibling chain; validate fences and
        key order.  Returns stats; raises on invariant violations."""
        self._refresh_root()
        stats = {"levels": self._root_level + 1, "leaves": 0, "keys": 0,
                 "internal_pages": 0}
        # walk down the leftmost spine
        addr = self._root_addr
        for lvl in range(self._root_level, 0, -1):
            pg = self.dsm.read_page(addr)
            assert int(pg[C.W_LEVEL]) == lvl, "level mismatch on spine"
            # count pages across this level via sibling chain
            a, n = addr, 0
            while not bits.addr_is_null(a):
                p = self.dsm.read_page(a)
                n += 1
                ents = layout.np_internal_entries(p)
                keys = [k for k, _ in ents]
                assert keys == sorted(keys), "unsorted internal page"
                a = int(p[C.W_SIBLING])
            stats["internal_pages"] += n
            addr = int(pg[C.W_LEFTMOST])
        # leaf chain
        a = addr
        last_high = None
        while not bits.addr_is_null(a):
            p = self.dsm.read_page(a)
            assert int(p[C.W_LEVEL]) == 0
            lo, hi = layout.np_lowest(p), layout.np_highest(p)
            if last_high is not None:
                assert lo == last_high, "leaf fence gap"
            for k, _, _ in layout.np_leaf_entries(p):
                assert lo <= k < hi, "leaf key outside fence"
                stats["keys"] += 1
            stats["leaves"] += 1
            last_high = hi
            a = int(p[C.W_SIBLING])
        assert last_high == C.KEY_POS_INF
        return stats
