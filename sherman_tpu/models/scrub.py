"""Online scrubber — periodic O(1)-step integrity pass + quarantine.

The reference's only structural check is an offline host walk
(``print_and_check_tree``); ours has the one-step device validator
(``models/validate.py``).  This module makes a SERVING-TIME tool of it:
a :class:`Scrubber` runs the validator's per-page local predicates
(``validate._scrub_kernel`` — the same code the full check uses) over
the live pool between engine steps, publishes ``scrub.*`` metrics, and
acts on what it finds:

- every violating page is **quarantined**: its global lock word is
  taken with the scrubber's own (live) lease, so no writer can touch
  the page — device inserts report the typed lock-timeout status, host
  writers hit the deadlock reporter — while reads keep flowing;
- a **structural** violation (torn page version pair, broken fence,
  unsorted internal page, broken B-link — ``validate.SCRUB_STRUCTURAL``)
  means the page cannot be trusted as a unit: the engine flips to
  read-only degraded mode (:meth:`BatchedEngine.enter_degraded`);
- a quarantine that cannot be taken (the lock is held by a live lease
  that never drains) is a containment failure: degrade as well.

Entry-level violations (a torn fver/rver slot, an out-of-fence slot)
stay contained: the page is fenced off from writers and counted, the
engine keeps serving.  The documented exit from degraded mode is
TARGETED REPAIR (``recovery.RecoveryPlane.targeted_repair``: restore
only the flagged pages from the checkpoint chain, scrub-recertify,
replay the op journal), with a full ``utils.checkpoint`` chain restore
as the fallback when repair cannot re-certify —
``tools/recovery_drill.py`` runs the repair sequence,
``tools/chaos_drill.py`` the full-restore one.

Metrics: ``scrub.passes``, ``scrub.pages_checked``,
``scrub.violations`` (counters), ``scrub.quarantined`` (gauge).
"""

from __future__ import annotations

from sherman_tpu import obs
from sherman_tpu.models.validate import (SCRUB_BITS, SCRUB_STRUCTURAL,
                                         scrub_pass)
from sherman_tpu.parallel import dsm as D

_OBS_PASSES = obs.counter("scrub.passes")
_OBS_CHECKED = obs.counter("scrub.pages_checked")
_OBS_VIOLATIONS = obs.counter("scrub.violations")
_OBS_QUARANTINED = obs.gauge("scrub.quarantined")

# CAS attempts to take a violating page's lock word before treating the
# quarantine as failed (a legitimately held lock drains within a step
# or two; a wedged-by-live-holder word never does)
_QUARANTINE_TRIES = 8


class Scrubber:
    """Periodic data-plane integrity scrubbing over a BatchedEngine.

    Drivers call :meth:`tick` between engine steps (every call is a
    counter bump; every ``interval``-th runs a pass) or :meth:`scrub`
    directly.  Registers its own client context so its quarantine
    leases are LIVE — lock-lease recovery will never revoke a
    quarantine.  Collective in multihost deployments (same contract as
    ``check_structure_device``: every process calls together).
    """

    def __init__(self, engine, interval: int = 64,
                 quarantine: bool = True):
        self.eng = engine
        self.tree = engine.tree
        self.interval = max(1, int(interval))
        self.quarantine = quarantine
        self.ctx = self.tree.cluster.register_client(replicated=True)
        self._ticks = 0
        # addr -> violation mask for every page ever flagged; lock words
        # this scrubber holds (quarantines) are tracked separately since
        # two pages can hash onto one word
        self.flagged: dict[int, int] = {}
        self._held_words: set[int] = set()

    # -- driving --------------------------------------------------------------

    def tick(self) -> dict | None:
        """Call between engine steps; runs a pass every ``interval``
        calls.  Returns the pass result when one ran."""
        self._ticks += 1
        if self._ticks % self.interval == 0:
            return self.scrub()
        return None

    def scrub(self) -> dict:
        """One pass: check, count, quarantine new violations, degrade
        on structural damage or containment failure."""
        with obs.span("scrub.pass"):
            res = scrub_pass(self.tree)
        _OBS_PASSES.inc()
        _OBS_CHECKED.inc(res["pages_checked"])
        # "new" = pages with violation BITS not seen before, so a page
        # first flagged entry-level (contained) still escalates when a
        # structural class appears on it later
        new = [(a, mk) for a, mk in res["bad"]
               if mk & ~self.flagged.get(a, 0)]
        _OBS_VIOLATIONS.inc(len(new))
        for addr, mask in new:
            self.flagged[addr] = self.flagged.get(addr, 0) | mask
            obs.record_event("scrub.violation", addr=hex(addr),
                             mask=self._mask_names(mask),
                             structural=bool(mask & SCRUB_STRUCTURAL))
            # hot-key tier: a flagged (about-to-be-quarantined) page's
            # keys must drop out of the leaf/value cache — the cache
            # must never vouch for content the scrubber just impeached
            # (structural damage additionally flushes wholesale via
            # enter_degraded below)
            if self.eng.leaf_cache is not None:
                self.eng.leaf_cache.invalidate_pages([addr])
            contained = self._quarantine_page(addr) if self.quarantine \
                else False
            if mask & SCRUB_STRUCTURAL:
                self.eng.enter_degraded(
                    f"scrub: structural violation on page {addr:#x} "
                    f"(mask {self._mask_names(mask)})")
            elif self.quarantine and not contained:
                self.eng.enter_degraded(
                    f"scrub: page {addr:#x} violated "
                    f"({self._mask_names(mask)}) and quarantine could "
                    "not take its lock")
        _OBS_QUARANTINED.set(len(self._held_words))
        res["new_violations"] = len(new)
        res["quarantined"] = len(self._held_words)
        res["degraded"] = self.eng.degraded
        return res

    # -- quarantine -----------------------------------------------------------

    def _quarantine_page(self, addr: int) -> bool:
        """Fence writers off a violating page by holding its global
        lock word under the scrubber's live lease.  True when the word
        is held (newly, or already ours via a hash-sharing page)."""
        la = self.tree._lock_word_addr(addr)
        if la in self._held_words:
            return True
        for _ in range(_QUARANTINE_TRIES):
            old, won = self.tree.dsm.cas(la, 0, 0, self.ctx.lease,
                                         space=D.SPACE_LOCK)
            if won or old == self.ctx.lease:
                self._held_words.add(la)
                obs.counter("scrub.pages_quarantined").inc()
                obs.record_event("scrub.quarantine", addr=hex(addr),
                                 lock_word=int(la))
                return True
            # a DEAD holder (e.g. the same fault storm that corrupted
            # the page wedged its lock) is revoked, then retaken
            self.tree._try_revoke_lease(la, old)
        return False

    def damaged_addrs(self) -> list[int]:
        """Every page address this scrubber has flagged (any violation
        class) — the targeted-repair input set."""
        return sorted(self.flagged)

    def release_quarantine(self) -> int:
        """Drop every quarantine lock (after repair + re-validation
        only — the drill's post-restore path).  Returns words freed."""
        n = 0
        for la in sorted(self._held_words):
            self.tree.dsm.write_word(la, 0, 0, space=D.SPACE_LOCK)
            n += 1
        self._held_words.clear()
        self.flagged.clear()
        _OBS_QUARANTINED.set(0)
        return n

    @staticmethod
    def _mask_names(mask: int) -> str:
        return "|".join(n for n, b in SCRUB_BITS.items() if mask & b) \
            or hex(mask)
