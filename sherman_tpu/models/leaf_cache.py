"""Hot-key tier: versioned compute-side leaf/value cache.

At YCSB skew (zipf theta 0.99) a tiny fraction of keys absorbs most
read traffic, yet the compute side caches only INTERNAL nodes (the
router / ``IndexCache.h`` mirror): every repeat read of a hot key still
pays a full descent plus a pool gather.  This module adds the missing
tier — a bounded, fixed-shape hot-set table mapping

    key -> (value, leaf addr, in-leaf slot, captured entry-version pair)

probed by ONE vectorized device lookup in front of the descent.  Hits
short-circuit the descent entirely; misses flow into the existing
fan-out as the residual (smaller) active set.

COHERENCE TOKEN — the entry-version halves the write path already
bumps (the ``CONFIG_ENABLE_CRC`` fver/rver pair, packed 16/16 in one
word, ``leaf_apply_spmd``) are exactly a cache-coherence token, so
staleness is validated for free: every probe MATCH is re-certified
against the live pool snapshot with a single page gather (the same
one-page cost as the router's seeded round-1 read, instead of
height-many descent gathers).  A hit requires ALL of:

- the cached address still holds a LEVEL-0 page with consistent
  front/rear page versions (splits and structural rewrites bump them);
- the cached slot is LIVE (fver == rver != 0 — a flipped/torn entry
  version, chaos's favorite fault, turns the hit into a miss, never a
  wrong answer) and holds the probed KEY (splits re-sort slots, deletes
  clear them — both turn into key mismatches);
- the slot's packed version word AND value words equal the captured
  ones (an in-place update bumps fver/rver; a split resets them — a
  version that "matches again" after a reset is accepted only if the
  value also matches, which is then bit-identical to what a descent
  returns, because a live key is unique across the tree).

Any probe match that fails validation is STALE: it is counted, the
slot is scatter-invalidated on device, and the key falls back into the
residual descent — so results are BIT-IDENTICAL to the uncached path
by construction (pinned in CI, the same contract as ``gather_impl``).

TABLE SHAPE — open addressing over ``slots`` (power of two) physical
slots with a bounded probe window of ``window`` consecutive slots
(the device probe is a fixed [B, window] gather — no data-dependent
shapes, so the probe lives inside the SEALED zero-retrace serving
loop).  Admitted-key capacity is ``slots // 2``: at load <= 0.5 with
hottest-first host-side placement the window almost never overflows
(overflowing keys simply stay uncached and are counted).

ADMISSION is frequency-based: :meth:`LeafCache.observe` feeds a
decayed top-K frequency sketch from the same key stream the zipf
sampler produces (``search``/``search_combined`` feed it their batch
histograms for free — the combine path already computes the unique
counts), and every ``admit_every`` observed batches the top
``capacity`` keys are re-resolved and the table rebuilt hottest-first.
Benchmark drivers that KNOW the hot set (the synthetic zipf keyspace:
rank r's key is ``mix64(r ^ salt)``) prefill it directly with
:meth:`fill` — the analytic zipf CDF then predicts the hit ratio
(:func:`sherman_tpu.workload.zipf.expected_hit_ratio`), published next
to the measured one in the bench receipt.

INVALIDATION SOURCES (all conservative — a spare invalidation is never
a missed one; validation stays the authoritative guard):

- the write path: engine ``insert``/``delete``/``mixed`` invalidate
  their batch's write keys (the same keys whose entry versions bump);
- the split/reclaim paths that rewrite leaves: reclaimed page
  addresses drop every entry that points at them
  (:meth:`invalidate_pages`); split-moved entries self-invalidate via
  the version/key checks;
- ``enter_degraded`` and scrub quarantine: a quarantined page's keys
  must drop out of the cache (:meth:`invalidate_pages` from the
  scrubber; degraded entry flushes wholesale);
- online migration (``sherman_tpu/migrate.py``): every migration
  batch scatter-invalidates its pages (:meth:`invalidate_pages`) when
  the batch's locks release — entries must not vouch for a page a
  migrator just held (pinned by the cached-read-during-migration
  bit-identity test in ``tests/test_migrate.py``);
- stale probe matches invalidate their own slot on device.

VOLATILITY CONTRACT — the cache is never checkpointed: recovery
(``RecoveryPlane.recover`` builds a fresh engine) and targeted repair
(explicit :meth:`flush`) always start cold; the journal replay path
re-warms nothing; the pool emitted by a live reshard restores into a
fresh engine (cold by construction), extending the contract to
migration cutover.  Metrics ride the ``cache.`` pull collector
(hits/misses/invalidations/evictions counters + hit-ratio gauge, the
``slo.``-collector shape).

The knob: ``config.leaf_cache_slots()`` / ``SHERMAN_LEAF_CACHE`` (off
is the shipped default until the chip receipts land — standing
guardrail: measurement-driven flips; the CPU receipts live in
BENCHMARKS.md "Round-10").
"""

from __future__ import annotations

import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ConfigError
from sherman_tpu.obs import device as DEV
from sherman_tpu.ops import bits, layout
from sherman_tpu.parallel import dsm as D
from sherman_tpu.parallel.mesh import AXIS

DEFAULT_WINDOW = 8  # open-addressing probe window (slots per key)


# ---------------------------------------------------------------------------
# Slot hash: device + bit-exact numpy twin (placement must agree with
# the probe, or every fill would miss).
# ---------------------------------------------------------------------------

def slot_hash_np(khi: np.ndarray, klo: np.ndarray) -> np.ndarray:
    """Host table hash of (hi, lo) int32 key pairs -> uint32 [B]
    (``bits.hash32_np`` is the vectorized murmur3 twin — one constant
    set shared with the device probe's :func:`slot_hash`)."""
    h = bits.hash32_np(np.asarray(klo).view(np.uint32))
    return bits.hash32_np(np.asarray(khi).view(np.uint32) ^ h)


def slot_hash(khi, klo):
    """Device twin of :func:`slot_hash_np` (int32 pairs -> uint32)."""
    h = bits.hash32(klo)
    return bits.hash32(
        jnp.bitwise_xor(jnp.asarray(khi, jnp.int32),
                        lax.bitcast_convert_type(h, jnp.int32)))


# ---------------------------------------------------------------------------
# The device probe core (shared by the engine probe program and the
# staged serving loop's cache_probe program).
# ---------------------------------------------------------------------------

def probe_rows(pool, tbl, khi, klo, active, *, cfg, axis_name: str = AXIS):
    """Vectorized probe + pool validation of one key batch.

    ``tbl``: dict of replicated [S] int32 arrays (khi, klo, vhi, vlo,
    ver, addr, slot); khi==klo==0 marks an empty slot (key 0 is below
    ``KEY_MIN``, never a user key).  Returns per-row

        (hit, vhi, vlo, stale, tidx)

    ``hit``: the cached value is certified current against THIS pool
    snapshot (serve it — bit-identical to a descent).  ``stale``: the
    table matched but validation failed (invalidate slot ``tidx`` and
    descend).  Cost: one [B, window] table gather + ONE page gather for
    the matching rows — the same single-page read a router-seeded
    round-1 descent pays, instead of height-many.
    """
    S = tbl["khi"].shape[0]
    W = min(DEFAULT_WINDOW, S)
    h = slot_hash(khi, klo)
    idx = lax.bitcast_convert_type(h & jnp.uint32(S - 1), jnp.int32)
    cand = (idx[:, None] + jnp.arange(W, dtype=jnp.int32)) \
        & jnp.int32(S - 1)                                   # [B, W]
    ck_hi, ck_lo = tbl["khi"][cand], tbl["klo"][cand]
    m = (active[:, None] & (ck_hi == khi[:, None])
         & (ck_lo == klo[:, None]) & ((ck_hi != 0) | (ck_lo != 0)))
    # one-hot first match (placement keeps keys unique, so at most one)
    first = m & (jnp.cumsum(m.astype(jnp.int32), axis=1) == 1)
    pmatch = jnp.any(m, axis=1)
    pick = lambda a: jnp.sum(jnp.where(first, a[cand], 0), axis=1)
    c_addr, c_slot, c_ver = pick(tbl["addr"]), pick(tbl["slot"]), \
        pick(tbl["ver"])
    c_vhi, c_vlo = pick(tbl["vhi"]), pick(tbl["vlo"])
    tidx = jnp.sum(jnp.where(first, cand, 0), axis=1)

    # authoritative re-certification on the current snapshot — the
    # entry-version coherence token plus the liveness/key/value checks
    # (see the module docstring's hit contract)
    if cfg.machine_nr == 1:
        # narrow validation: 8 WORD gathers (headers + the slot's 5
        # fields) instead of a 256-word page row per hit — on the CPU
        # mesh this is the difference between the probe paying ~a full
        # descent's bandwidth and paying ~3% of it (TPU gathers are
        # per-row latency-bound, so both forms cost alike there)
        P = pool.shape[0]
        row = bits.addr_page(c_addr)
        okr = pmatch & (row >= 0) & (row < P)
        r = jnp.clip(row, 0, P - 1)
        s = jnp.clip(c_slot, 0, C.LEAF_CAP - 1)
        pv = pool[r, C.L_VER_W + s]
        fv, rv = layout.ver_unpack(pv)
        hit = (okr
               & (pool[r, C.W_LEVEL] == 0)
               & (pool[r, C.W_FRONT_VER] == pool[r, C.W_REAR_VER])
               & (fv == rv) & (fv != 0)
               & (pool[r, C.L_KHI_W + s] == khi)
               & (pool[r, C.L_KLO_W + s] == klo)
               & (pv == c_ver)
               & (pool[r, C.L_VHI_W + s] == c_vhi)
               & (pool[r, C.L_VLO_W + s] == c_vlo))
    else:
        # multi-node: the cached leaf may live on a peer — ship the
        # page through the routed read exchange (requests are 1 word,
        # only replies carry pages; one exchange round, like a seeded
        # round-1 descent read)
        page, okr = D.read_pages_spmd(pool, c_addr, cfg=cfg,
                                      axis_name=axis_name, active=pmatch)
        so = (jnp.arange(C.LEAF_CAP, dtype=jnp.int32)[None, :]
              == jnp.clip(c_slot, 0, C.LEAF_CAP - 1)[:, None])
        blk = lambda st: jnp.sum(
            jnp.where(so, page[:, st:st + C.LEAF_CAP], 0), axis=-1)
        pv = blk(C.L_VER_W)
        fv, rv = layout.ver_unpack(pv)
        hit = (pmatch & okr
               & (layout.h_level(page) == 0)
               & layout.page_consistent(page)
               & (fv == rv) & (fv != 0)
               & (blk(C.L_KHI_W) == khi) & (blk(C.L_KLO_W) == klo)
               & (pv == c_ver)
               & (blk(C.L_VHI_W) == c_vhi) & (blk(C.L_VLO_W) == c_vlo))
    hit = hit & pmatch
    stale = pmatch & ~hit
    return (hit, jnp.where(hit, c_vhi, 0), jnp.where(hit, c_vlo, 0),
            stale, tidx)


def invalidation_mask(stale, tidx, n_slots: int, n_nodes: int,
                      axis_name: str = AXIS):
    """[S] int32 count of stale probe matches per table slot, psum'd
    across the mesh so every node derives the SAME invalidation (the
    table is replicated — a divergent update would desynchronize it)."""
    inval = jnp.zeros(n_slots, jnp.int32).at[
        jnp.where(stale, tidx, n_slots)].add(1, mode="drop")
    if n_nodes > 1:
        inval = lax.psum(inval, axis_name)
    return inval


class LeafCache:
    """Batched, versioned hot-key value cache over a
    :class:`~sherman_tpu.models.batched.BatchedEngine` (see the module
    docstring for the protocol).  Attach via
    ``engine.attach_leaf_cache()``; the engine's read entry points
    probe it automatically and its write entry points invalidate it.
    """

    def __init__(self, eng, slots: int | None = None,
                 window: int = DEFAULT_WINDOW, admit_every: int = 0):
        if slots is None:
            slots = C.leaf_cache_slots() or 65536
        if slots < 2 * window:
            slots = 2 * window
        S = 1 << (int(slots) - 1).bit_length()  # round up to pow2
        if window != DEFAULT_WINDOW:
            raise ConfigError(
                "leaf cache probe window is compiled into the probe "
                f"program as DEFAULT_WINDOW={DEFAULT_WINDOW}")
        self.eng = eng
        self.cfg = eng.cfg
        self.slots = S
        self.window = window
        #: admitted-key budget: load <= 0.5 keeps the bounded window
        #: near-lossless under hottest-first placement
        self.capacity = S // 2
        #: auto-admission cadence in observed batches (0 = manual fill)
        self.admit_every = int(admit_every)
        # host mirror of the device table (placement/invalidation
        # bookkeeping; the device copies are pushed lazily)
        self._khi = np.zeros(S, np.int32)
        self._klo = np.zeros(S, np.int32)
        self._vhi = np.zeros(S, np.int32)
        self._vlo = np.zeros(S, np.int32)
        self._ver = np.zeros(S, np.int32)
        self._addr = np.zeros(S, np.int32)
        self._slot = np.zeros(S, np.int32)
        self._keys = np.zeros(S, np.uint64)  # u64 view for isin lookups
        self._dev: tuple | None = None
        self._dirty = True
        self._lock = threading.RLock()
        self._probe_cache: dict = {}
        self._fill_cache: dict = {}
        # frequency sketch for auto-admission (decayed counts)
        self._freq: dict[int, float] = {}
        self._observed_batches = 0
        # cache.* pull collector (the slo.-collector shape): counters +
        # the hit-ratio gauge in every snapshot / scrape
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0
        self.fills = 0
        self.placement_failures = 0
        # payload sidecar (PR 16): {key: (heap handle, payload bytes)}
        # pinned by the serving read path after a heap gather.  A later
        # hit whose CACHED HANDLE still equals the tree's live value
        # (the handle IS the value for a heap-backed tree, and its
        # version field bumps on every rewrite) returns the pinned
        # bytes and skips the fused heap-resolve gather entirely; any
        # mismatch is stale — dropped and re-gathered, never served.
        # Bounded by the same admitted-key budget as the tables.
        self._sidecar: dict[int, tuple[int, bytes]] = {}
        self.sidecar_hits = 0
        self.sidecar_stale = 0
        self.sidecar_pins = 0
        ref = weakref.ref(self)

        def _collect():
            c = ref()
            return c.stats() if c is not None else {}

        obs.register_collector("cache", _collect)

    # -- metrics --------------------------------------------------------------

    def _note_probe(self, hits: int, misses: int, stale: int) -> None:
        """Hot-path accounting: plain integer adds only (the SL006
        no-allocation contract — this runs once per probed batch)."""
        self.hits += hits
        self.misses += misses
        self.invalidations += stale

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "invalidations": self.invalidations,
            "evictions": self.evictions,
            "fills": self.fills,
            "placement_failures": self.placement_failures,
            "sidecar_hits": self.sidecar_hits,
            "sidecar_stale": self.sidecar_stale,
            "sidecar_pins": self.sidecar_pins,
            "sidecar_keys": len(self._sidecar),
            "hit_ratio": (self.hits / total) if total else 0.0,
            "cached_keys": int((self._keys != 0).sum()),
            "slots": self.slots,
            "capacity": self.capacity,
        }

    # -- device table ---------------------------------------------------------

    def _table_host(self) -> tuple:
        return (self._khi, self._klo, self._vhi, self._vlo, self._ver,
                self._addr, self._slot)

    def device_tables(self) -> tuple:
        """The 7 replicated device arrays (khi, klo, vhi, vlo, ver,
        addr, slot), re-pushed from the host mirror when dirty.  The
        staged serving loop stages these ONCE before its sealed window
        (fixed [S] shapes — no data-dependent recompiles)."""
        from sherman_tpu.workload.device_prep import _rep_put
        with self._lock:
            if self._dirty or self._dev is None:
                self._dev = tuple(_rep_put(self.eng.dsm, a)
                                  for a in self._table_host())
                self._dirty = False
            return self._dev

    # -- probe ----------------------------------------------------------------

    def _get_probe(self, width: int):
        """The engine-path probe program: probe + validate + device-side
        stale-slot invalidation, one compiled shape per batch width."""
        fn = self._probe_cache.get(width)
        if fn is None:
            eng = self.eng
            cfg, S = self.cfg, self.slots
            N = cfg.machine_nr
            spec, rep = eng._spec, eng._rep

            def kernel(pool, tkhi, tklo, tvhi, tvlo, tver, taddr, tslot,
                       khi, klo, active):
                tbl = {"khi": tkhi, "klo": tklo, "vhi": tvhi,
                       "vlo": tvlo, "ver": tver, "addr": taddr,
                       "slot": tslot}
                hit, vhi, vlo, stale, tidx = probe_rows(
                    pool, tbl, khi, klo, active, cfg=cfg)
                inval = invalidation_mask(stale, tidx, S, N)
                keep = inval == 0
                nh = jnp.sum(hit.astype(jnp.int32))
                ns = jnp.sum(stale.astype(jnp.int32))
                if N > 1:
                    nh = lax.psum(nh, AXIS)
                    ns = lax.psum(ns, AXIS)
                return (hit, vhi, vlo, jnp.where(keep, tkhi, 0),
                        jnp.where(keep, tklo, 0), nh, ns)

            sm = jax.shard_map(
                kernel, mesh=eng.dsm.mesh,
                in_specs=(spec,) + (rep,) * 7 + (spec, spec, spec),
                out_specs=(spec, spec, spec, rep, rep, rep, rep),
                check_vma=False)
            fn = DEV.wrap_program("engine.cache_probe", jax.jit(sm))
            self._probe_cache[width] = fn
        return fn

    def probe(self, khi: np.ndarray, klo: np.ndarray, active: np.ndarray):
        """Probe one PADDED batch (host int32 pairs + active mask of the
        engine's ``machine_nr * B`` width) -> (hit, vhi, vlo) numpy
        arrays of the same width.  Stale matches are invalidated on
        device and counted; hits/misses land in the ``cache.``
        collector."""
        eng = self.eng
        dev = self.device_tables()
        fn = self._get_probe(khi.shape[0])
        with eng._step_mutex:  # launch-only, like every engine step
            out = fn(eng.dsm.pool, *dev, eng._shard(khi),
                     eng._shard(klo), eng._shard(active))
        hit, vhi, vlo, tkhi2, tklo2, nh, ns = out
        with self._lock:
            if not self._dirty:
                # adopt the device-side invalidations; a concurrent host
                # fill/invalidate marked dirty and supersedes them (the
                # stale entries re-miss and re-invalidate next probe)
                self._dev = (tkhi2, tklo2) + self._dev[2:]
        hit, vhi, vlo = eng._unshard(hit, vhi, vlo)
        nh_i = int(np.asarray(nh))
        ns_i = int(np.asarray(ns))
        self._note_probe(nh_i, int(active.sum()) - nh_i, ns_i)
        return np.array(hit), np.array(vhi), np.array(vlo)

    # -- fill (admission) -----------------------------------------------------

    def _get_fill(self, iters: int, with_start: bool):
        """Resolve program: descend candidate keys to their leaves and
        capture (addr, slot, packed version, value) — the table fill's
        one device pass (off the hot path)."""
        key = (iters, with_start)
        fn = self._fill_cache.get(key)
        if fn is None:
            from sherman_tpu.models.batched import _resolve_leaves
            eng = self.eng
            cfg = self.cfg
            spec, rep = eng._spec, eng._rep
            in_specs = [spec, spec, spec, spec, rep, spec]
            if with_start:
                in_specs.append(spec)

            def kernel(pool, counters, khi, klo, root, active, *rest):
                start = rest[0] if with_start else None
                counters, done, addr, found, _, _ = _resolve_leaves(
                    pool, counters, khi, klo, root, active, start,
                    cfg=cfg, iters=iters, axis_name=AXIS)
                page, okp = D.read_pages_spmd(pool, addr, cfg=cfg,
                                              active=done & found)
                f2, _, _, slot = layout.leaf_find_key(page, khi, klo)
                ok = done & found & okp & f2
                so = (jnp.arange(C.LEAF_CAP, dtype=jnp.int32)[None, :]
                      == jnp.clip(slot, 0, C.LEAF_CAP - 1)[:, None])
                blk = lambda s: jnp.sum(
                    jnp.where(so, page[:, s:s + C.LEAF_CAP], 0), axis=-1)
                z = lambda a: jnp.where(ok, a, 0)
                return (counters, ok, z(addr), z(slot),
                        z(blk(C.L_VER_W)), z(blk(C.L_VHI_W)),
                        z(blk(C.L_VLO_W)))

            sm = jax.shard_map(
                kernel, mesh=eng.dsm.mesh, in_specs=tuple(in_specs),
                out_specs=(spec,) * 7, check_vma=False)
            fn = DEV.wrap_program(
                "engine.cache_fill",
                jax.jit(sm, donate_argnums=C.donate_argnums(1)))
            self._fill_cache[key] = fn
        return fn

    def _resolve(self, keys: np.ndarray):
        """-> (ok, addr, slot, ver, vhi, vlo) host arrays [len(keys)]:
        each key's live leaf position + captured version/value, chunked
        through the engine's padded batch width."""
        eng = self.eng
        n = keys.shape[0]
        total = self.cfg.machine_nr * eng.B
        outs = [np.zeros(n, bool)] + [np.zeros(n, np.int32)
                                      for _ in range(5)]
        use_router = eng.router is not None
        fn = self._get_fill(eng._iters(), use_router)
        for i in range(0, n, total):
            chunk = keys[i:i + total]
            khi, klo = bits.keys_to_pairs(chunk)
            (khi, _), (klo, _) = eng._pad(khi), eng._pad(klo)
            active, _ = eng._pad(np.ones(chunk.shape[0], bool))
            args = [eng._shard(khi), eng._shard(klo),
                    np.int32(eng.tree._root_addr), eng._shard(active)]
            if use_router:
                args.append(eng._shard(eng.router.host_start(khi, klo)))
            with eng._step_mutex:
                eng.dsm.counters, *res = fn(eng.dsm.pool,
                                            eng.dsm.counters, *args)
            res = eng._unshard(*res)
            for o, r in zip(outs, res):
                o[i:i + total] = np.asarray(r)[:chunk.shape[0]]
        return tuple(outs)

    def fill(self, keys) -> dict:
        """Rebuild the table from ``keys`` (uint64, hottest FIRST — the
        admission ranking).  Each key is resolved to its live leaf
        position in one batched pass; placement is host-side open
        addressing, hottest first, within the bounded window — window
        overflow drops the key (counted, never silently resized).
        Returns {"placed", "failed", "resolved"}."""
        keys = np.asarray(keys, np.uint64)[:self.capacity]
        ok, addr, slot, ver, vhi, vlo = self._resolve(keys) \
            if keys.size else ((np.zeros(0, bool),) + (np.zeros(0, np.int32),) * 5)
        khi, klo = bits.keys_to_pairs(keys)
        h = slot_hash_np(khi, klo)
        S, W = self.slots, self.window
        nkhi = np.zeros(S, np.int32)
        nklo = np.zeros(S, np.int32)
        nvhi = np.zeros(S, np.int32)
        nvlo = np.zeros(S, np.int32)
        nver = np.zeros(S, np.int32)
        naddr = np.zeros(S, np.int32)
        nslot = np.zeros(S, np.int32)
        nkeys = np.zeros(S, np.uint64)
        placed = failed = 0
        base = (h & np.uint32(S - 1)).astype(np.int64)
        for i in np.nonzero(ok)[0].tolist():
            for o in range(W):
                j = int((base[i] + o) & (S - 1))
                if nkeys[j] == 0:
                    nkhi[j], nklo[j] = khi[i], klo[i]
                    nvhi[j], nvlo[j] = vhi[i], vlo[i]
                    nver[j], naddr[j], nslot[j] = ver[i], addr[i], slot[i]
                    nkeys[j] = keys[i]
                    placed += 1
                    break
            else:
                failed += 1  # window full of hotter keys: stay uncached
        with self._lock:
            evicted = int(np.setdiff1d(
                self._keys[self._keys != 0], nkeys,
                assume_unique=False).size)
            self.evictions += evicted
            (self._khi, self._klo, self._vhi, self._vlo, self._ver,
             self._addr, self._slot) = (nkhi, nklo, nvhi, nvlo, nver,
                                        naddr, nslot)
            self._keys = nkeys
            self._dirty = True
            self.fills += 1
            self.placement_failures += failed
        return {"placed": placed, "failed": failed,
                "resolved": int(ok.sum())}

    # -- admission (frequency sketch) ----------------------------------------

    def observe(self, keys) -> None:
        """Feed one read batch's key stream into the decayed frequency
        sketch; every ``admit_every`` batches rebuild the table from
        the top ``capacity`` keys.  No-op when ``admit_every == 0``
        (manual :meth:`fill` drivers — e.g. the staged bench loop,
        whose hot set is analytically known)."""
        if self.admit_every <= 0:
            return
        uk, cnt = np.unique(np.asarray(keys, np.uint64),
                            return_counts=True)
        with self._lock:
            f = self._freq
            for k, c in zip(uk.tolist(), cnt.tolist()):
                f[k] = f.get(k, 0.0) + c
            self._observed_batches += 1
            due = self._observed_batches % self.admit_every == 0
            if due:
                # decay + bound the sketch, then admit the top keys
                top = sorted(f.items(), key=lambda kv: -kv[1])
                self._freq = {k: v * 0.5
                              for k, v in top[:4 * self.capacity]}
                cand = np.array([k for k, _ in top[:self.capacity]],
                                np.uint64)
        if due and cand.size:
            self.fill(cand)

    def sketch_stats(self) -> dict:
        """Admission-sketch receipt for drivers (the serving front
        door's ``cache`` block): how many batches the decayed top-K
        sketch has observed, how many keys it currently tracks, and the
        auto-admission cadence.  Zero-observation stats mean the cache
        runs in manual-``fill`` mode."""
        with self._lock:
            return {
                "admit_every": self.admit_every,
                "observed_batches": self._observed_batches,
                "tracked_keys": len(self._freq),
            }

    # -- invalidation ---------------------------------------------------------

    def invalidate_keys(self, keys) -> int:
        """Drop every cached entry whose key is in ``keys`` (the write
        path's hook — these keys' entry versions bump this step)."""
        keys = np.asarray(keys, np.uint64)
        if keys.size == 0:
            return 0
        with self._lock:
            if self._sidecar:
                # a pin needs no table slot, so drop by key directly
                for k in keys:
                    self._sidecar.pop(int(k), None)
            m = (self._keys != 0) & np.isin(self._keys, keys)
            return self._clear(m)

    def invalidate_pages(self, addrs) -> int:
        """Drop every cached entry resident on the given packed page
        addresses (split/reclaim rewrites, scrub quarantine, migration
        batches)."""
        a = np.asarray(list(addrs), np.int64).astype(np.int32)
        if a.size == 0:
            return 0
        with self._lock:
            m = (self._keys != 0) & np.isin(self._addr, a)
            return self._clear(m)

    def flush(self) -> int:
        """Drop everything — the degraded-entry / recovery / targeted-
        repair contract (the cache is volatile by design)."""
        with self._lock:
            self._sidecar.clear()  # pins are volatile with the rest
            return self._clear(self._keys != 0)

    def _clear(self, m: np.ndarray) -> int:
        n = int(m.sum())
        if n:
            if self._sidecar:
                # pinned payloads ride the same invalidation: a write
                # to the key bumps its handle, so the pin is dead
                for k in self._keys[m]:
                    self._sidecar.pop(int(k), None)
            for a in self._table_host():
                a[m] = 0
            self._keys[m] = 0
            self._dirty = True
            self.invalidations += n
        return n

    # -- payload sidecar (PR 16) ----------------------------------------------

    def pin_payloads(self, keys, handles, blobs) -> int:
        """Pin gathered payload bytes keyed by (key, heap handle) so
        the NEXT read of the key skips the heap-resolve gather.  The
        handle is the staleness token: serving checks it against the
        tree's live value (which a rewrite always changes — new row,
        or same row under a bumped version nibble).  Returns pins
        stored; over-budget pins evict oldest-pinned first."""
        n = 0
        with self._lock:
            for k, h, b in zip(keys, handles, blobs):
                if b is None:
                    continue
                while len(self._sidecar) >= self.capacity:
                    self._sidecar.pop(next(iter(self._sidecar)))
                    self.evictions += 1
                self._sidecar[int(k)] = (int(h), bytes(b))
                n += 1
            self.sidecar_pins += n
        return n

    def payload_hits(self, keys, handles) -> list:
        """Per position: the pinned bytes when the sidecar holds the
        key under EXACTLY the given live handle, else ``None``.  A
        key pinned under a different handle is stale — dropped and
        counted, and the caller re-gathers (a stale pin can delay a
        gather, never falsify one)."""
        out = []
        hits = stale = 0
        with self._lock:
            for k, h in zip(keys, handles):
                ent = self._sidecar.get(int(k))
                if ent is None:
                    out.append(None)
                elif ent[0] == int(h):
                    hits += 1
                    out.append(ent[1])
                else:
                    stale += 1
                    del self._sidecar[int(k)]
                    out.append(None)
            self.sidecar_hits += hits
            self.sidecar_stale += stale
        return out

    def cached_keys(self) -> np.ndarray:
        """The currently admitted key set (uint64, unordered)."""
        with self._lock:
            return self._keys[self._keys != 0].copy()
