"""B+Tree page layout: vectorized pack/unpack over [..., 256]-word pages.

Mirrors the reference page structures (``Tree.h:130-210``):
``Header{leftmost_ptr, sibling_ptr, level, last_index, lowest, highest}``,
sorted ``InternalEntry{key, ptr}`` arrays, and unsorted ``LeafEntry`` slots
with the two-level (per-entry f/r) versions that enable single-entry
write-back (``Tree.cpp:914-921``) — but expressed as word offsets into a
256-word int32 page so that whole batches of pages can be searched with
vectorized compares on the VPU instead of per-entry scalar loops.

All functions accept pages of shape [..., PAGE_WORDS] and broadcast.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sherman_tpu import config as C
from sherman_tpu.ops import bits


# -- header accessors ---------------------------------------------------------

def h_front_ver(page):
    return page[..., C.W_FRONT_VER]


def h_rear_ver(page):
    return page[..., C.W_REAR_VER]


def h_leftmost(page):
    return page[..., C.W_LEFTMOST]


def h_sibling(page):
    return page[..., C.W_SIBLING]


def h_level(page):
    return page[..., C.W_LEVEL]


def h_nkeys(page):
    return page[..., C.W_NKEYS]


def h_lowest(page):
    return page[..., C.W_LOW_HI], page[..., C.W_LOW_LO]


def h_highest(page):
    return page[..., C.W_HIGH_HI], page[..., C.W_HIGH_LO]


def page_consistent(page):
    """Front/rear version match (torn-page check, ``Tree.cpp:600-618``)."""
    return h_front_ver(page) == h_rear_ver(page)


# -- internal pages -----------------------------------------------------------

def internal_entry_words(slot):
    """Word offset of internal entry `slot` (static int or array)."""
    return C.W_ENTRIES + slot * C.INTERNAL_ENTRY_WORDS


_I_SLOTS = np.arange(C.INTERNAL_CAP)
_I_KHI = C.W_ENTRIES + _I_SLOTS * C.INTERNAL_ENTRY_WORDS
_I_KLO = _I_KHI + 1
_I_PTR = _I_KHI + 2


def internal_keys(page):
    """-> (khi, klo) arrays of shape [..., INTERNAL_CAP]."""
    return page[..., _I_KHI], page[..., _I_KLO]


def internal_ptrs(page):
    return page[..., _I_PTR]


def internal_pick_child(page, khi, klo):
    """Vectorized child pick (``internal_page_search``, Tree.cpp:665-685).

    Sorted entries e_0..e_{n-1}; keys < e_0.key go to leftmost_ptr; else the
    child of the last entry with entry.key <= k.  Returns packed child addr.
    ``khi/klo`` broadcast against page batch dims.
    """
    ekhi, eklo = internal_keys(page)
    n = h_nkeys(page)[..., None]
    valid = _I_SLOTS < n
    le = bits.key_le(ekhi, eklo, khi[..., None], klo[..., None]) & valid
    # index of last entry with key <= k; -1 -> leftmost
    idx = jnp.sum(le.astype(jnp.int32), axis=-1) - 1
    ptrs = internal_ptrs(page)
    child = jnp.take_along_axis(ptrs, jnp.maximum(idx, 0)[..., None], axis=-1)[..., 0]
    return jnp.where(idx < 0, h_leftmost(page), child)


# -- leaf pages ---------------------------------------------------------------

_L_SLOTS = np.arange(C.LEAF_CAP)
_L_BASE = C.W_ENTRIES + _L_SLOTS * C.LEAF_ENTRY_WORDS
_L_FVER = _L_BASE + C.LE_FVER
_L_KHI = _L_BASE + C.LE_KEY_HI
_L_KLO = _L_BASE + C.LE_KEY_LO
_L_VHI = _L_BASE + C.LE_VAL_HI
_L_VLO = _L_BASE + C.LE_VAL_LO
_L_RVER = _L_BASE + C.LE_RVER


def leaf_entry_base(slot):
    return C.W_ENTRIES + slot * C.LEAF_ENTRY_WORDS


def leaf_slots_view(page):
    """-> dict of [..., LEAF_CAP] arrays: fver, khi, klo, vhi, vlo, rver."""
    return {
        "fver": page[..., _L_FVER],
        "khi": page[..., _L_KHI],
        "klo": page[..., _L_KLO],
        "vhi": page[..., _L_VHI],
        "vlo": page[..., _L_VLO],
        "rver": page[..., _L_RVER],
    }


def leaf_slot_used(page):
    """A slot is live iff fver == rver != 0 (two-level version rule)."""
    fv, rv = page[..., _L_FVER], page[..., _L_RVER]
    return (fv == rv) & (fv != 0)


def leaf_find_key(page, khi, klo):
    """Vectorized ``leaf_page_search`` (Tree.cpp:687-697): scan all slots.

    Returns (found, vhi, vlo, slot).  slot = -1 when absent.
    """
    used = leaf_slot_used(page)
    ekhi, eklo = page[..., _L_KHI], page[..., _L_KLO]
    hit = used & bits.key_eq(ekhi, eklo, khi[..., None], klo[..., None])
    slot = jnp.argmax(hit, axis=-1)
    found = jnp.any(hit, axis=-1)
    take = lambda a: jnp.take_along_axis(a, slot[..., None], axis=-1)[..., 0]
    vhi = jnp.where(found, take(page[..., _L_VHI]), 0)
    vlo = jnp.where(found, take(page[..., _L_VLO]), 0)
    return found, vhi, vlo, jnp.where(found, slot, -1)


def leaf_find_free_slot(page):
    """First free slot index, or -1 if the leaf is full."""
    free = ~leaf_slot_used(page)
    slot = jnp.argmax(free, axis=-1)
    any_free = jnp.any(free, axis=-1)
    return jnp.where(any_free, slot, -1)


def in_fence(page, khi, klo):
    """lowest <= k < highest (fence check, ``Tree.cpp:859-872``)."""
    lhi, llo = h_lowest(page)
    hhi, hlo = h_highest(page)
    return bits.key_le(lhi, llo, khi, klo) & bits.key_lt(khi, klo, hhi, hlo)


def needs_sibling_chase(page, khi, klo):
    """k >= highest -> follow B-link sibling (``Tree.cpp:626-629``)."""
    hhi, hlo = h_highest(page)
    return ~bits.key_lt(khi, klo, hhi, hlo)


# -- host-side page construction (numpy) -------------------------------------

def np_empty_page(level: int, lowest: int, highest: int,
                  sibling: int = 0, leftmost: int = 0,
                  version: int = 1) -> np.ndarray:
    """Build a fresh page as a host numpy word array."""
    pg = np.zeros(C.PAGE_WORDS, dtype=np.int32)
    pg[C.W_FRONT_VER] = version
    pg[C.W_REAR_VER] = version
    pg[C.W_LEFTMOST] = leftmost
    pg[C.W_SIBLING] = sibling
    pg[C.W_LEVEL] = level
    pg[C.W_NKEYS] = 0
    pg[C.W_LOW_HI], pg[C.W_LOW_LO] = bits.key_to_pair(lowest)
    pg[C.W_HIGH_HI], pg[C.W_HIGH_LO] = bits.key_to_pair(highest)
    return pg


def np_leaf_set_entry(pg: np.ndarray, slot: int, key: int, value: int,
                      ver: int = 1) -> None:
    base = leaf_entry_base(slot)
    pg[base + C.LE_FVER] = ver
    pg[base + C.LE_KEY_HI], pg[base + C.LE_KEY_LO] = bits.key_to_pair(key)
    pg[base + C.LE_VAL_HI], pg[base + C.LE_VAL_LO] = bits.key_to_pair(value)
    pg[base + C.LE_RVER] = ver


def np_leaf_clear_entry(pg: np.ndarray, slot: int) -> None:
    base = leaf_entry_base(slot)
    pg[base:base + C.LEAF_ENTRY_WORDS] = 0


def np_internal_set_entry(pg: np.ndarray, slot: int, key: int, child: int) -> None:
    base = internal_entry_words(slot)
    pg[base], pg[base + 1] = bits.key_to_pair(key)
    pg[base + 2] = child


def np_slot_live(pg: np.ndarray, slot: int) -> bool:
    """Host-side two-level version liveness rule: fver == rver != 0.
    (Single source of truth for host code; `leaf_slot_used` is the
    vectorized device twin.)"""
    base = leaf_entry_base(slot)
    fv, rv = pg[base + C.LE_FVER], pg[base + C.LE_RVER]
    return bool(fv == rv and fv != 0)


def np_leaf_entries(pg: np.ndarray) -> list[tuple[int, int, int]]:
    """-> list of (key, value, slot) of live entries (host debugging/tests)."""
    out = []
    for s in range(C.LEAF_CAP):
        if np_slot_live(pg, s):
            base = leaf_entry_base(s)
            k = bits.pair_to_key(pg[base + C.LE_KEY_HI], pg[base + C.LE_KEY_LO])
            v = bits.pair_to_key(pg[base + C.LE_VAL_HI], pg[base + C.LE_VAL_LO])
            out.append((k, v, s))
    return out


def np_internal_entries(pg: np.ndarray) -> list[tuple[int, int]]:
    out = []
    for s in range(int(pg[C.W_NKEYS])):
        base = internal_entry_words(s)
        k = bits.pair_to_key(pg[base], pg[base + 1])
        out.append((k, int(pg[base + 2])))
    return out


# -- host-side page queries (used by the slow/control paths) ------------------

def np_lowest(pg: np.ndarray) -> int:
    return bits.pair_to_key(pg[C.W_LOW_HI], pg[C.W_LOW_LO])


def np_highest(pg: np.ndarray) -> int:
    return bits.pair_to_key(pg[C.W_HIGH_HI], pg[C.W_HIGH_LO])


def np_pick_child(pg: np.ndarray, key: int) -> int:
    """Host ``internal_page_search`` (Tree.cpp:665-685)."""
    child = int(pg[C.W_LEFTMOST])
    for k, ptr in np_internal_entries(pg):
        if k <= key:
            child = ptr
        else:
            break
    return child


def np_leaf_find(pg: np.ndarray, key: int) -> tuple[int, int | None]:
    """Host leaf scan: -> (slot, value) or (-1, None)."""
    for k, v, s in np_leaf_entries(pg):
        if k == key:
            return s, v
    return -1, None


def np_leaf_free_slot(pg: np.ndarray) -> int:
    for s in range(C.LEAF_CAP):
        if not np_slot_live(pg, s):
            return s
    return -1
