"""B+Tree page layout: vectorized pack/unpack over [..., 256]-word pages.

Mirrors the reference page structures (``Tree.h:130-210``):
``Header{leftmost_ptr, sibling_ptr, level, last_index, lowest, highest}``,
sorted ``InternalEntry{key, ptr}`` arrays, and unsorted ``LeafEntry`` slots
with the two-level (per-entry f/r) versions that enable single-entry
write-back (``Tree.cpp:914-921``) — but expressed as word offsets into a
256-word int32 page so that whole batches of pages can be searched with
vectorized compares on the VPU instead of per-entry scalar loops.

All functions accept pages of shape [..., PAGE_WORDS] and broadcast.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sherman_tpu import config as C
from sherman_tpu.ops import bits


# -- header accessors ---------------------------------------------------------

def h_front_ver(page):
    return page[..., C.W_FRONT_VER]


def h_rear_ver(page):
    return page[..., C.W_REAR_VER]


def h_leftmost(page):
    return page[..., C.W_LEFTMOST]


def h_sibling(page):
    return page[..., C.W_SIBLING]


def h_level(page):
    return page[..., C.W_LEVEL]


def h_nkeys(page):
    return page[..., C.W_NKEYS]


def h_lowest(page):
    return page[..., C.W_LOW_HI], page[..., C.W_LOW_LO]


def h_highest(page):
    return page[..., C.W_HIGH_HI], page[..., C.W_HIGH_LO]


def page_consistent(page):
    """Front/rear version match (torn-page check, ``Tree.cpp:600-618``)."""
    return h_front_ver(page) == h_rear_ver(page)


# -- internal pages -----------------------------------------------------------
# SoA field blocks: every accessor is a static contiguous slice (fast on
# the VPU); see the layout rationale in config.py.

_I_SLOTS = np.arange(C.INTERNAL_CAP)


def internal_keys(page):
    """-> (khi, klo) arrays of shape [..., INTERNAL_CAP]."""
    return (page[..., C.I_KHI_W:C.I_KHI_W + C.INTERNAL_CAP],
            page[..., C.I_KLO_W:C.I_KLO_W + C.INTERNAL_CAP])


def internal_ptrs(page):
    return page[..., C.I_PTR_W:C.I_PTR_W + C.INTERNAL_CAP]


def internal_pick_child(page, khi, klo):
    """Vectorized child pick (``internal_page_search``, Tree.cpp:665-685).

    Sorted entries e_0..e_{n-1}; keys < e_0.key go to leftmost_ptr; else the
    child of the last entry with entry.key <= k.  Returns packed child addr.
    ``khi/klo`` broadcast against page batch dims.

    Implementation note: no ``take_along_axis`` — per-row dynamic indexing
    lowers terribly on the TPU VPU (no per-lane gather).  The last
    entry.key <= k slot is a prefix-mask boundary, so a one-hot masked sum
    extracts the child pointer in pure elementwise + reduce ops.
    """
    ekhi, eklo = internal_keys(page)
    n = h_nkeys(page)[..., None]
    valid = _I_SLOTS < n
    le = bits.key_le(ekhi, eklo, khi[..., None], klo[..., None]) & valid
    # boundary one-hot: the last slot with key <= k (le is a prefix mask
    # over the sorted valid entries)
    le_next = jnp.concatenate(
        [le[..., 1:], jnp.zeros_like(le[..., :1])], axis=-1)
    edge = le & ~le_next
    ptrs = internal_ptrs(page)
    child = jnp.sum(jnp.where(edge, ptrs, 0), axis=-1)
    any_le = jnp.any(le, axis=-1)
    return jnp.where(any_le, child, h_leftmost(page))


# -- leaf pages ---------------------------------------------------------------

_L_SLOTS = np.arange(C.LEAF_CAP)


def _lf(page, start):
    return page[..., start:start + C.LEAF_CAP]


def ver_unpack(v):
    """Packed entry version word -> (fver, rver); works for jnp and np."""
    return (v >> 16) & C.ENTRY_VER_MASK, v & C.ENTRY_VER_MASK


def ver_pack(x):
    """Consistent entry version pair from one 16-bit value.  (jnp int32
    shifts wrap two's-complement, so device use is bit-exact; host code
    building np.int32 words must go through :func:`ver_pack_np`.)"""
    return (x << 16) | x


def ver_pack_np(x) -> np.int32:
    """Host scalar packer: the int32 BIT PATTERN of (x << 16) | x."""
    p = ver_pack(int(x) & C.ENTRY_VER_MASK) & 0xFFFFFFFF
    return np.int32(p - (1 << 32) if p >= (1 << 31) else p)


def leaf_slots_view(page):
    """-> dict of [..., LEAF_CAP] arrays: ver (packed pair), khi, klo,
    vhi, vlo, plus derived fver/rver halves."""
    ver = _lf(page, C.L_VER_W)
    fv, rv = ver_unpack(ver)
    return {
        "ver": ver,
        "fver": fv,
        "rver": rv,
        "khi": _lf(page, C.L_KHI_W),
        "klo": _lf(page, C.L_KLO_W),
        "vhi": _lf(page, C.L_VHI_W),
        "vlo": _lf(page, C.L_VLO_W),
    }


def leaf_slot_used(page):
    """A slot is live iff fver == rver != 0 (two-level version rule,
    on the packed pair)."""
    fv, rv = ver_unpack(_lf(page, C.L_VER_W))
    return (fv == rv) & (fv != 0)


def leaf_find_key(page, khi, klo):
    """Vectorized ``leaf_page_search`` (Tree.cpp:687-697): scan all slots.

    Returns (found, vhi, vlo, slot).  slot = -1 when absent.  Live keys are
    unique per leaf, so ``hit`` is one-hot and masked sums extract the value
    without per-row dynamic indexing (slow on TPU).
    """
    used = leaf_slot_used(page)
    ekhi, eklo = _lf(page, C.L_KHI_W), _lf(page, C.L_KLO_W)
    hit = used & bits.key_eq(ekhi, eklo, khi[..., None], klo[..., None])
    found = jnp.any(hit, axis=-1)
    vhi = jnp.sum(jnp.where(hit, _lf(page, C.L_VHI_W), 0), axis=-1)
    vlo = jnp.sum(jnp.where(hit, _lf(page, C.L_VLO_W), 0), axis=-1)
    slot = jnp.sum(jnp.where(hit, _L_SLOTS, 0), axis=-1)
    return found, vhi, vlo, jnp.where(found, slot, -1)


def leaf_find_free_slot(page):
    """First free slot index, or -1 if the leaf is full."""
    free = ~leaf_slot_used(page)
    slot = jnp.argmax(free, axis=-1)
    any_free = jnp.any(free, axis=-1)
    return jnp.where(any_free, slot, -1)


def in_fence(page, khi, klo):
    """lowest <= k < highest (fence check, ``Tree.cpp:859-872``)."""
    lhi, llo = h_lowest(page)
    hhi, hlo = h_highest(page)
    return bits.key_le(lhi, llo, khi, klo) & bits.key_lt(khi, klo, hhi, hlo)


def needs_sibling_chase(page, khi, klo):
    """k >= highest -> follow B-link sibling (``Tree.cpp:626-629``)."""
    hhi, hlo = h_highest(page)
    return ~bits.key_lt(khi, klo, hhi, hlo)


# -- host-side page construction (numpy) -------------------------------------

def np_empty_page(level: int, lowest: int, highest: int,
                  sibling: int = 0, leftmost: int = 0,
                  version: int = 1) -> np.ndarray:
    """Build a fresh page as a host numpy word array."""
    pg = np.zeros(C.PAGE_WORDS, dtype=np.int32)
    pg[C.W_FRONT_VER] = version
    pg[C.W_REAR_VER] = version
    pg[C.W_LEFTMOST] = leftmost
    pg[C.W_SIBLING] = sibling
    pg[C.W_LEVEL] = level
    pg[C.W_NKEYS] = 0
    pg[C.W_LOW_HI], pg[C.W_LOW_LO] = bits.key_to_pair(lowest)
    pg[C.W_HIGH_HI], pg[C.W_HIGH_LO] = bits.key_to_pair(highest)
    return pg


def leaf_slot_words(slot):
    """Word offsets of one leaf slot's five fields (SoA blocks):
    (ver, khi, klo, vhi, vlo) — ver holds the packed fver/rver pair."""
    return (C.L_VER_W + slot, C.L_KHI_W + slot, C.L_KLO_W + slot,
            C.L_VHI_W + slot, C.L_VLO_W + slot)


def np_leaf_set_entry(pg: np.ndarray, slot: int, key: int, value: int,
                      ver: int = 1) -> None:
    wv, wkh, wkl, wvh, wvl = leaf_slot_words(slot)
    pg[wv] = ver_pack_np(ver)
    pg[wkh], pg[wkl] = bits.key_to_pair(key)
    pg[wvh], pg[wvl] = bits.key_to_pair(value)


def np_leaf_clear_entry(pg: np.ndarray, slot: int) -> None:
    for w in leaf_slot_words(slot):
        pg[w] = 0


def np_internal_rebuild(pg: np.ndarray, ents: list, level: int) -> np.ndarray:
    """Rebuild an internal page around sorted ``ents`` [(key, child)],
    preserving fences/sibling/leftmost and bumping the version — the
    shared merge protocol of internal_page_store's no-split branch
    (host _insert_parent and the engine's batched parent flush)."""
    ver = ((int(pg[C.W_FRONT_VER]) + 1) & 0x7FFFFFFF) or 1
    newpg = np_empty_page(
        level, np_lowest(pg), np_highest(pg), sibling=int(pg[C.W_SIBLING]),
        leftmost=int(pg[C.W_LEFTMOST]), version=ver)
    for i, (k, c) in enumerate(ents):
        np_internal_set_entry(newpg, i, k, c)
    newpg[C.W_NKEYS] = len(ents)
    return newpg


def np_internal_set_entry(pg: np.ndarray, slot: int, key: int, child: int) -> None:
    pg[C.I_KHI_W + slot], pg[C.I_KLO_W + slot] = bits.key_to_pair(key)
    pg[C.I_PTR_W + slot] = child


def np_slot_live(pg: np.ndarray, slot: int) -> bool:
    """Host-side two-level version liveness rule: fver == rver != 0 on
    the packed pair.  (Single source of truth for host code;
    `leaf_slot_used` is the vectorized device twin.)"""
    fv, rv = ver_unpack(int(pg[C.L_VER_W + slot]) & 0xFFFFFFFF)
    return bool(fv == rv and fv != 0)


def np_leaf_entries(pg: np.ndarray) -> list[tuple[int, int, int]]:
    """-> list of (key, value, slot) of live entries (host debugging/tests)."""
    out = []
    for s in range(C.LEAF_CAP):
        if np_slot_live(pg, s):
            k = bits.pair_to_key(pg[C.L_KHI_W + s], pg[C.L_KLO_W + s])
            v = bits.pair_to_key(pg[C.L_VHI_W + s], pg[C.L_VLO_W + s])
            out.append((k, v, s))
    return out


def np_leaf_entries_batch(pages: np.ndarray):
    """Vectorized live-entry extraction from [W, PAGE_WORDS] leaf pages
    (host twin of `leaf_slot_used`/`leaf_find_key` for whole-page scans).

    Returns (keys u64 [W, CAP], vals u64 [W, CAP], live bool [W, CAP]).
    """
    fv, rv = ver_unpack(
        pages[:, C.L_VER_W:C.L_VER_W + C.LEAF_CAP].view(np.uint32))
    live = (fv == rv) & (fv != 0)
    keys = bits.pairs_to_keys(pages[:, C.L_KHI_W:C.L_KHI_W + C.LEAF_CAP],
                              pages[:, C.L_KLO_W:C.L_KLO_W + C.LEAF_CAP])
    vals = bits.pairs_to_keys(pages[:, C.L_VHI_W:C.L_VHI_W + C.LEAF_CAP],
                              pages[:, C.L_VLO_W:C.L_VLO_W + C.LEAF_CAP])
    return keys, vals, live


def np_internal_entries(pg: np.ndarray) -> list[tuple[int, int]]:
    out = []
    for s in range(int(pg[C.W_NKEYS])):
        k = bits.pair_to_key(pg[C.I_KHI_W + s], pg[C.I_KLO_W + s])
        out.append((k, int(pg[C.I_PTR_W + s])))
    return out


# -- host-side page queries (used by the slow/control paths) ------------------

def np_lowest(pg: np.ndarray) -> int:
    return bits.pair_to_key(pg[C.W_LOW_HI], pg[C.W_LOW_LO])


def np_highest(pg: np.ndarray) -> int:
    return bits.pair_to_key(pg[C.W_HIGH_HI], pg[C.W_HIGH_LO])


def np_pick_child(pg: np.ndarray, key: int) -> int:
    """Host ``internal_page_search`` (Tree.cpp:665-685)."""
    child = int(pg[C.W_LEFTMOST])
    for k, ptr in np_internal_entries(pg):
        if k <= key:
            child = ptr
        else:
            break
    return child


def np_leaf_find(pg: np.ndarray, key: int) -> tuple[int, int | None]:
    """Host leaf scan: -> (slot, value) or (-1, None)."""
    for k, v, s in np_leaf_entries(pg):
        if k == key:
            return s, v
    return -1, None


def np_leaf_free_slot(pg: np.ndarray) -> int:
    for s in range(C.LEAF_CAP):
        if not np_slot_live(pg, s):
            return s
    return -1
