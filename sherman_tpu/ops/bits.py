"""Bit-level primitives: 64-bit keys as int32 pairs, packed global addresses,
unsigned comparisons, and the lock-index hash.

TPUs have no native 64-bit integer lanes, so all 64-bit quantities (keys,
values — reference ``Key``/``Value`` uint64) travel as (hi, lo) pairs of
int32 words holding the uint32 bit patterns.  Comparisons flip the sign bit
to reuse signed int32 compares as unsigned ones.

Global addresses are packed int32 {node:8, page:24} — the TPU analogue of the
reference's 64-bit ``GlobalAddress`` {nodeID:16, offset:48}
(``GlobalAddress.h:10-16``); word-granular sub-addressing uses a separate
word-offset field instead of byte offsets.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sherman_tpu.config import ADDR_PAGE_BITS, ADDR_PAGE_MASK

_SIGN = np.int32(np.uint32(0x80000000).view(np.int32))
_U32_MASK = (1 << 32) - 1


# -- host-side scalar helpers -------------------------------------------------

def key_to_pair(k: int) -> tuple[int, int]:
    """Split a Python uint64 key into (hi, lo) int32 bit patterns."""
    k = int(k) & ((1 << 64) - 1)
    hi = np.uint32(k >> 32).view(np.int32).item()
    lo = np.uint32(k & _U32_MASK).view(np.int32).item()
    return hi, lo


def pair_to_key(hi, lo) -> int:
    """Rebuild the Python uint64 key from (hi, lo) int32 bit patterns."""
    hi_u = int(np.int64(int(hi)) & _U32_MASK)
    lo_u = int(np.int64(int(lo)) & _U32_MASK)
    return (hi_u << 32) | lo_u


def keys_to_pairs(ks) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized host conversion: uint64 array -> (hi, lo) int32 arrays."""
    ks = np.asarray(ks, dtype=np.uint64)
    hi = (ks >> np.uint64(32)).astype(np.uint32).view(np.int32)
    lo = (ks & np.uint64(_U32_MASK)).astype(np.uint32).view(np.int32)
    return hi, lo


def pairs_to_keys(hi, lo) -> np.ndarray:
    hi = np.asarray(hi).view(np.uint32).astype(np.uint64)
    lo = np.asarray(lo).view(np.uint32).astype(np.uint64)
    return (hi << np.uint64(32)) | lo


# -- device-side (jnp) unsigned compare on (hi, lo) pairs ---------------------

def _ux(x):
    return jnp.bitwise_xor(x, _SIGN)


def u32_lt(a, b):
    return _ux(a) < _ux(b)


def u32_le(a, b):
    return _ux(a) <= _ux(b)


def key_lt(ahi, alo, bhi, blo):
    """(ahi,alo) < (bhi,blo) as uint64."""
    return u32_lt(ahi, bhi) | ((ahi == bhi) & u32_lt(alo, blo))


def key_le(ahi, alo, bhi, blo):
    return u32_lt(ahi, bhi) | ((ahi == bhi) & u32_le(alo, blo))


def key_eq(ahi, alo, bhi, blo):
    return (ahi == bhi) & (alo == blo)


# -- packed global page addresses --------------------------------------------

def make_addr(node, page):
    """Pack (node, page) into an int32 address; works for ints and arrays."""
    if isinstance(node, (int, np.integer)) and isinstance(page, (int, np.integer)):
        v = (int(node) << ADDR_PAGE_BITS) | (int(page) & ADDR_PAGE_MASK)
        return np.uint32(v).view(np.int32).item()
    return jnp.bitwise_or(
        jnp.left_shift(jnp.asarray(node, jnp.int32), ADDR_PAGE_BITS),
        jnp.bitwise_and(jnp.asarray(page, jnp.int32), ADDR_PAGE_MASK),
    )


def addr_node(addr):
    if isinstance(addr, (int, np.integer)):
        return (int(np.int64(int(addr)) & _U32_MASK)) >> ADDR_PAGE_BITS
    a = jnp.asarray(addr, jnp.int32).astype(jnp.uint32)
    return jnp.right_shift(a, ADDR_PAGE_BITS).astype(jnp.int32)


def addr_page(addr):
    if isinstance(addr, (int, np.integer)):
        return int(addr) & ADDR_PAGE_MASK
    return jnp.bitwise_and(jnp.asarray(addr, jnp.int32), ADDR_PAGE_MASK)


NULL_ADDR = 0


def addr_is_null(addr):
    if isinstance(addr, (int, np.integer)):
        return int(addr) == 0
    return addr == 0


# -- lock leases --------------------------------------------------------------
# A held global lock word encodes WHO holds it and under which lease
# epoch: {epoch:15, owner:16} (bit 31 stays clear so the int32 word is
# non-negative and mask arithmetic never sees the sign bit).  0 = free.
# The owner field is the client tag (client_id + 1, nonzero); the epoch
# is the owner's lease generation in the cluster's epoch table
# (``Cluster.lease_is_live``).  A holder whose (owner, epoch) no longer
# matches the table is DEAD — its lock is revocable by masked CAS on
# exactly these fields (the FUSEE-style lock-lease recovery shape).
# Step atomicity makes revocation sound: a dead client's protected
# write either landed as one step or not at all, so freeing its lock
# can never expose a torn page.

LEASE_OWNER_BITS = 16
LEASE_EPOCH_BITS = 15
LEASE_OWNER_MASK = (1 << LEASE_OWNER_BITS) - 1
LEASE_EPOCH_MASK = (1 << LEASE_EPOCH_BITS) - 1
# both fields — the bits a lease revocation masked-CAS compares/swaps
LEASE_MASK = (LEASE_EPOCH_MASK << LEASE_OWNER_BITS) | LEASE_OWNER_MASK


def lease_word(owner_tag: int, epoch: int = 1) -> int:
    """Pack (owner tag, lease epoch) into a held-lock word (int32 >= 0)."""
    assert 0 < int(owner_tag) <= LEASE_OWNER_MASK, "owner tag out of range"
    return ((int(epoch) & LEASE_EPOCH_MASK) << LEASE_OWNER_BITS) \
        | (int(owner_tag) & LEASE_OWNER_MASK)


def lease_owner(word: int) -> int:
    """Owner tag of a held-lock word (0 = free)."""
    return int(np.int64(int(word)) & _U32_MASK) & LEASE_OWNER_MASK


def lease_epoch(word: int) -> int:
    """Lease epoch of a held-lock word."""
    return (int(np.int64(int(word)) & _U32_MASK)
            >> LEASE_OWNER_BITS) & LEASE_EPOCH_MASK


# -- lock hash ---------------------------------------------------------------
# The reference hashes page addresses onto the on-chip lock table with
# CityHash64 % kNumOfLock (Tree.cpp:702-707,832-842).  We use a 32-bit
# Murmur3 finalizer — cheap on the VPU and well-mixing for packed addresses.

def hash32(x):
    x = jnp.asarray(x, jnp.int32).astype(jnp.uint32)
    x = jnp.bitwise_xor(x, jnp.right_shift(x, 16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = jnp.bitwise_xor(x, jnp.right_shift(x, 13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = jnp.bitwise_xor(x, jnp.right_shift(x, 16))
    return x


def lock_index(addr, locks_per_node: int):
    """Lock word index for a page address (on the page's owner node)."""
    return (hash32(addr) % jnp.uint32(locks_per_node)).astype(jnp.int32)


def hash32_np(x: np.ndarray) -> np.ndarray:
    """Vectorized host twin of :func:`hash32` on uint32 arrays —
    bit-exact, no device.  (Third sibling beside the device and scalar
    forms so a constant tweak can never diverge them: the leaf cache's
    host-side table placement must agree with its device probe.)"""
    v = np.asarray(x).astype(np.uint32).copy()
    v ^= v >> np.uint32(16)
    v *= np.uint32(0x85EBCA6B)
    v ^= v >> np.uint32(13)
    v *= np.uint32(0xC2B2AE35)
    v ^= v >> np.uint32(16)
    return v


def hash32_host(x: int) -> int:
    """Host scalar twin of :func:`hash32` — bit-exact, pure Python.  The
    host lock path hashes one address per lock acquisition; routing that
    through the jnp version dispatches a device computation per call
    (~tens of ms over a remote-access tunnel — measured 60 s of a 62 s
    flush pass before this existed)."""
    v = int(x) & _U32_MASK
    v ^= v >> 16
    v = (v * 0x85EBCA6B) & _U32_MASK
    v ^= v >> 13
    v = (v * 0xC2B2AE35) & _U32_MASK
    v ^= v >> 16
    return v


def lock_index_host(addr: int, locks_per_node: int) -> int:
    """Host scalar twin of :func:`lock_index` (same word, no device)."""
    return hash32_host(addr) % locks_per_node


# -- device-side 64-bit pair arithmetic ---------------------------------------
# TPUs have no 64-bit integer lanes; these compose uint32 (hi, lo) pairs
# into the few u64 ops the device-resident workload generator needs
# (full-width multiply for the splitmix64 finalizer).  All inputs/outputs
# are jnp.uint32 arrays; shifts are Python-int static.

def u32_mul_full(a, b):
    """Full 32x32 -> 64 multiply via 16-bit limbs: returns (hi, lo)
    uint32.  jnp uint32 * uint32 keeps only the low word, so the high
    word is assembled from the four partial products (each exact: a
    16x16 product fits 32 bits)."""
    a0, a1 = a & jnp.uint32(0xFFFF), a >> 16
    b0, b1 = b & jnp.uint32(0xFFFF), b >> 16
    p00, p01 = a0 * b0, a0 * b1
    p10, p11 = a1 * b0, a1 * b1
    t = (p00 >> 16) + (p01 & jnp.uint32(0xFFFF)) + (p10 & jnp.uint32(0xFFFF))
    lo = (p00 & jnp.uint32(0xFFFF)) | ((t & jnp.uint32(0xFFFF)) << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (t >> 16)
    return hi, lo


def u64_mul(ahi, alo, bhi, blo):
    """(ahi, alo) * (bhi, blo) mod 2^64 -> (hi, lo) uint32 pairs.  The
    cross terms contribute only to the high word (their low halves are
    shifted out), so wrapping uint32 multiplies suffice there."""
    hi, lo = u32_mul_full(alo, blo)
    hi = hi + alo * bhi + ahi * blo
    return hi, lo


def u64_shr(hi, lo, s: int):
    """Logical right shift of a (hi, lo) uint32 pair by static s."""
    if s == 0:
        return hi, lo
    if s < 32:
        return hi >> s, (lo >> s) | (hi << (32 - s))
    if s == 32:
        return jnp.zeros_like(hi), hi
    return jnp.zeros_like(hi), hi >> (s - 32)


def u64_shr_dyn(hi, lo, s):
    """Logical right shift of a (hi, lo) uint32 pair by a TRACED shift
    ``s`` (uint32 scalar or array, 0 <= s <= 63).  The static
    :func:`u64_shr` branches in Python; the device router probe needs
    the shift as data (the span grows under serving and a static shift
    would retrace the sealed prep program).  Shift amounts are clamped
    before use — XLA shifts >= bit width are undefined, so each branch
    only ever sees an in-range amount and ``jnp.where`` selects."""
    s = jnp.asarray(s, jnp.uint32)
    s_lo = jnp.minimum(s, jnp.uint32(31))          # safe for the s<32 lanes
    s_hi = jnp.where(s >= jnp.uint32(32), s - jnp.uint32(32), jnp.uint32(0))
    lo_small = (lo >> s_lo) | jnp.where(
        s_lo > 0, hi << (jnp.uint32(32) - s_lo), jnp.uint32(0))
    hi_small = hi >> s_lo
    lo_big = hi >> s_hi
    big = s >= jnp.uint32(32)
    out_hi = jnp.where(big, jnp.uint32(0), hi_small)
    out_lo = jnp.where(big, lo_big, jnp.where(s == 0, lo, lo_small))
    return out_hi, out_lo


_MIX64_C1 = (0xBF58476D, 0x1CE4E5B9)  # splitmix64 finalizer constants
_MIX64_C2 = (0x94D049BB, 0x133111EB)


def mix64_pair(hi, lo):
    """splitmix64 finalizer on (hi, lo) uint32 pairs — bit-exact twin of
    the native prep's rank->key map (native/src/prep.cc mix64), so a
    device-generated batch hits exactly the keys the bulk load wrote."""
    h, l = u64_shr(hi, lo, 30)
    hi, lo = hi ^ h, lo ^ l
    hi, lo = u64_mul(hi, lo, jnp.uint32(_MIX64_C1[0]), jnp.uint32(_MIX64_C1[1]))
    h, l = u64_shr(hi, lo, 27)
    hi, lo = hi ^ h, lo ^ l
    hi, lo = u64_mul(hi, lo, jnp.uint32(_MIX64_C2[0]), jnp.uint32(_MIX64_C2[1]))
    h, l = u64_shr(hi, lo, 31)
    return hi ^ h, lo ^ l


def mix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized host twin of :func:`mix64_pair` on uint64 arrays
    (numpy integer overflow wraps, matching the native mix64)."""
    x = np.asarray(x, np.uint64).copy()
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def mix64_host(x: int) -> int:
    """Host scalar twin of :func:`mix64_pair` (and of the native
    mix64) — for tests and native-free key-map parity."""
    x = int(x) & ((1 << 64) - 1)
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & ((1 << 64) - 1)
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & ((1 << 64) - 1)
    x ^= x >> 31
    return x
