"""Pallas page-gather kernel: an alternate path for the DSM read hot op.

Fetching a batch of 1 KB pages at data-dependent addresses is the innermost
loop of every tree operation (one gather per level per step — the analogue
of the NIC servicing ``rdmaRead`` requests, ``Operation.cpp:170``).  This
kernel streams row DMAs HBM -> VMEM with ``N_INFLIGHT`` copies in flight,
scalar-prefetching the page indices so DMA targets are known before the
body runs.

MEASURED VERDICT (v5e, 262144 rows x 1 KB): XLA's native gather runs at
~20-25 ns/row (latency-bound, independent of row width); this kernel's
sequential grid + per-row DMA wait achieves ~310 ns/row — 15x slower —
and single-row HBM slices additionally violate the (8,128) tiling on the
current Mosaic toolchain (worked around by 8-row aligned block DMAs, which
adds 8x read amplification).  The production read path therefore uses the
XLA gather (``pool[idx]``); this kernel is kept as the fallback shape for
toolchains where the tiling restriction is lifted and as the template for
future multi-core DMA pipelining.

Grid: one program per block of rows; each program pipelines its rows with
``N_INFLIGHT`` outstanding DMAs (double-buffering generalized).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from sherman_tpu.config import PAGE_WORDS

try:  # pallas is TPU-only at runtime; import lazily-tolerant
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

BLOCK = 256       # rows per grid program
N_INFLIGHT = 16   # outstanding row DMAs per program


def _gather_kernel(idx_ref, pool_ref, out_ref, sems):
    i = pl.program_id(0)
    base = i * BLOCK

    def row_dma(j, slot):
        return pltpu.make_async_copy(
            pool_ref.at[idx_ref[base + j]],
            out_ref.at[j],
            sems.at[slot],
        )

    # warm-up: fill the pipeline
    for j in range(N_INFLIGHT):
        row_dma(j, j).start()

    def body(j, _):
        row_dma(j, j % N_INFLIGHT).wait()

        @pl.when(j + N_INFLIGHT < BLOCK)
        def _():
            row_dma(j + N_INFLIGHT, (j + N_INFLIGHT) % N_INFLIGHT).start()

        return 0

    lax.fori_loop(0, BLOCK, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_tpu(pool, idx, interpret: bool = False):
    B = idx.shape[0]
    assert B % BLOCK == 0
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B // BLOCK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((BLOCK, PAGE_WORDS), lambda i, *_: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((N_INFLIGHT,))],
    )
    return pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((B, PAGE_WORDS), pool.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx, pool)


def gather_pages(pool, idx):
    """pool [P, PAGE_WORDS], idx [B] int32 (must be pre-clipped to [0, P)).

    Returns pool[idx] — Pallas row-DMA kernel on TPU, jnp gather elsewhere.
    """
    if not (_HAVE_PALLAS and jax.default_backend() == "tpu"):
        return pool[idx]
    B = idx.shape[0]
    pad = (-B) % BLOCK
    if pad:
        idx = jnp.pad(idx, (0, pad))
    out = _gather_tpu(pool, idx)
    return out[:B] if pad else out
