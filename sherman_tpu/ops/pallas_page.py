"""Pallas page-engine kernels: the explicit-DMA data plane for the pool.

The two measured memory floors every published number sits on
(BENCHMARKS.md phase table) are per-ROW and per-WORD latency floors of
XLA's gather/scatter primitives:

- the routed-search descent costs ~13-30 ns/row at 2 M rows because each
  level is "gather [B, PAGE_WORDS] pages to HBM, then elementwise pick"
  — the page round-trips through HBM between the two halves;
- the steady-state write-back scatter costs ~13.5 ms per word LANE at
  2 M rows — each of the update's 3-5 entry words is a separate
  full-batch scatter pass.

These are the TPU twins of the reference's one-sided READ descent loop
(``Tree.cpp:429-458``) and single-entry write-back (``Tree.cpp:914-921``);
the paper wins by making each RDMA op carry exactly the needed bytes.
This module is the hand-rolled equivalent for the page data plane, the
HBM<->VMEM complement of :mod:`~sherman_tpu.parallel.transport_pallas`'s
inter-chip lane:

1. :func:`descent_round` — the FUSED descent round: each row's page is
   streamed HBM->VMEM with double-buffered ``make_async_copy`` chunks
   (the next chunk's DMAs fly while the previous chunk's in-page
   search/child-pick runs on the VPU), and only the next-level address +
   leaf verdicts leave the kernel — no ``[B, PAGE_WORDS]`` intermediate
   is materialized in HBM between the gather and the pick.
2. :func:`writeback` — the multi-lane write-back: all 3-5 word lanes of
   an applied entry ride ONE kernel pass (per row, the lane writes are
   posted back-to-back as single-word DMAs — a doorbell batch), so cost
   stops scaling linearly per lane.
3. :func:`gather_pages` — the snapshot gather for the apply path's
   one-page-many-consumers read (``leaf_apply_spmd``'s page snapshot),
   row DMAs with an ``N_INFLIGHT``-deep ring.

Selection: ``DSMConfig.gather_impl = "xla" | "pallas"`` (mirroring
``exchange_impl``); wrappers raise :class:`PallasUnavailableError` naming
the knob when the toolchain is absent.  ``"xla"`` stays the default —
HISTORY: a round-1 Pallas page-gather kernel measured ~310 ns/row vs
XLA's ~20-25 ns/row on v5e (sequential per-row DMA waits; removed in
round 3, see BENCHMARKS.md reproducibility notes and
``git log -- sherman_tpu/ops/gather.py``).  This suite changes what is
FUSED (descent compute rides the stream; write lanes share one pass),
not just how bytes move, and ships with standing receipts
(``tools/profile_gather.py``, ``kernels.*`` obs counters, bench JSON
fields) so the pallas-vs-xla A/B is a one-command capture on chip —
the knob flips per deployment from measurement, not belief.

Parity contract: every kernel is BIT-IDENTICAL to its ``*_xla`` twin
(which mirrors the inline code in ``models/batched.py`` /
``parallel/dsm.py``) on ANY inputs — including garbage pages — pinned
by the interpreter-mode fuzz in ``tests/test_pallas_page.py``.  The one
exception is :func:`writeback`: rows with ``applied`` must carry
in-range (page, word) targets, which the apply kernels guarantee by
construction (clipped pages, found/ranked slots).

Mosaic toolchain notes (jax 0.4.37): integer reductions do not lower,
so in-kernel sums/anys run as exact float32 16-bit-half sums (<= 82
terms of < 2^16 each — exact in f32, recombined with int32 wrap
arithmetic, so results stay bit-identical to XLA's wrapping integer
sums); iota constants are ``lax.broadcasted_iota`` (kernels cannot
capture array constants).

Like transport_pallas, kernels run in INTERPRETER mode off-TPU (the CPU
test mesh) and are compile-smoked for the TPU target without hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from sherman_tpu.errors import ShermanError
from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.ops import bits, layout

try:  # pallas is TPU-oriented; CPU uses interpreter mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

_PW = C.PAGE_WORDS

# Rows per grid program (descent / snapshot / write-back).  256 keeps
# the VMEM blocks on the (8, 128)-divisible grid Mosaic requires and
# bounds per-program VMEM at 2 * CHUNK pages + the row blocks.
BLOCK = 256
# Rows per double-buffer slot of the descent stream: the next CHUNK's
# page DMAs are posted before the current CHUNK's search runs, and the
# (CHUNK, PAGE_WORDS) tile keeps the VPU lanes full during the pick.
CHUNK = 8
# In-flight row DMAs of the snapshot gather ring.
N_INFLIGHT = 16
# Write-back rows whose lane DMAs may be in flight at once.
WB_WINDOW = 8

# Traced-issue accounting (transport.py convention: one inc per program
# BUILD; per-execution truth stays with the dsm.* device counters).
_OBS_DESCENT = obs.counter("kernels.descent_rounds_traced")
_OBS_DESCENT_ROWS = obs.counter("kernels.descent_rows_per_round")
_OBS_SNAP = obs.counter("kernels.snapshot_gathers_traced")
_OBS_SNAP_ROWS = obs.counter("kernels.snapshot_rows_per_gather")
_OBS_WB = obs.counter("kernels.writeback_passes_traced")
_OBS_WB_ROWS = obs.counter("kernels.writeback_rows_per_pass")
_OBS_WB_LANES = obs.counter("kernels.writeback_lanes_traced")


class PallasUnavailableError(ShermanError, RuntimeError):
    """Typed, actionable: the Pallas/Mosaic toolchain is missing but a
    config knob selected it.  Names the knob to flip back."""

    def __init__(self, knob: str):
        super().__init__(
            f"Pallas/Mosaic toolchain unavailable but {knob}=\"pallas\" "
            f"was requested: set {knob}=\"xla\" (the default, "
            "compiler-scheduled path) or install a jaxlib with Pallas "
            "TPU support")
        self.knob = knob


def available() -> bool:
    return HAVE_PALLAS


def use_pallas(cfg) -> bool:
    """True iff ``cfg.gather_impl == "pallas"``; raises the typed error
    (naming the knob) when that was requested without the toolchain."""
    if cfg.gather_impl != "pallas":
        return False
    if not HAVE_PALLAS:
        raise PallasUnavailableError("DSMConfig.gather_impl")
    return True


def _interpret() -> bool:
    # same trace-time rule as transport.exchange: interpreter everywhere
    # but a real TPU backend
    return jax.default_backend() != "tpu"


def _pad_to_block(n: int) -> int:
    return -(-max(n, 1) // BLOCK) * BLOCK


def _pad1(x, n_pad, fill=0):
    n = x.shape[0]
    if n == n_pad:
        return x
    return jnp.concatenate(
        [x, jnp.full((n_pad - n,) + x.shape[1:], fill, x.dtype)])


# ---------------------------------------------------------------------------
# In-kernel page search primitives — bit-exact twins of ops/layout.py,
# expressed without captured array constants or integer reductions.
# ---------------------------------------------------------------------------

def _masked_isum(vals, mask):
    """Exact int32 wrap-sum of ``vals`` where ``mask`` (along the last
    axis) without integer reductions (Mosaic gap): sum the unsigned
    16-bit halves in float32 (<= 82 terms of < 2^16 each — exact), then
    recombine with int32 wrap arithmetic.  Equals XLA's wrapping integer
    masked sum bit-for-bit."""
    lo = vals & jnp.int32(0xFFFF)
    hi = jnp.right_shift(vals, 16) & jnp.int32(0xFFFF)
    z = jnp.float32(0)
    slo = jnp.sum(jnp.where(mask, lo.astype(jnp.float32), z), axis=-1)
    shi = jnp.sum(jnp.where(mask, hi.astype(jnp.float32), z), axis=-1)
    return (jnp.left_shift(shi.astype(jnp.int32), 16)
            + slo.astype(jnp.int32))


def _any_last(mask):
    """jnp.any(mask, -1) via an exact f32 count (no integer reduce)."""
    return jnp.sum(mask.astype(jnp.float32), axis=-1) > 0


def _pick_child_k(pg, kh, kl):
    """In-kernel ``layout.internal_pick_child`` twin.  The le_next shift
    reads the entry blocks offset by one word (static slice) instead of
    concatenating, masked so column CAP-1 is always False — identical to
    the zero-padded shift on ALL inputs, garbage pages included."""
    ICAP = C.INTERNAL_CAP
    ekhi = pg[:, C.I_KHI_W:C.I_KHI_W + ICAP]
    eklo = pg[:, C.I_KLO_W:C.I_KLO_W + ICAP]
    n = layout.h_nkeys(pg)[:, None]
    iota = lax.broadcasted_iota(jnp.int32, ekhi.shape, 1)
    le = bits.key_le(ekhi, eklo, kh[:, None], kl[:, None]) & (iota < n)
    ekhi1 = pg[:, C.I_KHI_W + 1:C.I_KHI_W + 1 + ICAP]
    eklo1 = pg[:, C.I_KLO_W + 1:C.I_KLO_W + 1 + ICAP]
    le_next = (bits.key_le(ekhi1, eklo1, kh[:, None], kl[:, None])
               & ((iota + 1) < n) & (iota < ICAP - 1))
    edge = le & ~le_next
    ptrs = pg[:, C.I_PTR_W:C.I_PTR_W + ICAP]
    child = _masked_isum(ptrs, edge)
    return jnp.where(_any_last(le), child, layout.h_leftmost(pg))


def _leaf_find_k(pg, kh, kl):
    """In-kernel ``layout.leaf_find_key`` twin (found, vhi, vlo)."""
    LCAP = C.LEAF_CAP
    fv, rv = layout.ver_unpack(pg[:, C.L_VER_W:C.L_VER_W + LCAP])
    used = (fv == rv) & (fv != 0)
    ekhi = pg[:, C.L_KHI_W:C.L_KHI_W + LCAP]
    eklo = pg[:, C.L_KLO_W:C.L_KLO_W + LCAP]
    hit = used & bits.key_eq(ekhi, eklo, kh[:, None], kl[:, None])
    found = _any_last(hit)
    vh = _masked_isum(pg[:, C.L_VHI_W:C.L_VHI_W + LCAP], hit)
    vl = _masked_isum(pg[:, C.L_VLO_W:C.L_VLO_W + LCAP], hit)
    return found, vh, vl


def _round_compute(pg, kh, kl, ok, stop_level: int):
    """One row-chunk's in-VMEM search: level/chase/child-pick/leaf-find
    on (CHUNK, PAGE_WORDS) pages, zeroed where not ok (the read_pages
    contract)."""
    pg = jnp.where(ok[:, None], pg, 0)
    lvl = layout.h_level(pg)
    chase = layout.needs_sibling_chase(pg, kh, kl)
    is_leaf = (lvl == stop_level) & ~chase
    nxt = jnp.where(chase, layout.h_sibling(pg), _pick_child_k(pg, kh, kl))
    f, vh, vl = _leaf_find_k(pg, kh, kl)
    return nxt, is_leaf, chase, f, vh, vl


# ---------------------------------------------------------------------------
# Kernel 1: fused descent round.
# ---------------------------------------------------------------------------

def _descent_kernel(addr_sref, addr_ref, khi_ref, klo_ref, act_ref,
                    pool_ref, nxt_ref, leaf_ref, chase_ref, ok_ref,
                    f_ref, vh_ref, vl_ref, buf, sems, *, n_pages: int,
                    stop_level: int):
    pid = pl.program_id(0)
    n_chunks = BLOCK // CHUNK

    def chunk_dma(c, slot, start):
        # CHUNK single-page copies posted back-to-back (doorbell batch);
        # the scalar-prefetched addrs are the DMA targets, clipped to
        # the pool exactly as the XLA gather clips.
        base = pid * BLOCK + c * CHUNK
        for r in range(CHUNK):
            pg = jnp.clip(addr_sref[base + r] & C.ADDR_PAGE_MASK, 0,
                          n_pages - 1)
            cp = pltpu.make_async_copy(pool_ref.at[pl.ds(pg, 1)],
                                       buf.at[slot, pl.ds(r, 1)],
                                       sems.at[slot, r])
            (cp.start if start else cp.wait)()

    chunk_dma(0, 0, True)

    def body(c, _):
        slot = lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():  # stream the NEXT chunk while this one is searched
            chunk_dma(c + 1, lax.rem(c + 1, 2), True)

        chunk_dma(c, slot, False)
        s = pl.ds(c * CHUNK, CHUNK)
        page_idx = addr_ref[s] & C.ADDR_PAGE_MASK
        ok = (act_ref[s] != 0) & (page_idx >= 0) & (page_idx < n_pages)
        nxt, is_leaf, chase, f, vh, vl = _round_compute(
            buf[slot], khi_ref[s], klo_ref[s], ok, stop_level)
        nxt_ref[s] = nxt
        leaf_ref[s] = is_leaf.astype(jnp.int32)
        chase_ref[s] = chase.astype(jnp.int32)
        ok_ref[s] = ok.astype(jnp.int32)
        f_ref[s] = f.astype(jnp.int32)
        vh_ref[s] = vh
        vl_ref[s] = vl
        return 0

    lax.fori_loop(0, n_chunks, body, 0)


def descent_round(pool, addr, khi, klo, active, *, stop_level: int = 0,
                  interpret: bool | None = None):
    """One fused descent round over ``[B]`` rows.

    For each active row: stream its page HBM->VMEM (double-buffered
    CHUNK tiles), search it in VMEM, and emit ``(nxt, is_leaf, chase,
    ok, found, vhi, vlo)`` — next-level address, (level == stop_level
    and in fence), sibling-chase flag, page-read validity, and the leaf
    lookup verdicts.  Bit-identical to :func:`descent_round_xla` (the
    gather + ``ops/layout`` composition the XLA path runs) on any
    inputs.  Bool outputs return as bool arrays.
    """
    if not HAVE_PALLAS:
        raise PallasUnavailableError("DSMConfig.gather_impl")
    B = addr.shape[0]
    P = pool.shape[0]
    Bp = _pad_to_block(B)
    addr_p = _pad1(jnp.asarray(addr, jnp.int32), Bp)
    khi_p = _pad1(jnp.asarray(khi, jnp.int32), Bp)
    klo_p = _pad1(jnp.asarray(klo, jnp.int32), Bp)
    act_p = _pad1(active.astype(jnp.int32), Bp)
    _OBS_DESCENT.inc()
    _OBS_DESCENT_ROWS.inc(B)

    bspec = lambda: pl.BlockSpec((BLOCK,), lambda i, idx: (i,))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Bp // BLOCK,),
        in_specs=[bspec(), bspec(), bspec(), bspec(),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=tuple(bspec() for _ in range(7)),
        scratch_shapes=[pltpu.VMEM((2, CHUNK, _PW), jnp.int32),
                        pltpu.SemaphoreType.DMA((2, CHUNK))],
    )
    sh = jax.ShapeDtypeStruct((Bp,), jnp.int32)
    kern = functools.partial(_descent_kernel, n_pages=P,
                             stop_level=stop_level)
    outs = pl.pallas_call(
        kern, out_shape=(sh,) * 7, grid_spec=grid_spec,
        interpret=_interpret() if interpret is None else interpret,
    )(addr_p, addr_p, khi_p, klo_p, act_p, pool)
    nxt, is_leaf, chase, ok, f, vh, vl = (o[:B] for o in outs)
    return (nxt, is_leaf != 0, chase != 0, ok != 0, f != 0, vh, vl)


def descent_round_xla(pool, addr, khi, klo, active, *, stop_level: int = 0):
    """Reference twin: the exact gather + layout composition the XLA
    descent paths run (``read_pages_spmd`` N==1 + ``advance``), with the
    same output tuple as :func:`descent_round`."""
    P = pool.shape[0]
    page = bits.addr_page(addr)
    ok = active & (page >= 0) & (page < P)
    pg = jnp.where(ok[:, None], pool[jnp.clip(page, 0, P - 1)], 0)
    lvl = layout.h_level(pg)
    chase = layout.needs_sibling_chase(pg, khi, klo)
    is_leaf = (lvl == stop_level) & ~chase
    nxt = jnp.where(chase, layout.h_sibling(pg),
                    layout.internal_pick_child(pg, khi, klo))
    f, vh, vl, _ = layout.leaf_find_key(pg, khi, klo)
    return nxt, is_leaf, chase, ok, f, vh, vl


# ---------------------------------------------------------------------------
# Kernel 2: multi-lane write-back.
# ---------------------------------------------------------------------------

def _writeback_kernel(page_sref, slot_sref, app_sref, ent_ref, pool_ref,
                      out_ref, sems, *, n_pages: int,
                      field_w: tuple[int, ...]):
    pid = pl.program_id(0)
    L = len(field_w)

    def lane_copies(r):
        base = pid * BLOCK + r
        pg = jnp.clip(page_sref[base], 0, n_pages - 1)
        sl = slot_sref[base]
        return [pltpu.make_async_copy(
                    ent_ref.at[pl.ds(r, 1), pl.ds(l, 1)],
                    out_ref.at[pl.ds(pg, 1), pl.ds(field_w[l] + sl, 1)],
                    sems.at[lax.rem(r, WB_WINDOW), l])
                for l in range(L)]

    def row(r, start):
        @pl.when(app_sref[pid * BLOCK + r] != 0)
        def _():
            # ALL lanes of the row posted before any wait — the
            # single-entry doorbell batch; cost per row is one DMA
            # latency, not one per lane.
            for cp in lane_copies(r):
                (cp.start if start else cp.wait)()

    def body(r, _):
        @pl.when(r >= WB_WINDOW)
        def _():  # recycle the slot's semaphores before reuse
            row(r - WB_WINDOW, False)
        row(r, True)
        return 0

    lax.fori_loop(0, BLOCK, body, 0)
    for k in range(WB_WINDOW):  # drain the tail window
        row(BLOCK - WB_WINDOW + k, False)


def writeback(pool, page, slot, applied, ent, field_w: tuple[int, ...],
              interpret: bool | None = None):
    """Multi-lane entry write-back: for each row with ``applied``, write
    ``ent[r, l]`` to ``pool[page[r], field_w[l] + slot[r]]`` — all lanes
    in ONE kernel pass over the rows (vs one full-batch XLA scatter per
    lane).  In-place on ``pool`` (input/output aliased).

    Contract: ``page`` pre-clipped to the pool (the apply kernels pass
    ``safe_page``) and applied rows carry in-page ``field_w[l] + slot``
    word targets — guaranteed by the apply kernels' found/ranked slots.
    Matches :func:`writeback_xla` under that contract; rows without
    ``applied`` are dropped exactly like the XLA path's out-of-range
    scatter indices.
    """
    if not HAVE_PALLAS:
        raise PallasUnavailableError("DSMConfig.gather_impl")
    M = page.shape[0]
    P = pool.shape[0]
    L = len(field_w)
    assert ent.shape == (M, L)
    Mp = _pad_to_block(M)
    page_p = _pad1(jnp.asarray(page, jnp.int32), Mp)
    slot_p = _pad1(jnp.asarray(slot, jnp.int32), Mp)
    app_p = _pad1(applied.astype(jnp.int32), Mp)
    ent_p = _pad1(jnp.asarray(ent, jnp.int32), Mp)
    _OBS_WB.inc()
    _OBS_WB_ROWS.inc(M)
    _OBS_WB_LANES.inc(L)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(Mp // BLOCK,),
        in_specs=[pl.BlockSpec((BLOCK, L), lambda i, *_: (i, 0)),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((WB_WINDOW, L))],
    )
    kern = functools.partial(_writeback_kernel, n_pages=P,
                             field_w=tuple(int(w) for w in field_w))
    return pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct((P, _PW), pool.dtype),
        grid_spec=grid_spec,
        input_output_aliases={4: 0},  # pool (after the 3 prefetch + ent)
        interpret=_interpret() if interpret is None else interpret,
    )(page_p, slot_p, app_p, ent_p, pool)


def writeback_xla(pool, page, slot, applied, ent, field_w: tuple[int, ...]):
    """Reference twin: the per-lane flat scatter the XLA apply path runs
    (``leaf_apply_spmd`` / ``leaf_delete_apply_spmd`` write-back)."""
    P = pool.shape[0]
    fw = jnp.asarray(list(field_w), jnp.int32)
    idx = (page * _PW)[:, None] + fw[None, :] + slot[:, None]
    idx = jnp.where(applied[:, None], idx, P * _PW)
    flat = pool.reshape(-1)
    flat = flat.at[idx.reshape(-1)].set(ent.reshape(-1), mode="drop")
    return flat.reshape(P, _PW)


# ---------------------------------------------------------------------------
# Kernel 3: snapshot gather (one page, many consumers).
# ---------------------------------------------------------------------------

def _gather_kernel(rows_sref, pool_ref, out_ref, sems, *, n_pages: int):
    pid = pl.program_id(0)

    def row_dma(j):
        pg = jnp.clip(rows_sref[pid * BLOCK + j], 0, n_pages - 1)
        return pltpu.make_async_copy(pool_ref.at[pl.ds(pg, 1)],
                                     out_ref.at[pl.ds(j, 1)],
                                     sems.at[lax.rem(j, N_INFLIGHT)])

    for j in range(N_INFLIGHT):  # fill the ring
        row_dma(j).start()

    def body(j, _):
        row_dma(j).wait()

        @pl.when(j + N_INFLIGHT < BLOCK)
        def _():
            row_dma(j + N_INFLIGHT).start()
        return 0

    lax.fori_loop(0, BLOCK, body, 0)


def gather_pages(pool, rows, interpret: bool | None = None):
    """``pool[jnp.clip(rows, 0, P - 1)]`` as an N_INFLIGHT-deep row-DMA
    ring — the apply path's materialized page snapshot (its output IS
    the snapshot buffer, so no ``optimization_barrier`` is needed to
    stop XLA re-fusing the gather into consumers)."""
    if not HAVE_PALLAS:
        raise PallasUnavailableError("DSMConfig.gather_impl")
    M = rows.shape[0]
    P = pool.shape[0]
    Mp = _pad_to_block(M)
    rows_p = _pad1(jnp.asarray(rows, jnp.int32), Mp)
    _OBS_SNAP.inc()
    _OBS_SNAP_ROWS.inc(M)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Mp // BLOCK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((BLOCK, _PW), lambda i, idx: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((N_INFLIGHT,))],
    )
    kern = functools.partial(_gather_kernel, n_pages=P)
    out = pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct((Mp, _PW), pool.dtype),
        grid_spec=grid_spec,
        interpret=_interpret() if interpret is None else interpret,
    )(rows_p, pool)
    return out[:M]


def gather_pages_xla(pool, rows):
    """Reference twin of :func:`gather_pages`."""
    P = pool.shape[0]
    return pool[jnp.clip(rows, 0, P - 1)]


def read_pages_local(pool, addrs, active):
    """The single-node ``read_pages_spmd`` contract over the pallas
    gather: (pages zeroed where not ok, ok)."""
    P = pool.shape[0]
    page = bits.addr_page(addrs)
    ok = active & (page >= 0) & (page < P)
    pages = gather_pages(pool, page)
    return jnp.where(ok[:, None], pages, 0), ok
