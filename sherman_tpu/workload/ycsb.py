"""YCSB A-F workload matrix — first-class core-workload generators.

The repo has run "YCSB-C-shaped" (zipf lookups) and "YCSB-A-shaped"
(50/50 mixed) loops since round 1, but as ad-hoc bench phases; this
module stands the full core matrix up as named, reproducible
generators with ANALYTIC expectations published next to every measured
row (the bench-receipt discipline: a number without its predicted twin
is a number nobody can audit):

========  =============================================  ============
workload  mix                                            distribution
========  =============================================  ============
A         50% read / 50% update                          zipf
B         95% read /  5% update                          zipf
C         100% read                                      zipf
D         95% read /  5% insert (read-latest)            latest
E         95% scan /  5% insert                          zipf
F         50% read / 50% read-modify-write               zipf
========  =============================================  ============

Keys are the repo's standard hashed keyspace (``bits.mix64_np(rank ^
salt)`` — the bulk-load/staged-loop key map), so zipf RANK skew lands
on uniformly scattered keys.  Scans therefore select by KEY SPAN, not
rank span: a scan of expected length L covers ``L * 2^64 / n_keys`` of
the key space (the ``tools/benchmark.py --scan-span`` construction),
and the measured rows-per-scan is published against that analytic
expectation.  "latest" (YCSB-D) skews toward the INSERT FRONTIER:
rank = frontier - 1 - Zipf(theta) sample, so freshly inserted records
are the hottest — the standard YCSB-D shape.

Payload sizes (the value heap's axis): ``value_bytes`` with
``value_dist`` "fixed" (every record exactly that size) or "uniform"
(per-key deterministic uniform in [1, value_bytes], hashed from the
key so regenerating a record is stable across processes).
``payload_for_key`` is the one deterministic record constructor every
driver and verifier shares.
"""

from __future__ import annotations

import numpy as np

from sherman_tpu.errors import ConfigError
from sherman_tpu.ops import bits
from sherman_tpu.workload.zipf import ZipfGen

__all__ = ["WORKLOADS", "YcsbGen", "payload_for_key"]

WORKLOADS = {
    "A": {"read": 0.50, "update": 0.50, "dist": "zipf"},
    "B": {"read": 0.95, "update": 0.05, "dist": "zipf"},
    "C": {"read": 1.00, "dist": "zipf"},
    "D": {"read": 0.95, "insert": 0.05, "dist": "latest"},
    "E": {"scan": 0.95, "insert": 0.05, "dist": "zipf",
          "max_scan": 100},
    "F": {"read": 0.50, "rmw": 0.50, "dist": "zipf"},
}


def payload_for_key(key: int, value_bytes: int,
                    value_dist: str = "fixed") -> bytes:
    """Deterministic variable-length record for ``key`` — the shared
    constructor (drivers write it, verifiers regenerate it).  "fixed"
    -> exactly ``value_bytes``; "uniform" -> stable per-key length in
    [1, value_bytes] (hashed from the key)."""
    if value_dist == "fixed":
        n = int(value_bytes)
    elif value_dist == "uniform":
        n = 1 + int(bits.mix64_host(int(key) ^ 0x5CAB) % int(value_bytes))
    else:
        raise ConfigError(
            f"value_dist={value_dist!r}: want fixed|uniform")
    seed = np.uint64(bits.mix64_host(int(key)))
    block = seed.tobytes()
    return (block * (n // 8 + 1))[:n]


class YcsbGen:
    """Batched op-stream generator for one YCSB core workload.

    ``batch(n)`` draws one closed-loop batch as class-separated arrays
    (the repo's batched execution model — no per-op scalar loop):
    ``{"read": keys, "update": keys, "insert": keys, "scan": [(lo,
    hi)], "rmw": keys}``, advancing the insert frontier for D/E.
    ``expectations()`` is the analytic twin every receipt publishes.
    """

    def __init__(self, workload: str, n_keys: int, *,
                 theta: float = 0.99, seed: int = 0,
                 salt: int = 0x5E17_AB1E_5A17,
                 value_bytes: int = 64, value_dist: str = "fixed"):
        if workload not in WORKLOADS:
            raise ConfigError(
                f"unknown YCSB workload {workload!r}: want one of "
                f"{sorted(WORKLOADS)}")
        self.workload = workload
        self.mix = WORKLOADS[workload]
        self.n_keys = int(n_keys)
        self.theta = float(theta)
        self.salt = int(salt)
        self.value_bytes = int(value_bytes)
        self.value_dist = value_dist
        self.rng = np.random.default_rng(seed)
        self.zipf = ZipfGen(self.n_keys, theta, seed=seed + 1)
        #: next fresh rank D/E inserts append at (read-latest skews
        #: toward it)
        self.frontier = self.n_keys
        self.ops_drawn = 0

    # -- keyspace -------------------------------------------------------------

    def keys_of_ranks(self, ranks) -> np.ndarray:
        k = bits.mix64_np(np.asarray(ranks, np.uint64)
                          ^ np.uint64(self.salt))
        # keep clear of the fence sentinels (astronomically rare, but a
        # generator must not be able to emit an illegal key)
        from sherman_tpu import config as C
        return np.clip(k, np.uint64(C.KEY_MIN), np.uint64(C.KEY_MAX))

    def payloads_for_keys(self, keys) -> list:
        return [payload_for_key(int(k), self.value_bytes,
                                self.value_dist) for k in keys]

    def _hot_ranks(self, n: int) -> np.ndarray:
        if self.mix["dist"] == "latest":
            # read-latest: hottest = newest (frontier - 1 - zipf)
            z = self.zipf.sample(n)
            return np.maximum(0, self.frontier - 1 - z)
        return self.zipf.sample(n)

    def scan_span(self, length: int) -> int:
        """Key-space span expected to cover ``length`` records in the
        hashed keyspace (uniform key scatter)."""
        live = max(1, self.frontier)
        return max(1, int(length * (2.0 ** 64) / live))

    # -- batches --------------------------------------------------------------

    def batch(self, n: int) -> dict:
        """One n-op batch as class-separated arrays (see class doc)."""
        u = self.rng.random(n)
        out: dict = {}
        edges = 0.0
        kinds = np.empty(n, dtype="U6")
        for kind, frac in self.mix.items():
            if kind in ("dist", "max_scan"):
                continue
            kinds[(u >= edges) & (u < edges + frac)] = kind
            edges += frac
        kinds[u >= edges] = next(k for k in self.mix
                                 if k not in ("dist", "max_scan"))
        for kind in ("read", "update", "rmw"):
            m = int((kinds == kind).sum())
            if m:
                out[kind] = self.keys_of_ranks(self._hot_ranks(m))
        m_ins = int((kinds == "insert").sum())
        if m_ins:
            ranks = np.arange(self.frontier, self.frontier + m_ins,
                              dtype=np.uint64)
            self.frontier += m_ins
            out["insert"] = self.keys_of_ranks(ranks)
        m_scan = int((kinds == "scan").sum())
        if m_scan:
            max_scan = int(self.mix.get("max_scan", 100))
            lens = self.rng.integers(1, max_scan + 1, m_scan)
            starts = self.keys_of_ranks(self._hot_ranks(m_scan))
            out["scan"] = [
                (int(s), min(int(s) + self.scan_span(int(ln)),
                             (1 << 64) - 1))
                for s, ln in zip(starts, lens)]
            out["scan_expected_rows"] = int(lens.sum())
        self.ops_drawn += n
        return out

    # -- analytics ------------------------------------------------------------

    def expectations(self) -> dict:
        """The receipt's analytic block: op-class fractions by
        construction, plus mean scan length (E)."""
        exp = {k: v for k, v in self.mix.items()
               if k not in ("dist", "max_scan")}
        out = {"mix": exp, "dist": self.mix["dist"],
               "theta": self.theta,
               "value_bytes": self.value_bytes,
               "value_dist": self.value_dist}
        if "scan" in exp:
            out["scan_len_mean"] = (1 + self.mix["max_scan"]) / 2.0
        return out
