"""Device-resident batch staging: the benchmark loop's entire client
side — zipf rank sampling, the synthetic rank->key map, request
combining (sort-based unique + inverse), and the index-cache probe —
as ONE jitted TPU computation fused with the serving step, so a
sustained loop ships NOTHING per step (the step counter threads through
device-resident carry; the host only dispatches).

Reference parity: the reference benchmark's client threads generate
their zipf key and issue it inline in the open loop
(``test/benchmark.cpp:159-188``) — nothing hoisted.  Here the TPU is
client and server fused, so generation runs on device inside the timed
step.  Fidelity:

- The rank distribution inverts the SAME Gray/Jain CDF the native
  sampler uses (``native/src/prep.cc``), via a host-precomputed
  quantile table: ``table[i]`` = inverse CDF at quantile ``i / 2^LB``
  (float64-exact head + Euler-Maclaurin tail, vectorized bisection).
  On device a sample is a 2-word counter-based PRNG draw: word 0 picks
  the quantile bin (the CDF is exact at bin edges — hot ranks span
  many whole bins, so the head is EXACT), word 1 lerps within the bin
  (piecewise-uniform; bins are <= ~2^14 ranks wide even in the deepest
  tail, where the zipf density is locally flat, so the within-bin
  approximation is statistically invisible).  The f32 lerp is exact to
  <1 rank for bin widths < 2^24 (asserted at table build).
- The rank->key map is bit-for-bit the native one:
  ``mix64(rank ^ salt)`` on (hi, lo) uint32 pairs
  (:func:`sherman_tpu.ops.bits.mix64_pair`), so device-generated
  batches hit exactly the keys the bulk load wrote.
- Dedup is a device ``lax.sort`` by key + segment scan; the unique set
  is compacted by a SECOND stable sort on the first-occurrence flag
  (sorts measure ~6 ms at 4 M rows on chip, while the scatter-based
  compaction they replace measured ~24 ms per scatter — random
  HBM writes are the expensive primitive, sorts are not).  The unique
  rows come out KEY-SORTED, which after a sequential bulk load is also
  page-address-sorted: the round-1 leaf gather gets the start-sorted
  locality win (measured ~27% on host-staged batches) for free.
- The step SERVES CLIENTS IN SORTED ORDER: the client view of the
  batch is the key-sorted permutation of the generated ops (client
  order carries no meaning — the reference's client threads are
  unordered).  That makes the per-request answer fan-out a MONOTONE
  gather (``ans[seg]``, seg nondecreasing) instead of a random one,
  and drops the inverse-permutation scatter entirely.  Every client
  op's answer is still materialized in HBM inside the step and
  VERIFIED on device: the carry accumulates the exact count of client
  ops whose returned value matched ``key ^ check_xor`` — the
  honest-accounting receipts ride inside the timed loop.

Program structure (the round-6 "staged-step anatomy" work): the step's
compiled-program split is a first-class knob (``fusion=`` /
``SHERMAN_STAGED_FUSION``, see :func:`make_staged_step`).  The default
``aligned`` form dispatches ``prep -> serve -> verify`` where the serve
IS the engine's host-staged combined-search fan-out program — the same
compiled executable the throughput phase runs — so no input-layout,
donation, or shard_map-fusion difference can exist between the staged
serve and the host-staged serve by construction.  Every form exposes
``step.programs`` and ``step.phase_profile`` (chained-delta per-phase
wall costs) so benchmarks publish per-phase timings instead of
re-profiling.
"""

from __future__ import annotations

import numpy as np

from sherman_tpu import config as C
from sherman_tpu.errors import ConfigError
from sherman_tpu.obs import device as DEV
from sherman_tpu.ops import bits


def zipf_table(n: int, theta: float, log2_bins: int = 20) -> np.ndarray:
    """Inverse-CDF quantile table for Zipf(theta) ranks over [0, n):
    int32 [2^log2_bins + 1], ``table[i]`` = smallest 0-based rank r with
    CDF(r) >= i / 2^log2_bins (``table[-1]`` = n - 1).

    theta == 0 degenerates to the uniform ramp.  Head ranks are exact
    (float64 cumsum of the harmonic series up to 2^22); tail CDF values
    use the Euler-Maclaurin continuation (error << one quantile), and
    the inversion is a vectorized bisection."""
    assert 0.0 <= theta < 1.0 and n >= 1
    nb = 1 << log2_bins
    if theta == 0.0:
        t = np.floor(np.arange(nb + 1, dtype=np.float64) * n / nb)
        table = np.minimum(t, n - 1).astype(np.int32)
    else:
        M = min(n, 1 << 22)
        f = np.arange(1, M + 1, dtype=np.float64) ** -theta
        Hhead = np.cumsum(f)
        om = 1.0 - theta

        def H(r):
            """Harmonic partial sum H(r) = sum_{k=1..r} k^-theta for
            real r >= M (Euler-Maclaurin; exact head)."""
            r = np.asarray(r, np.float64)
            integral = (r ** om - float(M) ** om) / om
            half = 0.5 * (r ** -theta - float(M) ** -theta)
            d112 = (theta / 12.0) * (r ** (-theta - 1.0)
                                     - float(M) ** (-theta - 1.0))
            return Hhead[-1] + integral + half - d112

        Hn = Hhead[-1] if n <= M else float(H(float(n)))
        q = np.arange(nb + 1, dtype=np.float64) / nb * Hn
        table = np.searchsorted(Hhead, q, side="left").astype(np.int64)
        tail = q > Hhead[-1]
        if tail.any():
            qt = q[tail]
            lo = np.full(qt.shape, float(M))
            hi = np.full(qt.shape, float(n))
            for _ in range(48):
                mid = 0.5 * (lo + hi)
                ge = H(mid) >= qt
                hi = np.where(ge, mid, hi)
                lo = np.where(ge, lo, mid)
            table[tail] = np.ceil(hi).astype(np.int64) - 1
        table = np.minimum(np.maximum(table, 0), n - 1).astype(np.int32)
    assert (np.diff(table) >= 0).all()
    assert int(np.diff(table.astype(np.int64)).max(initial=0)) < (1 << 24), \
        "quantile bin wider than the 24-bit lerp resolution; raise log2_bins"
    return table


def zipf_analytic_consts(n: int, theta: float, head: int = 64) -> dict:
    """Host-side float64 constants for the ANALYTIC device inverse CDF
    (:func:`_gen_ranks_analytic`): exact partial sums H(1..head) for the
    head, and the Euler-Maclaurin continuation constants for the tail.

    Same approximation class as the quantile table (exact head, E-M
    tail, ~single-rank precision where the density is steep and a flat
    local density where it is not) — but evaluated in VPU registers
    instead of a [2^20, 2] HBM gather, which is the dominant prep cost
    on chip (~15 ns/row)."""
    assert 0.0 < theta < 1.0 and n > head
    f = np.arange(1, head + 1, dtype=np.float64) ** -theta
    Hh = np.cumsum(f)
    om = 1.0 - theta
    M = float(head)

    def H(r):
        """E-M continuation of the harmonic partial sum for r >= head."""
        r = np.asarray(r, np.float64)
        return (Hh[-1] + (r ** om - M ** om) / om
                + 0.5 * (r ** -theta - M ** -theta)
                - (theta / 12.0) * (r ** (-theta - 1.0)
                                    - M ** (-theta - 1.0)))

    return {
        "head_sums": Hh, "om": om, "theta": theta, "M": M,
        "Hn": float(H(float(n))),
        # tail-init constant: r0 = (om*(x - B0))^(1/om) drops the small
        # E-M terms; Newton below restores them
        "B0": float(Hh[-1] - (M ** om) / om),
    }


def _gen_ranks_analytic(consts: dict, w, *, n_keys: int):
    """Zipf ranks via the analytic inverse CDF — NO table gather.

    u from 24 fresh PRNG bits -> x = u * H(n); head ranks (< head) by
    64 unrolled register compares against the exact partial sums (CDF-
    exact, like the table's head); tail by inverting the Euler-Maclaurin
    continuation: closed-form init + two Newton steps in f32
    (H'(r) = r^-theta).  f32 rank jitter in the deep tail (~1e4 ranks
    at r ~ 1e8) sits inside the quantile table's own bin width there
    (up to 2^24 ranks), so the two samplers share an approximation
    class; `tests/test_device_prep.py` pins both against the exact CDF.
    """
    import jax.numpy as jnp

    Hh = consts["head_sums"]
    om = jnp.float32(consts["om"])
    theta = jnp.float32(consts["theta"])
    Mf = jnp.float32(consts["M"])
    u = (w[0] >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)
    x = u * jnp.float32(consts["Hn"])
    # head: rank = #(partial sums < x), CDF-exact for ranks < head
    rank_head = jnp.zeros(x.shape, jnp.int32)
    for h in Hh:
        rank_head = rank_head + (x > jnp.float32(h)).astype(jnp.int32)
    HhM = jnp.float32(Hh[-1])
    c_half = jnp.float32(0.5 * consts["M"] ** -consts["theta"])
    c_d12 = jnp.float32((consts["theta"] / 12.0)
                        * consts["M"] ** (-consts["theta"] - 1.0))
    Mom = jnp.float32(consts["M"] ** consts["om"])
    B0 = jnp.float32(consts["B0"])

    def invert(xt):
        """Solve H(r) = xt for r >= head: closed-form init (small E-M
        terms dropped) + two Newton steps (H'(r) = r^-theta)."""
        r = jnp.exp(jnp.log(om * (xt - B0)) / om)
        for _ in range(2):
            r = jnp.maximum(r, Mf)
            rmt = jnp.exp(-theta * jnp.log(r))         # r^-theta
            Hr = (HhM + (r * rmt - Mom) / om + 0.5 * rmt - c_half
                  - (theta / jnp.float32(12.0)) * (rmt / r) + c_d12)
            r = r - (Hr - xt) / rmt
        return jnp.maximum(r, Mf)

    # tail: u has 24 bits, so ~4 M draws collide heavily on quantile
    # cells (2^24 cells); recover the lost entropy EXACTLY like the
    # quantile table does — invert at BOTH edges of the 2^-24-wide cell
    # and lerp on w[1] (a virtual [2^24]-bin table, piecewise-linear in
    # the locally flat tail)
    du = jnp.float32(2.0 ** -24) * jnp.float32(consts["Hn"])
    xt = jnp.maximum(x, HhM)
    r_lo = invert(xt)
    r_hi = invert(xt + du)
    v = (w[1] >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)
    rank_tail = (r_lo + (r_hi - r_lo) * v).astype(jnp.int32)
    rank = jnp.where(rank_head < jnp.int32(len(Hh)), rank_head, rank_tail)
    return jnp.clip(rank, 0, n_keys - 1)


def _gen_ranks(tpair, w, *, log2_bins: int, n_keys: int):
    """Zipf ranks from two uint32 PRNG words per sample: bin from the
    top ``log2_bins`` bits (CDF-exact edges), f32 lerp within the bin on
    24 fresh bits.  ``tpair`` is the [nb, 2] edge-pair table — one
    random gather per sample, not two (random HBM access is the
    dominant prep cost on chip — ~15 ns/row)."""
    import jax.numpy as jnp

    bin_ = (w[0] >> (32 - log2_bins)).astype(jnp.int32)
    t2 = tpair[bin_]                     # [batch, 2]
    lo_r, hi_r = t2[:, 0], t2[:, 1]
    frac = (w[1] >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)
    rank = lo_r + ((hi_r - lo_r).astype(jnp.float32)
                   * frac).astype(jnp.int32)
    return jnp.clip(rank, 0, n_keys - 1)


def _keys_of_ranks(rank, salt_hi, salt_lo):
    """The synthetic rank->key map, bit-for-bit the native one:
    ``mix64(rank ^ salt)`` on (hi, lo) uint32 pairs.  Ranks < 2^31, so
    the high word of ``rank ^ salt`` is salt's high word."""
    import jax.numpy as jnp
    from jax import lax

    xlo = lax.bitcast_convert_type(rank, jnp.uint32) ^ salt_lo
    xhi = jnp.full(rank.shape, salt_hi, jnp.uint32)
    return bits.mix64_pair(xhi, xlo)


def _sort_combine(khi, klo, cap):
    """Sort-based request combining: clients served in key-sorted order
    (no index payload, no inverse-permutation scatter).  Returns the
    sorted client keys, the unique rows compacted to ``cap``, the
    client->row segment map, and the unique count.

    The unique set is compacted with a flag-sort: plain 3-key sort, NOT
    ``is_stable=True`` — the composite (flag, khi, klo) is already a
    total order on the rows that matter (first rows have distinct
    keys), and the stable-sort path measured ~12x slower on chip.
    Sorts are ~4x cheaper than the equivalent scatters on chip."""
    import jax.numpy as jnp
    from jax import lax

    skhi, sklo = lax.sort((khi, klo), num_keys=2)
    first = jnp.concatenate([
        jnp.ones((1,), jnp.uint32),
        ((skhi[1:] != skhi[:-1])
         | (sklo[1:] != sklo[:-1])).astype(jnp.uint32)])
    seg = (jnp.cumsum(first) - 1).astype(jnp.int32)
    n_uniq = seg[-1] + 1
    _, ckhi, cklo = lax.sort((jnp.uint32(1) - first, skhi, sklo),
                             num_keys=3)
    return skhi, sklo, ckhi[:cap], cklo[:cap], seg, n_uniq


def _router_probe(rtable, ukhi, uklo, shift, nb):
    """Index-cache probe: bucket = min(key >> shift, nb - 1), one
    gather from the router table."""
    import jax.numpy as jnp

    bhi, blo = bits.u64_shr(ukhi, uklo, shift)
    bucket = jnp.where(bhi != 0, jnp.uint32(nb - 1),
                       jnp.minimum(blo, jnp.uint32(nb - 1)))
    return rtable[bucket.astype(jnp.int32)]


def _rep_put(dsm, x):
    """Host value -> device-resident REPLICATED array, multihost-aware:
    single-process meshes use a plain ``device_put``; process-spanning
    meshes build the global replicated array from every process's
    identical local copy (the engine's ``_shard`` idiom with an empty
    partition spec)."""
    import jax

    x = np.asarray(x)
    if getattr(dsm, "multihost", False):
        from jax.experimental import multihost_utils as mhu
        return mhu.host_local_array_to_global_array(
            x, dsm.mesh, jax.sharding.PartitionSpec())
    return jax.device_put(x)


def _stage_inputs(dsm, router, n_keys: int, theta: float, log2_bins: int,
                  seed: int, sampler: str = "table"):
    """Stage the step's device-resident inputs once, before any timed
    region: the [nb, 2] zipf edge-pair table (a tiny dummy when the
    analytic sampler needs no table), the router table, and the PRNG
    key.  All replicated (multihost-aware via :func:`_rep_put`)."""
    import jax

    if sampler == "analytic":
        table = np.zeros((1, 2), np.int32)
    else:
        t = zipf_table(n_keys, theta, log2_bins)
        table = np.stack([t[:-1], t[1:]], axis=1)
    with router._read_locked():
        rtable = np.array(router.table_np)
    rkey = np.asarray(jax.random.PRNGKey(seed))
    return (_rep_put(dsm, table), _rep_put(dsm, rtable),
            _rep_put(dsm, rkey))


def _delta_ms(loop, reps: int) -> float:
    """Chained-delta phase timing: run ``loop(K)`` and ``loop(2K)``
    (each a chain of data-dependent dispatches ending in a drain) and
    return ``(t_2K - t_K) / K`` in ms — the methodology of
    tools/profile_insert.py, which cancels the per-call dispatch + sync
    overhead exactly (a per-call timing through a remote access tunnel
    measures the tunnel, not the program)."""
    import time

    loop(1)  # warm: compile + remote program load stay out of the delta
    t0 = time.perf_counter()
    loop(reps)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    loop(2 * reps)
    t2 = time.perf_counter() - t0
    return max(0.0, (t2 - t1) / reps * 1e3)


def overlap_receipt(prep_ms: float, serve_ms: float, verify_ms: float,
                    wall_ms: float) -> dict:
    """The round-8 OVERLAP RECEIPT, computed in exactly one place (the
    read-only and mixed pipelined phase profiles and the
    profile_staged2 mode table all publish it): ``bubble_ms`` = wall −
    serve (the work NOT hidden behind the serve bound) and
    ``overlap_efficiency`` = 1 − wall/(prep+serve+verify) (0 = fully
    serial dispatch, (prep+verify)/sum = perfect hiding)."""
    serial = prep_ms + serve_ms + verify_ms
    return {
        "wall_ms": wall_ms,
        "bubble_ms": max(0.0, wall_ms - serve_ms),
        "overlap_efficiency": (1.0 - wall_ms / serial
                               if serial > 0 else 0.0),
    }


def record_phase_obs(prefix: str, phases: dict) -> None:
    """Route one phase/overlap dict into obs — the SINGLE copy of the
    routing every publisher (bench read-only + mixed, profile_staged2)
    shares: ``overlap_efficiency`` is a ratio and lands in a gauge;
    every wall cost lands in a ``<prefix>.<name>_ms`` histogram
    (``wall_ms``/``bubble_ms`` already carry the unit)."""
    from sherman_tpu import obs

    for name, v in phases.items():
        if name == "overlap_efficiency":
            obs.gauge(f"{prefix}.overlap_efficiency").set(v)
        else:
            h = name if name.endswith("_ms") else f"{name}_ms"
            obs.histogram(f"{prefix}.{h}").record(v)


def _two_deep_slot(jverify):
    """The pipelined modes' pending-slot protocol, in ONE copy shared
    by the read-only and mixed steps (the slot tuple contents and the
    verify program differ; the stateful contract must not): ``fold``
    folds a pending batch's verify inputs (if any) into the receipts,
    ``put`` parks batch k's, ``drain`` flushes the slot so the carry
    is bit-identical to the sequential mode's, ``reset`` clears it
    without folding (``new_carry()`` — a fresh receipts stream must
    not fold a stale batch left by an undrained previous run)."""
    pend = {"slot": None}

    def fold(rcarry):
        if pend["slot"] is not None:
            rcarry = jverify(rcarry, *pend["slot"])
        return rcarry

    def put(*slot):
        pend["slot"] = slot

    def drain(carry):
        step_idx, *rcarry = carry
        rcarry = tuple(rcarry)
        if pend["slot"] is not None:
            rcarry = jverify(rcarry, *pend["slot"])
            pend["slot"] = None
        return (step_idx,) + rcarry

    def reset():
        pend["slot"] = None

    return fold, put, drain, reset


def _rank_sampler(sampler: str, n_keys: int, theta: float,
                  log2_bins: int):
    """-> (rank(tpair, w), effective_name) for the chosen sampler.
    ``analytic`` (no HBM table gather) requires 0 < theta < 1 AND a
    keyspace larger than its exact head; BOTH out-of-range cases fall
    back to the quantile table (uniformly — never a crash on one and a
    silent fallback on the other), and the effective name is returned
    so drivers can log which sampler actually ran."""
    if sampler == "analytic" and 0.0 < theta < 1.0 and n_keys > 64:
        zc = zipf_analytic_consts(n_keys, theta)
        return (lambda tpair, w: _gen_ranks_analytic(zc, w,
                                                     n_keys=n_keys),
                "analytic")
    return (lambda tpair, w: _gen_ranks(tpair, w, log2_bins=log2_bins,
                                        n_keys=n_keys), "table")


def make_staged_step(eng, *, n_keys: int, theta: float, salt: int,
                     batch: int, dev_b: int, log2_bins: int = 20,
                     check_xor: int = 0xDEADBEEF, seed: int = 11,
                     staged=None, sampler: str = "table",
                     fusion: str | None = None, leaf_cache=None,
                     dev_b_resid: int | None = None):
    """Build the device-staged serving step for ``eng`` (a
    :class:`~sherman_tpu.models.batched.BatchedEngine` with an attached
    router).

    Returns ``(step, state)`` where ``state = (new_carry, table_d,
    rtable_d, rkey_d)``: ``new_carry()`` makes a fresh device-resident
    carry, the rest are device-resident inputs staged once, before any
    timed region.  Then

        ``counters, carry = step(pool, counters, table_d, rtable_d,
                                 rkey_d, carry)``

    runs ONE step: generate ``batch`` zipf client keys per node from the
    carry's step counter, combine to <= ``dev_b`` unique rows, probe the
    router, descend, fan out every answer in-step, and fold the
    verification receipts into the carry.  Carry fields (all replicated
    int32/uint32 scalars):

        (step_idx, ok, n_correct, sum_nuniq, max_nuniq)

    ``ok`` goes 0 if any step's unique count overflowed ``dev_b`` (its
    rows would be dropped, so the step's receipts are void);
    ``n_correct`` counts client ops whose value matched
    ``key ^ check_xor`` — after S steps it must equal
    ``S * batch * machine_nr``.  ``sum_nuniq`` accumulates per-node
    unique counts (psum across nodes) for combine-ratio reporting.

    ``fusion`` picks the compiled-program structure (default
    :func:`sherman_tpu.config.staged_fusion`, overridable via the
    ``SHERMAN_STAGED_FUSION`` env var):

    - ``"aligned"`` (default): THREE chained programs ``prep -> serve
      -> verify`` where the serve IS the engine's combined-search
      fan-out program (``BatchedEngine._get_search_fanout``) — the
      byte-identical compiled executable the host-staged throughput
      phase runs.  This forces the staged serve's input layouts,
      donation and HLO to match the host-staged case by construction,
      eliminating the cross-program layout / shard_map-fusion suspects
      of BENCHMARKS.md round-5 "known headroom"; the receipts
      arithmetic moves to its own elementwise ``verify`` program.
    - ``"pipelined"``: the SAME three compiled programs as ``aligned``
      (the serve is the same ``_get_search_fanout`` program OBJECT, so
      the CI program-identity pin extends to this mode), dispatched as
      a TWO-DEEP software pipeline: call k first folds batch k-1's
      already-materialized serve outputs through ``verify`` (consuming
      the pending slot), then dispatches ``prep`` for batch k into the
      slot the verify just released, then the serve — so while the
      device serves batch k-1, the host has already queued batch k's
      prep and batch k-2's verify, and a backend that overlaps
      independent programs hides the prep + verify walls behind the
      serve.  Double-buffered: at most TWO batches' staging arrays are
      alive (the in-flight prep outputs and the pending verify
      inputs); no extra pool or batch copies are materialized, and
      donation stays exactly the serve program's own
      (:func:`sherman_tpu.config.donate_argnums`-gated).  Receipts lag
      one batch in the returned carry; ``step.drain(carry)`` flushes
      the pending verify, after which the carry is BIT-IDENTICAL to S
      ``aligned`` steps' (same programs, same fold order).  A fresh
      ``new_carry()`` also resets the pipeline (a fresh receipts
      stream must not fold a stale pending batch).  CONTRACT: the
      pending slot lives on the STEP object, so one pipelined step
      drives ONE carry stream at a time — interleaving two carries
      through the same step folds one stream's pending batch into the
      other's receipts; build a second step (``staged=`` reuses the
      resident tables) for a second stream.
    - ``"chained"``: the round-5 two-program form (``prep -> serve``
      with fan-out + verification fused into the serve program), kept
      for continuity and A/B measurement against ``aligned``.
    - ``"fused"``: ONE jitted program.  On TPU, XLA compiles the prep
      pipeline fused into the serve's straggler while-loop ~50-100x
      slower than the sum of its parts (measured 6.8-10.3 s fused vs
      56 + 63 ms split on chip; ``optimization_barrier`` does not fix
      it), so this form exists for CPU-mesh regression tests — a single
      program PROVES no host round trip can hide between generation and
      serve — and for re-testing the pathology on new toolchains.

    ``leaf_cache`` (optional; aligned/pipelined only): an attached
    :class:`~sherman_tpu.models.leaf_cache.LeafCache` — a fourth
    compiled program ``cache_probe`` (fixed table shapes, so the sealed
    loop stays zero-retrace) runs between prep and serve: pool-validated
    hot-key hits leave the unique batch, the probe COMPACTS the misses
    into a ``dev_b_resid``-wide residual (descent cost is per ROW of
    the compiled shape, so deactivating rows saves nothing — shrinking
    the shape is the whole win), the serve descends only that residual,
    and the verify program merges the cache answers back per client row
    before the receipts arithmetic — so the drained receipts are
    BIT-IDENTICAL to the uncached loop's, with two extra carry scalars
    appended: ``sum_hits`` (client ops served from cache — the measured
    hit ratio's numerator) and ``sum_hits_uniq`` (unique rows removed
    from the serve — the residual-batch receipt).  ``dev_b_resid``
    (default ``dev_b`` — no shrink) caps the per-node residual; a step
    whose misses overflow it voids the phase through the ``ok``
    receipt, the SAME contract as the ``dev_b`` unique cap (drivers
    size it from a warmup step's measured residual, the mixed loop's
    cap-tightening dance).  The cache's device tables are staged ONCE
    (read-only sealed window: in-window stale entries just keep
    missing, validation stays authoritative); ``step.phase_labels``
    and the compile ledger carry the ``cache_probe`` label so the
    probe's cost is attributable.

    In every mode the dispatched programs are chained back-to-back with
    no host work or transfer between them (the multi-program forms pass
    device-resident arrays only).  ``counters`` is donated; the rcarry
    scalars are deliberately NOT donated — callers block their dispatch
    window on ``carry[1]`` (the LAST program's output; see bench.py
    ``run_windowed``), which must stay a live buffer after the next
    step consumes it.  Step attributes: ``step.fusion``,
    ``step.sampler``, ``step.programs`` (name -> jitted program in
    dispatch order), ``step.n_programs``, ``step.phase_profile``
    (chained-delta per-phase wall costs; in ``pipelined`` mode the
    dict also carries the OVERLAP RECEIPT — ``wall_ms`` the drained
    pipelined wall per step, ``bubble_ms`` = wall − serve, the host
    work not hidden behind the serve bound, and
    ``overlap_efficiency`` = 1 − wall/(prep + serve + verify), 0 =
    fully serial), ``step.drain`` (flush the pending verify; identity
    for non-pipelined modes), ``step.pipeline_depth`` (2 for
    ``pipelined``, else 1), plus per-mode handles (``step.jprep`` /
    ``step.jserve`` / ``step.jverify`` / ``step.jfused``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from sherman_tpu.models.batched import AXIS, search_routed_spmd
    from sherman_tpu.parallel import transport

    fusion = fusion or C.staged_fusion()
    if fusion not in ("aligned", "pipelined", "chained", "fused"):
        raise ConfigError(
            f"fusion={fusion!r}: want aligned|pipelined|chained|fused")
    use_cache = leaf_cache is not None
    if use_cache and fusion not in ("aligned", "pipelined"):
        raise ConfigError(
            f"leaf_cache requires fusion aligned|pipelined (got "
            f"{fusion!r}): the probe is its own chained program")
    router = eng.router
    assert router is not None, "attach_router() first"
    cfg = eng.cfg
    dsm = eng.dsm
    N = cfg.machine_nr
    iters = eng._iters()
    spec, rep = eng._spec, eng._rep
    shift, nb = int(router.shift), int(router.nb)
    LB = int(log2_bins)
    gen_ranks, sampler = _rank_sampler(sampler, n_keys, theta, LB)
    root = np.int32(eng.tree._root_addr)
    salt_hi = np.uint32((salt >> 32) & 0xFFFFFFFF)
    salt_lo = np.uint32(salt & 0xFFFFFFFF)
    cx_hi = np.uint32((check_xor >> 32) & 0xFFFFFFFF)
    cx_lo = np.uint32(check_xor & 0xFFFFFFFF)
    i32 = lambda x: lax.bitcast_convert_type(x, jnp.int32)

    assert batch >= dev_b, "dev_b is the unique-set cap; cannot exceed batch"

    def prep_core(tpair, rtable, rkey, step_idx):
        # per-node, per-step independent stream (counter-based PRNG):
        # fold the step counter and the node index into the key
        node = lax.axis_index(AXIS) if N > 1 else jnp.uint32(0)
        k = jax.random.fold_in(rkey, step_idx * np.uint32(N)
                               + node.astype(jnp.uint32))
        w = jax.random.bits(k, (2, batch), dtype=jnp.uint32)
        rank = gen_ranks(tpair, w)
        khi_u, klo_u = _keys_of_ranks(rank, salt_hi, salt_lo)
        # sort-based unique (request combining): clients are served in
        # key-sorted order (see module docstring), so no index payload
        # and no inverse-permutation scatter are needed
        skhi, sklo, ukhi, uklo, seg, n_uniq = _sort_combine(
            khi_u, klo_u, dev_b)
        active = lax.iota(jnp.int32, dev_b) < n_uniq
        start = _router_probe(rtable, ukhi, uklo, shift, nb)
        return skhi, sklo, ukhi, uklo, start, active, seg, n_uniq

    def serve_fanout(pool, counters, ukhi, uklo, start, active, seg):
        """chained/fused serve body: routed descent + the monotone
        per-client answer fan-out (seg is NONDECREASING, so the gather
        is sequential in HBM, unlike an inverse-permuted one).  GLOBAL
        indices on multi-node meshes: the answer table all-gathers
        tiled, node n's rows at [n*dev_b, (n+1)*dev_b)."""
        counters, done, found, vhi, vlo = search_routed_spmd(
            pool, counters, i32(ukhi), i32(uklo), root, active, start,
            cfg=cfg, iters=iters)
        ans = jnp.stack([found.astype(jnp.int32), vhi, vlo,
                         jnp.zeros_like(vhi)], axis=-1)     # [U_loc, 4]
        if N > 1:
            node = lax.axis_index(AXIS)
            ans = transport.gather_rows(ans, AXIS)
            seg = seg + node.astype(jnp.int32) * dev_b
        safe = jnp.clip(seg, 0, ans.shape[0] - 1)
        out = jnp.take_along_axis(ans, safe[:, None], axis=0)
        return counters, out[:, 0] != 0, out[:, 1], out[:, 2]

    def verify_core(rcarry, skhi, sklo, found, vhi, vlo, n_uniq):
        """Receipts: every (sorted-order) client answer must equal its
        key ^ check_xor; the scalar carries psum across the mesh."""
        ok, n_correct, sum_nu, max_nu = rcarry
        exp_hi = i32(skhi ^ cx_hi)
        exp_lo = i32(sklo ^ cx_lo)
        corr = found & (vhi == exp_hi) & (vlo == exp_lo)
        inc_corr = jnp.sum(corr.astype(jnp.int32))
        step_ok = (n_uniq <= dev_b).astype(jnp.int32)
        if N > 1:
            inc_corr = lax.psum(inc_corr, AXIS)
            sum_inc = lax.psum(n_uniq, AXIS)
            max_inc = lax.pmax(n_uniq, AXIS)
            step_ok = lax.pmin(step_ok, AXIS)
        else:
            sum_inc, max_inc = n_uniq, n_uniq
        return (jnp.minimum(ok, step_ok), n_correct + inc_corr,
                sum_nu + sum_inc, jnp.maximum(max_nu, max_inc))

    mesh = dsm.mesh
    root_rep = None
    _pipe_reset = None  # pipelined mode installs its slot reset here

    if fusion in ("aligned", "pipelined"):
        def prep(tpair, rtable, rkey, step_idx):
            skhi, sklo, ukhi, uklo, start, active, seg, n_uniq = \
                prep_core(tpair, rtable, rkey, step_idx)
            if N > 1:
                # the engine fan-out kernel takes GLOBAL unique indices
                node = lax.axis_index(AXIS)
                seg = seg + node.astype(jnp.int32) * dev_b
            # keys bitcast to int32 IN PREP: the serve consumes exactly
            # the dtypes/layouts the host-staged path ships
            return (step_idx + np.uint32(1), skhi, sklo, i32(ukhi),
                    i32(uklo), start, active, seg, n_uniq[None])

        # compile-ledger wraps (obs/device.py): the staged programs are
        # the serve path's white-box unit of account — a post-seal
        # compile on ANY of them is the silent-retrace hazard
        jprep = DEV.wrap_program("staged.prep", jax.jit(jax.shard_map(
            prep, mesh=mesh, in_specs=(rep, rep, rep, rep),
            out_specs=(rep,) + (spec,) * 8, check_vma=False)))
        # the serve is the ENGINE's host-staged program object: same jit
        # cache entry, same donation, same HLO as the throughput phase
        # (already ledger-wrapped at the engine cache site — wrap() is
        # idempotent, so the identity pin keeps holding)
        jserve = eng._get_search_fanout(iters)

        jcache = cache_tables = None
        R_resid = int(dev_b_resid) if dev_b_resid else dev_b
        if use_cache:
            from sherman_tpu.models.leaf_cache import probe_rows
            assert 0 < R_resid <= dev_b, \
                "dev_b_resid caps the residual within the unique cap"
            cache_tables = leaf_cache.device_tables()

            def cache_probe(pool, tkhi, tklo, tvhi, tvlo, tver, taddr,
                            tslot, khi, klo, active, start, inv):
                tbl = {"khi": tkhi, "klo": tklo, "vhi": tvhi,
                       "vlo": tvlo, "ver": tver, "addr": taddr,
                       "slot": tslot}
                hit, cvhi, cvlo, _, _ = probe_rows(
                    pool, tbl, khi, klo, active, cfg=cfg)
                # read-only sealed window: no device-side slot
                # invalidation here (the table arrays are staged
                # constants) — a stale entry keeps missing and the pool
                # validation stays the authoritative guard
                resid = active & ~hit
                n_resid = jnp.sum(resid.astype(jnp.int32))
                # compact the misses to the [R_resid] residual the
                # serve actually descends; overflowing rows drop and
                # VOID the step via the ok receipt (n_resid check in
                # verify), never silently mis-serve
                sidx = jnp.nonzero(resid, size=R_resid,
                                   fill_value=dev_b)[0].astype(jnp.int32)
                valid = sidx < dev_b
                ci = jnp.clip(sidx, 0, dev_b - 1)
                # remap client fan-out indices onto the residual rows;
                # hit clients land on row 0 (their garbage fan-out is
                # overwritten by the verify merge)
                remap = jnp.zeros(dev_b + 1, jnp.int32).at[
                    jnp.where(valid, sidx, dev_b)].set(
                    jnp.arange(R_resid, dtype=jnp.int32), mode="drop")
                if N > 1:
                    node = lax.axis_index(AXIS).astype(jnp.int32)
                    loc = jnp.clip(inv - node * dev_b, 0, dev_b)
                    inv_r = remap[loc] + node * R_resid
                else:
                    inv_r = remap[jnp.clip(inv, 0, dev_b)]
                return (hit, cvhi, cvlo, khi[ci], klo[ci], start[ci],
                        valid, inv_r, n_resid[None])

            jcache = DEV.wrap_program(
                "staged.cache_probe", jax.jit(jax.shard_map(
                    cache_probe, mesh=mesh,
                    in_specs=(spec,) + (rep,) * 7 + (spec,) * 5,
                    out_specs=(spec,) * 9, check_vma=False)))

        if not use_cache:
            def verify(rcarry, skhi, sklo, found, vhi, vlo, n_uniq_a):
                return verify_core(rcarry, skhi, sklo, found, vhi, vlo,
                                   n_uniq_a[0])

            jverify = DEV.wrap_program(
                "staged.verify", jax.jit(jax.shard_map(
                    verify, mesh=mesh,
                    in_specs=((rep,) * 4, spec, spec, spec, spec, spec,
                              spec),
                    out_specs=(rep,) * 4, check_vma=False)))
        else:
            def verify(rcarry, skhi, sklo, found, vhi, vlo, n_uniq_a,
                       seg, hit, cvhi, cvlo, n_resid_a):
                """Cache-aware receipts: merge the cache answers back
                per client row (the hit rows' serve outputs fanned out
                residual row 0), then run the SAME receipts arithmetic
                — plus the two hit accumulators and the residual-
                overflow void (the dev_b_resid twin of the unique cap's
                ok receipt)."""
                (ok, n_correct, sum_nu, max_nu, hits_c,
                 hits_u) = rcarry
                ctab = jnp.stack([hit.astype(jnp.int32), cvhi, cvlo,
                                  jnp.zeros_like(cvhi)], axis=-1)
                if N > 1:
                    ctab = transport.gather_rows(ctab, AXIS)
                safe = jnp.clip(seg, 0, ctab.shape[0] - 1)
                cout = jnp.take_along_axis(ctab, safe[:, None], axis=0)
                chit = cout[:, 0] != 0
                inc_hc = jnp.sum(chit.astype(jnp.int32))
                inc_hu = jnp.sum(hit.astype(jnp.int32))
                rok = (n_resid_a[0] <= R_resid).astype(jnp.int32)
                if N > 1:
                    inc_hc = lax.psum(inc_hc, AXIS)
                    inc_hu = lax.psum(inc_hu, AXIS)
                    rok = lax.pmin(rok, AXIS)
                base = verify_core(
                    (ok, n_correct, sum_nu, max_nu), skhi, sklo,
                    found | chit, jnp.where(chit, cout[:, 1], vhi),
                    jnp.where(chit, cout[:, 2], vlo), n_uniq_a[0])
                return ((jnp.minimum(base[0], rok),) + base[1:]
                        + (hits_c + inc_hc, hits_u + inc_hu))

            jverify = DEV.wrap_program(
                "staged.verify", jax.jit(jax.shard_map(
                    verify, mesh=mesh,
                    in_specs=((rep,) * 6,) + (spec,) * 11,
                    out_specs=(rep,) * 6, check_vma=False)))
        root_rep = _rep_put(dsm, root)

        if fusion == "aligned":
            def step(pool, counters, tpair, rtable, rkey, carry):
                step_idx, *rcarry = carry
                (step_idx, skhi, sklo, khi, klo, start, active, inv,
                 nu) = jprep(tpair, rtable, rkey, step_idx)
                if use_cache:
                    # hot-key probe: validated hits leave the batch and
                    # the misses compact into the [dev_b_resid]
                    # residual the serve descends
                    (hit, cvhi, cvlo, khi, klo, start, active, inv_s,
                     nr) = jcache(pool, *cache_tables, khi, klo,
                                  active, start, inv)
                else:
                    inv_s = inv
                counters, done, found, vhi, vlo = jserve(
                    pool, counters, khi, klo, root_rep, active, start,
                    inv_s)
                if use_cache:
                    rcarry = jverify(tuple(rcarry), skhi, sklo, found,
                                     vhi, vlo, nu, inv, hit, cvhi,
                                     cvlo, nr)
                else:
                    rcarry = jverify(tuple(rcarry), skhi, sklo, found,
                                     vhi, vlo, nu)
                return counters, (step_idx,) + tuple(rcarry)
        else:  # pipelined: two-deep software pipeline, same 3 programs
            # the pending slot (:func:`_two_deep_slot`): batch k-1's
            # verify inputs — device handles only, the serve outputs
            # are already materializing when the slot is consumed.
            # After S steps + drain the carry is bit-identical to S
            # aligned steps'.
            _fold, _put, _drain, _pipe_reset = _two_deep_slot(jverify)

            def step(pool, counters, tpair, rtable, rkey, carry):
                step_idx, *rcarry = carry
                # 1. consume batch k-1: fold its answers into the
                #    receipts — off the serve(k-1) -> serve(k) path
                rcarry = _fold(tuple(rcarry))
                # 2. prep batch k into the slot verify just released
                #    (independent of the in-flight serve: a backend
                #    that overlaps programs runs it behind the serve)
                (step_idx, skhi, sklo, khi, klo, start, active, inv,
                 nu) = jprep(tpair, rtable, rkey, step_idx)
                if use_cache:
                    (hit, cvhi, cvlo, khi, klo, start, active, inv_s,
                     nr) = jcache(pool, *cache_tables, khi, klo,
                                  active, start, inv)
                else:
                    inv_s = inv
                # 3. serve batch k — the SAME compiled program object
                #    aligned (and the host-staged phase) dispatches
                counters, done, found, vhi, vlo = jserve(
                    pool, counters, khi, klo, root_rep, active, start,
                    inv_s)
                if use_cache:
                    _put(skhi, sklo, found, vhi, vlo, nu, inv, hit,
                         cvhi, cvlo, nr)
                else:
                    _put(skhi, sklo, found, vhi, vlo, nu)
                return counters, (step_idx,) + rcarry

            step.drain = _drain

        step.jprep, step.jserve, step.jverify = jprep, jserve, jverify
        programs = {"prep": jprep, "serve_fanout": jserve,
                    "verify": jverify}
        if use_cache:
            step.jcache = jcache
            # dispatch order: prep -> cache_probe -> serve -> verify
            programs = {"prep": jprep, "cache_probe": jcache,
                        "serve_fanout": jserve, "verify": jverify}

    elif fusion == "chained":
        def prep(tpair, rtable, rkey, step_idx):
            skhi, sklo, ukhi, uklo, start, active, seg, n_uniq = \
                prep_core(tpair, rtable, rkey, step_idx)
            # n_uniq ships as [1] so it shards per node like the rest
            return (step_idx + np.uint32(1), skhi, sklo, ukhi, uklo,
                    start, active, seg, n_uniq[None])

        jprep = DEV.wrap_program("staged.prep", jax.jit(jax.shard_map(
            prep, mesh=mesh, in_specs=(rep, rep, rep, rep),
            out_specs=(rep,) + (spec,) * 8, check_vma=False)))

        def serve(pool, counters, rcarry, skhi, sklo, ukhi, uklo, start,
                  active, seg, n_uniq_a):
            counters, found, vhi, vlo = serve_fanout(
                pool, counters, ukhi, uklo, start, active, seg)
            rcarry = verify_core(rcarry, skhi, sklo, found, vhi, vlo,
                                 n_uniq_a[0])
            return counters, rcarry

        serve_sm = jax.shard_map(
            serve, mesh=mesh,
            in_specs=(spec, spec, (rep,) * 4) + (spec,) * 8,
            out_specs=(spec, (rep,) * 4), check_vma=False)
        # donate counters only: the prep intermediates' shapes cannot
        # alias any serve output (donating them just warns every
        # compile), and donating 4 replicated scalars saves nothing
        jserve = DEV.wrap_program(
            "staged.serve_fanout_verify",
            jax.jit(serve_sm, donate_argnums=C.donate_argnums(1)))

        def step(pool, counters, tpair, rtable, rkey, carry):
            step_idx, *rcarry = carry
            step_idx, *arrs = jprep(tpair, rtable, rkey, step_idx)
            counters, rcarry = jserve(pool, counters, tuple(rcarry),
                                      *arrs)
            return counters, (step_idx,) + tuple(rcarry)

        step.jprep, step.jserve = jprep, jserve
        programs = {"prep": jprep, "serve_fanout_verify": jserve}

    else:  # fused: one program, CPU regression / toolchain re-tests
        def fused(pool, counters, rcarry, tpair, rtable, rkey, step_idx):
            skhi, sklo, ukhi, uklo, start, active, seg, n_uniq = \
                prep_core(tpair, rtable, rkey, step_idx)
            counters, found, vhi, vlo = serve_fanout(
                pool, counters, ukhi, uklo, start, active, seg)
            rcarry = verify_core(rcarry, skhi, sklo, found, vhi, vlo,
                                 n_uniq)
            return step_idx + np.uint32(1), counters, rcarry

        fused_sm = jax.shard_map(
            fused, mesh=mesh,
            in_specs=(spec, spec, (rep,) * 4, rep, rep, rep, rep),
            out_specs=(rep, spec, (rep,) * 4), check_vma=False)
        jfused = DEV.wrap_program(
            "staged.fused_step",
            jax.jit(fused_sm, donate_argnums=C.donate_argnums(1)))

        def step(pool, counters, tpair, rtable, rkey, carry):
            step_idx, *rcarry = carry
            step_idx, counters, rcarry = jfused(
                pool, counters, tuple(rcarry), tpair, rtable, rkey,
                step_idx)
            return counters, (step_idx,) + tuple(rcarry)

        step.jfused = jfused
        programs = {"fused_step": jfused}

    step.fusion, step.sampler = fusion, sampler
    step.programs, step.n_programs = programs, len(programs)
    step.cache = use_cache
    step.cache_slots = leaf_cache.slots if use_cache else None
    step.dev_b_resid = R_resid if use_cache else None
    step.pipeline_depth = 2 if fusion == "pipelined" else 1
    if not hasattr(step, "drain"):
        step.drain = lambda carry: carry  # nothing pending off-pipeline
    # phase -> compile-ledger label, the join key the roofline receipts
    # use (obs/device.rooflines: phase_profile walls x cost_analysis
    # floors).  Overlap-receipt keys (wall_ms/bubble_ms/...) are
    # deliberately absent — they are not programs.
    step.phase_labels = {name: prog.label
                         for name, prog in programs.items()}

    # SLO accounting hook (obs/slo.py): the staged loop is an open read
    # loop of `batch` client ops per step; the driver attributes a whole
    # DRAINED window at once (per-batch wall = elapsed / n_steps — the
    # amortized per-op latency model), so the per-step dispatch path
    # carries ZERO extra obs work.
    step.slo_class = "read"

    def record_slo(n_steps: int, elapsed_s: float) -> None:
        from sherman_tpu.obs import slo as _slo
        _slo.observe("read", n_steps * batch, elapsed_s, batches=n_steps)

    step.record_slo = record_slo

    def new_carry():
        """Fresh device-resident carry.  Also resets the pipelined
        mode's pending slot: a fresh receipts stream must not fold a
        stale batch left by an undrained previous run.  With the leaf
        cache on, two hit accumulators (sum_hits, sum_hits_uniq) ride
        at the END so every base field keeps its index."""
        if _pipe_reset is not None:
            _pipe_reset()
        vals = [np.uint32(0), np.int32(1), np.int32(0), np.int32(0),
                np.int32(0)]
        if use_cache:
            vals += [np.int32(0), np.int32(0)]
        return tuple(_rep_put(dsm, v) for v in vals)

    def phase_profile(pool, counters, tpair, rtable, rkey, reps: int = 4):
        """Per-phase wall-cost attribution of the staged step: each
        dispatched program runs K and 2K CHAINED repetitions (data-
        dependent carries) and costs ``(t_2K - t_K)/K``
        (:func:`_delta_ms` — cancels per-call dispatch/sync overhead,
        so the numbers are honest through a remote access tunnel).
        Read-only: safe to run mid-benchmark.  NOTE the per-phase sum
        can exceed the pipelined ms/step — the pipelined loop overlaps
        prep with serve; attribution measures each program standalone.
        Returns ``({phase: ms}, counters)`` with the threaded counters
        handle (the serve donates its input counters)."""
        box = {"c": counters}
        out = {}
        if fusion == "fused":
            rc0 = new_carry()

            def floop(k):
                si, rc = rc0[0], tuple(rc0[1:])
                for _ in range(k):
                    si, box["c"], rc = jfused(pool, box["c"], rc, tpair,
                                              rtable, rkey, si)
                jax.block_until_ready(rc)

            out["fused_step"] = _delta_ms(floop, reps)
            return out, box["c"]

        def prep_loop(k):
            si, o = new_carry()[0], None
            for _ in range(k):
                o = jprep(tpair, rtable, rkey, si)
                si = o[0]
            jax.block_until_ready(o)

        out["prep"] = _delta_ms(prep_loop, reps)
        arrs = jprep(tpair, rtable, rkey, new_carry()[0])[1:]
        jax.block_until_ready(arrs)
        if fusion in ("aligned", "pipelined"):
            skhi, sklo, khi, klo, start, active, inv, nu = arrs
            inv_s = inv
            hit = cvhi = cvlo = nr = None
            if use_cache:
                def cache_loop(k):
                    o = None
                    for _ in range(k):
                        o = jcache(pool, *cache_tables, khi, klo,
                                   active, start, inv)
                    jax.block_until_ready(o)

                out["cache_probe"] = _delta_ms(cache_loop, reps)
                # the serve measures the COMPACTED residual — the
                # width the live cache-on loop actually descends
                (hit, cvhi, cvlo, khi, klo, start, active, inv_s,
                 nr) = jcache(pool, *cache_tables, khi, klo, active,
                              start, inv)

            def serve_loop(k):
                o = None
                for _ in range(k):
                    box["c"], done, f, vh, vl = jserve(
                        pool, box["c"], khi, klo, root_rep, active,
                        start, inv_s)
                    o = f
                jax.block_until_ready(o)

            out["serve_fanout"] = _delta_ms(serve_loop, reps)
            box["c"], done, f, vh, vl = jserve(
                pool, box["c"], khi, klo, root_rep, active, start,
                inv_s)

            def verify_loop(k):
                rc = tuple(new_carry()[1:])
                for _ in range(k):
                    if use_cache:
                        rc = jverify(rc, skhi, sklo, f, vh, vl, nu,
                                     inv, hit, cvhi, cvlo, nr)
                    else:
                        rc = jverify(rc, skhi, sklo, f, vh, vl, nu)
                jax.block_until_ready(rc)

            out["verify"] = _delta_ms(verify_loop, reps)
            if fusion == "pipelined":
                # OVERLAP RECEIPT (:func:`overlap_receipt`): the
                # drained pipelined wall per step (same chained-delta
                # method) against the serial sum of the standalone
                # phase walls just measured.  The cache probe sits on
                # the prep side of the serve bound (it must finish
                # before the serve's active mask exists), so its wall
                # folds into the prep term.
                def pipe_loop(k):
                    c = new_carry()
                    for _ in range(k):
                        box["c"], c = step(pool, box["c"], tpair,
                                           rtable, rkey, c)
                    c = step.drain(c)
                    jax.block_until_ready(c)

                # warm both carry variants (fresh new_carry() inputs
                # vs threaded program outputs are distinct jit cache
                # entries) so no trace lands inside the delta
                pipe_loop(2)
                out.update(overlap_receipt(
                    out["prep"] + out.get("cache_probe", 0.0),
                    out["serve_fanout"], out["verify"],
                    _delta_ms(pipe_loop, reps)))
        else:  # chained

            def sv_loop(k):
                rc = tuple(new_carry()[1:])
                for _ in range(k):
                    box["c"], rc = jserve(pool, box["c"], rc, *arrs)
                jax.block_until_ready(rc)

            out["serve_fanout_verify"] = _delta_ms(sv_loop, reps)
        return out, box["c"]

    step.phase_profile = phase_profile

    table_d, rtable_d, rkey_d = staged or _stage_inputs(
        dsm, router, n_keys, theta, LB, seed, sampler)
    return step, (new_carry, table_d, rtable_d, rkey_d)


def make_staged_mixed_step(eng, *, n_keys: int, theta: float, salt: int,
                           batch: int, read_ratio: float, dev_rb: int,
                           dev_wb: int, log2_bins: int = 20,
                           check_xor: int = 0xDEADBEEF, seed: int = 13,
                           staged=None, sampler: str = "table",
                           fusion: str | None = None):
    """Device-staged sustained MIXED loop (YCSB-A/B shape): the same
    nothing-shipped open loop as :func:`make_staged_step`, but each step
    carries both point lookups and in-place updates through ONE fused
    ``mixed_step_spmd`` descent (reads see the pre-step snapshot, writes
    apply at the step boundary — reference parity:
    ``test/benchmark.cpp:159-188`` with ``kReadRatio < 100``).

    Client layout per node per step: ``R = round(batch * read_ratio)``
    read clients then ``batch - R`` write clients (roles fixed by slot;
    keys are iid zipf draws, so a fixed per-step count is the
    hypergeometric twin of the reference's per-op biased coin — same
    marginal mix, no dynamic shapes).  Each class is combined
    independently by the sort/flag-sort pipeline and served from the
    ``[reads | writes]`` row block the engine's half-width apply expects
    (``mixed_step_spmd`` ``write_lo``).

    Write values ENCODE THE WRITING STEP: ``v = key ^ check_xor ^
    (step + 1)`` (uint64, step in the low word).  Combining stays sound
    — a step's duplicate writes carry identical values, so supersede
    returns the value every duplicate wrote — and read verification
    becomes a linearization check, on device, inside the timed loop: a
    read's value must decode to a step STRICTLY BEFORE its own
    (``decoded <= step`` with writers stamping ``step + 1``), i.e.
    reads must observe the pre-step snapshot, never their own step's
    writes.  Bulk-loaded values decode to 0 and pass.

    Write receipts: every unique write row must come back ``ST_APPLIED``
    (update-only over live keys; on multi-node meshes a cross-node
    same-key duplicate may be ``ST_SUPERSEDED`` by the identical-value
    winner — also a success), and every write client's fanned-out
    status is checked in-step.

    Carry fields (replicated scalars):

        (step_idx, ok, n_correct_reads, n_ok_writes, sum_nuniq,
         max_nuniq_r, max_nuniq_w, serve_step_idx)

    ``serve_step_idx`` is the serve program's OWN step counter (prep's
    is already bumped when serve runs, so the linearization check keeps
    a separate one).  After S steps ``n_correct_reads ==
    S * R * machine_nr`` and ``n_ok_writes == S * (batch - R) *
    machine_nr`` or the phase is void.

    ``fusion`` picks the program structure, mirroring
    :func:`make_staged_step`'s knob on the mixed loop's two credible
    forms (default: ``pipelined`` iff ``SHERMAN_STAGED_FUSION`` says
    so, else ``chained`` — the mixed loop has no separate "aligned"
    comparator, its chained serve IS the canonical fused
    ``mixed_step_spmd`` program):

    - ``"chained"`` (default): prep -> serve, receipts folded inside
      the serve program (the round-5 form).
    - ``"pipelined"``: prep -> serve -> verify as a TWO-DEEP software
      pipeline — the receipts arithmetic moves to its own program fed
      from a pending slot one batch behind, exactly like the read-only
      pipelined mode, and the write batch k's journal-relevant apply
      still happens in serve order (the pipeline reorders only the
      RECEIPTS fold, never the pool writes).  Same arithmetic, same
      fold order: after ``step.drain`` the carry is bit-identical to
      ``chained``'s.

    The hot-key leaf cache deliberately stays OUT of this loop: its
    write half re-stamps the hot keys every step, so cached entries
    would invalidate as fast as they fill (the read-only staged loop
    and the engine's host ``mixed`` entry point are the cache's
    consumers; a mixed-loop A/B belongs behind its own receipt if the
    read ratio ever skews high enough to pay)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from sherman_tpu.models.batched import (
        AXIS, ST_APPLIED, ST_SUPERSEDED, mixed_step_spmd)
    from sherman_tpu.parallel import transport

    router = eng.router
    assert router is not None, "attach_router() first"
    cfg = eng.cfg
    dsm = eng.dsm
    N = cfg.machine_nr
    iters = eng._iters()
    spec, rep = eng._spec, eng._rep
    shift, nb = int(router.shift), int(router.nb)
    LB = int(log2_bins)
    gen_ranks, sampler = _rank_sampler(sampler, n_keys, theta, LB)
    root = np.int32(eng.tree._root_addr)
    salt_hi = np.uint32((salt >> 32) & 0xFFFFFFFF)
    salt_lo = np.uint32(salt & 0xFFFFFFFF)
    cx_hi = np.uint32((check_xor >> 32) & 0xFFFFFFFF)
    cx_lo = np.uint32(check_xor & 0xFFFFFFFF)
    i32 = lambda x: lax.bitcast_convert_type(x, jnp.int32)
    u32 = lambda x: lax.bitcast_convert_type(x, jnp.uint32)

    R = int(round(batch * read_ratio))
    Wc = batch - R
    assert 0 < R <= batch and Wc > 0, "mixed loop needs both classes"
    assert R >= dev_rb and Wc >= dev_wb, "dev caps cannot exceed class sizes"

    def prep(tpair, rtable, rkey, step_idx):
        node = lax.axis_index(AXIS) if N > 1 else jnp.uint32(0)
        k = jax.random.fold_in(rkey, step_idx * np.uint32(N)
                               + node.astype(jnp.uint32))
        w = jax.random.bits(k, (2, batch), dtype=jnp.uint32)
        rank = gen_ranks(tpair, w)
        khi_u, klo_u = _keys_of_ranks(rank, salt_hi, salt_lo)
        # slots [0, R) are read clients, [R, batch) write clients; each
        # class combines independently (same pipeline as the read-only
        # staged step)
        rskhi, rsklo, rukhi, ruklo, rseg, r_nu = _sort_combine(
            khi_u[:R], klo_u[:R], dev_rb)
        wskhi, wsklo, wukhi, wuklo, wseg, w_nu = _sort_combine(
            khi_u[R:], klo_u[R:], dev_wb)
        # the [reads | writes] row block mixed_step_spmd serves
        akhi = jnp.concatenate([rukhi, wukhi])
        aklo = jnp.concatenate([ruklo, wuklo])
        act_r = jnp.concatenate([
            lax.iota(jnp.int32, dev_rb) < r_nu,
            jnp.zeros((dev_wb,), bool)])
        act_w = jnp.concatenate([
            jnp.zeros((dev_rb,), bool),
            lax.iota(jnp.int32, dev_wb) < w_nu])
        # write value = key ^ check_xor ^ (step + 1): identical across a
        # step's duplicates (combining sound), step-decodable for the
        # read-side linearization check
        stamp = step_idx + np.uint32(1)
        vhi = jnp.concatenate([jnp.zeros((dev_rb,), jnp.uint32),
                               wukhi ^ cx_hi])
        vlo = jnp.concatenate([jnp.zeros((dev_rb,), jnp.uint32),
                               wuklo ^ cx_lo ^ stamp])
        start = _router_probe(rtable, akhi, aklo, shift, nb)
        return (step_idx + np.uint32(1), akhi, aklo, vhi, vlo, act_r,
                act_w, start, rskhi, rsklo, rseg, r_nu[None],
                wseg, w_nu[None])

    def serve_fanout_core(pool, locks, counters, akhi, aklo, vhi, vlo,
                          act_r, act_w, start, rseg, wseg):
        """The mixed serve minus receipts: fused descent/apply + the
        monotone per-client fan-out of read answers and write statuses
        (GLOBAL indices on multi-node meshes).  Shared verbatim by the
        chained and pipelined forms so their pools and receipts cannot
        diverge."""
        pool, counters, status, done_r, found, rvh, rvl = mixed_step_spmd(
            pool, locks, counters, i32(akhi), i32(aklo), i32(vhi),
            i32(vlo), root, act_r, act_w, start, cfg=cfg, iters=iters,
            write_lo=dev_rb, update_only=True)
        ans = jnp.stack([found.astype(jnp.int32), rvh, rvl,
                         jnp.zeros_like(rvh)], axis=-1)[:dev_rb]
        stat_w = status[dev_rb:]
        if N > 1:
            node = lax.axis_index(AXIS)
            ans = transport.gather_rows(ans, AXIS)
            stat_w = transport.gather_rows(stat_w, AXIS)
            rseg = rseg + node.astype(jnp.int32) * dev_rb
            wseg = wseg + node.astype(jnp.int32) * dev_wb
        out = jnp.take_along_axis(
            ans, jnp.clip(rseg, 0, ans.shape[0] - 1)[:, None], axis=0)
        st_cli = jnp.take_along_axis(
            stat_w, jnp.clip(wseg, 0, stat_w.shape[0] - 1), axis=0)
        return pool, counters, out, st_cli

    def verify_mixed_core(rcarry, rskhi, rsklo, out, st_cli, r_nu, w_nu):
        """Receipts: the on-device linearization check (a read's value
        must decode to a strictly earlier step — writers stamp step+1,
        bulk decodes to 0) + the write-status audit."""
        ok, n_corr_r, n_ok_w, sum_nu, max_nu_r, max_nu_w, sidx = rcarry
        dec_hi = u32(out[:, 1]) ^ rskhi ^ cx_hi
        dec_lo = u32(out[:, 2]) ^ rsklo ^ cx_lo
        corr_r = ((out[:, 0] != 0) & (dec_hi == 0) & (dec_lo <= sidx))
        ok_w = ((st_cli == ST_APPLIED)
                | ((st_cli == ST_SUPERSEDED) if N > 1
                   else jnp.zeros_like(st_cli, bool)))
        inc_r = jnp.sum(corr_r.astype(jnp.int32))
        inc_w = jnp.sum(ok_w.astype(jnp.int32))
        step_ok = ((r_nu <= dev_rb) & (w_nu <= dev_wb)).astype(jnp.int32)
        if N > 1:
            inc_r = lax.psum(inc_r, AXIS)
            inc_w = lax.psum(inc_w, AXIS)
            sum_inc = lax.psum(r_nu + w_nu, AXIS)
            max_r = lax.pmax(r_nu, AXIS)
            max_w = lax.pmax(w_nu, AXIS)
            step_ok = lax.pmin(step_ok, AXIS)
        else:
            sum_inc, max_r, max_w = r_nu + w_nu, r_nu, w_nu
        return (jnp.minimum(ok, step_ok), n_corr_r + inc_r,
                n_ok_w + inc_w, sum_nu + sum_inc,
                jnp.maximum(max_nu_r, max_r),
                jnp.maximum(max_nu_w, max_w),
                sidx + jnp.uint32(1))

    fusion = fusion or ("pipelined" if C.staged_fusion() == "pipelined"
                        else "chained")
    if fusion not in ("chained", "pipelined"):
        raise ConfigError(f"mixed fusion={fusion!r}: want "
                         "chained|pipelined")
    mesh = dsm.mesh
    _pipe_reset = None
    prep_sm = jax.shard_map(
        prep, mesh=mesh, in_specs=(rep, rep, rep, rep),
        out_specs=(rep,) + (spec,) * 13, check_vma=False)
    jprep = DEV.wrap_program("staged_mixed.prep", jax.jit(prep_sm))

    if fusion == "chained":
        def serve(pool, locks, counters, rcarry, akhi, aklo, vhi, vlo,
                  act_r, act_w, start, rskhi, rsklo, rseg, r_nu_a, wseg,
                  w_nu_a):
            pool, counters, out, st_cli = serve_fanout_core(
                pool, locks, counters, akhi, aklo, vhi, vlo, act_r,
                act_w, start, rseg, wseg)
            rcarry = verify_mixed_core(rcarry, rskhi, rsklo, out,
                                       st_cli, r_nu_a[0], w_nu_a[0])
            return pool, counters, rcarry

        serve_sm = jax.shard_map(
            serve, mesh=mesh,
            in_specs=(spec, spec, spec, (rep,) * 7) + (spec,) * 13,
            out_specs=(spec, spec, (rep,) * 7), check_vma=False)
        # pool + counters donated; rcarry is NOT (callers block the
        # dispatch window on carry[1] — see the read-only step's note)
        jserve = DEV.wrap_program(
            "staged_mixed.serve_fanout_verify",
            jax.jit(serve_sm, donate_argnums=C.donate_argnums(0, 2)))

        def step(pool, locks, counters, tpair, rtable, rkey, carry):
            step_idx, *rcarry = carry
            step_idx, *arrs = jprep(tpair, rtable, rkey, step_idx)
            pool, counters, rcarry = jserve(pool, locks, counters,
                                            tuple(rcarry), *arrs)
            return pool, counters, (step_idx,) + tuple(rcarry)

        step.jprep, step.jserve = jprep, jserve
        step.programs = {"prep": jprep, "serve_fanout_verify": jserve}
    else:  # pipelined: receipts fold one batch behind the serve
        def serve_p(pool, locks, counters, akhi, aklo, vhi, vlo, act_r,
                    act_w, start, rseg, wseg):
            return serve_fanout_core(pool, locks, counters, akhi, aklo,
                                     vhi, vlo, act_r, act_w, start,
                                     rseg, wseg)

        serve_sm = jax.shard_map(
            serve_p, mesh=mesh, in_specs=(spec,) * 12,
            out_specs=(spec,) * 4, check_vma=False)
        jserve = DEV.wrap_program(
            "staged_mixed.serve_fanout",
            jax.jit(serve_sm, donate_argnums=C.donate_argnums(0, 2)))

        def verify_p(rcarry, rskhi, rsklo, out, st_cli, r_nu_a, w_nu_a):
            return verify_mixed_core(rcarry, rskhi, rsklo, out, st_cli,
                                     r_nu_a[0], w_nu_a[0])

        verify_sm = jax.shard_map(
            verify_p, mesh=mesh,
            in_specs=((rep,) * 7,) + (spec,) * 6,
            out_specs=(rep,) * 7, check_vma=False)
        jverify = DEV.wrap_program("staged_mixed.verify",
                                   jax.jit(verify_sm))
        _fold, _put, _drain, _pipe_reset = _two_deep_slot(jverify)

        def step(pool, locks, counters, tpair, rtable, rkey, carry):
            step_idx, *rcarry = carry
            # consume batch k-1's fanned-out answers/statuses; the
            # POOL writes of batch k-1 already landed in serve order —
            # the pipeline reorders only the receipts fold
            rcarry = _fold(tuple(rcarry))
            (step_idx, akhi, aklo, vhi, vlo, act_r, act_w, start,
             rskhi, rsklo, rseg, r_nu_a, wseg, w_nu_a) = jprep(
                tpair, rtable, rkey, step_idx)
            pool, counters, out, st_cli = jserve(
                pool, locks, counters, akhi, aklo, vhi, vlo, act_r,
                act_w, start, rseg, wseg)
            _put(rskhi, rsklo, out, st_cli, r_nu_a, w_nu_a)
            return pool, counters, (step_idx,) + rcarry

        step.drain = _drain
        step.jprep, step.jserve, step.jverify = jprep, jserve, jverify
        step.programs = {"prep": jprep, "serve_fanout": jserve,
                         "verify": jverify}

    step.sampler = sampler
    step.fusion = fusion
    step.n_programs = len(step.programs)
    step.pipeline_depth = 2 if fusion == "pipelined" else 1
    if not hasattr(step, "drain"):
        step.drain = lambda carry: carry
    # roofline join key (see the read-only factory's phase_labels note)
    step.phase_labels = {name: prog.label
                         for name, prog in step.programs.items()}

    # SLO hook (see make_staged_step): the fused read/write batch is the
    # mixed class's wall, attributed per drained window by the driver
    step.slo_class = "mixed"

    def record_slo(n_steps: int, elapsed_s: float) -> None:
        from sherman_tpu.obs import slo as _slo
        _slo.observe("mixed", n_steps * batch, elapsed_s, batches=n_steps)

    step.record_slo = record_slo

    def new_carry():
        """(step_idx, ok, n_correct_reads, n_ok_writes, sum_nuniq,
        max_nuniq_r, max_nuniq_w, serve_step_idx) — serve keeps its own
        step counter (last slot) so its linearization check cannot read
        prep's already-bumped one."""
        if _pipe_reset is not None:
            _pipe_reset()
        return tuple(_rep_put(dsm, v)
                     for v in (np.uint32(0), np.int32(1), np.int32(0),
                               np.int32(0), np.int32(0), np.int32(0),
                               np.int32(0), np.uint32(0)))

    def phase_profile(pool, locks, counters, tpair, rtable, rkey,
                      reps: int = 4):
        """Per-phase attribution of the mixed step (same chained-delta
        methodology as the read-only step's).  NOT read-only: the serve
        chain re-applies ONE prep's write batch each repetition (same
        keys, same stamped values — idempotent tree content, but the
        profiled steps' stamps land in the pool), so run it only AFTER
        the receipt-checked windows.  Returns ``({phase: ms}, pool,
        counters)``."""
        box = {"p": pool, "c": counters}

        def prep_loop(k):
            si, o = new_carry()[0], None
            for _ in range(k):
                o = jprep(tpair, rtable, rkey, si)
                si = o[0]
            jax.block_until_ready(o)

        out = {"prep": _delta_ms(prep_loop, reps)}
        arrs = jprep(tpair, rtable, rkey, new_carry()[0])[1:]
        jax.block_until_ready(arrs)

        if fusion == "chained":
            def sv_loop(k):
                rc = tuple(new_carry()[1:])
                for _ in range(k):
                    box["p"], box["c"], rc = jserve(box["p"], locks,
                                                    box["c"], rc, *arrs)
                jax.block_until_ready(rc)

            out["serve_fanout_verify"] = _delta_ms(sv_loop, reps)
            return out, box["p"], box["c"]

        # pipelined: attribute the split serve and verify programs,
        # then the drained pipelined wall (the overlap receipt — see
        # the read-only step's phase_profile)
        (akhi, aklo, vhi, vlo, act_r, act_w, start, rskhi, rsklo,
         rseg, r_nu_a, wseg, w_nu_a) = arrs

        def serve_loop(k):
            o = None
            for _ in range(k):
                box["p"], box["c"], o, st = jserve(
                    box["p"], locks, box["c"], akhi, aklo, vhi, vlo,
                    act_r, act_w, start, rseg, wseg)
            jax.block_until_ready(o)

        out["serve_fanout"] = _delta_ms(serve_loop, reps)
        box["p"], box["c"], o, st = jserve(
            box["p"], locks, box["c"], akhi, aklo, vhi, vlo, act_r,
            act_w, start, rseg, wseg)

        def verify_loop(k):
            rc = tuple(new_carry()[1:])
            for _ in range(k):
                rc = jverify(rc, rskhi, rsklo, o, st, r_nu_a, w_nu_a)
            jax.block_until_ready(rc)

        out["verify"] = _delta_ms(verify_loop, reps)

        def pipe_loop(k):
            c = new_carry()
            for _ in range(k):
                box["p"], box["c"], c = step(box["p"], locks, box["c"],
                                             tpair, rtable, rkey, c)
            c = step.drain(c)
            jax.block_until_ready(c)

        # warm both carry variants (see the read-only overlap receipt)
        pipe_loop(2)
        out.update(overlap_receipt(out["prep"], out["serve_fanout"],
                                   out["verify"],
                                   _delta_ms(pipe_loop, reps)))
        return out, box["p"], box["c"]

    step.phase_profile = phase_profile

    table_d, rtable_d, rkey_d = staged or _stage_inputs(
        dsm, router, n_keys, theta, LB, seed, sampler)
    return step, (new_carry, table_d, rtable_d, rkey_d)


def make_device_prep(eng, *, width: int):
    """Fused DEVICE request-plane prep for the ingress step (PR 17's
    ``config.prep_impl = "device"``): one compiled program per ladder
    width that performs on device exactly what the host path's
    ``np.unique`` + ``LeafRouter.host_start`` + zero-padding do —
    duplicate-key combining, dedup, key sort, router probe — emitting
    the staged fan-out inputs ``(khi, klo, active, start, inv)``
    BIT-IDENTICALLY (the CI pin in tests/test_prep.py), plus the
    unique count as a replicated device scalar.

    Anatomy (the same two-sort discipline as :func:`_sort_combine`,
    generalized to partial batches): a 5-operand ``lax.sort`` orders
    the raw (hi, lo) pairs unsigned and carries the original index; a
    segment scan numbers the unique groups (this IS the host ``inv`` —
    ``np.unique``'s inverse is the rank of each key in sorted unique
    order); a flag-sort compacts the first-occurrence rows (already
    key-sorted, so the unique set matches ``np.unique``'s order); and
    the router probe reuses the HOST table uploaded as a replicated
    device array with the shift as TRACED data
    (:func:`sherman_tpu.ops.bits.u64_shr_dyn`) — a span grow updates a
    scalar input instead of retracing the sealed program.

    Padding contract: rows past ``n`` carry the KEY_POS_INF sentinel
    pair ``(-1, -1)`` — excluded from the valid key range
    (config.KEY_MAX < KEY_POS_INF), it can never collide with a client
    key, sorts strictly last, and therefore forms exactly ONE trailing
    unique group iff ``n < width`` — subtracting it yields the host
    ``U``.  Masked unique rows and the inverse map then zero exactly
    like the host path's padding.

    Returns ``(prep_fn, upload)``: ``prep_fn(khi_raw, klo_raw, n,
    rtable, shift) -> (khi, klo, active, start, inv, n_uniq)`` with the
    five arrays node-sharded for the fan-out and ``n_uniq`` replicated;
    ``upload(x)`` places host values as replicated device arrays
    (multihost-aware)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    dsm = eng.dsm
    rep_sharding = jax.sharding.NamedSharding(
        dsm.mesh, jax.sharding.PartitionSpec())

    def prep_core(khi_raw, klo_raw, n, rtable, shift):
        idx0 = jnp.arange(width, dtype=jnp.int32)
        # sort by unsigned 64-bit key, carrying the raw pair + index
        _, _, skhi, sklo, sidx = lax.sort(
            (bits._ux(khi_raw), bits._ux(klo_raw), khi_raw, klo_raw,
             idx0), num_keys=2)
        first = jnp.concatenate([
            jnp.ones((1,), bool),
            (skhi[1:] != skhi[:-1]) | (sklo[1:] != sklo[:-1])])
        seg = (jnp.cumsum(first.astype(jnp.int32)) - 1)
        # the sentinel contributes one trailing group iff padding exists
        n_uniq = seg[-1] + 1 - (n < width).astype(jnp.int32)
        # compact first-occurrence rows (key-sorted, = np.unique order);
        # the sentinel group's head lands at position n_uniq and is
        # masked to zero with the rest of the tail, like the host pad
        flag = (~first).astype(jnp.int32)
        _, _, _, ckhi, cklo = lax.sort(
            (flag, bits._ux(skhi), bits._ux(sklo), skhi, sklo),
            num_keys=3)
        active = idx0 < n_uniq
        ukhi = jnp.where(active, ckhi, 0)
        uklo = jnp.where(active, cklo, 0)
        # router probe, dynamic shift (host_start twin: key 0 -> bucket
        # 0 -> table[0] covers the masked tail exactly like the host)
        nb = rtable.shape[0]
        bhi, blo = bits.u64_shr_dyn(
            lax.bitcast_convert_type(ukhi, jnp.uint32),
            lax.bitcast_convert_type(uklo, jnp.uint32), shift)
        bucket = jnp.where(bhi != 0, jnp.uint32(nb - 1),
                           jnp.minimum(blo, jnp.uint32(nb - 1)))
        start = rtable[bucket.astype(jnp.int32)]
        # un-sort the segment map: inv[i] = unique rank of client row i
        _, inv = lax.sort((sidx, seg), num_keys=1)
        inv = jnp.where(idx0 < n, inv, 0)
        return ukhi, uklo, active, start, inv, n_uniq

    prep_fn = DEV.wrap_program(
        "serve.device_prep",
        jax.jit(prep_core,
                out_shardings=(dsm.shard, dsm.shard, dsm.shard,
                               dsm.shard, dsm.shard, rep_sharding)))
    return prep_fn, (lambda x: _rep_put(dsm, x))


def make_ingress_step(eng, *, width: int, leaf_cache=None,
                      prep_impl: str | None = None):
    """External-driver hook on the staged serving substrate — the
    serving front door's read path (:mod:`sherman_tpu.serve`).

    The staged factories above generate their client batches ON DEVICE
    (the bench's synthetic zipf open loop); a front door serves batches
    that arrive from OUTSIDE.  This factory is the host-fed twin: client
    key batches of ONE fixed compiled ``width`` are combined, probed and
    dispatched through the SAME serve program OBJECT the staged loops
    and the host-staged throughput phase run
    (``BatchedEngine._get_search_fanout`` — so the CI program-identity
    pin and the compile-ledger label extend to the front door), with the
    per-request answer fan-out on device via the unique-inverse map,
    exactly like ``search_combined`` but at the CALLER's width instead
    of the engine's fixed ``machine_nr * B``.  Fixed width is the whole
    point: the adaptive batcher picks a step width from a pre-warmed
    ladder, and every ladder rung is one compiled shape — the sealed
    serving loop stays zero-retrace by construction.

    Split dispatch/complete protocol (the two-deep pipeline's raw
    material — the front door keeps ONE batch in flight and overlaps
    batch k's host prep + dispatch with batch k-1's device serve, the
    ``fusion="pipelined"`` discipline applied to external traffic)::

        handle = step.dispatch(keys)        # launch only, keys u64 [n]
        vals, found = step.complete(handle) # blocks, materializes

    ``dispatch`` contract (it is a registered SL001 hot function — no
    host syncs of device data inside): ``keys`` MUST already be a
    uint64 ndarray with ``0 < n <= width`` and every key in
    ``[KEY_MIN, KEY_MAX]`` (the front door validates at admission);
    duplicate keys share one descent row (request combining — the
    unique set is key-sorted, the round-1 locality win).  With
    ``leaf_cache`` attached the unique batch is probed first
    (pool-validated hits leave the active set and merge back per client
    row in ``complete`` — bit-identical to the uncached path, the
    engine read paths' own contract) and the raw client stream feeds
    the admission sketch (``observe``), so sketch-driven admission
    learns from REAL request streams.

    Straggler contract: rows whose descent overran the budget (stale
    router seeds after splits/growth) are rescued in ``complete`` via
    the engine's root-descent ``search`` — warm it before sealing.

    NOTE this factory and ``BatchedEngine.search_combined`` implement
    the same combine/probe/fan-out/rescue/merge protocol at different
    width regimes (the engine's fixed ``machine_nr * B`` + client
    quantum vs the caller's ladder rung); the bit-identity pin in
    ``tests/test_serve.py`` (ingress vs ``search_combined`` on the
    same batch) is the guard that keeps the two copies from
    diverging.
    """
    router = eng.router
    if router is None:
        raise ConfigError("make_ingress_step: attach_router() first — "
                          "the front door serves router-seeded descents")
    if width <= 0 or width % eng.cfg.machine_nr != 0:
        raise ConfigError(
            f"ingress width {width} must be a positive multiple of "
            f"machine_nr={eng.cfg.machine_nr} (the batch shards over "
            "the node mesh)")
    if prep_impl is None:
        prep_impl = C.prep_impl()
    if prep_impl not in ("host", "device"):
        raise ConfigError(
            f"make_ingress_step: prep_impl={prep_impl!r}: want "
            "host|device")
    if prep_impl == "device" and leaf_cache is not None:
        # documented fallback (config.prep_impl): the cache probe is
        # host-in/host-out (it syncs its hit count), so device prep
        # composed with it would reintroduce the per-batch host
        # round-trip the knob exists to remove
        prep_impl = "host"
    iters = eng._iters()
    fn = eng._get_search_fanout(iters)
    root = np.int32(eng.tree._root_addr)
    # prep-phase attribution (PR 17): per-dispatch host wall of the
    # request plane, split host-vs-device — histogram handles created
    # here so dispatch (SL001-hot) only records plain floats
    import time as _time
    from sherman_tpu import obs as _obs
    _h_prep = _obs.histogram(
        "prep.device_dispatch_ms" if prep_impl == "device"
        else "prep.host_ms")
    _obs.gauge("prep.impl_device").set(
        1.0 if prep_impl == "device" else 0.0)

    def dispatch(keys):
        t0p = _time.perf_counter()
        n = keys.shape[0]
        uk, inv = np.unique(keys, return_inverse=True)
        U = uk.shape[0]
        kh, kl = bits.keys_to_pairs(uk)
        khi = np.zeros(width, kh.dtype)
        klo = np.zeros(width, kl.dtype)
        khi[:U] = kh
        klo[:U] = kl
        active = np.zeros(width, bool)
        active[:U] = True
        chit = cvhi = cvlo = None
        if leaf_cache is not None:
            # admission sketch sees the RAW (duplicated) client stream —
            # frequency ranking needs the multiplicities — then the
            # probe drops pool-validated hits out of the device batch
            leaf_cache.observe(keys)
            chit, cvhi, cvlo = leaf_cache.probe(khi, klo, active)
            active = active & ~chit
        start = router.host_start(khi, klo)
        inv_p = np.zeros(width, np.int32)
        inv_p[:n] = inv.astype(np.int32)
        args = (eng._shard(khi), eng._shard(klo), root,
                eng._shard(active), eng._shard(start),
                eng._shard(inv_p))
        with eng._step_mutex:  # launch-only, the engine step contract
            eng.dsm.counters, done, found, vhi, vlo = fn(
                eng.dsm.pool, eng.dsm.counters, *args)
        _h_prep.record((_time.perf_counter() - t0p) * 1e3)
        return (n, U, uk, inv, done, found, vhi, vlo, chit, cvhi, cvlo)

    def complete(handle):
        n, U, uk, inv, done, found, vhi, vlo, chit, cvhi, cvlo = handle
        done, found, vhi, vlo = eng._unshard(done, found, vhi, vlo)
        done_u = np.asarray(done[:U])
        if chit is not None:
            done_u = done_u | chit[:U]
        if not bool(done_u.all()):
            # straggler rescue (stale seeds / height growth): the
            # engine's root-descent path answers the whole unique set,
            # host fan-out (search() owns retries + SLO attribution)
            vals_u, found_u = eng.search(uk)
            return vals_u[inv][:n], found_u[inv][:n]
        vals = np.array(bits.pairs_to_keys(vhi[:n], vlo[:n]))
        fnd = np.array(found[:n])
        if chit is not None and chit[:U].any():
            # cache hits' device rows were inactive — overwrite their
            # client rows through the same inverse map the fan-out used
            ch = chit[:U][inv][:n]
            fnd[ch] = True
            vals[ch] = np.asarray(bits.pairs_to_keys(
                cvhi[:U], cvlo[:U]))[inv][:n][ch]
        return vals, fnd

    def step(keys):
        """Synchronous convenience: dispatch + complete in one call
        (closed-loop drivers and tests; the front door pipelines the
        two halves itself)."""
        return complete(dispatch(keys))

    def drain(handle):
        """Teardown-path completion (the front door's kill/drain hook):
        materialize the handle's device work and discard it WITHOUT
        the straggler rescue — a draining or crashing server must not
        launch fresh root descents (``eng.search`` compiles programs,
        takes the step mutex, and can raise through a degraded
        engine).  The in-flight step's device buffers are blocked on
        and released; nothing is returned — the caller has already
        failed or resolved the slot's futures."""
        _n, _U, _uk, _inv, done, found, vhi, vlo, *_ = handle
        eng._unshard(done, found, vhi, vlo)

    prep_fn = None
    if prep_impl == "device":
        import jax

        prep_fn, _upload = make_device_prep(eng, width=width)
        # router-table snapshot versioned by the split/grow counters:
        # plain Python ints, so staleness detection costs two compares
        # per dispatch and the re-upload happens only when the table
        # actually moved (splits_noted / span_grows bump)
        _rt = {"ver": None, "rtable": None, "shift": None}

        def _router_state():
            ver = (router.splits_noted, router.span_grows)
            if _rt["ver"] != ver:
                with router._read_locked():
                    table = np.array(router.table_np)
                    shift = np.uint32(router.shift)
                    ver = (router.splits_noted, router.span_grows)
                _rt["rtable"] = _upload(table)
                _rt["shift"] = _upload(shift)
                _rt["ver"] = ver
            return _rt["rtable"], _rt["shift"]

        def dispatch_device(keys):
            """Device-prep twin of ``dispatch`` (same SL001 hot-path
            contract: launch-only, no host syncs of device data): the
            host's only per-batch work is the pair split + sentinel
            pad + three scalar/array uploads — combining, dedup, sort
            and the router probe all run in the sealed ``prep_fn``
            program, whose outputs feed the serve fan-out without
            touching the host."""
            t0p = _time.perf_counter()
            n = keys.shape[0]
            kh, kl = bits.keys_to_pairs(keys)
            khi_raw = np.full(width, -1, np.int32)   # KEY_POS_INF pair
            klo_raw = np.full(width, -1, np.int32)
            khi_raw[:n] = kh
            klo_raw[:n] = kl
            rtable, shift = _router_state()
            khi, klo, active, start, inv_p, n_uniq = prep_fn(
                jax.device_put(khi_raw), jax.device_put(klo_raw),
                jax.device_put(np.int32(n)), rtable, shift)
            with eng._step_mutex:  # launch-only, the engine step contract
                eng.dsm.counters, done, found, vhi, vlo = fn(
                    eng.dsm.pool, eng.dsm.counters, khi, klo, root,
                    active, start, inv_p)
            _h_prep.record((_time.perf_counter() - t0p) * 1e3)
            return (n, n_uniq, (khi, klo), inv_p, done, found, vhi, vlo,
                    None, None, None)

        def complete_device(handle):
            """Completion half (materializes by design): the unique
            count syncs here, and the straggler rescue lazily
            materializes the unique set + inverse map only when a
            descent actually overran."""
            n, n_uniq, ukpair, inv_p, done, found, vhi, vlo, *_ = handle
            done, found, vhi, vlo = eng._unshard(done, found, vhi, vlo)
            U = int(np.asarray(n_uniq))
            if not bool(np.asarray(done[:U]).all()):
                ukhi, uklo = eng._unshard(*ukpair)
                uk = bits.pairs_to_keys(ukhi[:U], uklo[:U])
                inv = np.asarray(eng._unshard(inv_p))[:n]
                vals_u, found_u = eng.search(uk)
                return vals_u[inv], found_u[inv]
            vals = np.array(bits.pairs_to_keys(vhi[:n], vlo[:n]))
            return vals, np.array(found[:n])

        dispatch, complete = dispatch_device, complete_device

    def prep_profile(keys, reps: int = 8) -> dict:
        """Chained-delta wall of the request-plane prep ALONE for this
        step's impl — the host-vs-device A/B's per-phase number
        (tools/profile_prep.py publishes it; record_phase_obs routes it
        into the ``prep.*`` histograms).  Host mode times the actual
        ``np.unique`` + router-probe + pad sequence; device mode chains
        ``prep_fn`` dispatches and blocks once at the end, so the
        per-dispatch overhead cancels exactly like every other
        chained-delta phase receipt."""
        keys = np.asarray(keys, np.uint64)
        n = keys.shape[0]
        if prep_impl == "device":
            import jax

            kh, kl = bits.keys_to_pairs(keys)
            khi_raw = np.full(width, -1, np.int32)
            klo_raw = np.full(width, -1, np.int32)
            khi_raw[:n] = kh
            klo_raw[:n] = kl
            rtable, shift = _router_state()
            dk, dl = jax.device_put(khi_raw), jax.device_put(klo_raw)
            dn = jax.device_put(np.int32(n))

            def loop(k):
                out = None
                for _ in range(k):
                    out = prep_fn(dk, dl, dn, rtable, shift)
                np.asarray(out[-1])  # drain
            return {"prep_device_ms": _delta_ms(loop, reps)}

        def loop(k):
            for _ in range(k):
                uk, inv = np.unique(keys, return_inverse=True)
                U = uk.shape[0]
                kh, kl = bits.keys_to_pairs(uk)
                khi = np.zeros(width, kh.dtype)
                klo = np.zeros(width, kl.dtype)
                khi[:U] = kh
                klo[:U] = kl
                router.host_start(khi, klo)
        return {"prep_host_ms": _delta_ms(loop, reps)}

    step.dispatch = dispatch
    step.complete = complete
    step.drain = drain
    step.width = width
    step.cache = leaf_cache is not None
    step.prep_impl = prep_impl
    step.prep_profile = prep_profile
    step.programs = {"serve_fanout": fn}
    step.phase_labels = {"serve_fanout": fn.label}
    if prep_fn is not None:
        step.programs["device_prep"] = prep_fn
        step.phase_labels["device_prep"] = prep_fn.label
    return step
