"""Device-resident batch staging: the benchmark loop's entire client
side — zipf rank sampling, the synthetic rank->key map, request
combining (sort-based unique + inverse), and the index-cache probe —
as ONE jitted TPU computation fused with the serving step, so a
sustained loop ships NOTHING per step (the step counter threads through
device-resident carry; the host only dispatches).

Reference parity: the reference benchmark's client threads generate
their zipf key and issue it inline in the open loop
(``test/benchmark.cpp:159-188``) — nothing hoisted.  Here the TPU is
client and server fused, so generation runs on device inside the timed
step.  Fidelity:

- The rank distribution inverts the SAME Gray/Jain CDF the native
  sampler uses (``native/src/prep.cc``), via a host-precomputed
  quantile table: ``table[i]`` = inverse CDF at quantile ``i / 2^LB``
  (float64-exact head + Euler-Maclaurin tail, vectorized bisection).
  On device a sample is a 2-word counter-based PRNG draw: word 0 picks
  the quantile bin (the CDF is exact at bin edges — hot ranks span
  many whole bins, so the head is EXACT), word 1 lerps within the bin
  (piecewise-uniform; bins are <= ~2^14 ranks wide even in the deepest
  tail, where the zipf density is locally flat, so the within-bin
  approximation is statistically invisible).  The f32 lerp is exact to
  <1 rank for bin widths < 2^24 (asserted at table build).
- The rank->key map is bit-for-bit the native one:
  ``mix64(rank ^ salt)`` on (hi, lo) uint32 pairs
  (:func:`sherman_tpu.ops.bits.mix64_pair`), so device-generated
  batches hit exactly the keys the bulk load wrote.
- Dedup is a device ``lax.sort`` by key + segment scan; the unique set
  is compacted by a SECOND stable sort on the first-occurrence flag
  (sorts measure ~6 ms at 4 M rows on chip, while the scatter-based
  compaction they replace measured ~24 ms per scatter — random
  HBM writes are the expensive primitive, sorts are not).  The unique
  rows come out KEY-SORTED, which after a sequential bulk load is also
  page-address-sorted: the round-1 leaf gather gets the start-sorted
  locality win (measured ~27% on host-staged batches) for free.
- The step SERVES CLIENTS IN SORTED ORDER: the client view of the
  batch is the key-sorted permutation of the generated ops (client
  order carries no meaning — the reference's client threads are
  unordered).  That makes the per-request answer fan-out a MONOTONE
  gather (``ans[seg]``, seg nondecreasing) instead of a random one,
  and drops the inverse-permutation scatter entirely.  Every client
  op's answer is still materialized in HBM inside the step and
  VERIFIED on device: the carry accumulates the exact count of client
  ops whose returned value matched ``key ^ check_xor`` — the
  honest-accounting receipts ride inside the timed loop.
"""

from __future__ import annotations

import numpy as np

from sherman_tpu.ops import bits


def zipf_table(n: int, theta: float, log2_bins: int = 20) -> np.ndarray:
    """Inverse-CDF quantile table for Zipf(theta) ranks over [0, n):
    int32 [2^log2_bins + 1], ``table[i]`` = smallest 0-based rank r with
    CDF(r) >= i / 2^log2_bins (``table[-1]`` = n - 1).

    theta == 0 degenerates to the uniform ramp.  Head ranks are exact
    (float64 cumsum of the harmonic series up to 2^22); tail CDF values
    use the Euler-Maclaurin continuation (error << one quantile), and
    the inversion is a vectorized bisection."""
    assert 0.0 <= theta < 1.0 and n >= 1
    nb = 1 << log2_bins
    if theta == 0.0:
        t = np.floor(np.arange(nb + 1, dtype=np.float64) * n / nb)
        table = np.minimum(t, n - 1).astype(np.int32)
    else:
        M = min(n, 1 << 22)
        f = np.arange(1, M + 1, dtype=np.float64) ** -theta
        Hhead = np.cumsum(f)
        om = 1.0 - theta

        def H(r):
            """Harmonic partial sum H(r) = sum_{k=1..r} k^-theta for
            real r >= M (Euler-Maclaurin; exact head)."""
            r = np.asarray(r, np.float64)
            integral = (r ** om - float(M) ** om) / om
            half = 0.5 * (r ** -theta - float(M) ** -theta)
            d112 = (theta / 12.0) * (r ** (-theta - 1.0)
                                     - float(M) ** (-theta - 1.0))
            return Hhead[-1] + integral + half - d112

        Hn = Hhead[-1] if n <= M else float(H(float(n)))
        q = np.arange(nb + 1, dtype=np.float64) / nb * Hn
        table = np.searchsorted(Hhead, q, side="left").astype(np.int64)
        tail = q > Hhead[-1]
        if tail.any():
            qt = q[tail]
            lo = np.full(qt.shape, float(M))
            hi = np.full(qt.shape, float(n))
            for _ in range(48):
                mid = 0.5 * (lo + hi)
                ge = H(mid) >= qt
                hi = np.where(ge, mid, hi)
                lo = np.where(ge, lo, mid)
            table[tail] = np.ceil(hi).astype(np.int64) - 1
        table = np.minimum(np.maximum(table, 0), n - 1).astype(np.int32)
    assert (np.diff(table) >= 0).all()
    assert int(np.diff(table.astype(np.int64)).max(initial=0)) < (1 << 24), \
        "quantile bin wider than the 24-bit lerp resolution; raise log2_bins"
    return table


def make_staged_step(eng, *, n_keys: int, theta: float, salt: int,
                     batch: int, dev_b: int, log2_bins: int = 20,
                     check_xor: int = 0xDEADBEEF, seed: int = 11):
    """Build the device-staged serving step for ``eng`` (a
    :class:`~sherman_tpu.models.batched.BatchedEngine` with an attached
    router).

    Returns ``(step, state)`` where ``state = (new_carry, table_d,
    rtable_d, rkey_d)``: ``new_carry()`` makes a fresh device-resident
    carry (the previous one is donated), the rest are device-resident
    inputs staged once, before any timed region.  Then

        ``counters, carry = step(pool, counters, table_d, rtable_d,
                                 rkey_d, carry)``

    runs ONE step: generate ``batch`` zipf client keys per node from the
    carry's step counter, combine to <= ``dev_b`` unique rows, probe the
    router, descend, fan out every answer in-step, and fold the
    verification receipts into the carry.  The step is TWO chained
    jitted programs (``step.jprep`` -> ``step.jserve``) dispatched
    back-to-back with no host work or transfer between them: XLA
    compiles the prep pipeline fused into the serve's straggler
    while-loop ~50-100x slower than the sum of its parts (measured
    6.8-10.3 s fused vs 56 + 63 ms split on chip, optimization_barrier
    included), so the split IS the fast form.  ``counters``/``carry``
    and the intermediate prep arrays are donated.  Carry fields (all
    replicated int32/uint32 scalars):

        (step_idx, ok, n_correct, sum_nuniq, max_nuniq)

    ``ok`` goes 0 if any step's unique count overflowed ``dev_b`` (its
    rows would be dropped, so the step's receipts are void);
    ``n_correct`` counts client ops whose value matched
    ``key ^ check_xor`` — after S steps it must equal
    ``S * batch * machine_nr``.  ``sum_nuniq`` accumulates per-node
    unique counts (psum across nodes) for combine-ratio reporting."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from sherman_tpu.models.batched import AXIS, search_routed_spmd

    router = eng.router
    assert router is not None, "attach_router() first"
    cfg = eng.cfg
    N = cfg.machine_nr
    iters = eng._iters()
    spec, rep = eng._spec, eng._rep
    shift, nb = int(router.shift), int(router.nb)
    LB = int(log2_bins)
    root = np.int32(eng.tree._root_addr)
    salt_hi = np.uint32((salt >> 32) & 0xFFFFFFFF)
    salt_lo = np.uint32(salt & 0xFFFFFFFF)
    i32 = lambda x: lax.bitcast_convert_type(x, jnp.int32)

    assert batch >= dev_b, "dev_b is the unique-set cap; cannot exceed batch"

    def prep(tpair, rtable, rkey, step_idx):
        # per-node, per-step independent stream (counter-based PRNG):
        # fold the step counter and the node index into the key
        node = lax.axis_index(AXIS) if N > 1 else jnp.uint32(0)
        k = jax.random.fold_in(rkey, step_idx * np.uint32(N)
                               + node.astype(jnp.uint32))
        w = jax.random.bits(k, (2, batch), dtype=jnp.uint32)
        # zipf rank: bin from the top LB bits (CDF-exact edges), f32
        # lerp within the bin on 24 fresh bits.  The table is staged as
        # [nb, 2] = (edge_i, edge_{i+1}) pairs so the bin lookup is ONE
        # random gather, not two (random HBM access is the dominant prep
        # cost on chip — ~15 ns/row).
        bin_ = (w[0] >> (32 - LB)).astype(jnp.int32)
        t2 = tpair[bin_]                     # [batch, 2]
        lo_r, hi_r = t2[:, 0], t2[:, 1]
        frac = (w[1] >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)
        rank = lo_r + ((hi_r - lo_r).astype(jnp.float32)
                       * frac).astype(jnp.int32)
        rank = jnp.clip(rank, 0, n_keys - 1)
        # key = mix64(rank ^ salt): ranks < 2^31 so the high word of
        # (rank ^ salt) is salt's high word
        xlo = lax.bitcast_convert_type(rank, jnp.uint32) ^ salt_lo
        xhi = jnp.full((batch,), salt_hi, jnp.uint32)
        khi_u, klo_u = bits.mix64_pair(xhi, xlo)
        # sort-based unique (request combining): clients are served in
        # key-sorted order (see module docstring), so no index payload
        # and no inverse-permutation scatter are needed
        skhi, sklo = lax.sort((khi_u, klo_u), num_keys=2)
        first = jnp.concatenate([
            jnp.ones((1,), jnp.uint32),
            ((skhi[1:] != skhi[:-1])
             | (sklo[1:] != sklo[:-1])).astype(jnp.uint32)])
        seg = (jnp.cumsum(first) - 1).astype(jnp.int32)  # [batch] slots
        n_uniq = seg[-1] + 1
        # compact the unique set with a flag-sort: first occurrences to
        # the front, key order preserved.  Plain 3-key sort, NOT
        # is_stable=True — the composite (flag, khi, klo) is already a
        # total order on the rows that matter (first rows have distinct
        # keys), and the stable-sort path measured ~12x slower on chip.
        # Sorts are ~4x cheaper than the equivalent scatters on chip.
        _, ckhi, cklo = lax.sort((jnp.uint32(1) - first, skhi, sklo),
                                 num_keys=3)
        ukhi, uklo = ckhi[:dev_b], cklo[:dev_b]
        active = lax.iota(jnp.int32, dev_b) < n_uniq
        # router probe: bucket = min(key >> shift, nb - 1)
        bhi, blo = bits.u64_shr(ukhi, uklo, shift)
        bucket = jnp.where(bhi != 0, jnp.uint32(nb - 1),
                           jnp.minimum(blo, jnp.uint32(nb - 1)))
        start = rtable[bucket.astype(jnp.int32)]
        # n_uniq ships as a [1] array so it shards per node like the rest
        return (step_idx + np.uint32(1), skhi, sklo, ukhi, uklo, start,
                active, seg, n_uniq[None])

    def serve(pool, counters, rcarry, skhi, sklo, ukhi, uklo, start,
              active, seg, n_uniq_a):
        ok, n_correct, sum_nu, max_nu = rcarry
        n_uniq = n_uniq_a[0]
        counters, done, found, vhi, vlo = search_routed_spmd(
            pool, counters, i32(ukhi), i32(uklo), root, active, start,
            cfg=cfg, iters=iters)
        ans = jnp.stack([found.astype(jnp.int32), vhi, vlo,
                         jnp.zeros_like(vhi)], axis=-1)     # [U_loc, 4]
        # per-client fan-out: seg is NONDECREASING, so this gather is
        # monotone (sequential HBM locality), unlike an inverse-permuted
        # one.  GLOBAL indices on multi-node meshes: the answer table
        # all-gathers tiled, node n's rows at [n*dev_b, (n+1)*dev_b).
        if N > 1:
            node = lax.axis_index(AXIS)
            ans = lax.all_gather(ans, AXIS, axis=0, tiled=True)
            seg = seg + node.astype(jnp.int32) * dev_b
        safe = jnp.clip(seg, 0, ans.shape[0] - 1)
        out = jnp.take_along_axis(ans, safe[:, None], axis=0)
        # in-step verification: value must be (sorted) client key ^
        # check_xor
        exp_hi = i32(skhi ^ jnp.uint32((check_xor >> 32) & 0xFFFFFFFF))
        exp_lo = i32(sklo ^ jnp.uint32(check_xor & 0xFFFFFFFF))
        corr = ((out[:, 0] != 0) & (out[:, 1] == exp_hi)
                & (out[:, 2] == exp_lo))
        inc_corr = jnp.sum(corr.astype(jnp.int32))
        step_ok = (n_uniq <= dev_b).astype(jnp.int32)
        if N > 1:
            inc_corr = lax.psum(inc_corr, AXIS)
            sum_inc = lax.psum(n_uniq, AXIS)
            max_inc = lax.pmax(n_uniq, AXIS)
            step_ok = lax.pmin(step_ok, AXIS)
        else:
            sum_inc, max_inc = n_uniq, n_uniq
        rcarry = (jnp.minimum(ok, step_ok),
                  n_correct + inc_corr,
                  sum_nu + sum_inc,
                  jnp.maximum(max_nu, max_inc))
        return counters, rcarry

    mesh = eng.dsm.mesh
    # prep is per-node independent (no collectives); its 8 array outputs
    # shard along the node axis (each node's local block), the bumped
    # step counter is replicated
    prep_sm = jax.shard_map(
        prep, mesh=mesh, in_specs=(rep, rep, rep, rep),
        out_specs=(rep,) + (spec,) * 8, check_vma=False)
    jprep = jax.jit(prep_sm)
    serve_sm = jax.shard_map(
        serve, mesh=mesh,
        in_specs=(spec, spec, (rep,) * 4) + (spec,) * 8,
        out_specs=(spec, (rep,) * 4), check_vma=False)
    # donate counters + the receipts carry only: the prep intermediates'
    # shapes cannot alias any serve output, so donating them just emits
    # a "donated buffers were not usable" warning every compile (they
    # are freed after the call regardless)
    jserve = jax.jit(serve_sm, donate_argnums=(1, 2))

    def step(pool, counters, tpair, rtable, rkey, carry):
        step_idx, *rcarry = carry
        step_idx, *arrs = jprep(tpair, rtable, rkey, step_idx)
        counters, rcarry = jserve(pool, counters, tuple(rcarry), *arrs)
        return counters, (step_idx,) + tuple(rcarry)

    step.jprep, step.jserve = jprep, jserve

    def new_carry():
        """Fresh device-resident carry (the previous one is donated)."""
        return tuple(jax.device_put(v)
                     for v in (np.uint32(0), np.int32(1), np.int32(0),
                               np.int32(0), np.int32(0)))

    t = zipf_table(n_keys, theta, LB)
    table_d = jax.device_put(np.stack([t[:-1], t[1:]], axis=1))  # [nb, 2]
    with router._read_locked():
        rtable_d = jax.device_put(router.table_np)
    rkey_d = jax.device_put(jax.random.PRNGKey(seed))
    return step, (new_carry, table_d, rtable_d, rkey_d)
