"""Zipfian workload generator — YCSB-style skewed key sampling.

Plays the role of the reference's MICA-derived sampler (``test/zipf.h``,
``mehcached_zipf_init/next``): ranks follow a Zipf(theta) distribution over
[0, n).  Implemented from the standard Gray et al. formulation ("Quickly
Generating Billion-Record Synthetic Databases", SIGMOD '94) with fully
vectorized numpy sampling — one call yields millions of samples, matching
the batched execution model (no per-op scalar next() on the hot path,
though one is provided for parity).

theta = 0.99 reproduces the canonical YCSB skew (BASELINE.md configs).
"""

from __future__ import annotations

import numpy as np


def _zeta(n: int, theta: float, chunk: int = 1 << 22) -> float:
    """zeta(n, theta) = sum_{i=1..n} 1/i^theta, chunked to bound memory."""
    total = 0.0
    i = 1
    while i <= n:
        j = min(n, i + chunk - 1)
        ks = np.arange(i, j + 1, dtype=np.float64)
        total += float(np.sum(ks ** -theta))
        i = j + 1
    return total


class ZipfGen:
    """Zipf(theta) rank sampler over [0, n).

    Prefers the native C++ sampler (:mod:`sherman_tpu.native`) and falls
    back to the vectorized numpy path when the toolchain is unavailable.
    """

    def __init__(self, n: int, theta: float = 0.99, seed: int = 0):
        assert n >= 1 and 0.0 <= theta < 1.0
        self.n = n
        self.theta = theta
        self._native = None
        try:
            from sherman_tpu import native
            if native.available():
                self._native = native.ZipfGen(n, theta, seed)
        except Exception:
            self._native = None
        self.rng = np.random.default_rng(seed)
        if self._native is None:
            self._init_fallback()

    def _init_fallback(self) -> None:
        """O(n) zeta sums — only paid when the native sampler is absent."""
        n, theta = self.n, self.theta
        self.zetan = _zeta(n, theta)
        self.zeta2 = _zeta(2, theta)
        self.alpha = 1.0 / (1.0 - theta)
        self.eta = ((1.0 - (2.0 / n) ** (1.0 - theta))
                    / (1.0 - self.zeta2 / self.zetan))

    def sample(self, size: int) -> np.ndarray:
        """-> int64 ranks [size] in [0, n); rank 0 is the hottest."""
        if self._native is not None:
            return self._native.sample(size).astype(np.int64)
        u = self.rng.random(size)
        uz = u * self.zetan
        ranks = (self.n * (self.eta * u - self.eta + 1.0) ** self.alpha
                 ).astype(np.int64)
        ranks = np.where(uz < 1.0, 0, ranks)
        ranks = np.where((uz >= 1.0) & (uz < 1.0 + 0.5 ** self.theta),
                         1, ranks)
        return np.clip(ranks, 0, self.n - 1)

    def next(self) -> int:
        """Scalar parity API (mehcached_zipf_next)."""
        return int(self.sample(1)[0])


def uniform_ranks(n: int, size: int, rng: np.random.Generator) -> np.ndarray:
    """theta=0 degenerate case: uniform over [0, n)."""
    return rng.integers(0, n, size, dtype=np.int64)


def expected_hit_ratio(n: int, theta: float, k: int) -> float:
    """Analytic Zipf(theta) CDF at rank ``k``: the probability that one
    sample over [0, n) lands in the hottest ``k`` ranks — i.e. the hit
    ratio a hot-key cache holding exactly the top-``k`` keys should
    measure (:mod:`sherman_tpu.models.leaf_cache`; published next to
    the measured ratio in the bench receipt's ``cache`` block).

    ``expected_hit_ratio(n, theta, k) = zeta(k, theta) / zeta(n, theta)``
    with the same partial harmonic sums the samplers invert; theta = 0
    degenerates to ``k / n``."""
    assert n >= 1 and 0.0 <= theta < 1.0
    k = max(0, min(int(k), int(n)))
    if k == 0:
        return 0.0
    if theta == 0.0:
        return k / n
    return _zeta(k, theta) / _zeta(n, theta)
