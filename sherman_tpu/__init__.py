"""sherman_tpu — a TPU-native disaggregated-memory B+Tree framework.

A from-scratch reimplementation of the capabilities of Sherman (SIGMOD'22, a
write-optimized distributed B+Tree on disaggregated memory over one-sided
RDMA; reference at /root/reference) designed TPU-first:

- The "disaggregated memory pool" is HBM sharded across a ``jax.sharding.Mesh``
  of TPU chips; the one-sided RDMA verb layer (reference ``src/rdma/``,
  ``include/DSM.h``) becomes :class:`sherman_tpu.parallel.dsm.DSM`, a batched
  SPMD transport whose READ/WRITE/CAS/FAA requests ride XLA ``all_to_all``
  collectives over ICI.
- The NIC on-chip lock words (reference ``Common.h:86-93``,
  ``DirectoryConnection.cpp:24-30``) become a per-chip lock table shard with
  per-step linearized CAS semantics.
- ``Tree::search/insert`` (reference ``src/Tree.cpp``) become *batched* device
  kernels: a batch of keys walks the tree level-by-level under ``jit`` inside
  ``shard_map``; coroutine latency-hiding (reference ``Tree.cpp:1059-1122``)
  is subsumed by batching.

See SURVEY.md for the full reference analysis this build follows.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # Compatibility shim for JAX < 0.6: the public ``jax.shard_map``
    # entry point (keyword-only, ``check_vma=``) is the experimental
    # ``shard_map`` (``check_rep=``).  Installed once at package import
    # so every call site can use the current public spelling.
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, *, mesh, in_specs, out_specs,
                          check_vma: bool = True, **kwargs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma,
                          **kwargs)

    _jax.shard_map = _compat_shard_map

from sherman_tpu.config import DSMConfig, TreeConfig  # noqa: E402

__version__ = "0.1.0"

__all__ = ["DSMConfig", "TreeConfig", "__version__"]
