"""sherman_tpu — a TPU-native disaggregated-memory B+Tree framework.

A from-scratch reimplementation of the capabilities of Sherman (SIGMOD'22, a
write-optimized distributed B+Tree on disaggregated memory over one-sided
RDMA; reference at /root/reference) designed TPU-first:

- The "disaggregated memory pool" is HBM sharded across a ``jax.sharding.Mesh``
  of TPU chips; the one-sided RDMA verb layer (reference ``src/rdma/``,
  ``include/DSM.h``) becomes :class:`sherman_tpu.parallel.dsm.DSM`, a batched
  SPMD transport whose READ/WRITE/CAS/FAA requests ride XLA ``all_to_all``
  collectives over ICI.
- The NIC on-chip lock words (reference ``Common.h:86-93``,
  ``DirectoryConnection.cpp:24-30``) become a per-chip lock table shard with
  per-step linearized CAS semantics.
- ``Tree::search/insert`` (reference ``src/Tree.cpp``) become *batched* device
  kernels: a batch of keys walks the tree level-by-level under ``jit`` inside
  ``shard_map``; coroutine latency-hiding (reference ``Tree.cpp:1059-1122``)
  is subsumed by batching.

See SURVEY.md for the full reference analysis this build follows.
"""

from sherman_tpu.config import DSMConfig, TreeConfig

__version__ = "0.1.0"

__all__ = ["DSMConfig", "TreeConfig", "__version__"]
