"""Nanosecond timer — ``include/Timer.h`` parity.

The reference wraps ``clock_gettime(CLOCK_REALTIME)`` with ``begin()`` /
``end(loop)`` / ``end_print(loop)`` (`Timer.h:12-43`) plus a spinning
``sleep`` helper (`Timer.h:45-53`).  Here ``time.perf_counter_ns`` is the
monotonic ns clock; the API shape is kept so drivers read the same.
"""

from __future__ import annotations

import time


class Timer:
    """begin/end ns timer; ``end(loop)`` returns ns amortized per loop."""

    def __init__(self):
        self._t0 = 0

    def begin(self) -> None:
        self._t0 = time.perf_counter_ns()

    def end(self, loop: int = 1) -> float:
        """Elapsed ns since ``begin``, divided by ``loop`` (Timer.h:24-33)."""
        return (time.perf_counter_ns() - self._t0) / max(loop, 1)

    def end_print(self, loop: int = 1, label: str = "") -> float:
        ns = self.end(loop)
        prefix = f"{label}: " if label else ""
        if ns >= 1e9:
            print(f"{prefix}{ns / 1e9:.3f} s")
        elif ns >= 1e6:
            print(f"{prefix}{ns / 1e6:.3f} ms")
        elif ns >= 1e3:
            print(f"{prefix}{ns / 1e3:.3f} us")
        else:
            print(f"{prefix}{ns:.0f} ns")
        return ns

    def end_us(self, loop: int = 1) -> float:
        return self.end(loop) / 1e3


def spin_sleep_ns(ns: int) -> None:
    """Busy-wait for ``ns`` nanoseconds (Timer.h:45-53 ``sleep``) — for
    sub-scheduler-quantum pacing in benchmark drivers."""
    end = time.perf_counter_ns() + ns
    while time.perf_counter_ns() < end:
        pass
