"""Leveled ANSI logging — ``include/Debug.h`` / ``src/Debug.cpp`` parity.

The reference exposes ``notifyInfo`` (green), ``notifyError`` (red) and a
compile-gated ``debugItem`` (yellow) (`Debug.h:15-38`, `Debug.cpp:57-83`).
Here the gate is a runtime level (env ``SHERMAN_LOG`` or :func:`set_level`)
instead of a macro — same three entry points, same colors.
"""

from __future__ import annotations

import os
import sys
import threading

ERROR, INFO, DEBUG = 0, 1, 2
_NAMES = {"error": ERROR, "info": INFO, "debug": DEBUG}

_level = _NAMES.get(os.environ.get("SHERMAN_LOG", "info").lower(), INFO)
_lock = threading.Lock()

_RED = "\x1b[31m"
_GREEN = "\x1b[32m"
_YELLOW = "\x1b[33m"
_RESET = "\x1b[0m"


def set_level(level: int | str) -> None:
    global _level
    _level = _NAMES[level.lower()] if isinstance(level, str) else int(level)


def _emit(color: str, msg: str, file) -> None:
    if not file.isatty():
        color, reset = "", ""
    else:
        reset = _RESET
    with _lock:
        print(f"{color}{msg}{reset}", file=file, flush=True)


def notify_info(fmt: str, *args) -> None:
    if _level >= INFO:
        _emit(_GREEN, fmt % args if args else fmt, sys.stdout)


def notify_error(fmt: str, *args) -> None:
    _emit(_RED, fmt % args if args else fmt, sys.stderr)


def debug_item(fmt: str, *args) -> None:
    if _level >= DEBUG:
        _emit(_YELLOW, fmt % args if args else fmt, sys.stdout)
