"""Write-ahead op journal — the replayable half of the recovery plane.

The reference has no durability story at all (SURVEY.md §5); our
checkpoints (``utils/checkpoint.py``) bound the loss window to
"everything since the last save".  This module closes that window: an
append-only journal of CRC-framed, length-prefixed **batch records**,
one per acknowledged engine write op (op kind + the rows it actually
applied), fsync'd before the op is acknowledged.  Recovery is then

    restore checkpoint chain  +  replay journal in record order

and the loss of *acknowledged* ops (RPO) is zero: an op is acked only
after its record is durable, and replay re-applies records onto the
restored pool.  Replay is convergent because the engine's write ops are
idempotent in-order (insert is an upsert — last writer per key wins;
delete clears; re-running a prefix that already landed re-produces the
same state), so segments may safely be replayed from any checkpoint at
or before their first record.

Frame format (little-endian, after the 8-byte file magic)::

    [u32 length | u32 crc32(payload) | payload]
    payload = u8 kind | u8 flags | u8 x 2 pad | u32 nrows
              (| rid u64 when flags & FLAG_RID)
              | keys u64[n] (| vals u64[n])

Format v2 (magic ``SHJRNL02``, PR 15): the second header byte is a
FLAGS field; ``FLAG_RID`` marks a client request id (u64) riding the
record — the exactly-once plane's join key (``sherman_tpu/serve.py``
dedup window).  v1 segments (``SHJRNL01``) wrote that byte as zero
pad, and readers decode them with flags forced to 0: old journals
replay cleanly, just with no request ids — dedup is DISABLED for
those segments (the client-contract back-compat rule).  Appends to a
v1 segment keep writing v1 records (rid silently dropped, ack records
refused as no-ops) so one segment never mixes formats.

Ack records (``J_ACK``, v2 only): one frame carrying the CACHED
RESULTS of a batch of client write requests — per ack ``(rid, tenant,
op kind, ok-per-key bitmap)`` — appended by the serving front door
after the engine batch record and BEFORE any future resolves (the
same durability gate).  Replay hands them to ``ack_sink`` so
``RecoveryPlane.recover`` reconstructs the exactly-once dedup window:
a write retried across a cold crash re-acks its ORIGINAL result
instead of re-applying.

Torn-tail contract (crash mid-append): a frame that runs past EOF, or
whose CRC fails **at the very tail**, is a partially flushed append —
readers truncate it away (``journal.truncated_tails``) and the journal
stays usable.  A CRC failure with more bytes *after* the frame is
content corruption, not a torn append: readers raise the typed
:class:`JournalCorruptError` — a corrupt journal must never silently
replay wrong rows (``tests/test_fuzz.py`` storms both cases).

Group commit (``Journal(sync=True, group_commit_ms=...)``): bounded-
delay batched acks — multiple ops' records coalesce into one fsync
before ANY of their acks release, so RPO stays zero by construction
while the fsync cost amortizes across the group (the prerequisite for
a pipelined or multi-client write path, which a per-op fsync would
re-serialize).  See the :class:`Journal` docstring for the
leader/follower protocol and the measured ack-latency tradeoff.

Observability: ``journal.appends`` / ``journal.rows`` /
``journal.bytes`` / ``journal.fsyncs`` (real fsyncs — under group
commit ``appends/fsyncs`` is the coalescing ratio) /
``journal.truncated_tails`` / ``journal.replayed_records`` /
``journal.replayed_rows``.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

import numpy as np

from sherman_tpu import obs
from sherman_tpu.errors import ConfigError, ShermanError, StateError

MAGIC = b"SHJRNL02"      # format v2: flags byte + optional request id
MAGIC_V1 = b"SHJRNL01"   # format v1: no flags (decoded with flags=0)
_HDR = struct.Struct("<II")          # length, crc32(payload)
_PAY = struct.Struct("<BBxxI")       # kind, flags, nrows
_ACK = struct.Struct("<QBBH")        # rid, op kind, tenant len, n_ops
_RID = struct.Struct("<Q")

FLAG_RID = 1     # payload carries a client request id after the header

# ack-entry op-byte flag (PR 16): the entry carries payload PROVENANCE
# — one u64 handle per op (slab address + slab version packed by
# models/value_heap.pack_handles; 0 = no provenance for that op) after
# the ok bitmap.  Old readers never see it (old records never set the
# bit) and old records decode unchanged (4-tuples), so the wire format
# stays back-compatible in both directions.
ACK_PROV = 0x80

J_UPSERT = 1     # keys + values (engine insert / mixed write rows)
J_DELETE = 2     # keys only
J_HEAP_PUT = 3   # value-heap slab writes: keys + handles + payload blob
J_HEAP_FREE = 4  # value-heap slab frees: keys + handles
J_ACK = 5        # client-contract ack batch: (rid, tenant, op, ok bits)
KINDS = (J_UPSERT, J_DELETE, J_HEAP_PUT, J_HEAP_FREE, J_ACK)
# kinds whose payload is keys + one u64 value lane (shared layout)
_TWO_LANE = (J_UPSERT, J_HEAP_FREE)

# One frame is one engine-op batch; anything claiming more than this is
# a corrupt length word, not a real record (the engine chunks batches
# far below it).
MAX_PAYLOAD = 1 << 30

_OBS_APPENDS = obs.counter("journal.appends")
_OBS_ROWS = obs.counter("journal.rows")
_OBS_BYTES = obs.counter("journal.bytes")
_OBS_FSYNCS = obs.counter("journal.fsyncs")
_OBS_TORN = obs.counter("journal.truncated_tails")
_OBS_RP_RECORDS = obs.counter("journal.replayed_records")
_OBS_RP_ROWS = obs.counter("journal.replayed_rows")

# indirection for tests (monkeypatching os.fsync itself would also
# intercept numpy/jax internals)
_fsync = os.fsync


class JournalCorruptError(ShermanError, RuntimeError):
    """A journal frame failed its CRC (or framing) with further bytes
    following it — content corruption, not a torn tail.  Replay refuses
    rather than applying rows it cannot trust."""


class JournalSyncError(ShermanError, RuntimeError):
    """An fsync on this journal failed, poisoning it: on Linux a failed
    fsync CONSUMES the writeback error and may drop the dirty pages, so
    a retried fsync on the same fd can return success without the
    records ever reaching disk — releasing an ack on that retry would
    be silent RPO > 0.  Every append after the failure raises this
    (chained to the original OSError); rotate to a fresh segment
    (``RecoveryPlane._rotate_journal``) to resume."""


def encode_record(kind: int, keys, values=None, rid=None) -> bytes:
    """One framed record (header + payload) for ``append``/tests.
    ``rid`` (optional client request id, u64) rides the v2 flags
    field — see the module docstring's exactly-once contract."""
    if kind not in KINDS or kind in (J_HEAP_PUT, J_ACK):
        raise ConfigError(f"unknown journal record kind {kind}"
                          if kind not in (J_HEAP_PUT, J_ACK) else
                          "J_HEAP_PUT/J_ACK records have their own "
                          "encoders: encode_heap_record / "
                          "encode_ack_record")
    keys = np.ascontiguousarray(keys, np.uint64)
    flags = 0 if rid is None else FLAG_RID
    payload = _PAY.pack(kind, flags, keys.size)
    if rid is not None:
        payload += _RID.pack(int(rid) & 0xFFFFFFFFFFFFFFFF)
    payload += keys.tobytes()
    if kind in _TWO_LANE:
        values = np.ascontiguousarray(values, np.uint64)
        if values.shape != keys.shape:
            raise ConfigError("journal upsert needs one value per key")
        payload += values.tobytes()
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def encode_ack_record(acks) -> bytes:
    """One framed ack-batch record: ``acks`` is a sequence of
    ``(rid, tenant, op_kind, ok)`` with ``ok`` a bool array (one bit
    per submitted op of the ORIGINAL request), optionally extended to
    ``(rid, tenant, op_kind, ok, handles)`` where ``handles`` (u64,
    one per op; 0 = none) is payload provenance for heap writes — the
    slab address + version the acked payload landed at (flagged with
    :data:`ACK_PROV` in the op byte; see the flag's comment).  One
    frame covers every client write a flush coalesced, so the
    exactly-once plane costs one extra append (not one per request)
    per write batch."""
    n = len(acks)
    if n == 0 or n > 0xFFFFFFFF:
        raise ConfigError(f"ack record wants 1..2^32-1 acks, got {n}")
    payload = _PAY.pack(J_ACK, 0, n)
    for entry in acks:
        rid, tenant, op, ok = entry[:4]
        handles = entry[4] if len(entry) > 4 else None
        tb = str(tenant).encode("utf-8")
        if len(tb) > 255:
            raise ConfigError(f"tenant name over 255 bytes: {tenant!r}")
        ok = np.ascontiguousarray(ok, bool)
        if ok.size > 0xFFFF:
            raise ConfigError(
                f"ack result of {ok.size} ops exceeds the u16 bound")
        if op not in (J_UPSERT, J_DELETE, J_HEAP_PUT):
            raise ConfigError(f"ack op kind {op}: want a write kind")
        opb = int(op)
        hb = b""
        if handles is not None:
            handles = np.ascontiguousarray(handles, np.uint64)
            if handles.shape != ok.shape:
                raise ConfigError(
                    "ack provenance wants one handle per op")
            opb |= ACK_PROV
            hb = handles.tobytes()
        payload += _ACK.pack(int(rid) & 0xFFFFFFFFFFFFFFFF, opb,
                             len(tb), ok.size)
        payload += tb + np.packbits(ok).tobytes() + hb
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def _decode_acks(body: bytes, n: int, off: int):
    """-> [(rid, tenant, op_kind, ok bool[n_ops]), ...] — entries
    flagged :data:`ACK_PROV` come back as 5-tuples with a trailing
    ``handles`` u64[n_ops] provenance lane (star-unpack tolerant:
    ``rid, tenant, op, ok, *prov = entry``)."""
    out = []
    pos = 0
    for _ in range(n):
        if pos + _ACK.size > len(body):
            raise JournalCorruptError(
                f"journal record at byte {off}: ack batch overruns "
                "its body")
        rid, op, tlen, nops = _ACK.unpack_from(body, pos)
        pos += _ACK.size
        prov = bool(op & ACK_PROV)
        op &= ~ACK_PROV
        nbytes = (nops + 7) // 8 + (nops * 8 if prov else 0)
        if pos + tlen + nbytes > len(body):
            raise JournalCorruptError(
                f"journal record at byte {off}: ack entry overruns "
                "its body")
        tenant = body[pos: pos + tlen].decode("utf-8")
        pos += tlen
        nok = (nops + 7) // 8
        ok = np.unpackbits(
            np.frombuffer(body[pos: pos + nok], np.uint8),
            count=nops).astype(bool)
        pos += nok
        if prov:
            handles = np.frombuffer(
                body[pos: pos + nops * 8], np.uint64).copy()
            pos += nops * 8
            out.append((int(rid), tenant, int(op), ok, handles))
        else:
            out.append((int(rid), tenant, int(op), ok))
    if pos != len(body):
        raise JournalCorruptError(
            f"journal record at byte {off}: {len(body) - pos} trailing "
            "bytes after the last ack")
    return out


def encode_heap_record(kind: int, keys, handles, payloads) -> bytes:
    """Value-heap put record: keys + handles + per-key byte lengths +
    concatenated payload blob (``payloads``: list of bytes).  The
    handle encodes the slab address, so replay rewrites every payload
    AT its recorded slab — bit-identical heap content after
    restore+replay."""
    if kind != J_HEAP_PUT:
        raise ConfigError(f"encode_heap_record wants J_HEAP_PUT, "
                          f"got {kind}")
    keys = np.ascontiguousarray(keys, np.uint64)
    handles = np.ascontiguousarray(handles, np.uint64)
    if handles.shape != keys.shape or len(payloads) != keys.size:
        raise ConfigError("heap record needs one handle+payload per key")
    lens = np.asarray([len(b) for b in payloads], np.uint32)
    blob = b"".join(bytes(b) for b in payloads)
    payload = (_PAY.pack(kind, 0, keys.size) + keys.tobytes()
               + handles.tobytes() + lens.tobytes() + blob)
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def _decode_payload(payload: bytes, off: int, fmt: int = 2):
    """payload bytes -> (kind, keys, aux, rid); raises on bad shape.
    ``aux`` is the value lane (u64, or None for J_DELETE), except
    J_HEAP_PUT where it is ``(handles u64[n], payloads list[bytes])``
    and J_ACK where ``keys`` is None and ``aux`` the decoded ack list.
    ``fmt`` is the segment format (1 = pre-rid: the flags byte was pad,
    decoded as 0 — dedup disabled for that segment)."""
    kind, flags, n = _PAY.unpack_from(payload)
    if fmt < 2:
        flags = 0
    rid = None
    body = payload[_PAY.size:]
    if flags & FLAG_RID:
        if len(body) < _RID.size:
            raise JournalCorruptError(
                f"journal record at byte {off}: rid flag with no rid")
        rid = _RID.unpack_from(body)[0]
        body = body[_RID.size:]
    if kind == J_ACK:
        return kind, None, _decode_acks(body, n, off), rid
    if kind == J_HEAP_PUT:
        fixed = n * 8 * 2 + n * 4
        if len(body) < fixed:
            raise JournalCorruptError(
                f"journal record at byte {off}: heap-put nrows={n} "
                f"does not fit its {len(body)}-byte body")
        keys = np.frombuffer(body[: n * 8], np.uint64).copy()
        handles = np.frombuffer(body[n * 8: n * 16], np.uint64).copy()
        lens = np.frombuffer(body[n * 16: fixed], np.uint32)
        blob = body[fixed:]
        if int(lens.sum()) != len(blob):
            raise JournalCorruptError(
                f"journal record at byte {off}: heap-put blob length "
                f"{len(blob)} does not match its length table")
        payloads = []
        pos = 0
        for ln in lens.tolist():
            payloads.append(blob[pos: pos + ln])
            pos += ln
        return kind, keys, (handles, payloads), rid
    want = n * 8 * (2 if kind in _TWO_LANE else 1)
    if kind not in KINDS or len(body) != want:
        raise JournalCorruptError(
            f"journal record at byte {off}: kind={kind} nrows={n} does "
            f"not match its {len(body)}-byte body")
    keys = np.frombuffer(body[: n * 8], np.uint64).copy()
    vals = (np.frombuffer(body[n * 8:], np.uint64).copy()
            if kind in _TWO_LANE else None)
    return kind, keys, vals, rid


class Journal:
    """Appender for one journal segment file.

    ``sync=True`` (default) makes every append durable before it
    returns — the RPO-zero contract; ``sync=False`` trades durability
    of the last few records for throughput (still torn-tail-safe).
    Thread-safe appends; one writer process per file.

    **Group commit** (``group_commit_ms > 0``, with ``sync=True``):
    bounded-delay batched acks.  An append still BLOCKS until an fsync
    covers its record — RPO zero holds by construction — but instead
    of one fsync per record, the first committer of a group becomes
    the LEADER: it holds the commit open for up to ``group_commit_ms``
    so concurrent appends can join (their records land in the OS file
    during the window), then issues ONE fsync covering everything
    written and releases every joined ack at once.  A per-op fsync
    re-serializes any pipelined or multi-client write path on the
    fsync latency; group commit amortizes it at the cost of up to
    ``group_commit_ms`` of added ack latency — the measured tradeoff
    is published by ``tools/ckpt_bench.py`` (acks/s, added ack
    latency, acks per fsync) and the recovery drill pins RPO 0 with
    the knob on.  ``journal.fsyncs`` counts REAL fsyncs, so
    ``journal.appends / journal.fsyncs`` is the measured coalescing
    ratio.

    The window only opens UNDER CONTENTION: a leader with no other
    appender in flight (tracked at ``append`` entry) skips the wait
    entirely, so a lone writer pays per-op-fsync latency — not
    ``group_commit_ms`` per ack — while concurrent writers always get
    the full window to coalesce into.

    Failure contract: a raising fsync POISONS the journal (see
    :class:`JournalSyncError`) — the failed append raises, every
    parked follower raises, and every later append raises until a
    fresh segment is opened.  Retrying the fsync instead would be
    unsound: Linux reports a writeback error to ONE fsync caller and
    may drop the dirty pages, so the retry can spuriously succeed
    over records that never hit disk.
    """

    def __init__(self, path: str, sync: bool = True,
                 group_commit_ms: float = 0.0):
        self.path = path
        self.sync = bool(sync)
        self.group_commit_ms = float(group_commit_ms)
        self._lock = threading.Lock()
        # per-INSTANCE accounting (the obs counters above are process-
        # wide totals): the serving front door's receipt needs THIS
        # segment's appends/fsyncs to publish its acks-per-fsync
        # coalescing ratio (tools/serve_bench.py)
        self.appends = 0
        self.rows = 0
        self.fsyncs = 0
        # group-commit state (guarded by _lock via the condition):
        # records are sequenced as they hit the OS file; an ack may
        # only release once _synced_seq covers its sequence number
        self._commit_cv = threading.Condition(self._lock)
        self._written_seq = 0
        self._synced_seq = 0
        self._leader = False
        self._failed: BaseException | None = None  # fsync poison
        # appenders currently inside append() (own lock: counted at
        # ENTRY, before the main lock, so writers blocked on it still
        # register) — a leader holds the commit window open only when
        # this shows company; a lone writer fsyncs immediately
        self._entrants = 0
        self._entrants_lock = threading.Lock()
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        # format pinning: a fresh segment is v2; appending to an
        # existing segment keeps ITS format (one segment never mixes —
        # a v1 segment's appends stay rid-less and ack records are
        # refused as no-ops: dedup disabled for that segment, the
        # back-compat contract)
        self.format = 2
        if not fresh:
            with open(path, "rb") as rf:
                head = rf.read(len(MAGIC))
            if head == MAGIC_V1:
                self.format = 1
        self._f = open(path, "ab")
        # host-memory accountant source (obs/device.py): the live
        # segment's on-disk bytes as ``device.host_journal_bytes``.
        # Re-registering under the one name is the rotation contract —
        # a fresh segment supersedes its ancestor's gauge.
        import weakref

        from sherman_tpu.obs import device as _dev
        _ref = weakref.ref(self)
        _dev.get_accountant().register(
            "journal", (lambda r=_ref: (
                os.path.getsize(r().path)
                if r() is not None and os.path.exists(r().path) else 0)),
            kind="host")
        if fresh:
            self._f.write(MAGIC)
            self._f.flush()
            if self.sync:
                _fsync(self._f.fileno())
                # make the DIRECTORY ENTRY durable too: records fsync'd
                # into a file whose name is lost to power failure are
                # RPO > 0 that recovery cannot even detect
                dfd = os.open(os.path.dirname(os.path.abspath(path)),
                              os.O_RDONLY)
                try:
                    _fsync(dfd)
                finally:
                    os.close(dfd)

    def append(self, kind: int, keys, values=None, rid=None) -> int:
        """Append one batch record; returns bytes written.  Durable on
        return when ``sync`` (the ack gate for RPO zero) — via one
        fsync per record, or one fsync per group under
        ``group_commit_ms``.  ``rid`` tags the record with a client
        request id (v2 segments; silently dropped on a v1 segment —
        dedup disabled there by the back-compat contract)."""
        keys = np.ascontiguousarray(keys, np.uint64)
        if keys.size == 0:
            return 0  # nothing applied: no record
        rec = encode_record(kind, keys, values,
                            rid=rid if self.format >= 2 else None)
        return self._append_rec(rec, int(keys.size))

    def append_acks(self, acks) -> int:
        """Append one client-contract ack-batch record (see
        :func:`encode_ack_record`) under the same durability gate as
        :meth:`append` — the front door calls this AFTER the engine
        batch record and BEFORE resolving any of the batch's futures,
        so a crash can lose an unacked result but never an acked one.
        No-op (returns 0) on an empty batch or a v1 segment."""
        if not acks or self.format < 2:
            return 0
        rec = encode_ack_record(acks)
        return self._append_rec(rec, len(acks))

    def sync_now(self) -> None:
        """Push a covering fsync for everything appended so far — the
        graceful-drain epilogue (``ShermanServer.drain``).  Redundant
        under ``sync=True`` (every ack already gated on a covering
        fsync); for a ``sync=False`` journal it is the only flush."""
        with self._lock:
            if self._f.closed:
                return
            if self._failed is not None:
                raise JournalSyncError(
                    f"journal {self.path} poisoned by an earlier fsync "
                    "failure; rotate to a fresh segment") from self._failed
            self._f.flush()
            _fsync(self._f.fileno())
            self._synced_seq = self._written_seq
            _OBS_FSYNCS.inc()
            self.fsyncs += 1

    def append_heap(self, kind: int, keys, handles, payloads) -> int:
        """Append one value-heap batch record (keys + handles + payload
        bytes; see :func:`encode_heap_record`) under the same
        durability/group-commit contract as :meth:`append`."""
        keys = np.ascontiguousarray(keys, np.uint64)
        if keys.size == 0:
            return 0
        rec = encode_heap_record(kind, keys, handles, payloads)
        return self._append_rec(rec, int(keys.size))

    def _append_rec(self, rec: bytes, nrows: int) -> int:
        with self._entrants_lock:
            self._entrants += 1
        try:
            with self._lock:
                if self._f.closed:
                    raise StateError(f"journal {self.path} is closed")
                if self._failed is not None:
                    raise JournalSyncError(
                        f"journal {self.path} poisoned by an earlier "
                        "fsync failure; rotate to a fresh segment") \
                        from self._failed
                self._f.write(rec)
                self._f.flush()
                self._written_seq += 1
                seq = self._written_seq
                # per-instance receipt counters under the SAME lock as
                # the sequence they describe (concurrent group-commit
                # appenders would otherwise lose increments)
                self.appends += 1
                self.rows += nrows
                if self.sync and self.group_commit_ms <= 0:
                    try:
                        _fsync(self._f.fileno())
                    except BaseException as e:
                        self._failed = e
                        obs.record_event("journal.poisoned",
                                         path=self.path, error=repr(e))
                        raise
                    self._synced_seq = seq
                    _OBS_FSYNCS.inc()
                    self.fsyncs += 1
            if self.sync and self.group_commit_ms > 0:
                self._commit(seq)
        finally:
            with self._entrants_lock:
                self._entrants -= 1
        _OBS_APPENDS.inc()
        _OBS_ROWS.inc(nrows)
        _OBS_BYTES.inc(len(rec))
        return len(rec)

    def _commit(self, seq: int) -> None:
        """Block until an fsync covers record ``seq`` (leader/follower
        group commit; see the class docstring)."""
        with self._commit_cv:
            while self._synced_seq < seq:
                if self._failed is not None:
                    # a leader's fsync failed after our record was
                    # written: the kernel may have dropped our dirty
                    # pages and consumed the error, so NO retry can
                    # prove durability — raise, never ack
                    raise JournalSyncError(
                        f"journal {self.path} poisoned by an fsync "
                        "failure; this record is NOT durable") \
                        from self._failed
                if self._leader:
                    # a leader's commit is in flight: its fsync will
                    # cover this record iff it was written before the
                    # leader snapshots; either way the notify wakes us
                    self._commit_cv.wait(1.0)
                    continue
                self._leader = True
                if self._entrants > 1:
                    # the commit window: release the lock so concurrent
                    # appends can land and join this group.  Skipped
                    # when no other appender is in flight — a lone
                    # writer must not pay the window per ack for
                    # coalescing that cannot happen.
                    self._commit_cv.wait(self.group_commit_ms / 1e3)
                cover = self._written_seq
                try:
                    if not self._f.closed:
                        try:
                            _fsync(self._f.fileno())
                        except BaseException as e:
                            # advance NOTHING and poison: a raising
                            # fsync must not release any follower's
                            # ack, now or via a spuriously-succeeding
                            # retry (silent RPO > 0 — the exact loss
                            # the per-op path cannot produce)
                            self._failed = e
                            obs.record_event("journal.poisoned",
                                             path=self.path,
                                             error=repr(e),
                                             group_commit=True)
                            raise
                        _OBS_FSYNCS.inc()
                        self.fsyncs += 1
                    self._synced_seq = max(self._synced_seq, cover)
                finally:
                    self._leader = False
                    self._commit_cv.notify_all()

    def stats(self) -> dict:
        """Per-instance accounting snapshot: {appends, rows, fsyncs,
        appends_per_fsync} — the front door's durability receipt
        (each append covers one engine batch record, so client write
        acks per fsync = acked requests / fsyncs on the caller's
        side)."""
        return {
            "appends": self.appends,
            "rows": self.rows,
            "fsyncs": self.fsyncs,
            "appends_per_fsync": (self.appends / self.fsyncs
                                  if self.fsyncs else None),
        }

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                if self.sync and self._failed is None:
                    # a poisoned journal skips the final fsync: it
                    # could spuriously succeed over dropped pages, and
                    # parked followers raise off _failed regardless
                    _fsync(self._f.fileno())
                    # release any followers parked on the condition:
                    # the final fsync covered everything written
                    self._synced_seq = self._written_seq
                self._f.close()
            self._commit_cv.notify_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str, truncate_torn: bool = False,
                 with_rids: bool = False) -> list[tuple]:
    """Parse a segment -> [(kind, keys, values|None), ...] — or
    4-tuples ``(kind, keys, values, rid)`` when ``with_rids`` (the
    exactly-once consumers; rid is None on v1 segments and untagged
    records).

    Applies the torn-tail contract (see module docstring):
    partially-appended tail frames are dropped (and physically truncated
    from the file when ``truncate_torn`` — recovery does this so the
    next appender starts from a clean frame boundary); mid-file
    corruption raises :class:`JournalCorruptError`.
    """
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(MAGIC):
        # a file torn inside the magic itself: an append never succeeded
        _truncate(path, 0, len(blob), truncate_torn)
        return []
    if blob[: len(MAGIC)] == MAGIC:
        fmt = 2
    elif blob[: len(MAGIC)] == MAGIC_V1:
        fmt = 1  # pre-rid segment: flags byte decodes as 0
    else:
        raise JournalCorruptError(
            f"{path}: bad journal magic {blob[:8]!r}")
    out: list[tuple] = []
    off = len(MAGIC)
    size = len(blob)
    while off < size:
        if off + _HDR.size > size:
            _truncate(path, off, size, truncate_torn)  # torn header
            break
        length, crc = _HDR.unpack_from(blob, off)
        end = off + _HDR.size + length
        if length > MAX_PAYLOAD:
            if end > size or end < 0:
                # the claimed frame runs past EOF: equally consistent
                # with a torn length word — tail rule applies
                _truncate(path, off, size, truncate_torn)
                break
            raise JournalCorruptError(
                f"{path}: frame at byte {off} claims {length} bytes "
                f"(> {MAX_PAYLOAD}) with bytes following")
        if end > size:
            _truncate(path, off, size, truncate_torn)  # torn payload
            break
        payload = blob[off + _HDR.size: end]
        if zlib.crc32(payload) != crc:
            if end == size:
                # tail frame with bad CRC: torn append (length landed,
                # payload only partially)
                _truncate(path, off, size, truncate_torn)
                break
            raise JournalCorruptError(
                f"{path}: CRC mismatch at byte {off} with "
                f"{size - end} bytes following — content corruption, "
                "refusing to replay")
        row = _decode_payload(payload, off, fmt)
        out.append(row if with_rids else row[:3])
        off = end
    return out


def read_acks(path: str) -> dict:
    """Reconstruct one segment's exactly-once window:
    ``{(tenant, rid): entry}`` over every J_ACK record, in ack order
    (later entries override earlier — the front door's own last-writer
    window semantics).  Entries are the raw ack tuples
    ``(rid, tenant, op, ok[, handles])`` — provenance-bearing heap
    entries (5-tuples, PR 16) carry through whole so re-encoding
    preserves the handles.  Shared by journal rotation's ack
    carry-forward (``RecoveryPlane._rotate_journal``) and the
    multihost drill's merged acked-op ledger (one call per host
    segment, dict-union across hosts — disjoint by the router's
    key-partition, PR 19)."""
    window: dict = {}
    for kind, _keys, aux in read_records(path):
        if kind == J_ACK:
            for entry in aux:
                window[(entry[1], entry[0])] = entry
    return window


def crc_of_range(path: str, start: int, end: int) -> int:
    """CRC32 of the raw segment bytes ``[start, end)`` — the anti-
    entropy audit's ground truth.  A follower's tailer accumulates the
    same rolling CRC over every byte it CONSUMED; re-reading the range
    from the primary's file must reproduce it exactly, or the follower
    applied bytes the chain never shipped (divergence, not lag)."""
    with open(path, "rb") as f:
        f.seek(max(0, int(start)))
        crc = 0
        left = int(end) - int(start)
        while left > 0:
            chunk = f.read(min(left, 1 << 20))
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            left -= len(chunk)
    return crc


def frame_blob(payload: bytes) -> bytes:
    """One journal-framed blob: ``[u32 len | u32 crc32 | payload]`` —
    the same ``_HDR`` frame every segment record rides, reused by the
    host-failure plane's durable control records (lease heartbeats and
    the ownership map, ``sherman_tpu/hostlease.py``) so their
    corruption discipline is the journal's own."""
    payload = bytes(payload)
    if len(payload) > MAX_PAYLOAD:
        raise ConfigError(f"frame payload {len(payload)} B > MAX_PAYLOAD")
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def unframe_blob(blob: bytes) -> bytes:
    """Decode exactly one :func:`frame_blob` frame -> payload.  Raises
    :class:`JournalCorruptError` on a short header, a length that
    disagrees with the blob, or a CRC mismatch — torn and corrupt
    records are the same typed refusal."""
    if len(blob) < _HDR.size:
        raise JournalCorruptError(
            f"framed blob of {len(blob)} B is shorter than the header")
    length, crc = _HDR.unpack_from(blob, 0)
    end = _HDR.size + length
    if length > MAX_PAYLOAD or end > len(blob):
        raise JournalCorruptError(
            f"framed blob claims {length} B payload with "
            f"{len(blob) - _HDR.size} B present — torn record")
    payload = blob[_HDR.size:end]
    if zlib.crc32(payload) != crc:
        raise JournalCorruptError("framed blob CRC mismatch — content "
                                  "corruption, refusing to decode")
    return payload


def iter_frames(blob: bytes):
    """Walk consecutive :func:`frame_blob` frames -> (payloads, clean):
    every CRC-valid complete frame from the front, stopping at the
    first torn/invalid frame; ``clean`` is True when the walk consumed
    the whole blob.  The append-only control-log reader (ownership map
    adoptions survive an adopter crash mid-append by truncating at the
    last clean frame, exactly the journal's torn-tail rule)."""
    out = []
    pos = 0
    size = len(blob)
    while pos + _HDR.size <= size:
        length, crc = _HDR.unpack_from(blob, pos)
        end = pos + _HDR.size + length
        if length > MAX_PAYLOAD or end > size:
            break
        payload = blob[pos + _HDR.size:end]
        if zlib.crc32(payload) != crc:
            break
        out.append(payload)
        pos = end
    return out, pos == size


def _truncate(path: str, off: int, size: int, do_truncate: bool) -> None:
    _OBS_TORN.inc()
    obs.record_event("journal.torn_tail", path=path, at_byte=off,
                     dropped_bytes=size - off, truncated=do_truncate)
    # a file torn inside the magic itself keeps nothing (a fresh
    # appender then rewrites the magic); otherwise cut at the last
    # clean frame boundary
    keep = off if off >= len(MAGIC) else 0
    if do_truncate and size > keep:
        with open(path, "r+b") as f:
            f.truncate(keep)
            f.flush()
            _fsync(f.fileno())


def apply_records(records, eng, ack_sink=None, stats=None) -> dict:
    """Apply decoded journal records through a (writable) engine, in
    record order — the SHARED apply core of recovery replay
    (:func:`replay` / ``RecoveryPlane.recover``) and the replication
    followers (``sherman_tpu/replica.py``): both planes converge on
    this one dispatch loop, so a follower applies a shipped segment
    exactly the way recovery would replay it, by construction.

    ``records`` is any iterable of decoded tuples — 3-tuples
    ``(kind, keys, aux)`` or the ``with_rids`` 4-tuples; extra
    elements are ignored.  The engine's own journaling must be
    detached by the caller (RecoveryPlane and followers both do) so
    applying does not re-journal.  ``ack_sink`` (a list) collects
    J_ACK entries in record order — the dedup-window reconstruction
    feed; with no sink they are counted and skipped.  ``stats`` (an
    existing dict) accumulates in place across calls — the follower's
    incremental tail applies batches as they ship.  Returns the stats
    dict {"records", "rows", "upserts", "deletes", "heap_puts",
    "heap_frees", "acks"}."""
    if stats is None:
        stats = {}
    for k in ("records", "rows", "upserts", "deletes", "heap_puts",
              "heap_frees", "acks"):
        stats.setdefault(k, 0)
    for rec in records:
        kind, keys, vals = rec[0], rec[1], rec[2]
        if kind == J_ACK:
            # contract plane: cached client results, no engine state —
            # replayed into the dedup window, never applied
            if ack_sink is not None:
                ack_sink.extend(vals)
            stats["acks"] += len(vals)
            stats["records"] += 1
            _OBS_RP_RECORDS.inc()
            continue
        if kind in (J_HEAP_PUT, J_HEAP_FREE):
            # value-heap records (models/value_heap.py): slab rewrites
            # at their RECORDED addresses — the engine must carry an
            # attached heap, or replay cannot honor the record
            heap = getattr(eng, "value_heap", None)
            if heap is None:
                raise StateError(
                    "journal carries value-heap records but the engine "
                    "has no attached ValueHeap (attach_value_heap "
                    "before replay)")
            if kind == J_HEAP_PUT:
                handles, payloads = vals
                heap.replay_put(keys, handles, payloads)
                stats["heap_puts"] += 1
            else:
                heap.replay_free(keys, vals)
                stats["heap_frees"] += 1
        elif kind == J_UPSERT:
            eng.insert(keys, vals)
            stats["upserts"] += 1
        else:
            eng.delete(keys)
            stats["deletes"] += 1
        stats["records"] += 1
        stats["rows"] += int(keys.size)
        _OBS_RP_RECORDS.inc()
        _OBS_RP_ROWS.inc(int(keys.size))
    return stats


def replay(path: str, eng, ack_sink=None) -> dict:
    """Re-apply one segment's records through a (writable) engine, in
    record order — :func:`read_records` (torn tails truncated, the
    recovery contract) fed through the shared :func:`apply_records`
    core.  See ``apply_records`` for the sink/stats semantics."""
    return apply_records(read_records(path, truncate_torn=True), eng,
                         ack_sink=ack_sink)
