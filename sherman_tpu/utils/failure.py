"""Failure detection + fail-fast guards (beyond-reference subsystem).

The reference has NO failure handling (SURVEY.md §5): cluster membership
is join-only monotonic (``Keeper.cpp:87-113``), verb errors print and
``sleep(5)`` (``Operation.cpp:13-25``), and a dead peer leaves every
other node spinning forever inside a memcached barrier or a CQ poll —
"failed nodes hang the system".  This module gives the TPU build a
crash-only failure story instead:

Two distinct failure classes, two detectors:

  peer DEATH   (process gone, heartbeats stop) — detected by the
               coordination service's heartbeat tracking: every
               survivor is TERMINATED with a diagnostic ("another task
               died") within ``heartbeat_timeout_s`` of the death
               (``bootstrap.init_multihost`` exposes the knob; jax
               default 100 s) instead of hanging in its next
               collective.  Termination, not an exception: the error
               poller fires from a C++ thread, so death detection is
               crash-only BY DESIGN — which is sound here, because
               device steps are atomic (a step either completed or the
               process died with it; there is no partial-step state).
  peer STALL   (process alive — heartbeats fine — but stuck: deadlock,
               livelock, wedged I/O) — heartbeats cannot see this.
               ``DistributedKeeper.barrier(name, timeout_s=...)`` bounds
               the wait and raises a catchable :class:`PeerFailure`
               naming the peers that never arrived, letting the
               survivor choose: keep serving reads, retry, or exit.
               (If those peers were in fact dead, heartbeat detection
               terminates this process moments later — so a PeerFailure
               the program gets to HANDLE means the peers are alive.)
  fail fast    :class:`Watchdog` — a host-side deadline around any
               blocking section (device-step sync, collective
               checkpoint).  A wedged XLA collective cannot be
               cancelled from Python, so on expiry the watchdog dumps
               diagnostics and exits the process rather than hanging
               the job; the launcher restarts it.
  PREEMPTION   (eviction SIGTERM with notice — the one failure you see
               coming) — :class:`PreemptionGuard`: the notice on any
               one host flips ``should_act(step)`` on EVERY host at
               the same step, so the cluster checkpoints collectively
               at a clean boundary and exits instead of becoming a
               peer-death event seconds later.
  recover      relaunch + ``utils.checkpoint.restore``: collective
               checkpoints are atomic, nonce-tagged and
               epoch-validated, so the relaunched cluster resumes from
               the last completed checkpoint.

The end-to-end drills (peer killed -> survivor terminated fast with the
diagnostic -> fresh cluster restores the pre-crash checkpoint and
verifies; peer stalled -> survivor catches PeerFailure within the
deadline -> both resume) are ``tests/test_failure.py``.

Scope note: detection and fail-fast are host/control-plane mechanisms.
Data-plane steps already queued on devices either complete or die with
the process — there is no partial-step state to repair, which is what
makes crash-only recovery sound (step atomicity).
"""

from __future__ import annotations

import os
import re
import signal
import sys
import threading
import time

from sherman_tpu.errors import ConfigError, ShermanError, StateError


class PeerFailure(ShermanError, RuntimeError):
    """A guarded collective's deadline expired because peers never
    arrived (dead OR stalled — the deadline cannot tell; if they are
    dead, the runtime's heartbeat detection will terminate this process
    shortly anyway, so a *caught* PeerFailure in practice means a stall).

    ``missing`` holds the process indices that never arrived, parsed
    from the coordination service's timeout report; empty when the
    service could not attribute the failure.  ``attempt`` is the barrier
    attempt number that timed out (see :func:`barrier_guarded`).
    """

    def __init__(self, msg: str, missing=(), attempt: int = -1):
        super().__init__(msg)
        self.missing = sorted(int(p) for p in missing)
        self.attempt = attempt


def coordination_client():
    """The jax.distributed coordination-service client, or None when not
    running multihost (single-process clusters have nothing to probe)."""
    from jax._src import distributed
    return distributed.global_state.client


def live_processes(num_processes: int, client=None) -> list[int]:
    """Collective liveness roll call: process indices the coordination
    service considers live.

    COLLECTIVE semantics (like the service API underneath): every live
    process must call this together — replicated control flow's natural
    shape, e.g. a periodic health check between engine steps.  A
    unilateral call blocks until the absent peers either join or are
    declared dead, so do NOT use it to diagnose a peer that may be
    stalled; :class:`PeerFailure.missing` already names never-arrived
    peers without any extra probe.

    Returns all indices when no coordination client exists (single
    process: trivially live).
    """
    if client is None:
        client = coordination_client()
    if client is None:
        return list(range(num_processes))
    alive = client.get_live_nodes(list(range(num_processes)))
    return sorted(int(p) for p in alive)


class Watchdog:
    """Deadline for a blocking host section — fail fast instead of hang.

    Usage::

        with Watchdog(120, what="collective checkpoint",
                      diagnostics=lambda: dsm.counter_snapshot()):
            ck.checkpoint(cluster, path)

    If the body does not finish within ``timeout_s`` the watchdog thread
    fires: it prints ``what`` + the diagnostics callback's result to
    stderr and invokes ``action`` — by default ``os._exit(86)``, because
    a Python thread cannot interrupt a C-level blocking collective; the
    only sound move is to kill the process and let the launcher restart
    it (recovery = restore the last checkpoint).  Pass ``action`` to
    override (tests record instead of exiting).

    ``timeout_s <= 0`` disarms entirely (zero-cost no-op), which is what
    :meth:`maybe` returns when its env knob is unset.
    """

    EXIT_CODE = 86  # distinct, grep-able "watchdog fired" status

    def __init__(self, timeout_s: float, what: str = "blocking section",
                 action=None, diagnostics=None):
        self.timeout_s = float(timeout_s)
        self.what = what
        self.action = action
        self.diagnostics = diagnostics
        self.fired = False
        self._timer: threading.Timer | None = None

    @classmethod
    def maybe(cls, env: str = "SHERMAN_COLLECTIVE_TIMEOUT_S",
              what: str = "blocking section", diagnostics=None) -> "Watchdog":
        """Env-gated watchdog: armed only when ``env`` is set to a
        positive number of seconds (deployments opt in per-site — a
        sound default deadline depends on pool size and interconnect).

        A malformed value is a configuration error on a safety knob:
        raise with a message naming the knob rather than silently
        disarming the protection the operator asked for."""
        raw = os.environ.get(env, 0) or 0
        try:
            timeout_s = float(raw)
        except ValueError:
            raise ConfigError(
                f"{env}={raw!r} is not a number of seconds; fix the env "
                "var (e.g. '120') or unset it to disarm the watchdog"
            ) from None
        return cls(timeout_s, what=what, diagnostics=diagnostics)

    DIAG_DEADLINE_S = 5.0

    def _fire(self):
        self.fired = True
        # black box FIRST: the default action is os._exit, so the flight
        # recorder's dump (env-gated; force bypasses the debounce — this
        # process is about to die) is the postmortem's only record.
        # Lazy import: failure.py stays importable without the obs tree.
        try:
            from sherman_tpu.obs import recorder as _fr
            _fr.record_event("watchdog.fired", what=self.what,
                             timeout_s=self.timeout_s)
            _fr.auto_dump("watchdog", force=True)
        except Exception:
            pass  # the watchdog's exit must never be blocked by obs
        msg = (f"[sherman watchdog] '{self.what}' exceeded "
               f"{self.timeout_s:g}s deadline")
        if self.diagnostics is not None:
            # The diagnostics callback may itself touch the wedged
            # runtime (e.g. a device-to-host counter transfer queued
            # behind the stuck collective) and block forever — which
            # would defeat the fail-fast exit.  Run it on its own
            # daemon thread with a short deadline and abandon it if it
            # doesn't come back.
            box: list = []

            def run():
                try:
                    box.append(f"diagnostics: {self.diagnostics()}")
                except Exception as e:
                    box.append(f"diagnostics failed: {e!r}")

            th = threading.Thread(target=run, daemon=True)
            th.start()
            th.join(self.DIAG_DEADLINE_S)
            msg += "\n[sherman watchdog] " + (
                box[0] if box else
                f"diagnostics hung > {self.DIAG_DEADLINE_S:g}s (wedged "
                "runtime?); abandoned")
        print(msg, file=sys.stderr, flush=True)
        if self.action is not None:
            self.action()
        else:
            os._exit(self.EXIT_CODE)

    def __enter__(self) -> "Watchdog":
        if self.timeout_s > 0:
            self._timer = threading.Timer(self.timeout_s, self._fire)
            self._timer.daemon = True
            self._timer.start()
        return self

    def __exit__(self, *exc) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None


class PreemptionGuard:
    """Checkpoint-on-preemption: turn an eviction SIGTERM into a clean
    collective checkpoint + exit instead of a dead cluster.

    Cloud TPU VMs receive SIGTERM shortly before preemption or
    maintenance.  The reference has no story — a preempted node is a
    dead node and the cluster hangs (SURVEY.md §5).  Here:

    - single-process: a Python signal handler latches a flag the driver
      polls between steps (:meth:`should_act`).
    - multi-host: jax's preemption sync manager (coordination service).
      The preempted host's notice propagates to every host, and
      ``reached_sync_point(step)`` turns True on ALL hosts at the SAME
      step — so the collective checkpoint that follows is entered in
      lock-step, preserving the replicated-driver invariant.  The
      manager's own SIGTERM notifier does the catching; no Python
      handler is installed.

    Driver shape (see ``tools/benchmark.py --preempt-ckpt``)::

        guard = PreemptionGuard(keeper)
        for step in ...:
            run_step()
            if guard.should_act(step):
                checkpoint(cluster, path)
                break   # exit cleanly; relaunch restores

    ``should_act`` must be called with a monotonically increasing step
    on every host each iteration (replicated control flow — the same
    contract every other collective here relies on).
    """

    def __init__(self, keeper=None, signals=(signal.SIGTERM,)):
        self._flag = False
        self._prev: dict[int, object] = {}
        self._multihost = keeper is not None and keeper.is_multihost
        if self._multihost:
            from jax._src import distributed
            if distributed.global_state.preemption_sync_manager is None:
                distributed.global_state.initialize_preemption_sync_manager()
            self._psm = distributed.global_state.preemption_sync_manager
            if self._psm is None:
                raise StateError(
                    "preemption sync manager unavailable (jax config "
                    "jax_enable_preemption_service is off)")
        else:
            for s in signals:
                self._prev[s] = signal.signal(s, self._latch)

    def _latch(self, signum, frame):
        self._flag = True

    def should_act(self, step: int) -> bool:
        """True when this (and, multihost, EVERY) process should stop
        after the current step and checkpoint."""
        if self._multihost:
            return bool(self._psm.reached_sync_point(int(step)))
        return self._flag

    def close(self) -> None:
        """Restore the signal handlers this guard installed.  A handler
        installed from C (signal.signal returned None) cannot be
        re-installed from Python; leave it to the latch in that case."""
        for s, prev in self._prev.items():
            if prev is not None:
                signal.signal(s, prev)
        self._prev.clear()


def _error_status(e: Exception) -> str:
    """Best-effort status text of a coordination-service error: the UNION
    of any structured code/status attributes (grpc/absl expose one on
    some exception types) and the message — absl status strings lead
    with the code name.  Matching against the union means a numeric or
    unrelated ``code`` attribute (e.g. an integer gRPC code) can never
    mask the message fallback."""
    parts = []
    for attr in ("code", "status"):
        v = getattr(e, attr, None)
        if v is not None:
            try:
                s = str(v() if callable(v) else v)
            except Exception:
                continue
            if s:
                parts.append(s)
    parts.append(str(e))
    return " ".join(parts).upper()


def _is_deadline_error(e: Exception) -> bool:
    s = _error_status(e)
    return "DEADLINE_EXCEEDED" in s or "TIMED OUT" in s


def _read_burn_marker(client, key: str) -> int:
    """Last burned attempt for a barrier name, -1 when none exists.  Only
    a not-found answer means 'no marker'; any other coordination error
    (lost connection, auth) propagates — treating those as 'no marker'
    would silently break attempt realignment."""
    try:
        return int(client.key_value_try_get(key))
    except Exception as e:
        if "NOT_FOUND" in _error_status(e):
            return -1
        raise


def barrier_guarded(name: str, timeout_s: float, *,
                    attempt: int, client=None) -> int:
    """Host-level named barrier with a deadline (the memcached
    fetch-add-and-spin barrier of ``DSMKeeper.cpp:148-161``, with the
    spin bounded).  Returns the attempt number actually used.

    A barrier instance (id) is burned once its deadline fires, so each
    use needs a fresh id.  ``attempt`` is the caller's local use count
    for this name; under replicated control flow every process passes
    the same count and the ids line up.  After a timeout they would NOT
    line up anymore (the survivor advanced, the stalled peer did not),
    so the timeout path publishes the burned attempt in the
    coordination KV and every caller fast-forwards past it on entry —
    a survivor's RETRY and a recovered peer's late first call land on
    the same fresh id.  Raises :class:`PeerFailure` (carrying the
    attempt and the never-arrived peers parsed from the service's
    report) on deadline expiry; any non-deadline coordination error
    (invalid id, lost connection, ...) propagates untouched — those are
    not peer failures and retrying them as stalls would mask real bugs.

    Control-plane only: unlike the default ``DistributedKeeper.barrier``
    (a global DEVICE sync), this does not flush queued device work —
    callers guarding a device-step boundary want a :class:`Watchdog`
    around the blocking sync instead.
    """
    if client is None:
        client = coordination_client()
    if client is None:
        return attempt  # single process: arrival == completion
    burn_key = f"sherman:barrier-burned:{name}"
    retried = False
    while True:
        burned = _read_burn_marker(client, burn_key)
        eff = max(attempt, burned + 1)
        bid = f"sherman:barrier:{name}:{eff}"
        t0 = time.monotonic()
        try:
            client.wait_at_barrier(bid, int(timeout_s * 1000))
            return eff
        except Exception as e:
            # Burn-marker race: a survivor may have burned `eff` between
            # our marker read and our arrival (we joined an
            # already-burned id — depending on the service that surfaces
            # as a non-deadline error or a wasted timeout).  Re-read the
            # marker and realign ONCE at the fast-forwarded id before
            # classifying the failure.
            if not retried:
                try:
                    burned2 = _read_burn_marker(client, burn_key)
                except Exception:
                    burned2 = -1  # coordination layer failing: keep `e`
                if burned2 >= eff:
                    retried = True
                    continue
            if not _is_deadline_error(e):
                raise  # not a peer failure: configuration/connection error
            msg = str(e)
            waited = time.monotonic() - t0
            # burn this attempt so every side's next use aligns at eff+1
            try:
                client.key_value_set(burn_key, str(eff),
                                     allow_overwrite=True)
            except Exception:
                pass  # marker is best-effort; worst case one extra timeout
            # The service's timeout report names the tasks that never
            # arrived ("Some timed out task names: .../task:N").  Parse it
            # rather than probing live_processes(), which is itself a
            # collective and must not be entered unilaterally from an
            # error path.
            missing: list[int] = []
            m = re.search(r"timed out task names:(.*)", msg, re.S)
            if m:
                missing = sorted(
                    {int(t) for t in re.findall(r"task:(\d+)", m.group(1))})
            raise PeerFailure(
                f"barrier '{name}' timed out after {waited:.1f}s "
                f"(deadline {timeout_s:g}s, attempt {eff}); never arrived: "
                f"{missing or 'unknown'}: {msg}",
                missing=missing, attempt=eff) from e
