"""Elastic cluster resize: transform a checkpoint onto a different node count.

Beyond-reference capability (SURVEY.md §5 lists "elastic recovery: none" —
the reference's membership is join-only and its address space is fixed at
cluster birth).  A Sherman-style tree bakes packed ``{node, page}``
addresses into every internal entry, sibling link and the root meta word,
so scaling a live dataset from N to M nodes is not a data copy — it is an
address-space rewrite.  This module does that rewrite OFFLINE on a
checkpoint, vectorized in numpy:

1. identify the live page rows of every old node (the bump allocators'
   ``dir_next`` high-water marks from the manifest; page 0 per node is
   reserved),
2. repack them contiguously onto the new node partition (block
   assignment, page 1 upward per new node),
3. rewrite every pointer word through the old->new address map — header
   ``leftmost``/``sibling`` of every page, the valid ``InternalEntry``
   ptr slots (slots >= nkeys are dead and never dereferenced), and the
   root meta word — leaving leaf key/value words untouched (they are
   user data, not addresses),
4. emit a fresh checkpoint (single-process format, or multi-host format
   with per-host shard files when ``hosts > 1``) whose manifest carries
   the new DSMConfig and per-node allocator high-water marks, ready for
   ``utils.checkpoint.restore`` on the new mesh.

The workflow is crash-only elastic scaling: checkpoint -> reshard ->
relaunch at the new size -> restore.  Locks are emitted cleared (restore
clears them anyway: no client of the old incarnation survives) and op
counters keep their cluster totals on node 0.

CLI: ``python tools/reshard.py <src> <dst> --nodes M [--hosts H]``.
"""

from __future__ import annotations

import os

import numpy as np

from sherman_tpu import config as C
from sherman_tpu.config import DSMConfig
from sherman_tpu.errors import ConfigError, ReshardError
from sherman_tpu.parallel.dsm import N_COUNTERS
from sherman_tpu.utils.checkpoint import (_CFG_FIELDS, _MANIFEST_FIELDS,
                                          _savez_atomic, cfg_from_json,
                                          cfg_to_json, make_epoch)

_PTR_HEADER_WORDS = (C.W_LEFTMOST, C.W_SIBLING)


def _load_checkpoint(path: str):
    """-> (manifest dict, pool [N*P, PW], locks, counters) with multihost
    shard files reassembled in node order when the source is one."""
    if not path.endswith(".npz") and not os.path.exists(path):
        path += ".npz"
    with np.load(path) as z:
        man = {k: np.asarray(z[k]) for k in z.files}
    saved_mh = int(man["multihost"][0]) if "multihost" in man else 0
    if saved_mh == 0:
        pool = man.pop("pool")
        locks = man.pop("locks")
        counters = man.pop("counters")
        return man, pool, locks, counters
    blocks = []
    for h in range(saved_mh):
        with np.load(f"{path}.host{h}.npz") as z:
            blk = {k: np.asarray(z[k]) for k in z.files}
        # same torn-pair rule as checkpoint._restore_multihost: a
        # mixed legacy/tagged pair IS torn — skipping the comparison
        # would launder state from two different checkpoints into a
        # consistently-tagged output that restore then accepts
        if ("epoch" in man) != ("epoch" in blk):
            raise ReshardError(
                f"host {h} shard and the manifest disagree on epoch "
                "tagging (mixed legacy/tagged files = torn checkpoint)")
        if "epoch" in blk and not np.array_equal(
                blk["epoch"].ravel(), man["epoch"].ravel()):
            raise ReshardError(
                f"host {h} shard is from a different checkpoint epoch "
                "than the manifest (torn checkpoint)")
        blocks.append(blk)
    blocks.sort(key=lambda b: int(b["nodes"][0]))
    nodes = np.concatenate([b["nodes"] for b in blocks])
    if not np.array_equal(nodes, np.arange(nodes.size)):
        raise ReshardError(f"host shards do not cover nodes 0..N-1: {nodes}")
    return (man,
            np.concatenate([b["pool"] for b in blocks]),
            np.concatenate([b["locks"] for b in blocks]),
            np.concatenate([b["counters"] for b in blocks]))


def _map_ptrs(ptrs: np.ndarray, amap: np.ndarray, P_old: int,
              what: str) -> np.ndarray:
    """Rewrite packed addresses through the old->new map; NULL stays NULL.
    Raises if a nonzero pointer targets a page outside the live set
    (dangling address = corrupted source checkpoint)."""
    u = ptrs.view(np.uint32) if ptrs.dtype == np.int32 else \
        ptrs.astype(np.uint32)
    node = (u >> np.uint32(C.ADDR_PAGE_BITS)).astype(np.int64)
    page = (u & np.uint32(C.ADDR_PAGE_MASK)).astype(np.int64)
    live = ptrs != 0
    # validate BOTH address fields: a page >= P_old would alias into the
    # next node's map region and rewrite to an unrelated live page
    N_old = amap.size // P_old
    oob = live & ((node >= N_old) | (page >= P_old))
    if oob.any():
        raise ReshardError(
            f"{what}: {int(oob.sum())} pointer(s) outside the source "
            f"address space (e.g. {ptrs[oob][:4].tolist()})")
    mapped = amap[np.clip(node * P_old + page, 0, amap.size - 1)]
    if (live & (mapped == 0)).any():
        bad = ptrs[live & (mapped == 0)][:4]
        raise ReshardError(
            f"{what}: {int((live & (mapped == 0)).sum())} pointer(s) target "
            f"pages outside the live set (e.g. {bad.tolist()}) — source "
            "checkpoint is corrupt or allocator marks are wrong")
    return np.where(live, mapped, 0).astype(np.int32)


def live_rows(front_ver: np.ndarray, next_by_node: np.ndarray,
              dir_free, P_old: int, N_old: int) -> np.ndarray:
    """Global pool rows the repack must carry: every allocated page
    ([1, dir_next) per node — the bump allocators never reuse, so the
    high-water mark bounds every allocated page) minus leased-but-
    never-written chunk-tail pages (``front_ver == 0``, the pool's
    ``W_FRONT_VER`` column — the same liveness test the leaf scan uses:
    every written page has a nonzero front version) minus the
    reclaimed-page free pool (nonzero versions but unreachable from the
    tree; repacking them would resurrect them as permanent dead
    weight).  Shared by the offline transform below and the online
    migrator's copy plan (:mod:`sherman_tpu.migrate`) — ONE liveness
    definition, so the two paths cannot diverge on what "the pool's
    content" means."""
    rows = np.concatenate([
        n * P_old + np.arange(1, int(next_by_node[n]), dtype=np.int64)
        for n in range(N_old)]) if N_old else np.zeros(0, np.int64)
    if rows.size:
        rows = rows[front_ver[rows] != 0]
    if rows.size and dir_free is not None and np.asarray(dir_free).size:
        fa = np.asarray(dir_free).astype(np.int64)
        fnode = (fa >> C.ADDR_PAGE_BITS) & 0xFF
        fpage = fa & C.ADDR_PAGE_MASK
        rows = rows[~np.isin(rows, fnode * P_old + fpage)]
    return rows


def reshard_arrays(man: dict, pool: np.ndarray, locks: np.ndarray,
                   counters: np.ndarray, machine_nr: int, *,
                   pages_per_node: int | None = None,
                   locks_per_node: int | None = None,
                   heap: np.ndarray | None = None):
    """The pure array-level address-space rewrite: (manifest, state
    arrays) of an N-node pool -> (arrays, new_cfg, summary) for an
    M-node pool.  No file I/O — :func:`reshard` wraps it for the
    offline checkpoint workflow and ``sherman_tpu/migrate.py`` feeds it
    the staged image of a LIVE pool at cutover, so the online and
    offline transforms are the same code by construction (the drill's
    bit-identity pin leans on exactly this).

    ``arrays`` holds ``pool``/``locks``/``counters`` plus the new
    manifest fields (:data:`~sherman_tpu.utils.checkpoint._MANIFEST_FIELDS`).
    """
    old_cfg = cfg_from_json(man["cfg"])  # raises on layout mismatch
    cfg_dict = {f: getattr(old_cfg, f) for f in _CFG_FIELDS}
    N_old, P_old = old_cfg.machine_nr, old_cfg.pages_per_node
    if pool.shape != (N_old * P_old, C.PAGE_WORDS):
        raise ReshardError(f"pool shape {pool.shape} does not match the "
                           f"manifest config ({N_old}x{P_old} pages)")

    # 1. live rows per old node (see live_rows: allocated minus unwritten
    # tails minus the dir_free pool).  dir_next for the new checkpoint
    # comes from the packed counts below, so dropped rows return to the
    # allocatable tail.
    next_by_node = np.ones(N_old, np.int64)
    for nid, nxt in zip(man["dir_nodes"], man["dir_next"]):
        next_by_node[int(nid)] = int(nxt)
    rows = live_rows(pool[:, C.W_FRONT_VER], next_by_node,
                     man.get("dir_free"), P_old, N_old)
    L = rows.size

    # 2. new geometry + block assignment (page 0 per new node reserved)
    per_new = -(-L // machine_nr) if L else 0
    if pages_per_node is None:
        pages_per_node = max((N_old * P_old) // machine_nr, per_new + 1)
    # value-heap geometry: handles address the heap by GLOBAL row, so
    # the transform never rewrites them — the flat region just re-splits
    # over the new node count (padded up so every old row keeps its
    # index; the tail pages are uncarved spare capacity)
    H_old = old_cfg.heap_pages_per_node
    if (heap is None) != (H_old == 0):
        raise ReshardError(
            "heap array and manifest heap_pages_per_node disagree "
            f"(heap {'present' if heap is not None else 'absent'}, "
            f"cfg says {H_old} pages/node)")
    heap_per_new = -(-(N_old * H_old) // machine_nr) if H_old else 0
    new_cfg = DSMConfig(**{**cfg_dict,
                           "machine_nr": machine_nr,
                           "pages_per_node": pages_per_node,
                           "heap_pages_per_node": heap_per_new,
                           **({"locks_per_node": locks_per_node}
                              if locks_per_node else {})})
    if per_new + 1 > pages_per_node:
        raise ConfigError(
            f"{L} live pages need {per_new} pages/node on {machine_nr} "
            f"nodes; pages_per_node={pages_per_node} is too small")
    idx = np.arange(L, dtype=np.int64)
    new_node = idx // max(per_new, 1)
    new_page = idx - new_node * per_new + 1
    amap = np.zeros(N_old * P_old, np.int32)
    amap[rows] = ((new_node << C.ADDR_PAGE_BITS) | new_page).astype(np.int32)

    # 3. repack + rewrite every address word through the map
    new_pool = np.zeros((machine_nr * pages_per_node, C.PAGE_WORDS), np.int32)
    dst_rows = new_node * pages_per_node + new_page
    sub = pool[rows]  # fancy indexing: already a fresh writable array
    for w in _PTR_HEADER_WORDS:
        sub[:, w] = _map_ptrs(sub[:, w], amap, P_old, f"header word {w}")
    internal = sub[:, C.W_LEVEL] > 0
    ptrs = sub[:, C.I_PTR_W:C.I_PTR_W + C.INTERNAL_CAP]
    valid = (internal[:, None]
             & (np.arange(C.INTERNAL_CAP)[None, :] < sub[:, C.W_NKEYS][:, None]))
    # dead slots (>= nkeys) may hold stale addresses; they are never
    # dereferenced (internal_pick_child masks by nkeys) — leave them.
    # ptrs is a VIEW of sub: the fancy assignment writes through
    ptrs[valid] = _map_ptrs(ptrs[valid], amap, P_old, "internal entry")
    new_pool[dst_rows] = sub

    # root meta word (reserved page 0 of node 0 in both address spaces)
    old_root = int(pool[0, C.META_ROOT_ADDR_W])
    new_root = 0
    root_level = -1
    if old_root:
        new_root = int(_map_ptrs(np.asarray([old_root], np.int32), amap,
                                 P_old, "root meta")[0])
        u = np.uint32(np.int64(old_root) & 0xFFFFFFFF)
        root_level = int(pool[int(u >> C.ADDR_PAGE_BITS) * P_old
                              + int(u & C.ADDR_PAGE_MASK), C.W_LEVEL])
    new_pool[0, C.META_ROOT_ADDR_W] = new_root

    # 4. fresh locks (cleared — no client of the old incarnation survives),
    # counters keep their cluster totals on node 0
    new_locks = np.zeros(machine_nr * new_cfg.locks_per_node, np.int32)
    new_counters = np.zeros(machine_nr * N_COUNTERS, np.uint32)
    new_counters[:N_COUNTERS] = (
        counters.reshape(-1, N_COUNTERS).astype(np.uint64).sum(0)
        & np.uint64(0xFFFFFFFF)).astype(np.uint32)

    counts = np.bincount(new_node, minlength=machine_nr) if L else \
        np.zeros(machine_nr, np.int64)
    new_man = dict(
        cfg=np.frombuffer(cfg_to_json(new_cfg), np.uint8),
        dir_nodes=np.arange(machine_nr, dtype=np.int64),
        dir_next=(counts + 1).astype(np.int64),
        dir_root=np.asarray([[new_root, root_level]] * machine_nr, np.int64),
        # the repack compacts live pages contiguously: the old free pool
        # is simply not carried (its space returns to the bump tail)
        dir_free=np.zeros(0, np.int64),
    )
    assert set(new_man) == set(_MANIFEST_FIELDS)
    arrays = dict(pool=new_pool, locks=new_locks, counters=new_counters,
                  **new_man)
    if heap is not None:
        if heap.shape != (N_old * H_old, C.PAGE_WORDS):
            raise ReshardError(
                f"heap shape {heap.shape} does not match the manifest "
                f"config ({N_old}x{H_old} heap pages)")
        new_heap = np.zeros((machine_nr * heap_per_new, C.PAGE_WORDS),
                            np.int32)
        new_heap[: heap.shape[0]] = heap  # global rows preserved
        arrays["heap"] = new_heap
    summary = {
        "live_pages": int(L),
        "old": {"machine_nr": N_old, "pages_per_node": P_old},
        "new": {"machine_nr": machine_nr, "pages_per_node": pages_per_node},
        "pages_per_new_node": counts.tolist(),
        "root": new_root,
        "root_level": root_level,
    }
    return arrays, new_cfg, summary


def write_resharded(dst: str, arrays: dict, new_cfg, hosts: int = 1) -> str:
    """Persist a :func:`reshard_arrays` result as a restorable
    checkpoint (single-process format, or per-host shard files +
    epoch-tagged manifest when ``hosts > 1``).  Returns the manifest
    path written."""
    machine_nr = new_cfg.machine_nr
    pages_per_node = new_cfg.pages_per_node
    new_man = {k: arrays[k] for k in _MANIFEST_FIELDS}
    if not dst.endswith(".npz"):
        dst += ".npz"
    if hosts == 1:
        extra = ({"heap": arrays["heap"]} if "heap" in arrays else {})
        _savez_atomic(dst, 0, pool=arrays["pool"], locks=arrays["locks"],
                      counters=arrays["counters"], **extra, **new_man)
        return dst
    if "heap" in arrays:
        raise ConfigError(
            "the value heap is single-process only: emit hosts=1 "
            "checkpoints for heap-bearing clusters")
    if machine_nr % hosts:
        raise ConfigError(f"hosts={hosts} must divide machine_nr="
                          f"{machine_nr} (contiguous node blocks)")
    nph = machine_nr // hosts
    epoch = make_epoch(new_man, 0)
    for h in range(hosts):
        nodes = np.arange(h * nph, (h + 1) * nph, dtype=np.int64)
        sl = slice(h * nph * pages_per_node, (h + 1) * nph * pages_per_node)
        _savez_atomic(
            f"{dst}.host{h}.npz", h,
            pool=arrays["pool"][sl],
            locks=arrays["locks"][h * nph * new_cfg.locks_per_node:
                                  (h + 1) * nph * new_cfg.locks_per_node],
            counters=arrays["counters"][h * nph * N_COUNTERS:
                                        (h + 1) * nph * N_COUNTERS],
            nodes=nodes, epoch=epoch)
    _savez_atomic(dst, 0, multihost=np.asarray([hosts], np.int64),
                  epoch=epoch, **new_man)
    return dst


def reshard(src: str, dst: str, machine_nr: int, *,
            pages_per_node: int | None = None,
            locks_per_node: int | None = None,
            hosts: int = 1) -> dict:
    """Rewrite checkpoint ``src`` for a ``machine_nr``-node cluster into
    ``dst``.  -> summary dict (live_pages, per-node occupancy, geometry).

    ``pages_per_node`` defaults to preserving the total pool size
    (``old_total // machine_nr``).  ``hosts > 1`` emits the multi-host
    checkpoint format (``machine_nr`` must divide evenly; restore with
    one process per host).  The source may be either format.
    """
    man, pool, locks, counters = _load_checkpoint(src)
    heap = man.pop("heap", None)
    arrays, new_cfg, summary = reshard_arrays(
        man, pool, locks, counters, machine_nr,
        pages_per_node=pages_per_node, locks_per_node=locks_per_node,
        heap=heap)
    write_resharded(dst, arrays, new_cfg, hosts=hosts)
    summary["new"]["hosts"] = hosts
    return summary
