"""Checkpoint / resume of the whole DSM cluster state.

The reference has NO durability story (SURVEY.md §5: "Checkpoint /
resume. Absent.") — a crashed cluster loses the index.  This module goes
beyond parity: one call snapshots everything a cluster needs to come
back — the sharded pool (which contains every page AND the root-pointer
meta words), the lock table, op counters, and each directory's allocator
bump state — into a single ``.npz``; ``restore`` rebuilds a live Cluster
on any mesh of the same ``machine_nr``.

Client-side chunk leases (LocalAllocator tails) are deliberately NOT
saved: clients re-register after restore and lease fresh chunks.  The
abandoned tails are unreachable pages — the same class of leak as the
reference's no-op ``free`` (DSM.h:226), bounded by one chunk per client.

Locks are saved as-is; a checkpoint taken mid-operation may hold locks
whose owners are gone, so ``restore(clear_locks=True)`` (default) zeroes
the table — valid because restore is a cluster-wide restart: no client
of the old incarnation survives.
"""

from __future__ import annotations

import json
import os

import numpy as np

from sherman_tpu.config import DSMConfig

_CFG_FIELDS = ("machine_nr", "pages_per_node", "locks_per_node",
               "step_capacity", "host_step_capacity", "chunk_pages",
               "exchange_impl")


def checkpoint(cluster, path: str) -> None:
    """Write the cluster's full state to ``path`` (.npz).

    Single-process clusters only (every shard addressable from this
    host): a multi-host deployment needs per-host shard files + a
    gathered manifest, which is future work.
    """
    if not path.endswith(".npz"):
        path += ".npz"  # np.savez appends it silently; keep restore in sync
    if cluster.keeper.is_multihost:
        raise NotImplementedError(
            "checkpoint of a multi-host cluster is not supported yet: "
            "the pool spans non-addressable devices; snapshot per host")
    dsm = cluster.dsm
    cfg = {f: getattr(cluster.cfg, f) for f in _CFG_FIELDS}
    np.savez_compressed(
        path,
        cfg=np.frombuffer(json.dumps(cfg).encode(), np.uint8),
        pool=np.asarray(dsm.pool),
        locks=np.asarray(dsm.locks),
        counters=np.asarray(dsm.counters),
        dir_nodes=np.asarray([d.node_id for d in cluster.directories],
                             np.int64),
        dir_next=np.asarray(
            [d.allocator._next for d in cluster.directories], np.int64),
        dir_root=np.asarray(
            [[d.root_ptr, d.root_level] for d in cluster.directories],
            np.int64),
    )


def restore(path: str, mesh=None, keeper=None, clear_locks: bool = True):
    """Rebuild a live Cluster from a checkpoint.  -> Cluster."""
    import jax

    from sherman_tpu.cluster import Cluster

    if not path.endswith(".npz") and not os.path.exists(path):
        path += ".npz"
    with np.load(path) as z:
        cfg = DSMConfig(**json.loads(bytes(z["cfg"]).decode()))
        cluster = Cluster(cfg, mesh=mesh, keeper=keeper)
        dsm = cluster.dsm
        dsm.pool = jax.device_put(z["pool"], dsm.shard)
        locks = z["locks"]
        if clear_locks:
            locks = np.zeros_like(locks)
        dsm.locks = jax.device_put(locks, dsm.shard)
        dsm.counters = jax.device_put(z["counters"], dsm.shard)
        by_node = {int(n): i for i, n in enumerate(z["dir_nodes"])}
        for d in cluster.directories:
            i = by_node.get(d.node_id)
            if i is None:
                continue  # node had no directory in the saved cluster
            d.allocator._next = int(z["dir_next"][i])
            d.root_ptr = int(z["dir_root"][i][0])
            d.root_level = int(z["dir_root"][i][1])
    return cluster
