"""Checkpoint / resume of the whole DSM cluster state.

The reference has NO durability story (SURVEY.md §5: "Checkpoint /
resume. Absent.") — a crashed cluster loses the index.  This module goes
beyond parity: one call snapshots everything a cluster needs to come
back — the sharded pool (which contains every page AND the root-pointer
meta words), the lock table, op counters, and each directory's allocator
bump state — into a single ``.npz``; ``restore`` rebuilds a live Cluster
on any mesh of the same ``machine_nr``.  Multi-host clusters checkpoint
collectively: one shard file per host plus the (mirrored, identical)
manifest written by every host, restored onto the same nodes-per-host
partition.

Client-side chunk leases (LocalAllocator tails) are deliberately NOT
saved: clients re-register after restore and lease fresh chunks.  The
abandoned tails are unreachable pages — the same class of leak as the
reference's no-op ``free`` (DSM.h:226), bounded by one chunk per client.

Locks are saved as-is; a checkpoint taken mid-operation may hold locks
whose owners are gone, so ``restore(clear_locks=True)`` (default) zeroes
the table — valid because restore is a cluster-wide restart: no client
of the old incarnation survives.
"""

from __future__ import annotations

import glob
import json
import os
import zlib

import numpy as np

from sherman_tpu import config as _C
from sherman_tpu import obs
from sherman_tpu.config import DSMConfig
from sherman_tpu.errors import (CheckpointFormatError, ConfigError,
                                ShermanError)

_CFG_FIELDS = ("machine_nr", "pages_per_node", "locks_per_node",
               "step_capacity", "host_step_capacity", "chunk_pages",
               "exchange_impl", "gather_impl", "heap_pages_per_node")

# fsync indirection for tests (patching os.fsync itself would also
# intercept interpreter/numpy internals)
_fsync = os.fsync

_OBS_FULL_SAVES = obs.counter("ckpt.full_saves")
_OBS_DELTA_SAVES = obs.counter("ckpt.delta_saves")
_OBS_DELTA_PAGES = obs.counter("ckpt.delta_pages")
_OBS_DELTA_BYTES = obs.counter("ckpt.delta_bytes")
_OBS_ORPHANS = obs.counter("ckpt.orphans_swept")


class CheckpointCorruptError(ShermanError, RuntimeError):
    """A checkpoint artifact failed its content CRC / framing / chain
    pairing — corruption is detected at restore time, never served."""

# Page-layout fingerprint stamped into every checkpoint: the pool is raw
# words, so restoring across a layout change (e.g. round 4's packed
# 16/16 entry version pair, 41 -> 49 leaf slots) would silently
# misinterpret every page.  Missing tag = pre-stamp checkpoint, also
# rejected.
LAYOUT_TAG = (f"pw{_C.PAGE_WORDS}"
              f"+leaf{_C.LEAF_ENTRY_WORDS}x{_C.LEAF_CAP}"
              f"+int{_C.INTERNAL_ENTRY_WORDS}x{_C.INTERNAL_CAP}")


def cfg_to_json(cfg) -> bytes:
    d = {f: getattr(cfg, f) for f in _CFG_FIELDS}
    d["_layout"] = LAYOUT_TAG
    return json.dumps(d).encode()


def cfg_from_json(raw) -> DSMConfig:
    """Saved cfg JSON -> DSMConfig, under the _CFG_FIELDS forward-compat
    contract: fields ABSENT from the JSON (a checkpoint written before
    the field existed, e.g. pre-``gather_impl``) take the DSMConfig
    default — never a KeyError; fields this build does NOT know (a
    checkpoint written by a newer build) refuse loudly — silently
    dropping a semantic knob could reinterpret the pool."""
    import dataclasses
    d = json.loads(bytes(raw).decode())
    tag = d.pop("_layout", None)
    if tag != LAYOUT_TAG:
        raise CheckpointFormatError(
            f"checkpoint page layout {tag or 'unstamped'!r} does not match "
            f"this build's {LAYOUT_TAG!r}; re-create the checkpoint (raw "
            "page words cannot be reinterpreted across layouts)")
    known = {f.name for f in dataclasses.fields(DSMConfig)}
    unknown = sorted(set(d) - known)
    if unknown:
        raise CheckpointFormatError(
            f"checkpoint cfg carries unknown fields {unknown} (written "
            "by a newer build?); refusing to drop config knobs silently")
    return DSMConfig(**d)


def _local_block(arr) -> np.ndarray:
    """This host's contiguous block of a node-sharded array, shards
    ordered by their global row offset."""
    shards = sorted(arr.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def checkpoint(cluster, path: str):
    """Write the cluster's full state to ``path`` (.npz).

    Multi-host clusters write one shard file per host
    (``<path>.host<k>.npz`` with that process's node block) and EVERY
    process writes the (identical, mirrored) manifest at ``<path>`` —
    each host's own disk gets both files, no shared filesystem needed;
    every process must call (collective — barrier at the end).  All
    files are written atomically (tmp + replace) and carry a shared
    epoch, so a crash mid-checkpoint leaves the PREVIOUS checkpoint
    intact and restore rejects mixed-epoch shard/manifest pairs.
    Restore requires the same machine_nr AND the same nodes-per-host
    partition.
    """
    if not path.endswith(".npz"):
        path += ".npz"  # np.savez appends it silently; keep restore in sync
    if cluster.keeper.is_multihost:
        from sherman_tpu.utils import failure

        # a peer dying mid-protocol would hang every other host inside
        # the broadcast/allgather/barrier below; the env-gated watchdog
        # (SHERMAN_COLLECTIVE_TIMEOUT_S) turns that into a fail-fast
        # exit so the launcher can restart from the previous checkpoint
        with failure.Watchdog.maybe(
                what="collective checkpoint save",
                diagnostics=lambda: cluster.dsm.counter_snapshot()):
            _checkpoint_multihost(cluster, path)
        return None
    dsm = cluster.dsm
    man = _manifest(cluster)
    # Epoch on single-host full checkpoints too: the (nonce, seq, crc)
    # triple is what delta artifacts chain their parent_epoch to.
    seq = cluster.keeper.mem_fetch_and_add("checkpoint_epoch")
    epoch = make_epoch(man, seq)
    arrays = dict(
        pool=np.asarray(dsm.pool),
        locks=np.asarray(dsm.locks),
        counters=np.asarray(dsm.counters),
        epoch=epoch,
        **man,
    )
    # value-heap region (optional — heap-off checkpoints are unchanged)
    if dsm.heap is not None:
        arrays["heap"] = dsm.heap_snapshot()
    arrays["integrity"] = _integrity(arrays)
    _savez_atomic(path, 0, **arrays)
    _OBS_FULL_SAVES.inc()
    obs.record_event("checkpoint.save", path=path, seq=int(seq))
    # A full save captures everything: dirty tracking restarts here.
    dsm.clear_dirty()
    return epoch


def _checkpoint_multihost(cluster, path: str) -> None:
    import jax
    dsm = cluster.dsm
    me = jax.process_index()
    # Epoch pairing shard <-> manifest AND checkpoint <-> checkpoint:
    # (nonce, seq, digest).  The nonce is random on process 0 and
    # broadcast, making every checkpoint invocation globally unique —
    # a per-process counter alone resets across restarts and the
    # manifest digest alone is unchanged by update-in-place traffic,
    # so (seq, dig) could collide across distinct checkpoints.
    # int32 throughout: restore allgathers the epoch, and jax (x64
    # disabled) canonicalizes int64 -> int32, which would wrap an
    # unsigned crc and break the cross-host equality check.
    from jax.experimental import multihost_utils as mhu
    seq = cluster.keeper.mem_fetch_and_add("checkpoint_epoch")
    man = _manifest(cluster)
    nonce = np.frombuffer(os.urandom(4), np.int32).copy()
    nonce = np.asarray(mhu.broadcast_one_to_all(nonce))
    epoch = make_epoch(man, seq, nonce=int(nonce[0]))
    # Save-time epoch agreement, BEFORE any file write: seq is a
    # process-local counter and dig hashes the (supposedly mirrored)
    # manifest — if the replicated-driver invariant was ever violated,
    # hosts would diverge here, every os.replace would still succeed,
    # and the previous good checkpoint would be overwritten by a set
    # restore rejects as mixed-epoch (losing BOTH).  Abort loudly with
    # the prior files untouched instead.
    all_ep = np.asarray(mhu.process_allgather(epoch))
    if not (all_ep == all_ep[0]).all():
        raise CheckpointFormatError(
            "checkpoint aborted before writing: hosts disagree on the "
            f"checkpoint epoch {all_ep.tolist()} (divergent checkpoint "
            "counts or manifests — the replicated-driver invariant is "
            "broken); the previous checkpoint is left intact")
    shard_arrays = dict(
        pool=_local_block(dsm.pool),
        locks=_local_block(dsm.locks),
        counters=_local_block(dsm.counters),
        nodes=np.asarray(list(dsm.local_nodes), np.int64),
        epoch=epoch,
    )
    shard_arrays["integrity"] = _integrity(shard_arrays)
    _savez_atomic(f"{path}.host{me}.npz", me, **shard_arrays)
    man_arrays = dict(
        multihost=np.asarray([jax.process_count()], np.int64),
        epoch=epoch, **man)
    man_arrays["integrity"] = _integrity(man_arrays)
    _savez_atomic(path, me, **man_arrays)
    _OBS_FULL_SAVES.inc()
    cluster.keeper.barrier("checkpoint")


def make_epoch(man: dict, seq: int, nonce: int | None = None) -> np.ndarray:
    """The (nonce, seq, manifest-crc) epoch triple pairing shard files
    with their manifest — ONE construction shared by the collective
    checkpoint save and the offline resharder (utils/reshard.py), so
    emitted checkpoints always satisfy restore's pairing rules.  int32
    throughout: restore allgathers the epoch under jax's x64-disabled
    canonicalization (see the save path's comment)."""
    import zlib
    dig = zlib.crc32(b"".join(np.ascontiguousarray(v).tobytes()
                              for v in man.values()))
    if nonce is None:
        nonce = int(np.frombuffer(os.urandom(4), np.int32)[0])
    return np.asarray([nonce, seq, np.uint32(dig).view(np.int32)], np.int32)


def _sweep_tmp_orphans(path: str) -> int:
    """Remove ``<path>.tmp*.npz`` leftovers from a writer that crashed
    mid-:func:`_savez_atomic` (before its os.replace).  Returns the
    count removed.  Safe by construction: a live writer's tmp file only
    exists inside its own _savez_atomic call, which sweeps BEFORE
    creating it; concurrent writers to one path are already excluded by
    the single-saver contract."""
    n = 0
    for orphan in glob.glob(glob.escape(path) + ".tmp*.npz"):
        try:
            os.unlink(orphan)
            n += 1
        except OSError:
            pass  # raced with another sweeper: gone either way
    if n:
        _OBS_ORPHANS.inc(n)
    return n


def _savez_atomic(path: str, tag: int, **arrays) -> None:
    """np.savez_compressed via tmp + fsync + atomic replace + directory
    fsync: a crash mid-write never clobbers an existing checkpoint file,
    and a completed save survives power loss (the data AND the rename
    are both on disk before return).  Stale tmp orphans from a previous
    crash are swept first."""
    _sweep_tmp_orphans(path)
    tmp = f"{path}.tmp{tag}.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        _fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        _fsync(dfd)
    finally:
        os.close(dfd)


def _integrity(arrays: dict) -> np.ndarray:
    """Per-array content CRCs, stored alongside the arrays so restore
    detects corruption instead of serving it (npz member checksums
    cover the compressed stream; this covers the decoded content, one
    named CRC per array — a typed CheckpointCorruptError names the
    damaged array)."""
    crcs = {k: int(np.uint32(zlib.crc32(
        np.ascontiguousarray(v).tobytes())))
        for k, v in arrays.items()}
    return np.frombuffer(json.dumps(crcs).encode(), np.uint8).copy()


def _verify_integrity(arrays: dict, path: str) -> None:
    """Check every loaded array against the artifact's stored CRC map
    (legacy artifacts without one pass — integrity is opt-out only by
    age).  Raises :class:`CheckpointCorruptError` naming the array."""
    blob = arrays.get("integrity")
    if blob is None:
        return
    try:
        crcs = json.loads(bytes(np.asarray(blob)).decode())
    except Exception as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable integrity map ({e})") from e
    for k, v in arrays.items():
        if k == "integrity" or k not in crcs:
            continue
        got = int(np.uint32(zlib.crc32(np.ascontiguousarray(v).tobytes())))
        if got != int(crcs[k]):
            raise CheckpointCorruptError(
                f"{path}: array {k!r} failed its content CRC "
                f"({got:#x} != stored {int(crcs[k]):#x}) — the artifact "
                "is corrupt; restore from another chain link")


def _load_arrays(path: str, keys=None) -> dict:
    """np.load + materialize (+ CRC verify) with typed failure: any
    unreadable/torn/corrupt artifact surfaces as
    :class:`CheckpointCorruptError`, never a stack of zipfile/zlib
    internals half-way through a restore."""
    try:
        with np.load(path) as z:
            names = z.files if keys is None else \
                [k for k in z.files if k in set(keys) | {"integrity"}]
            out = {k: np.asarray(z[k]) for k in names}
    except CheckpointCorruptError:
        raise
    except Exception as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable checkpoint artifact "
            f"({type(e).__name__}: {e})") from e
    _verify_integrity(out, path)
    return out


# The manifest schema (one source of truth: _manifest() must emit exactly
# these keys; _restore_multihost materializes exactly these + extras).
_MANIFEST_FIELDS = ("cfg", "dir_nodes", "dir_next", "dir_root", "dir_free")


def _manifest(cluster) -> dict:
    """Config + directory/allocator state — the part of a checkpoint that
    is host-independent (mirrored on every process in multi-host).
    ``dir_free`` carries each allocator's reclaimed-page pool as packed
    addresses (reclaim_empty_leaves output): those pages sit below the
    bump high-water mark with nonzero versions, so without this field a
    restore would permanently re-leak everything reclamation freed."""
    from sherman_tpu.ops import bits as _bits
    free = []
    for d in cluster.directories:
        free += [_bits.make_addr(d.node_id, p) & 0xFFFFFFFF
                 for p in d.allocator.free_pages_list]
    out = dict(
        cfg=np.frombuffer(cfg_to_json(cluster.cfg), np.uint8),
        dir_nodes=np.asarray([d.node_id for d in cluster.directories],
                             np.int64),
        dir_next=np.asarray(
            [d.allocator._next for d in cluster.directories], np.int64),
        dir_root=np.asarray(
            [[d.root_ptr, d.root_level] for d in cluster.directories],
            np.int64),
        dir_free=np.asarray(sorted(free), np.int64),
    )
    assert set(out) == set(_MANIFEST_FIELDS)
    return out


def restore(path: str, mesh=None, keeper=None, clear_locks: bool = True):
    """Rebuild a live Cluster from a checkpoint.  -> Cluster."""
    import jax

    from sherman_tpu.cluster import Cluster

    if not path.endswith(".npz") and not os.path.exists(path):
        path += ".npz"
    if keeper is not None and keeper.is_multihost:
        from sherman_tpu.utils import failure
        with failure.Watchdog.maybe(what="collective checkpoint restore"):
            return _restore_multihost(path, mesh, keeper, clear_locks)
    z = _load_arrays(path)
    if "delta" in z:
        raise CheckpointCorruptError(
            f"{path} is a DELTA artifact: restore its chain with "
            "restore_chain(base, deltas) — a delta alone holds only the "
            "pages written since its parent")
    cfg = cfg_from_json(z["cfg"])
    saved_mh = int(z["multihost"][0]) if "multihost" in z else 0
    if saved_mh != 0:  # durability check: must survive python -O
        raise CheckpointFormatError(
            "multi-host checkpoint needs a multi-host cluster (pass "
            "init_multihost()'s keeper on every host)")
    cluster = Cluster(cfg, mesh=mesh, keeper=keeper)
    dsm = cluster.dsm
    dsm.pool = jax.device_put(z["pool"], dsm.shard)
    locks = z["locks"]
    if clear_locks:
        locks = np.zeros_like(locks)
    dsm.locks = jax.device_put(locks, dsm.shard)
    dsm.counters = jax.device_put(z["counters"], dsm.shard)
    if dsm.heap is not None:
        if "heap" not in z:
            raise CheckpointFormatError(
                f"{path}: cfg configures a value heap "
                f"({cfg.heap_pages_per_node} pages/node) but the "
                "artifact carries no heap array")
        dsm.heap = jax.device_put(z["heap"], dsm.shard)
    _restore_directories(cluster, z)
    # flight event: a restore is the recovery step every drill's black
    # box must show after the degraded transition
    obs.record_event("checkpoint.restore", path=path)
    return cluster


def _restore_directories(cluster, man) -> None:
    """SET the directory/allocator state to the manifest's (replace, not
    merge: the free pool is cleared first, so chain restores can apply
    successive manifests without double-reclaiming pages)."""
    from sherman_tpu.ops import bits as _bits
    by_node = {int(n): i for i, n in enumerate(man["dir_nodes"])}
    free_by_node: dict[int, list[int]] = {}
    if "dir_free" in man:
        for a in np.asarray(man["dir_free"]).tolist():
            free_by_node.setdefault(_bits.addr_node(int(a)), []).append(
                _bits.addr_page(int(a)))
    for d in cluster.directories:
        i = by_node.get(d.node_id)
        if i is None:
            continue  # node had no directory in the saved cluster
        d.allocator._next = int(man["dir_next"][i])
        d.root_ptr = int(man["dir_root"][i][0])
        d.root_level = int(man["dir_root"][i][1])
        d.allocator._free.clear()
        if free_by_node.get(d.node_id):
            d.allocator.reclaim(free_by_node[d.node_id])


def _restore_multihost(path: str, mesh, keeper, clear_locks: bool):
    """Multi-host restore, COLLECTIVE-FIRST: every host resolves ALL its
    fallible local work (file loads, epoch pairing) into a status vector,
    every host allgathers it unconditionally, and only then asserts — a
    host-local failure before the collective would leave the other hosts
    hanging in it (or in the Cluster constructor's own collectives)
    instead of erroring cleanly everywhere."""
    import jax
    from jax.experimental import multihost_utils as mhu
    from jax.sharding import PartitionSpec

    from sherman_tpu.cluster import Cluster
    from sherman_tpu.parallel.mesh import AXIS

    me = jax.process_index()
    EW = 3  # epoch words; sentinel -1s for legacy/odd shapes
    man = shard = None
    err = ""
    # materialize only the manifest keys (the _manifest schema + the
    # multihost extras): a mistakenly-pointed-at single-host checkpoint
    # carries the full pool in its manifest file, and eagerly
    # decompressing gigabytes just to fail the host-count check below
    # would be wasteful
    man_keys = set(_MANIFEST_FIELDS) | {"multihost", "epoch"}
    try:
        # typed + CRC-verified loads (corruption surfaces here and rides
        # the status gather like any other host-local load failure)
        man = _load_arrays(path, keys=man_keys)
        shard = _load_arrays(f"{path}.host{me}.npz")
    except Exception as e:  # missing/torn/corrupt file: report via gather
        err = f"{type(e).__name__}: {e}"
    loads_ok = int(man is not None and shard is not None and "cfg" in man)
    pair_ok, saved_mh = 1, -1
    ep = np.full(EW, -1, np.int32)
    if loads_ok:
        saved_mh = int(man["multihost"][0]) if "multihost" in man else 0
        if ("epoch" in shard) != ("epoch" in man):
            pair_ok = 0  # mixed legacy/tagged files = torn pair
        elif "epoch" in shard:
            he = np.asarray(shard["epoch"]).ravel()
            ze = np.asarray(man["epoch"]).ravel()
            if he.shape != ze.shape or not (he == ze).all():
                pair_ok = 0
            else:
                ep[: min(EW, he.size)] = he[:EW].astype(np.int32)
    status = np.concatenate(
        [np.asarray([loads_ok, pair_ok, saved_mh], np.int32), ep])
    all_st = np.asarray(mhu.process_allgather(status))
    # durability-critical validation: explicit raises (a bare assert is
    # stripped under python -O and would silently restore torn state)
    if not (all_st[:, 0] == 1).all():
        raise CheckpointFormatError("a host failed to load its checkpoint files "
                           f"({err or 'other host'})")
    if not (all_st[:, 1] == 1).all():
        raise CheckpointFormatError(
            "a host holds a torn checkpoint (shard/manifest from different "
            "checkpoints or mixed legacy/tagged files)")
    if not (all_st[:, 2] == jax.process_count()).all():
        raise CheckpointFormatError(
            f"checkpoint host count {sorted(set(all_st[:, 2].tolist()))} != "
            f"{jax.process_count()} restoring processes")
    if not (all_st[:, 3:] == all_st[0, 3:]).all():
        raise CheckpointFormatError(
            "hosts hold checkpoints from different epochs (crashed "
            "mid-checkpoint?): refusing to mix")

    # all hosts validated: collectives are now safe
    cfg = cfg_from_json(man["cfg"])
    cluster = Cluster(cfg, mesh=mesh, keeper=keeper)
    dsm = cluster.dsm
    nodes_ok = int(list(shard["nodes"]) == list(dsm.local_nodes))
    all_nodes = np.asarray(mhu.process_allgather(
        np.asarray([nodes_ok], np.int32)))
    if not (all_nodes == 1).all():
        raise CheckpointFormatError("per-host node blocks changed since the "
                           "checkpoint")
    spec = PartitionSpec(AXIS)
    glob = lambda x: mhu.host_local_array_to_global_array(x, dsm.mesh, spec)
    dsm.pool = glob(shard["pool"])
    locks = shard["locks"]
    if clear_locks:
        locks = np.zeros_like(locks)
    dsm.locks = glob(locks)
    dsm.counters = glob(shard["counters"])
    _restore_directories(cluster, man)
    return cluster


# ---------------------------------------------------------------------------
# Incremental (delta) checkpoints — the recovery plane's cheap-frequent
# half (utils/journal.py is the replayable-log half; sherman_tpu/recovery.py
# coordinates both).  A delta saves only the pages written since the
# previous chain link (the DSM's dirty tracking: device-marked by the
# engine's write programs, host-marked at the DSM.step boundary), plus
# the full (tiny) locks/counters/manifest state, chained by the same
# (nonce, seq, crc) epoch machinery the multihost save uses: each delta
# records its parent's epoch, and restore refuses out-of-order or
# mixed-chain links.  Multihost meshes save per-host row-range deltas
# (PR 19) — each process's chain covers the rows it owns.
# ---------------------------------------------------------------------------

def checkpoint_delta(cluster, path: str, parent_epoch) -> dict:
    """Save a delta artifact chained onto ``parent_epoch`` (the epoch
    returned by the previous :func:`checkpoint` / :func:`checkpoint_delta`
    of this chain).  Clears the DSM's dirty tracking on success.
    Returns {"pages", "bytes", "epoch"}.

    Multihost meshes (PR 19): each process saves a delta of its OWN
    row range only — ``dirty_rows()`` is ownership-filtered and the
    page gather reads this process's addressable shards
    (``read_rows_local``, collective-free), so N hosts write N
    disjoint delta streams concurrently.  Restore is per-host too:
    each host's chain replays onto the rows it owns
    (``RecoveryPlane.recover_union``'s contract)."""
    if not path.endswith(".npz"):
        path += ".npz"
    if parent_epoch is None:
        raise ConfigError(
            "checkpoint_delta needs the parent artifact's epoch "
            "(returned by checkpoint()/checkpoint_delta())")
    import jax.numpy as jnp
    dsm = cluster.dsm
    rows = dsm.dirty_rows()
    man = _manifest(cluster)
    seq = cluster.keeper.mem_fetch_and_add("checkpoint_epoch")
    epoch = make_epoch(man, seq)
    # gather the dirty pages DEVICE-side: the d2h transfer is then
    # O(dirty pages) like the artifact, not O(pool) — at the 100 M-key
    # config a full-pool materialization would cost the whole 4.3 GB
    # tunnel transfer per "cheap frequent delta".  Multihost: the
    # owned-shard gather (a global fancy-index would be a cross-host
    # collective inside a per-host save).
    if dsm.multihost:
        pages = dsm.read_rows_local(rows)
    else:
        pages = (np.asarray(dsm.pool[jnp.asarray(rows)]) if rows.size
                 else np.zeros((0, _C.PAGE_WORDS), np.int32))
    if dsm.multihost:
        # this process's lock/counter shards only (the full arrays
        # are not addressable here; the owner rows are what this
        # host's chain replays onto anyway)
        locks = np.concatenate([np.asarray(s.data) for s in
                                dsm.locks.addressable_shards])
        counters = np.concatenate([np.asarray(s.data) for s in
                                   dsm.counters.addressable_shards])
    else:
        locks = np.asarray(dsm.locks)
        counters = np.asarray(dsm.counters)
    arrays = dict(
        delta=np.asarray([1], np.int64),
        parent_epoch=np.asarray(parent_epoch, np.int32).ravel(),
        epoch=epoch,
        delta_rows=rows.astype(np.int64),
        delta_pages=pages,
        locks=locks,
        counters=counters,
        **man,
    )
    # value-heap dirty rows ride the same link (optional arrays —
    # heap-off deltas are byte-compatible with pre-heap builds)
    if dsm.heap is not None:
        hrows = dsm.heap_dirty_rows()
        arrays["heap_rows"] = hrows.astype(np.int64)
        if dsm.multihost:
            arrays["heap_pages"] = dsm.read_rows_local(hrows, "heap")
        else:
            arrays["heap_pages"] = (
                np.asarray(dsm.heap[jnp.asarray(hrows)]) if hrows.size
                else np.zeros((0, _C.PAGE_WORDS), np.int32))
    arrays["integrity"] = _integrity(arrays)
    _savez_atomic(path, 0, **arrays)
    dsm.clear_dirty()
    _OBS_DELTA_SAVES.inc()
    _OBS_DELTA_PAGES.inc(int(rows.size))
    size = os.path.getsize(path)
    _OBS_DELTA_BYTES.inc(size)
    return {"pages": int(rows.size), "bytes": int(size), "epoch": epoch}


def _check_delta_link(z: dict, path: str, base_cfg_raw: bytes,
                      prev_epoch, n_rows_max: int) -> None:
    """Chain-pairing + sanity rules for one delta artifact."""
    if "delta" not in z:
        raise CheckpointCorruptError(
            f"{path}: not a delta artifact (chain links after the base "
            "must be checkpoint_delta outputs)")
    if bytes(np.asarray(z["cfg"])) != base_cfg_raw:
        raise CheckpointCorruptError(
            f"{path}: delta cfg does not match the chain's base cfg — "
            "links from different clusters cannot be mixed")
    pe = np.asarray(z["parent_epoch"]).ravel()
    prev = np.asarray(prev_epoch).ravel()
    if pe.shape != prev.shape or not (pe == prev).all():
        raise CheckpointCorruptError(
            f"{path}: parent epoch {pe.tolist()} does not pair with the "
            f"previous chain link's epoch {prev.tolist()} (wrong order, "
            "a skipped link, or artifacts from different chains)")
    rows = np.asarray(z["delta_rows"])
    pages = np.asarray(z["delta_pages"])
    if rows.ndim != 1 or pages.shape != (rows.size, _C.PAGE_WORDS):
        raise CheckpointCorruptError(
            f"{path}: delta rows/pages shape mismatch "
            f"({rows.shape} vs {pages.shape})")
    if rows.size and (rows.min() < 0 or rows.max() >= n_rows_max):
        raise CheckpointCorruptError(
            f"{path}: delta rows outside the pool "
            f"[0, {n_rows_max}) — corrupt row index")


def restore_chain(base_path: str, delta_paths, mesh=None,
                  clear_locks: bool = True):
    """Rebuild a live Cluster from ``base`` + ordered delta artifacts.

    Every artifact is CRC-verified and the (nonce, seq, crc) epoch chain
    is checked link by link — a corrupted, reordered or foreign link
    raises :class:`CheckpointCorruptError` instead of materializing a
    silently wrong pool.  The LAST link's locks/counters/allocator
    manifest win (each link carries the full small state).
    -> Cluster."""
    import jax
    import jax.numpy as jnp

    cluster = restore(base_path, mesh=mesh, clear_locks=clear_locks)
    if not delta_paths:
        return cluster
    dsm = cluster.dsm
    base = _load_arrays(base_path, keys=("cfg", "epoch"))
    if "epoch" not in base:
        raise CheckpointCorruptError(
            f"{base_path}: base carries no epoch (pre-chain legacy "
            "checkpoint) — take a fresh base to start a delta chain")
    base_cfg_raw = bytes(np.asarray(base["cfg"]))
    prev_epoch = np.asarray(base["epoch"])
    n_rows = dsm.pool.shape[0]
    for path in delta_paths:
        z = _load_arrays(path)
        _check_delta_link(z, path, base_cfg_raw, prev_epoch, n_rows)
        rows = np.asarray(z["delta_rows"], np.int64)
        if rows.size:
            dsm.pool = jax.device_put(
                dsm.pool.at[jnp.asarray(rows)].set(
                    jnp.asarray(z["delta_pages"])), dsm.shard)
        if dsm.heap is not None and "heap_rows" in z:
            hrows = np.asarray(z["heap_rows"], np.int64)
            if hrows.size:
                hpages = np.asarray(z["heap_pages"])
                if hpages.shape != (hrows.size, _C.PAGE_WORDS) \
                        or hrows.min() < 0 \
                        or hrows.max() >= dsm.heap.shape[0]:
                    raise CheckpointCorruptError(
                        f"{path}: heap delta rows/pages shape mismatch "
                        "or rows outside the heap region")
                dsm.heap = jax.device_put(
                    dsm.heap.at[jnp.asarray(hrows)].set(
                        jnp.asarray(hpages)), dsm.shard)
        locks = np.asarray(z["locks"])
        if clear_locks:
            locks = np.zeros_like(locks)
        dsm.locks = jax.device_put(locks, dsm.shard)
        dsm.counters = jax.device_put(np.asarray(z["counters"]), dsm.shard)
        _restore_directories(cluster, z)
        prev_epoch = np.asarray(z["epoch"])
    # restored state predates the crash-lost dirty tracking: callers
    # start a fresh chain (RecoveryPlane re-bases after replay)
    dsm.clear_dirty()
    return cluster


def read_chain_rows(base_path: str, delta_paths, rows) -> np.ndarray:
    """Reconstruct the CONTENT of specific pool rows as of the chain's
    tip, without materializing a cluster: the latest link containing a
    row wins, the base covers everything else.  The targeted-repair
    primitive (sherman_tpu/recovery.py): recovery cost scales with the
    damage, not the pool.  -> pages [len(rows), PAGE_WORDS] int32."""
    rows = np.asarray(rows, np.int64)
    base = _load_arrays(base_path)
    if "delta" in base:
        raise CheckpointCorruptError(
            f"{base_path}: chain base must be a full checkpoint")
    pool = np.asarray(base["pool"])
    if rows.size and (rows.min() < 0 or rows.max() >= pool.shape[0]):
        raise CheckpointCorruptError(
            f"repair rows outside the pool [0, {pool.shape[0]})")
    out = pool[rows].copy()
    base_cfg_raw = bytes(np.asarray(base["cfg"]))
    prev_epoch = np.asarray(base["epoch"]) if "epoch" in base else None
    for path in delta_paths:
        z = _load_arrays(path, keys=("delta", "cfg", "epoch",
                                     "parent_epoch", "delta_rows",
                                     "delta_pages"))
        if prev_epoch is None:
            raise CheckpointCorruptError(
                f"{base_path}: base carries no epoch to chain from")
        _check_delta_link(z, path, base_cfg_raw, prev_epoch,
                          pool.shape[0])
        drows = np.asarray(z["delta_rows"], np.int64)
        dpages = np.asarray(z["delta_pages"])
        pos = {int(r): i for i, r in enumerate(drows)}
        for i, r in enumerate(rows.tolist()):
            j = pos.get(int(r))
            if j is not None:
                out[i] = dpages[j]
        prev_epoch = np.asarray(z["epoch"])
    return out
