"""Host utilities: timing and leveled logging.

Reference parity: ``include/Timer.h`` (ns timer, spin-sleep, per-loop
print) and ``include/Debug.h`` / ``src/Debug.cpp`` (printf-style leveled
logging with ANSI colors, compile-time gates).
"""

from __future__ import annotations

from sherman_tpu.utils.debug import (DEBUG, ERROR, INFO, debug_item,
                                     notify_error, notify_info, set_level)
from sherman_tpu.utils.timer import Timer, spin_sleep_ns

__all__ = [
    "Timer", "spin_sleep_ns",
    "notify_info", "notify_error", "debug_item", "set_level",
    "INFO", "ERROR", "DEBUG",
]
