"""Host utilities: timing, leveled logging, tracing.

Reference parity: ``include/Timer.h`` (ns timer, spin-sleep, per-loop
print) and ``include/Debug.h`` / ``src/Debug.cpp`` (printf-style leveled
logging with ANSI colors, compile-time gates).  Beyond the reference:
step/phase tracing and XLA device traces, now part of the unified
observability plane (``sherman_tpu.obs``; ``utils.trace`` re-exports —
the reference has no tracer, SURVEY.md §5).
"""

from __future__ import annotations

from sherman_tpu.utils.debug import (DEBUG, ERROR, INFO, debug_item,
                                     notify_error, notify_info, set_level)
from sherman_tpu.utils.timer import Timer, spin_sleep_ns
from sherman_tpu.utils.trace import SpanTracer, StepTrace, device_trace

__all__ = [
    "Timer", "spin_sleep_ns",
    "notify_info", "notify_error", "debug_item", "set_level",
    "INFO", "ERROR", "DEBUG",
    "StepTrace", "SpanTracer", "device_trace",
]
