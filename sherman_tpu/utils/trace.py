"""Back-compat shim — tracing moved to :mod:`sherman_tpu.obs.spans`.

The observability subsystem (``sherman_tpu/obs/``) absorbed this
module: :class:`StepTrace` (the flat per-phase micro-tracer) and
:func:`device_trace` (the XLA profiler capture) live in
``obs.spans`` alongside the nested :class:`~sherman_tpu.obs.spans.
SpanTracer` and its Chrome-trace export.  Importing from here keeps
working for existing drivers and tests.
"""

from __future__ import annotations

from sherman_tpu.obs.spans import SpanTracer, StepTrace, device_trace

__all__ = ["StepTrace", "SpanTracer", "device_trace"]
