"""Step tracing/profiling — beyond the reference's Timer+rdtsc surface.

The reference has no tracer (SURVEY.md §5): profiling is a manual ns Timer
and latency histograms.  This module keeps those (``utils.timer``,
``native.LatencyHistogram``) and adds the TPU-native pieces:

- :class:`StepTrace` — per-named-phase wall spans with step counts, the
  micro-tracer for driver loops (host-side; ~100 ns overhead per record).
- :func:`device_trace` — context manager around ``jax.profiler.trace``:
  captures an XLA/TPU execution trace viewable in TensorBoard/Perfetto
  (kernel timings, DMA waits, fusion boundaries) for any code block.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict


class StepTrace:
    """Accumulate (phase -> spans) across a driver loop.

    >>> tr = StepTrace()
    >>> with tr.span("descend"):
    ...     ...
    >>> tr.summary()  # {'descend': {'n': 1, 'total_s': ..., 'mean_ms': ...}}
    """

    def __init__(self):
        self._spans = defaultdict(list)

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self._spans[name].append(time.perf_counter() - t0)

    def record(self, name: str, seconds: float) -> None:
        self._spans[name].append(float(seconds))

    def summary(self) -> dict[str, dict[str, float]]:
        out = {}
        for name, spans in self._spans.items():
            tot = sum(spans)
            out[name] = {"n": len(spans), "total_s": tot,
                         "mean_ms": tot / len(spans) * 1e3}
        return out

    def report(self) -> str:
        lines = []
        for name, s in sorted(self.summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{name:24s} n={s['n']:<6d} "
                         f"total={s['total_s']:8.3f}s "
                         f"mean={s['mean_ms']:8.3f}ms")
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture an XLA device trace for the enclosed block.

    View with TensorBoard's profile plugin or Perfetto.  No-op overhead
    outside the block; inside, the runtime records kernel/DMA timelines.
    """
    import jax
    with jax.profiler.trace(log_dir):
        yield
