"""Multihost service plane — per-host chain ownership, key routing,
and a cross-host front door (PR 19).

Sherman is a symmetric cluster bootstrapped by an all-pairs metadata
plane (survey L2/L3): every host serves clients against the shared
pool.  The reproduction sharded the POOL from PR 1, but the SERVICE —
the front door, the journal, the checkpoint chain — stayed
single-process.  This module is the service half:

- **ownership**: the key space is partitioned over hosts by a
  deterministic mix hash (:class:`HostRouter`).  Each host owns ONE
  journal stream and one chain namespace in the shared recovery
  directory (``base-h<i>.npz`` / ``delta-h<i>-<cid>-k.npz`` /
  ``journal-h<i>-<cid>-k.wal`` — ``sherman_tpu/recovery.py``), so N
  hosts fsync/rotate/sweep fully independently: ack bandwidth
  multiplies by host count instead of serializing on one stream.
- **front door**: per-host ingress dispatchers (one
  :class:`~sherman_tpu.serve.ShermanServer` per host, each with its
  own ``WidthController``) behind ONE logical
  :class:`MultihostService`: a submit splits by owner host, each
  sub-batch rides the owner's sealed programs, and the write ack gates
  on the OWNER's journal only.  :func:`merge_host_stats` folds the
  per-host receipts into one logical SLO plane (summed throughput
  counters, worst-host tail percentiles — on a real pod the same
  reduction is one psum over the per-host receipt vector).
- **recovery**: ``RecoveryPlane.recover_union`` — the union of
  per-host chains, each restored + replayed independently; a torn tail
  on one host never blocks another's replay, and cross-host replay
  order is immaterial because no two hosts' journals ever carry the
  same key (the router is the partition proof).
- **replication seam**: a follower on host B ships host A's chain by
  pointing the PR 16 tailer at A's namespace
  (``JournalTailer(dir, cid, host_id=A)``) — same shared
  ``apply_records`` core, now cross-host.

**Scope honesty.**  This container's jaxlib (0.4.37 CPU) has no
multiprocess collectives, so the plane is exercised via EMULATION: N
host contexts (N single-process clusters = N chain namespaces + one
routing table) in one process.  Every file-format, routing, recovery
and replication path is the real code; the transport (one mesh
spanning processes) is not — true 2-process drills stay gated behind
:func:`multihost_capable` (the conftest probe, re-homed here so bench
receipts can stamp it) and real-pod captures are queued in
BENCHMARKS.md.  ``SHERMAN_HOSTS=1`` (the shipped default) constructs
no plane at all: artifact names, journal bytes and receipts are
bit-identical to a build without this module.
"""

from __future__ import annotations

import threading

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ConfigError, StateError

_OBS_SPLITS = obs.counter("multihost.split_submits")
_OBS_ROUTED = obs.counter("multihost.routed_ops")
_OBS_SCANS = obs.counter("multihost.fanout_scans")
_OBS_ADOPTIONS = obs.counter("multihost.adoptions")


class HostDownError(StateError):
    """The owner host of (part of) this request is unreachable —
    crashed or frozen at the dispatch seam.  Typed so clients retry by
    rid once an adopter serves the namespace (exactly-once re-acks),
    instead of stranding a half-submitted merged future."""

#: cached :func:`multihost_capable` probe result —
#: ``[(ok: bool, reason: str)]`` once probed, shared with conftest
_MULTIHOST_PROBE: list = []

_PROBE_WORKER = r'''
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"localhost:{port}", 2, pid)
import numpy as np
from jax.experimental import multihost_utils
out = multihost_utils.process_allgather(np.asarray([pid], np.int32))
assert sorted(np.asarray(out).ravel().tolist()) == [0, 1]
print("PROBE-OK", flush=True)
'''


def multihost_capable() -> tuple[bool, str]:
    """(capable, reason) — can THIS jaxlib run CPU multiprocess
    collectives?  Probed once per process (two tiny subprocesses run a
    cross-process allgather with a deadline), subprocess-isolated so
    the probe can neither poison nor be poisoned by this process's jax
    runtime.  Gates the true 2-process drills
    (``tests/test_multihost.py``) and is stamped into bench receipts
    (``config.multihost_capable``) so chip-session artifacts are
    self-describing about which transport they exercised."""
    if _MULTIHOST_PROBE:
        return _MULTIHOST_PROBE[0]
    import os
    import socket
    import subprocess
    import sys as _sys
    import tempfile
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as d:
        worker = os.path.join(d, "probe.py")
        with open(worker, "w") as f:
            f.write(_PROBE_WORKER)
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = str(s.getsockname()[1])
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("XLA_FLAGS", None)
        procs = [subprocess.Popen(
            [_sys.executable, worker, str(pid), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True) for pid in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=120)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            _MULTIHOST_PROBE.append(
                (False, "probe timed out (collective hung)"))
            return _MULTIHOST_PROBE[0]
        if all(p.returncode == 0 and "PROBE-OK" in o
               for p, o in zip(procs, outs)):
            _MULTIHOST_PROBE.append((True, ""))
        else:
            tail = next((o for p, o in zip(procs, outs)
                         if p.returncode != 0), outs[0])[-600:]
            _MULTIHOST_PROBE.append(
                (False, "this jaxlib cannot run CPU multiprocess "
                 "collectives: " + tail.strip().replace("\n", " | ")))
    return _MULTIHOST_PROBE[0]


# ---------------------------------------------------------------------------
# Key -> owner-host routing
# ---------------------------------------------------------------------------

class HostRouter:
    """Deterministic key -> owner-host partition (the service plane's
    ownership function).  A splitmix64-style finalizer over the raw
    key, mod host count: stateless, identical on every host and every
    retry (exactly-once composes — a retried rid re-splits into the
    SAME per-host sub-batches), and independent of the tree's node
    routing (pool placement and service ownership are different
    axes: any host can read any page; only the owner journals the
    write).

    **Adoption overlay** (PR 20): :meth:`owner` is namespace IDENTITY
    and never changes — a dead host's keys still belong to ITS chain
    namespace.  The overlay answers a different question — which
    host's PROCESS currently serves that namespace
    (:meth:`route`): after host-loss failover, ``overlay[dead] =
    adopter``.  The map itself is durably journaled by the failover
    plane (``hostlease.OwnershipLog``); this is the in-memory routing
    view the service publishes.
    """

    __slots__ = ("hosts", "overlay")

    def __init__(self, hosts: int):
        if int(hosts) < 1:
            raise ConfigError(f"HostRouter wants hosts >= 1 (got {hosts})")
        self.hosts = int(hosts)
        #: namespace -> serving host (absent = serves itself)
        self.overlay: dict[int, int] = {}

    def route(self, host: int) -> int:
        """Which host's process serves ``host``'s namespace right now
        (identity until an adoption installs an overlay entry)."""
        return self.overlay.get(int(host), int(host))

    def adopt(self, dead: int, adopter: int) -> None:
        """Install one adoption: ``dead``'s namespace is now served by
        ``adopter``'s process.  Ownership (:meth:`owner`) is
        unchanged — the adopted front door runs over the DEAD
        namespace's recovered engine, not the adopter's own."""
        dead, adopter = int(dead), int(adopter)
        if not (0 <= dead < self.hosts and 0 <= adopter < self.hosts):
            raise ConfigError(
                f"adopt({dead} -> {adopter}): hosts outside "
                f"[0, {self.hosts})")
        if dead == adopter:
            raise ConfigError(f"host {dead} cannot adopt itself")
        self.overlay[dead] = adopter

    def handback(self, dead: int) -> None:
        """Drop one adoption overlay entry: ``dead``'s namespace
        serves itself again.  The routing half of the explicit
        hand-back (``hostlease.HostFailover.handback``) — the caller
        re-registers the returning host and rebuilds its door before
        traffic routes back."""
        self.overlay.pop(int(dead), None)

    def owner(self, keys) -> np.ndarray:
        """Owner host per key -> int32 [n] in [0, hosts)."""
        k = np.ascontiguousarray(keys, np.uint64)
        if self.hosts == 1:
            return np.zeros(k.shape, np.int32)
        # splitmix64 finalizer: unsigned wraparound is the algorithm
        x = k.copy()
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
        return (x % np.uint64(self.hosts)).astype(np.int32)

    def split(self, keys, values=None):
        """Partition one request by owner -> list of
        ``(host, idx, keys_h, values_h)`` with ``idx`` the positions
        of ``keys_h`` in the original batch (the merge permutation).
        Hosts with no keys in the batch are absent."""
        k = np.ascontiguousarray(keys, np.uint64)
        own = self.owner(k)
        v = None if values is None \
            else np.ascontiguousarray(values, np.uint64)
        out = []
        for h in range(self.hosts):
            idx = np.nonzero(own == h)[0]
            if idx.size:
                out.append((h, idx, k[idx],
                            None if v is None else v[idx]))
        return out


# ---------------------------------------------------------------------------
# Emulated host context
# ---------------------------------------------------------------------------

class HostContext:
    """One host's slice of the plane: its cluster/tree/engine triple,
    its recovery plane (chain namespace ``-h<host_id>-``), and its
    front-door server.  On a real pod each process holds exactly one
    of these (``SHERMAN_HOST_ID``); the CPU emulation constructs all N
    in one process — same objects, same files, in-process transport."""

    __slots__ = ("host_id", "cluster", "tree", "eng", "plane", "server")

    def __init__(self, host_id: int, cluster=None, tree=None, eng=None,
                 plane=None, server=None):
        self.host_id = int(host_id)
        self.cluster = cluster
        self.tree = tree
        self.eng = eng
        self.plane = plane
        self.server = server


# ---------------------------------------------------------------------------
# Cross-host front door
# ---------------------------------------------------------------------------

class _MergedFuture:
    """Future over one split submit: resolves when every owner host's
    sub-future has, reassembling per-host results into the original
    batch order.  Duck-types the :class:`~sherman_tpu.serve.ServeFuture`
    surface the clients use (``result`` / ``done`` / ``deduped``)."""

    __slots__ = ("op", "tenant", "n_ops", "rid", "parts", "_lock")

    def __init__(self, op: str, tenant: str, n_ops: int, rid,
                 parts: list):
        self.op = op
        self.tenant = tenant
        self.n_ops = int(n_ops)
        self.rid = rid
        #: [(host, idx, sub_future)] — idx maps sub-results home
        self.parts = parts
        self._lock = threading.Lock()

    def done(self) -> bool:
        return all(f.done() for _h, _i, f in self.parts)

    @property
    def deduped(self) -> bool:
        """True when EVERY owner host re-acked from its exactly-once
        window — the split retry's analog of the single-door flag (the
        router is deterministic, so a retried rid reaches the same
        owners and each dedups independently)."""
        return all(f.deduped for _h, _i, f in self.parts)

    def result(self, timeout: float | None = None):
        subs = [(idx, f.result(timeout)) for _h, idx, f in self.parts]
        if self.op == "read":
            vals = np.zeros(self.n_ops, np.uint64)
            found = np.zeros(self.n_ops, bool)
            for idx, (v, fnd) in subs:
                vals[idx] = np.asarray(v, np.uint64)
                found[idx] = np.asarray(fnd, bool)
            return vals, found
        # insert -> ok per key; delete -> found per key
        ok = np.zeros(self.n_ops, bool)
        for idx, r in subs:
            ok[idx] = np.asarray(r, bool)
        return ok


class _MergedScan:
    """Future over one fan-out scan: every host runs the SAME range
    set over its own shard (a hash partition scatters any range's keys
    across all hosts), and each range's per-host results concatenate
    and re-sort by key — ``range_query_many``'s per-range order,
    restored plane-wide.  Duck-types the ``ServeFuture`` surface."""

    __slots__ = ("tenant", "n_ranges", "parts")

    def __init__(self, tenant: str, n_ranges: int, parts: list):
        self.tenant = tenant
        self.n_ranges = int(n_ranges)
        #: [(host, sub_future)] — every host contributes to every range
        self.parts = parts

    def done(self) -> bool:
        return all(f.done() for _h, f in self.parts)

    @property
    def deduped(self) -> bool:
        return False            # scans never ride the write contract

    def result(self, timeout: float | None = None):
        per_host = [f.result(timeout) for _h, f in self.parts]
        out = []
        for r in range(self.n_ranges):
            ks = np.concatenate([np.asarray(ph[r][0], np.uint64)
                                 for ph in per_host])
            vs = np.concatenate([np.asarray(ph[r][1], np.uint64)
                                 for ph in per_host])
            order = np.argsort(ks, kind="stable")
            out.append((ks[order], vs[order]))
        return out


class MultihostService:
    """One logical front door over N per-host servers.

    Reads and writes split by owner host
    (:meth:`HostRouter.split`); each sub-batch is admitted by the
    owner's own ``WidthController``/tenant gates and — for writes —
    acked only after the OWNER's journal fsync covers it.  The merged
    future resolves in the original batch order.  Scans FAN OUT: a
    hash partition scatters every range's keys across all hosts, so
    each host runs the whole range set over its shard and the merged
    future re-sorts each range plane-wide (YCSB-E runs through the
    merged door).  The one typed refusal left is a scan carrying a
    resume ``cursor``: a cursor token is positional within ONE host's
    range walk and does not compose over a hash partition.

    The service itself holds NO pool state — it is a routing table
    plus futures glue, exactly the piece a real pod runs on every
    ingress host.
    """

    def __init__(self, servers, router: HostRouter | None = None,
                 planes=None):
        if not servers:
            raise ConfigError("MultihostService wants >= 1 server")
        self.servers = list(servers)
        self.hosts = len(self.servers)
        self.router = router or HostRouter(self.hosts)
        if self.router.hosts != self.hosts:
            raise ConfigError(
                f"router spans {self.router.hosts} hosts but "
                f"{self.hosts} servers were given")
        #: per-host recovery planes (host order) when the caller wants
        #: frontier tokens through the service handle; optional — the
        #: front door itself never touches the chain
        self.planes = list(planes) if planes is not None else None
        self._chaos = None      # HostChaos at the dispatch seam
        self.adoptions = 0

    def attach_chaos(self, host_chaos) -> None:
        """Install a ``chaos.HostChaos`` layer at the dispatch seam:
        every sub-batch's serving host is checked before routing —
        crashed/frozen hosts refuse typed (:class:`HostDownError`)."""
        self._chaos = host_chaos

    def _check_dispatch(self, owners) -> None:
        """Ask the chaos layer about EVERY serving host of this
        request BEFORE submitting any part — a typed refusal must not
        strand sub-futures already admitted on live hosts.  The
        dispatch clock ticks ONCE per service dispatch (refused or
        not), never once per host probed, so scheduled fault windows
        elapse independently of a request's fan-out."""
        if self._chaos is None:
            return
        try:
            for h in owners:
                serving = self.router.route(h)
                d = self._chaos.on_dispatch(serving)
                if d is not None and d.get("down"):
                    raise HostDownError(
                        f"host {serving} (serving namespace {h}) is "
                        f"unreachable ({d.get('state')}); retry by rid "
                        "once the namespace is adopted")
        finally:
            self._chaos.tick()

    def submit(self, op: str, keys=None, values=None, *,
               tenant: str = "default", ranges=None, cursor=None,
               rid=None, deadline_ms: float | None = None):
        """Split-admit one request across owner hosts -> a merged
        future (original batch order).  Single-host planes delegate
        straight through — zero added surface at hosts=1."""
        if cursor is not None:
            raise ConfigError(
                "scan cursors do not resume over a hash-partitioned "
                "host plane (a resume token is positional within one "
                "host's range walk); re-submit the full ranges, or "
                "resume on a single-host front door")
        if self.hosts == 1:
            return self.servers[0].submit(
                op, keys, values, tenant=tenant, ranges=ranges,
                rid=rid, deadline_ms=deadline_ms)
        if op == "scan":
            if not ranges:
                raise ConfigError("scan submit needs ranges")
            self._check_dispatch(range(self.hosts))
            _OBS_SCANS.inc()
            parts = [(h, self.servers[h].submit(
                "scan", tenant=tenant, ranges=ranges,
                deadline_ms=deadline_ms)) for h in range(self.hosts)]
            return _MergedScan(tenant, len(ranges), parts)
        keys = np.ascontiguousarray(keys, np.uint64)
        parts_in = self.router.split(keys, values)
        self._check_dispatch([h for h, _i, _k, _v in parts_in])
        _OBS_SPLITS.inc()
        _OBS_ROUTED.inc(int(keys.size))
        parts = []
        for h, idx, k_h, v_h in parts_in:
            f = self.servers[h].submit(
                op, k_h, v_h, tenant=tenant, rid=rid,
                deadline_ms=deadline_ms)
            parts.append((h, idx, f))
        return _MergedFuture(op, tenant, int(keys.size), rid, parts)

    def adopt(self, dead: int, server, *, plane=None,
              adopter: int | None = None) -> None:
        """Swap ``dead``'s front door for the ADOPTED one (a fresh
        server over the dead namespace's recovered engine, run by the
        adopter's process) and install the router overlay.  Called by
        ``hostlease.HostFailover.adopt`` after the done frame is
        durable — the service's in-memory view follows the journaled
        ownership map, never leads it."""
        dead = int(dead)
        if not (0 <= dead < self.hosts):
            raise ConfigError(f"adopt: host {dead} outside "
                              f"[0, {self.hosts})")
        self.servers[dead] = server
        if self.planes is not None and plane is not None:
            self.planes[dead] = plane
        if adopter is not None:
            self.router.adopt(dead, adopter)
        self.adoptions += 1
        _OBS_ADOPTIONS.inc()

    def journal_frontiers(self) -> list[tuple[str, int]]:
        """Per-host durable journal frontier tokens, host order —
        the union coverage token (a follower set covering every
        entry holds everything any host acked)."""
        if self.planes is None:
            raise StateError(
                "MultihostService was built without planes= — frontier "
                "tokens live on the per-host RecoveryPlanes")
        return [p.journal_frontier() for p in self.planes]

    def stats(self) -> dict:
        """One logical SLO plane over the per-host receipts
        (:func:`merge_host_stats`).  Adoption state rides along only
        once an adoption happened — an unfailed plane's receipt is
        byte-identical to the pre-failover build's."""
        out = merge_host_stats([s.stats() for s in self.servers])
        if self.adoptions:
            out["adoptions"] = self.adoptions
            out["overlay"] = {str(d): a for d, a
                              in sorted(self.router.overlay.items())}
        return out


def merge_host_stats(per_host: list[dict]) -> dict:
    """Fold per-host ``ShermanServer.stats()`` receipts into ONE
    logical SLO plane: throughput counters SUM (the plane serves the
    union of the hosts' traffic), tail percentiles take the WORST host
    (a plane's p99 promise is broken if any host's is), journal
    coalescing re-derives from the summed acks/fsyncs.  On a real pod
    this exact reduction is one psum over the per-host receipt vector
    — emulation computes it host-side, which is bit-identical for the
    integer counters by commutativity."""
    if not per_host:
        raise ConfigError("merge_host_stats wants >= 1 stats dict")
    merged = {
        "hosts": len(per_host),
        "admitted_ops": sum(s.get("admitted_ops", 0) for s in per_host),
        "served_ops": sum(s.get("served_ops", 0) for s in per_host),
        "acked_writes": sum(s.get("acked_writes", 0) for s in per_host),
        "rejects": {
            "overload": sum(s.get("rejects", {}).get("overload", 0)
                            for s in per_host),
            "degraded": sum(s.get("rejects", {}).get("degraded", 0)
                            for s in per_host),
        },
        "dispatch_errors": sum(s.get("dispatch_errors", 0)
                               for s in per_host),
        "retraces": sum(s.get("retraces", 0) for s in per_host),
        "widths": [(s.get("controller") or {}).get(
            "settled_width", (s.get("controller") or {}).get("cap_width"))
            for s in per_host],
    }
    # worst-host tail per op class over the hosts that observed it
    window: dict = {}
    for s in per_host:
        for cls, w in (s.get("window") or {}).items():
            cur = window.setdefault(cls, {
                "ops_s": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                "window_ops": 0, "ops_total": 0})
            cur["ops_s"] += float(w.get("ops_s", 0.0))
            cur["p50_ms"] = max(cur["p50_ms"],
                                float(w.get("p50_ms", 0.0)))
            cur["p99_ms"] = max(cur["p99_ms"],
                                float(w.get("p99_ms", 0.0)))
            cur["window_ops"] += int(w.get("window_ops", 0))
            cur["ops_total"] += int(w.get("ops_total", 0))
    merged["window"] = window
    # exactly-once window, summed (disjoint by construction: one rid's
    # entries live only on its sub-batches' owner hosts)
    merged["contract"] = {
        k: sum((s.get("contract") or {}).get(k, 0) for s in per_host)
        for k in ("dedup_hits", "deadline_shed", "duplicate_applies",
                  "cached_rids", "pending_rids")}
    fsyncs = sum((s.get("journal") or {}).get("fsyncs", 0)
                 for s in per_host)
    appends = sum((s.get("journal") or {}).get("appends", 0)
                  for s in per_host)
    if fsyncs:
        merged["journal"] = {
            "fsyncs": fsyncs, "appends": appends,
            "acks_per_fsync": round(
                merged["acked_writes"] / fsyncs, 3),
        }
    return merged


# ---------------------------------------------------------------------------
# Knob-gated construction
# ---------------------------------------------------------------------------

def plane_from_env() -> tuple[int, int]:
    """(hosts, host_id) from the knobs — the shipped default (1, 0)
    constructs NO plane (legacy names, one front door); callers pass
    the pair straight into ``RecoveryPlane(..., hosts=, host_id=)``."""
    return C.hosts(), C.host_id()
