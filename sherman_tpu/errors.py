"""Typed error taxonomy for the sherman_tpu library.

PR 4 started replacing bare ``ValueError``/``RuntimeError`` raises with
typed classes (``PallasUnavailableError``, ``ExchangeLaneError``) so
callers can branch on WHAT failed instead of string-matching messages;
this module finishes the sweep with a single hierarchy every library
raise belongs to.  ``shermanlint`` rule SL003 enforces it: a bare
``raise ValueError(...)`` / ``RuntimeError(...)`` / ``AssertionError``
in ``sherman_tpu/`` is a lint error.

Design rules:

- Every class multiply-inherits the stdlib exception it replaced
  (``ConfigError`` IS a ``ValueError``), so pre-existing
  ``except ValueError`` / ``pytest.raises(RuntimeError)`` callers keep
  working — the sweep is observable only to callers that opt into the
  typed classes.
- ``ShermanError`` is the catch-all root: ``except ShermanError`` traps
  every library-originated failure without also swallowing stdlib
  errors from user code.
- Subsystem-local typed errors that predate this module
  (``JournalCorruptError``, ``CheckpointCorruptError``,
  ``DegradedError``, ``TargetedRepairFailed``,
  ``PallasUnavailableError``, ``ExchangeLaneError``, ``PrepOverflow``)
  — and newer ones following the same pattern
  (``ServeOverloadError``, the serving front door's typed admission
  backpressure in :mod:`sherman_tpu.serve`) — stay defined next to
  the code that raises them; they all inherit ``ShermanError`` so the
  root catch covers them.  This module is import-leaf (stdlib only)
  precisely so they can.
"""

__all__ = [
    "ShermanError",
    "ConfigError",
    "KeyRangeError",
    "DoubleFreeError",
    "ProtocolError",
    "StateError",
    "MultiprocessUnsupportedError",
    "TreeCorruptError",
    "CheckpointFormatError",
    "ReshardError",
    "NativeBuildError",
    "NativeUnavailableError",
]


class ShermanError(Exception):
    """Root of every typed error the library raises."""


class ConfigError(ShermanError, ValueError):
    """A knob, argument, or environment value failed validation —
    including call preconditions ("bulk_load requires an empty tree"),
    malformed env vars, and unknown enum-style strings.  The message
    names the knob/argument and the accepted values."""


class KeyRangeError(ShermanError, ValueError):
    """Request keys fall outside ``[KEY_MIN, KEY_MAX]`` (the fence-key
    sentinels are reserved; see ops/bits.py)."""


class DoubleFreeError(ShermanError, ValueError):
    """A page was returned to the reclaim pool twice — granting it
    again would silently alias two leaves onto one page."""


class ProtocolError(ShermanError, RuntimeError):
    """A wire/lock/SPMD protocol invariant was breached at runtime:
    a host DSM op refused a row, a local-lock hand-over contract broke,
    or replicated drivers diverged across processes.  These indicate a
    bug (ours or the caller's driver), never a transient condition."""


class StateError(ShermanError, RuntimeError):
    """The object is in the wrong state for this call (journal closed,
    reclaim already running, no checkpoint chain started)."""


class MultiprocessUnsupportedError(ShermanError, RuntimeError):
    """A single-process-only feature was invoked on a multihost mesh
    (chaos injection, dirty-row export, RecoveryPlane, delta
    checkpoints)."""


class TreeCorruptError(ShermanError, RuntimeError):
    """Structural validation failed: the pool holds pages that violate
    the B+-tree invariants (validate.py names each violating class)."""


class CheckpointFormatError(ShermanError, RuntimeError):
    """A checkpoint artifact is structurally unusable — wrong build,
    wrong config, missing arrays, incompatible layout.  Distinct from
    :class:`~sherman_tpu.utils.checkpoint.CheckpointCorruptError`
    (content CRC mismatch on an artifact with the right shape)."""


class ReshardError(ShermanError, RuntimeError):
    """A checkpoint could not be repacked onto the target mesh shape
    (non-covering host shards, address overflow, shape mismatch)."""


class NativeBuildError(ShermanError, RuntimeError):
    """The native helper library failed to compile."""


class NativeUnavailableError(ShermanError, RuntimeError):
    """The native helper library is not importable/loadable in this
    environment; callers fall back to the pure-numpy paths."""
