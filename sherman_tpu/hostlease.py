"""Host-failure plane — cross-host lease table, zombie-host fencing,
and chain adoption by surviving hosts (PR 20).

The multihost service plane (PR 19) partitions the key space over
hosts but leaves a single-host blast radius: ``HostRouter`` is a
static key -> owner map, so a dead host's keyspace is unserveable
until a human intervenes.  This module composes three existing
single-host mechanisms into host-granularity failover:

- **cross-host lease table** (:class:`HostLeaseTable`): one durable
  heartbeat record per host in the SHARED chain directory
  (``hostlease-h<i>.rec`` — ``{host_id, epoch, hwm, timestamp}``,
  CRC-framed with the journal's own frame, updated by atomic rename),
  probed on the same expiry discipline as the client lease table
  (``cluster.lease_epochs``): expiry alone changes nothing durable —
  it licenses a surviving host to bump the dead host's epoch (the
  fence point) and adopt;
- **zombie-host fencing** (:class:`HostFence`): each host's journal
  durability gate checks its OWN host-lease epoch before every append
  — the ``_FencedJournal`` pattern of PR 18's replica plane lifted to
  host granularity.  A frozen-then-revived host whose epoch was
  bumped appends past a fence point captured at the bump; its
  post-expiry acks are a provably-never-merged fenced suffix
  (``audit.check_fenced_rejected`` + :func:`count_fenced_suffix`),
  and once its lease view heals, the next append raises a typed
  :class:`StaleHostError`;
- **chain adoption** (:class:`HostFailover`): on detected host death
  a surviving host runs the dead host's ``-h<dead>-`` namespace
  through the existing restore-then-replay core
  (``RecoveryPlane.recover`` scoped to one peer), re-seeds the dead
  host's exactly-once window into the adopted front door
  (``seed_dedup``, re-journaled for second-crash durability), and
  publishes an epoch-versioned ownership map.  The map is an
  APPEND-ONLY CRC-framed log (``ownership.maplog``): adoption writes
  a ``begin`` frame (carrying the captured fence point) before
  touching the dead chain and a ``done`` frame after the window
  re-seed, so an adopter crashing mid-adoption leaves a durable
  in-flight marker that :meth:`HostFailover.resume` completes —
  re-asserting the journaled epoch bump (the crash may have landed
  before :meth:`HostLeaseTable.expire` ran) and reusing the journaled
  fence (zombie appends between crash and resume stay in the fenced
  suffix) — takeover survives the adopter dying too.  A restarted
  previously-adopted host cannot silently rejoin at the fence epoch:
  ``register`` refuses typed until :meth:`HostFailover.handback`
  clears the overlay and opens a fresh lease generation.

**Scope honesty.**  Same caveat as the rest of the multihost plane:
this container's jaxlib has no multiprocess collectives, so hosts are
EMULATED (N host contexts in one process sharing one directory).
Every file format, the lease/fence/adoption protocol, and the
recovery paths are the real code; the transport is not.  ``hosts=1``
builds construct NONE of this (the table refuses construction), so
single-host artifacts and journal bytes stay bit-identical to
pre-plane builds — CI-pinned in ``scripts/hostfail_ci.sh``.

Observability: the ``hostfail.`` pull collector (leases_renewed /
expirations / adoptions / fenced_host_acks / adoption_ms) plus flight
events ``host.lease_expired`` / ``host.adopt_begin`` /
``host.adopt_done`` / ``host.zombie_fenced``, with the debounced
black-box dump fired on every completed adoption.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ShermanError, StateError
from sherman_tpu.utils import journal as J

_RECORD = "hostlease-h{host}.rec"
_MAPLOG = "ownership.maplog"

#: flat ``hostfail.`` pull-collector state — module-global because the
#: incrementing sites span three classes (table, fence, failover) that
#: share one plane; registered lazily on first table construction so
#: hosts=1 builds never even grow the collector (bit-identity)
_STATS = {"leases_renewed": 0, "expirations": 0, "adoptions": 0,
          "fenced_host_acks": 0, "adoption_ms": 0.0}
_COLLECTOR_ARMED: list = []


def _ensure_collector() -> None:
    if not _COLLECTOR_ARMED:
        obs.register_collector("hostfail", lambda: dict(_STATS))
        _COLLECTOR_ARMED.append(True)


class StaleHostError(StateError):
    """This host's lease epoch was bumped by an adopter: the append is
    fenced — a zombie host must not fork its (now adopted) journal."""


class HostAdoptedError(StateError):
    """This host's namespace is currently ADOPTED by a surviving peer:
    re-registering would rejoin at the fence epoch and dual-write the
    chain the adopter is serving.  An explicit hand-back
    (:meth:`HostFailover.handback`) clears the overlay and opens a
    fresh lease generation first."""


class HostLeaseCorruptError(ShermanError, RuntimeError):
    """A lease record failed its CRC frame — corruption in the lease
    table is a typed refusal, never a silently-parsed heartbeat."""


# ---------------------------------------------------------------------------
# The cross-host lease table
# ---------------------------------------------------------------------------


class HostLeaseTable:
    """Durable per-host heartbeat records in the shared chain
    directory.  One record per host, journal-CRC-framed, replaced
    atomically (tmp + fsync + ``os.replace``) so a reader never sees a
    torn heartbeat; liveness is judged by record age against
    ``lease_s`` (``SHERMAN_HOST_LEASE_S``), epochs by exact match —
    the client lease table's discipline (``cluster.lease_is_live``),
    durable on disk.

    Requires ``hosts >= 2``: a single-host plane has no peer to probe
    or adopt, and constructing a table there would break the hosts=1
    bit-identity contract (no ``hostlease-*`` files, no collector)."""

    def __init__(self, directory: str, hosts: int,
                 lease_s: float | None = None, chaos=None):
        if int(hosts) < 2:
            raise StateError(
                f"HostLeaseTable wants hosts >= 2 (got {hosts}); a "
                "single-host plane has no peer lease to keep")
        self.dir = directory
        self.hosts = int(hosts)
        self.lease_s = float(lease_s) if lease_s is not None \
            else C.host_lease_s()
        #: host-chaos layer (``chaos.HostChaos``): the lease-renewal
        #: seam — a crashed/frozen/zombified host's renewals are
        #: suppressed, so its lease expires under traffic
        self.chaos = chaos
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        _ensure_collector()

    def _path(self, host_id: int) -> str:
        return os.path.join(self.dir, _RECORD.format(host=int(host_id)))

    def _write(self, rec: dict) -> None:
        """Atomic durable record publish — tmp + fsync + rename, the
        follower-watermark pattern, under the journal CRC frame."""
        path = self._path(rec["host_id"])
        blob = J.frame_blob(json.dumps(rec, sort_keys=True).encode())
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read(self, host_id: int) -> dict | None:
        """The host's current heartbeat record, or None when absent
        (never registered / swept).  A record that fails its CRC frame
        raises :class:`HostLeaseCorruptError` typed."""
        try:
            with open(self._path(host_id), "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        try:
            return json.loads(J.unframe_blob(blob))
        except (J.JournalCorruptError, ValueError) as e:
            raise HostLeaseCorruptError(
                f"host {int(host_id)} lease record unreadable: {e}"
            ) from e

    def register(self, host_id: int, hwm=None) -> int:
        """Join (or re-join) the table: adopt the recorded epoch if a
        record exists (a restarting host continues its own lease
        generation), else start at epoch 1; write a fresh heartbeat.
        Returns the epoch this host now holds.

        A record carrying an ``adopter`` stamp is a namespace someone
        else is SERVING right now: rejoining at the recorded (fence)
        epoch would make ``HostFence.check`` pass on the restarted
        host while the adopter appends to the same chain — a
        dual-writer.  Refused typed (:class:`HostAdoptedError`) until
        an explicit hand-back (:meth:`handback`) clears the stamp."""
        rec = self.read(host_id)
        if rec is not None and "adopter" in rec:
            raise HostAdoptedError(
                f"host {int(host_id)}'s namespace is adopted by host "
                f"{int(rec['adopter'])} (fence epoch {int(rec['epoch'])}); "
                "re-registering would dual-write the adopted chain — "
                "hand the namespace back first")
        epoch = int(rec["epoch"]) if rec is not None else 1
        self.renew(host_id, epoch, hwm=hwm, force=True)
        return epoch

    def renew(self, host_id: int, epoch: int, hwm=None,
              force: bool = False) -> bool:
        """One heartbeat: re-stamp the record's timestamp (and the
        durable journal frontier ``hwm``, when given).  Returns False
        without writing when the chaos layer says this host makes no
        progress (crashed/frozen/zombie — the lease-renewal seam), or
        when the host no longer owns the recorded epoch (a fenced host
        must not resurrect its lease).  ``force`` skips the epoch
        guard for :meth:`register`."""
        if self.chaos is not None \
                and not self.chaos.allow_renew(int(host_id)):
            return False
        with self._lock:
            rec = None
            if not force:
                rec = self.read(host_id)
                if rec is not None and int(rec["epoch"]) != int(epoch):
                    return False
            new = {"host_id": int(host_id), "epoch": int(epoch),
                   "hwm": self._hwm_field(hwm),
                   "timestamp": time.time()}
            if rec is not None and "adopter" in rec:
                # the adoption stamp is sticky across heartbeats: only
                # an explicit hand-back may clear it
                new["adopter"] = int(rec["adopter"])
            self._write(new)
        _STATS["leases_renewed"] += 1
        return True

    @staticmethod
    def _hwm_field(hwm):
        """Journal-frontier token -> JSON shape: a
        ``RecoveryPlane.journal_frontier()`` pair becomes
        ``[segment basename, size]``; None stays None."""
        if hwm is None:
            return None
        path, size = hwm
        return [os.path.basename(str(path)), int(size)]

    def probe(self, host_id: int, now: float | None = None) -> str:
        """Liveness verdict: ``"live"`` / ``"expired"`` / ``"absent"``
        — record age against ``lease_s``, the client lease table's
        expiry discipline made durable."""
        rec = self.read(host_id)
        if rec is None:
            return "absent"
        now = time.time() if now is None else float(now)
        return "expired" if now - float(rec["timestamp"]) > self.lease_s \
            else "live"

    def is_live(self, host_id: int, epoch: int) -> bool:
        """Does ``host_id`` still hold ``epoch``?  Exact-match epoch
        discipline (``cluster.lease_is_live``): the adopter's durable
        epoch bump — not wall-clock expiry — is what fences a host;
        before the bump the (possibly slow) host is still the
        legitimate owner and its acks are legal."""
        rec = self.read(host_id)
        return rec is not None and int(rec["epoch"]) == int(epoch)

    def expire(self, host_id: int, adopter: int | None = None) -> int:
        """The fence: durably bump the host's lease epoch (the
        adoption-time analog of ``cluster.expire_client``).  Every
        later append through the old epoch's fence raises
        :class:`StaleHostError`.  Records the adopter for the
        published ownership story; returns the NEW epoch."""
        with self._lock:
            rec = self.read(host_id)
            old = int(rec["epoch"]) if rec is not None else 0
            new = {"host_id": int(host_id), "epoch": old + 1,
                   "hwm": rec.get("hwm") if rec is not None else None,
                   "timestamp": time.time()}
            if adopter is not None:
                new["adopter"] = int(adopter)
            self._write(new)
        _STATS["expirations"] += 1
        return old + 1

    def ensure_epoch(self, host_id: int, epoch: int,
                     adopter: int | None = None) -> int:
        """Idempotent fence toward a journaled epoch: durably raise
        ``host_id``'s lease epoch to AT LEAST ``epoch``.  The resume
        path's bump — an adopter that crashed between the ``begin``
        frame and :meth:`expire` left the dead host's epoch one short
        of the journaled fence, and without the repair the zombie's
        fence check and renewals would still pass.  A no-op when the
        recorded epoch already reached ``epoch`` (the bump happened
        before the crash).  Returns the recorded epoch after."""
        with self._lock:
            rec = self.read(host_id)
            cur = int(rec["epoch"]) if rec is not None else 0
            if cur >= int(epoch):
                return cur
            new = {"host_id": int(host_id), "epoch": int(epoch),
                   "hwm": rec.get("hwm") if rec is not None else None,
                   "timestamp": time.time()}
            if adopter is not None:
                new["adopter"] = int(adopter)
            self._write(new)
        _STATS["expirations"] += 1
        return int(epoch)

    def handback(self, host_id: int) -> int:
        """Clear the adopter stamp and bump the epoch — the explicit
        hand-back that lets a previously-adopted host re-register
        (:meth:`register` refuses typed while the stamp is set).  The
        bump opens a FRESH lease generation: every epoch the zombie or
        the adopter ever fenced against stays behind the new fence.
        Idempotent when no stamp is set (crash-retry safe); typed when
        the host never registered.  Returns the epoch a re-register
        will now join."""
        with self._lock:
            rec = self.read(host_id)
            if rec is None:
                raise StateError(
                    f"host {int(host_id)} has no lease record to hand "
                    "back")
            if "adopter" not in rec:
                return int(rec["epoch"])
            new = {"host_id": int(host_id),
                   "epoch": int(rec["epoch"]) + 1,
                   "hwm": rec.get("hwm"),
                   "timestamp": time.time()}
            self._write(new)
            return int(rec["epoch"]) + 1

    def epochs(self) -> dict:
        """{host: epoch} over every present record — the receipt
        shape."""
        out = {}
        for h in range(self.hosts):
            rec = self.read(h)
            if rec is not None:
                out[h] = int(rec["epoch"])
        return out


# ---------------------------------------------------------------------------
# The zombie fence at the journal durability gate
# ---------------------------------------------------------------------------


class _FencedHostJournal:
    """Journal proxy that checks this HOST's lease epoch before every
    append — PR 18's ``_FencedJournal`` lifted to host granularity.
    Everything else (close, stats, path, rotation handoff) delegates,
    so the recovery plane's rotation protocol is untouched."""

    def __init__(self, inner, fence: "HostFence"):
        self._inner = inner
        self._fence = fence

    def append(self, *a, **kw):
        self._fence.check()
        return self._inner.append(*a, **kw)

    def append_acks(self, *a, **kw):
        self._fence.check()
        return self._inner.append_acks(*a, **kw)

    def append_heap(self, *a, **kw):
        self._fence.check()
        return self._inner.append_heap(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class HostFence:
    """One host's epoch check at its journal durability gate.

    ``install(eng)`` wraps the engine's journal ATTACH point (not one
    segment), so every rotation's fresh segment appends through the
    check too.  The check routes the lease-table read through the
    chaos layer when attached: a zombified host sees a FROZEN snapshot
    of its own record — it cannot watch its epoch get bumped, so it
    keeps acking (the split-brain ingredient the fence point +
    fenced-suffix accounting make safe); the heal surfaces
    :class:`StaleHostError` to its next append."""

    def __init__(self, table: HostLeaseTable, host_id: int, epoch: int,
                 chaos=None):
        self.table = table
        self.host_id = int(host_id)
        self.epoch = int(epoch)
        self.chaos = chaos if chaos is not None else table.chaos
        self.fenced = 0  # appends refused typed through this fence

    def install(self, eng) -> None:
        fence = self
        orig_attach = eng.attach_journal

        def fenced_attach(journal):
            orig_attach(None if journal is None
                        else _FencedHostJournal(journal, fence))

        eng.attach_journal = fenced_attach
        if eng.journal is not None:
            orig_attach(_FencedHostJournal(eng.journal, fence))

    def check(self) -> None:
        rec = self.table.read(self.host_id)
        if self.chaos is not None:
            rec = self.chaos.lease_view(self.host_id, rec)
        live = rec is not None and int(rec["epoch"]) == self.epoch
        if not live:
            self.fenced += 1
            _STATS["fenced_host_acks"] += 1
            obs.record_event("host.zombie_fenced", host=self.host_id,
                             epoch=self.epoch,
                             table_epoch=None if rec is None
                             else int(rec["epoch"]))
            raise StaleHostError(
                f"host {self.host_id} lease epoch {self.epoch} was "
                "bumped (namespace adopted by a surviving host): this "
                "write is fenced — a zombie host must not fork its "
                "journal")


def count_fenced_suffix(fence: tuple[str, int] | None) -> int:
    """Complete CRC-valid frames past a fence point ``(path, size)``:
    writes a zombie host durably appended (and acked) AFTER its epoch
    bump — the provably-rejected set the drill pins against
    ``fenced_acks_merged``.  Trailing torn bytes are an unacked
    in-flight append, not counted.  (The replica plane's
    ``count_fenced_suffix`` walk, shared shape.)"""
    if fence is None:
        return 0
    path, base = fence
    try:
        with open(path, "rb") as f:
            f.seek(int(base))
            blob = f.read()
    except OSError:
        return 0
    n = 0
    pos = 0
    size = len(blob)
    while pos + J._HDR.size <= size:
        length, crc = J._HDR.unpack_from(blob, pos)
        end = pos + J._HDR.size + length
        if length > J.MAX_PAYLOAD or end > size:
            break
        if zlib.crc32(blob[pos + J._HDR.size:end]) != crc:
            break
        n += 1
        pos = end
    return n


# ---------------------------------------------------------------------------
# The epoch-versioned ownership map
# ---------------------------------------------------------------------------


class OwnershipLog:
    """Append-only CRC-framed adoption log (``ownership.maplog``) —
    the durable ownership map.  Every adoption appends a ``begin``
    frame (before the dead chain is touched) and a ``done`` frame
    (after the window re-seed), each ``{version, dead, adopter,
    epoch, state}`` with a monotonic version; :meth:`load` folds the
    frames into the current overlay plus the in-flight set, so an
    adopter crashing mid-adoption leaves a durable marker that
    :meth:`HostFailover.resume` completes.  A torn trailing frame is
    a crashed append — truncated-by-ignoring, the journal's own
    torn-tail rule."""

    def __init__(self, directory: str):
        self.path = os.path.join(directory, _MAPLOG)
        self._lock = threading.Lock()

    def append(self, rec: dict) -> None:
        blob = J.frame_blob(json.dumps(rec, sort_keys=True).encode())
        with self._lock:
            with open(self.path, "ab") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())

    def load(self) -> dict:
        """-> ``{"version", "overlay": {dead: adopter}, "pending":
        [(dead, adopter, epoch, fence), ...], "records"}``.
        ``overlay`` is the completed adoptions (latest version per
        dead host wins); ``pending`` the begun-but-not-done set a
        resume must finish, each carrying the fence point captured in
        its ``begin`` frame (``[relpath, size]`` or None) so the
        resume never recomputes it; a ``handback`` frame clears the
        host's overlay entry (the namespace serves itself again)."""
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            blob = b""
        frames, _clean = J.iter_frames(blob)
        records = [json.loads(p) for p in frames]
        overlay: dict = {}
        open_begins: dict = {}
        version = 0
        for r in records:
            version = max(version, int(r["version"]))
            dead = int(r["dead"])
            if r["state"] == "begin":
                open_begins[dead] = r
            elif r["state"] == "done":
                open_begins.pop(dead, None)
                overlay[dead] = int(r["adopter"])
            elif r["state"] == "handback":
                open_begins.pop(dead, None)
                overlay.pop(dead, None)
        pending = [(int(r["dead"]), int(r["adopter"]), int(r["epoch"]),
                    r.get("fence"))
                   for r in open_begins.values()]
        return {"version": version, "overlay": overlay,
                "pending": pending, "records": records}


# ---------------------------------------------------------------------------
# Chain adoption
# ---------------------------------------------------------------------------


class HostFailover:
    """Failure detector + adoption orchestrator for one shared chain
    directory.  Liveness rides :meth:`detect` (or the knob-gated
    background prober, ``SHERMAN_HOST_PROBE_S``); takeover is
    :meth:`adopt`: fence-point capture -> durable ``begin`` frame ->
    epoch bump -> restore-then-replay of the dead namespace ->
    exactly-once window re-seed into the adopted front door ->
    ``done`` frame + router overlay.  Crash-resume is
    :meth:`resume`."""

    def __init__(self, directory: str, table: HostLeaseTable,
                 hosts: int, recover_kw: dict | None = None):
        self.dir = directory
        self.table = table
        self.hosts = int(hosts)
        #: kwargs forwarded into ``RecoveryPlane.recover`` for the
        #: dead namespace (batch_per_node, tcfg, group_commit_ms, ...)
        self.recover_kw = dict(recover_kw or {})
        self.log = OwnershipLog(directory)
        self.adoption_ms = 0.0
        self._seen_expired: set[int] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        _ensure_collector()

    # -- detection -----------------------------------------------------------

    def detect(self, now: float | None = None) -> list[int]:
        """Expired hosts whose namespace nobody has adopted yet.  Each
        NEW expiry fires one ``host.lease_expired`` flight event."""
        adopted = set(self.log.load()["overlay"])
        out = []
        for h in range(self.hosts):
            if h in adopted:
                continue
            if self.table.probe(h, now=now) == "expired":
                out.append(h)
                if h not in self._seen_expired:
                    self._seen_expired.add(h)
                    obs.record_event("host.lease_expired", host=h,
                                     lease_s=self.table.lease_s)
        return out

    def unadopted_dead_hosts(self, now: float | None = None) -> int:
        """The drill's zero-pin: expired hosts still awaiting
        adoption."""
        return len(self.detect(now=now))

    def start(self) -> None:
        """Knob-gated background prober (``SHERMAN_HOST_PROBE_S`` > 0
        — ships OFF): sweeps :meth:`detect` so expiries surface as
        flight events without an operator in the loop.  Detection
        only; adoption stays an explicit call (WHO adopts is a
        placement decision)."""
        cadence = C.host_probe_s()
        if cadence <= 0 or self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                self.detect()
                self._stop.wait(cadence)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="sherman-host-probe")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- adoption ------------------------------------------------------------

    def fence_point(self, dead: int) -> tuple[str, int] | None:
        """The dead host's durable journal frontier ``(live segment
        path, clean size)`` from its on-disk chain — every byte a
        zombie appends past it is the fenced suffix.  ``clean size``
        is the last complete CRC-valid frame boundary, NOT the raw
        file size: a torn in-flight tail (crash mid-append) is about
        to be truncated away by the adoption's replay, and the
        zombie's post-truncation appends land exactly at the clean
        boundary.  None when the dead host has no live segment to
        fence."""
        from sherman_tpu.recovery import RecoveryPlane
        try:
            _cid, _deltas, journals = RecoveryPlane._discover(
                self.dir, host_id=int(dead))
        except FileNotFoundError:
            return None
        if not journals:
            return None
        path = journals[-1]
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return (path, 0)
        pos = len(J.MAGIC) if blob[:len(J.MAGIC)] == J.MAGIC else 0
        size = len(blob)
        while pos + J._HDR.size <= size:
            length, crc = J._HDR.unpack_from(blob, pos)
            end = pos + J._HDR.size + length
            if length > J.MAX_PAYLOAD or end > size:
                break
            if zlib.crc32(blob[pos + J._HDR.size:end]) != crc:
                break
            pos = end
        return (path, pos)

    def adopt(self, dead: int, adopter: int, *, door_factory=None,
              service=None) -> dict:
        """Take over ``dead``'s namespace onto ``adopter``.

        Protocol (every step durable before the next):

        1. capture the fence point (dead's live-segment size);
        2. append the ``begin`` frame, the fence point journaled
           inside it (crash after this is resumable, and the resume
           reuses THIS fence rather than recomputing a later one);
        3. durably raise dead's lease epoch to the journaled fence
           epoch (:meth:`HostLeaseTable.ensure_epoch`) — zombie
           appends from here land past the fence;
        4. restore-then-replay dead's chain (``RecoveryPlane.recover``
           scoped to one peer, stale sweep deferred so the fenced
           zombie segment stays on disk as evidence);
        5. ``door_factory(plane, cluster, tree, eng)`` builds + starts
           the adopted front door (run by the ADOPTER's process);
           the dead window re-seeds into it (``seed_dedup``,
           re-journaled);
        6. append the ``done`` frame, install the service overlay
           (keys of ``dead`` now route to ``adopter``), publish the
           receipt + the black-box dump.

        Returns the adoption receipt; the recovered context rides in
        under ``"context"`` for the caller to own."""
        st = self.log.load()
        version = st["version"] + 1
        # resume path re-enters with the begin frame already durable
        pend = next((p for p in st["pending"] if p[0] == int(dead)),
                    None)
        return self._run_adoption(int(dead), int(adopter), version,
                                  pend, door_factory, service)

    def resume(self, *, door_factory=None, service=None) -> list[dict]:
        """Finish every begun-but-not-done adoption in the ownership
        log — the adopter-crashed-mid-adoption exit.  Re-running the
        restore-then-replay core is safe: recover() rebuilds from the
        chain and re-bases.  The crash may have landed BETWEEN the
        begin frame and the epoch bump, so the resume re-asserts the
        journaled epoch (``ensure_epoch`` — a no-op when the bump
        already happened, a repair when it did not: without it the
        zombie's fence check and renewals would still pass while the
        adopter serves the namespace).  The fence point is the one
        captured in the begin frame, never recomputed — a zombie may
        have appended between the crash and the resume, and those
        frames belong to the fenced suffix too."""
        out = []
        for pend in self.log.load()["pending"]:
            version = self.log.load()["version"] + 1
            out.append(self._run_adoption(pend[0], pend[1], version,
                                          pend, door_factory, service))
        return out

    def handback(self, dead: int, router=None) -> int:
        """Explicit hand-back: the adopted namespace returns to its
        (restarted) owner.  Durably appends a ``handback`` frame to
        the ownership log (clearing the overlay, so ``detect`` can
        see the host again), clears the lease record's adopter stamp
        and bumps the epoch (:meth:`HostLeaseTable.handback` — the
        returning host re-registers into a FRESH generation, so no
        fence the adopter raised ever passes again), and drops the
        in-memory router overlay entry when a router is given.  The
        caller owns rebuilding the host's front door before routing
        traffic back.  Crash-retry safe: the log frame lands before
        the lease record changes, and both halves are idempotent.
        Returns the lease epoch a re-register now joins."""
        dead = int(dead)
        st = self.log.load()
        rec = self.table.read(dead)
        stamped = rec is not None and "adopter" in rec
        if dead not in st["overlay"] and not stamped:
            raise StateError(
                f"host {dead} is not adopted; nothing to hand back")
        if dead in st["overlay"]:
            self.log.append({"version": st["version"] + 1, "dead": dead,
                             "adopter": int(st["overlay"][dead]),
                             "epoch": (int(rec["epoch"]) + 1
                                       if rec is not None else 0),
                             "state": "handback"})
        epoch = self.table.handback(dead)
        self._seen_expired.discard(dead)
        if router is not None:
            router.handback(dead)
        obs.record_event("host.handback", host=dead, epoch=epoch)
        return epoch

    def _fence_field(self, fence: tuple[str, int] | None):
        """Fence point -> its begin-frame shape (path made relative to
        the chain directory, so the log moves with the directory)."""
        if fence is None:
            return None
        path, size = fence
        return [os.path.relpath(str(path), self.dir), int(size)]

    def _fence_from_field(self, field) -> tuple[str, int] | None:
        if field is None:
            return None
        rel, size = field
        return (os.path.join(self.dir, str(rel)), int(size))

    def _run_adoption(self, dead: int, adopter: int, version: int,
                      pending, door_factory, service) -> dict:
        from sherman_tpu.recovery import RecoveryPlane
        t0 = time.perf_counter()
        if pending is None:
            # fresh adoption: capture the fence, journal it inside the
            # durable intent marker, then bump the epoch — a crash
            # between any two steps leaves either nothing (retry from
            # detect) or a pending begin frame (resume)
            fence = self.fence_point(dead)
            rec = self.table.read(dead)
            epoch_new = (int(rec["epoch"]) if rec is not None else 0) + 1
            self.log.append({"version": version, "dead": dead,
                             "adopter": adopter, "epoch": epoch_new,
                             "state": "begin",
                             "fence": self._fence_field(fence)})
        else:
            _d, _a, epoch_new, fence_field = pending
            fence = self._fence_from_field(fence_field)
        # idempotent toward the journaled epoch: on the fresh path
        # this IS the bump; on resume it repairs the crash window
        # between the begin frame and the bump
        self.table.ensure_epoch(dead, epoch_new, adopter=adopter)
        obs.record_event("host.adopt_begin", dead=dead, adopter=adopter,
                         epoch=epoch_new, version=version,
                         fence=None if fence is None else
                         [os.path.basename(fence[0]), fence[1]])
        plane, cluster, tree, eng, rec = RecoveryPlane.recover(
            self.dir, host_id=dead, hosts=self.hosts,
            sweep_stale=False, **self.recover_kw)
        server = None
        seeded = 0
        if door_factory is not None:
            server = door_factory(plane, cluster, tree, eng)
            # second-crash durability: the re-journaled ack batch
            # lands in the ADOPTED chain's fresh segment
            seeded = server.seed_dedup(plane.dedup_window,
                                       rejournal=True)
        self.log.append({"version": version, "dead": dead,
                         "adopter": adopter, "epoch": epoch_new,
                         "state": "done"})
        if service is not None:
            service.adopt(dead,
                          server if server is not None
                          else service.servers[dead],
                          plane=plane, adopter=adopter)
        ms = (time.perf_counter() - t0) * 1e3
        self.adoption_ms = round(ms, 1)
        _STATS["adoptions"] += 1
        _STATS["adoption_ms"] = self.adoption_ms
        obs.record_event("host.adopt_done", dead=dead, adopter=adopter,
                         epoch=epoch_new, version=version,
                         seeded=seeded, adoption_ms=self.adoption_ms)
        # the black box: an adoption is exactly the kind of incident a
        # post-mortem replays — debounced like every other trigger
        obs.auto_dump("host.adopt_done")
        return {
            "dead": dead, "adopter": adopter, "version": version,
            "epoch": epoch_new, "seeded": seeded,
            "fence": None if fence is None else
            {"segment": os.path.basename(fence[0]), "size": fence[1]},
            "recover": rec,
            "adoption_ms": self.adoption_ms,
            "context": (plane, cluster, tree, eng),
            "server": server,
        }
