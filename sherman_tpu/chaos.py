"""Deterministic data-plane fault injection — the chaos subsystem.

The reference has no failure story at all and our control plane got one
in PR 1 (``utils/failure.py``: peer death, stalls, preemption).  This
module is the DATA plane's matching tool: a seedable, deterministic
:class:`FaultPlan` installed on the DSM and fired at the host-step
boundary (``DSM.step`` — the single injection hook), able to

- **corrupt pool words**: tear a page's front/rear version pair
  (``torn_page``) or flip one half of a leaf slot's packed fver/rver
  pair (``flip_entry_ver``) — exactly the torn-read classes Sherman
  gates behind ``CONFIG_ENABLE_CRC`` and the step-atomic design makes
  impossible *without* injection; the online scrubber
  (``models/scrub.py``) must catch both;
- **wedge lock words** as held-by-a-dead-client (``wedge_lock``): the
  lock word gets a lease no live client owns, so spin paths must detect
  and revoke it (lock-lease recovery) instead of hanging;
- **drop a step's CAS winners** (``drop_cas``): every CAS/masked-CAS
  request in the target step has its expectation perturbed so it loses
  honestly (ok=0) — retry paths must absorb it;
- **serve a stale-snapshot reply** (``stale_read``): page reads in the
  target step answer from an older pool snapshot — the torn-NIC-read
  analogue at step granularity.

Faults that corrupt STATE (torn/flip/wedge) record the overwritten
words, so :meth:`FaultPlan.undo` can restore them — the chaos fuzz
leans on this to inject, assert detection, repair and continue.

Determinism: everything derives from the plan (and its seed for
``random`` plans); the step index is the count of ``DSM.step`` calls
since installation.  Zero cost when off: the DSM's hook is a single
``is None`` test, and no engine/staged program changes at all.

Env: ``SHERMAN_CHAOS`` installs a plan on every DSM at construction —
either a JSON list of fault dicts (``[{"kind": "wedge_lock", "step":
2, "addr": 5}]``) or ``random:SEED[:N]`` for N seeded random faults.
Observability: every injection counts under ``chaos.*``.

Scope: single-process meshes (drills, CI, the CPU fuzz tier).  The
corruptions target the shared pool/locks arrays, so they are seen by
EVERY program — engine steps, staged loops, scrub kernels — not just
host-API steps; only ``drop_cas``/``stale_read`` are host-step-local.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ConfigError, MultiprocessUnsupportedError
from sherman_tpu.ops import bits

KINDS = ("torn_page", "flip_entry_ver", "wedge_lock", "drop_cas",
         "stale_read")

# a lease word no live client can own: unregistered owner tag + an
# epoch far from any real client's generation
DEAD_OWNER_TAG = 0xDEAD
DEAD_OWNER_EPOCH = 0x5A

_OBS = {k: obs.counter(f"chaos.{k}") for k in KINDS}
_OBS_TOTAL = obs.counter("chaos.faults_injected")


@dataclasses.dataclass
class Fault:
    """One scheduled fault.  ``step`` is the host-step index (count of
    ``DSM.step`` calls after plan installation) at which it fires; a
    fault whose step has already passed fires on the next step.
    ``addr`` is a packed pool-page address (torn/flip) or a lock-space
    address ``make_addr(node, lock_index)`` (wedge); ``addr=-1`` means
    "pick a live page/lock deterministically from the plan's RNG at
    fire time" (random plans) — a deferred corruption fault that finds
    no live page yet stays pending and retries at the next step."""

    kind: str
    step: int = 0
    addr: int = -1
    slot: int = 0                  # flip_entry_ver: leaf slot
    owner: int = DEAD_OWNER_TAG    # wedge_lock: lease owner tag
    epoch: int = DEAD_OWNER_EPOCH  # wedge_lock: lease epoch
    fired: bool = dataclasses.field(default=False, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ConfigError(f"unknown fault kind {self.kind!r}; "
                             f"want one of {KINDS}")


class FaultPlan:
    """A deterministic schedule of data-plane faults over one DSM."""

    def __init__(self, faults, seed: int = 0):
        self.faults = [f if isinstance(f, Fault) else Fault(**f)
                       for f in faults]
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self._steps = 0
        self._undo: list = []       # (space, row, col, old_value)
        self._stale_pool = None     # np snapshot for stale_read serving
        self.injected = 0

    # -- construction ---------------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``SHERMAN_CHAOS`` grammar: a JSON list of fault dicts, or
        ``random:SEED[:N]``."""
        spec = spec.strip()
        if spec.startswith("["):
            return cls(json.loads(spec))
        if spec.startswith("random"):
            parts = spec.split(":")
            seed = int(parts[1]) if len(parts) > 1 else 0
            n = int(parts[2]) if len(parts) > 2 else 3
            return cls.random(seed, n_faults=n)
        raise ConfigError(
            f"SHERMAN_CHAOS={spec!r}: want a JSON fault list or "
            "'random:SEED[:N]'")

    @classmethod
    def from_env(cls, env: str = "SHERMAN_CHAOS") -> "FaultPlan | None":
        spec = os.environ.get(env)
        return cls.parse(spec) if spec else None

    @classmethod
    def random(cls, seed: int, n_faults: int = 3, step_lo: int = 0,
               step_hi: int = 8, kinds=("torn_page", "flip_entry_ver",
                                        "wedge_lock")) -> "FaultPlan":
        """Seeded random plan.  Targets are deferred (``addr=-1``): each
        fault picks a live page (or a lock word) from the plan RNG at
        fire time, so the same seed over the same state sequence lands
        on the same words.  Default kinds are the persistent-corruption
        set whose DETECTION the chaos fuzz asserts; ``drop_cas`` /
        ``stale_read`` perturb only transient host-step replies."""
        rng = np.random.default_rng(int(seed))
        faults = [Fault(kind=str(rng.choice(list(kinds))),
                        step=int(rng.integers(step_lo, max(step_hi, 1))),
                        slot=int(rng.integers(0, C.LEAF_CAP)))
                  for _ in range(n_faults)]
        return cls(faults, seed=seed)

    @classmethod
    def storm(cls, seed: int, n_faults: int = 6, step_hi: int = 32
              ) -> "FaultPlan":
        """Seeded serving-storm plan: the persistent-corruption set
        PLUS the transient reply faults (``drop_cas``/``stale_read``)
        spread over a wider step range — the shape the front-door
        drills fire UNDER live client traffic (contract drill, client-
        contract fuzz), where retry paths must absorb lost CAS rounds
        and the scrubber/lease machinery must catch the rest."""
        return cls.random(seed, n_faults=n_faults, step_hi=step_hi,
                          kinds=KINDS)

    # -- the DSM hook (called under the DSM step mutex) -----------------------

    def on_step(self, dsm, reqs):
        """Fire every due fault; returns (reqs, post) where ``post`` is
        truthy when :meth:`on_replies` must post-process this step's
        replies (stale_read)."""
        if dsm.multihost:
            raise MultiprocessUnsupportedError(
                "chaos injection supports single-process meshes only")
        step = self._steps
        self._steps += 1
        post = False
        # arm the stale snapshot at the plan's FIRST step: serving a
        # fault-step read from its own pre-step pool would be the normal
        # reply — staleness must reach back at least one mutation
        if self._stale_pool is None and any(
                f.kind == "stale_read" and not f.fired
                for f in self.faults):
            self._stale_pool = np.asarray(dsm.pool)
        for f in self.faults:
            if f.fired or f.step > step:
                continue
            if f.kind == "torn_page":
                landed = self._torn_page(dsm, f)
            elif f.kind == "flip_entry_ver":
                landed = self._flip_entry_ver(dsm, f)
            elif f.kind == "wedge_lock":
                self._wedge_lock(dsm, f)
                landed = True
            elif f.kind == "drop_cas":
                reqs = self._drop_cas(reqs)
                landed = True
            else:  # stale_read: snapshot armed at the plan's first step
                post = True
                landed = True
            if not landed:
                continue  # nothing live to corrupt yet: defer the fault
            f.fired = True
            self.injected += 1
            _OBS_TOTAL.inc()
            _OBS[f.kind].inc()
            # black box: every injection is a flight event, so a drill's
            # dump shows WHAT was injected before WHAT was detected
            obs.record_event("chaos.inject", fault=f.kind, step=step,
                             addr=int(f.addr), slot=int(f.slot))
        return reqs, post

    def on_replies(self, dsm, reqs, rep):
        """stale_read: answer this step's page reads from the armed
        older snapshot (the reference's torn/stale NIC read, at step
        granularity)."""
        import sherman_tpu.parallel.dsm as D
        P = self._stale_pool.shape[0] // dsm.cfg.machine_nr
        op = np.asarray(reqs["op"]).reshape(-1)
        addr = np.asarray(reqs["addr"]).reshape(-1)
        data = np.array(rep.data)  # materialized replies are read-only
        for i in np.nonzero(op == D.OP_READ)[0]:
            node = bits.addr_node(int(addr[i]))
            page = bits.addr_page(int(addr[i]))
            row = node * P + page
            if 0 <= row < self._stale_pool.shape[0]:
                data[i] = self._stale_pool[row]
        return D.Replies(data=data, old=rep.old, ok=rep.ok)

    # -- fault bodies ---------------------------------------------------------

    def _pick_live_page(self, dsm) -> int:
        """Deferred-target resolution: a deterministic live non-meta
        page (front version != 0), from the plan RNG."""
        fv = np.asarray(dsm.pool[:, C.W_FRONT_VER])
        hi = np.asarray(dsm.pool[:, C.W_HIGH_HI])
        lo = np.asarray(dsm.pool[:, C.W_HIGH_LO])
        P = fv.shape[0] // dsm.cfg.machine_nr
        rows = np.nonzero((fv != 0) & ~((hi == 0) & (lo == 0))
                          & (np.arange(fv.shape[0]) % P != 0))[0]
        if rows.size == 0:
            return 0
        r = int(rows[int(self._rng.integers(0, rows.size))])
        return bits.make_addr(r // P, r % P)

    def _poke_pool(self, dsm, row: int, col: int, value: int) -> None:
        import jax
        old = int(np.asarray(dsm.pool[row, col]))
        self._undo.append(("pool", row, col, old, int(np.int32(value))))
        dsm.pool = jax.device_put(
            dsm.pool.at[row, col].set(np.int32(value)), dsm.shard)

    def _poke_lock(self, dsm, row: int, value: int) -> None:
        import jax
        old = int(np.asarray(dsm.locks[row]))
        self._undo.append(("lock", row, 0, old, int(np.int32(value))))
        dsm.locks = jax.device_put(
            dsm.locks.at[row].set(np.int32(value)), dsm.shard)

    def _pool_row(self, dsm, addr: int) -> int:
        return (bits.addr_node(addr) * dsm.cfg.pages_per_node
                + bits.addr_page(addr))

    def _torn_page(self, dsm, f: Fault) -> bool:
        """Tear the page's front/rear version pair: rear := front + 1
        (the mid-write state a torn NIC read would expose).  False when
        a deferred target (-1) found no live page to corrupt yet."""
        addr = f.addr if f.addr != -1 else self._pick_live_page(dsm)
        if addr == 0:
            return False
        row = self._pool_row(dsm, addr)
        front = int(np.asarray(dsm.pool[row, C.W_FRONT_VER]))
        self._poke_pool(dsm, row, C.W_REAR_VER, (front + 1) & 0x7FFFFFFF)
        return True

    def _flip_entry_ver(self, dsm, f: Fault) -> bool:
        """Flip the fver half of a leaf slot's packed version pair:
        fver != rver is unreachable by construction (ver_pack writes
        both halves equal in one step), so any occurrence is corruption
        the scrubber must flag.  False when a deferred target (-1)
        found no live page to corrupt yet."""
        addr = f.addr if f.addr != -1 else self._pick_live_page(dsm)
        if addr == 0:
            return False
        row = self._pool_row(dsm, addr)
        col = C.L_VER_W + (int(f.slot) % C.LEAF_CAP)
        old = int(np.asarray(dsm.pool[row, col]))
        self._poke_pool(dsm, row, col, old ^ (1 << 16))
        return True

    def _wedge_lock(self, dsm, f: Fault) -> None:
        """Wedge a lock word as held by a dead client: a lease no live
        registration owns.  ``addr`` addresses the lock space
        (``make_addr(node, lock_index)``); -1 picks a random word."""
        L = dsm.cfg.locks_per_node
        if f.addr != -1:
            row = bits.addr_node(f.addr) * L + bits.addr_page(f.addr)
        else:
            row = int(self._rng.integers(0, dsm.cfg.machine_nr * L))
        self._poke_lock(dsm, row,
                        bits.lease_word(f.owner or DEAD_OWNER_TAG,
                                        f.epoch))

    @staticmethod
    def _drop_cas(reqs):
        """Perturb every CAS/masked-CAS expectation in this step so the
        op honestly loses (ok=0) — the caller's retry path must absorb
        a cluster-wide lost-CAS round."""
        import sherman_tpu.parallel.dsm as D
        reqs = dict(reqs)
        op = np.asarray(reqs["op"])
        arg0 = np.array(reqs["arg0"], np.int32, copy=True)
        arg2 = np.asarray(reqs["arg2"])
        cas = op == D.OP_CAS
        arg0[cas] ^= np.int32(0x40000000)
        mcas = op == D.OP_MASKED_CAS
        # flip the masked bits of the expectation (mask 0 has no winner
        # to drop anyway)
        arg0[mcas] ^= arg2[mcas]
        reqs["arg0"] = arg0
        return reqs

    # -- repair / bookkeeping -------------------------------------------------

    def undo(self, dsm) -> int:
        """Restore every corrupted word (reverse order) — the fuzz
        harness's repair step.  A word that no longer holds the
        INJECTED value was legitimately rewritten after injection
        (e.g. a split rebuilt the page, a client re-acquired the lock):
        restoring the pre-fault value there would itself corrupt state,
        so such entries are skipped.  Returns the number of words
        restored.  Only state faults are undoable; drop_cas/stale_read
        perturbed replies, not state."""
        import jax
        n = 0
        for space, row, col, old, injected in reversed(self._undo):
            if space == "pool":
                if int(np.asarray(dsm.pool[row, col])) != injected:
                    continue  # overwritten since: leave the legit value
                dsm.pool = jax.device_put(
                    dsm.pool.at[row, col].set(np.int32(old)), dsm.shard)
            else:
                if int(np.asarray(dsm.locks[row])) != injected:
                    continue
                dsm.locks = jax.device_put(
                    dsm.locks.at[row].set(np.int32(old)), dsm.shard)
            n += 1
        self._undo.clear()
        return n

    def corrupted_pool_rows(self) -> list[int]:
        """Global pool rows of every POOL word this plan corrupted
        (pending undo) — the ground-truth damage set a recovery drill
        hands to targeted repair alongside the scrubber's flagged set
        (the scrubber only flags what a pass has SEEN violate).
        Convert with :meth:`rows_to_addrs`."""
        return sorted({row for space, row, *_ in self._undo
                       if space == "pool"})

    @staticmethod
    def rows_to_addrs(rows, pages_per_node: int) -> list[int]:
        """Global pool rows -> packed page addresses."""
        return [bits.make_addr(int(r) // pages_per_node,
                               int(r) % pages_per_node) for r in rows]

    @property
    def exhausted(self) -> bool:
        return all(f.fired for f in self.faults)

    def describe(self) -> list[dict]:
        return [{"kind": f.kind, "step": f.step, "addr": f.addr,
                 "fired": f.fired} for f in self.faults]
