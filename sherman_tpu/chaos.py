"""Deterministic data-plane fault injection — the chaos subsystem.

The reference has no failure story at all and our control plane got one
in PR 1 (``utils/failure.py``: peer death, stalls, preemption).  This
module is the DATA plane's matching tool: a seedable, deterministic
:class:`FaultPlan` installed on the DSM and fired at the host-step
boundary (``DSM.step`` — the single injection hook), able to

- **corrupt pool words**: tear a page's front/rear version pair
  (``torn_page``) or flip one half of a leaf slot's packed fver/rver
  pair (``flip_entry_ver``) — exactly the torn-read classes Sherman
  gates behind ``CONFIG_ENABLE_CRC`` and the step-atomic design makes
  impossible *without* injection; the online scrubber
  (``models/scrub.py``) must catch both;
- **wedge lock words** as held-by-a-dead-client (``wedge_lock``): the
  lock word gets a lease no live client owns, so spin paths must detect
  and revoke it (lock-lease recovery) instead of hanging;
- **drop a step's CAS winners** (``drop_cas``): every CAS/masked-CAS
  request in the target step has its expectation perturbed so it loses
  honestly (ok=0) — retry paths must absorb it;
- **serve a stale-snapshot reply** (``stale_read``): page reads in the
  target step answer from an older pool snapshot — the torn-NIC-read
  analogue at step granularity.

Faults that corrupt STATE (torn/flip/wedge) record the overwritten
words, so :meth:`FaultPlan.undo` can restore them — the chaos fuzz
leans on this to inject, assert detection, repair and continue.

Determinism: everything derives from the plan (and its seed for
``random`` plans); the step index is the count of ``DSM.step`` calls
since installation.  Zero cost when off: the DSM's hook is a single
``is None`` test, and no engine/staged program changes at all.

Env: ``SHERMAN_CHAOS`` installs a plan on every DSM at construction —
either a JSON list of fault dicts (``[{"kind": "wedge_lock", "step":
2, "addr": 5}]``) or ``random:SEED[:N]`` for N seeded random faults.
Observability: every injection counts under ``chaos.*``.

Scope: single-process meshes (drills, CI, the CPU fuzz tier).  The
corruptions target the shared pool/locks arrays, so they are seen by
EVERY program — engine steps, staged loops, scrub kernels — not just
host-API steps; only ``drop_cas``/``stale_read`` are host-step-local.

**Replication fault layer (PR 18).**  The data-plane kinds above
perturb POOL state; the ``repl_*`` kinds perturb the REPLICATION
plane's two message boundaries instead — the journal-shipping tail a
follower polls, and the lease-table view the primary's durability
fence consults:

- ``repl_drop``: a poll's fetch is lost — the follower sees no new
  bytes this round (offset untouched, natural retry);
- ``repl_delay``: shipped bytes are in flight — same observable as a
  drop in the pull model (nothing new until the window closes, then
  everything arrives at once), counted separately;
- ``repl_reorder``: the fetched byte view has two chunks swapped (the
  reordered-packet analogue) — per-frame CRC must detect it and the
  follower must retry a clean view, never apply;
- ``repl_partition``: the follower (scope ``"ship"``) cannot reach the
  primary's journal at all, and/or the PRIMARY (scope ``"lease"``)
  sees a frozen snapshot of the cluster lease table — the split-brain
  ingredient: a fenced primary that cannot observe its own epoch bump
  keeps acking until the partition heals;
- ``repl_slow``: the follower's poll stalls ``ms`` before fetching —
  the slow-node tail that quorum waits must absorb or time out on.

View faults never touch the journal FILE — they perturb one poll's
read of it, so detection-then-clean-retry is always possible and the
primary's durability story is never confused with the fault.
``ReplFault`` windows are measured on the layer's replication clock
(one tick per tailer poll across the group); the same seed over the
same poll sequence fires the same faults.  Drills drive partitions
manually with :meth:`ReplChaos.hold` / :meth:`ReplChaos.heal`.
Counters ride ``chaos.repl_*``; every window start is a
``chaos.repl_inject`` flight event.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ConfigError, MultiprocessUnsupportedError
from sherman_tpu.ops import bits

KINDS = ("torn_page", "flip_entry_ver", "wedge_lock", "drop_cas",
         "stale_read")
REPL_KINDS = ("repl_drop", "repl_delay", "repl_reorder",
              "repl_partition", "repl_slow")
HOST_KINDS = ("host_crash", "host_freeze", "host_zombie")

# a lease word no live client can own: unregistered owner tag + an
# epoch far from any real client's generation
DEAD_OWNER_TAG = 0xDEAD
DEAD_OWNER_EPOCH = 0x5A

_OBS = {k: obs.counter(f"chaos.{k}") for k in KINDS}
_OBS_TOTAL = obs.counter("chaos.faults_injected")
_OBS_REPL = {k: obs.counter(f"chaos.{k}") for k in REPL_KINDS}
_OBS_REPL_TOTAL = obs.counter("chaos.repl_faults_injected")
_OBS_REPL_DETECTED = obs.counter("chaos.repl_detected")
_OBS_HOST = {k: obs.counter(f"chaos.{k}") for k in HOST_KINDS}
_OBS_HOST_TOTAL = obs.counter("chaos.host_faults_injected")


@dataclasses.dataclass
class Fault:
    """One scheduled fault.  ``step`` is the host-step index (count of
    ``DSM.step`` calls after plan installation) at which it fires; a
    fault whose step has already passed fires on the next step.
    ``addr`` is a packed pool-page address (torn/flip) or a lock-space
    address ``make_addr(node, lock_index)`` (wedge); ``addr=-1`` means
    "pick a live page/lock deterministically from the plan's RNG at
    fire time" (random plans) — a deferred corruption fault that finds
    no live page yet stays pending and retries at the next step."""

    kind: str
    step: int = 0
    addr: int = -1
    slot: int = 0                  # flip_entry_ver: leaf slot
    owner: int = DEAD_OWNER_TAG    # wedge_lock: lease owner tag
    epoch: int = DEAD_OWNER_EPOCH  # wedge_lock: lease epoch
    fired: bool = dataclasses.field(default=False, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ConfigError(f"unknown fault kind {self.kind!r}; "
                             f"want one of {KINDS}")


@dataclasses.dataclass
class ReplFault:
    """One scheduled replication fault.  ``poll`` is the window start
    on the layer's replication clock (one tick per tailer poll across
    the whole group), ``span`` the window length in ticks.
    ``follower`` restricts ship-side faults to one follower index
    (-1 = all).  ``ms`` is the per-poll stall for ``repl_slow``.
    ``scope`` applies to ``repl_partition`` only: ``"ship"`` cuts the
    follower's view of the journal tail, ``"lease"`` freezes the
    PRIMARY's view of the cluster lease table, ``"both"`` does both."""

    kind: str
    poll: int = 0
    span: int = 1
    follower: int = -1
    ms: float = 2.0
    scope: str = "ship"
    fired: bool = dataclasses.field(default=False, compare=False)

    def __post_init__(self):
        if self.kind not in REPL_KINDS:
            raise ConfigError(f"unknown repl fault kind {self.kind!r}; "
                              f"want one of {REPL_KINDS}")
        if self.scope not in ("ship", "lease", "both"):
            raise ConfigError(f"repl fault scope {self.scope!r}: want "
                              "'ship', 'lease' or 'both'")
        if self.span < 1:
            raise ConfigError(f"repl fault span {self.span}: want >= 1")


class ReplChaos:
    """The replication-plane fault layer a :class:`FaultPlan` exposes.

    Attached to a ``ReplicaGroup`` (``group.attach_chaos``); the
    journal tailer asks :meth:`on_poll` for this poll's directives and
    routes fetched bytes through :meth:`view` when told to reorder;
    the primary's durability fence routes the lease table through
    :meth:`lease_view`.  Everything is deterministic in (plan, seed,
    poll sequence).  Drills drive partitions by hand with
    :meth:`hold`/:meth:`heal` — scheduled windows and manual holds
    compose."""

    def __init__(self, faults, seed: int = 0):
        self.faults = [f if isinstance(f, ReplFault) else ReplFault(**f)
                       for f in faults]
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed ^ 0x5EA1)
        self._clock = 0             # replication time: one tick per poll
        self._held: set[str] = set()
        self._lease_frozen = None   # snapshot while a lease cut is active
        self.injected = 0
        self.detected = 0

    @classmethod
    def storm(cls, seed: int, n_faults: int = 8, poll_hi: int = 24,
              span_hi: int = 4, followers: int = 2,
              kinds=REPL_KINDS) -> "ReplChaos":
        """Seeded random storm over the shipping tail: windows of
        drop/delay/reorder/partition/slow spread over ``poll_hi`` ticks
        of replication time.  Ship scope only — lease cuts change WHO
        may ack and belong to the drills' manual holds, not a fuzz
        storm's background noise."""
        rng = np.random.default_rng(int(seed))
        faults = [ReplFault(
            kind=str(rng.choice(list(kinds))),
            poll=int(rng.integers(0, max(poll_hi, 1))),
            span=1 + int(rng.integers(0, max(span_hi, 1))),
            follower=int(rng.integers(-1, max(followers, 1))),
            ms=float(rng.integers(1, 4)))
            for _ in range(n_faults)]
        return cls(faults, seed=seed)

    # -- scheduling -----------------------------------------------------------

    def _active(self, t: int, follower: int, side: str):
        """Faults whose window covers tick ``t`` for this follower and
        boundary (``side`` in {"ship", "lease"})."""
        out = []
        for f in self.faults:
            if not (f.poll <= t < f.poll + f.span):
                continue
            if side == "lease":
                if f.kind == "repl_partition" and f.scope in ("lease",
                                                             "both"):
                    out.append(f)
                continue
            if f.kind == "repl_partition" and f.scope == "lease":
                continue
            if f.follower not in (-1, follower):
                continue
            out.append(f)
        return out

    def _fire(self, f: ReplFault, t: int) -> None:
        if f.fired:
            return
        f.fired = True
        self.injected += 1
        _OBS_REPL_TOTAL.inc()
        _OBS_REPL[f.kind].inc()
        obs.record_event("chaos.repl_inject", fault=f.kind, poll=t,
                         span=int(f.span), follower=int(f.follower),
                         scope=f.scope)

    # -- the tailer hook (journal-shipping boundary) --------------------------

    def on_poll(self, follower: int = 0) -> dict | None:
        """Directives for this poll of ``follower``'s tailer, or None
        when nothing is active (the zero-cost common case).  Ticks the
        replication clock."""
        t = self._clock
        self._clock += 1
        live = self._active(t, follower, "ship")
        held = "ship" in self._held or "both" in self._held
        if not live and not held:
            return None
        d = {"drop": False, "freeze": False, "reorder": False,
             "partition": held, "slow_ms": 0.0}
        for f in live:
            self._fire(f, t)
            if f.kind == "repl_drop":
                d["drop"] = True
            elif f.kind == "repl_delay":
                d["freeze"] = True
            elif f.kind == "repl_reorder":
                d["reorder"] = True
            elif f.kind == "repl_partition":
                d["partition"] = True
            else:  # repl_slow
                d["slow_ms"] = max(d["slow_ms"], float(f.ms))
        return d

    def view(self, blob: bytes) -> bytes:
        """The reorder perturbation: swap two chunks of one poll's
        fetched byte view (the file itself is never touched).  Per-
        frame CRC must refuse the view; the next clean poll re-reads
        the true bytes from the unchanged offset."""
        n = len(blob)
        b = bytearray(blob)
        if n < 48:
            if n:                     # too short to swap: flip one bit
                b[n // 2] ^= 0x01
            return bytes(b)
        ch = 16
        i = int(self._rng.integers(0, n - 2 * ch))
        j = int(self._rng.integers(i + ch, n - ch + 1))
        b[i:i + ch], b[j:j + ch] = b[j:j + ch], b[i:i + ch]
        if bytes(b) == blob:          # identical chunks: force a change
            b[i] ^= 0x01
        return bytes(b)

    def note_detected(self) -> None:
        """A perturbed view was refused (typed corruption / empty
        fetch absorbed) — the detection half of every injection."""
        self.detected += 1
        _OBS_REPL_DETECTED.inc()

    # -- the fence hook (lease-table boundary) --------------------------------

    def lease_view(self, epochs: dict) -> dict:
        """The lease table as the PRIMARY's durability fence sees it.
        While a lease-scope partition is active the view is frozen at
        the cut's first observation — the primary cannot watch its own
        epoch get bumped, so it keeps acking (split-brain's stale
        half); healing restores the live table and the fence fires."""
        t = self._clock
        active = ("lease" in self._held or "both" in self._held
                  or bool(self._active(t, -1, "lease")))
        if not active:
            self._lease_frozen = None
            return epochs
        for f in self._active(t, -1, "lease"):
            self._fire(f, t)
        if self._lease_frozen is None:
            self._lease_frozen = dict(epochs)
        return self._lease_frozen

    # -- manual partition control (drills) ------------------------------------

    def hold(self, scope: str = "both") -> None:
        """Open a partition by hand (``scope`` in ship/lease/both) —
        held until :meth:`heal`.  Counted and flight-recorded like a
        scheduled window."""
        if scope not in ("ship", "lease", "both"):
            raise ConfigError(f"hold scope {scope!r}: want 'ship', "
                              "'lease' or 'both'")
        self._held.add(scope)
        self.injected += 1
        _OBS_REPL_TOTAL.inc()
        _OBS_REPL["repl_partition"].inc()
        obs.record_event("chaos.repl_inject", fault="repl_partition",
                         poll=self._clock, span=-1, follower=-1,
                         scope=scope)

    def heal(self) -> None:
        """Close every manual partition; the next fence check sees the
        live lease table and the next poll fetches real bytes."""
        self._held.clear()
        self._lease_frozen = None
        obs.record_event("chaos.repl_heal", poll=self._clock)

    @property
    def exhausted(self) -> bool:
        """Every scheduled window has passed and no manual hold is
        open — the storm is over."""
        return not self._held and all(
            f.poll + f.span <= self._clock for f in self.faults)

    def describe(self) -> list[dict]:
        return [{"kind": f.kind, "poll": f.poll, "span": f.span,
                 "follower": f.follower, "scope": f.scope,
                 "fired": f.fired} for f in self.faults]


@dataclasses.dataclass
class HostFault:
    """One scheduled HOST-granularity fault.  ``at`` is the window
    start on the host layer's dispatch clock (one tick per
    ``MultihostService`` dispatch), ``span`` the window length in
    ticks, ``host`` the victim host index.  ``host_crash`` and
    ``host_freeze`` make the host unreachable at the dispatch seam and
    suppress its lease renewals (crash = process gone, freeze = alive
    but making no progress); ``host_zombie`` keeps the host reachable
    and acking but freezes its VIEW of its own lease record — the
    fencing plane's split-brain ingredient."""

    kind: str
    host: int = 0
    at: int = 0
    span: int = 1
    fired: bool = dataclasses.field(default=False, compare=False)

    def __post_init__(self):
        if self.kind not in HOST_KINDS:
            raise ConfigError(f"unknown host fault kind {self.kind!r}; "
                              f"want one of {HOST_KINDS}")
        if self.span < 1:
            raise ConfigError(f"host fault span {self.span}: want >= 1")
        if self.host < 0:
            raise ConfigError(f"host fault host {self.host}: want >= 0")


class HostChaos:
    """The host-granularity fault layer a :class:`FaultPlan` exposes.

    Attached to a ``MultihostService`` (``service.attach_chaos``),
    which asks :meth:`on_dispatch` before routing any sub-batch to a
    host and then advances the dispatch clock ONCE per dispatch via
    :meth:`tick` (never once per host probed — fan-out must not age
    the schedule); the host lease table asks :meth:`allow_renew`
    before each heartbeat and routes a zombified host's self-reads
    through :meth:`lease_view`.  Drills drive failures by hand with
    :meth:`crash`/:meth:`freeze`/:meth:`revive`/:meth:`heal` — the
    two compose, like :class:`ReplChaos`'s holds."""

    def __init__(self, faults, seed: int = 0):
        self.faults = [f if isinstance(f, HostFault) else HostFault(**f)
                       for f in faults]
        self.seed = int(seed)
        self._clock = 0              # host time: one tick per dispatch
        self._crashed: set[int] = set()
        self._frozen: set[int] = set()
        self._zombie: set[int] = set()
        #: per-host frozen lease-record snapshots (zombie view)
        self._lease_frozen: dict[int, dict | None] = {}
        self.injected = 0

    # -- scheduling -----------------------------------------------------------

    def _active(self, t: int, host: int) -> list[HostFault]:
        return [f for f in self.faults
                if f.host == host and f.at <= t < f.at + f.span]

    def _fire(self, f: HostFault, t: int) -> None:
        if f.fired:
            return
        f.fired = True
        self.injected += 1
        _OBS_HOST_TOTAL.inc()
        _OBS_HOST[f.kind].inc()
        obs.record_event("chaos.host_inject", fault=f.kind,
                         host=int(f.host), at=t, span=int(f.span))

    def _manual_inject(self, kind: str, host: int) -> None:
        self.injected += 1
        _OBS_HOST_TOTAL.inc()
        _OBS_HOST[kind].inc()
        obs.record_event("chaos.host_inject", fault=kind,
                         host=int(host), at=self._clock, span=-1)

    def _state(self, host: int, t: int, tick_fire: bool) -> str:
        """Composed manual + scheduled state: ``"up"`` / ``"crash"`` /
        ``"freeze"`` / ``"zombie"`` (crash dominates freeze dominates
        zombie)."""
        host = int(host)
        kinds = set()
        if host in self._crashed:
            kinds.add("host_crash")
        if host in self._frozen:
            kinds.add("host_freeze")
        if host in self._zombie:
            kinds.add("host_zombie")
        for f in self._active(t, host):
            if tick_fire:
                self._fire(f, t)
            kinds.add(f.kind)
        if "host_crash" in kinds:
            return "crash"
        if "host_freeze" in kinds:
            return "freeze"
        if "host_zombie" in kinds:
            return "zombie"
        return "up"

    # -- the dispatch hook (service routing seam) -----------------------------

    def on_dispatch(self, host: int) -> dict | None:
        """Directive for routing one sub-batch to ``host`` at the
        CURRENT dispatch tick, or None when the host is healthy (the
        zero-cost common case).  ``{"down": True}`` means the host is
        unreachable (crashed or frozen) — the service must refuse
        typed rather than strand a sub-future.  A zombie host is NOT
        down: it accepts and acks (that's the hazard the fence
        catches).  Pure with respect to the clock: a request probes
        EVERY serving host at the same tick (a scan probes all of
        them), and :meth:`tick` advances time once per dispatch."""
        state = self._state(host, self._clock, tick_fire=True)
        if state == "up":
            return None
        return {"down": state in ("crash", "freeze"), "state": state}

    def tick(self) -> int:
        """Advance the dispatch clock by ONE — called exactly once
        per ``MultihostService`` dispatch, after the per-host probes,
        so scheduled fault windows elapse at the documented
        one-tick-per-dispatch rate regardless of a request's fan-out.
        Returns the new clock value."""
        self._clock += 1
        return self._clock

    # -- the lease-renewal seam -----------------------------------------------

    def allow_renew(self, host: int) -> bool:
        """May ``host`` heartbeat its lease record right now?  False
        while crashed, frozen OR zombified — a zombie's renewals are
        suppressed too (its lease legitimately expired; letting it
        re-stamp the record would resurrect the lease the adopter is
        about to bump)."""
        return self._state(int(host), self._clock,
                           tick_fire=False) == "up"

    def lease_view(self, host: int, record: dict | None):
        """``host``'s lease record as ITS OWN fence sees it.  While the
        host is frozen or zombified the view is pinned at the first
        observation — the host cannot watch its epoch get bumped, so
        it keeps acking; heal/revive restores the live record and the
        fence fires on the next append."""
        host = int(host)
        state = self._state(host, self._clock, tick_fire=False)
        if state in ("freeze", "zombie"):
            if host not in self._lease_frozen:
                self._lease_frozen[host] = None if record is None \
                    else dict(record)
            return self._lease_frozen[host]
        self._lease_frozen.pop(host, None)
        return record

    # -- manual failure control (drills) --------------------------------------

    def crash(self, host: int) -> None:
        """Kill ``host`` by hand: unreachable at the dispatch seam,
        renewals suppressed, until :meth:`revive`/:meth:`heal`."""
        self._crashed.add(int(host))
        self._manual_inject("host_crash", host)

    def freeze(self, host: int) -> None:
        """Freeze ``host`` by hand: alive but making no progress —
        dispatch refused, renewals suppressed, lease view pinned."""
        self._frozen.add(int(host))
        self._manual_inject("host_freeze", host)

    def revive(self, host: int, zombie: bool = True) -> None:
        """Bring a crashed/frozen host back.  ``zombie=True`` (the
        interesting case) revives it with its lease view still pinned
        at the pre-failure snapshot: it dispatches and acks as if it
        still owned its epoch — the fenced-suffix scenario.
        ``zombie=False`` is a clean restart (live view)."""
        host = int(host)
        self._crashed.discard(host)
        self._frozen.discard(host)
        if zombie:
            self._zombie.add(host)
            self._manual_inject("host_zombie", host)
        else:
            self._zombie.discard(host)
            self._lease_frozen.pop(host, None)

    def heal(self, host: int | None = None) -> None:
        """End every manual failure (or just ``host``'s): the next
        lease-view read sees the live record, so a fenced host's next
        append raises typed."""
        if host is None:
            self._crashed.clear()
            self._frozen.clear()
            self._zombie.clear()
            self._lease_frozen.clear()
        else:
            host = int(host)
            self._crashed.discard(host)
            self._frozen.discard(host)
            self._zombie.discard(host)
            self._lease_frozen.pop(host, None)
        obs.record_event("chaos.host_heal", at=self._clock,
                         host=-1 if host is None else host)

    @property
    def exhausted(self) -> bool:
        """Every scheduled window has passed and no manual failure is
        open."""
        return (not self._crashed and not self._frozen
                and not self._zombie and all(
                    f.at + f.span <= self._clock for f in self.faults))

    def describe(self) -> list[dict]:
        return [{"kind": f.kind, "host": f.host, "at": f.at,
                 "span": f.span, "fired": f.fired} for f in self.faults]


class FaultPlan:
    """A deterministic schedule of data-plane faults over one DSM.
    ``repl_*`` kinds in the same grammar are split out into the
    replication layer (:meth:`repl_layer`), ``host_*`` kinds into the
    host layer (:meth:`host_layer`), instead of the DSM hook."""

    def __init__(self, faults, seed: int = 0):
        self.faults = []
        repl = []
        host = []
        for f in faults:
            if isinstance(f, ReplFault):
                repl.append(f)
            elif isinstance(f, HostFault):
                host.append(f)
            elif isinstance(f, Fault):
                self.faults.append(f)
            elif isinstance(f, dict) and f.get("kind") in REPL_KINDS:
                repl.append(ReplFault(**f))
            elif isinstance(f, dict) and f.get("kind") in HOST_KINDS:
                host.append(HostFault(**f))
            else:
                self.faults.append(Fault(**f))
        self.seed = int(seed)
        self.repl_faults = repl
        self.host_faults = host
        self._repl_layer: ReplChaos | None = None
        self._host_layer: HostChaos | None = None
        self._rng = np.random.default_rng(self.seed)
        self._steps = 0
        self._undo: list = []       # (space, row, col, old_value)
        self._stale_pool = None     # np snapshot for stale_read serving
        self.injected = 0

    def repl_layer(self) -> "ReplChaos | None":
        """The plan's replication fault layer (None when the plan has
        no ``repl_*`` faults); built once, shared by every caller so
        the replication clock is group-global."""
        if self._repl_layer is None and self.repl_faults:
            self._repl_layer = ReplChaos(self.repl_faults,
                                         seed=self.seed)
        return self._repl_layer

    def host_layer(self) -> "HostChaos | None":
        """The plan's host fault layer (None when the plan has no
        ``host_*`` faults); built once, shared by every caller so the
        dispatch clock is service-global."""
        if self._host_layer is None and self.host_faults:
            self._host_layer = HostChaos(self.host_faults,
                                         seed=self.seed)
        return self._host_layer

    # -- construction ---------------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``SHERMAN_CHAOS`` grammar: a JSON list of fault dicts, or
        ``random:SEED[:N]``."""
        spec = spec.strip()
        if spec.startswith("["):
            return cls(json.loads(spec))
        if spec.startswith("random"):
            parts = spec.split(":")
            seed = int(parts[1]) if len(parts) > 1 else 0
            n = int(parts[2]) if len(parts) > 2 else 3
            return cls.random(seed, n_faults=n)
        raise ConfigError(
            f"SHERMAN_CHAOS={spec!r}: want a JSON fault list or "
            "'random:SEED[:N]'")

    @classmethod
    def from_env(cls, env: str = "SHERMAN_CHAOS") -> "FaultPlan | None":
        spec = os.environ.get(env)
        return cls.parse(spec) if spec else None

    @classmethod
    def random(cls, seed: int, n_faults: int = 3, step_lo: int = 0,
               step_hi: int = 8, kinds=("torn_page", "flip_entry_ver",
                                        "wedge_lock")) -> "FaultPlan":
        """Seeded random plan.  Targets are deferred (``addr=-1``): each
        fault picks a live page (or a lock word) from the plan RNG at
        fire time, so the same seed over the same state sequence lands
        on the same words.  Default kinds are the persistent-corruption
        set whose DETECTION the chaos fuzz asserts; ``drop_cas`` /
        ``stale_read`` perturb only transient host-step replies."""
        rng = np.random.default_rng(int(seed))
        faults = [Fault(kind=str(rng.choice(list(kinds))),
                        step=int(rng.integers(step_lo, max(step_hi, 1))),
                        slot=int(rng.integers(0, C.LEAF_CAP)))
                  for _ in range(n_faults)]
        return cls(faults, seed=seed)

    @classmethod
    def storm(cls, seed: int, n_faults: int = 6, step_hi: int = 32
              ) -> "FaultPlan":
        """Seeded serving-storm plan: the persistent-corruption set
        PLUS the transient reply faults (``drop_cas``/``stale_read``)
        spread over a wider step range — the shape the front-door
        drills fire UNDER live client traffic (contract drill, client-
        contract fuzz), where retry paths must absorb lost CAS rounds
        and the scrubber/lease machinery must catch the rest."""
        return cls.random(seed, n_faults=n_faults, step_hi=step_hi,
                          kinds=KINDS)

    # -- the DSM hook (called under the DSM step mutex) -----------------------

    def on_step(self, dsm, reqs):
        """Fire every due fault; returns (reqs, post) where ``post`` is
        truthy when :meth:`on_replies` must post-process this step's
        replies (stale_read)."""
        if dsm.multihost:
            raise MultiprocessUnsupportedError(
                "chaos injection supports single-process meshes only")
        step = self._steps
        self._steps += 1
        post = False
        # arm the stale snapshot at the plan's FIRST step: serving a
        # fault-step read from its own pre-step pool would be the normal
        # reply — staleness must reach back at least one mutation
        if self._stale_pool is None and any(
                f.kind == "stale_read" and not f.fired
                for f in self.faults):
            self._stale_pool = np.asarray(dsm.pool)
        for f in self.faults:
            if f.fired or f.step > step:
                continue
            if f.kind == "torn_page":
                landed = self._torn_page(dsm, f)
            elif f.kind == "flip_entry_ver":
                landed = self._flip_entry_ver(dsm, f)
            elif f.kind == "wedge_lock":
                self._wedge_lock(dsm, f)
                landed = True
            elif f.kind == "drop_cas":
                reqs = self._drop_cas(reqs)
                landed = True
            else:  # stale_read: snapshot armed at the plan's first step
                post = True
                landed = True
            if not landed:
                continue  # nothing live to corrupt yet: defer the fault
            f.fired = True
            self.injected += 1
            _OBS_TOTAL.inc()
            _OBS[f.kind].inc()
            # black box: every injection is a flight event, so a drill's
            # dump shows WHAT was injected before WHAT was detected
            obs.record_event("chaos.inject", fault=f.kind, step=step,
                             addr=int(f.addr), slot=int(f.slot))
        return reqs, post

    def on_replies(self, dsm, reqs, rep):
        """stale_read: answer this step's page reads from the armed
        older snapshot (the reference's torn/stale NIC read, at step
        granularity)."""
        import sherman_tpu.parallel.dsm as D
        P = self._stale_pool.shape[0] // dsm.cfg.machine_nr
        op = np.asarray(reqs["op"]).reshape(-1)
        addr = np.asarray(reqs["addr"]).reshape(-1)
        data = np.array(rep.data)  # materialized replies are read-only
        for i in np.nonzero(op == D.OP_READ)[0]:
            node = bits.addr_node(int(addr[i]))
            page = bits.addr_page(int(addr[i]))
            row = node * P + page
            if 0 <= row < self._stale_pool.shape[0]:
                data[i] = self._stale_pool[row]
        return D.Replies(data=data, old=rep.old, ok=rep.ok)

    # -- fault bodies ---------------------------------------------------------

    def _pick_live_page(self, dsm) -> int:
        """Deferred-target resolution: a deterministic live non-meta
        page (front version != 0), from the plan RNG."""
        fv = np.asarray(dsm.pool[:, C.W_FRONT_VER])
        hi = np.asarray(dsm.pool[:, C.W_HIGH_HI])
        lo = np.asarray(dsm.pool[:, C.W_HIGH_LO])
        P = fv.shape[0] // dsm.cfg.machine_nr
        rows = np.nonzero((fv != 0) & ~((hi == 0) & (lo == 0))
                          & (np.arange(fv.shape[0]) % P != 0))[0]
        if rows.size == 0:
            return 0
        r = int(rows[int(self._rng.integers(0, rows.size))])
        return bits.make_addr(r // P, r % P)

    def _poke_pool(self, dsm, row: int, col: int, value: int) -> None:
        import jax
        old = int(np.asarray(dsm.pool[row, col]))
        self._undo.append(("pool", row, col, old, int(np.int32(value))))
        dsm.pool = jax.device_put(
            dsm.pool.at[row, col].set(np.int32(value)), dsm.shard)

    def _poke_lock(self, dsm, row: int, value: int) -> None:
        import jax
        old = int(np.asarray(dsm.locks[row]))
        self._undo.append(("lock", row, 0, old, int(np.int32(value))))
        dsm.locks = jax.device_put(
            dsm.locks.at[row].set(np.int32(value)), dsm.shard)

    def _pool_row(self, dsm, addr: int) -> int:
        return (bits.addr_node(addr) * dsm.cfg.pages_per_node
                + bits.addr_page(addr))

    def _torn_page(self, dsm, f: Fault) -> bool:
        """Tear the page's front/rear version pair: rear := front + 1
        (the mid-write state a torn NIC read would expose).  False when
        a deferred target (-1) found no live page to corrupt yet."""
        addr = f.addr if f.addr != -1 else self._pick_live_page(dsm)
        if addr == 0:
            return False
        row = self._pool_row(dsm, addr)
        front = int(np.asarray(dsm.pool[row, C.W_FRONT_VER]))
        self._poke_pool(dsm, row, C.W_REAR_VER, (front + 1) & 0x7FFFFFFF)
        return True

    def _flip_entry_ver(self, dsm, f: Fault) -> bool:
        """Flip the fver half of a leaf slot's packed version pair:
        fver != rver is unreachable by construction (ver_pack writes
        both halves equal in one step), so any occurrence is corruption
        the scrubber must flag.  False when a deferred target (-1)
        found no live page to corrupt yet."""
        addr = f.addr if f.addr != -1 else self._pick_live_page(dsm)
        if addr == 0:
            return False
        row = self._pool_row(dsm, addr)
        col = C.L_VER_W + (int(f.slot) % C.LEAF_CAP)
        old = int(np.asarray(dsm.pool[row, col]))
        self._poke_pool(dsm, row, col, old ^ (1 << 16))
        return True

    def _wedge_lock(self, dsm, f: Fault) -> None:
        """Wedge a lock word as held by a dead client: a lease no live
        registration owns.  ``addr`` addresses the lock space
        (``make_addr(node, lock_index)``); -1 picks a random word."""
        L = dsm.cfg.locks_per_node
        if f.addr != -1:
            row = bits.addr_node(f.addr) * L + bits.addr_page(f.addr)
        else:
            row = int(self._rng.integers(0, dsm.cfg.machine_nr * L))
        self._poke_lock(dsm, row,
                        bits.lease_word(f.owner or DEAD_OWNER_TAG,
                                        f.epoch))

    @staticmethod
    def _drop_cas(reqs):
        """Perturb every CAS/masked-CAS expectation in this step so the
        op honestly loses (ok=0) — the caller's retry path must absorb
        a cluster-wide lost-CAS round."""
        import sherman_tpu.parallel.dsm as D
        reqs = dict(reqs)
        op = np.asarray(reqs["op"])
        arg0 = np.array(reqs["arg0"], np.int32, copy=True)
        arg2 = np.asarray(reqs["arg2"])
        cas = op == D.OP_CAS
        arg0[cas] ^= np.int32(0x40000000)
        mcas = op == D.OP_MASKED_CAS
        # flip the masked bits of the expectation (mask 0 has no winner
        # to drop anyway)
        arg0[mcas] ^= arg2[mcas]
        reqs["arg0"] = arg0
        return reqs

    # -- repair / bookkeeping -------------------------------------------------

    def undo(self, dsm) -> int:
        """Restore every corrupted word (reverse order) — the fuzz
        harness's repair step.  A word that no longer holds the
        INJECTED value was legitimately rewritten after injection
        (e.g. a split rebuilt the page, a client re-acquired the lock):
        restoring the pre-fault value there would itself corrupt state,
        so such entries are skipped.  Returns the number of words
        restored.  Only state faults are undoable; drop_cas/stale_read
        perturbed replies, not state."""
        import jax
        n = 0
        for space, row, col, old, injected in reversed(self._undo):
            if space == "pool":
                if int(np.asarray(dsm.pool[row, col])) != injected:
                    continue  # overwritten since: leave the legit value
                dsm.pool = jax.device_put(
                    dsm.pool.at[row, col].set(np.int32(old)), dsm.shard)
            else:
                if int(np.asarray(dsm.locks[row])) != injected:
                    continue
                dsm.locks = jax.device_put(
                    dsm.locks.at[row].set(np.int32(old)), dsm.shard)
            n += 1
        self._undo.clear()
        return n

    def corrupted_pool_rows(self) -> list[int]:
        """Global pool rows of every POOL word this plan corrupted
        (pending undo) — the ground-truth damage set a recovery drill
        hands to targeted repair alongside the scrubber's flagged set
        (the scrubber only flags what a pass has SEEN violate).
        Convert with :meth:`rows_to_addrs`."""
        return sorted({row for space, row, *_ in self._undo
                       if space == "pool"})

    @staticmethod
    def rows_to_addrs(rows, pages_per_node: int) -> list[int]:
        """Global pool rows -> packed page addresses."""
        return [bits.make_addr(int(r) // pages_per_node,
                               int(r) % pages_per_node) for r in rows]

    @property
    def exhausted(self) -> bool:
        """Every DATA-plane fault has fired (repl windows are judged by
        :attr:`ReplChaos.exhausted` on the layer's own clock)."""
        return all(f.fired for f in self.faults)

    def describe(self) -> list[dict]:
        out = [{"kind": f.kind, "step": f.step, "addr": f.addr,
                "fired": f.fired} for f in self.faults]
        if self._repl_layer is not None:
            out.extend(self._repl_layer.describe())
        else:
            out.extend({"kind": f.kind, "poll": f.poll, "span": f.span,
                        "follower": f.follower, "scope": f.scope,
                        "fired": f.fired} for f in self.repl_faults)
        if self._host_layer is not None:
            out.extend(self._host_layer.describe())
        else:
            out.extend({"kind": f.kind, "host": f.host, "at": f.at,
                        "span": f.span, "fired": f.fired}
                       for f in self.host_faults)
        return out
