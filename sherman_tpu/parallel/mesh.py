"""Device mesh helpers.

The cluster axis is a 1-D ``jax.sharding.Mesh`` named ``"node"``: each device
plays the role of one symmetric Sherman node (compute node + memory node,
reference ``README.md:60-61``).  Tests run this on 8 virtual CPU devices via
``XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

import jax
import numpy as np

from sherman_tpu.errors import ConfigError

AXIS = "node"


def make_mesh(n_nodes: int | None = None) -> jax.sharding.Mesh:
    devs = jax.devices()
    n = n_nodes if n_nodes is not None else len(devs)
    if len(devs) < n:
        raise ConfigError(f"need {n} devices, have {len(devs)}")
    return jax.sharding.Mesh(np.array(devs[:n]), (AXIS,))


def node_sharding(mesh: jax.sharding.Mesh) -> jax.sharding.NamedSharding:
    """Shard dim 0 across nodes."""
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(AXIS))
