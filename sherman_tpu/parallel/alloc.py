"""Remote-memory allocation: chunk-grain directory service + client bumps.

Three cooperating pieces, mirroring the reference:

- :class:`GlobalAllocator` — per memory node, owned by its directory: hands
  out fixed-size chunks of the node's pool partition, bump-scan, no reuse
  (``GlobalAllocator.h:31-50``; 32 MB chunks, ``Common.h:80``).
- :class:`Directory` — the per-node memory-node agent serving MALLOC / FREE
  / NEW_ROOT (``Directory.cpp:60-92``).  In single-process SPMD the "RPC"
  is a method call; the interface is kept RPC-shaped (explicit request
  types) so a multi-host build can put a real host service behind it.
- :class:`LocalAllocator` — per client thread: bump-allocates pages inside
  leased chunks, round-robining target nodes per allocation the way
  ``DSM::alloc`` round-robins its chunk leases (``DSM.h:200-221``,
  ``LocalAllocator.h:21-43``).  ``free`` is a no-op, faithful to the
  reference (``DSM.h:226``).

Page 0 of every node is reserved (page 0 of node 0 carries the root-pointer
meta words; addr 0 doubles as NULL).
"""

from __future__ import annotations

import threading

import numpy as np

from sherman_tpu.config import ADDR_PAGE_BITS, DSMConfig
from sherman_tpu.errors import DoubleFreeError
from sherman_tpu.ops import bits

RESERVED_PAGES = 1


class GlobalAllocator:
    """Bump chunk allocator over one node's pool partition."""

    def __init__(self, node_id: int, pages_per_node: int, chunk_pages: int,
                 reserved: int = RESERVED_PAGES):
        self.node_id = node_id
        self.chunk_pages = chunk_pages
        self._next = reserved
        self._limit = pages_per_node
        # Reclaimed single pages (beyond-reference: the reference's free()
        # is a no-op, DSM.h:226).  Fed by the engine's quarantined
        # empty-leaf reclaim (BatchedEngine.reclaim_empty_leaves); served
        # before fresh bump space for page-grain allocations.
        self._free: list[int] = []
        # Concurrent host clients (the reference's 26-thread axis) lease
        # chunks from shared directories; the bump must be atomic or two
        # clients get the same chunk (silent page aliasing).
        self._mu = threading.Lock()

    def alloc_chunk(self) -> tuple[int, int]:
        """-> (first page index, size) of a fresh chunk; raises when
        exhausted.  The partition tail yields one truncated chunk (the
        reserved page 0 makes partitions non-multiples of chunk_pages, so
        insisting on full chunks would strand the tail — e.g. a
        single-chunk partition would be unusable)."""
        with self._mu:
            size = min(self.chunk_pages, self._limit - self._next)
            if size <= 0:
                raise MemoryError(
                    f"node {self.node_id}: DSM partition exhausted "
                    f"({self._limit} pages)")
            start = self._next
            self._next += size
            return start, size

    def reclaim(self, pages) -> None:
        """Return page indices to this node's free pool.  Callers own the
        safety argument (quarantine): a returned page must be unreachable
        from the tree AND past any stale reader's grace period.  Raises
        on a double-free — the same page pooled twice would eventually be
        granted twice (silent aliasing), so surface it at the boundary."""
        with self._mu:
            incoming = [int(p) for p in pages]
            dup = set(incoming) & set(self._free)
            if dup or len(set(incoming)) != len(incoming):
                raise DoubleFreeError(
                    f"node {self.node_id}: double-free into the reclaim "
                    f"pool (duplicates: {sorted(dup)[:4]})")
            self._free.extend(incoming)

    def pop_free_page(self) -> int:
        """-> one reclaimed page index, or -1 when the free pool is empty."""
        with self._mu:
            return self._free.pop() if self._free else -1

    @property
    def pages_free(self) -> int:
        with self._mu:
            return len(self._free)

    @property
    def free_pages_list(self) -> list[int]:
        """Snapshot of the reclaimed-page pool (checkpoint manifest)."""
        with self._mu:
            return list(self._free)

    @property
    def pages_used(self) -> int:
        return self._next


class Directory:
    """Memory-node agent: chunk MALLOC + NEW_ROOT bookkeeping.

    The reference spawns one directory thread per node polling UD messages
    (``Directory.cpp:23-58``); here requests arrive as calls.  NEW_ROOT
    updates the node-local root hint exactly like ``Directory.cpp:75-86``.
    """

    def __init__(self, node_id: int, cfg: DSMConfig):
        self.node_id = node_id
        self.allocator = GlobalAllocator(
            node_id, cfg.pages_per_node, cfg.chunk_pages)
        self.root_ptr = 0      # g_root_ptr analogue
        self.root_level = -1   # g_root_level analogue

    def malloc_chunk(self) -> tuple[int, int]:
        """MALLOC RPC: -> (chunk base addr, chunk size in pages)."""
        start, size = self.allocator.alloc_chunk()
        return bits.make_addr(self.node_id, start), size

    def new_root(self, addr: int, level: int) -> None:
        """NEW_ROOT RPC (broadcast target, ``Tree.cpp:116-124``)."""
        self.root_ptr = addr
        self.root_level = level


class LocalAllocator:
    """Per-client page allocator over leased chunks, one lease per node.

    ``directories`` are the node agents this client can lease from — all
    nodes in single-process SPMD, only the host-local node(s) in a
    multi-host deployment (each host allocates from its own partition;
    remote-chunk RPC is not needed because any node's pages are reachable
    one-sidedly once allocated).  Addresses are always packed with the
    directory's REAL node id, which need not equal its list position.
    """

    def __init__(self, directories: list[Directory]):
        self._dirs = directories
        self._by_node = {d.node_id: d for d in directories}
        self._cur: dict[int, tuple[int, int]] = {}  # node -> (next_page, end)
        self._rr = 0

    def _pick(self, node: int | None) -> Directory:
        if node is None:
            d = self._dirs[self._rr % len(self._dirs)]
            self._rr += 1
            return d
        if node not in self._by_node:
            raise KeyError(
                f"node {node} has no local directory (locals: "
                f"{sorted(self._by_node)}); allocate from a local node")
        return self._by_node[node]

    def alloc(self, npages: int = 1, node: int | None = None) -> int:
        """Allocate npages *contiguous* pages; -> packed addr of the first.

        Target node round-robins per call unless pinned (DSM.h:200-203).
        Page-grain allocations are served from the node's reclaimed-page
        pool first (beyond-reference; empty when reclamation is unused).
        """
        d = self._pick(node)
        nid = d.node_id
        if npages == 1:
            pg = d.allocator.pop_free_page()
            if pg >= 0:
                return bits.make_addr(nid, pg)
        nxt, end = self._cur.get(nid, (0, 0))
        if nxt + npages > end:
            base_addr, chunk_pages = d.malloc_chunk()
            nxt = bits.addr_page(base_addr)
            end = nxt + chunk_pages
            if npages > chunk_pages:
                # keep the (truncated) grant leased for smaller allocs
                self._cur[nid] = (nxt, end)
                raise MemoryError(
                    f"node {nid}: contiguous alloc of {npages} pages "
                    f"exceeds the granted chunk ({chunk_pages} pages)")
        self._cur[nid] = (nxt + npages, end)
        return bits.make_addr(nid, nxt)

    def alloc_many(self, count: int) -> np.ndarray:
        """Vectorized allocation of ``count`` single pages (bulk-load path).

        Leases whole chunks round-robin across nodes and fills them; any
        partial last chunk stays leased for future alloc() calls.  Returns
        an int64 array of packed addresses.
        """
        out = np.empty(count, np.int64)
        filled = 0
        while filled < count:
            d = self._dirs[self._rr % len(self._dirs)]
            self._rr += 1
            nid = d.node_id
            nxt, end = self._cur.pop(nid, (0, 0))
            if nxt >= end:
                base_addr, chunk_pages = d.malloc_chunk()
                nxt = bits.addr_page(base_addr)
                end = nxt + chunk_pages
            take = min(end - nxt, count - filled)
            out[filled:filled + take] = (
                (nid << ADDR_PAGE_BITS) | np.arange(nxt, nxt + take))
            filled += take
            if nxt + take < end:
                self._cur[nid] = (nxt + take, end)
        return out

    def free(self, addr: int, npages: int = 1) -> None:
        """No-op, like the reference (``DSM.h:226``, LocalAllocator.h:45-47).
        Page reclamation is future work in both systems."""
