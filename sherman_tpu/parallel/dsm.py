"""DSM — the one-sided remote-memory runtime, TPU-native.

This is the analogue of the reference's ``DSM`` class (``include/DSM.h``,
``src/DSM.cpp``): a cluster-wide word/page-addressable memory pool with
one-sided READ / WRITE / CAS / FAA, plus the separate small lock-word space
standing in for NIC on-chip device memory (the ``_dm`` op variants,
``DSM.cpp:395-523``).

Design (TPU-first, not a port):

- The pool is one global jax array ``[machine_nr * pages_per_node, 256]``
  int32, sharded over the 1-D ``node`` mesh axis — each chip's HBM shard is
  that node's DSM partition (reference: hugepage pool per node, DSM.cpp:40).
- One *step* executes a whole batch of requests from every node as one SPMD
  program: bucket-route requests by owner (``transport.py``), owners apply
  them to their local shard, replies route back.  A step is the unit of
  visibility: reads snapshot the pre-step pool; conflicting atomics within a
  step are linearized deterministically (CAS: at most one winner per word per
  step; FAA: serial prefix semantics).  Cross-step concurrency is governed by
  the same lock/version protocol as the reference.
- Async latency hiding (coroutines yielding per verb, reference
  ``Tree.cpp:1059-1122``; doorbell batching, ``Operation.cpp:351-481``) is
  subsumed by batching: dependent op pairs (write+unlock, cas+read) are
  simply issued in consecutive steps or fused into one step where ordering
  permits (writes in a step become visible together, which IS the
  write+unlock coalescing guarantee).

Apply-order within a step: READ (snapshot) < CAS < FAA < WRITE_WORD < WRITE.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from sherman_tpu import config as CFG
from sherman_tpu import obs
from sherman_tpu.config import DSMConfig, PAGE_WORDS
from sherman_tpu.errors import ConfigError, ProtocolError
from sherman_tpu.ops import bits
from sherman_tpu.parallel import transport
from sherman_tpu.parallel.mesh import AXIS, make_mesh, node_sharding

# Request opcodes (cf. verb set in Rdma.h:89-143).
OP_NOP = 0
OP_READ = 1        # read one page; reply in data[:, :256]
OP_WRITE = 2       # write nw words starting at woff of page addr (payload)
OP_WRITE_WORD = 3  # write single word arg1 at (addr, woff) / lock word
OP_CAS = 4         # compare-and-swap word: expected=arg0, desired=arg1
OP_FAA = 5         # fetch-and-add word: delta=arg0
OP_READ_WORD = 6   # read single word; reply in old
OP_MASKED_CAS = 7  # CAS under bitmask arg2 (ibv_exp masked CAS,
                   #   Operation.cpp:253-283): compare/swap only mask bits
OP_MASKED_FAA = 8  # fetch-add within the field arg2 (boundary FAA,
                   #   Operation.cpp:316-348): delta=arg0 pre-shifted to the
                   #   field; carries never leave the field.  One winner per
                   #   word per step (losers retry with ok=0)

# Address spaces: pool pages vs the lock table ("on-chip device memory",
# reference DirectoryConnection.cpp:24-30, DSM::fill_keys_dest DSM.cpp:169).
SPACE_POOL = 0
SPACE_LOCK = 1

REQ_FIELDS = ("op", "addr", "woff", "nw", "space", "arg0", "arg1",
              "arg2")

# Counter slots (reference op counters, DSM.cpp:17-21).
CNT_READ_OPS = 0
CNT_READ_PAGES = 1
CNT_WRITE_OPS = 2
CNT_WRITE_WORDS = 3
CNT_CAS_OPS = 4
CNT_FAA_OPS = 5
CNT_WW_OPS = 6
# Write-combining accounting (PR 17): fed by the leaf-apply kernels when
# config.write_combine() is on — per-batch page-group head count and the
# lock consults the HOCL-style handover saved (rows that rode a group
# head's verdict instead of gathering their own lock word).  Device-side
# slots so the hot path never syncs; the ``combine.*`` obs collector
# materializes them at PULL time like every other collector.
CNT_COMBINE_GROUPS = 7
CNT_COMBINE_SAVED = 8
N_COUNTERS = 10  # slot 9 spare

# Host-side step counter (device op counts ride the sharded counters
# array and surface via the registry's "dsm" collector; this one counts
# host-API step LAUNCHES — the control-plane round-trip rate).
_OBS_HOST_STEPS = obs.counter("dsm.host_steps")


def empty_requests(n: int) -> dict[str, np.ndarray]:
    """Host-side all-NOP request batch of n slots."""
    reqs = {f: np.zeros(n, np.int32) for f in REQ_FIELDS}
    reqs["payload"] = np.zeros((n, PAGE_WORDS), np.int32)
    return reqs


# ---------------------------------------------------------------------------
# Owner-side apply (runs on each node's local shard).
# ---------------------------------------------------------------------------

def _word_apply(flat, m_cas, m_faa, m_ww, m_rw, widx, arg0, arg1,
                m_mcas=None, m_mfaa=None, arg2=None):
    """Linearized word ops on a flat word array.

    Returns (new_flat, old[M], ok[M]) where old is: pre-step value for
    CAS/READ_WORD/masked ops; serial pre-value for FAA.  ok is the winner
    flag for CAS-like ops (True for everything else).

    Masked ops fold into the CAS machinery by rewriting expected/desired
    against the pre-step value: masked CAS compares and swaps only the
    ``arg2`` bits; masked FAA always matches and adds ``arg0`` inside the
    ``arg2`` field, dropping carries that leave it — at most one masked
    FAA per word lands per step (the NIC serializes; here losers retry).
    """
    M = widx.shape[0]
    W = flat.shape[0]
    if m_mcas is None:
        m_mcas = jnp.zeros(M, bool)
    if m_mfaa is None:
        m_mfaa = jnp.zeros(M, bool)
    if arg2 is None:
        arg2 = jnp.zeros(M, jnp.int32)
    prio = jnp.arange(M, dtype=jnp.int32)
    any_word = m_cas | m_faa | m_ww | m_rw | m_mcas | m_mfaa
    gidx = jnp.where(any_word, widx, 0)
    gidx = jnp.clip(gidx, 0, W - 1)
    cur = flat[gidx]

    # CAS-like: at most one winner per word per step — the lowest-priority
    # request whose expected value matches (linearization point = step start).
    m_caslike = m_cas | m_mcas | m_mfaa
    exp_eff = jnp.where(m_mcas, (cur & ~arg2) | (arg0 & arg2),
                        jnp.where(m_mfaa, cur, arg0))
    des_eff = jnp.where(
        m_mcas, (cur & ~arg2) | (arg1 & arg2),
        jnp.where(m_mfaa, (cur & ~arg2) | (((cur & arg2) + arg0) & arg2),
                  arg1))
    eligible = m_caslike & (cur == exp_eff)
    key_w = jnp.where(m_caslike, widx, W)
    perm = jnp.lexsort((prio, ~eligible, key_w))
    sw = key_w[perm]
    head = jnp.concatenate([jnp.ones(1, bool), sw[1:] != sw[:-1]])
    winner_s = head & eligible[perm] & (sw < W)
    winner = jnp.zeros(M, bool).at[perm].set(winner_s)
    flat = flat.at[jnp.where(winner, widx, W)].set(des_eff, mode="drop")

    # FAA: all succeed; each sees the serial prefix value (post-CAS state).
    cur2 = flat[gidx]
    key_f = jnp.where(m_faa, widx, W)
    permf = jnp.lexsort((prio, key_f))
    sf = key_f[permf]
    d = jnp.where(m_faa, arg0, 0)[permf]
    csum = jnp.cumsum(d)
    excl = csum - d
    startsf = jnp.searchsorted(sf, sf, side="left")
    in_seg_excl = excl - excl[startsf]
    old_faa_s = cur2[permf] + in_seg_excl
    old_faa = jnp.zeros(M, flat.dtype).at[permf].set(old_faa_s)
    flat = flat.at[jnp.where(m_faa, widx, W)].add(arg0, mode="drop")

    # WRITE_WORD: plain store, wins over same-step CAS/FAA results.
    flat = flat.at[jnp.where(m_ww, widx, W)].set(arg1, mode="drop")

    old = jnp.where(m_faa, old_faa, cur)
    ok = jnp.where(m_caslike, winner, True)
    return flat, old, ok


def _apply(pool, locks, counters, req):
    """Apply incoming requests [M] to this node's shard."""
    P, PW = pool.shape
    page = bits.addr_page(req["addr"])
    op = req["op"]
    m_pool = req["space"] == SPACE_POOL
    m_lock = req["space"] == SPACE_LOCK

    # In-shard bounds checks: the page field must index a real pool page (or
    # a real lock word for the lock space), word ops must stay inside their
    # page, and multi-word writes must not spill into the next page.
    # Out-of-range or unroutable (op, space) requests fail with ok=0 rather
    # than silently clamping or corrupting neighbors.
    woff, nw = req["woff"], req["nw"]
    page_ok = jnp.where(m_lock, page < locks.shape[0], page < P) & (page >= 0)
    word_ok = m_lock | ((woff >= 0) & (woff < PW))
    write_ok = (woff >= 0) & (nw >= 0) & (woff + nw <= PW)
    wordspace = m_pool | m_lock

    is_read = (op == OP_READ) & m_pool & page_ok
    m_cas = (op == OP_CAS) & wordspace & page_ok & word_ok
    m_faa = (op == OP_FAA) & wordspace & page_ok & word_ok
    m_ww = (op == OP_WRITE_WORD) & wordspace & page_ok & word_ok
    m_rw = (op == OP_READ_WORD) & wordspace & page_ok & word_ok
    m_mcas = (op == OP_MASKED_CAS) & wordspace & page_ok & word_ok
    m_mfaa = (op == OP_MASKED_FAA) & wordspace & page_ok & word_ok
    is_write = (op == OP_WRITE) & m_pool & page_ok & write_ok

    # READ: snapshot gather of whole pages before any mutation.
    rpage = pool[jnp.clip(page, 0, P - 1)]
    data = jnp.where(is_read[:, None], rpage, 0)

    # Word-granular ops on the pool space...
    flatpool = pool.reshape(-1)
    widx_pool = page * PW + woff
    flatpool, old_p, ok_p = _word_apply(
        flatpool, m_cas & m_pool, m_faa & m_pool, m_ww & m_pool, m_rw & m_pool,
        widx_pool, req["arg0"], req["arg1"],
        m_mcas & m_pool, m_mfaa & m_pool, req["arg2"])
    # ...and on the lock space (lock index rides the addr page field).
    locks, old_l, ok_l = _word_apply(
        locks, m_cas & m_lock, m_faa & m_lock, m_ww & m_lock, m_rw & m_lock,
        page, req["arg0"], req["arg1"],
        m_mcas & m_lock, m_mfaa & m_lock, req["arg2"])

    # Page WRITE: word-masked scatter (single-entry write-back support —
    # the reference's write-amplification optimization, Tree.cpp:914-921).
    cols = jnp.arange(PW, dtype=jnp.int32)
    idx = widx_pool[:, None] + cols[None, :]
    wmask = is_write[:, None] & (cols[None, :] < nw[:, None])
    idx = jnp.where(wmask, idx, P * PW)
    flatpool = flatpool.at[idx.reshape(-1)].set(
        req["payload"].reshape(-1), mode="drop")
    pool = flatpool.reshape(P, PW)

    handled = (is_read | is_write | m_cas | m_faa | m_ww | m_rw
               | m_mcas | m_mfaa)
    old = jnp.where(m_lock, old_l, old_p)
    ok = jnp.where(m_lock, ok_l, ok_p) & handled

    u32 = lambda m: jnp.sum(m.astype(jnp.uint32))
    counters = counters.at[CNT_READ_OPS].add(u32(is_read))
    counters = counters.at[CNT_READ_PAGES].add(u32(is_read))
    counters = counters.at[CNT_WRITE_OPS].add(u32(is_write))
    counters = counters.at[CNT_WRITE_WORDS].add(
        jnp.sum(jnp.where(is_write, req["nw"], 0)).astype(jnp.uint32))
    counters = counters.at[CNT_CAS_OPS].add(u32(m_cas | m_mcas))
    counters = counters.at[CNT_FAA_OPS].add(u32(m_faa | m_mfaa))
    counters = counters.at[CNT_WW_OPS].add(u32(m_ww))
    return pool, locks, counters, data, old, ok


# ---------------------------------------------------------------------------
# The SPMD step (composable inside shard_map).
# ---------------------------------------------------------------------------

def dsm_step_spmd(pool, locks, counters, reqs, *, cfg: DSMConfig,
                  axis_name: str = AXIS):
    """One DSM step on per-node shards; call inside shard_map.

    reqs: dict of [R] arrays (+ payload [R, 256]).
    Returns (pool, locks, counters, replies) with replies =
    {"data": [R,256], "old": [R], "ok": [R] bool}.
    """
    N, C = cfg.machine_nr, cfg.step_capacity
    xch = functools.partial(transport.exchange, axis_name=axis_name,
                            impl=cfg.exchange_impl)
    active = reqs["op"] != OP_NOP
    dest = bits.addr_node(reqs["addr"])
    bucket_idx, routed = transport.bucketize(dest, active, N, C)

    out = {k: transport.scatter_to_buckets(v, bucket_idx, N * C)
           for k, v in reqs.items()}
    inc = xch(out)

    pool, locks, counters, data, old, ok = _apply(pool, locks, counters, inc)

    rep = xch({"data": data, "old": old, "ok": ok})
    safe_b = jnp.where(routed, bucket_idx, 0)
    replies = {
        "data": jnp.where((active & routed)[:, None], rep["data"][safe_b], 0),
        "old": jnp.where(active & routed, rep["old"][safe_b], 0),
        "ok": jnp.where(active, routed & rep["ok"][safe_b], True),
    }
    return pool, locks, counters, replies


def read_pages_spmd(pool, addrs, *, cfg: DSMConfig, axis_name: str = AXIS,
                    active=None):
    """Lightweight read-only exchange: fetch pages for a batch of addrs.

    The hot-loop primitive for batched tree descent — avoids shipping write
    payloads: requests are 1 word each; only replies carry pages.
    Returns (pages [R, 256], ok [R]).

    ``cfg.gather_impl`` selects the page-fetch engine: "xla" (default)
    is the native gather; "pallas" routes the owner-side page reads
    through the explicit-DMA snapshot kernel
    (:mod:`sherman_tpu.ops.pallas_page`) — bit-identical results, same
    op accounting (counters are per ROW, not per impl).
    """
    from sherman_tpu.ops import pallas_page
    N, C = cfg.machine_nr, cfg.step_capacity
    P = pool.shape[0]
    if active is None:
        active = jnp.ones(addrs.shape, bool)
    if N == 1:
        if pallas_page.use_pallas(cfg):
            return pallas_page.read_pages_local(pool, addrs, active)
        # Single-node fast path: no routing, direct local gather.
        page = bits.addr_page(addrs)
        ok = active & (page >= 0) & (page < P)
        pages = pool[jnp.clip(page, 0, P - 1)]
        return jnp.where(ok[:, None], pages, 0), ok
    dest = bits.addr_node(addrs)
    xch = functools.partial(transport.exchange, axis_name=axis_name,
                            impl=cfg.exchange_impl)
    bucket_idx, routed = transport.bucketize(dest, active, N, C)
    out = transport.scatter_to_buckets(bits.addr_page(addrs), bucket_idx, N * C)
    inc = xch(out)
    if pallas_page.use_pallas(cfg):
        data = pallas_page.gather_pages(pool, inc)
    else:
        data = pool[jnp.clip(inc, 0, P - 1)]
    rep = xch({"data": data, "okb": (inc >= 0) & (inc < P)})
    safe_b = jnp.where(routed, bucket_idx, 0)
    served = active & routed & rep["okb"][safe_b]
    pages = jnp.where(served[:, None], rep["data"][safe_b], 0)
    return pages, served


# ---------------------------------------------------------------------------
# Host-facing runtime.
# ---------------------------------------------------------------------------

@dataclass
class Replies:
    data: np.ndarray
    old: np.ndarray
    ok: np.ndarray


class _HostOps:
    """Host convenience API over :meth:`_batch` (one small step per call).

    Shared by :class:`DSM` (single-process / raw per-process multihost
    mode) and :class:`ReplicatedDSM` (replicated-driver multihost mode);
    subclasses provide ``_batch``.
    """

    def _batch(self, rows: list[dict]) -> Replies:  # pragma: no cover
        raise NotImplementedError

    @staticmethod
    def _require_ok(ok, what: str) -> None:
        """Host-API ops must not fail silently: a refused row (bad
        address, routing overflow) indicates a protocol bug or an
        undersized step, and a bare assert would be stripped under
        python -O — masking lost writes as success."""
        if not bool(np.all(ok)):
            raise ProtocolError(f"host DSM op failed: {what}")

    def read_page(self, addr: int) -> np.ndarray:
        r = self._batch([{"op": OP_READ, "addr": addr}])
        self._require_ok(r.ok[0], "read_page (bad address?)")
        return r.data[0]

    def read_pages(self, addrs) -> np.ndarray:
        rows = [{"op": OP_READ, "addr": int(a)} for a in addrs]
        r = self._batch(rows)
        self._require_ok(r.ok, "read_pages overflow: raise step_capacity")
        return r.data

    def write_page(self, addr: int, words: np.ndarray):
        r = self._batch([{"op": OP_WRITE, "addr": addr, "woff": 0,
                          "nw": PAGE_WORDS, "payload": words}])
        self._require_ok(r.ok[0], "write_page (bad address?)")

    def write_words(self, addr: int, woff: int, words: np.ndarray):
        words = np.asarray(words, np.int32)
        r = self._batch([{"op": OP_WRITE, "addr": addr, "woff": woff,
                          "nw": words.shape[0], "payload": words}])
        self._require_ok(r.ok[0], "write_words (bad address/range?)")

    def write_rows(self, rows: list[dict]):
        """Batched writes in ONE step — the write_batch/doorbell analogue
        (Operation.cpp:351-380): all writes in a step become visible
        atomically at the step boundary."""
        r = self._batch(rows)
        self._require_ok(r.ok, "write_rows (bad address or overflow)")

    def cas(self, addr: int, woff: int, expected: int, desired: int,
            space: int = SPACE_POOL) -> tuple[int, bool]:
        r = self._batch([{"op": OP_CAS, "addr": addr, "woff": woff,
                          "arg0": expected, "arg1": desired, "space": space}])
        return int(r.old[0]), bool(r.ok[0])

    def faa(self, addr: int, woff: int, delta: int,
            space: int = SPACE_POOL) -> int:
        r = self._batch([{"op": OP_FAA, "addr": addr, "woff": woff,
                          "arg0": delta, "space": space}])
        self._require_ok(r.ok[0], "faa (bad address?)")
        return int(r.old[0])

    def read_word(self, addr: int, woff: int, space: int = SPACE_POOL) -> int:
        r = self._batch([{"op": OP_READ_WORD, "addr": addr, "woff": woff,
                          "space": space}])
        self._require_ok(r.ok[0], "read_word (bad address?)")
        return int(r.old[0])

    def write_word(self, addr: int, woff: int, value: int,
                   space: int = SPACE_POOL):
        r = self._batch([{"op": OP_WRITE_WORD, "addr": addr, "woff": woff,
                          "arg1": value, "space": space}])
        self._require_ok(r.ok[0], "write_word (bad address?)")

    def masked_cas(self, addr: int, woff: int, expected: int, desired: int,
                   mask: int, space: int = SPACE_POOL) -> tuple[int, bool]:
        """CAS only the ``mask`` bits (ibv_exp masked CAS parity,
        Operation.cpp:253-283): other bits are untouched and ignored in
        the comparison.  -> (old_word, won)."""
        r = self._batch([{"op": OP_MASKED_CAS, "addr": addr, "woff": woff,
                          "arg0": expected, "arg1": desired, "arg2": mask,
                          "space": space}])
        return int(r.old[0]), bool(r.ok[0])

    def masked_faa(self, addr: int, woff: int, delta: int, mask: int,
                   space: int = SPACE_POOL) -> tuple[int, bool]:
        """Fetch-and-add within the ``mask`` field (boundary FAA parity,
        Operation.cpp:316-348): ``delta`` must be pre-shifted into the
        field; carries never cross out of it.  One per word lands per
        step; a lost race returns won=False to retry.
        -> (old_word, won)."""
        r = self._batch([{"op": OP_MASKED_FAA, "addr": addr, "woff": woff,
                          "arg0": delta, "arg2": mask, "space": space}])
        return int(r.old[0]), bool(r.ok[0])

    # -- coalesced dependent-op chains (doorbell parity) ----------------------
    # One step = one "doorbell": its ops land atomically at the step
    # boundary, which is the guarantee the reference builds from chained
    # WRs + fences (Operation.cpp:351-481).

    def cas_read(self, cas_addr: int, woff: int, expected: int, desired: int,
                 read_addr: int, cas_space: int = SPACE_LOCK
                 ) -> tuple[int, bool, np.ndarray]:
        """CAS a word and read a page in ONE step (rdmaCasRead,
        Operation.cpp:382-414) — the lock-acquire + page-fetch fusion.

        The read returns the pre-step page snapshot.  That is exactly the
        fenced post-CAS read when the CAS wins a *lock*: the previous
        holder's page write and its unlock land in one earlier step, so
        any snapshot taken at or after the unlock already contains the
        protected write.  -> (old_word, cas_won, page).
        """
        r = self._batch([
            {"op": OP_CAS, "addr": cas_addr, "woff": woff,
             "arg0": expected, "arg1": desired, "space": cas_space},
            {"op": OP_READ, "addr": read_addr},
        ])
        self._require_ok(r.ok[1], "cas_read: bad page address")
        return int(r.old[0]), bool(r.ok[0]), r.data[1]

    def write_cas(self, waddr: int, woff: int, payload: np.ndarray,
                  cas_addr: int, cas_woff: int, expected: int, desired: int,
                  cas_space: int = SPACE_LOCK) -> bool:
        """Write words and CAS a word in ONE step (rdmaWriteCas,
        Operation.cpp:449-481).  The CAS linearizes on the pre-step value;
        both effects land together.  -> cas_won."""
        payload = np.asarray(payload, np.int32)
        r = self._batch([
            {"op": OP_WRITE, "addr": waddr, "woff": woff,
             "nw": payload.shape[0], "payload": payload},
            {"op": OP_CAS, "addr": cas_addr, "woff": cas_woff,
             "arg0": expected, "arg1": desired, "space": cas_space},
        ])
        self._require_ok(r.ok[0], "write_cas: bad write address")
        return bool(r.ok[1])

    def write_faa(self, waddr: int, woff: int, payload: np.ndarray,
                  faa_addr: int, faa_woff: int, delta: int,
                  faa_space: int = SPACE_POOL) -> int:
        """Write words and fetch-and-add a word in ONE step (rdmaWriteFaa,
        Operation.cpp:416-447).  -> the FAA's serial pre-value."""
        payload = np.asarray(payload, np.int32)
        r = self._batch([
            {"op": OP_WRITE, "addr": waddr, "woff": woff,
             "nw": payload.shape[0], "payload": payload},
            {"op": OP_FAA, "addr": faa_addr, "woff": faa_woff,
             "arg0": delta, "space": faa_space},
        ])
        self._require_ok(r.ok[0] and r.ok[1], "write_faa: bad address")
        return int(r.old[1])


class DSM(_HostOps):
    """Host handle to the cluster: owns the sharded pool/locks/counters and a
    jitted step.  The analogue of ``DSM::getInstance`` (DSM.cpp:23-35).

    Single-process SPMD: one Python process drives all nodes (the mesh).
    Multi-host meshes use the same code path via jax.distributed — the mesh
    simply spans processes.
    """

    def __init__(self, cfg: DSMConfig, mesh: jax.sharding.Mesh | None = None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.machine_nr)
        if self.mesh.devices.size != cfg.machine_nr:
            raise ConfigError("mesh size must equal cfg.machine_nr")
        self.shard = node_sharding(self.mesh)
        N, P, L = cfg.machine_nr, cfg.pages_per_node, cfg.locks_per_node

        # Multi-host: the mesh spans processes.  Host-API calls are then
        # COLLECTIVES — every process must issue the same sequence of
        # steps, each contributing requests from its own (contiguous)
        # block of nodes and receiving its own replies (multi-controller
        # SPMD, the jax.distributed execution model).
        me = jax.process_index()
        flat = list(self.mesh.devices.flat)
        self.multihost = any(d.process_index != me for d in flat)
        local_idx = [i for i, d in enumerate(flat) if d.process_index == me]
        assert local_idx, "mesh has no process-local devices"
        lo, hi = local_idx[0], local_idx[-1] + 1
        assert local_idx == list(range(lo, hi)), (
            "process-local devices must be contiguous in the mesh")
        self.local_nodes = range(lo, hi)

        def _zeros(shape, dtype):
            if not self.multihost:
                return jax.device_put(jnp.zeros(shape, dtype), self.shard)
            return jax.make_array_from_callback(
                shape, self.shard,
                lambda idx: np.zeros(self.shard.shard_shape(shape), dtype))

        self.pool = _zeros((N * P, PAGE_WORDS), jnp.int32)
        self.locks = _zeros((N * L,), jnp.int32)
        self.counters = _zeros((N * N_COUNTERS,), jnp.uint32)
        # Out-of-line VALUE HEAP — the second DSM region (see
        # DSMConfig.heap_pages_per_node; models/value_heap.py owns the
        # slab/handle protocol on top).  Sharded over nodes like the
        # pool; None when disabled, so a heap-off build carries no
        # extra device state and stays bit-identical to pre-heap
        # builds.  Single-process only for now (like delta checkpoints
        # and the recovery plane — the heap's allocator/journal
        # integration assumes one driver).
        self.heap = None
        self._heap_dirty_host: set[int] = set()
        self._heap_write = None
        if cfg.heap_pages_per_node > 0:
            # multihost allocation rides the same make_array_from_
            # callback path as the pool (PR 19): ownership is row-
            # range-based — each process's allocator hands out slabs
            # from its OWN nodes' heap rows only (global-row handles
            # stay valid everywhere; only allocation is local), so no
            # cross-host allocator coordination exists to get wrong.
            self.heap = _zeros((N * cfg.heap_pages_per_node, PAGE_WORDS),
                               jnp.int32)
        # Dirty-page tracking (the recovery plane's delta-checkpoint
        # feed, utils/checkpoint.checkpoint_delta): pages written since
        # the last checkpoint artifact.  Two tiers, united at save time:
        # - ``dirty``: a pool-sharded device mask the engine's compiled
        #   write programs OR into owner-side (leaf applies, splits,
        #   deletes — their target pages never surface host-side);
        # - ``_dirty_host``: a host set of global pool rows, marked at
        #   the DSM.step boundary from the (host-visible) request batch
        #   — one address-set union per control-plane step — plus
        #   explicit marks for direct installs (bulk_load).
        # Chaos corruption pokes bypass both on purpose: injected damage
        # is not a legal write and must NOT leak into delta artifacts.
        self.dirty = _zeros((N * P,), jnp.bool_)
        self._dirty_host: set[int] = set()
        # Dirty SINKS (the online migrator's feed, sherman_tpu/migrate.py):
        # checkpoint saves consume-and-clear the dirty tracking, which
        # would silently hide post-copy writes from any second consumer.
        # A registered sink is handed the rows about to be cleared, so a
        # concurrent consumer (the migration re-copy queue) never loses
        # dirt to a checkpoint racing its polls.  Empty list = zero cost.
        self._dirty_sinks: list = []

        spec = jax.sharding.PartitionSpec(AXIS)
        in_specs = (spec, spec, spec,
                    {k: spec for k in (*REQ_FIELDS, "payload")})
        out_specs = (spec, spec, spec, {k: spec for k in ("data", "old", "ok")})
        # The host control-plane step uses its own small routing capacity —
        # see DSMConfig.host_step_capacity.
        import dataclasses as _dc
        self._host_cfg = _dc.replace(
            cfg, step_capacity=min(cfg.step_capacity,
                                   cfg.host_step_capacity))
        step = jax.shard_map(
            functools.partial(dsm_step_spmd, cfg=self._host_cfg),
            mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)
        self._step = jax.jit(step, donate_argnums=CFG.donate_argnums(0, 1, 2))
        # Per-step request slots available to the *host* API; device kernels
        # compose dsm_step_spmd directly and have their own batches.
        self.host_slots = len(self.local_nodes) * self._host_cfg.step_capacity
        # Host-API steps mutate self.pool/locks/counters with donated
        # buffers; serialize them so multithreaded clients (the local
        # lock tier's use case) can't interleave inside a step.
        import threading
        self._step_mutex = threading.Lock()

        # Chaos injection hook (sherman_tpu/chaos.py): a FaultPlan fired
        # at the host-step boundary.  None (the default) costs one `is
        # None` test per host step — engine/staged programs are
        # untouched, so receipts with chaos off are bit-identical to a
        # build without the subsystem.  Env-drivable: SHERMAN_CHAOS
        # installs a plan on every DSM at construction.
        import os as _os
        self.chaos = None
        if _os.environ.get("SHERMAN_CHAOS"):
            from sherman_tpu.chaos import FaultPlan
            self.chaos = FaultPlan.from_env()

        # Observability: expose the device op/byte counters as a pull
        # collector on the process-wide registry — snapshots then carry
        # ``dsm.read_ops`` etc. without any per-op host cost (the
        # counters accumulate on device; reading them is the same
        # materialization counter_snapshot always did).  Weakly bound:
        # a dead DSM drops out instead of pinning its device arrays.
        import weakref
        ref = weakref.ref(self)
        obs.register_collector(
            "dsm", lambda: (lambda d: d.counter_snapshot() if d is not None
                            else {})(ref()))
        # HBM accountant (obs/device.py): the DSM's device-resident
        # arrays ARE the pool-side HBM footprint — register them as
        # weakref-bound byte sources so ``device.hbm_*`` gauges and the
        # peak watermark track the live buffers (a dead DSM reports 0
        # and drops out; the step-donated handles are re-read per
        # snapshot, so rotation through donation is invisible here).
        acct = obs.get_accountant()
        for _src in ("pool", "locks", "counters", "dirty"):
            acct.register(_src, (lambda r=ref, n=_src: (
                getattr(r(), n).nbytes if r() is not None else 0)))
        if self.heap is not None:
            acct.register("heap", (lambda r=ref: (
                r().heap.nbytes
                if r() is not None and r().heap is not None else 0)))

    # -- raw step ------------------------------------------------------------

    def step(self, reqs: dict[str, np.ndarray]) -> Replies:
        """Run one DSM step.

        Single-process: ``reqs`` are global request arrays [N*R]; replies
        cover all slots.  Multi-host: a COLLECTIVE — every process calls
        with its own host-local arrays [len(local_nodes)*R] and receives
        replies for its slots only.

        Thread-safe: one step at a time (the state arrays are donated).
        """
        _OBS_HOST_STEPS.inc()
        self._mark_dirty_from_reqs(reqs)
        with self._step_mutex:
            if self.chaos is None:
                return self._step_locked(reqs)
            # Fault injection at the step boundary (the single chaos
            # hook): due faults corrupt pool/lock words or rewrite this
            # step's requests before it runs; stale_read faults
            # post-process its replies.  Runs under the step mutex, so
            # the corruption + step land as one atomic handle swap.
            reqs0 = reqs
            reqs, post = self.chaos.on_step(self, reqs)
            rep = self._step_locked(reqs)
            return self.chaos.on_replies(self, reqs0, rep) if post else rep

    def _step_locked(self, reqs: dict[str, np.ndarray]) -> Replies:
        if self.multihost:
            from jax.experimental import multihost_utils as mhu
            reqs = {k: mhu.host_local_array_to_global_array(
                        np.asarray(v), self.mesh,
                        jax.sharding.PartitionSpec(AXIS))
                    for k, v in reqs.items()}
        else:
            reqs = {k: jax.device_put(jnp.asarray(v), self.shard)
                    for k, v in reqs.items()}
        self.pool, self.locks, self.counters, rep = self._step(
            self.pool, self.locks, self.counters, reqs)
        if self.multihost:
            from jax.experimental import multihost_utils as mhu
            spec = jax.sharding.PartitionSpec(AXIS)
            rep = {k: mhu.global_array_to_host_local_array(v, self.mesh, spec)
                   for k, v in rep.items()}
        return Replies(data=np.asarray(rep["data"]), old=np.asarray(rep["old"]),
                       ok=np.asarray(rep["ok"]))

    def install_chaos(self, plan) -> None:
        """Install (or clear, with ``None``) a chaos
        :class:`~sherman_tpu.chaos.FaultPlan`; its step indices count
        host steps from the moment of installation."""
        self.chaos = plan

    # -- dirty-page tracking (delta-checkpoint feed) -------------------------

    _POOL_WRITE_OPS = (OP_WRITE, OP_WRITE_WORD, OP_CAS, OP_FAA,
                       OP_MASKED_CAS, OP_MASKED_FAA)

    def local_row_range(self) -> tuple[int, int]:
        """``[lo, hi)`` global pool rows owned by THIS process — the
        row-range ownership basis of the multihost service plane
        (PR 19).  Single-process: the whole pool.  Global-row
        addressing means a reshard never rewrites a handle; ownership
        is just which process's dirty tracking / delta artifacts a row
        lands in."""
        P = self.cfg.pages_per_node
        return (self.local_nodes.start * P, self.local_nodes.stop * P)

    def _mark_dirty_from_reqs(self, reqs) -> None:
        """One address-set union per host step: every pool-space request
        that CAN mutate its page marks that page dirty (CAS losers
        over-mark — a harmless extra delta row, never a missed one).
        Pure numpy (no device trip); out-of-range addresses are the
        requests _apply refuses with ok=0 — skipped here too.
        Multihost: only LOCALLY-OWNED rows are tracked (row-range
        ownership, PR 19) — a remote-node write is the remote process's
        to track, from its own copy of the same collective step."""
        op = np.asarray(reqs["op"]).ravel()
        wr = np.isin(op, self._POOL_WRITE_OPS) \
            & (np.asarray(reqs["space"]).ravel() == SPACE_POOL)
        if not wr.any():
            return
        a = np.asarray(reqs["addr"]).ravel()[wr].astype(np.int64) \
            & 0xFFFFFFFF
        node = a >> CFG.ADDR_PAGE_BITS
        page = a & CFG.ADDR_PAGE_MASK
        ok = (node < self.cfg.machine_nr) & (page < self.cfg.pages_per_node)
        rows = node[ok] * self.cfg.pages_per_node + page[ok]
        if self.multihost:
            lo, hi = self.local_row_range()
            rows = rows[(rows >= lo) & (rows < hi)]
        self._dirty_host.update(int(r) for r in np.unique(rows))

    def mark_dirty_rows(self, rows) -> None:
        """Explicitly mark global pool rows dirty (direct pool installs
        — bulk_load — whose writes bypass the step/request path).
        Multihost: rows outside this process's ownership range are
        dropped (the owner marks them from its own call)."""
        rows = np.asarray(rows, np.int64).ravel()
        if self.multihost:
            lo, hi = self.local_row_range()
            rows = rows[(rows >= lo) & (rows < hi)]
        self._dirty_host.update(int(r) for r in rows)

    def dirty_rows(self) -> np.ndarray:
        """Sorted global pool rows written since the last clear: the
        device mask (engine write programs) united with the host set
        (DSM.step boundary + direct installs).  Multihost: THIS
        process's owned rows only — the device mask is read from the
        addressable shards (collective-free; each shard's mesh
        position gives its global row offset), and the host set was
        ownership-filtered at mark time.  The union of every host's
        return IS the cluster's dirty set, disjoint by construction —
        the per-host delta artifacts the union recovery replays."""
        if self.multihost:
            P = self.cfg.pages_per_node
            parts = [self._dirty_host]
            for s in self.dirty.addressable_shards:
                off = s.index[0].start or 0
                loc = np.nonzero(np.asarray(s.data))[0]
                parts.append(set((loc + off).tolist()))
            allr = set().union(*parts)
            return np.array(sorted(allr), np.int64)
        dev = np.nonzero(np.asarray(self.dirty))[0].astype(np.int64)
        if not self._dirty_host:
            return dev
        host = np.fromiter(self._dirty_host, np.int64,
                           len(self._dirty_host))
        return np.union1d(dev, host)

    def read_rows_local(self, rows, region: str = "pool") -> np.ndarray:
        """Gather pool/heap rows host-side from this process's
        ADDRESSABLE shards only — the collective-free gather the
        per-host delta save needs on a process-spanning mesh (a global
        fancy-index there would be a cross-host collective).  ``rows``
        must lie in :meth:`local_row_range` (scaled to the heap's rows
        for ``region="heap"``); out-of-range rows raise."""
        import jax.numpy as _jnp
        arr = self.heap if region == "heap" else self.pool
        if arr is None:
            raise ConfigError("no value heap configured")
        rows = np.asarray(rows, np.int64).ravel()
        if rows.size == 0:
            return np.zeros((0, arr.shape[1]), np.int32)
        if not self.multihost:
            return np.asarray(arr[_jnp.asarray(rows)])
        out = np.zeros((rows.size, arr.shape[1]), np.int32)
        seen = np.zeros(rows.size, bool)
        for s in arr.addressable_shards:
            off = s.index[0].start or 0
            n = s.data.shape[0]
            sel = (rows >= off) & (rows < off + n)
            if sel.any():
                out[sel] = np.asarray(s.data)[rows[sel] - off]
                seen |= sel
        if not seen.all():
            raise ConfigError(
                f"read_rows_local: {int((~seen).sum())} row(s) outside "
                "this process's addressable shards — gather them on "
                "their owner host")
        return out

    # -- value-heap region (the second DSM region) ---------------------------
    # Word-cell writes + page reads over ``self.heap``.  The slab/handle
    # protocol (size classes, versions, freelists) lives in
    # models/value_heap.py; these are the raw region ops, kept on the
    # DSM so dirty tracking and checkpoints see ONE owner for both
    # regions.  Single-process only (enforced at construction).

    def _require_heap(self) -> None:
        if self.heap is None:
            raise ConfigError(
                "no value heap configured: set "
                "DSMConfig.heap_pages_per_node > 0 (SHERMAN_VALUE_HEAP)")

    def heap_write_cells(self, rows, woffs, vals) -> None:
        """Scatter int32 words into heap pages in ONE device step:
        ``heap[rows[i], woffs[i]] = vals[i]``.  Row/word arrays are
        padded to a power-of-two quantum so the compiled scatter count
        stays bounded (pad cells target row H with ``mode="drop"``).
        Marks the touched heap rows dirty (delta-checkpoint feed)."""
        self._require_heap()
        rows = np.asarray(rows, np.int64)
        woffs = np.asarray(woffs, np.int32)
        vals = np.asarray(vals, np.int32)
        if rows.size == 0:
            return
        H = self.heap.shape[0]
        n = max(256, 1 << int(np.ceil(np.log2(rows.size))))
        pr = np.full(n, H, np.int32)   # out-of-range: dropped
        pw = np.zeros(n, np.int32)
        pv = np.zeros(n, np.int32)
        pr[: rows.size] = rows.astype(np.int32)
        pw[: rows.size] = woffs
        pv[: rows.size] = vals
        with self._step_mutex:
            self.heap = self._heap_write_jit()(
                self.heap, jnp.asarray(pr), jnp.asarray(pw),
                jnp.asarray(pv))
        self._heap_dirty_host.update(int(r) for r in np.unique(rows))

    def _heap_write_jit(self):
        if self._heap_write is None:
            self._heap_write = jax.jit(
                lambda h, r, w, v: h.at[r, w].set(v, mode="drop"),
                donate_argnums=CFG.donate_argnums(0))
        return self._heap_write

    def heap_read_rows(self, rows) -> np.ndarray:
        """Gather heap pages by global heap row (host convenience — the
        reference resolver / scrub path; the hot read path gathers on
        device inside the fused fan-out).  Takes the step mutex: the
        heap handle is DONATED by heap_write_cells, so an unguarded
        read racing a writer thread can hit a deleted buffer."""
        self._require_heap()
        rows = np.asarray(rows, np.int64)
        if rows.size == 0:
            return np.zeros((0, PAGE_WORDS), np.int32)
        with self._step_mutex:
            return np.asarray(self.heap[jnp.asarray(rows)])

    def heap_snapshot(self) -> np.ndarray:
        """Materialize the whole heap region (mutex-guarded handle
        read — see :meth:`heap_read_rows`)."""
        self._require_heap()
        with self._step_mutex:
            return np.asarray(self.heap)

    def mark_heap_dirty_rows(self, rows) -> None:
        """Explicitly mark global heap rows dirty (restore/replay paths
        whose writes bypass heap_write_cells)."""
        self._heap_dirty_host.update(int(r) for r in np.asarray(rows).ravel())

    def heap_dirty_rows(self) -> np.ndarray:
        """Sorted global heap rows written since the last clear."""
        if not self._heap_dirty_host:
            return np.zeros(0, np.int64)
        return np.sort(np.fromiter(self._heap_dirty_host, np.int64,
                                   len(self._heap_dirty_host)))

    def add_dirty_sink(self, fn) -> None:
        """Register a callable handed the dirty rows at every
        :meth:`clear_dirty` (BEFORE the reset) — the second-consumer
        contract for the dirty tracking (see ``_dirty_sinks``).
        Multihost: the sink sees this process's OWNED rows only
        (:meth:`dirty_rows`' row-range contract)."""
        self._dirty_sinks.append(fn)

    def remove_dirty_sink(self, fn) -> None:
        if fn in self._dirty_sinks:
            self._dirty_sinks.remove(fn)

    def clear_dirty(self) -> None:
        """Reset both dirty tiers (a checkpoint artifact captured them).
        Registered dirty sinks see the rows first — a clear must not
        hide writes from a concurrent consumer (migration re-copy)."""
        if self._dirty_sinks:
            rows = self.dirty_rows()
            if rows.size:
                for fn in list(self._dirty_sinks):
                    fn(rows)
        N, P = self.cfg.machine_nr, self.cfg.pages_per_node
        if not self.multihost:
            self.dirty = jax.device_put(jnp.zeros(N * P, jnp.bool_),
                                        self.shard)
        else:
            self.dirty = jax.make_array_from_callback(
                (N * P,), self.shard,
                lambda idx: np.zeros(self.shard.shard_shape((N * P,)),
                                     bool))
        self._dirty_host.clear()
        self._heap_dirty_host.clear()

    # -- host convenience ops (control plane / slow paths / tests) -----------
    # Each builds a small batch and steps once; requests are spread over
    # source nodes round-robin so per-(src,dst) capacity is not the limit.

    def _batch(self, rows: list[dict]) -> Replies:
        # Cap one host step at host_step_capacity TOTAL rows so that no
        # destination bucket can overflow regardless of the rows' targets.
        # Multi-host: rows ride THIS process's node block only (each
        # process contributes its own rows to the collective step).
        cap = self._host_cfg.step_capacity
        n_src = len(self.local_nodes)
        n = n_src * cap
        if len(rows) > cap:
            if self.multihost:
                # Refuse to split silently: each chunk is one COLLECTIVE
                # step, and a data-dependent chunk count would desync the
                # processes' step sequences (a silent cluster deadlock).
                # Callers chunk identically on every host instead.
                raise ConfigError(
                    f"multi-host host-API batch of {len(rows)} rows "
                    f"exceeds host_step_capacity={cap}: chunk the call "
                    "identically on every process (each chunk is one "
                    "collective step)")
            out = [self._batch(rows[i:i + cap])
                   for i in range(0, len(rows), cap)]
            return Replies(
                data=np.concatenate([r.data for r in out]),
                old=np.concatenate([r.old for r in out]),
                ok=np.concatenate([r.ok for r in out]))
        reqs = empty_requests(n)
        R = cap
        slots = []
        # round-robin rows over local source nodes: slot = s*R + idx
        per_src = [0] * n_src
        for i, row in enumerate(rows):
            src = i % n_src
            slot = src * R + per_src[src]
            per_src[src] += 1
            slots.append(slot)
            for k, v in row.items():
                if k == "payload":
                    v = np.asarray(v, np.int32)
                    reqs["payload"][slot, :v.shape[0]] = v
                else:
                    # accept full uint32 bit patterns (e.g. high-bit masks
                    # like 0xFFFF0000): wrap to the int32 representation —
                    # NumPy 2 raises OverflowError on a raw assignment
                    reqs[k][slot] = np.uint32(
                        int(v) & 0xFFFFFFFF).astype(np.int32)
        rep = self.step(reqs)
        sl = np.array(slots, np.int64)
        return Replies(data=rep.data[sl], old=rep.old[sl], ok=rep.ok[sl])

    # -- observability (write_test.cpp:72-76 parity) -------------------------

    def counter_snapshot(self) -> dict[str, int]:
        """Op counters summed over this process's nodes (single-process:
        the whole cluster).  Multi-host drivers aggregate across hosts
        with ``keeper.sum`` — the reference's pattern exactly
        (``dsm->sum``, test/benchmark.cpp:336-346)."""
        if self.multihost:
            c = np.concatenate([np.asarray(s.data)
                                for s in self.counters.addressable_shards])
        else:
            c = np.asarray(self.counters)
        c = c.reshape(-1, N_COUNTERS)
        tot = c.sum(axis=0, dtype=np.uint64)
        return {
            "read_ops": int(tot[CNT_READ_OPS]),
            "read_bytes": int(tot[CNT_READ_PAGES]) * CFG.PAGE_BYTES,
            "write_ops": int(tot[CNT_WRITE_OPS]),
            "write_bytes": int(tot[CNT_WRITE_WORDS]) * 4,
            "cas_ops": int(tot[CNT_CAS_OPS]),
            "faa_ops": int(tot[CNT_FAA_OPS]),
            "write_word_ops": int(tot[CNT_WW_OPS]),
            "combine_groups": int(tot[CNT_COMBINE_GROUPS]),
            "combine_locks_saved": int(tot[CNT_COMBINE_SAVED]),
        }


class ReplicatedDSM(_HostOps):
    """Replicated-driver host API over a process-spanning DSM.

    Multi-controller JAX runs the SAME host program on every process, so
    a host-API op (lock CAS, page read/write, coalesced chains) is
    requested by every process but must execute on the cluster exactly
    ONCE.  This wrapper is that contract: every process calls every
    method with identical arguments (replicated control flow — the
    engine enforces it with input digests); process 0 posts the real
    request rows while the others contribute empty collective steps, and
    the replies are broadcast so each process returns identical results.
    The role parallels the reference's UD-RPC control plane
    (``Directory.cpp:60-92``): one requester executes, everyone learns
    the outcome (here: synchronously, via the broadcast).

    Batches of any length are chunked to ``host_step_capacity`` rows per
    step; the chunk count derives from the (replicated) row list, so the
    processes' collective step sequences can never desync — the hazard
    :meth:`DSM._batch` refuses to risk in raw per-process mode.

    Device state (pool/locks/counters) is shared with the wrapped DSM;
    the batched engine keeps driving the raw arrays directly.
    """

    def __init__(self, dsm: DSM):
        from jax.experimental import multihost_utils as mhu
        assert dsm.multihost, "ReplicatedDSM wraps a process-spanning DSM"
        self._dsm = dsm
        self._leader = jax.process_index() == 0
        # tiled reassembly in engine._unshard requires process-local node
        # blocks ordered by process index; verify once per cluster
        firsts = np.asarray(mhu.process_allgather(
            np.asarray([dsm.local_nodes[0]], np.int32))).ravel()
        assert (np.diff(firsts) > 0).all(), (
            "mesh node blocks must ascend with process index")

    # -- shared-state passthrough (the engine mutates pool/counters) ---------

    pool = property(lambda s: s._dsm.pool,
                    lambda s, v: setattr(s._dsm, "pool", v))
    locks = property(lambda s: s._dsm.locks,
                     lambda s, v: setattr(s._dsm, "locks", v))
    counters = property(lambda s: s._dsm.counters,
                        lambda s, v: setattr(s._dsm, "counters", v))
    dirty = property(lambda s: s._dsm.dirty,
                     lambda s, v: setattr(s._dsm, "dirty", v))
    cfg = property(lambda s: s._dsm.cfg)
    mesh = property(lambda s: s._dsm.mesh)
    shard = property(lambda s: s._dsm.shard)
    multihost = property(lambda s: s._dsm.multihost)
    local_nodes = property(lambda s: s._dsm.local_nodes)
    host_slots = property(lambda s: s._dsm.host_slots)
    _host_cfg = property(lambda s: s._dsm._host_cfg)
    _step_mutex = property(lambda s: s._dsm._step_mutex)

    def counter_snapshot(self) -> dict[str, int]:
        return self._dsm.counter_snapshot()

    def mark_dirty_rows(self, rows) -> None:
        self._dsm.mark_dirty_rows(rows)

    def clear_dirty(self) -> None:
        self._dsm.clear_dirty()

    def _batch(self, rows: list[dict]) -> Replies:
        from jax.experimental import multihost_utils as mhu
        if not rows:
            self._dsm._batch([])  # still one collective step
            return Replies(data=np.zeros((0, PAGE_WORDS), np.int32),
                           old=np.zeros(0, np.int32), ok=np.zeros(0, bool))
        cap = self._dsm._host_cfg.step_capacity
        parts = []
        for i in range(0, len(rows), cap):
            chunk = rows[i:i + cap]
            if self._leader:
                parts.append(self._dsm._batch(chunk))
            else:
                self._dsm._batch([])
                parts.append(Replies(
                    data=np.zeros((len(chunk), PAGE_WORDS), np.int32),
                    old=np.zeros(len(chunk), np.int32),
                    ok=np.zeros(len(chunk), bool)))
        rep = Replies(data=np.concatenate([p.data for p in parts]),
                      old=np.concatenate([p.old for p in parts]),
                      ok=np.concatenate([p.ok for p in parts]))
        # one-to-all broadcast of the leader's replies (non-leaders pass
        # shape/dtype placeholders — rows are replicated so shapes agree)
        g = mhu.broadcast_one_to_all((rep.data, rep.old, rep.ok))
        return Replies(data=np.asarray(g[0]), old=np.asarray(g[1]),
                       ok=np.asarray(g[2]))
