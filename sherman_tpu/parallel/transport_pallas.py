"""Pallas ICI remote-DMA exchange — the explicit RDMA-verbs data plane.

The default transport (:mod:`sherman_tpu.parallel.transport`) routes request
buckets with one XLA ``all_to_all`` — idiomatic, compiler-scheduled.  This
module is the hand-rolled equivalent the reference's verb layer maps to most
literally (``src/rdma/Operation.cpp``): each node posts ONE one-sided remote
write per peer (``pltpu.make_async_remote_copy`` over ICI), with DMA
semaphores as the completion queue.  Per step and per peer:

- bucket ``p`` of the local request array is pushed straight into bucket
  ``my_id`` of peer ``p``'s incoming array (a one-sided RDMA WRITE with
  rkey/addr replaced by the SPMD-symmetric ref + row slice);
- all N-1 pushes start before any wait (the doorbell batch: full bisection
  bandwidth, no serialization on a ring);
- ``descriptor.wait()`` drains send + receive semaphores (CQ polling,
  ``pollWithCQ`` role, Operation.cpp:3-43).

Parity/selection: ``DSMConfig.exchange_impl = "xla" | "pallas"`` switches
the DSM step's exchanges.  The Pallas path is validated in interpreter mode
on the virtual CPU mesh (tests); the XLA path remains the default
(compiler-scheduled, equal-or-faster, and exempt from Mosaic toolchain
constraints).  COVERAGE: the pre-post cluster barrier (``use_barrier``)
cannot run in the interpreter (it cannot lower ``get_barrier_semaphore``
and runs devices sequentially), but the full compiled form — barrier
included — is COMPILE-SMOKED without multi-chip hardware: the 8-device
program is lowered for the TPU target through the Pallas->Mosaic pipeline
over an ``AbstractMesh`` (``tests/test_transport_pallas.py::
test_multichip_tpu_lowering_smoke``), which verifies the semaphore
signal/wait and remote-copy lowering.  EXECUTING the barrier still needs
real multi-chip hardware; until then treat "pallas" as experimental there.

Layout contract (same as ``transport.exchange`` with tiled all_to_all):
arrays are ``[N * C, ...]`` per node — row block ``d*C:(d+1)*C`` is the
bucket for/from peer ``d``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:  # pallas is TPU-oriented; CPU uses interpreter mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
    # JAX < 0.5 spells CompilerParams TPUCompilerParams
    _CompilerParams = getattr(pltpu, "CompilerParams",
                              getattr(pltpu, "TPUCompilerParams", None))
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

from sherman_tpu import obs
from sherman_tpu.ops.pallas_page import PallasUnavailableError
from sherman_tpu.errors import ShermanError


class ExchangeLaneError(ShermanError, TypeError):
    """Typed, actionable: a request field cannot ride the packed 32-bit
    exchange buffer.  Names the knob whose default path has no such
    constraint."""

    def __init__(self, dtype):
        super().__init__(
            f"pallas exchange carries 32-bit lanes; got {dtype} — widen "
            "the field to a 32-bit dtype (bools and any 4-byte dtype "
            "travel bit-exactly) or set DSMConfig.exchange_impl=\"xla\" "
            "(the default all_to_all transport, which has no lane-width "
            "constraint)")
        self.dtype = dtype


# Traced-issue accounting (see transport.py for the trace-time
# semantics): per kernel BUILD, the number of one-sided remote writes
# it posts per execution and the packed payload bytes it moves.
_OBS_REMOTE_WRITES = obs.counter("transport.pallas_remote_writes_traced")
_OBS_PACKED_BYTES = obs.counter("transport.pallas_packed_bytes_per_step")

def _collective_id(n_nodes: int, rows: int, width: int) -> int:
    """Barrier-semaphore key, distinct per program shape family.

    Two pallas programs sharing a collective_id share a barrier
    semaphore and could cross-credit if the runtime ever overlapped
    them; deriving the id from (n_nodes, rows_per_peer, width) gives
    each compiled exchange shape its own semaphore.  A hash collision
    degrades to the shared-semaphore case, which is still safe under
    the TPU runtime's in-launch-order execution of collectives — the
    same contract a single fixed id relied on for ALL families."""
    return 11 + (n_nodes * 7919 + rows * 131 + width) % 4093


def _exchange_kernel(x_ref, out_ref, send_sem, recv_sem, *, n_nodes: int,
                     rows_per_peer: int, axis_name: str,
                     use_barrier: bool):
    """All-to-all of per-peer row blocks via N-1 one-sided remote writes."""
    my = jax.lax.axis_index(axis_name)
    C = rows_per_peer

    # Cluster barrier BEFORE posting any one-sided write: without it a
    # fast device can race ahead into the NEXT exchange kernel and its
    # remote writes could credit a slow peer's still-pending recv
    # semaphores from THIS kernel (scratch semaphore slots are reused
    # across calls).  Keyed by compiler_params.collective_id.  The
    # interpreter runs devices sequentially (no such race) and cannot
    # lower get_barrier_semaphore, so compiled runs only.
    if use_barrier:
        bar = pltpu.get_barrier_semaphore()
        for k in range(1, n_nodes):
            pltpu.semaphore_signal(
                bar, inc=1, device_id=jax.lax.rem(my + k, n_nodes),
                device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(bar, n_nodes - 1)

    # local bucket: plain local DMA (no network)
    local = pltpu.make_async_copy(
        x_ref.at[pl.ds(my * C, C)],
        out_ref.at[pl.ds(my * C, C)],
        send_sem.at[0],
    )
    local.start()

    # post every remote write first (doorbell batch), then wait all.
    # step-indexed semaphore slots keep sender/receiver symmetric: my
    # step-k push signals the receiver's recv_sem[k], and the step-k
    # push ARRIVING here (from (my - k) % N) signals mine.
    rdmas = []
    for k in range(1, n_nodes):
        peer = jax.lax.rem(my + k, n_nodes)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[pl.ds(peer * C, C)],
            dst_ref=out_ref.at[pl.ds(my * C, C)],
            send_sem=send_sem.at[k],
            recv_sem=recv_sem.at[k],
            device_id=peer,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdmas.append(rdma)

    local.wait()
    for rdma in rdmas:
        rdma.wait()


def exchange_pallas(x, axis_name: str, n_nodes: int, *,
                    interpret: bool = False):
    """Pallas remote-DMA all_to_all of one [N*C, W] int32 array.

    Call inside shard_map on per-node shards.  Equivalent to
    ``lax.all_to_all(x, axis_name, 0, 0, tiled=True)``.
    """
    if not HAVE_PALLAS:
        raise PallasUnavailableError("DSMConfig.exchange_impl")
    rows = x.shape[0]
    assert rows % n_nodes == 0
    C = rows // n_nodes
    _OBS_REMOTE_WRITES.inc(n_nodes - 1)
    _OBS_PACKED_BYTES.inc(x.size * x.dtype.itemsize)
    kernel = functools.partial(
        _exchange_kernel, n_nodes=n_nodes, rows_per_peer=C,
        axis_name=axis_name, use_barrier=not interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((n_nodes,)),
                        pltpu.SemaphoreType.DMA((n_nodes,))],
        compiler_params=_CompilerParams(
            collective_id=_collective_id(
                n_nodes, C, math.prod(x.shape[1:]))),
        interpret=interpret,
    )(x)


def exchange(tree, axis_name: str, n_nodes: int, *, interpret: bool = False):
    """Drop-in for ``transport.exchange``: the whole pytree is packed into
    ONE [N*C, sum(W)] int32 buffer and rides one kernel — one barrier and
    N-1 posted writes per step, however many request fields there are.

    Bools widen to int32; other 32-bit dtypes travel BIT-EXACTLY via
    bitcast (a value cast would corrupt floats); anything else is
    rejected rather than silently truncated.
    """
    leaves, treedef = jax.tree.flatten(tree)
    assert leaves, "empty exchange"
    rows = leaves[0].shape[0]

    def to_i32(x):
        dt = x.dtype
        if dt == jnp.bool_:
            x2 = x.astype(jnp.int32)
        elif dt == jnp.int32:
            x2 = x
        elif x.dtype.itemsize == 4:
            x2 = jax.lax.bitcast_convert_type(x, jnp.int32)
        else:
            raise ExchangeLaneError(dt)
        assert x2.shape[0] == rows, "exchange arrays must share dim 0"
        return x2.reshape(rows, -1)

    cols = [to_i32(x) for x in leaves]
    widths = [c.shape[1] for c in cols]
    packed = cols[0] if len(cols) == 1 else jnp.concatenate(cols, axis=1)
    out = exchange_pallas(packed, axis_name, n_nodes, interpret=interpret)

    outs = []
    off = 0
    for x, w in zip(leaves, widths):
        piece = out[:, off:off + w].reshape(x.shape)
        off += w
        if x.dtype == jnp.bool_:
            piece = piece.astype(jnp.bool_)
        elif x.dtype != jnp.int32:
            piece = jax.lax.bitcast_convert_type(piece, x.dtype)
        outs.append(piece)
    return jax.tree.unflatten(treedef, outs)
