"""Cluster bootstrap + control-plane collectives — the Keeper role.

The reference coordinates out-of-band through memcached: node-ID assignment
(``Keeper.cpp:67-85``), all-pairs QP handshake (``DSMKeeper.cpp:36-134``),
named barriers via fetch-add + spin (``DSMKeeper.cpp:148-161``) and ``sum``
all-reduce via per-node keys (``DSMKeeper.cpp:163-176``).

On TPU the fabric needs no QP handshake — the mesh IS the connection table —
so the Keeper reduces to a small KV + collectives surface.  Single-process
SPMD (one Python process driving the whole mesh) implements it in-memory;
a multi-host deployment would back the same interface with
``jax.distributed`` 's KV store and process-group barriers, which
``jax.distributed.initialize`` already provides.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from sherman_tpu.errors import ConfigError


class Keeper:
    """In-process KV / barrier / sum with DSMKeeper's interface."""

    is_multihost = False

    def __init__(self, machine_nr: int):
        self.machine_nr = machine_nr
        self._kv: dict[str, bytes] = {}
        self._counters: dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()
        self._server_num = 0

    # -- membership (Keeper::serverEnter, Keeper.cpp:67-85) ------------------

    def server_enter(self) -> int:
        with self._lock:
            node_id = self._server_num
            self._server_num += 1
            assert node_id < self.machine_nr, "cluster full"
            return node_id

    # -- KV (Keeper::memSet/memGet/memFetchAndAdd, Keeper.cpp:115-160) -------

    def mem_set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._kv[key] = value

    def mem_get(self, key: str) -> bytes | None:
        with self._lock:
            return self._kv.get(key)

    def mem_fetch_and_add(self, key: str, delta: int = 1) -> int:
        with self._lock:
            old = self._counters[key]
            self._counters[key] = old + delta
            return old

    # -- collectives (DSMKeeper.cpp:148-176) ---------------------------------

    def barrier(self, name: str, timeout_s: float | None = None) -> None:
        """Named cluster barrier.  In single-process SPMD every node's work
        is already serialized through one driver, so arrival==completion;
        the fetch-add bookkeeping is kept for interface parity.
        ``timeout_s`` is accepted for interface parity with the guarded
        multihost barrier (trivially met here)."""
        self.mem_fetch_and_add("barrier:" + name, 1)

    def sum(self, name: str, value: int) -> int:
        """All-reduce sum of one contribution per call (cluster throughput
        aggregation in the benchmark driver, test/benchmark.cpp:336-346)."""
        with self._lock:
            k = "sum:" + name
            self._counters[k] += int(value)
            return self._counters[k]


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None,
                   heartbeat_timeout_s: int | None = None
                   ) -> "DistributedKeeper":
    """Join a multi-host deployment and return its Keeper.

    The memcached bootstrap role (``Keeper.cpp:28-56``): every host calls
    this before building the Cluster; ``jax.distributed.initialize`` is the
    out-of-band rendezvous (its coordinator service is the memcached
    analogue), after which the global mesh spans all hosts and the
    ICI/DCN fabric is the data plane.  Args follow jax.distributed
    (auto-detected on TPU pods when omitted).  ``scripts/
    multihost_launch.sh`` passes them via SHERMAN_COORD / SHERMAN_NPROC /
    SHERMAN_PROC_ID, read here when the args are omitted.

    ``heartbeat_timeout_s`` (env ``SHERMAN_HEARTBEAT_S``) tunes the
    coordination service's DEATH-detection latency: when a process stops
    heartbeating for this long, every surviving process is terminated
    with a diagnostic instead of hanging in its next collective — the
    crash-only "fail fast" half of the failure story (utils/failure.py;
    the reference hangs forever, SURVEY.md §5).  Default follows jax
    (100 s).  Stalled-but-alive peers are the other half: guarded
    ``barrier(..., timeout_s=...)`` raises a catchable PeerFailure.
    """
    import os

    import jax
    if coordinator_address is None:
        coordinator_address = os.environ.get("SHERMAN_COORD")
        if coordinator_address is not None:
            # env fills only the args the caller omitted; partial launcher
            # env falls through as None (jax.distributed auto-detects
            # where the platform supports it)
            nproc = os.environ.get("SHERMAN_NPROC")
            pid = os.environ.get("SHERMAN_PROC_ID")
            if num_processes is None and nproc:
                num_processes = int(nproc)
            if process_id is None and pid:
                process_id = int(pid)
    if heartbeat_timeout_s is None:
        hb = os.environ.get("SHERMAN_HEARTBEAT_S")
        if hb:
            try:
                heartbeat_timeout_s = int(hb)
            except ValueError:
                raise ConfigError(
                    f"SHERMAN_HEARTBEAT_S={hb!r} is not a whole number of "
                    "seconds; fix the env var (e.g. '10') or unset it to "
                    "keep jax's default") from None
    if coordinator_address is not None:
        # Must run before ANY jax computation or backend query — even
        # jax.process_count() initializes the backends and would make
        # this raise.  Omit coordinator_address if jax.distributed was
        # already initialized out-of-band (e.g. TPU pod auto-init).
        kw = {}
        if heartbeat_timeout_s is not None:
            kw["heartbeat_timeout_seconds"] = heartbeat_timeout_s
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id, **kw)
    elif heartbeat_timeout_s is not None:
        # auto-init path (e.g. TPU pod pre-initialized out-of-band):
        # jax.distributed is already up, the knob cannot be applied —
        # say so instead of letting the operator believe death
        # detection runs at the requested latency
        import warnings
        warnings.warn(
            f"heartbeat_timeout_s={heartbeat_timeout_s} ignored: "
            "jax.distributed was initialized outside init_multihost "
            "(auto-init); death detection keeps the pre-configured "
            "timeout", RuntimeWarning, stacklevel=2)
    return DistributedKeeper()


class DistributedKeeper(Keeper):
    """Multi-host Keeper over jax's process group.

    Replaces the in-process KV/collectives when the mesh spans hosts:
    node-ID assignment maps to ``jax.process_index`` (``serverEnter``'s
    atomic-increment role, Keeper.cpp:67-85), ``barrier`` to a global
    device sync (DSMKeeper.cpp:148-161), and ``sum`` to a process
    allgather + reduce (DSMKeeper.cpp:163-176).  The KV surface stays
    host-local: cluster-global state lives in the DSM itself (the root
    pointer is a meta-page word installed by CAS), so cross-host KV is
    only needed for diagnostics.
    """

    is_multihost = True

    def __init__(self):
        import jax
        super().__init__(machine_nr=jax.process_count())
        self._jax = jax

    def server_enter(self) -> int:
        return self._jax.process_index()

    def barrier(self, name: str, timeout_s: float | None = None) -> None:
        """Named cluster barrier.

        Default (``timeout_s=None``): a global DEVICE sync — flushes
        queued device work everywhere, the strongest form.  Like the
        reference's memcached spin (``DSMKeeper.cpp:148-161``) it hangs
        forever if a peer died.

        Guarded (``timeout_s`` set): a host-level barrier with a
        deadline through the coordination service's heartbeat tracking;
        raises :class:`sherman_tpu.utils.failure.PeerFailure` naming the
        missing processes instead of hanging (the failure-detection
        surface the reference lacks — SURVEY.md §5 "failed nodes hang
        the system").  Control-plane only: does not flush device queues.
        """
        if timeout_s is not None:
            from sherman_tpu.utils import failure
            key = "guarded_barrier:" + name
            with self._lock:
                attempt = self._counters[key]
            used = attempt
            try:
                used = failure.barrier_guarded(name, timeout_s,
                                               attempt=attempt)
            except failure.PeerFailure as e:
                used = e.attempt
                raise
            finally:
                # advance past the attempt actually consumed (success OR
                # burned-by-timeout) so a retry after PeerFailure — and
                # the stalled peer's own late call, via the burn marker —
                # land on a fresh, matching barrier id
                with self._lock:
                    self._counters[key] = max(self._counters[key], used + 1)
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)

    def live_processes(self) -> list[int]:
        """Heartbeat-based liveness probe (see utils.failure)."""
        from sherman_tpu.utils import failure
        return failure.live_processes(self.machine_nr)

    def sum(self, name: str, value: int) -> int:
        import numpy as np
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            np.asarray([value], np.int64))
        return int(np.sum(gathered))
