"""Request routing over the ICI mesh: the RDMA-fabric analogue.

Where the reference posts verbs on per-destination RC queue pairs
(``ThreadConnection.cpp:21-27``, ``src/rdma/Operation.cpp``), we route a
fixed-capacity batch of requests per step with one ``all_to_all`` exchange:
each node scatters its requests into per-destination buckets of capacity
``C``; one tiled all_to_all delivers every bucket to its owner; replies ride
the reverse exchange.  Requests beyond a bucket's capacity are dropped with
``ok=0`` and retried by the caller — the moral equivalent of a full RDMA
send queue.

All helpers run *inside* ``shard_map`` on per-node shards.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sherman_tpu import obs

# Collective-issue accounting.  ``exchange`` executes INSIDE compiled
# SPMD programs, so a per-execution host counter is impossible without
# round-tripping device state; what IS observable host-side is each
# exchange issued during program tracing.  The counters therefore mean:
# one inc per collective issued per program BUILD (recompiles included),
# with ``bytes`` the per-node payload that collective moves on every
# execution of that program.  Executed-op truth stays with the DSM's
# device counters ("dsm.*" in the registry snapshot).
_OBS_XCH_ISSUES = obs.counter("transport.exchange_issues_traced")
_OBS_XCH_BYTES = obs.counter("transport.exchange_bytes_per_step")
_OBS_XCH_PALLAS = obs.counter("transport.pallas_exchange_issues_traced")
_OBS_AG_ISSUES = obs.counter("transport.allgather_issues_traced")
_OBS_AG_BYTES = obs.counter("transport.allgather_bytes_per_step")


def _tree_nbytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def bucketize(dest, active, n_nodes: int, capacity: int):
    """Assign each request a slot in its destination bucket.

    Args:
      dest: [R] int32 destination node per request.
      active: [R] bool; inactive requests are never routed.
      n_nodes, capacity: static bucket geometry.

    Returns:
      (bucket_idx[R] int32 in [0, n_nodes*capacity) or -1,
       routed[R] bool).
    """
    R = dest.shape[0]
    d = jnp.where(active, dest, n_nodes).astype(jnp.int32)
    perm = jnp.argsort(d, stable=True)
    sd = d[perm]
    starts = jnp.searchsorted(sd, sd, side="left")
    rank = jnp.arange(R, dtype=jnp.int32) - starts.astype(jnp.int32)
    ok = (sd < n_nodes) & (rank < capacity)
    bidx = jnp.where(ok, sd * capacity + rank, -1).astype(jnp.int32)
    bucket_idx = jnp.zeros(R, jnp.int32).at[perm].set(bidx)
    return bucket_idx, bucket_idx >= 0


def scatter_to_buckets(field, bucket_idx, n_slots: int):
    """Place request fields [R, ...] into bucket slots [n_slots, ...]."""
    safe = jnp.where(bucket_idx >= 0, bucket_idx, n_slots)
    out = jnp.zeros((n_slots,) + field.shape[1:], field.dtype)
    return out.at[safe].set(field, mode="drop")


def gather_rows(x, axis_name: str):
    """Tiled ``all_gather`` of ``x`` along dim 0 — the reply-side
    answer-table broadcast shared by every fan-out kernel (the engine's
    combined-search fan-out and the device-staged serve/mixed serve):
    each node contributes its local row block, every node receives the
    full table, and client slots gather from GLOBAL row indices.

    One helper so collective PLACEMENT is a single code site: the
    all-gather always runs AFTER the descent/stack (on the packed [U, 4]
    answer lanes, never on the raw descent outputs — 4 int32 words/row
    is the minimal reply payload) and before the per-client take.
    Traced-issue accounting follows :func:`exchange`'s convention: one
    inc per collective per program BUILD, bytes = the per-step GLOBAL
    payload every node receives."""
    if hasattr(jax.lax, "axis_size"):
        n = jax.lax.axis_size(axis_name)
    else:  # JAX < 0.5: psum of a literal folds to a static int
        n = jax.lax.psum(1, axis_name)
    _OBS_AG_ISSUES.inc()
    _OBS_AG_BYTES.inc(int(x.size) * x.dtype.itemsize * int(n))
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)


def exchange(tree, axis_name: str, *, impl: str = "xla"):
    """Tiled all_to_all of every array in the pytree along dim 0.

    impl="xla" (default): one XLA all_to_all per array — compiler-
    scheduled over ICI.  impl="pallas": the whole pytree packed into one
    buffer of explicit per-peer one-sided remote-DMA writes
    (:mod:`transport_pallas`) — the literal RDMA-verbs analogue;
    interpreter-mode on CPU meshes.
    """
    if impl == "pallas":
        from sherman_tpu.parallel import transport_pallas
        if hasattr(jax.lax, "axis_size"):
            n_nodes = jax.lax.axis_size(axis_name)
        else:  # JAX < 0.5: psum of a literal folds to a static int
            n_nodes = jax.lax.psum(1, axis_name)
        interpret = jax.default_backend() != "tpu"
        _OBS_XCH_PALLAS.inc()
        _OBS_XCH_BYTES.inc(_tree_nbytes(tree))
        return transport_pallas.exchange(tree, axis_name, n_nodes,
                                         interpret=interpret)
    _OBS_XCH_ISSUES.inc(len(jax.tree.leaves(tree)))
    _OBS_XCH_BYTES.inc(_tree_nbytes(tree))
    return jax.tree.map(
        lambda x: jax.lax.all_to_all(x, axis_name, 0, 0, tiled=True), tree
    )
