"""Client-contract auditor — bounded history recorder + per-key
linearizability checker for the serving front door.

Every robustness receipt so far pinned STATE ("no acked write lost",
"pool bit-identical"); none pinned ORDER.  This module closes that gap
with a Jepsen-lineage history checker (PAPERS.md: Knossos, Porcupine):
the front door's completion path records *invocation/response* events
per key, and a checker decides whether the acked history is
**linearizable per key** over the repo's single-key read/insert/delete
model (no CAS) — the strongest client-visible correctness claim the
serving plane can publish, and the one that catches the bugs state
audits cannot see (a duplicate apply that resurrects a superseded
value, a stale read served after a newer write's ack).

The model (what "linearizable per key" does and does NOT claim):

- **P-composition**: linearizability is checked per key and composes
  (Herlihy/Wing locality) — a history is linearizable iff every
  per-key sub-history is.  Cross-key ordering is NOT judged (the front
  door promises none; see the serve module docstring).
- **Ops**: ``insert`` (an upsert: the register's write), ``delete``
  (writes "absent"), ``read`` (returns ``(found, value)``).  Acked-ok
  ops only: a typed-rejected op did not happen by contract and is
  never recorded.
- **Windows**: invocation = the request's submit time, response = its
  ack time — the widest (most conservative) window, so a legal
  linearization point always lies inside it.
- **Soundness polarity**: the checker NEVER false-alarms on a
  linearizable history (every flagged read provably observed a value
  no legal linearization could produce), but it can ACCEPT
  non-linearizable histories when distinct writes wrote equal values
  (reads-from ambiguity) or when sampling/ring bounds dropped events.
  An auditor that cries wolf gets turned off; one that stays quiet
  until it is RIGHT gets trusted.

The per-key check, for each read R (interval ``[inv, resp]``):

- a write W is a *legal source* iff ``W.inv < R.resp`` (W may
  linearize before R) and W is not *superseded* — no write W' lies
  entirely between them (``W.resp < W'.inv`` and ``W'.resp < R.inv``);
- the *initial state* is legal iff no write responded entirely before
  R began; an UNKNOWN initial (recorder attached mid-stream) makes
  such reads pass vacuously rather than guess;
- R must match some legal source's outcome (insert v -> ``(True,
  v)``; delete -> ``(False, ·)``), else it is flagged — ``stale_read``
  when it matches a superseded source (the duplicate-apply signature),
  ``phantom_read`` when it matches nothing ever written.

Deployment shapes:

- **inline** (:class:`Auditor`): a sampling recorder hooked into the
  serve completion path (keys sampled by hash, so ALL ops on a sampled
  key are seen — per-op sampling would fabricate missing-write
  violations) plus a background checker thread; violations count under
  ``audit.violations``, flight-record (``audit.violation``) and
  auto-dump the black box.  Inline cost is self-timed
  (:meth:`Auditor.cost_frac`) and pinned < 2% of the serve wall in CI
  (the obs-cost-pin pattern).
- **offline** (:func:`check_events` / :func:`check_jsonl`): the
  contract drill records its full client-side history and re-checks it
  after crash + recovery + migration — ``linearizable == true`` in the
  committed receipt is a perfgate hard red when false.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

import numpy as np

from sherman_tpu import obs
from sherman_tpu.errors import ConfigError
from sherman_tpu.ops import bits

__all__ = ["OP_READ", "OP_INSERT", "OP_DELETE", "HistoryRecorder",
           "Auditor", "check_events", "check_key_history", "check_jsonl",
           "dump_jsonl"]

OP_READ = 0
OP_INSERT = 1
OP_DELETE = 2
_OP_NAMES = {OP_READ: "read", OP_INSERT: "insert", OP_DELETE: "delete"}

_OBS_EVENTS = obs.counter("audit.events")
_OBS_READS = obs.counter("audit.reads_checked")
_OBS_HIST = obs.counter("audit.histories_checked")
_OBS_VIOL = obs.counter("audit.violations")
_OBS_WINDOWS = obs.counter("audit.windows")
_OBS_RESETS = obs.counter("audit.carry_resets")


# ---------------------------------------------------------------------------
# The checker (pure functions over event tuples)
# ---------------------------------------------------------------------------
# An event is (key, op, t_inv, t_resp, value, found):
#   read:   value/found = the observed result (value meaningful iff found)
#   insert: value = the written value (found unused)
#   delete: value unused

def check_key_history(events, initial=None, open_writes=()):
    """Check one key's events (see the module docstring's rule).

    ``initial``: ``(found0, value0)`` when the pre-history state is
    known (e.g. the bulk-loaded value), else None = UNKNOWN — reads
    with the initial state legal then pass vacuously.  ``open_writes``:
    outcomes ``(found, value)`` of writes known in flight beyond this
    window (the incremental checker's retained tail) — always legal,
    never superseding.  Returns a list of violation dicts.
    """
    writes = sorted((e for e in events if e[1] != OP_READ),
                    key=lambda e: e[2])
    reads = [e for e in events if e[1] == OP_READ]
    out = []
    open_set = set(open_writes)
    for r in reads:
        _, _, r_inv, r_resp, r_val, r_found = r
        observed = (bool(r_found), int(r_val) if r_found else None)
        if observed in open_set:
            continue
        # T = latest invocation among writes ENTIRELY before this read:
        # any write responding before T is superseded for this read
        t_super = None
        for w in writes:
            if w[3] < r_inv and (t_super is None or w[2] > t_super):
                t_super = w[2]
        legal = set()
        stale = set()
        none_before = True
        for w in writes:
            if w[3] < r_inv:
                none_before = False
            if w[2] >= r_resp:
                continue  # cannot linearize before the read
            outcome = (True, int(w[4])) if w[1] == OP_INSERT \
                else (False, None)
            if t_super is not None and w[3] < t_super:
                stale.add(outcome)  # superseded: illegal, but a match
                continue            # here names the failure class
            legal.add(outcome)
        if none_before:
            if initial is None:
                continue  # unknown initial state still legal: vacuous
            legal.add((bool(initial[0]),
                       int(initial[1]) if initial[0] else None))
        if observed in legal:
            continue
        out.append({
            "key": int(r[0]),
            "kind": "stale_read" if observed in stale else "phantom_read",
            "observed": {"found": observed[0], "value": observed[1]},
            "legal": sorted(
                {"absent" if not f else v for f, v in legal},
                key=str),
            "read": {"t_inv": r_inv, "t_resp": r_resp},
        })
    return out


def check_events(events, initial=None, open_writes=None):
    """Group events by key, check each sub-history (P-composition).

    ``initial``: {key: (found0, value0)} or None.  ``open_writes``:
    {key: [(found, value), ...]} of in-flight write outcomes per key.
    -> {"keys", "events", "reads", "violations": [...],
    "linearizable": bool}.
    """
    by_key: dict = {}
    for e in events:
        by_key.setdefault(int(e[0]), []).append(e)
    violations = []
    reads = 0
    for k, evs in by_key.items():
        reads += sum(1 for e in evs if e[1] == OP_READ)
        violations.extend(check_key_history(
            evs,
            initial=(initial or {}).get(k),
            open_writes=(open_writes or {}).get(k, ())))
    return {"keys": len(by_key), "events": len(events), "reads": reads,
            "violations": violations,
            "linearizable": not violations}


def dump_jsonl(events, path: str) -> int:
    """Persist events as grep-able JSONL (one object per line) — the
    drill's offline-recheck artifact."""
    n = 0
    with open(path, "w") as f:
        for k, op, t_inv, t_resp, val, found in events:
            f.write(json.dumps({
                "key": int(k), "op": _OP_NAMES[op],
                "t_inv": t_inv, "t_resp": t_resp,
                "value": int(val) if val is not None else None,
                "found": bool(found)}) + "\n")
            n += 1
    return n


def check_jsonl(path: str, initial=None) -> dict:
    """Offline check over a :func:`dump_jsonl` artifact (drill
    receipts re-audited after the fact)."""
    names = {v: k for k, v in _OP_NAMES.items()}
    events = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            events.append((d["key"], names[d["op"]], d["t_inv"],
                           d["t_resp"], d["value"], d["found"]))
    return check_events(events, initial=initial)


def check_fenced_rejected(read_fn, fenced) -> dict:
    """Prove fenced acks never merged (PR 18's split-brain pin).

    ``fenced``: iterable of ``(key, value)`` pairs a STALE primary
    acked after its lease epoch was bumped — writes that landed past
    the promotion fence point and must never become visible.
    ``read_fn``: ``keys ndarray -> (values, found)`` against the
    promoted primary's live state.  A fenced pair counts as MERGED
    only when the key is found AND carries the fenced value — a
    found key with a different value is the re-driven client's own
    legitimate write through the new primary's dedup window, which
    is exactly the contract (typed rejection then re-drive), not a
    merge.  -> ``{"fenced", "merged", "violations": [...]}`` with
    ``merged`` the drill's ``fenced_acks_merged`` receipt field.
    """
    pairs = [(int(k), int(v)) for k, v in fenced]
    if not pairs:
        return {"fenced": 0, "merged": 0, "violations": []}
    keys = np.asarray([k for k, _ in pairs], np.uint64)
    vals, found = read_fn(keys)
    vals = np.asarray(vals)
    found = np.asarray(found, bool)
    violations = []
    for i, (k, v) in enumerate(pairs):
        if bool(found[i]) and int(vals[i]) == v:
            violations.append({"key": k, "fenced_value": v,
                               "kind": "fenced_ack_merged"})
    return {"fenced": len(pairs), "merged": len(violations),
            "violations": violations}


# ---------------------------------------------------------------------------
# Bounded recorder
# ---------------------------------------------------------------------------

class HistoryRecorder:
    """Bounded, thread-safe ring of per-key invocation/response events.

    ``sample_mod``: record only keys with ``mix64(key) % sample_mod ==
    0`` — sampling is BY KEY (every op on a sampled key is seen), the
    only shape under which a missing event cannot fabricate a
    violation.  1 = record everything (the drill's client-side
    ledger).  Ring overflow drops oldest and counts ``dropped`` — the
    incremental checker resets its carried state when it sees drops
    (bounded memory over false alarms).
    """

    def __init__(self, capacity: int = 1 << 16, sample_mod: int = 1):
        if capacity <= 0 or sample_mod <= 0:
            raise ConfigError(
                "HistoryRecorder wants positive capacity/sample_mod")
        self.capacity = int(capacity)  # bound in EVENTS, not batches
        self.sample_mod = int(sample_mod)
        self._lock = threading.Lock()
        self._ring: deque = deque()  # batch entries; _size sums events
        self._size = 0
        self.events = 0
        self.dropped = 0

    def sample_mask(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized per-key sampling decision (hash, not modulo of
        the raw key: sequential keyspaces must not alias the stride)."""
        if self.sample_mod == 1:
            return np.ones(keys.shape, bool)
        return bits.mix64_np(np.ascontiguousarray(keys, np.uint64)) \
            % np.uint64(self.sample_mod) == 0

    def observe(self, op: int, keys, t_inv: float, t_resp: float,
                values=None, found=None, ok=None) -> int:
        """Record one completed batch's per-key events (sampled).

        ``values``: written/read values (insert/read); ``found``: read
        results; ``ok``: write apply mask (False rows did not happen —
        typed-rejected, never recorded).  Returns events recorded.

        HOT PATH (the < 2% pin's numerator): the batch is stored as
        ONE ring entry of numpy arrays — a vectorized mask + slice and
        an append, no per-key Python loop; expansion to per-key event
        tuples happens at :meth:`drain`, on the checker's clock.
        """
        keys = np.ascontiguousarray(keys, np.uint64)
        if self.sample_mod == 1 and ok is None:
            # full-recording fast path: reference the caller's batch
            # arrays as-is (serve hands completed, no-longer-mutated
            # slices) — no mask, no index, no copy
            n = int(keys.size)
            if n == 0:
                return 0
            ks = keys
            vs = np.ascontiguousarray(values, np.uint64) \
                if values is not None else None
            fs = np.ascontiguousarray(found, bool) \
                if found is not None else None
        else:
            mask = self.sample_mask(keys)
            if ok is not None:
                mask = mask & np.ascontiguousarray(ok, bool)
            idx = np.nonzero(mask)[0]
            n = int(idx.size)
            if n == 0:
                return 0
            ks = keys[idx]
            vs = np.ascontiguousarray(values, np.uint64)[idx] \
                if values is not None else None
            fs = np.ascontiguousarray(found, bool)[idx] \
                if found is not None else None
        if n > self.capacity:
            self.dropped += n - self.capacity
            ks = ks[-self.capacity:]
            vs = vs[-self.capacity:] if vs is not None else None
            fs = fs[-self.capacity:] if fs is not None else None
        with self._lock:
            self._size += min(n, self.capacity)
            self._ring.append((op, ks, t_inv, t_resp, vs, fs))
            while self._size > self.capacity and len(self._ring) > 1:
                old = self._ring.popleft()
                self._size -= int(old[1].size)
                self.dropped += int(old[1].size)
            self.events += n
        _OBS_EVENTS.inc(n)
        return n

    @staticmethod
    def _expand(batch) -> list:
        """One ring batch -> per-key event tuples (checker-side)."""
        op, ks, t_inv, t_resp, vs, fs = batch
        kl = ks.tolist()
        vl = vs.tolist() if vs is not None else None
        fl = fs.tolist() if fs is not None else None
        return [(kl[i], op, t_inv, t_resp,
                 vl[i] if vl is not None else None,
                 fl[i] if fl is not None else True)
                for i in range(len(kl))]

    def drain(self, before: float | None = None,
              floor: float | None = None):
        """Pop a SETTLED window of events (all, when ``before`` is
        None) -> (drained, retained_writes, dropped_since_last).

        The cut is ``min(before, floor, oldest retained invocation)``:
        an event whose window reaches back past the candidate cut pins
        the cut at its invocation, so no retained event ever overlaps
        a drained one — the incremental checker then never judges a
        read in one window against a carry that overwrote a write the
        read was actually concurrent with (the window-split false
        positive; the checker's no-false-alarms polarity).  ``floor``
        is the oldest still-UNRECORDED operation's start (the serve
        layer's write-flush intents): an op the ring cannot see yet
        must also never be split from the reads that observed it.
        Retained writes are still handed back as the ``open_writes``
        belt for the checker."""
        with self._lock:
            if before is None:
                db = list(self._ring)
                self._ring.clear()
                self._size = 0
                kb = []
            else:
                # fixpoint cut: the largest c <= min(before, floor)
                # such that NO batch spans it (inv < c <= resp).  A
                # single pass over resp >= before is not enough — a
                # batch retained only because ANOTHER batch lowered
                # the cut must still contribute its own invocation,
                # or its source writes drain out from under it.  One
                # descending-resp sweep reaches the fixpoint: once a
                # batch's resp falls below the running cut, no later
                # (smaller-resp) batch can be retained either.
                cut = before if floor is None else min(before, floor)
                for b in sorted(self._ring, key=lambda b: -b[3]):
                    if b[3] < cut:
                        break
                    if b[2] < cut:
                        cut = b[2]
                db, kb = [], []
                for b in self._ring:
                    (db if b[3] < cut else kb).append(b)
                self._ring.clear()
                self._ring.extend(kb)
                self._size = sum(int(b[1].size) for b in kb)
            dropped, self.dropped = self.dropped, 0
        drained = [e for b in db for e in self._expand(b)]
        retained = [e for b in kb if b[0] != OP_READ
                    for e in self._expand(b)]
        return drained, retained, dropped

    def snapshot(self) -> list:
        with self._lock:
            batches = list(self._ring)
        return [e for b in batches for e in self._expand(b)]


# ---------------------------------------------------------------------------
# The inline sampling auditor
# ---------------------------------------------------------------------------

class Auditor:
    """Sampling background auditor over the serve completion stream.

    The serve hooks call :meth:`observe_read` / :meth:`observe_write`
    inline (vectorized mask + ring append — the self-timed cost the
    < 2% CI pin measures); :meth:`tick` runs the checker over a
    settled window (events older than ``horizon_s``, so cross-thread
    recording lag cannot split a read from the write it observed) and
    carries each key's last unambiguous write forward as the next
    window's initial state.  ``start()`` runs ticks on a daemon
    thread; drills call :meth:`tick` directly for determinism.

    On violation: ``audit.violations`` counts, an ``audit.violation``
    flight event records the first few, and the black box auto-dumps
    (env-gated + debounced — the degraded-entry contract).
    """

    def __init__(self, sample_mod: int = 8, capacity: int = 1 << 16,
                 interval_s: float = 0.25, horizon_s: float = 0.05):
        self.rec = HistoryRecorder(capacity=capacity,
                                   sample_mod=sample_mod)
        self.interval_s = float(interval_s)
        self.horizon_s = float(horizon_s)
        self._carry: dict = {}   # key -> (found, value) settled initial
        # _lock guards carry/intents/counters and is taken by the
        # serve hot path (begin_ops/end_ops) — the expensive checker
        # pass must NEVER run under it; _tick_lock serializes whole
        # ticks (background thread vs drills calling tick() directly)
        self._lock = threading.Lock()
        self._tick_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.cost_ns = 0         # inline observe cost (self-timed)
        # in-flight write-flush intents: registered BEFORE a flush
        # applies, released after its events are recorded — the drain
        # floor (an applied-but-unrecorded write, e.g. parked behind a
        # group-commit fsync past the horizon, must never be split
        # from the reads that already observed it)
        self._intents: dict = {}
        self._intent_seq = 0
        self.windows = 0
        self.histories_checked = 0
        self.reads_checked = 0
        self.violations = 0
        self.carry_resets = 0
        self.last_violations: list = []
        import weakref
        ref = weakref.ref(self)

        def _collect():
            a = ref()
            return a._collect() if a is not None else {}

        obs.register_collector("audit", _collect)

    # -- inline hooks (self-timed; the < 2% pin's numerator) -----------------

    def observe_read(self, keys, values, found, t_inv: float,
                     t_resp: float) -> None:
        t0 = time.perf_counter_ns()
        self.rec.observe(OP_READ, keys, t_inv, t_resp,
                         values=values, found=found)
        self._note_cost(time.perf_counter_ns() - t0)

    def observe_write(self, op: int, keys, t_inv: float, t_resp: float,
                      values=None, ok=None) -> None:
        t0 = time.perf_counter_ns()
        self.rec.observe(op, keys, t_inv, t_resp, values=values, ok=ok)
        self._note_cost(time.perf_counter_ns() - t0)

    def _note_cost(self, ns: int) -> None:
        self.cost_ns += ns

    def begin_ops(self, t_floor: float | None = None) -> int:
        """Register an in-flight batch intent (called BEFORE a read
        dispatch / write flush); the background cut will not advance
        past ``t_floor`` (the batch's oldest invocation — defaults to
        now) until :meth:`end_ops` releases it.  This is what makes
        the incremental checker sound against RECORDING lag: an op's
        events land in the ring only after its ack (a write can park
        behind a group-commit fsync; a pipelined read completes a
        whole iteration later), and a window must never close over
        ops that observed it but have not surfaced yet."""
        with self._lock:
            self._intent_seq += 1
            tok = self._intent_seq
            self._intents[tok] = time.perf_counter() \
                if t_floor is None else float(t_floor)
        return tok

    def end_ops(self, tok: int) -> None:
        """Release a batch intent — AFTER its events were recorded
        (or the batch failed without applying)."""
        with self._lock:
            self._intents.pop(tok, None)

    def cost_frac(self, wall_s: float) -> float:
        """Inline observe cost as a fraction of ``wall_s`` — the
        obs-cost-pin receipt (< 0.02 asserted in CI and published by
        the contract drill)."""
        return (self.cost_ns / 1e9) / wall_s if wall_s > 0 else 0.0

    # -- the background check -------------------------------------------------

    def tick(self, drain_all: bool = False) -> dict:
        """One checker pass over the settled window; returns its
        :func:`check_events` verdict.

        Lock discipline: ``_tick_lock`` serializes whole ticks; the
        shared ``_lock`` (which ``begin_ops``/``end_ops`` take on the
        serve DISPATCH path) is held only for the carry/intents
        snapshots and the counter fold — never across the expensive
        ``check_events`` pass, so a long window can not stall the
        serving loop behind the checker."""
        with self._tick_lock:
            return self._tick_locked(drain_all)

    def _tick_locked(self, drain_all: bool) -> dict:
        cutoff = None if drain_all \
            else time.perf_counter() - self.horizon_s
        with self._lock:
            floor = min(self._intents.values()) if self._intents \
                else None
        events, retained, dropped = self.rec.drain(before=cutoff,
                                                   floor=floor)
        if os.environ.get("SHERMAN_AUDIT_DEBUG"):
            import sys
            print(f"AUDITTICK now={time.perf_counter():.4f} "
                  f"cutoff={cutoff} floor={floor} "
                  f"drained={len(events)} kept={len(self.rec._ring)} "
                  f"dropped={dropped}", file=sys.stderr)
        with self._lock:
            if dropped:
                # ring overflow dropped events: the carried initials
                # may name superseded writes — reset to UNKNOWN
                # (vacuous passes) rather than fabricate violations
                self._carry.clear()
                self.carry_resets += 1
                _OBS_RESETS.inc()
            carry_before = dict(self._carry)
        open_w: dict = {}
        for e in retained:
            open_w.setdefault(int(e[0]), []).append(
                (True, int(e[4])) if e[1] == OP_INSERT
                else (False, None))
        res = check_events(events, initial=carry_before,
                           open_writes=open_w)
        if res["violations"] and os.environ.get("SHERMAN_AUDIT_DEBUG"):
            import sys
            for v in res["violations"][:4]:
                k = v["key"]
                print(f"AUDITDBG key={k} carry={carry_before.get(k)}"
                      f" floor={floor} cutoff={cutoff}"
                      f" window={[e for e in events if e[0] == k]}"
                      f" retained={[e for e in retained if e[0] == k]}"
                      f" viol={v}", file=sys.stderr)
        with self._lock:
            self._update_carry(events, retained)
            self.windows += 1
            self.histories_checked += res["keys"]
            self.reads_checked += res["reads"]
            _OBS_WINDOWS.inc()
            _OBS_HIST.inc(res["keys"])
            _OBS_READS.inc(res["reads"])
            if res["violations"]:
                self.violations += len(res["violations"])
                _OBS_VIOL.inc(len(res["violations"]))
                self.last_violations = res["violations"][-8:]
        for v in res["violations"][:4]:
            obs.record_event("audit.violation", key=v["key"],
                             violation=v["kind"],
                             observed=v["observed"]["value"],
                             found=v["observed"]["found"])
        if res["violations"]:
            obs.auto_dump("audit-violation")
        return res

    def _update_carry(self, events, retained) -> None:
        """Carry each key's last write forward as the next window's
        initial state — UNAMBIGUOUS writes only: when another write
        overlaps the last one with a different outcome, the key's
        initial is unknowable and carrying a guess could fabricate a
        violation next window, so the key drops to UNKNOWN."""
        last: dict = {}
        for e in events:
            if e[1] == OP_READ:
                continue
            k = int(e[0])
            cur = last.get(k)
            if cur is None or e[3] > cur[3]:
                last[k] = e
        overlap_keys = {int(e[0]) for e in retained}
        for k, w in last.items():
            outcome = (True, int(w[4])) if w[1] == OP_INSERT \
                else (False, None)
            ambiguous = k in overlap_keys or any(
                e is not w and e[1] != OP_READ and int(e[0]) == k
                and e[3] > w[2]
                and ((True, int(e[4])) if e[1] == OP_INSERT
                     else (False, None)) != outcome
                for e in events)
            if ambiguous:
                self._carry.pop(k, None)
            else:
                self._carry[k] = outcome

    def seed_initial(self, keys, values) -> None:
        """Declare the pre-history state of ``keys`` (e.g. the
        bulk-loaded values) so reads preceding the first recorded
        write are judged instead of passing vacuously."""
        keys = np.ascontiguousarray(keys, np.uint64)
        values = np.ascontiguousarray(values, np.uint64)
        with self._lock:
            for k, v in zip(keys.tolist(), values.tolist()):
                self._carry[k] = (True, v)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Auditor":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — the auditor
                    # must never take serving down; a raising checker
                    # is recorded and the loop keeps watching
                    obs.record_event("audit.checker_error",
                                     error=repr(e))

        self._thread = threading.Thread(target=_loop,
                                        name="sherman-audit",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, final_tick: bool = True) -> dict | None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10.0)
            self._thread = None
        return self.tick(drain_all=True) if final_tick else None

    # -- telemetry ------------------------------------------------------------

    def _collect(self) -> dict:
        return {
            "events": float(self.rec.events),
            "dropped": float(self.rec.dropped),
            "windows": float(self.windows),
            "histories_checked": float(self.histories_checked),
            "reads_checked": float(self.reads_checked),
            "violations": float(self.violations),
            "carry_resets": float(self.carry_resets),
            "cost_ms": self.cost_ns / 1e6,
        }

    def stats(self) -> dict:
        out = {
            "sample_mod": self.rec.sample_mod,
            "events": self.rec.events,
            "windows": self.windows,
            "histories_checked": self.histories_checked,
            "reads_checked": self.reads_checked,
            "violations": self.violations,
            "carry_resets": self.carry_resets,
            "cost_ms": round(self.cost_ns / 1e6, 3),
            "linearizable": self.violations == 0,
        }
        if self.last_violations:
            out["last_violations"] = list(self.last_violations)
        return out
