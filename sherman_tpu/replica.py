"""Replication plane — journal-shipped replica groups, lease-epoch
failover, and replica-served reads.

Sherman keeps exactly one copy of every page (survey L2/L3: the MN
pool is singular), so the recovery plane's answer to node loss is a
disk restore — RPO 0, but an availability gap of seconds while the
chain restores and the journal replays.  This module closes that gap
with the substrate the repo already has: the CRC-framed v2 journal
(``utils/journal.py``) *is* a replication log, and the lease-epoch
table (``cluster.py``) already names liveness.

**Topology** (the repo's one-process-cluster emulation pattern): a
:class:`ReplicaGroup` of N in-process **follower** engines, each built
from the primary's on-disk checkpoint chain exactly the way
``RecoveryPlane.recover`` builds one (restore chain -> Tree ->
BatchedEngine -> heap rebuild), then fed by a **journal-shipping
tail**: an incremental reader (:class:`JournalTailer`) over the
primary's live segment directory.  Followers apply shipped
J_UPSERT/J_DELETE/J_HEAP_*/J_ACK records through
:func:`sherman_tpu.utils.journal.apply_records` — the SAME dispatch
loop recovery replays through, so a follower's apply semantics and
recovery's are identical by construction, not by convention.

**Watermarks**: each follower publishes a durable ``applied_(cid,
link, seq)`` watermark (atomic JSON + fsync in its own directory)
after every apply batch — the promotion-time freshness order and the
operator's replication-lag receipt.

**Tail contract at the shipping boundary**: a torn frame at the tail
of the LIVE segment is an append in flight — the follower WAITS (it
must never truncate the primary's file; that is recovery's
prerogative).  A torn tail on a segment that has a successor (or
after the primary is declared dead) is final by the same rule
recovery applies: skip it and advance.  Mid-file corruption raises
the typed ``JournalCorruptError`` — a follower must refuse rather
than silently diverge.  A swept current segment (a checkpoint
retired it under the tail) or a re-based chain id triggers a
re-bootstrap from the newer chain — convergent, because the chain
captured everything the swept segment carried.

**Failover** rides the lease-epoch table: the group registers a
lease for the primary's write authority and fences every journal
append through it (:class:`_FencedJournal`).  :meth:`ReplicaGroup.
promote` expires that lease (``cluster.expire_client`` — the same
epoch bump that makes a dead client's locks revocable), bumps the
group epoch, catches every follower up to the durable journal end
(records are fsync'd pre-ack, so the catch-up is RPO 0), and picks
the highest-watermark follower.  A stale primary that keeps writing
hits the epoch check at its own durability gate and fails typed
(:class:`StalePrimaryError`) — fenced, never silently divergent.
The promoted follower's replayed J_ACK window re-seeds the front
door's exactly-once dedup window (``ShermanServer.seed_dedup``), so
a write retried across the failover re-acks its original result.

**Replica reads**: a follower serves the hot-key tier's traffic
through the leaf cache's existing version-revalidation token against
its OWN snapshot — a probe hit is re-certified against the
follower's pool, bit-identical to a descent there; anything stale is
a miss and forwards to the primary, never a lie.  The group serves
replica reads only from a follower that is caught up to the durable
journal end at its last pump (the freshness gate the drill pins).

``tools/failover_drill.py`` (``bench.py --failover-drill``) rehearses
kill -> promote -> retry-across-failover end to end and pins
``lost_acks == 0``, ``duplicate_acks == 0``, ``linearizable ==
true``.  OFF by default (``SHERMAN_REPL=0``): no follower is
constructed and the primary is bit-identical to a build without the
subsystem (the replica-off identity pin).

Observability: the ``repl.`` collector (followers, applied records/
rows, absorbed acks, torn-tail waits, re-bootstraps, promotions,
fenced writes, replica reads served/forwarded, watermark, epoch) plus
``repl.lag_ms`` / ``repl.availability_gap_ms`` gauges and flight
events (``repl.promote``, ``repl.fenced``, ``repl.tail_torn_wait``,
``repl.rebootstrap``).

**Partition plane (PR 18).**  Four additions close the replication
plane's impolite-failure half:

- **Quorum acks** (:meth:`ReplicaGroup.wait_quorum`, rode by the
  front door's ``ServeConfig.ack_quorum`` / ``SHERMAN_ACK_QUORUM``,
  default 1 = primary durability only, bit-identical when off): an
  ack resolves only after K-1 follower watermarks COVER the durable
  journal frontier captured when the write's engine op returned — a
  coverage token ``(segment, size)``, compared against each tailer's
  consumed ``(segment, offset)``.  Bounded wait, typed
  :class:`QuorumTimeoutError` on expiry; the write is already durable
  on the primary and its rid is already in the dedup window, so a
  client retry re-acks exactly-once.
- **Replication chaos** (:meth:`ReplicaGroup.attach_chaos`): a
  ``chaos.ReplChaos`` layer perturbs tailer polls (drop / delay /
  reorder / partition / slow) and the fence's lease-table view (a
  frozen snapshot = the primary cannot see its own epoch bump — the
  split-brain ingredient).  Reordered views fail the per-frame CRC
  typed and are retried clean: detection-or-refusal, never silent
  divergence.
- **Split-brain fence point**: :meth:`ReplicaGroup.promote` expires
  the lease and captures the durable frontier ATOMICALLY (under the
  journal's own append lock), fencing every tailer at that byte.  A
  lease-partitioned stale primary keeps acking past the fence; those
  bytes are provably never shipped (the fence caps every poll), the
  heal surfaces :class:`StalePrimaryError` to the next write, and
  :meth:`ReplicaGroup.count_fenced_suffix` counts the rejected
  suffix for the drill's ``fenced_acks_merged == 0`` pin.
- **Anti-entropy repair** (:class:`AntiEntropy`): a periodic audit
  (watermark freshness + consumed-segment CRC vs a re-read of the
  same byte range + pool-page compare against the primary, sampled
  or full) that QUARANTINES a divergent follower out of the
  read-serving set and every quorum, re-ships it through the same
  restore-then-replay core bootstrap uses, re-audits, and re-admits.
  ``SHERMAN_ANTI_ENTROPY_S`` drives a background cadence (0 = off,
  the shipped default); drills call :meth:`AntiEntropy.tick`.

A live torn tail is additionally watched: after ``SHERMAN_TAIL_WAIT_S``
at one position the tailer probes the primary's lease and raises a
typed :class:`TailStalledError` when it is dead (satellite: a
follower must never hang forever on a dead primary's torn tail).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
import zlib

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ConfigError, StateError
from sherman_tpu.utils import journal as J

_OBS_LAG_MS = obs.gauge("repl.lag_ms")
_OBS_GAP_MS = obs.gauge("repl.availability_gap_ms")
_OBS_APPLIED = obs.counter("repl.applied_records")
_OBS_PROMOTIONS = obs.counter("repl.promotions")
_OBS_FENCED = obs.counter("repl.fenced_writes")
_OBS_QUORUM_MS = obs.gauge("repl.quorum_wait_ms")
_OBS_QUORUM_TIMEOUTS = obs.counter("repl.quorum_timeouts")
_OBS_STALLS = obs.counter("repl.tail_stalls")
_OBS_AUDITS = obs.counter("repl.anti_entropy_audits")
_OBS_QUARANTINES = obs.counter("repl.quarantines")
_OBS_REPAIRS = obs.counter("repl.repairs")


class StalePrimaryError(StateError):
    """A write reached the durability gate under an EXPIRED primary
    lease: the group promoted a follower (epoch bumped past this
    primary's), so appending would fork the journal behind the new
    primary's back.  The write fails typed — the fence that makes
    split-brain structurally impossible instead of merely unlikely."""


class QuorumTimeoutError(StateError):
    """A quorum-ack wait expired: fewer than ``ack_quorum - 1``
    follower watermarks covered the write's durable journal frontier
    within the bounded wait (partitioned, quarantined or slow
    followers).  The write IS durable on the primary and its rid is
    already in the exactly-once dedup window — a client retry re-acks
    the original result once the quorum recovers."""


class TailStalledError(StateError):
    """The journal-shipping tail waited ``SHERMAN_TAIL_WAIT_S`` at one
    torn-tail position and the primary's lease is no longer live: the
    in-flight append will never complete (the appender is dead), so
    waiting longer just hangs the follower.  The caller escalates —
    typically by promoting (whose ``final`` catch-up pass skips the
    torn tail exactly as recovery truncates it)."""


class _ResyncRequired(StateError):
    """Internal tailer signal: the current segment was swept (a
    checkpoint covered it) or the chain re-based — re-bootstrap the
    follower from the newer chain (convergent by the checkpoint
    coverage argument)."""


# -- incremental segment reader ---------------------------------------------


class JournalTailer:
    """Incremental frame reader over one recovery directory's live
    journal segments — the shipping feed.  Tracks (segment, byte
    offset); :meth:`poll` decodes every frame fully landed since the
    last call and advances across rotations.  See the module
    docstring for the torn-tail / sweep / re-base contract."""

    def __init__(self, directory: str, cid: str,
                 host_id: int | None = None):
        self.dir = directory
        self.cid = cid
        #: chain namespace to tail: ``None`` = the legacy un-tagged
        #: single-host chain; an integer tails that host's ``-h<id>-``
        #: chain — the cross-host replication seam (PR 19): a follower
        #: on host B points this at host A's namespace in the shared
        #: directory and ships A's stream through the same core
        self.host_id = host_id
        self._cur: str | None = None   # current segment path
        self._off = 0                  # consumed bytes (past magic)
        self._fmt = 2
        self.torn_waits = 0
        #: replication fault layer (``chaos.ReplChaos``) + this
        #: tailer's follower index on its clock — group-attached
        self.chaos = None
        self.follower_idx = 0
        #: fence point ``(segment path, byte limit)``: promotion caps
        #: every poll here — bytes past it are a stale primary's
        #: fenced suffix, never shipped
        self.fence: tuple[str, int] | None = None
        #: rolling CRC32 over every byte CONSUMED of the current
        #: segment (from byte 0) — the anti-entropy audit re-reads the
        #: same range and must reproduce it exactly
        self.seg_crc = 0
        #: stall watchdog: ``() -> bool`` probe of the primary's lease
        #: + the bounded torn-tail wait (SHERMAN_TAIL_WAIT_S)
        self.lease_probe = None
        self.tail_wait_s = C.tail_wait_s()
        self.stalls = 0
        self._torn_pos: tuple | None = None
        self._torn_since = 0.0
        self._stall_evented = False
        #: what the fault layer did to the LAST poll — ``pump`` uses
        #: these to classify a typed refusal as provably transient
        #: (perturbed view) and an empty poll as a cut feed (the
        #: caught-up gate must not certify freshness through a
        #: partition)
        self.last_poll_perturbed = False
        self.last_poll_cut = False
        self._perturb_next = False
        # anchor EAGERLY: the tailer owes its creator every record in
        # the earliest segment alive NOW.  A lazy (first-poll) anchor
        # would let a checkpoint sweep that segment unseen — the
        # records would land in a delta the follower never restored,
        # and the tail would silently resume past them.  Anchored,
        # the sweep trips the `_cur not in segs` resync check above.
        segs = self._segments()
        if segs:
            self._cur = segs[0]

    def _segments(self) -> list[str]:
        from sherman_tpu.recovery import RecoveryPlane
        cid, _deltas, journals = RecoveryPlane._discover(
            self.dir, host_id=self.host_id)
        if cid != self.cid:
            raise _ResyncRequired(
                f"chain re-based ({self.cid} -> {cid})")
        return journals

    def poll(self, final: bool = False) -> list[tuple]:
        """-> decoded records (``with_rids`` 4-tuples) newly durable
        since the last poll, across any number of rotations.  With
        ``final`` (the primary is dead — promotion's catch-up pass) a
        torn tail on the LAST segment is final too: skipped, exactly
        as recovery would truncate it.

        The replication fault layer, when attached, perturbs THIS
        POLL'S VIEW only: a drop/delay/partition directive loses the
        fetch (no new bytes, offset untouched — the natural retry), a
        slow directive stalls first, a reorder directive routes the
        fetched bytes through :meth:`chaos.ReplChaos.view` so the
        per-frame CRC refuses them typed.  The file is never touched.
        """
        self.last_poll_perturbed = False
        self.last_poll_cut = False
        self._perturb_next = False
        if self.chaos is not None:
            d = self.chaos.on_poll(self.follower_idx)
            if d is not None:
                if d["slow_ms"]:
                    time.sleep(d["slow_ms"] / 1e3)
                if d["partition"] or d["drop"] or d["freeze"]:
                    # the fetch never arrives this round
                    self.last_poll_cut = True
                    return []
                self._perturb_next = d["reorder"]
        out: list[tuple] = []
        try:
            self._poll_into(out, final)
        except J.JournalCorruptError:
            if not out:
                raise
            # records from EARLIER segments in this round were already
            # consumed (their offsets advanced): return them — losing
            # them here would be silent divergence.  The corrupt
            # segment's offset is untouched, so the error re-manifests
            # (or a clean view supersedes a perturbed one) next poll.
        return out

    def _poll_into(self, out: list, final: bool) -> None:
        while True:
            segs = self._segments()
            if self.fence is not None:
                # promotion's fence point: the old chain ends at an
                # exact byte — segments past it (a stale primary's
                # rotations) do not exist for this tailer
                segs = [s for s in segs if s <= self.fence[0]]
            if self._cur is not None and self._cur not in segs:
                # the segment under the tail was swept: a checkpoint
                # covers it, but bytes may have landed there after our
                # last read — only the chain knows, so re-bootstrap
                # (always safe; sweeps happen once per checkpoint)
                raise _ResyncRequired(
                    f"segment {os.path.basename(self._cur)} swept "
                    "under the tail")
            if self._cur is None:
                if not segs:
                    return
                self._cur, self._off, self._fmt = segs[0], 0, 2
                self.seg_crc = 0
            # list-then-read ordering matters: a successor listed NOW
            # proves the current segment was closed before we read it,
            # so a torn tail below is final, not in flight
            recs, torn = self._poll_segment(self._cur)
            out.extend(recs)
            later = [s for s in segs if s > self._cur]
            if later:
                # rotation: finish here (torn tail, if any, is final —
                # the successor supersedes it) and advance
                self._cur, self._off, self._fmt = later[0], 0, 2
                self.seg_crc = 0
                continue
            if torn and not final:
                # live-tail rule: an append may be in flight — wait.
                self.torn_waits += 1
                obs.record_event("repl.tail_torn_wait",
                                 segment=os.path.basename(self._cur),
                                 at_byte=self._off)
                self._note_torn_wait()
            return

    def _note_torn_wait(self) -> None:
        """Bounded-wait watchdog: a torn tail stuck at ONE position
        past ``tail_wait_s`` is either a slow-but-live appender (lease
        live: keep waiting, event once) or a dead primary's forever-
        torn append (lease dead — or no probe to ask: raise typed
        rather than hang the follower)."""
        now = time.monotonic()
        pos = (self._cur, self._off)
        if self._torn_pos != pos:
            self._torn_pos = pos
            self._torn_since = now
            self._stall_evented = False
            return
        waited = now - self._torn_since
        if waited < self.tail_wait_s:
            return
        if self.lease_probe is not None and self.lease_probe():
            if not self._stall_evented:
                self._stall_evented = True
                obs.record_event(
                    "repl.tail_slow",
                    segment=os.path.basename(self._cur),
                    at_byte=self._off, waited_s=round(waited, 3))
            return
        self.stalls += 1
        _OBS_STALLS.inc()
        obs.record_event("repl.tail_stalled",
                         segment=os.path.basename(self._cur),
                         at_byte=self._off, waited_s=round(waited, 3))
        raise TailStalledError(
            f"journal tail torn at {os.path.basename(self._cur)}"
            f":{self._off} for {waited:.1f}s with the primary's lease "
            "dead — the in-flight append will never land; promote "
            "(the final catch-up pass skips it) instead of waiting")

    def covers(self, path: str, size: int) -> bool:
        """True when every byte of ``path[:size]`` has been consumed —
        this follower's durable watermark reaches the frontier token
        (the quorum-ack coverage test).  Segment names sort in append
        order within one chain, so a LATER current segment means
        ``path`` was fully consumed (or swept into the chain this
        follower restored — covered either way)."""
        if self._cur is None:
            return False
        if self._cur > path:
            return True
        return self._cur == path and self._off >= int(size)

    def _poll_segment(self, path: str) -> tuple[list[tuple], bool]:
        """-> (records decoded from complete frames past the offset,
        torn) — ``torn`` True when a partial frame remains at the
        tail.  Never writes the file (the primary owns it)."""
        try:
            with open(path, "rb") as f:
                f.seek(self._off)
                blob = f.read()
        except FileNotFoundError:
            raise _ResyncRequired(
                f"segment {os.path.basename(path)} swept under the "
                "tail")
        base = self._off
        if self.fence is not None and path == self.fence[0]:
            # cap the view at the fence point: bytes past it are a
            # stale primary's fenced suffix (mid-frame cut decodes as
            # a torn tail, which the final pass skips)
            blob = blob[: max(0, self.fence[1] - base)]
        if self._perturb_next and blob:
            blob = self.chaos.view(blob)
            self.last_poll_perturbed = True
            self._perturb_next = False
        pos = 0
        if base == 0:
            if len(blob) < len(J.MAGIC):
                return [], True  # magic still landing
            head = blob[: len(J.MAGIC)]
            if head == J.MAGIC:
                self._fmt = 2
            elif head == J.MAGIC_V1:
                self._fmt = 1  # pre-rid segment: dedup-disabled replay
            else:
                raise J.JournalCorruptError(
                    f"{path}: bad journal magic {head!r}")
            pos = len(J.MAGIC)
        out: list[tuple] = []
        size = len(blob)
        while pos < size:
            if pos + J._HDR.size > size:
                break  # torn header
            length, crc = J._HDR.unpack_from(blob, pos)
            end = pos + J._HDR.size + length
            if length > J.MAX_PAYLOAD:
                if end > size or end < 0:
                    break  # torn length word — tail rule
                raise J.JournalCorruptError(
                    f"{path}: frame at byte {base + pos} claims "
                    f"{length} bytes (> {J.MAX_PAYLOAD}) with bytes "
                    "following")
            if end > size:
                break  # torn payload
            payload = blob[pos + J._HDR.size: end]
            if zlib.crc32(payload) != crc:
                if end == size:
                    break  # torn append at the tail
                raise J.JournalCorruptError(
                    f"{path}: CRC mismatch at byte {base + pos} with "
                    f"{size - end} bytes following — content "
                    "corruption, refusing to apply")
            out.append(J._decode_payload(payload, base + pos,
                                         self._fmt))
            pos = end
        # consumed frames are CRC-clean, so the prefix is byte-equal
        # to the true file even under a perturbed view (any changed
        # byte fails its covering frame and stops consumption first)
        if pos:
            self.seg_crc = zlib.crc32(blob[:pos], self.seg_crc)
        self._off = base + pos
        return out, pos < size


# -- the epoch fence at the durability gate ---------------------------------


class _FencedJournal:
    """Journal proxy that checks the primary's lease epoch before
    every append — the write fence.  Everything else (close, stats,
    path, rotation handoff) delegates to the wrapped segment, so the
    recovery plane's rotation protocol is untouched."""

    def __init__(self, inner, group: "ReplicaGroup"):
        self._inner = inner
        self._group = group

    def append(self, *a, **kw):
        self._group._check_fence()
        return self._inner.append(*a, **kw)

    def append_acks(self, *a, **kw):
        self._group._check_fence()
        return self._inner.append_acks(*a, **kw)

    def append_heap(self, *a, **kw):
        self._group._check_fence()
        return self._inner.append_heap(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


# -- one follower -----------------------------------------------------------


class Follower:
    """One in-process follower engine: bootstrapped from the
    primary's on-disk chain the way ``RecoveryPlane.recover``
    bootstraps (the shared-code contract), tailed from its journal
    directory, publishing a durable applied watermark."""

    def __init__(self, group: "ReplicaGroup", idx: int):
        self.group = group
        self.idx = idx
        self.dir = os.path.join(group.dir, f"follower-{idx}")
        os.makedirs(self.dir, exist_ok=True)
        self.stats: dict = {}
        #: replayed exactly-once entries {(tenant, rid): (op, ok[,
        #: handles])} — promotion re-seeds the front door from the
        #: winner's window (``ShermanServer.seed_dedup``)
        self.window: dict = {}
        self.rebootstraps = -1  # first bootstrap is not a re-
        self.caught_up = False
        #: anti-entropy verdict: a quarantined follower serves no
        #: replica read and counts toward no quorum until repaired
        self.quarantined = False
        self.chaos_detected = 0  # perturbed views refused typed
        self.cluster = self.tree = self.eng = None
        self.cid = None
        self.link = 0   # delta links restored at (re)bootstrap
        self.seq = 0    # records applied since (re)bootstrap
        self._bootstrap()

    def _bootstrap(self) -> None:
        """(Re)build the engine from the primary's chain — the same
        restore -> Tree -> engine -> heap-rebuild sequence
        ``RecoveryPlane.recover`` runs, minus the re-base (a follower
        never writes the chain it follows)."""
        from sherman_tpu.models.batched import BatchedEngine
        from sherman_tpu.models.btree import Tree
        from sherman_tpu.recovery import RecoveryPlane
        from sherman_tpu.utils import checkpoint as CK

        g = self.group
        cid, deltas, _journals = RecoveryPlane._discover(
            g.primary_dir, host_id=g.primary_host)
        from sherman_tpu.recovery import _base_name
        cluster = CK.restore_chain(
            os.path.join(g.primary_dir, _base_name(g.primary_host)),
            deltas)
        tree = Tree(cluster)
        eng = BatchedEngine(tree, batch_per_node=g.batch_per_node,
                            tcfg=g.tcfg)
        eng.attach_router()
        if cluster.cfg.heap_pages_per_node > 0:
            from sherman_tpu.models.value_heap import ValueHeap
            ValueHeap(eng).rebuild()
        if g.cache_slots:
            eng.attach_leaf_cache(slots=g.cache_slots)
        self.cluster, self.tree, self.eng = cluster, tree, eng
        self.cid = cid
        self.link = len(deltas)
        self.seq = 0
        self.window.clear()
        self.caught_up = False
        self.tailer = JournalTailer(g.primary_dir, cid,
                                    host_id=g.primary_host)
        g._arm_tailer(self)
        # a checkpoint that lands between the restore above and the
        # tailer's anchor would sweep records into a delta we did not
        # restore while the tailer anchors past them — re-discover and
        # start over if the chain moved (bounded: one loop per
        # checkpoint, and checkpoints are seconds apart)
        cid2, deltas2, _ = RecoveryPlane._discover(
            g.primary_dir, host_id=g.primary_host)
        if cid2 != cid or len(deltas2) != len(deltas):
            self._bootstrap()
            return
        self.rebootstraps += 1
        if self.rebootstraps:
            obs.record_event("repl.rebootstrap", follower=self.idx,
                             cid=cid, link=self.link)
        self._publish_watermark()

    def pump(self, final: bool = False) -> int:
        """Poll the tail and apply every newly durable record through
        the shared :func:`~sherman_tpu.utils.journal.apply_records`
        core; publish the watermark.  Returns records applied."""
        try:
            recs = self.tailer.poll(final=final)
        except _ResyncRequired:
            self._bootstrap()
            recs = self.tailer.poll(final=final)
        except J.JournalCorruptError:
            if not self.tailer.last_poll_perturbed:
                raise  # real mid-file corruption: refuse, typed
            # the fault layer perturbed THIS poll's view — provably
            # transient (the file was never touched, the offset never
            # advanced past a refused frame): count the detection and
            # retry a clean view next poll
            if self.tailer.chaos is not None:
                self.tailer.chaos.note_detected()
            self.chaos_detected += 1
            self.caught_up = False
            return 0
        if not recs:
            if self.tailer.last_poll_perturbed:
                # the perturbed view was refused WITHOUT an error: the
                # damage landed in the last frame, which decodes as a
                # torn tail (CRC break at end-of-view) — refused all
                # the same, so it counts as a detection; the offset
                # never advanced, the next clean poll supersedes
                if self.tailer.chaos is not None:
                    self.tailer.chaos.note_detected()
                self.chaos_detected += 1
                self.caught_up = False
                return 0
            # an empty poll certifies freshness only when the feed was
            # actually read — a cut fetch (drop/partition) says nothing
            self.caught_up = not self.tailer.last_poll_cut
            return 0
        sink: list = []
        J.apply_records(recs, self.eng, ack_sink=sink,
                        stats=self.stats)
        for entry in sink:
            # later acks override earlier — the front door's own
            # last-writer window semantics; provenance rides along
            rid, tenant = entry[0], entry[1]
            self.window[(tenant, rid)] = tuple(entry[2:])
        self.seq += len(recs)
        if self.tailer.last_poll_perturbed:
            # a clean prefix applied ahead of the refused damage (the
            # prefix is byte-equal to the true file — any changed byte
            # fails its covering frame first): still one detection
            if self.tailer.chaos is not None:
                self.tailer.chaos.note_detected()
            self.chaos_detected += 1
        self.caught_up = not (self.tailer.last_poll_perturbed
                              or self.tailer.last_poll_cut)
        _OBS_APPLIED.inc(len(recs))
        self._publish_watermark()
        return len(recs)

    def watermark(self) -> tuple[str, int, int]:
        """``(cid, link, seq)`` — the promotion freshness order
        (compared lexicographically on (link, seq) within one cid;
        promote catches every follower up first, so the order only
        breaks ties between already-converged followers)."""
        return (self.cid, self.link, self.seq)

    def _publish_watermark(self) -> None:
        """Durable ``applied_(cid, seq)`` watermark: atomic JSON
        (tmp + rename + fsync) in the follower's own directory — an
        operator (or a future cold-started group) reads how far this
        follower got without touching its engine."""
        path = os.path.join(self.dir, "watermark.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"cid": self.cid, "link": self.link,
                                "seq": self.seq}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def serve_read(self, keys):
        """Replica-served reads through the leaf cache's revalidation
        token against the follower's OWN snapshot: a probe hit is
        re-certified against this pool (bit-identical to a descent
        here); a stale or absent entry is a miss.  Returns ``(vals,
        hit)`` — or ``None`` when this follower may not serve at all
        (no cache attached, not caught up to the durable journal end
        at its last pump, or quarantined by the anti-entropy audit:
        staleness forwards, never lies)."""
        cache = self.eng.leaf_cache
        if cache is None or not self.caught_up or self.quarantined:
            return None
        from sherman_tpu.ops import bits
        eng = self.eng
        keys = np.asarray(keys, np.uint64)
        n = keys.shape[0]
        total = eng.cfg.machine_nr * eng.B
        vals = np.zeros(n, np.uint64)
        hit = np.zeros(n, bool)
        for i in range(0, n, total):
            chunk = keys[i:i + total]
            khi, klo = bits.keys_to_pairs(chunk)
            (khi, _), (klo, _) = eng._pad(khi), eng._pad(klo)
            active, _ = eng._pad(np.ones(chunk.shape[0], bool))
            h, vhi, vlo = cache.probe(khi, klo, active)
            v = bits.pairs_to_keys(vhi, vlo)
            vals[i:i + total] = v[: chunk.shape[0]]
            hit[i:i + total] = h[: chunk.shape[0]]
        return vals, hit

    def admit(self, keys) -> dict:
        """Admit ``keys`` into the follower's leaf cache (resolved
        against its own snapshot) — the replica read set."""
        if self.eng.leaf_cache is None:
            raise StateError("follower has no leaf cache attached "
                             "(ReplicaGroup(cache_slots=...))")
        return self.eng.leaf_cache.fill(np.asarray(keys, np.uint64))


# -- the group --------------------------------------------------------------


class ReplicaGroup:
    """N journal-shipped followers + the lease-epoch failover plane
    over one primary ``RecoveryPlane``.  See the module docstring for
    the full protocol; lifecycle::

        plane.checkpoint_base()          # the chain followers feed on
        group = ReplicaGroup(plane, n=2)
        group.start()                    # background tail (or pump())
        ...
        srv.kill()                       # primary dies
        rcpt = group.promote(t_dead=t)   # fence + catch-up + pick
        new_eng = group.promoted.eng     # resume the front door here
        srv2 = ShermanServer(new_eng, cfg)
        srv2.start(...)
        srv2.seed_dedup(group.promoted_window())
        group.note_resumed()             # availability-gap receipt
    """

    def __init__(self, plane, n: int | None = None, *,
                 poll_ms: float | None = None,
                 batch_per_node: int = 512, tcfg=None,
                 cache_slots: int | None = None,
                 directory: str | None = None):
        n = C.replica_count() if n is None else int(n)
        if n <= 0:
            raise ConfigError(
                "ReplicaGroup wants >= 1 follower (replication is OFF "
                "by default — SHERMAN_REPL=0; use ReplicaGroup."
                "from_env for knob-gated construction)")
        if plane.cid is None:
            raise StateError("primary has no chain yet: "
                             "plane.checkpoint_base() first")
        self.plane = plane
        self.primary_dir = plane.dir
        #: chain namespace the followers tail (the primary plane's own
        #: host tag): ``None`` on a single-host plane; on a multihost
        #: plane this is the owner's ``-h<id>-`` namespace, so a group
        #: constructed against host A's plane but PUMPED from host B's
        #: context ships A's stream — the cross-host seam (PR 19)
        self.primary_host = plane._htag
        self.batch_per_node = int(batch_per_node)
        self.tcfg = tcfg
        self.cache_slots = cache_slots
        self.poll_ms = C.replica_poll_ms() if poll_ms is None \
            else float(poll_ms)
        self.dir = directory or os.path.join(plane.dir, "replicas")
        os.makedirs(self.dir, exist_ok=True)
        #: group epoch: bumped at every promotion; the fence below
        #: rides the CLUSTER lease-epoch table, this mirrors it for
        #: receipts
        self.epoch = 1
        # the primary's write authority as a lease on its own cluster:
        # promotion expires it (the same epoch bump that revokes a
        # dead client's locks) and the fence checks it per append
        self._lease = plane.cluster.register_client()
        self._install_fence(plane.eng)
        self.promoted: Follower | None = None
        self._t_dead: float | None = None
        self.availability_gap_ms: float | None = None
        # receipt counters (plain adds on the accounting paths, SL006)
        self.promotions = 0
        self.fenced_writes = 0
        self.reads_served = 0
        self.reads_forwarded = 0
        self.last_pump_records = 0
        self.quorum_acks = 0
        self.quorum_timeouts = 0
        self.quorum_wait_ms = 0.0
        self.quorum_timeout_s = 5.0
        self.fenced_suffix_records = 0
        self._chaos = None                       # chaos.ReplChaos
        self._ship_chaos_off = False  # promote detaches the ship side
        self._fence: tuple[str, int] | None = None
        self.anti_entropy: "AntiEntropy | None" = None
        self._last_pump_t = 0.0
        self._rr = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pump_lock = threading.Lock()
        self.followers = [Follower(self, i) for i in range(n)]
        ref = weakref.ref(self)

        def _collect():
            g = ref()
            return g._collect() if g is not None else {}

        obs.register_collector("repl", _collect)

    @classmethod
    def from_env(cls, plane, **kw):
        """Knob-gated construction: ``None`` when ``SHERMAN_REPL`` is
        unset/0 (the shipped default — no follower, no tailer, the
        primary bit-identical to a build without the subsystem)."""
        n = C.replica_count()
        return None if n == 0 else cls(plane, n, **kw)

    # -- hot accounting (SL006 scope: plain adds only) -----------------------

    def _note_reads(self, served: int, forwarded: int) -> None:
        self.reads_served += served
        self.reads_forwarded += forwarded

    def _note_fenced(self) -> None:
        self.fenced_writes += 1

    def _note_quorum(self, ms: float) -> None:
        self.quorum_acks += 1
        self.quorum_wait_ms += ms

    # -- replication chaos ---------------------------------------------------

    def attach_chaos(self, layer) -> None:
        """Install a replication fault layer (``chaos.ReplChaos``):
        every tailer poll routes through its directives and the
        durability fence reads the lease table through its (possibly
        frozen) view.  Detach with ``attach_chaos(None)``."""
        self._chaos = layer
        for f in self.followers:
            self._arm_tailer(f)

    def _arm_tailer(self, f: Follower) -> None:
        """(Re)wire a follower's tailer to the group-level hooks —
        called at every (re)bootstrap so a fresh tailer inherits the
        fault layer, the stall probe and the promotion fence."""
        t = f.tailer
        t.follower_idx = f.idx
        t.chaos = None if self._ship_chaos_off else self._chaos
        t.lease_probe = self._lease_probe
        t.fence = self._fence

    def _lease_probe(self) -> bool:
        """Is the primary's write lease still live?  The stall
        watchdog's question — asked of the TRUE lease table (the
        followers sit on the majority side; only the partitioned
        primary's own view can be frozen by chaos)."""
        return self.plane.cluster.lease_is_live(self._lease.tag,
                                                self._lease.epoch)

    # -- quorum acks ---------------------------------------------------------

    def quorum_token(self) -> tuple[str, int]:
        """The durable journal frontier ``(segment path, size)`` — the
        coverage token quorum waits resolve against
        (``RecoveryPlane.journal_frontier``).  Appends fsync before
        returning, so a token captured AFTER an engine op returned
        bounds every byte of that op's records."""
        return self.plane.journal_frontier()

    def wait_quorum(self, need: int, timeout_s: float | None = None,
                    token: tuple[str, int] | None = None) -> dict:
        """Block until ``need`` non-quarantined follower watermarks
        COVER the durable journal frontier (``token``, default:
        captured now) — the quorum-ack gate.  Pumps the tail while
        waiting; raises :class:`QuorumTimeoutError` at the bounded
        deadline.  Returns ``{"needed", "covered", "waited_ms"}``."""
        need = int(need)
        rc = {"needed": need, "covered": 0, "waited_ms": 0.0}
        if need <= 0:
            return rc
        if need > len(self.followers):
            raise ConfigError(
                f"quorum of {need} followers wanted but the group has "
                f"{len(self.followers)} — ack_quorum counts the "
                "primary plus at most every follower")
        path, size = token if token is not None else self.quorum_token()
        t0 = time.perf_counter()
        deadline = t0 + (self.quorum_timeout_s if timeout_s is None
                         else float(timeout_s))
        while True:
            n = 0
            for f in self.followers:
                if not f.quarantined and f.tailer.covers(path, size):
                    n += 1
            if n >= need:
                break
            if time.perf_counter() >= deadline:
                self.quorum_timeouts += 1
                _OBS_QUORUM_TIMEOUTS.inc()
                obs.record_event("repl.quorum_timeout", needed=need,
                                 covered=n,
                                 segment=os.path.basename(path),
                                 size=size)
                raise QuorumTimeoutError(
                    f"quorum ack: {n}/{need} followers cover the "
                    f"frontier ({os.path.basename(path)}:{size}) at "
                    "the deadline — partitioned, quarantined or slow "
                    "followers; the write IS durable on the primary "
                    "and its rid stays in the dedup window, so a "
                    "retry re-acks exactly-once")
            if self.pump() == 0:
                time.sleep(0.001)
        ms = (time.perf_counter() - t0) * 1e3
        self._note_quorum(ms)
        _OBS_QUORUM_MS.set(ms)
        rc["covered"] = n
        rc["waited_ms"] = ms
        return rc

    # -- fencing -------------------------------------------------------------

    def _install_fence(self, eng) -> None:
        """Wrap the primary engine's journal attachment so EVERY
        segment (current and every future rotation) appends through
        the epoch check — the fence survives checkpoint rotations
        because it wraps the attach point, not one segment."""
        group = self
        orig_attach = eng.attach_journal

        def fenced_attach(journal):
            orig_attach(None if journal is None
                        else _FencedJournal(journal, group))

        eng.attach_journal = fenced_attach
        if eng.journal is not None:
            orig_attach(_FencedJournal(eng.journal, group))

    def _check_fence(self) -> None:
        cl = self.plane.cluster
        if self._chaos is not None:
            # the lease-table boundary's fault hook: under a lease-
            # scope partition the PRIMARY sees a frozen snapshot — it
            # cannot watch its own epoch get bumped, so it keeps
            # acking until the heal (the split-brain ingredient the
            # fence point + fenced-suffix accounting make safe)
            view = self._chaos.lease_view(cl.lease_epochs)
            live = view.get(int(self._lease.tag)) \
                == int(self._lease.epoch)
        else:
            live = cl.lease_is_live(self._lease.tag, self._lease.epoch)
        if not live:
            self._note_fenced()
            _OBS_FENCED.inc()
            obs.record_event("repl.fenced", epoch=self.epoch,
                             owner_tag=self._lease.tag)
            raise StalePrimaryError(
                "primary lease expired (group promoted under epoch "
                f"{self.epoch}): this write is fenced — a stale "
                "primary must not fork the journal")

    # -- tailing -------------------------------------------------------------

    def pump(self, final: bool = False) -> int:
        """One synchronous shipping round: every follower polls the
        tail and applies what landed.  Returns records applied (max
        over followers — they consume the same feed)."""
        with self._pump_lock:
            applied = [f.pump(final=final) for f in self.followers]
            self._last_pump_t = time.perf_counter()
        self.last_pump_records = max(applied) if applied else 0
        return self.last_pump_records

    def start(self) -> None:
        """Background shipping at ``poll_ms`` cadence (the knob-driven
        mode; drills that want determinism call :meth:`pump`)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                try:
                    self.pump()
                except Exception as e:  # noqa: BLE001 — the tail must
                    # not die silently mid-drill; surface and stop
                    obs.record_event("repl.tail_error", error=repr(e))
                    break
                self._stop.wait(self.poll_ms / 1e3)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="sherman-repl-tail")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def measure_lag(self) -> float:
        """Replication lag receipt: wall ms from 'records are durable
        in the primary journal' to 'every follower has applied them'
        — one synchronous pump, timed.  Published as ``repl.lag_ms``
        (the SLO plane's gauge)."""
        t0 = time.perf_counter()
        self.pump()
        ms = (time.perf_counter() - t0) * 1e3
        _OBS_LAG_MS.set(ms)
        return ms

    # -- replica reads -------------------------------------------------------

    def read(self, keys, forward=None):
        """Serve a read batch from the replica tier: pump, pick the
        next caught-up follower round-robin, serve its certified
        cache hits, and forward everything else (misses, stale
        entries, or a follower that may not serve) to ``forward``
        (default: the primary engine's read path).  Never a lie: a
        served value is certified against the follower's own pool AND
        the follower was caught up to the durable journal end."""
        keys = np.asarray(keys, np.uint64)
        if forward is None:
            forward = self.plane.eng.search
        # pump at the poll cadence, not per read — a read burst must
        # not turn every request into a full tail drain (the caught-up
        # gate below still bounds staleness to one poll window)
        if time.perf_counter() - self._last_pump_t \
                >= self.poll_ms / 1e3:
            self.pump()
        f = self.followers[self._rr % len(self.followers)]
        self._rr += 1
        res = f.serve_read(keys)
        if res is None:
            vals, found = forward(keys)
            self._note_reads(0, int(keys.size))
            return np.asarray(vals), np.asarray(found)
        vals, hit = res
        out_v = np.array(vals)
        out_f = np.array(hit)
        miss = ~hit
        if miss.any():
            fv, ff = forward(keys[miss])
            out_v[miss] = np.asarray(fv)
            out_f[miss] = np.asarray(ff)
        self._note_reads(int(hit.sum()), int(miss.sum()))
        return out_v, out_f

    # -- failover ------------------------------------------------------------

    def promote(self, t_dead: float | None = None) -> dict:
        """Fail over: expire the primary's lease (every later append
        through its journal is fenced typed), bump the group epoch,
        catch every follower up to the durable journal end (``final``
        poll — the dead primary appends nothing more, so a torn tail
        is final), and pick the highest-watermark follower.  Returns
        the promotion receipt; the caller resumes the front door on
        ``self.promoted.eng`` and adopts :meth:`promoted_window`."""
        t0 = time.perf_counter()
        self._t_dead = t_dead if t_dead is not None else t0
        self.stop()
        # the split-brain FENCE POINT: expire the lease and capture
        # the durable frontier ATOMICALLY with respect to appenders
        # (the journal's own append lock quiesces them), so "before
        # the epoch bump" names an exact byte.  Every byte past it is
        # a stale primary's fenced suffix: the tailers below are
        # capped there and never ship it.
        jrn = self.plane.eng.journal
        inner = getattr(jrn, "_inner", jrn)
        lock = getattr(inner, "_lock", None) \
            if inner is not None else None
        fence = None
        if lock is not None:
            with lock:
                self.plane.cluster.expire_client(self._lease.tag)
                try:
                    fence = (inner.path, os.path.getsize(inner.path))
                except OSError:
                    fence = None
        else:
            self.plane.cluster.expire_client(self._lease.tag)
        old_epoch, self.epoch = self.epoch, self.epoch + 1
        self._fence = fence
        # the majority side can reach the journal store by definition
        # of majority: the catch-up pass runs with the fault layer
        # detached from the SHIP side (the fence above still caps it
        # at the epoch bump).  The lease-table view stays chaos-routed
        # — a lease-partitioned stale primary must keep seeing its
        # frozen snapshot until the drill heals it.
        self._ship_chaos_off = True
        for f in self.followers:
            self._arm_tailer(f)
            f.pump(final=True)
        self.promoted = max(self.followers,
                            key=lambda f: (f.link, f.seq))
        self.promotions += 1
        _OBS_PROMOTIONS.inc()
        ms = (time.perf_counter() - t0) * 1e3
        receipt = {
            "winner": self.promoted.idx,
            "epoch": {"old": old_epoch, "new": self.epoch},
            "watermarks": [{"follower": f.idx, "cid": f.cid,
                            "link": f.link, "seq": f.seq}
                           for f in self.followers],
            "window": len(self.promoted.window),
            "promote_ms": round(ms, 1),
            "fence": None if fence is None else {
                "segment": os.path.basename(fence[0]),
                "size": fence[1]},
        }
        obs.record_event("repl.promote", winner=self.promoted.idx,
                         epoch=self.epoch,
                         seq=self.promoted.seq,
                         promote_ms=receipt["promote_ms"])
        return receipt

    def promoted_window(self) -> dict:
        """The winner's replayed exactly-once window, in
        ``seed_dedup`` shape ``{(tenant, rid): (op, ok[, handles])}``
        — heap-write entries keep their payload provenance."""
        if self.promoted is None:
            raise StateError("no promotion yet: promote() first")
        return dict(self.promoted.window)

    def note_resumed(self) -> float:
        """The availability-gap receipt: call when the promoted front
        door serves its first request — gap = that instant minus
        ``t_dead`` (the kill), published as
        ``repl.availability_gap_ms``."""
        if self._t_dead is None:
            raise StateError("no failover in flight: promote() first")
        ms = (time.perf_counter() - self._t_dead) * 1e3
        self.availability_gap_ms = round(ms, 1)
        _OBS_GAP_MS.set(ms)
        return self.availability_gap_ms

    def count_fenced_suffix(self) -> int:
        """Complete CRC-valid frames past the promotion fence point:
        writes a lease-partitioned stale primary durably appended
        (and acked) AFTER the epoch bump — the provably-rejected set
        the drill pins against ``fenced_acks_merged``.  Trailing torn
        bytes are an unacked in-flight append, not counted.  Call
        after the heal (the suffix grows while the partition lasts);
        updates the collector's ``fenced_suffix_records``."""
        fence = self._fence
        if fence is None:
            return 0
        path, base = fence
        try:
            with open(path, "rb") as f:
                f.seek(base)
                blob = f.read()
        except OSError:
            return 0
        n = 0
        pos = 0
        size = len(blob)
        while pos + J._HDR.size <= size:
            length, crc = J._HDR.unpack_from(blob, pos)
            end = pos + J._HDR.size + length
            if length > J.MAX_PAYLOAD or end > size:
                break
            if zlib.crc32(blob[pos + J._HDR.size: end]) != crc:
                break
            n += 1
            pos = end
        self.fenced_suffix_records = n
        return n

    # -- receipts ------------------------------------------------------------

    def _collect(self) -> dict:
        """``repl.`` pull collector — flat numbers only (the obs
        collector contract)."""
        st: dict = {}
        for f in self.followers:
            for k, v in f.stats.items():
                st[k] = st.get(k, 0) + int(v)
        top = max(self.followers, key=lambda f: (f.link, f.seq))
        return {
            "followers": len(self.followers),
            "epoch": self.epoch,
            "applied_records": st.get("records", 0),
            "applied_rows": st.get("rows", 0),
            "absorbed_acks": st.get("acks", 0),
            "torn_waits": sum(f.tailer.torn_waits
                              for f in self.followers),
            "rebootstraps": sum(f.rebootstraps
                                for f in self.followers),
            "watermark_link": top.link,
            "watermark_seq": top.seq,
            "promotions": self.promotions,
            "fenced_writes": self.fenced_writes,
            "reads_served": self.reads_served,
            "reads_forwarded": self.reads_forwarded,
            "last_pump_records": self.last_pump_records,
            "quorum_acks": self.quorum_acks,
            "quorum_timeouts": self.quorum_timeouts,
            "quorum_wait_ms": round(self.quorum_wait_ms, 3),
            "tail_stalls": sum(f.tailer.stalls
                               for f in self.followers),
            "chaos_detected": sum(f.chaos_detected
                                  for f in self.followers),
            "fenced_suffix_records": self.fenced_suffix_records,
            "quarantined": sum(1 for f in self.followers
                               if f.quarantined),
            "anti_entropy_audits": 0 if self.anti_entropy is None
            else self.anti_entropy.audits,
            "anti_entropy_repairs": 0 if self.anti_entropy is None
            else self.anti_entropy.repairs,
            "divergences": 0 if self.anti_entropy is None
            else self.anti_entropy.divergences,
        }

    def stats(self) -> dict:
        return self._collect()

    def close(self) -> None:
        if self.anti_entropy is not None:
            self.anti_entropy.stop()
        self.stop()


# -- anti-entropy follower repair --------------------------------------------


class AntiEntropy:
    """Periodic follower audit -> quarantine -> re-ship -> re-admit.

    Three checks per follower, run under the group's pump lock with
    the tail pumped and the durable frontier STABLE across the
    compare (so a mismatch is divergence, not lag):

    - **watermark freshness**: after a pump the tailer covers the
      durable journal frontier (a partitioned/lagging follower is not
      divergent — it just skips the page compare this round);
    - **consumed-segment CRC**: the rolling CRC the tailer accumulated
      over every byte it CONSUMED must equal a re-read of the same
      byte range from the primary's file (``journal.crc_of_range``) —
      a mismatch means the follower applied bytes the chain never
      shipped;
    - **pool-page compare**: rows of the follower's pool must be
      bit-identical to the primary's (the apply loop is shared code
      and deterministic, so byte equality IS the contract) — sampled
      (``sample_rows``) for the cheap background cadence, full
      (``sample_rows=0``) for the drill's detection pin.

    A divergent follower is **quarantined** (serves no replica read,
    counts toward no quorum), re-shipped through the SAME
    restore-then-replay core bootstrap uses (chain restore + journal
    tail), re-audited with a FULL page compare, and re-admitted only
    when clean — a follower that still diverges stays quarantined and
    shows up in the collector's ``quarantined`` /
    ``diverged_followers_unrepaired`` receipt (perfgate hard-reds it).

    ``SHERMAN_ANTI_ENTROPY_S`` drives the background thread cadence
    (0 disables it — the shipped default); drills and tests call
    :meth:`tick` deterministically."""

    def __init__(self, group: ReplicaGroup, *,
                 period_s: float | None = None, sample_rows: int = 128,
                 seed: int = 0):
        self.group = group
        self.period_s = C.anti_entropy_s() if period_s is None \
            else float(period_s)
        self.sample_rows = int(sample_rows)
        self._rng = np.random.default_rng(int(seed))
        self.audits = 0
        self.divergences = 0
        self.repairs = 0
        self.last_repair_ms = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        group.anti_entropy = self

    def tick(self) -> dict:
        """One audit round over every follower; divergent followers
        are quarantined, re-shipped and (when the re-audit is clean)
        re-admitted.  Returns the round receipt."""
        g = self.group
        out = []
        with g._pump_lock:
            for f in g.followers:
                r = self._audit_one(f)
                self.audits += 1
                _OBS_AUDITS.inc()
                if r["diverged"]:
                    self.divergences += 1
                    self._quarantine(f, r)
                    r["repair"] = self._repair(f)
                out.append(r)
        return {"followers": out,
                "quarantined": sum(1 for f in g.followers
                                   if f.quarantined)}

    def unrepaired(self) -> int:
        """Divergent followers still quarantined after their repair
        attempt — the drill's ``diverged_followers_unrepaired`` pin
        (perfgate marginless hard red when > 0)."""
        return sum(1 for f in self.group.followers if f.quarantined)

    # -- the audit -----------------------------------------------------------

    def _audit_one(self, f: Follower) -> dict:
        g = self.group
        f.pump()
        tok = g.quorum_token()
        t = f.tailer
        r: dict = {"follower": f.idx, "diverged": False,
                   "watermark_ok": None, "seg_crc_ok": None,
                   "pages_ok": None}
        fresh = f.caught_up and t.covers(*tok)
        r["watermark_ok"] = bool(fresh)
        if t._cur is not None and t._off > 0:
            try:
                want = J.crc_of_range(t._cur, 0, t._off)
            except OSError:
                want = None  # segment swept mid-audit: next round
            if want is not None:
                ok = t.seg_crc == want
                r["seg_crc_ok"] = bool(ok)
                r["diverged"] |= not ok
        if fresh and g.quorum_token() == tok:
            # frontier stable across the compare: a mismatch cannot
            # be lag
            ok = self._pages_equal(f, full=False)
            r["pages_ok"] = bool(ok)
            r["diverged"] |= not ok
        return r

    def _pages_equal(self, f: Follower, *, full: bool) -> bool:
        pp = np.asarray(self.group.plane.cluster.dsm.pool)
        fp = np.asarray(f.cluster.dsm.pool)
        if pp.shape != fp.shape:
            return False
        n = pp.shape[0]
        k = self.sample_rows
        if full or not k or k >= n:
            return bool(np.array_equal(pp, fp))
        rows = np.unique(self._rng.integers(0, n, k))
        return bool(np.array_equal(pp[rows], fp[rows]))

    # -- quarantine / repair -------------------------------------------------

    def _quarantine(self, f: Follower, r: dict) -> None:
        f.quarantined = True
        _OBS_QUARANTINES.inc()
        obs.record_event("repl.quarantine", follower=f.idx,
                         watermark_ok=bool(r["watermark_ok"]),
                         seg_crc_ok=r["seg_crc_ok"] is not False,
                         pages_ok=r["pages_ok"] is not False)

    def _repair(self, f: Follower) -> dict:
        """Re-ship the follower through the restore-then-replay core
        (the same chain + journal sequence bootstrap and recovery
        run), re-audit with a FULL page compare, re-admit when clean.
        Returns ``{"ok", "catchup_ms"}``."""
        t0 = time.perf_counter()
        f._bootstrap()
        f.pump()
        ok = f.caught_up and self._pages_equal(f, full=True)
        ms = (time.perf_counter() - t0) * 1e3
        self.last_repair_ms = round(ms, 1)
        if ok:
            f.quarantined = False
            self.repairs += 1
            _OBS_REPAIRS.inc()
        obs.record_event("repl.repair", follower=f.idx, ok=bool(ok),
                         catchup_ms=self.last_repair_ms)
        return {"ok": bool(ok), "catchup_ms": self.last_repair_ms}

    # -- background cadence --------------------------------------------------

    def start(self) -> None:
        """Background audits every ``period_s`` (the knob-driven mode;
        no thread when the period is 0 — the shipped default)."""
        if self.period_s <= 0 or self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                if self._stop.wait(self.period_s):
                    return
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — the audit
                    # must not die silently; surface and stop
                    obs.record_event("repl.anti_entropy_error",
                                     error=repr(e))
                    return

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="sherman-anti-entropy")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
