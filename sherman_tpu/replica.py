"""Replication plane — journal-shipped replica groups, lease-epoch
failover, and replica-served reads.

Sherman keeps exactly one copy of every page (survey L2/L3: the MN
pool is singular), so the recovery plane's answer to node loss is a
disk restore — RPO 0, but an availability gap of seconds while the
chain restores and the journal replays.  This module closes that gap
with the substrate the repo already has: the CRC-framed v2 journal
(``utils/journal.py``) *is* a replication log, and the lease-epoch
table (``cluster.py``) already names liveness.

**Topology** (the repo's one-process-cluster emulation pattern): a
:class:`ReplicaGroup` of N in-process **follower** engines, each built
from the primary's on-disk checkpoint chain exactly the way
``RecoveryPlane.recover`` builds one (restore chain -> Tree ->
BatchedEngine -> heap rebuild), then fed by a **journal-shipping
tail**: an incremental reader (:class:`JournalTailer`) over the
primary's live segment directory.  Followers apply shipped
J_UPSERT/J_DELETE/J_HEAP_*/J_ACK records through
:func:`sherman_tpu.utils.journal.apply_records` — the SAME dispatch
loop recovery replays through, so a follower's apply semantics and
recovery's are identical by construction, not by convention.

**Watermarks**: each follower publishes a durable ``applied_(cid,
link, seq)`` watermark (atomic JSON + fsync in its own directory)
after every apply batch — the promotion-time freshness order and the
operator's replication-lag receipt.

**Tail contract at the shipping boundary**: a torn frame at the tail
of the LIVE segment is an append in flight — the follower WAITS (it
must never truncate the primary's file; that is recovery's
prerogative).  A torn tail on a segment that has a successor (or
after the primary is declared dead) is final by the same rule
recovery applies: skip it and advance.  Mid-file corruption raises
the typed ``JournalCorruptError`` — a follower must refuse rather
than silently diverge.  A swept current segment (a checkpoint
retired it under the tail) or a re-based chain id triggers a
re-bootstrap from the newer chain — convergent, because the chain
captured everything the swept segment carried.

**Failover** rides the lease-epoch table: the group registers a
lease for the primary's write authority and fences every journal
append through it (:class:`_FencedJournal`).  :meth:`ReplicaGroup.
promote` expires that lease (``cluster.expire_client`` — the same
epoch bump that makes a dead client's locks revocable), bumps the
group epoch, catches every follower up to the durable journal end
(records are fsync'd pre-ack, so the catch-up is RPO 0), and picks
the highest-watermark follower.  A stale primary that keeps writing
hits the epoch check at its own durability gate and fails typed
(:class:`StalePrimaryError`) — fenced, never silently divergent.
The promoted follower's replayed J_ACK window re-seeds the front
door's exactly-once dedup window (``ShermanServer.seed_dedup``), so
a write retried across the failover re-acks its original result.

**Replica reads**: a follower serves the hot-key tier's traffic
through the leaf cache's existing version-revalidation token against
its OWN snapshot — a probe hit is re-certified against the
follower's pool, bit-identical to a descent there; anything stale is
a miss and forwards to the primary, never a lie.  The group serves
replica reads only from a follower that is caught up to the durable
journal end at its last pump (the freshness gate the drill pins).

``tools/failover_drill.py`` (``bench.py --failover-drill``) rehearses
kill -> promote -> retry-across-failover end to end and pins
``lost_acks == 0``, ``duplicate_acks == 0``, ``linearizable ==
true``.  OFF by default (``SHERMAN_REPL=0``): no follower is
constructed and the primary is bit-identical to a build without the
subsystem (the replica-off identity pin).

Observability: the ``repl.`` collector (followers, applied records/
rows, absorbed acks, torn-tail waits, re-bootstraps, promotions,
fenced writes, replica reads served/forwarded, watermark, epoch) plus
``repl.lag_ms`` / ``repl.availability_gap_ms`` gauges and flight
events (``repl.promote``, ``repl.fenced``, ``repl.tail_torn_wait``,
``repl.rebootstrap``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
import zlib

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ConfigError, StateError
from sherman_tpu.utils import journal as J

_OBS_LAG_MS = obs.gauge("repl.lag_ms")
_OBS_GAP_MS = obs.gauge("repl.availability_gap_ms")
_OBS_APPLIED = obs.counter("repl.applied_records")
_OBS_PROMOTIONS = obs.counter("repl.promotions")
_OBS_FENCED = obs.counter("repl.fenced_writes")


class StalePrimaryError(StateError):
    """A write reached the durability gate under an EXPIRED primary
    lease: the group promoted a follower (epoch bumped past this
    primary's), so appending would fork the journal behind the new
    primary's back.  The write fails typed — the fence that makes
    split-brain structurally impossible instead of merely unlikely."""


class _ResyncRequired(StateError):
    """Internal tailer signal: the current segment was swept (a
    checkpoint covered it) or the chain re-based — re-bootstrap the
    follower from the newer chain (convergent by the checkpoint
    coverage argument)."""


# -- incremental segment reader ---------------------------------------------


class JournalTailer:
    """Incremental frame reader over one recovery directory's live
    journal segments — the shipping feed.  Tracks (segment, byte
    offset); :meth:`poll` decodes every frame fully landed since the
    last call and advances across rotations.  See the module
    docstring for the torn-tail / sweep / re-base contract."""

    def __init__(self, directory: str, cid: str):
        self.dir = directory
        self.cid = cid
        self._cur: str | None = None   # current segment path
        self._off = 0                  # consumed bytes (past magic)
        self._fmt = 2
        self.torn_waits = 0
        # anchor EAGERLY: the tailer owes its creator every record in
        # the earliest segment alive NOW.  A lazy (first-poll) anchor
        # would let a checkpoint sweep that segment unseen — the
        # records would land in a delta the follower never restored,
        # and the tail would silently resume past them.  Anchored,
        # the sweep trips the `_cur not in segs` resync check above.
        segs = self._segments()
        if segs:
            self._cur = segs[0]

    def _segments(self) -> list[str]:
        from sherman_tpu.recovery import RecoveryPlane
        cid, _deltas, journals = RecoveryPlane._discover(self.dir)
        if cid != self.cid:
            raise _ResyncRequired(
                f"chain re-based ({self.cid} -> {cid})")
        return journals

    def poll(self, final: bool = False) -> list[tuple]:
        """-> decoded records (``with_rids`` 4-tuples) newly durable
        since the last poll, across any number of rotations.  With
        ``final`` (the primary is dead — promotion's catch-up pass) a
        torn tail on the LAST segment is final too: skipped, exactly
        as recovery would truncate it."""
        out: list[tuple] = []
        while True:
            segs = self._segments()
            if self._cur is not None and self._cur not in segs:
                # the segment under the tail was swept: a checkpoint
                # covers it, but bytes may have landed there after our
                # last read — only the chain knows, so re-bootstrap
                # (always safe; sweeps happen once per checkpoint)
                raise _ResyncRequired(
                    f"segment {os.path.basename(self._cur)} swept "
                    "under the tail")
            if self._cur is None:
                if not segs:
                    return out
                self._cur, self._off, self._fmt = segs[0], 0, 2
            # list-then-read ordering matters: a successor listed NOW
            # proves the current segment was closed before we read it,
            # so a torn tail below is final, not in flight
            recs, torn = self._poll_segment(self._cur)
            out.extend(recs)
            later = [s for s in segs if s > self._cur]
            if later:
                # rotation: finish here (torn tail, if any, is final —
                # the successor supersedes it) and advance
                self._cur, self._off, self._fmt = later[0], 0, 2
                continue
            if torn and not final:
                # live-tail rule: an append may be in flight — wait.
                self.torn_waits += 1
                obs.record_event("repl.tail_torn_wait",
                                 segment=os.path.basename(self._cur),
                                 at_byte=self._off)
            return out

    def _poll_segment(self, path: str) -> tuple[list[tuple], bool]:
        """-> (records decoded from complete frames past the offset,
        torn) — ``torn`` True when a partial frame remains at the
        tail.  Never writes the file (the primary owns it)."""
        try:
            with open(path, "rb") as f:
                f.seek(self._off)
                blob = f.read()
        except FileNotFoundError:
            raise _ResyncRequired(
                f"segment {os.path.basename(path)} swept under the "
                "tail")
        base = self._off
        pos = 0
        if base == 0:
            if len(blob) < len(J.MAGIC):
                return [], True  # magic still landing
            head = blob[: len(J.MAGIC)]
            if head == J.MAGIC:
                self._fmt = 2
            elif head == J.MAGIC_V1:
                self._fmt = 1  # pre-rid segment: dedup-disabled replay
            else:
                raise J.JournalCorruptError(
                    f"{path}: bad journal magic {head!r}")
            pos = len(J.MAGIC)
        out: list[tuple] = []
        size = len(blob)
        while pos < size:
            if pos + J._HDR.size > size:
                break  # torn header
            length, crc = J._HDR.unpack_from(blob, pos)
            end = pos + J._HDR.size + length
            if length > J.MAX_PAYLOAD:
                if end > size or end < 0:
                    break  # torn length word — tail rule
                raise J.JournalCorruptError(
                    f"{path}: frame at byte {base + pos} claims "
                    f"{length} bytes (> {J.MAX_PAYLOAD}) with bytes "
                    "following")
            if end > size:
                break  # torn payload
            payload = blob[pos + J._HDR.size: end]
            if zlib.crc32(payload) != crc:
                if end == size:
                    break  # torn append at the tail
                raise J.JournalCorruptError(
                    f"{path}: CRC mismatch at byte {base + pos} with "
                    f"{size - end} bytes following — content "
                    "corruption, refusing to apply")
            out.append(J._decode_payload(payload, base + pos,
                                         self._fmt))
            pos = end
        self._off = base + pos
        return out, pos < size


# -- the epoch fence at the durability gate ---------------------------------


class _FencedJournal:
    """Journal proxy that checks the primary's lease epoch before
    every append — the write fence.  Everything else (close, stats,
    path, rotation handoff) delegates to the wrapped segment, so the
    recovery plane's rotation protocol is untouched."""

    def __init__(self, inner, group: "ReplicaGroup"):
        self._inner = inner
        self._group = group

    def append(self, *a, **kw):
        self._group._check_fence()
        return self._inner.append(*a, **kw)

    def append_acks(self, *a, **kw):
        self._group._check_fence()
        return self._inner.append_acks(*a, **kw)

    def append_heap(self, *a, **kw):
        self._group._check_fence()
        return self._inner.append_heap(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


# -- one follower -----------------------------------------------------------


class Follower:
    """One in-process follower engine: bootstrapped from the
    primary's on-disk chain the way ``RecoveryPlane.recover``
    bootstraps (the shared-code contract), tailed from its journal
    directory, publishing a durable applied watermark."""

    def __init__(self, group: "ReplicaGroup", idx: int):
        self.group = group
        self.idx = idx
        self.dir = os.path.join(group.dir, f"follower-{idx}")
        os.makedirs(self.dir, exist_ok=True)
        self.stats: dict = {}
        #: replayed exactly-once entries {(tenant, rid): (op, ok[,
        #: handles])} — promotion re-seeds the front door from the
        #: winner's window (``ShermanServer.seed_dedup``)
        self.window: dict = {}
        self.rebootstraps = -1  # first bootstrap is not a re-
        self.caught_up = False
        self.cluster = self.tree = self.eng = None
        self.cid = None
        self.link = 0   # delta links restored at (re)bootstrap
        self.seq = 0    # records applied since (re)bootstrap
        self._bootstrap()

    def _bootstrap(self) -> None:
        """(Re)build the engine from the primary's chain — the same
        restore -> Tree -> engine -> heap-rebuild sequence
        ``RecoveryPlane.recover`` runs, minus the re-base (a follower
        never writes the chain it follows)."""
        from sherman_tpu.models.batched import BatchedEngine
        from sherman_tpu.models.btree import Tree
        from sherman_tpu.recovery import RecoveryPlane
        from sherman_tpu.utils import checkpoint as CK

        g = self.group
        cid, deltas, _journals = RecoveryPlane._discover(g.primary_dir)
        cluster = CK.restore_chain(
            os.path.join(g.primary_dir, "base.npz"), deltas)
        tree = Tree(cluster)
        eng = BatchedEngine(tree, batch_per_node=g.batch_per_node,
                            tcfg=g.tcfg)
        eng.attach_router()
        if cluster.cfg.heap_pages_per_node > 0:
            from sherman_tpu.models.value_heap import ValueHeap
            ValueHeap(eng).rebuild()
        if g.cache_slots:
            eng.attach_leaf_cache(slots=g.cache_slots)
        self.cluster, self.tree, self.eng = cluster, tree, eng
        self.cid = cid
        self.link = len(deltas)
        self.seq = 0
        self.window.clear()
        self.caught_up = False
        self.tailer = JournalTailer(g.primary_dir, cid)
        # a checkpoint that lands between the restore above and the
        # tailer's anchor would sweep records into a delta we did not
        # restore while the tailer anchors past them — re-discover and
        # start over if the chain moved (bounded: one loop per
        # checkpoint, and checkpoints are seconds apart)
        cid2, deltas2, _ = RecoveryPlane._discover(g.primary_dir)
        if cid2 != cid or len(deltas2) != len(deltas):
            self._bootstrap()
            return
        self.rebootstraps += 1
        if self.rebootstraps:
            obs.record_event("repl.rebootstrap", follower=self.idx,
                             cid=cid, link=self.link)
        self._publish_watermark()

    def pump(self, final: bool = False) -> int:
        """Poll the tail and apply every newly durable record through
        the shared :func:`~sherman_tpu.utils.journal.apply_records`
        core; publish the watermark.  Returns records applied."""
        try:
            recs = self.tailer.poll(final=final)
        except _ResyncRequired:
            self._bootstrap()
            recs = self.tailer.poll(final=final)
        if not recs:
            self.caught_up = True
            return 0
        sink: list = []
        J.apply_records(recs, self.eng, ack_sink=sink,
                        stats=self.stats)
        for entry in sink:
            # later acks override earlier — the front door's own
            # last-writer window semantics; provenance rides along
            rid, tenant = entry[0], entry[1]
            self.window[(tenant, rid)] = tuple(entry[2:])
        self.seq += len(recs)
        self.caught_up = True
        _OBS_APPLIED.inc(len(recs))
        self._publish_watermark()
        return len(recs)

    def watermark(self) -> tuple[str, int, int]:
        """``(cid, link, seq)`` — the promotion freshness order
        (compared lexicographically on (link, seq) within one cid;
        promote catches every follower up first, so the order only
        breaks ties between already-converged followers)."""
        return (self.cid, self.link, self.seq)

    def _publish_watermark(self) -> None:
        """Durable ``applied_(cid, seq)`` watermark: atomic JSON
        (tmp + rename + fsync) in the follower's own directory — an
        operator (or a future cold-started group) reads how far this
        follower got without touching its engine."""
        path = os.path.join(self.dir, "watermark.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"cid": self.cid, "link": self.link,
                                "seq": self.seq}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def serve_read(self, keys):
        """Replica-served reads through the leaf cache's revalidation
        token against the follower's OWN snapshot: a probe hit is
        re-certified against this pool (bit-identical to a descent
        here); a stale or absent entry is a miss.  Returns ``(vals,
        hit)`` — or ``None`` when this follower may not serve at all
        (no cache attached, or not caught up to the durable journal
        end at its last pump: staleness forwards, never lies)."""
        cache = self.eng.leaf_cache
        if cache is None or not self.caught_up:
            return None
        from sherman_tpu.ops import bits
        eng = self.eng
        keys = np.asarray(keys, np.uint64)
        n = keys.shape[0]
        total = eng.cfg.machine_nr * eng.B
        vals = np.zeros(n, np.uint64)
        hit = np.zeros(n, bool)
        for i in range(0, n, total):
            chunk = keys[i:i + total]
            khi, klo = bits.keys_to_pairs(chunk)
            (khi, _), (klo, _) = eng._pad(khi), eng._pad(klo)
            active, _ = eng._pad(np.ones(chunk.shape[0], bool))
            h, vhi, vlo = cache.probe(khi, klo, active)
            v = bits.pairs_to_keys(vhi, vlo)
            vals[i:i + total] = v[: chunk.shape[0]]
            hit[i:i + total] = h[: chunk.shape[0]]
        return vals, hit

    def admit(self, keys) -> dict:
        """Admit ``keys`` into the follower's leaf cache (resolved
        against its own snapshot) — the replica read set."""
        if self.eng.leaf_cache is None:
            raise StateError("follower has no leaf cache attached "
                             "(ReplicaGroup(cache_slots=...))")
        return self.eng.leaf_cache.fill(np.asarray(keys, np.uint64))


# -- the group --------------------------------------------------------------


class ReplicaGroup:
    """N journal-shipped followers + the lease-epoch failover plane
    over one primary ``RecoveryPlane``.  See the module docstring for
    the full protocol; lifecycle::

        plane.checkpoint_base()          # the chain followers feed on
        group = ReplicaGroup(plane, n=2)
        group.start()                    # background tail (or pump())
        ...
        srv.kill()                       # primary dies
        rcpt = group.promote(t_dead=t)   # fence + catch-up + pick
        new_eng = group.promoted.eng     # resume the front door here
        srv2 = ShermanServer(new_eng, cfg)
        srv2.start(...)
        srv2.seed_dedup(group.promoted_window())
        group.note_resumed()             # availability-gap receipt
    """

    def __init__(self, plane, n: int | None = None, *,
                 poll_ms: float | None = None,
                 batch_per_node: int = 512, tcfg=None,
                 cache_slots: int | None = None,
                 directory: str | None = None):
        n = C.replica_count() if n is None else int(n)
        if n <= 0:
            raise ConfigError(
                "ReplicaGroup wants >= 1 follower (replication is OFF "
                "by default — SHERMAN_REPL=0; use ReplicaGroup."
                "from_env for knob-gated construction)")
        if plane.cid is None:
            raise StateError("primary has no chain yet: "
                             "plane.checkpoint_base() first")
        self.plane = plane
        self.primary_dir = plane.dir
        self.batch_per_node = int(batch_per_node)
        self.tcfg = tcfg
        self.cache_slots = cache_slots
        self.poll_ms = C.replica_poll_ms() if poll_ms is None \
            else float(poll_ms)
        self.dir = directory or os.path.join(plane.dir, "replicas")
        os.makedirs(self.dir, exist_ok=True)
        #: group epoch: bumped at every promotion; the fence below
        #: rides the CLUSTER lease-epoch table, this mirrors it for
        #: receipts
        self.epoch = 1
        # the primary's write authority as a lease on its own cluster:
        # promotion expires it (the same epoch bump that revokes a
        # dead client's locks) and the fence checks it per append
        self._lease = plane.cluster.register_client()
        self._install_fence(plane.eng)
        self.promoted: Follower | None = None
        self._t_dead: float | None = None
        self.availability_gap_ms: float | None = None
        # receipt counters (plain adds on the accounting paths, SL006)
        self.promotions = 0
        self.fenced_writes = 0
        self.reads_served = 0
        self.reads_forwarded = 0
        self.last_pump_records = 0
        self._last_pump_t = 0.0
        self._rr = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pump_lock = threading.Lock()
        self.followers = [Follower(self, i) for i in range(n)]
        ref = weakref.ref(self)

        def _collect():
            g = ref()
            return g._collect() if g is not None else {}

        obs.register_collector("repl", _collect)

    @classmethod
    def from_env(cls, plane, **kw):
        """Knob-gated construction: ``None`` when ``SHERMAN_REPL`` is
        unset/0 (the shipped default — no follower, no tailer, the
        primary bit-identical to a build without the subsystem)."""
        n = C.replica_count()
        return None if n == 0 else cls(plane, n, **kw)

    # -- hot accounting (SL006 scope: plain adds only) -----------------------

    def _note_reads(self, served: int, forwarded: int) -> None:
        self.reads_served += served
        self.reads_forwarded += forwarded

    def _note_fenced(self) -> None:
        self.fenced_writes += 1

    # -- fencing -------------------------------------------------------------

    def _install_fence(self, eng) -> None:
        """Wrap the primary engine's journal attachment so EVERY
        segment (current and every future rotation) appends through
        the epoch check — the fence survives checkpoint rotations
        because it wraps the attach point, not one segment."""
        group = self
        orig_attach = eng.attach_journal

        def fenced_attach(journal):
            orig_attach(None if journal is None
                        else _FencedJournal(journal, group))

        eng.attach_journal = fenced_attach
        if eng.journal is not None:
            orig_attach(_FencedJournal(eng.journal, group))

    def _check_fence(self) -> None:
        cl = self.plane.cluster
        if not cl.lease_is_live(self._lease.tag, self._lease.epoch):
            self._note_fenced()
            _OBS_FENCED.inc()
            obs.record_event("repl.fenced", epoch=self.epoch,
                             owner_tag=self._lease.tag)
            raise StalePrimaryError(
                "primary lease expired (group promoted under epoch "
                f"{self.epoch}): this write is fenced — a stale "
                "primary must not fork the journal")

    # -- tailing -------------------------------------------------------------

    def pump(self, final: bool = False) -> int:
        """One synchronous shipping round: every follower polls the
        tail and applies what landed.  Returns records applied (max
        over followers — they consume the same feed)."""
        with self._pump_lock:
            applied = [f.pump(final=final) for f in self.followers]
            self._last_pump_t = time.perf_counter()
        self.last_pump_records = max(applied) if applied else 0
        return self.last_pump_records

    def start(self) -> None:
        """Background shipping at ``poll_ms`` cadence (the knob-driven
        mode; drills that want determinism call :meth:`pump`)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                try:
                    self.pump()
                except Exception as e:  # noqa: BLE001 — the tail must
                    # not die silently mid-drill; surface and stop
                    obs.record_event("repl.tail_error", error=repr(e))
                    break
                self._stop.wait(self.poll_ms / 1e3)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="sherman-repl-tail")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def measure_lag(self) -> float:
        """Replication lag receipt: wall ms from 'records are durable
        in the primary journal' to 'every follower has applied them'
        — one synchronous pump, timed.  Published as ``repl.lag_ms``
        (the SLO plane's gauge)."""
        t0 = time.perf_counter()
        self.pump()
        ms = (time.perf_counter() - t0) * 1e3
        _OBS_LAG_MS.set(ms)
        return ms

    # -- replica reads -------------------------------------------------------

    def read(self, keys, forward=None):
        """Serve a read batch from the replica tier: pump, pick the
        next caught-up follower round-robin, serve its certified
        cache hits, and forward everything else (misses, stale
        entries, or a follower that may not serve) to ``forward``
        (default: the primary engine's read path).  Never a lie: a
        served value is certified against the follower's own pool AND
        the follower was caught up to the durable journal end."""
        keys = np.asarray(keys, np.uint64)
        if forward is None:
            forward = self.plane.eng.search
        # pump at the poll cadence, not per read — a read burst must
        # not turn every request into a full tail drain (the caught-up
        # gate below still bounds staleness to one poll window)
        if time.perf_counter() - self._last_pump_t \
                >= self.poll_ms / 1e3:
            self.pump()
        f = self.followers[self._rr % len(self.followers)]
        self._rr += 1
        res = f.serve_read(keys)
        if res is None:
            vals, found = forward(keys)
            self._note_reads(0, int(keys.size))
            return np.asarray(vals), np.asarray(found)
        vals, hit = res
        out_v = np.array(vals)
        out_f = np.array(hit)
        miss = ~hit
        if miss.any():
            fv, ff = forward(keys[miss])
            out_v[miss] = np.asarray(fv)
            out_f[miss] = np.asarray(ff)
        self._note_reads(int(hit.sum()), int(miss.sum()))
        return out_v, out_f

    # -- failover ------------------------------------------------------------

    def promote(self, t_dead: float | None = None) -> dict:
        """Fail over: expire the primary's lease (every later append
        through its journal is fenced typed), bump the group epoch,
        catch every follower up to the durable journal end (``final``
        poll — the dead primary appends nothing more, so a torn tail
        is final), and pick the highest-watermark follower.  Returns
        the promotion receipt; the caller resumes the front door on
        ``self.promoted.eng`` and adopts :meth:`promoted_window`."""
        t0 = time.perf_counter()
        self._t_dead = t_dead if t_dead is not None else t0
        self.stop()
        self.plane.cluster.expire_client(self._lease.tag)
        old_epoch, self.epoch = self.epoch, self.epoch + 1
        for f in self.followers:
            f.pump(final=True)
        self.promoted = max(self.followers,
                            key=lambda f: (f.link, f.seq))
        self.promotions += 1
        _OBS_PROMOTIONS.inc()
        ms = (time.perf_counter() - t0) * 1e3
        receipt = {
            "winner": self.promoted.idx,
            "epoch": {"old": old_epoch, "new": self.epoch},
            "watermarks": [{"follower": f.idx, "cid": f.cid,
                            "link": f.link, "seq": f.seq}
                           for f in self.followers],
            "window": len(self.promoted.window),
            "promote_ms": round(ms, 1),
        }
        obs.record_event("repl.promote", winner=self.promoted.idx,
                         epoch=self.epoch,
                         seq=self.promoted.seq,
                         promote_ms=receipt["promote_ms"])
        return receipt

    def promoted_window(self) -> dict:
        """The winner's replayed exactly-once window, in
        ``seed_dedup`` shape ``{(tenant, rid): (op, ok[, handles])}``
        — heap-write entries keep their payload provenance."""
        if self.promoted is None:
            raise StateError("no promotion yet: promote() first")
        return dict(self.promoted.window)

    def note_resumed(self) -> float:
        """The availability-gap receipt: call when the promoted front
        door serves its first request — gap = that instant minus
        ``t_dead`` (the kill), published as
        ``repl.availability_gap_ms``."""
        if self._t_dead is None:
            raise StateError("no failover in flight: promote() first")
        ms = (time.perf_counter() - self._t_dead) * 1e3
        self.availability_gap_ms = round(ms, 1)
        _OBS_GAP_MS.set(ms)
        return self.availability_gap_ms

    # -- receipts ------------------------------------------------------------

    def _collect(self) -> dict:
        """``repl.`` pull collector — flat numbers only (the obs
        collector contract)."""
        st: dict = {}
        for f in self.followers:
            for k, v in f.stats.items():
                st[k] = st.get(k, 0) + int(v)
        top = max(self.followers, key=lambda f: (f.link, f.seq))
        return {
            "followers": len(self.followers),
            "epoch": self.epoch,
            "applied_records": st.get("records", 0),
            "applied_rows": st.get("rows", 0),
            "absorbed_acks": st.get("acks", 0),
            "torn_waits": sum(f.tailer.torn_waits
                              for f in self.followers),
            "rebootstraps": sum(f.rebootstraps
                                for f in self.followers),
            "watermark_link": top.link,
            "watermark_seq": top.seq,
            "promotions": self.promotions,
            "fenced_writes": self.fenced_writes,
            "reads_served": self.reads_served,
            "reads_forwarded": self.reads_forwarded,
            "last_pump_records": self.last_pump_records,
        }

    def stats(self) -> dict:
        return self._collect()

    def close(self) -> None:
        self.stop()
