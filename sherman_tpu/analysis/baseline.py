"""Grandfathered-finding baseline with a freshness contract.

A baseline lets a new rule land while pre-existing violations are
worked off — but a baseline that silently rots is worse than none: an
entry pointing at code that moved keeps suppressing whatever NEW
violation drifts onto that line.  So matching here is exact (rule +
path + line + stripped line content), and every entry that fails to
match both the file content and a live finding is an ERROR (lint exit
2), never a skip.  The committed repo target is an EMPTY baseline:
deliberate exceptions belong in inline pragmas WITH reasons, where the
diff that adds them carries the justification.

Format (JSON, one object)::

    {"version": 1,
     "entries": [{"rule": "SL003", "path": "sherman_tpu/x.py",
                  "line": 12, "snippet": "raise ValueError(...)",
                  "reason": "why this is grandfathered"}]}
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from sherman_tpu.analysis.core import Finding
from sherman_tpu.errors import ShermanError

FORMAT_VERSION = 1


class BaselineError(ShermanError, ValueError):
    """The baseline file itself is unusable (bad JSON/shape/version)."""


@dataclass
class Baseline:
    entries: list[dict] = field(default_factory=list)

    def apply(self, findings: list[Finding], root: Path):
        """-> (kept_findings, absorbed_findings, stale_errors).

        An entry is FRESH iff the file still has exactly its snippet at
        its line AND a live finding matches it; otherwise it is stale
        and reported.  Findings not covered by a fresh entry are kept.
        """
        by_key = {}
        for e in self.entries:
            by_key[(e["rule"], e["path"], int(e["line"]),
                    e["snippet"])] = e
        kept, absorbed, stale = [], [], []
        matched: set[tuple] = set()
        for f in findings:
            if f.key() in by_key:
                absorbed.append(f)
                matched.add(f.key())
            else:
                kept.append(f)
        for key, e in by_key.items():
            if key in matched:
                continue
            rule, path, line, snippet = key
            p = root / path
            if not p.is_file():
                stale.append(f"baseline entry {rule} {path}:{line}: "
                             "file no longer exists — remove the entry")
                continue
            lines = p.read_text().splitlines()
            actual = lines[line - 1].strip() if 0 < line <= len(lines) \
                else "<past end of file>"
            if actual != snippet:
                stale.append(
                    f"baseline entry {rule} {path}:{line}: line content "
                    f"changed ({actual!r} != {snippet!r}) — re-anchor or "
                    "remove the entry")
            else:
                stale.append(
                    f"baseline entry {rule} {path}:{line}: no finding is "
                    "produced there any more — the violation was fixed, "
                    "remove the entry")
        return kept, absorbed, stale


def load_baseline(path) -> Baseline:
    path = Path(path)
    if not path.is_file():
        return Baseline(entries=[])
    try:
        data = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        raise BaselineError(f"baseline {path} is not valid JSON: {e}") \
            from None
    if not isinstance(data, dict) or data.get("version") != FORMAT_VERSION:
        raise BaselineError(
            f"baseline {path}: want {{'version': {FORMAT_VERSION}, "
            "'entries': [...]}")
    entries = data.get("entries")
    if not isinstance(entries, list):
        raise BaselineError(f"baseline {path}: 'entries' must be a list")
    for e in entries:
        missing = {"rule", "path", "line", "snippet"} - set(e)
        if missing:
            raise BaselineError(
                f"baseline {path}: entry {e!r} missing {sorted(missing)}")
        if not str(e.get("reason", "")).strip():
            raise BaselineError(
                f"baseline {path}: entry {e['rule']} {e['path']}:"
                f"{e['line']} has no reason — grandfathering without a "
                "recorded why is how conventions rot")
    return Baseline(entries=entries)


def write_baseline(path, findings: list[Finding],
                   reason: str = "grandfathered at baseline creation"
                   ) -> None:
    """Serialize ``findings`` as a fresh baseline (the bootstrap path a
    new rule uses; the committed target is still to fix and empty it)."""
    data = {
        "version": FORMAT_VERSION,
        "entries": [{"rule": f.rule, "path": f.path, "line": f.line,
                     "snippet": f.snippet, "reason": reason}
                    for f in sorted(findings, key=lambda f: f.key())],
    }
    Path(path).write_text(json.dumps(data, indent=1) + "\n")
