"""The shermanlint framework: findings, parsed sources, pragmas, runner.

Everything here is rule-agnostic.  A :class:`Rule` gets a
:class:`SourceFile` (AST with parent links + dotted qualnames + the
pragma table) and a registry object, and returns :class:`Finding`\\ s;
the runner applies pragma suppression and (optionally) a baseline.

Suppression contract: ``# shermanlint: disable=SL003 <reason>`` on the
finding's line, or on a comment-only line directly above it.  The
reason is MANDATORY — a pragma without one does not suppress and is
itself reported (SL000), so every deliberate exception carries its
justification in the diff that introduces it.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field
from pathlib import Path

PRAGMA_CODE = "SL000"
_PRAGMA_RE = re.compile(
    r"#\s*shermanlint:\s*disable=([A-Za-z0-9,\s]+?)(?:\s+(\S.*))?$")
_CODE_RE = re.compile(r"^SL\d{3}$")


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source line.

    ``snippet`` is the stripped source text of that line — the content
    fingerprint the baseline uses, so a baseline entry goes stale the
    moment the line it grandfathers changes.
    """

    rule: str
    path: str       # repo-relative, POSIX separators
    line: int       # 1-indexed
    message: str
    snippet: str

    def key(self) -> tuple:
        return (self.rule, self.path, self.line, self.snippet)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[tuple[Finding, str]] = field(default_factory=list)
    pragma_errors: list[Finding] = field(default_factory=list)
    baseline_errors: list[str] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    files_checked: int = 0

    @property
    def clean(self) -> bool:
        return not (self.findings or self.pragma_errors
                    or self.baseline_errors)


class SourceFile:
    """A parsed module: AST with parent links, qualnames, pragmas.

    ``rel`` is the repo-relative POSIX path every rule and registry
    pattern matches against (``sherman_tpu/parallel/dsm.py``).
    """

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._sherman_parent = node  # type: ignore[attr-defined]
        # line -> (codes, reason); codes empty-string reason == invalid
        self.pragmas: dict[int, tuple[set[str], str]] = {}
        self.pragma_errors: list[Finding] = []
        self._scan_pragmas()

    # -- pragmas -------------------------------------------------------------

    def _scan_pragmas(self) -> None:
        # real COMMENT tokens only — a pragma spelled inside a
        # docstring or regex literal is prose, not a suppression
        import io
        import tokenize
        comments: list[tuple[int, str]] = []
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.string))
        except tokenize.TokenError:
            pass
        for i, comment in comments:
            if "shermanlint:" not in comment:
                continue
            m = _PRAGMA_RE.search(comment)
            if m is None:
                self.pragma_errors.append(self._finding(
                    PRAGMA_CODE, i,
                    "malformed shermanlint pragma (want '# shermanlint: "
                    "disable=SLxxx <reason>')"))
                continue
            codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
            reason = (m.group(2) or "").strip()
            bad = sorted(c for c in codes if not _CODE_RE.match(c))
            if bad:
                self.pragma_errors.append(self._finding(
                    PRAGMA_CODE, i,
                    f"pragma names invalid rule id(s) {bad} (want SLxxx)"))
                continue
            if not reason:
                self.pragma_errors.append(self._finding(
                    PRAGMA_CODE, i,
                    "pragma has no reason — every suppression must say "
                    "why (disable=SLxxx <reason>)"))
                continue
            self.pragmas[i] = (codes, reason)

    def suppression(self, rule: str, line: int) -> str | None:
        """Reason text if ``rule`` is suppressed at ``line``, else None.

        A pragma applies to its own line, or — when it sits alone on a
        comment line — to the first following non-comment line.
        """
        hit = self.pragmas.get(line)
        if hit and rule in hit[0]:
            return hit[1]
        for back in range(line - 1, 0, -1):
            txt = self.lines[back - 1].strip() if back <= len(self.lines) \
                else ""
            if not txt.startswith("#"):
                break
            hit = self.pragmas.get(back)
            if hit and rule in hit[0]:
                return hit[1]
        return None

    # -- helpers for rules ---------------------------------------------------

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def _finding(self, rule: str, line: int, message: str) -> Finding:
        return Finding(rule=rule, path=self.rel, line=line,
                       message=message, snippet=self.snippet(line))

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return self._finding(rule, getattr(node, "lineno", 1), message)

    def qualname(self, node: ast.AST) -> str:
        """Dotted enclosing-scope name: ``Journal.append``,
        ``make_staged_step.step`` (no ``<locals>`` noise — lint
        patterns should read like the code does)."""
        parts: list[str] = []
        cur: ast.AST | None = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = getattr(cur, "_sherman_parent", None)
        return ".".join(reversed(parts))

    def functions(self) -> list[ast.FunctionDef | ast.AsyncFunctionDef]:
        return [n for n in ast.walk(self.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def enclosing_function(self, node: ast.AST):
        cur = getattr(node, "_sherman_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "_sherman_parent", None)
        return None


def match_scope(patterns, rel: str, qual: str) -> bool:
    """True when any ``(path_glob, qualname_glob)`` pair matches."""
    return any(fnmatch.fnmatch(rel, pp) and fnmatch.fnmatch(qual, qp)
               for pp, qp in patterns)


def callee_name(call: ast.Call) -> str:
    """Terminal name of a call target: ``a.b.c(...)`` -> ``c``."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    return f.id if isinstance(f, ast.Name) else ""


def dotted_name(node: ast.AST) -> str:
    """``jax.device_get`` for the matching Attribute/Name chain, ``""``
    when the expression is not a plain dotted name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class Rule:
    """Base class: subclasses set ``code``/``name``/``doc`` and
    implement ``check``.  ``doc`` is the one-line lesson the rule
    encodes — it feeds the README catalog via :func:`rule_catalog`."""

    code = "SL999"
    name = "unnamed"
    doc = ""

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        raise NotImplementedError


def iter_py_files(paths) -> list[Path]:
    """Expand files/directories into a sorted, de-duplicated ``.py``
    list; skips ``__pycache__`` and hidden directories BELOW each
    argument (ancestors of the argument are the caller's business — a
    checkout under ``~/.cache`` must still lint)."""
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(
                f for f in p.rglob("*.py")
                if "__pycache__" not in f.relative_to(p).parts
                and not any(part.startswith(".")
                            for part in f.relative_to(p).parts)))
        elif p.suffix == ".py":
            out.append(p)
    seen: set[Path] = set()
    uniq = []
    for f in out:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(f)
    return uniq


def _rel(path: Path, root: Path | None) -> str:
    p = path.resolve()
    if root is not None:
        try:
            return p.relative_to(root.resolve()).as_posix()
        except ValueError:
            pass
    return path.as_posix()


def run(paths, rules=None, registry=None, baseline=None,
        root: Path | None = None) -> LintResult:
    """Lint ``paths`` -> :class:`LintResult`.

    ``baseline`` (a :class:`~sherman_tpu.analysis.baseline.Baseline`)
    absorbs grandfathered findings; stale entries — file gone, line
    moved, content changed, or the finding no longer produced — land in
    ``baseline_errors`` (the freshness contract).
    """
    from sherman_tpu.analysis.registry import DEFAULT_REGISTRY
    from sherman_tpu.analysis.rules import ALL_RULES

    rules = ALL_RULES if rules is None else rules
    registry = DEFAULT_REGISTRY if registry is None else registry
    if root is None:
        root = Path.cwd()

    result = LintResult()
    # a path that lints NOTHING is an infrastructure error, never a
    # silent green: a typo'd directory in CI must not read as clean
    for p in paths:
        if not Path(p).exists():
            result.baseline_errors.append(
                f"{p}: input path does not exist — nothing was linted")
    sources: list[SourceFile] = []
    for path in iter_py_files(paths):
        rel = _rel(path, root)
        try:
            sf = SourceFile(path, rel, path.read_text())
        except (OSError, SyntaxError) as e:
            result.baseline_errors.append(f"{rel}: unreadable: {e}")
            continue
        sources.append(sf)
        result.files_checked += 1
    if result.files_checked == 0:
        result.baseline_errors.append(
            "no Python files found under the given paths — a lint run "
            "that checks nothing cannot vouch for anything")

    raw: list[Finding] = []
    for sf in sources:
        result.pragma_errors.extend(sf.pragma_errors)
        for rule in rules:
            for f in rule.check(sf, registry):
                reason = sf.suppression(f.rule, f.line)
                if reason is not None:
                    result.suppressed.append((f, reason))
                else:
                    raw.append(f)

    if baseline is not None:
        kept, absorbed, stale = baseline.apply(raw, root)
        result.baselined = absorbed
        result.baseline_errors.extend(stale)
        result.findings.extend(kept)
    else:
        result.findings.extend(raw)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
