"""shermanlint — AST-based enforcement of the repo's protocol invariants.

Sherman's correctness rests on conventions the codebase bled for one PR
at a time: kw-only ``dirty=`` threading for delta checkpoints (PR 5),
typed errors instead of bare raises (PR 4), fsync-before-ack journaling
(PR 5/6), sealed-window zero-retrace serving (PR 8), and hot paths that
must not sync to host or allocate.  Each was enforced by review or
after-the-fact dynamic detection; this package turns them into
machine-checked rules that fail at commit time — before a violation
costs a chip session.

Stdlib-only by constraint AND by design (``ast``, ``dataclasses``,
``pathlib``; this container has no ruff/mypy, and a linter that needs a
dependency resolver to run will eventually not run).

Layout:

- :mod:`~sherman_tpu.analysis.core` — the framework: ``Finding``,
  ``SourceFile`` (parse + pragma extraction + qualnames), the runner,
  inline ``# shermanlint: disable=SLxxx <reason>`` suppression.
- :mod:`~sherman_tpu.analysis.registry` — the repo-specific knowledge
  the rules consult (which functions are hot, which primitives mutate
  the pool, where the append path lives).  Tests swap in their own.
- :mod:`~sherman_tpu.analysis.rules` — the seven rules, SL001-SL007.
- :mod:`~sherman_tpu.analysis.baseline` — grandfathered findings with
  a freshness contract: an entry whose file/line no longer matches is
  an ERROR, never a silent skip.

Run it: ``python tools/shermanlint.py sherman_tpu/ tools/ bench.py``.
"""

from sherman_tpu.analysis.baseline import (Baseline, BaselineError,
                                           load_baseline, write_baseline)
from sherman_tpu.analysis.core import (Finding, LintResult, Rule,
                                       SourceFile, iter_py_files, run)
from sherman_tpu.analysis.registry import DEFAULT_REGISTRY, Registry
from sherman_tpu.analysis.rules import ALL_RULES, rule_catalog

__all__ = [
    "ALL_RULES", "Baseline", "BaselineError", "DEFAULT_REGISTRY",
    "Finding", "LintResult", "Registry", "Rule", "SourceFile",
    "iter_py_files", "load_baseline", "rule_catalog", "run",
    "write_baseline",
]
