"""The repo-specific knowledge shermanlint's rules consult.

Rules are generic mechanisms ("no host sync in a registered hot
function"); THIS module is where the repo names its hot functions,
pool mutators, append paths and obs increment paths.  Patterns are
``fnmatch`` globs over ``(repo-relative path, dotted qualname)`` —
see :func:`sherman_tpu.analysis.core.match_scope`.

Tests build their own :class:`Registry` pointing at fixture files, so
every rule is exercised without depending on the live tree's content.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Registry:
    # -- SL001: traced/per-step hot functions: no host syncs ------------------
    hot_functions: list[tuple[str, str]] = field(default_factory=list)
    #: attribute-chain roots whose reads are static Python config, not
    #: device arrays — ``int(cfg.machine_nr)`` is fine in a hot body
    static_roots: set[str] = field(default_factory=set)

    # -- SL002: dirty-threading contract --------------------------------------
    #: terminal callee/reference names that mutate the pool
    pool_mutators: set[str] = field(default_factory=set)
    #: compositions deliberately outside the durability contract
    dirty_allowlist: list[tuple[str, str]] = field(default_factory=list)

    # -- SL003: typed errors --------------------------------------------------
    #: path globs where bare stdlib raises are banned (library code)
    library_paths: list[str] = field(default_factory=list)
    banned_raises: set[str] = field(default_factory=lambda: {
        "ValueError", "RuntimeError", "AssertionError"})

    # -- SL004: retrace hazards at jit dispatch sites -------------------------
    #: callee-name globs whose RESULT is a compiled program
    jit_factory_patterns: list[str] = field(default_factory=list)

    # -- SL005: fsync-before-ack ----------------------------------------------
    append_paths: list[tuple[str, str]] = field(default_factory=list)
    fsync_names: set[str] = field(default_factory=lambda: {
        "fsync", "_fsync", "fdatasync", "_commit"})
    durable_write_names: set[str] = field(default_factory=lambda: {"write"})

    # -- SL006: no allocation in obs increment paths --------------------------
    obs_hot_functions: list[tuple[str, str]] = field(default_factory=list)

    # -- SL007: documented knobs ----------------------------------------------
    knob_prefix: str = "SHERMAN_"
    readme: str = "README.md"
    #: extra documentation files a knob may appear in instead
    knob_docs: list[str] = field(default_factory=list)
    #: when set, SL007 checks against this text instead of reading the
    #: doc files — the hook fixture tests use
    knob_doc_text: str | None = None


DEFAULT_REGISTRY = Registry(
    hot_functions=[
        # the device-step programs: traced under jit/shard_map — a host
        # sync here either breaks tracing or serializes every step
        ("sherman_tpu/models/batched.py", "descend_spmd"),
        ("sherman_tpu/models/batched.py", "search_routed_spmd"),
        ("sherman_tpu/models/batched.py", "search_spmd"),
        ("sherman_tpu/models/batched.py", "leaf_apply_spmd"),
        ("sherman_tpu/models/batched.py", "leaf_delete_apply_spmd"),
        ("sherman_tpu/models/batched.py", "_resolve_leaves"),
        ("sherman_tpu/models/batched.py", "_route_and_apply"),
        ("sherman_tpu/models/batched.py", "insert_step_spmd"),
        ("sherman_tpu/models/batched.py", "delete_step_spmd"),
        ("sherman_tpu/models/batched.py", "mixed_step_spmd"),
        ("sherman_tpu/parallel/dsm.py", "dsm_step_spmd"),
        ("sherman_tpu/parallel/dsm.py", "read_pages_spmd"),
        ("sherman_tpu/parallel/dsm.py", "_word_apply"),
        ("sherman_tpu/parallel/dsm.py", "_apply"),
        # the staged serving loops' per-step dispatch closures (PR 2/6):
        # one stray .item() here is a per-step device round-trip the
        # 33.8 M ops/s number does not survive
        ("sherman_tpu/workload/device_prep.py", "make_staged_step.step"),
        ("sherman_tpu/workload/device_prep.py", "make_staged_step.prep"),
        ("sherman_tpu/workload/device_prep.py", "make_staged_step.serve"),
        ("sherman_tpu/workload/device_prep.py", "make_staged_step.fused"),
        ("sherman_tpu/workload/device_prep.py", "make_staged_step.verify"),
        ("sherman_tpu/workload/device_prep.py",
         "make_staged_step.prep_core"),
        ("sherman_tpu/workload/device_prep.py",
         "make_staged_step.verify_core"),
        # mixed factory: per-step dispatch closures + traced cores only
        # (phase_profile / record_slo / new_carry are diagnostics that
        # legitimately touch host)
        ("sherman_tpu/workload/device_prep.py",
         "make_staged_mixed_step.step"),
        ("sherman_tpu/workload/device_prep.py",
         "make_staged_mixed_step.prep"),
        ("sherman_tpu/workload/device_prep.py",
         "make_staged_mixed_step.serve*"),
        ("sherman_tpu/workload/device_prep.py",
         "make_staged_mixed_step.verify*"),
        ("sherman_tpu/workload/device_prep.py", "_two_deep_slot.*"),
        # hot-key tier (PR 11): the probe/validate kernels are traced
        # (a host sync breaks tracing), and the staged cache_probe
        # closure rides the sealed per-step dispatch path
        ("sherman_tpu/models/leaf_cache.py", "probe_rows"),
        ("sherman_tpu/models/leaf_cache.py", "invalidation_mask"),
        ("sherman_tpu/models/leaf_cache.py", "slot_hash"),
        ("sherman_tpu/models/leaf_cache.py", "LeafCache._get_probe.kernel"),
        ("sherman_tpu/models/leaf_cache.py", "LeafCache._get_fill.kernel"),
        ("sherman_tpu/workload/device_prep.py",
         "make_staged_step.cache_probe"),
        # serving front door (PR 13): the per-step ingress dispatch
        # closures — the front door's continuous-batching loop runs one
        # of these per device step, so a stray host sync here serializes
        # every serving step on the access-tunnel RTT (completion
        # belongs in the complete() half, which materializes by design)
        ("sherman_tpu/workload/device_prep.py",
         "make_ingress_step.dispatch"),
        # device-resident request plane (PR 17): the on-device prep
        # program family (combine/sort/route in one compiled ladder
        # rung) and the device-mode ingress dispatch closure — the
        # whole point of device prep is that nothing syncs before the
        # fused fan-out launches, so a stray host sync here re-creates
        # the host-prep serialization the knob exists to remove
        ("sherman_tpu/workload/device_prep.py",
         "make_device_prep.prep_core"),
        ("sherman_tpu/workload/device_prep.py", "make_device_prep.*"),
        ("sherman_tpu/workload/device_prep.py",
         "make_ingress_step.dispatch_device"),
        ("sherman_tpu/serve.py", "ShermanServer._dispatch_reads"),
        # client-contract plane (PR 15): the dispatch-path queue pops
        # run per formed step under the admission lock — deadline
        # shedding and the fair-share take are plain pops/adds, and a
        # stray host sync here stalls every client behind the lock
        ("sherman_tpu/serve.py", "ShermanServer._take"),
        ("sherman_tpu/serve.py", "ShermanServer._shed_expired"),
        # value heap (PR 14): the handle-resolve kernels are traced
        # (the gather phase of the fused read fan-out), and the fused
        # program closure composes the descent + gather on device — a
        # host sync in either breaks tracing or serializes every
        # payload read
        ("sherman_tpu/models/value_heap.py", "resolve_rows"),
        ("sherman_tpu/models/value_heap.py",
         "ValueHeap._get_resolve.kernel"),
        ("sherman_tpu/models/value_heap.py",
         "ValueHeap._get_fused.kernel"),
        # replication plane (PR 16): the follower apply loop runs once
        # per poll for EVERY shipped record batch — a stray host sync
        # here turns replication lag into a per-record device
        # round-trip, and the lag gauge is a headline receipt number
        ("sherman_tpu/replica.py", "Follower.pump"),
        # partition plane (PR 18): the tailer poll loop runs per
        # shipping round per follower, now with the chaos-directive
        # and fence checks inline — a host sync here stalls every
        # follower's apply cadence and the quorum-ack wait that pumps
        # through it
        ("sherman_tpu/replica.py", "JournalTailer.poll"),
    ],
    static_roots={"cfg", "config", "self", "C", "D", "CFG", "bits",
                  "layout"},
    # the BOTTOM layer: these either scatter into the pool directly or
    # take dirty positionally as traced-kernel arguments.  Everything
    # composing them (insert/delete/mixed_step_spmd, engine closures)
    # is checked for the kw-only dirty= contract.
    pool_mutators={
        "_route_and_apply", "leaf_apply_spmd", "leaf_delete_apply_spmd",
        "writeback", "writeback_xla",
    },
    dirty_allowlist=[
        # PR 5 contract: device_prep/profiler compositions leave
        # dirty=None — bench-only, outside the durability contract
        ("sherman_tpu/workload/device_prep.py", "*"),
        ("sherman_tpu/chaos.py", "*"),
        ("tools/profile_insert.py", "*"),
        ("tools/profile_gather.py", "*"),
        ("tools/profile_staged2.py", "*"),
        ("tools/profile_prep.py", "*"),
    ],
    library_paths=["sherman_tpu/*"],
    jit_factory_patterns=["_get_*", "*_jit", "wrap_program"],
    append_paths=[
        ("sherman_tpu/utils/journal.py", "Journal.append"),
        # the client-contract ack records ride the same gate: an ack
        # cached in the dedup window must be durable before any future
        # resolves (PR 15)
        ("sherman_tpu/utils/journal.py", "Journal.append_acks"),
        # quorum acks (PR 18): the fence proxy is the SAME fsync
        # domain — it delegates every append to the wrapped segment
        # after the lease check, so a quorum ack released on its
        # return is released on durable bytes (SL005 sees the pure
        # delegation and the wrapped Journal.append's own fsync)
        ("sherman_tpu/replica.py", "_FencedJournal.append"),
        ("sherman_tpu/replica.py", "_FencedJournal.append_acks"),
    ],
    obs_hot_functions=[
        ("sherman_tpu/obs/registry.py", "Counter.inc"),
        ("sherman_tpu/obs/registry.py", "Gauge.set"),
        ("sherman_tpu/obs/registry.py", "Gauge.add"),
        ("sherman_tpu/obs/registry.py", "Histogram.record"),
        ("sherman_tpu/obs/slo.py", "LatencyTracker.record"),
        ("sherman_tpu/obs/slo.py", "WindowedRate.add"),
        ("sherman_tpu/obs/slo.py", "SloTracker.observe"),
        # hot-key tier: the per-probed-batch accounting path (plain
        # integer adds only — the cache.* collector allocates at PULL
        # time, which is off the hot path)
        ("sherman_tpu/models/leaf_cache.py", "LeafCache._note_probe"),
        # online migration (PR 12): the dirty-tracking hooks run inside
        # every checkpoint save (the sink) and every migration batch
        # (the poll) — plain set-folding loops; the migrate.* collector
        # allocates at PULL time like the cache's
        ("sherman_tpu/migrate.py", "Migrator._on_dirty_clear"),
        ("sherman_tpu/migrate.py", "Migrator._poll_dirt"),
        # serving front door (PR 13): the admission/serve accounting
        # runs on every submit and every completed batch inside the
        # open loop — plain integer adds only; the serve.* collector
        # allocates at PULL time like the cache's and migrate's
        ("sherman_tpu/serve.py", "ShermanServer._note_*"),
        # value heap (PR 14): per-batch put/get/free accounting —
        # plain integer adds; the heap.* collector allocates at PULL
        # time like every other collector
        ("sherman_tpu/models/value_heap.py", "ValueHeap._note_*"),
        # client-contract auditor (PR 15): the inline observe cost
        # accounting runs on every completed batch inside the serve
        # wall (the < 2% pin's own numerator must not allocate)
        ("sherman_tpu/audit.py", "Auditor._note_cost"),
        # write combining (PR 17): per-batch combined-kernel accounting
        # runs inside the insert/mixed write wall — plain integer adds;
        # the group/saved counts live in device counter slots and the
        # combine.* collector allocates at PULL time like every other
        ("sherman_tpu/models/batched.py",
         "BatchedEngine._note_combine_step"),
        # replication plane (PR 16): replica-read and fencing
        # accounting — _note_reads runs on every replica-tier read
        # batch and _note_fenced inside the durability gate's fence
        # check; plain integer adds, the repl.* collector allocates at
        # PULL time like every other collector
        ("sherman_tpu/replica.py", "ReplicaGroup._note_reads"),
        ("sherman_tpu/replica.py", "ReplicaGroup._note_fenced"),
        # quorum acks (PR 18): the wait accounting runs once per
        # quorum-gated ack inside the serve write wall (the latency-
        # delta receipt's own numerator) — plain adds only; the
        # server-side twin is covered by the ShermanServer._note_*
        # glob above
        ("sherman_tpu/replica.py", "ReplicaGroup._note_quorum"),
    ],
    knob_docs=["BENCHMARKS.md"],
)
