"""The seven shermanlint rules — each encodes a lesson this repo paid
for in a previous PR.  See the README "Static analysis" catalog for the
history; each rule's ``doc`` is the one-line version.

Rules deliberately check REGISTERED scopes (see registry.py) rather
than guessing hotness or mutation from code shape: a static pass that
cries wolf gets pragma'd into silence, so precision beats recall here —
growing the registry is a one-line diff reviewed like any other.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from pathlib import Path

from sherman_tpu.analysis.core import (Finding, Rule, SourceFile,
                                       callee_name, dotted_name,
                                       match_scope)

# ---------------------------------------------------------------------------
# SL001 — host sync in a hot path
# ---------------------------------------------------------------------------

_SYNC_ATTR_CALLS = {"item"}
_SYNC_DOTTED = {"jax.device_get", "np.asarray", "numpy.asarray",
                "onp.asarray", "np.array", "numpy.array", "onp.array"}
_CONCRETIZERS = {"float", "int", "bool"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "sharding"}


def _is_static_expr(node: ast.AST, static_roots: set[str]) -> bool:
    """True when evaluating ``node`` cannot touch device data: literals,
    config-attribute chains, shapes/dtypes, ``len()``, and arithmetic
    over those."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id.isupper() or node.id in static_roots
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return True
        root = node.value
        while isinstance(root, ast.Attribute):
            root = root.value
        return isinstance(root, ast.Name) and (
            root.id in static_roots or root.id.isupper())
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value, static_roots)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand, static_roots)
    if isinstance(node, ast.BinOp):
        return (_is_static_expr(node.left, static_roots)
                and _is_static_expr(node.right, static_roots))
    if isinstance(node, ast.Call):
        return (callee_name(node) in {"len", "min", "max", "abs", "round"}
                and all(_is_static_expr(a, static_roots)
                        for a in node.args))
    return False


class HostSyncInHotPath(Rule):
    code = "SL001"
    name = "host-sync-in-hot-path"
    doc = ("No `.item()`/`float()`/`np.asarray`/`jax.device_get` inside "
           "registered hot step functions — one stray sync is a per-step "
           "device round-trip (PR 2/6/8: the staged loops' whole design "
           "is that nothing ships per step).")

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        out: list[Finding] = []
        for fn in sf.functions():
            if not match_scope(reg.hot_functions, sf.rel, sf.qualname(fn)):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = dotted_name(node.func)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_ATTR_CALLS):
                    out.append(sf.finding(
                        self.code, node,
                        f"`.{node.func.attr}()` in hot function "
                        f"`{sf.qualname(fn)}` forces a device->host sync"))
                elif dotted in _SYNC_DOTTED or dotted == "device_get":
                    out.append(sf.finding(
                        self.code, node,
                        f"`{dotted}` in hot function `{sf.qualname(fn)}` "
                        "materializes device data on the host"))
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in _CONCRETIZERS
                      and len(node.args) == 1
                      and not _is_static_expr(node.args[0],
                                              reg.static_roots)):
                    out.append(sf.finding(
                        self.code, node,
                        f"`{node.func.id}(...)` on a possibly-traced "
                        f"value in hot function `{sf.qualname(fn)}` "
                        "concretizes (device sync / trace error); keep "
                        "it an array or hoist it to prep"))
        return out


# ---------------------------------------------------------------------------
# SL002 — pool mutation without dirty= threading
# ---------------------------------------------------------------------------

def _own_nodes(fn: ast.AST):
    """Walk ``fn`` excluding nested function bodies (those are checked
    as their own scopes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class UntrackedPoolWrite(Rule):
    code = "SL002"
    name = "untracked-pool-write"
    doc = ("Functions composing pool-mutating primitives must accept and "
           "thread `dirty=` (kw-only at the library surface) or sit on "
           "the explicit allowlist — PR 5's delta checkpoints are only "
           "sound if every tracked write path marks its pages.")

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        out: list[Finding] = []
        for fn in sf.functions():
            if fn.name in reg.pool_mutators:
                continue
            qual = sf.qualname(fn)
            if match_scope(reg.dirty_allowlist, sf.rel, qual):
                continue
            used = sorted({
                name for node in _own_nodes(fn)
                for name in (
                    [node.id] if isinstance(node, ast.Name)
                    else [node.attr] if isinstance(node, ast.Attribute)
                    else [])
                if name in reg.pool_mutators})
            if not used:
                continue
            a = fn.args
            kwonly = {x.arg for x in a.kwonlyargs}
            positional = {x.arg for x in a.args + a.posonlyargs}
            parent = getattr(fn, "_sherman_parent", None)
            nested = isinstance(parent, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) or \
                isinstance(getattr(parent, "_sherman_parent", None),
                           (ast.FunctionDef, ast.AsyncFunctionDef))
            if "dirty" in kwonly or (nested and "dirty" in positional):
                continue
            if "dirty" in positional:
                out.append(sf.finding(
                    self.code, fn,
                    f"`{qual}` threads `dirty` positionally; the library "
                    "contract is KEYWORD-ONLY (`*, dirty=None`) so legacy "
                    "callers stay valid (PR 5)"))
            else:
                out.append(sf.finding(
                    self.code, fn,
                    f"`{qual}` composes pool mutator(s) {used} without a "
                    "kw-only `dirty=` parameter — its writes are "
                    "invisible to delta checkpoints; thread `dirty=` or "
                    "allowlist it with a reason"))
        return out


# ---------------------------------------------------------------------------
# SL003 — bare stdlib raises in library code
# ---------------------------------------------------------------------------

class BareStdlibRaise(Rule):
    code = "SL003"
    name = "bare-stdlib-raise"
    doc = ("Library code raises the typed classes in "
           "`sherman_tpu/errors.py`, never bare ValueError/RuntimeError/"
           "AssertionError — callers branch on types, not message "
           "strings (PR 4's sweep, finished in PR 9).")

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        if not any(fnmatch.fnmatch(sf.rel, p) for p in reg.library_paths):
            return []
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = ""
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in reg.banned_raises:
                out.append(sf.finding(
                    self.code, node,
                    f"bare `raise {name}` in library code — use a typed "
                    "class from sherman_tpu/errors.py (subclassing "
                    f"{name} keeps existing callers working)"))
        return out


# ---------------------------------------------------------------------------
# SL004 — retrace hazard at a jit dispatch site
# ---------------------------------------------------------------------------

def _matches_factory(name: str, patterns) -> bool:
    return bool(name) and any(fnmatch.fnmatch(name, p) for p in patterns)


def _scalar_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _scalar_literal(node.operand)
    if isinstance(node, ast.Call) and callee_name(node) in ("int", "float"):
        return True
    return False


class RetraceHazard(Rule):
    code = "SL004"
    name = "retrace-hazard"
    doc = ("No Python scalars positionally at jit dispatch sites — a "
           "weak_type/value drift recompiles per call; wrap in "
           "`np.int32(...)`/arrays or make it a static factory arg "
           "(the static twin of PR 8's sealed-ledger retrace detector).")

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        out: list[Finding] = []
        for fn in sf.functions():
            jit_names: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and _matches_factory(callee_name(node.value),
                                             reg.jit_factory_patterns):
                    jit_names.add(node.targets[0].id)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                # dispatch through a bound program (fn = self._get_x(...);
                # fn(...)) or immediately (self._get_x(...)(...))
                direct = isinstance(node.func, ast.Name) \
                    and node.func.id in jit_names
                immediate = isinstance(node.func, ast.Call) \
                    and _matches_factory(callee_name(node.func),
                                         reg.jit_factory_patterns)
                if not (direct or immediate):
                    continue
                for i, arg in enumerate(node.args):
                    if _scalar_literal(arg):
                        out.append(sf.finding(
                            self.code, arg,
                            f"positional arg {i} of a jit dispatch is a "
                            "Python scalar — every distinct value/weak "
                            "type is a fresh compile; pass "
                            "`np.int32(...)`/an array, or make it a "
                            "static arg of the program factory"))
        return out


# ---------------------------------------------------------------------------
# SL005 — ack released before the covering fsync
# ---------------------------------------------------------------------------

class AckBeforeFsync(Rule):
    code = "SL005"
    name = "ack-before-fsync"
    doc = ("On registered journal append paths, every return after the "
           "record write must be preceded by an fsync-domain call "
           "(`_fsync`/`_commit`) — an ack that outruns its fsync is "
           "silent RPO > 0 (PR 5/6's whole durability story).")

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        out: list[Finding] = []
        for fn in sf.functions():
            if not match_scope(reg.append_paths, sf.rel, sf.qualname(fn)):
                continue
            events: list[tuple[tuple[int, int], str, ast.AST]] = []
            for node in ast.walk(fn):
                pos = (getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0))
                if isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr in reg.durable_write_names:
                        events.append((pos, "write", node))
                    elif callee_name(node) in reg.fsync_names:
                        events.append((pos, "sync", node))
                elif isinstance(node, ast.Return):
                    events.append((pos, "return", node))
            events.sort(key=lambda e: e[0])
            first_write = next((pos for pos, kind, _ in events
                                if kind == "write"), None)
            if first_write is None:
                continue
            for pos, kind, node in events:
                if kind != "return" or pos <= first_write:
                    continue
                covered = any(k == "sync" and first_write < p < pos
                              for p, k, _ in events)
                if not covered:
                    out.append(sf.finding(
                        self.code, node,
                        f"`{sf.qualname(fn)}` returns after writing a "
                        "record with no fsync-domain call "
                        f"({sorted(reg.fsync_names)}) between write and "
                        "return — the ack can outrun durability"))
        return out


# ---------------------------------------------------------------------------
# SL006 — allocation/formatting in an obs increment path
# ---------------------------------------------------------------------------

_ALLOC_CALLS = {"str", "repr", "dict", "list", "set", "sorted", "format"}


class ObsHotAllocation(Rule):
    code = "SL006"
    name = "obs-hot-allocation"
    doc = ("Registered obs increment paths (`Counter.inc`, "
           "`Histogram.record`, the SLO observers) build no "
           "dicts/lists/f-strings — they run per step inside timed "
           "windows, and PR 7 pinned their cost < 2% of the staged "
           "wall.")

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        out: list[Finding] = []
        for fn in sf.functions():
            qual = sf.qualname(fn)
            if not match_scope(reg.obs_hot_functions, sf.rel, qual):
                continue
            for node in _own_nodes(fn):
                bad = None
                if isinstance(node, ast.JoinedStr):
                    bad = "f-string construction"
                elif isinstance(node, ast.Dict) and node.keys:
                    bad = "dict construction"
                elif isinstance(node, (ast.DictComp, ast.ListComp,
                                       ast.SetComp, ast.GeneratorExp)):
                    bad = "comprehension"
                elif isinstance(node, (ast.List, ast.Set)) and node.elts:
                    bad = "list/set construction"
                elif isinstance(node, ast.Call) and (
                        callee_name(node) in _ALLOC_CALLS
                        or (isinstance(node.func, ast.Attribute)
                            and node.func.attr == "format")):
                    bad = f"`{callee_name(node)}(...)` allocation"
                if bad:
                    out.append(sf.finding(
                        self.code, node,
                        f"{bad} in obs hot path `{qual}` — this runs "
                        "per step inside timed windows; precompute at "
                        "registration or move to the snapshot side"))
        return out


# ---------------------------------------------------------------------------
# SL007 — undocumented SHERMAN_* knob
# ---------------------------------------------------------------------------

def module_str_constants(sf: SourceFile) -> dict[str, str]:
    consts: dict[str, str] = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    return consts


def env_reads(sf: SourceFile, prefix: str) -> list[dict]:
    """Every ``os.environ.get / os.getenv / os.environ[...]`` read of a
    ``prefix``-named variable in ``sf`` — plus bare string literals
    matching the prefix (helper-indirected reads like
    ``_env("SHERMAN_PEAK_GBPS", 1e9)``), marked ``via="literal"``.
    The knob-inventory tool consumes the full list; rule SL007 gates on
    the resolved reads only.
    """
    consts = module_str_constants(sf)
    reads: list[dict] = []
    seen_lines: set[tuple[str, int]] = set()

    def _resolve(node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        return None

    def _add(name: str | None, node: ast.AST, via: str, default: str):
        if not name or not name.startswith(prefix):
            return
        key = (name, getattr(node, "lineno", 0))
        if key in seen_lines:
            return
        seen_lines.add(key)
        reads.append({"name": name, "path": sf.rel,
                      "line": getattr(node, "lineno", 0),
                      "via": via, "default": default})

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            dotted = dotted_name(node.func)
            if dotted.endswith("environ.get") or dotted.endswith(".getenv") \
                    or dotted == "getenv":
                if node.args:
                    default = ast.unparse(node.args[1]) \
                        if len(node.args) > 1 else "(unset -> None)"
                    _add(_resolve(node.args[0]), node, "env-read", default)
        elif isinstance(node, ast.Subscript):
            if dotted_name(node.value).endswith("environ"):
                _add(_resolve(node.slice), node, "env-read", "(required)")
    resolved_names = {r["name"] for r in reads}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith(prefix) \
                and node.value[len(prefix):].replace("_", "").isalnum() \
                and node.value not in resolved_names:
            _add(node.value, node, "literal", "")
    return reads


class UndocumentedKnob(Rule):
    code = "SL007"
    name = "undocumented-knob"
    doc = ("Every `SHERMAN_*` env read must appear in the README knob "
           "docs (the generated inventory table keeps them from "
           "drifting) — round 5's sampler-mode ambiguity is what an "
           "undocumented knob costs.")

    def __init__(self):
        self._doc_cache: dict[tuple, str] = {}

    def _doc_text(self, reg) -> str:
        if reg.knob_doc_text is not None:
            return reg.knob_doc_text
        key = (reg.readme, tuple(reg.knob_docs))
        if key not in self._doc_cache:
            text = []
            for p in [reg.readme, *reg.knob_docs]:
                p = Path(p)
                if p.is_file():
                    text.append(p.read_text())
            self._doc_cache[key] = "\n".join(text)
        return self._doc_cache[key]

    def check(self, sf: SourceFile, reg) -> list[Finding]:
        docs = self._doc_text(reg)
        out: list[Finding] = []
        for read in env_reads(sf, reg.knob_prefix):
            if read["via"] != "env-read":
                continue  # literals gate nothing; the inventory lists them
            # word-boundary match: SHERMAN_BENCH must not pass because
            # SHERMAN_BENCH_KEYS is documented (prefix collisions are
            # guaranteed in this namespace)
            if not re.search(rf"\b{re.escape(read['name'])}\b", docs):
                out.append(Finding(
                    rule=self.code, path=sf.rel, line=read["line"],
                    message=(f"env knob `{read['name']}` is read here but "
                             f"appears nowhere in {reg.readme} — run "
                             "`python tools/knobs.py --write` and describe "
                             "it"),
                    snippet=sf.snippet(read["line"])))
        return out


ALL_RULES: list[Rule] = [
    HostSyncInHotPath(), UntrackedPoolWrite(), BareStdlibRaise(),
    RetraceHazard(), AckBeforeFsync(), ObsHotAllocation(),
    UndocumentedKnob(),
]


def rule_catalog() -> list[tuple[str, str, str]]:
    """[(code, name, one-line lesson)] — feeds the README catalog."""
    return [(r.code, r.name, r.doc) for r in ALL_RULES]
