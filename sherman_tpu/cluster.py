"""Cluster — the top-level runtime handle (``DSM::getInstance`` analogue).

Bundles the sharded-memory transport (:class:`~sherman_tpu.parallel.dsm.DSM`),
the bootstrap Keeper, and one Directory per node, and hands out per-client
contexts the way ``DSM::registerThread`` does (``DSM.cpp:68-92``).

Construction order mirrors the reference init path (SURVEY.md §3.1):
pool allocation -> fabric (the mesh itself) -> keeper enter -> directories
-> cluster barrier.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

from sherman_tpu.config import DSMConfig
from sherman_tpu.parallel.alloc import Directory, LocalAllocator
from sherman_tpu.parallel.bootstrap import Keeper
from sherman_tpu.parallel.dsm import DSM, ReplicatedDSM


@dataclass
class ClientContext:
    """Per-client state (the registerThread product): a client id and a
    private page allocator with per-node chunk leases."""

    client_id: int
    alloc: LocalAllocator

    @property
    def tag(self) -> int:
        """Lock-holder tag; must be nonzero (thread_tag, DSM.cpp:76)."""
        return self.client_id + 1


class Cluster:
    def __init__(self, cfg: DSMConfig, mesh: jax.sharding.Mesh | None = None,
                 keeper: Keeper | None = None):
        self.cfg = cfg
        self.dsm = DSM(cfg, mesh)
        self.keeper = keeper if keeper is not None else Keeper(cfg.machine_nr)
        # A process-spanning mesh REQUIRES the multihost keeper: with the
        # in-process keeper every host would take the single-process
        # branch and serve ALL nodes' directories, so two hosts hand out
        # the same chunks (silent corruption).  (The converse — a
        # DistributedKeeper on a 1-process deployment — is fine: it is
        # just a 1-host cluster.)
        assert not (self.dsm.multihost and not self.keeper.is_multihost), (
            "mesh spans processes but the keeper is single-process: pass "
            "bootstrap.init_multihost()'s keeper to Cluster on every host")
        if self.keeper.is_multihost:
            # Replicated-driver SPMD (see dsm.ReplicatedDSM): every host
            # process enters the cluster once and then mirrors ALL nodes'
            # directories.  Identical replicated control flow keeps the
            # mirrors in lock-step, which is what lets any client lease
            # chunks on ANY node — DSM::alloc's round-robin over every
            # directory (DSM.h:200-221) — without a cross-host RPC.
            # Divergent per-process request streams would desync the
            # mirrors (and the collective step sequences); the batched
            # engine guards that with input-digest checks.
            self.keeper.server_enter()
            self.node_ids = list(range(cfg.machine_nr))
        else:
            # single-process SPMD: this process plays every symmetric
            # CN+MN node
            self.node_ids = [self.keeper.server_enter()
                             for _ in range(cfg.machine_nr)]
        self.directories = [Directory(n, cfg) for n in self.node_ids]
        # host_dsm is the handle Tree/engine host paths use: raw DSM in
        # single-process mode; the leader-posted replicated wrapper when
        # the mesh spans processes (each host-API op must execute once
        # cluster-wide even though every process requests it)
        self.host_dsm = (ReplicatedDSM(self.dsm) if self.dsm.multihost
                         else self.dsm)
        # Hierarchical lock, local tier (Sherman technique #1,
        # Tree.cpp:1124-1173): one process-wide native ticket-lock table
        # indexed like the global lock space; Tree clients of this
        # process queue here first and hand the GLOBAL lock down the
        # ticket train (bounded by kMaxHandOverTime=8), paying one
        # remote CAS + one remote unlock per train instead of per op.
        # Disabled on process-spanning meshes: hand-over decisions are
        # per-process thread-timing-dependent, and ReplicatedDSM requires
        # every process to issue the IDENTICAL collective step sequence.
        from sherman_tpu import native
        self.local_locks = (
            native.LocalLockTable(cfg.machine_nr * cfg.locks_per_node)
            if not self.dsm.multihost and native.available() else None)
        self._next_client = 0
        self.keeper.barrier("DSM-init")

    def register_client(self, replicated: bool | None = None
                        ) -> ClientContext:
        """Per-client context (``DSM::registerThread``).

        Multi-host: allocation state is MIRRORED on every process
        (replicated-driver SPMD), so a registered client may only
        allocate from replicated control flow — identical calls on every
        process (the Tree/BatchedEngine path, which digest-checks its
        inputs).  Divergent per-process allocation would advance the
        mirrors differently and hand out colliding pages.  To make that
        contract structural rather than documentation, registering a
        client on a multi-host cluster requires ``replicated=True`` as
        an explicit acknowledgment; raw per-process drivers
        (``cluster.dsm``) get a loud error here instead of silent
        corruption later.
        """
        if self.dsm.multihost and replicated is not True:
            raise RuntimeError(
                "multi-host clients allocate from MIRRORED directories: "
                "pass register_client(replicated=True) to acknowledge "
                "that this client runs identical (replicated) control "
                "flow on every process; raw per-process drivers must "
                "not allocate")
        cid = self._next_client
        self._next_client += 1
        return ClientContext(client_id=cid,
                             alloc=LocalAllocator(self.directories))

    # NEW_ROOT broadcast (Tree.cpp:116-124): update the local directories'
    # hints.  The hint is advisory acceleration only — the authoritative
    # root is the meta-page word every client reads (Tree._refresh_root),
    # so other hosts' hints converge lazily rather than via cross-host RPC.
    def broadcast_new_root(self, addr: int, level: int) -> None:
        for d in self.directories:
            d.new_root(addr, level)
