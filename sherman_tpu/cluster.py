"""Cluster — the top-level runtime handle (``DSM::getInstance`` analogue).

Bundles the sharded-memory transport (:class:`~sherman_tpu.parallel.dsm.DSM`),
the bootstrap Keeper, and one Directory per node, and hands out per-client
contexts the way ``DSM::registerThread`` does (``DSM.cpp:68-92``).

Construction order mirrors the reference init path (SURVEY.md §3.1):
pool allocation -> fabric (the mesh itself) -> keeper enter -> directories
-> cluster barrier.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

from sherman_tpu.config import DSMConfig
from sherman_tpu.errors import MultiprocessUnsupportedError
from sherman_tpu.parallel.alloc import Directory, LocalAllocator
from sherman_tpu.parallel.bootstrap import Keeper
from sherman_tpu.parallel.dsm import DSM, ReplicatedDSM


@dataclass
class ClientContext:
    """Per-client state (the registerThread product): a client id and a
    private page allocator with per-node chunk leases."""

    client_id: int
    alloc: LocalAllocator
    # lease epoch under which this client's lock acquisitions are valid
    # (bumped by Cluster.expire_client when the control plane declares
    # the client dead; a lock word holding the old epoch is revocable)
    epoch: int = 1

    @property
    def tag(self) -> int:
        """Lock-holder tag; must be nonzero (thread_tag, DSM.cpp:76)."""
        return self.client_id + 1

    @property
    def lease(self) -> int:
        """The lock word this client writes when acquiring a global
        lock: {epoch:15, owner:16} (see ops.bits lease helpers)."""
        from sherman_tpu.ops import bits
        return bits.lease_word(self.tag, self.epoch)


class Cluster:
    def __init__(self, cfg: DSMConfig, mesh: jax.sharding.Mesh | None = None,
                 keeper: Keeper | None = None):
        self.cfg = cfg
        self.dsm = DSM(cfg, mesh)
        self.keeper = keeper if keeper is not None else Keeper(cfg.machine_nr)
        # A process-spanning mesh REQUIRES the multihost keeper: with the
        # in-process keeper every host would take the single-process
        # branch and serve ALL nodes' directories, so two hosts hand out
        # the same chunks (silent corruption).  (The converse — a
        # DistributedKeeper on a 1-process deployment — is fine: it is
        # just a 1-host cluster.)
        assert not (self.dsm.multihost and not self.keeper.is_multihost), (
            "mesh spans processes but the keeper is single-process: pass "
            "bootstrap.init_multihost()'s keeper to Cluster on every host")
        if self.keeper.is_multihost:
            # Replicated-driver SPMD (see dsm.ReplicatedDSM): every host
            # process enters the cluster once and then mirrors ALL nodes'
            # directories.  Identical replicated control flow keeps the
            # mirrors in lock-step, which is what lets any client lease
            # chunks on ANY node — DSM::alloc's round-robin over every
            # directory (DSM.h:200-221) — without a cross-host RPC.
            # Divergent per-process request streams would desync the
            # mirrors (and the collective step sequences); the batched
            # engine guards that with input-digest checks.
            self.keeper.server_enter()
            self.node_ids = list(range(cfg.machine_nr))
        else:
            # single-process SPMD: this process plays every symmetric
            # CN+MN node
            self.node_ids = [self.keeper.server_enter()
                             for _ in range(cfg.machine_nr)]
        self.directories = [Directory(n, cfg) for n in self.node_ids]
        # host_dsm is the handle Tree/engine host paths use: raw DSM in
        # single-process mode; the leader-posted replicated wrapper when
        # the mesh spans processes (each host-API op must execute once
        # cluster-wide even though every process requests it)
        self.host_dsm = (ReplicatedDSM(self.dsm) if self.dsm.multihost
                         else self.dsm)
        # Hierarchical lock, local tier (Sherman technique #1,
        # Tree.cpp:1124-1173): one process-wide native ticket-lock table
        # indexed like the global lock space; Tree clients of this
        # process queue here first and hand the GLOBAL lock down the
        # ticket train (bounded by kMaxHandOverTime=8), paying one
        # remote CAS + one remote unlock per train instead of per op.
        # Disabled on process-spanning meshes: hand-over decisions are
        # per-process thread-timing-dependent, and ReplicatedDSM requires
        # every process to issue the IDENTICAL collective step sequence.
        from sherman_tpu import native
        self.local_locks = (
            native.LocalLockTable(cfg.machine_nr * cfg.locks_per_node)
            if not self.dsm.multihost and native.available() else None)
        self._next_client = 0
        # Lock-lease epoch table: tag -> current lease epoch of every
        # registered client.  The data-plane liveness oracle for lock
        # revocation (Tree._try_revoke_lease): a lock word whose
        # (owner, epoch) is absent or stale here belongs to a dead
        # client and may be revoked.  Mirrored across processes by the
        # replicated-registration contract (identical register_client
        # streams), exactly like the directories above.
        self.lease_epochs: dict[int, int] = {}
        self.keeper.barrier("DSM-init")

    def register_client(self, replicated: bool | None = None
                        ) -> ClientContext:
        """Per-client context (``DSM::registerThread``).

        Multi-host: allocation state is MIRRORED on every process
        (replicated-driver SPMD), so a registered client may only
        allocate from replicated control flow — identical calls on every
        process (the Tree/BatchedEngine path, which digest-checks its
        inputs).  Divergent per-process allocation would advance the
        mirrors differently and hand out colliding pages.  To make that
        contract structural rather than documentation, registering a
        client on a multi-host cluster requires ``replicated=True`` as
        an explicit acknowledgment; raw per-process drivers
        (``cluster.dsm``) get a loud error here instead of silent
        corruption later.
        """
        if self.dsm.multihost and replicated is not True:
            raise MultiprocessUnsupportedError(
                "multi-host clients allocate from MIRRORED directories: "
                "pass register_client(replicated=True) to acknowledge "
                "that this client runs identical (replicated) control "
                "flow on every process; raw per-process drivers must "
                "not allocate")
        cid = self._next_client
        self._next_client += 1
        ctx = ClientContext(client_id=cid,
                            alloc=LocalAllocator(self.directories))
        self.lease_epochs[ctx.tag] = ctx.epoch
        return ctx

    # -- lock-lease liveness (data-plane failure story) ----------------------
    # The control plane (utils/failure.py) detects peer DEATH and stalls;
    # these methods are the data plane's matching oracle: whether a lock
    # word's holder is still entitled to it.  The spin paths consult ONLY
    # the host-local epoch table (a dict lookup — no collective, no extra
    # DSM op); ``sweep_dead_processes`` is the periodic maintenance pass
    # that folds coordination-service liveness into the table.

    def lease_is_live(self, owner_tag: int, epoch: int) -> bool:
        """True iff a lock word's (owner, epoch) names a live lease:
        the tag is registered here and the epoch matches its current
        lease generation.  An unregistered tag (a client of a previous
        incarnation, or junk from corruption) is dead; a registered tag
        at a stale epoch was expired by the control plane."""
        return self.lease_epochs.get(int(owner_tag)) == int(epoch)

    def expire_client(self, owner_tag: int) -> None:
        """Declare a client's current lease dead: bump its epoch so any
        lock word it still holds fails ``lease_is_live`` and becomes
        revocable.  Called by control-plane death handling (and tests);
        on multi-host meshes every process must call identically (the
        table is mirrored, like the directories)."""
        t = int(owner_tag)
        self.lease_epochs[t] = self.lease_epochs.get(t, 0) + 1

    def sweep_dead_processes(self, tags_by_process: dict[int, list[int]]
                             ) -> list[int]:
        """COLLECTIVE maintenance pass: consult the coordination
        service's liveness roll call (``failure.live_processes`` — every
        live process must call this together) and expire every client
        tag owned by a process that is no longer live.  ``tags_by_
        process`` maps process index -> the tags that process's
        non-replicated drivers registered (replicated clients exist on
        every process and die only with the whole cluster).  Returns the
        expired tags.  Single-process clusters trivially expire nothing.
        """
        from sherman_tpu.utils import failure
        live = set(failure.live_processes(
            self.keeper.machine_nr if self.keeper.is_multihost else 1))
        expired = []
        for proc, tags in tags_by_process.items():
            if int(proc) in live:
                continue
            for t in tags:
                self.expire_client(t)
                expired.append(int(t))
        return expired

    # NEW_ROOT broadcast (Tree.cpp:116-124): update the local directories'
    # hints.  The hint is advisory acceleration only — the authoritative
    # root is the meta-page word every client reads (Tree._refresh_root),
    # so other hosts' hints converge lazily rather than via cross-host RPC.
    def broadcast_new_root(self, addr: int, level: int) -> None:
        for d in self.directories:
            d.new_root(addr, level)
