"""Recovery plane — checkpoint chains + op journal + targeted repair.

PR 3 made the failure story detection-rich (chaos injection, lock-lease
recovery, online scrubbing, degraded mode) but recovery-poor: the only
documented exit was a FULL cluster restore — minutes of unavailability
and every op since the last checkpoint lost, for a single flipped word.
This module is the recovery half, coordinating three primitives so that
recovery time scales with the *damage*, not the pool:

- **incremental checkpoints** (``utils/checkpoint.checkpoint_delta``):
  cheap frequent deltas of only the pages written since the previous
  chain link (the DSM's dirty tracking), chained by the (nonce, seq,
  crc) epoch machinery with per-array CRCs;
- the **write-ahead op journal** (``utils/journal.py``): one CRC-framed
  batch record per acknowledged engine write op, fsync'd before the
  ack, so ``restore chain + replay journal`` loses zero acknowledged
  ops (RPO 0);
- **targeted repair**: degraded mode's real exit — restore only the
  quarantined/violating pages from the chain, re-certify with a scrub
  pass, exit degraded, and catch the repaired pages up by replaying the
  journal.  Structure-changing damage that a local repair cannot mend
  fails TYPED (:class:`TargetedRepairFailed`) and the caller falls back
  to the full-restore path — never a silently wrong pool.

On-disk layout under one recovery directory (single-process meshes —
the chaos/drill tier; multihost deployments use the collective full
checkpoint path)::

    base.npz                     full checkpoint (chain link 0)
    delta-<cid>-000001.npz ...   delta links, in order
    journal-<cid>-000001.wal ... op journal segments (segment k holds
                                 the ops acknowledged after chain link k)

``<cid>`` is the chain id (the base epoch's random nonce), so artifacts
of a superseded chain can never be mistaken for the live one: after a
crash + recover, the plane re-bases (new cid) and stale files are both
ignored by discovery and swept.

**Per-host chain ownership (the multihost service plane, PR 19).**
With ``hosts > 1`` each host owns ONE journal stream and its own chain
namespace in the shared directory — every artifact name carries the
owner's host tag::

    base-h<host>.npz
    delta-h<host>-<cid>-000001.npz ...
    journal-h<host>-<cid>-000001.wal ...

so N hosts append/fsync/rotate/sweep fully independently (N fsync
streams instead of one — the ack-bandwidth multiplier the drill
measures), and recovery becomes :meth:`RecoveryPlane.recover_union`:
the union of per-host chains, each restored + replayed independently in
its own (host, cid, seq) order.  Cross-host replay order is immaterial
by construction — the front door routes every key to exactly one owner
host, so no two hosts' journals ever carry records for the same key.
Each host's epoch/nonce machinery is likewise independent: a torn tail
or re-based chain on one host never blocks another host's replay, and a
host's stale-cid sweep touches only its OWN ``-h<host>-`` artifacts.
``hosts == 1`` (the shipped default) keeps the legacy un-tagged names
byte for byte — a single-host deployment's artifacts are bit-identical
to a build without the plane.

The crash contract, window by window:

- crash before a journal append completes: the op was never acked; the
  torn tail is truncated at replay (``journal.truncated_tails``);
- crash after append, before the engine returns: the op replays — "ack
  may lag apply" (at-least-once), never the reverse;
- crash mid-checkpoint: ``_savez_atomic`` leaves the previous artifact
  intact, the tmp orphan is swept at the next save;
- crash between a checkpoint and its journal rotation: the old segment
  overlaps the new link; in-order replay is convergent (upsert/delete
  idempotency), so replaying it is correct, just redundant.

``tools/recovery_drill.py`` (``bench.py --recovery-drill``) rehearses
the whole sequence end to end and publishes measured ``recovery.rpo_ops``
/ ``recovery.rto_ms``.
"""

from __future__ import annotations

import glob
import os
import time

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import (MultiprocessUnsupportedError, ShermanError,
                                StateError)
from sherman_tpu.utils import checkpoint as CK
from sherman_tpu.utils import journal as J

_OBS_RPO = obs.gauge("recovery.rpo_ops")
_OBS_RTO = obs.gauge("recovery.rto_ms")
_OBS_RECOVERS = obs.counter("recovery.recovers")
_OBS_REPAIRS = obs.counter("recovery.targeted_repairs")
_OBS_REPAIR_FAILS = obs.counter("recovery.targeted_repair_failures")
_OBS_PAGES_REPAIRED = obs.counter("recovery.pages_repaired")
_OBS_STALE_REPAIRS = obs.counter("recovery.stale_page_repairs")
_OBS_RESURRECTED = obs.counter("recovery.resurrected_keys")


class TargetedRepairFailed(ShermanError, RuntimeError):
    """Chain-based page repair could not re-certify the pool (structure
    changed since the chain tip, or damage beyond the repaired set):
    the engine STAYS degraded and the caller falls back to a full
    restore (``RecoveryPlane.recover``)."""


def _cid_of(epoch) -> str:
    return f"{int(np.asarray(epoch).ravel()[0]) & 0xFFFFFFFF:08x}"


def _base_name(host_id: int | None) -> str:
    """Base-artifact filename of one host's chain.  ``None`` = the
    legacy single-host namespace (un-tagged names, bit-identical to
    pre-multihost builds)."""
    return "base.npz" if host_id is None else f"base-h{int(host_id)}.npz"


def _host_tag(host_id: int | None) -> str:
    """The artifact-name infix of one host's chain namespace: deltas
    and journals are ``delta<tag>-<cid>-k.npz`` / ``journal<tag>-<cid>-
    k.wal`` with ``tag = "-h<id>"`` (empty for the legacy namespace)."""
    return "" if host_id is None else f"-h{int(host_id)}"


class RecoveryPlane:
    """Durability coordinator over one (cluster, tree, engine) triple.

    Lifecycle: construct -> :meth:`checkpoint_base` (starts the chain
    and the journal; from here every engine write op is journaled) ->
    periodic :meth:`checkpoint_delta` -> on crash,
    :meth:`RecoveryPlane.recover`; on data-plane corruption caught by
    the scrubber, :meth:`targeted_repair`.
    """

    def __init__(self, cluster, tree, eng, directory: str,
                 journal_sync: bool = True,
                 group_commit_ms: float = 0.0,
                 ack_carry: int = 65536,
                 host_id: int = 0, hosts: int = 1):
        if cluster.dsm.multihost and int(hosts) <= 1:
            # a process-spanning mesh with NO host plane configured has
            # no per-host chain namespace to own — the pre-PR-19 wall.
            # The multihost service plane (sherman_tpu/multihost.py)
            # constructs one plane per host with hosts > 1 instead.
            raise MultiprocessUnsupportedError(
                "RecoveryPlane on a multihost mesh needs per-host chain "
                "ownership: pass hosts=<N>, host_id=<this host> (the "
                "multihost service plane does; see sherman_tpu/"
                "multihost.py)")
        if not (0 <= int(host_id) < int(hosts)):
            raise StateError(
                f"host_id={host_id} outside [0, hosts={hosts})")
        #: this plane's position in the host plane: ``hosts == 1`` is
        #: the shipped default (legacy un-tagged artifact names, bit-
        #: identical to pre-multihost builds); ``hosts > 1`` scopes
        #: every artifact + sweep to the ``-h<host_id>-`` namespace
        self.hosts = int(hosts)
        self.host_id = int(host_id)
        self._htag: int | None = self.host_id if self.hosts > 1 else None
        #: exactly-once ack entries carried across journal rotations
        #: (most-recent wins; bounds the re-forwarded window)
        self.ack_carry = int(ack_carry)
        self.cluster = cluster
        self.tree = tree
        self.eng = eng
        self.dir = directory
        self.journal_sync = bool(journal_sync)
        #: re-base sweep gate: an adopting host recovers a DEAD peer's
        #: chain with the sweep deferred (``recover(sweep_stale=
        #: False)``) so the fenced zombie segment stays on disk as
        #: evidence for the fenced-suffix audit (hostlease.py)
        self.sweep_stale = True
        # bounded-delay journal group commit (utils/journal.py): acks
        # still gate on a covering fsync (RPO 0 by construction), but
        # concurrent ops coalesce into one fsync per window
        self.group_commit_ms = float(group_commit_ms)
        os.makedirs(directory, exist_ok=True)
        self.base_path = os.path.join(directory, _base_name(self._htag))
        self.cid: str | None = None
        self.delta_paths: list[str] = []
        self._tip_epoch = None
        self._segment = 0
        #: exactly-once window reconstructed by :meth:`recover` from
        #: the journal's J_ACK records: {(tenant, rid): (op_kind, ok)}
        #: — heap-write entries carry a third payload-provenance
        #: element (handles u64, PR 16) — in ack order;
        #: ``ShermanServer.seed_dedup`` adopts it so a
        #: write retried across the crash re-acks its ORIGINAL result
        self.dedup_window: dict = {}
        # host-memory accountant source (obs/device.py): total on-disk
        # bytes of the chain's artifacts (base + deltas + journals) as
        # ``device.host_checkpoints_bytes``; weakref-bound so a closed
        # plane drops to 0 instead of pinning the directory scan.
        import weakref

        from sherman_tpu.obs import device as _dev

        def _chain_bytes(r=weakref.ref(self)) -> int:
            p = r()
            if p is None:
                return 0
            total = 0
            for f in glob.glob(os.path.join(p.dir, "*")):
                try:
                    total += os.path.getsize(f)
                except OSError:
                    pass  # artifact swept mid-scan
            return total

        _dev.get_accountant().register("checkpoints", _chain_bytes,
                                       kind="host")

    # -- artifact naming ------------------------------------------------------

    def _delta_path(self, k: int) -> str:
        return os.path.join(
            self.dir, f"delta{_host_tag(self._htag)}-{self.cid}-{k:06d}.npz")

    def _journal_path(self, k: int) -> str:
        return os.path.join(
            self.dir,
            f"journal{_host_tag(self._htag)}-{self.cid}-{k:06d}.wal")

    @staticmethod
    def _discover(directory: str, host_id: int | None = None):
        """-> (cid, delta_paths, journal_paths) of the on-disk chain
        anchored at this namespace's base; stale-cid artifacts are
        ignored.  ``host_id=None`` (the default) discovers the legacy
        un-tagged chain; an integer discovers that host's ``-h<id>-``
        chain only — one host's artifacts are invisible to another
        host's discovery by name."""
        tag = _host_tag(host_id)
        base = os.path.join(directory, _base_name(host_id))
        if not os.path.exists(base):
            raise FileNotFoundError(
                f"{directory}: no {_base_name(host_id)} — nothing to "
                "recover")
        epoch = CK._load_arrays(base, keys=("epoch",)).get("epoch")
        if epoch is None:
            raise CK.CheckpointCorruptError(
                f"{base}: base carries no epoch (pre-recovery-plane "
                "artifact) — cannot anchor a chain")
        cid = _cid_of(epoch)
        deltas = sorted(glob.glob(
            os.path.join(directory, f"delta{tag}-{cid}-*.npz")))
        journals = sorted(glob.glob(
            os.path.join(directory, f"journal{tag}-{cid}-*.wal")))
        return cid, deltas, journals

    def _sweep_stale(self) -> int:
        """Remove artifacts whose cid is not the live chain's (a
        superseded chain after a re-base).  Host-scoped: with
        ``hosts > 1`` only THIS host's ``-h<id>-`` namespace is swept —
        another host's live chain (same directory, different tag, its
        own cids) is never this host's to judge."""
        tag = _host_tag(self._htag)
        n = 0
        for f in glob.glob(os.path.join(self.dir, f"delta{tag}-*.npz")) \
                + glob.glob(os.path.join(self.dir,
                                         f"journal{tag}-*.wal")):
            name = os.path.basename(f)
            if self._htag is None and name.split("-")[1].startswith("h"):
                # legacy sweep never touches host-tagged chains (a cid
                # is 8 hex digits — it can never start with 'h')
                continue
            if self.cid is not None and f"-{self.cid}-" in name:
                continue
            try:
                os.unlink(f)
                n += 1
            except OSError:
                pass
        return n

    # -- saving ---------------------------------------------------------------

    def _rotate_journal(self, k: int) -> None:
        """Start journal segment ``k`` (ops after chain link ``k``).
        The previous segment's J_ACK records are NOT state (no
        checkpoint captures them), so they are carried FORWARD into
        the fresh segment — the exactly-once window stays
        reconstructible across any number of rotations, bounded by
        ``ack_carry`` most-recent entries.

        Rotation does NOT delete retired segments — that is
        :meth:`_sweep_retired_segments`, called only AFTER the chain
        artifact covering their ops is durable.  Unlinking here would
        open a crash window (rotate, crash before the save lands: the
        retired ops exist nowhere on disk), while leaving an
        overlapping segment merely replays redundantly — convergent
        by the module contract."""
        old = self.eng.journal
        fresh = J.Journal(
            self._journal_path(k), sync=self.journal_sync,
            group_commit_ms=self.group_commit_ms)
        # attach BEFORE closing the old segment: a live dispatcher's
        # appends race this rotation, and an append must always find
        # an OPEN journal (old until the swap, fresh after)
        self.eng.attach_journal(fresh)
        self._segment = k
        if old is not None:
            old.close()
            try:
                # provenance-bearing entries (heap writes, PR 16)
                # carry forward whole — re-encoding preserves handles
                carry = J.read_acks(old.path)
                acks = list(carry.values())[-self.ack_carry:] \
                    if self.ack_carry > 0 else []
                if acks:
                    fresh.append_acks(acks)
            except (OSError, J.JournalCorruptError):
                pass  # an unreadable retiring segment loses only dedup
                # coverage (retries re-apply idempotently), never state

    def _sweep_retired_segments(self) -> None:
        """Delete every journal segment other than the live one —
        only once the chain artifact capturing their ops is DURABLE
        (after a base/delta save, never at rotation time)."""
        for f in glob.glob(os.path.join(
                self.dir,
                f"journal{_host_tag(self._htag)}-{self.cid}-*.wal")):
            if f != self._journal_path(self._segment):
                try:
                    os.unlink(f)
                except OSError:
                    pass

    def journal_frontier(self) -> tuple[str, int]:
        """The durable journal frontier ``(live segment path, size)``
        — the coverage token the replication plane's quorum acks and
        promotion fence point resolve against (PR 18).  Appends fsync
        before returning, so a frontier captured AFTER an engine op
        returned bounds every byte of that op's records; a follower
        tailer whose consumed ``(segment, offset)`` reaches it holds
        everything acked so far."""
        if self.cid is None:
            raise StateError("no chain yet: checkpoint_base() first")
        path = self._journal_path(self._segment)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        return (path, int(size))

    def checkpoint_base(self) -> dict:
        """Full checkpoint -> new chain (new cid); sweeps the superseded
        chain's artifacts and starts journal segment 1."""
        self.eng.flush_parents()  # deferred parent entries are state
        epoch = CK.checkpoint(self.cluster, self.base_path)
        self.cid = _cid_of(epoch)
        self._tip_epoch = epoch
        self.delta_paths = []
        if self.sweep_stale:
            self._sweep_stale()
        self._rotate_journal(1)
        # the base save above is already durable: retired segments of
        # this chain (none on a fresh chain) can go now
        self._sweep_retired_segments()
        obs.record_event("recovery.checkpoint_base", cid=self.cid,
                         bytes=os.path.getsize(self.base_path))
        return {"path": self.base_path, "cid": self.cid,
                "bytes": os.path.getsize(self.base_path)}

    def checkpoint_delta(self) -> dict:
        """Delta link: journal rotation, THEN only the pages written
        since the previous link.  Falls back to :meth:`checkpoint_base`
        when no chain exists yet.

        Rotation runs FIRST — the live-dispatcher ordering (PR 15): an
        op racing this checkpoint then lands in the NEW segment and
        replays convergently over the link (redundant, never wrong —
        the module docstring's overlap rule).  Rotating after the
        snapshot instead would let a racing op apply after the
        snapshot yet journal into the RETIRING segment — silent
        RPO > 0 under a concurrent writer (the serving front door's
        whole shape) once that segment is swept.  The retired segment
        is deleted only AFTER the delta artifact is durable: a crash
        in between leaves BOTH segments on disk, and recover() replays
        the overlap convergently — never a window where the retired
        ops exist nowhere.  ``checkpoint_base`` still requires a
        quiesced writer stream (its rotation needs the new chain id,
        which only exists after the save)."""
        if self.cid is None:
            return self.checkpoint_base()
        k = len(self.delta_paths) + 1
        self._rotate_journal(k + 1)
        self.eng.flush_parents()
        path = self._delta_path(k)
        info = CK.checkpoint_delta(self.cluster, path,
                                   parent_epoch=self._tip_epoch)
        self.delta_paths.append(path)
        self._tip_epoch = info["epoch"]
        # the delta (capturing every op in the retired segment) is
        # durable: NOW the retired segment can go
        self._sweep_retired_segments()
        info["path"] = path
        obs.record_event("recovery.checkpoint_delta", cid=self.cid,
                         link=k, pages=int(info.get("pages", -1)))
        return info

    def close(self) -> None:
        if self.eng.journal is not None:
            self.eng.journal.close()
            self.eng.attach_journal(None)

    # -- full recovery --------------------------------------------------------

    @classmethod
    def recover(cls, directory: str, mesh=None, batch_per_node: int = 512,
                tcfg=None, journal_sync: bool = True,
                attach_router: bool = True,
                group_commit_ms: float = 0.0,
                host_id: int = 0, hosts: int = 1,
                sweep_stale: bool = True):
        """Rebuild a serving engine from the on-disk chain + journal.

        restore(base + deltas) -> replay journal segments in order ->
        re-base (fresh chain capturing the replayed state).  Returns
        (plane, cluster, tree, eng, receipt) with the receipt carrying
        the per-phase wall times and replay counts — the drill turns
        these into the published RTO.  With ``hosts > 1`` this is ONE
        host's half of :meth:`recover_union` — it restores/replays/
        re-bases the ``-h<host_id>-`` chain namespace only.
        ``sweep_stale=False`` defers the re-base's stale-chain sweep:
        host adoption keeps the dead host's old segments on disk so
        the fenced zombie suffix stays auditable (hostlease.py).
        """
        from sherman_tpu.models.batched import BatchedEngine
        from sherman_tpu.models.btree import Tree

        htag = int(host_id) if int(hosts) > 1 else None
        t0 = time.perf_counter()
        cid, deltas, journals = cls._discover(directory, host_id=htag)
        cluster = CK.restore_chain(
            os.path.join(directory, _base_name(htag)), deltas, mesh=mesh)
        t_restore = time.perf_counter()
        tree = Tree(cluster)
        eng = BatchedEngine(tree, batch_per_node=batch_per_node, tcfg=tcfg)
        if attach_router:
            eng.attach_router()
        # value heap: re-attach + rebuild the allocator from the
        # restored region BEFORE replay (heap journal records rewrite
        # slabs at their recorded addresses through the attached heap)
        if cluster.cfg.heap_pages_per_node > 0:
            from sherman_tpu.models.value_heap import ValueHeap
            ValueHeap(eng).rebuild()
        replay_stats = {"records": 0, "rows": 0, "upserts": 0,
                        "deletes": 0, "segments": 0}
        # replay ALL live-chain segments ascending: in-order replay is
        # convergent, so a segment overlapping its checkpoint (crash
        # between save and rotation) is redundant, never wrong.  J_ACK
        # records ride along into the ack sink — the exactly-once
        # window reconstruction (later acks override earlier, matching
        # the front door's own last-writer window semantics).
        acks: list = []
        for seg in journals:
            st = J.replay(seg, eng, ack_sink=acks)
            for k2, v in st.items():
                replay_stats[k2] = replay_stats.get(k2, 0) + v
            replay_stats["segments"] += 1
        t_replay = time.perf_counter()
        plane = cls(cluster, tree, eng, directory,
                    journal_sync=journal_sync,
                    group_commit_ms=group_commit_ms,
                    host_id=host_id, hosts=hosts)
        plane.sweep_stale = bool(sweep_stale)
        for rid, tenant, op, ok, *prov in acks:
            plane.dedup_window[(tenant, rid)] = (op, ok, *prov)
        plane.checkpoint_base()  # re-base: fresh chain, stale cid swept
        t_end = time.perf_counter()
        _OBS_RECOVERS.inc()
        obs.record_event(
            "recovery.recover", cid=cid, deltas=len(deltas),
            host=int(host_id), segments=replay_stats["segments"],
            replayed_records=replay_stats["records"],
            total_ms=round((t_end - t0) * 1e3, 1))
        chain_info = {"cid": cid, "deltas": len(deltas)}
        if int(hosts) > 1:
            # hosts=1 receipts stay byte-identical to pre-plane builds
            chain_info["host"] = int(host_id)
        receipt = {
            "chain": chain_info,
            "restore_ms": round((t_restore - t0) * 1e3, 1),
            "replay_ms": round((t_replay - t_restore) * 1e3, 1),
            "rebase_ms": round((t_end - t_replay) * 1e3, 1),
            "total_ms": round((t_end - t0) * 1e3, 1),
            "replay": replay_stats,
        }
        return plane, cluster, tree, eng, receipt

    @classmethod
    def recover_union(cls, directory: str, hosts: int, mesh=None,
                      batch_per_node: int = 512, tcfg=None,
                      journal_sync: bool = True,
                      attach_router: bool = True,
                      group_commit_ms: float = 0.0):
        """Union recovery over every host's chain in one directory —
        the multihost service plane's crash exit.  Each host's chain is
        restored + replayed INDEPENDENTLY in its own (cid, seq) order
        (keys are partitioned by owner host, so no cross-host record
        ordering exists to get wrong); a torn tail on one host's live
        segment truncates only that host's replay, exactly as the
        single-chain contract, and never blocks another host's.

        ALL-OR-TYPED: a host whose chain is missing (no base) or
        corrupt (a skipped/missing delta link, a mid-file journal CRC
        failure) raises the underlying typed error
        (:class:`FileNotFoundError` /
        :class:`~sherman_tpu.utils.checkpoint.CheckpointCorruptError` /
        :class:`~sherman_tpu.utils.journal.JournalCorruptError`) —
        never a silently partial union with one host's acked ops gone.

        -> (contexts, receipt): ``contexts[h]`` is host ``h``'s
        (plane, cluster, tree, eng, receipt) exactly as
        :meth:`recover` returns; ``receipt`` carries the per-host
        chains + summed replay counts."""
        if int(hosts) < 2:
            raise StateError(
                f"recover_union wants hosts >= 2 (got {hosts}); a "
                "single-host directory is recover()'s job")
        t0 = time.perf_counter()
        contexts = []
        for h in range(int(hosts)):
            contexts.append(cls.recover(
                directory, mesh=mesh, batch_per_node=batch_per_node,
                tcfg=tcfg, journal_sync=journal_sync,
                attach_router=attach_router,
                group_commit_ms=group_commit_ms,
                host_id=h, hosts=hosts))
        replay = {}
        for ctx in contexts:
            for k, v in ctx[4]["replay"].items():
                replay[k] = replay.get(k, 0) + v
        receipt = {
            "hosts": int(hosts),
            "chains": [ctx[4]["chain"] for ctx in contexts],
            "replay": replay,
            "per_host_ms": [ctx[4]["total_ms"] for ctx in contexts],
            "total_ms": round((time.perf_counter() - t0) * 1e3, 1),
        }
        obs.record_event("recovery.recover_union", hosts=int(hosts),
                         replayed_records=replay.get("records", 0),
                         total_ms=receipt["total_ms"])
        return contexts, receipt

    # -- targeted repair (degraded mode's real exit) --------------------------

    def targeted_repair(self, scrubber=None, addrs=(),
                        verify_structure: bool = True) -> dict:
        """Restore only the damaged pages from the chain, re-certify,
        exit degraded, replay the journal to catch the repaired pages
        up.  ``addrs``: extra packed page addresses beyond the
        scrubber's flagged set.  Raises :class:`TargetedRepairFailed`
        (engine stays degraded) when the scrub pass does not come back
        clean — the caller falls back to :meth:`recover`.
        """
        from sherman_tpu.models.validate import scrub_pass
        from sherman_tpu.ops import bits
        from sherman_tpu.parallel import dsm as D

        if self.cid is None:
            raise StateError("no chain: checkpoint_base() first")
        t0 = time.perf_counter()
        damaged = sorted(set(int(a) for a in addrs)
                         | (set(scrubber.flagged) if scrubber is not None
                            else set()))
        if not damaged:
            return {"pages": 0, "ok": True, "repair_ms": 0.0}
        obs.record_event("recovery.targeted_repair_begin",
                         pages=len(damaged),
                         addrs=[hex(a) for a in damaged[:16]])
        P = self.cluster.cfg.pages_per_node
        rows = [bits.addr_node(a) * P + bits.addr_page(a) for a in damaged]
        pages = CK.read_chain_rows(self.base_path, self.delta_paths, rows)
        # PAGE-VERSION-AWARE repair: the chain's content is only valid
        # for a page whose live version still matches the chain tip.  A
        # page legally REWRITTEN since the tip (split, reclaim-reuse —
        # its front version moved past the chain's; min of the live
        # pair, because torn-version damage only raises one half) or
        # ALLOCATED after it (chain front version 0) must not be
        # blind-restored — resurrecting a pre-split image beside its
        # live sibling corrupts the chain shape (duplicate coverage,
        # double in-degree) in a way the local scrub pass cannot even
        # see.  Such pages are repaired IN PLACE instead: heal the
        # version pair, clear the violating slots, and re-upsert any
        # chain-tip key the damage dropped (``_stale_candidates``) —
        # post-tip ops replay from the journal afterwards, so the
        # convergence argument is recover()'s own.
        live = self.tree.dsm.read_pages(damaged)
        restore_idx, stale_idx = [], []
        for i in range(len(damaged)):
            chain_fv = int(pages[i][C.W_FRONT_VER])
            live_ver = min(int(live[i][C.W_FRONT_VER]),
                           int(live[i][C.W_REAR_VER]))
            # the version test alone only defends against RAISING
            # damage (a zeroed/lowered version half on a since-split
            # page would read as restorable); require the page's
            # structural identity — level, fences, sibling — to still
            # match the chain image too.  Every legal structural
            # rewrite (split, reclaim absorb) changes these WITH a
            # version bump, so a mismatch means the chain image is for
            # a different incarnation of the page.  (Damage to the
            # header words themselves also lands here: the in-place
            # patch cannot mend headers, so the scrub re-certify fails
            # typed into the full-restore fallback — capability given
            # up for never-wrong.)
            same_identity = all(
                int(pages[i][w]) == int(live[i][w])
                for w in (C.W_LEVEL, C.W_LOW_HI, C.W_LOW_LO,
                          C.W_HIGH_HI, C.W_HIGH_LO, C.W_SIBLING))
            if chain_fv != 0 and live_ver <= chain_fv and same_identity:
                restore_idx.append(i)
            else:
                stale_idx.append(i)
        write_rows = [
            {"op": D.OP_WRITE, "addr": damaged[i], "woff": 0,
             "nw": pages.shape[1], "payload": pages[i]}
            for i in restore_idx]
        candidates: dict[int, int] = {}
        for i in stale_idx:
            patched = self._patch_stale_page(live[i])
            if patched is not None:
                write_rows.append(
                    {"op": D.OP_WRITE, "addr": damaged[i], "woff": 0,
                     "nw": patched.shape[0], "payload": patched})
            # chain-tip content of EVERY stale page feeds the
            # resurrection candidate set: a cleared slot's pre-tip key
            # may now live under any damaged page's old range
            candidates.update(self._chain_leaf_entries(pages[i]))
        # raw DSM page writes: unaffected by the scrubber's quarantine
        # locks (those fence TREE writers), marked dirty for the next
        # delta by the host-step boundary union
        if write_rows:
            self.tree.dsm.write_rows(write_rows)
        _OBS_PAGES_REPAIRED.inc(len(damaged))
        if stale_idx:
            _OBS_STALE_REPAIRS.inc(len(stale_idx))
        # re-certify BEFORE exiting degraded: the whole pool must scrub
        # clean — a repair that only moved the damage fails typed here
        res = scrub_pass(self.tree)
        if res["violations"]:
            _OBS_REPAIR_FAILS.inc()
            obs.record_event("recovery.targeted_repair_failed",
                             pages=len(damaged),
                             violations=int(res["violations"]))
            raise TargetedRepairFailed(
                f"scrub still reports {res['violations']} violating "
                f"page(s) after repairing {len(damaged)} "
                f"({res['classes']}); falling back to full recover() "
                "is the documented exit")
        if scrubber is not None:
            scrubber.release_quarantine()
        # the hot-key tier is volatile across repair by contract: entry
        # versions of the restored pages rolled BACK to chain-tip values
        # (a state legal cached entries may coincidentally match), so
        # the cache restarts cold here; degraded entry already flushed
        # it, this pins the contract even for repairs driven without a
        # degraded transition
        if self.eng.leaf_cache is not None:
            self.eng.leaf_cache.flush()
        self.eng.exit_degraded()
        # content catch-up: ops acknowledged since the chain tip live in
        # the journal; replaying them (journal detached — replay must
        # not re-journal itself) rebuilds the repaired pages' lost
        # writes; untouched pages just re-apply their own values
        seg, self.eng.journal = self.eng.journal, None
        resurrected = 0
        try:
            if seg is not None:
                seg.close()
            # resurrection pass for stale-chain (version-ahead) pages:
            # a cleared slot may have dropped a PRE-tip key that no
            # journal record will replay; re-upsert every chain-tip
            # candidate that is absent from the live tree NOW.  Runs
            # with the journal DETACHED and BEFORE the replay: a
            # journaled resurrection would replay the stale tip value
            # AFTER the segment's newer records (regression), while in
            # this order post-tip ops win — recover()'s own convergence
            # argument.  A key deleted post-tip comes back briefly and
            # the replayed delete removes it again.
            if candidates:
                ck = np.asarray(sorted(candidates), np.uint64)
                _, found = self.eng.search(ck)
                miss = ck[~found]
                if miss.size:
                    st = self.eng.insert(miss, np.asarray(
                        [candidates[int(k)] for k in miss], np.uint64))
                    # a resurrection that could not apply (its leaf's
                    # lock held by a live lease past the retry budget)
                    # is a LOST pre-tip key — failing silently here
                    # while reporting ok=True would be exactly the
                    # wrong-answer class this module exists to prevent:
                    # re-enter degraded and fail typed (full recover()
                    # is the documented fallback)
                    if st["lock_timeouts"]:
                        _OBS_REPAIR_FAILS.inc()
                        self.eng.enter_degraded(
                            "targeted repair: resurrection upserts "
                            f"lock-timed-out on {st['lock_timeouts']} "
                            "key(s)")
                        raise TargetedRepairFailed(
                            f"{st['lock_timeouts']} resurrection "
                            "key(s) could not apply (page lock held by "
                            "a live lease past the retry budget); "
                            "falling back to full recover() is the "
                            "documented exit")
                    resurrected = int(miss.size)
                    _OBS_RESURRECTED.inc(resurrected)
            if os.path.exists(self._journal_path(self._segment)):
                acks: list = []
                replay_stats = J.replay(
                    self._journal_path(self._segment), self.eng,
                    ack_sink=acks)
                for rid, tenant, op, ok, *prov in acks:
                    self.dedup_window[(tenant, rid)] = (op, ok, *prov)
            else:
                replay_stats = {"records": 0, "rows": 0}
        finally:
            # reopen the segment for appends (replay only truncated torn
            # tails; the records themselves stay — recovery replays them
            # again idempotently if we crash later)
            self.eng.attach_journal(J.Journal(
                self._journal_path(self._segment),
                sync=self.journal_sync,
                group_commit_ms=self.group_commit_ms))
        out = {"pages": len(damaged), "ok": True,
               "stale_pages": len(stale_idx),
               "resurrected": resurrected,
               "replay": replay_stats,
               "repair_ms": round((time.perf_counter() - t0) * 1e3, 1)}
        if verify_structure:
            from sherman_tpu.models.validate import check_structure_device
            out["structure"] = check_structure_device(self.tree)
        _OBS_REPAIRS.inc()
        obs.record_event("recovery.targeted_repair", pages=len(damaged),
                         stale_pages=len(stale_idx),
                         repair_ms=out["repair_ms"],
                         replayed_records=int(
                             out["replay"].get("records", 0)))
        return out

    # -- stale-page (version-ahead) repair helpers ----------------------------

    @staticmethod
    def _patch_stale_page(live_pg: np.ndarray) -> np.ndarray | None:
        """In-place repair image for a LEAF page whose live version is
        ahead of the chain tip: heal a torn front/rear page-version
        pair (both := the max — a rewrite never lowers the version) and
        clear every violating slot (torn fver/rver halves, live slots
        outside the page fence).  Internal pages return ``None`` —
        entry order cannot be locally reconstructed, so their damage is
        left for the scrub re-certify to judge (typed fallback when it
        does not come back clean)."""
        pg = np.array(live_pg, np.int32)
        if int(pg[C.W_LEVEL]) != 0:
            return None
        ver = max(int(pg[C.W_FRONT_VER]), int(pg[C.W_REAR_VER]))
        pg[C.W_FRONT_VER] = pg[C.W_REAR_VER] = ver
        LC = C.LEAF_CAP
        vw = pg[C.L_VER_W:C.L_VER_W + LC].view(np.uint32)
        fver = (vw >> np.uint32(16)) & np.uint32(0xFFFF)
        rver = vw & np.uint32(0xFFFF)
        torn = fver != rver
        # live-slot fence containment (uint64 keys from the hi/lo pairs)
        from sherman_tpu.ops import bits as _b
        skeys = _b.pairs_to_keys(pg[C.L_KHI_W:C.L_KHI_W + LC],
                                 pg[C.L_KLO_W:C.L_KLO_W + LC])
        lo = _b.pair_to_key(int(pg[C.W_LOW_HI]), int(pg[C.W_LOW_LO]))
        hi = _b.pair_to_key(int(pg[C.W_HIGH_HI]), int(pg[C.W_HIGH_LO]))
        s_live = (fver == rver) & (fver != 0)
        oob = s_live & ((skeys < np.uint64(lo)) | (skeys >= np.uint64(hi)))
        pg[C.L_VER_W:C.L_VER_W + LC][torn | oob] = 0
        return pg

    @staticmethod
    def _chain_leaf_entries(chain_pg: np.ndarray) -> dict[int, int]:
        """{key: value} of every live slot of a chain-tip LEAF image —
        the resurrection candidate pool for stale-page repair.  Empty
        for dead/internal chain rows (a page allocated after the tip
        has no chain content to resurrect)."""
        if int(chain_pg[C.W_FRONT_VER]) == 0 \
                or int(chain_pg[C.W_LEVEL]) != 0:
            return {}
        from sherman_tpu.ops import layout
        return {int(k): int(v)
                for k, v, _ in layout.np_leaf_entries(
                    np.asarray(chain_pg, np.int32))}
