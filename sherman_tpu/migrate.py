"""Online elastic reshard — grow/shrink the pool under traffic.

``utils/reshard.py`` already rewrites an N-node pool onto M nodes —
OFFLINE, on a checkpoint, with the cluster down for the whole
transform.  This module promotes that transform to an *online*
operation: a background :class:`Migrator` walks the live pool in
bounded batches while the engine keeps serving, so the downtime of an
N→M resize shrinks from "checkpoint + rewrite + restore" to one brief
quiesced cutover whose work is proportional to the pages written since
their copy, not the pool.

The protocol, batch by batch (the scrubber's quarantine pattern):

1. **lock**: the batch's page lock words are CAS-acquired under the
   migrator's OWN live lease (``cluster.register_client``), so no
   writer can touch a page mid-copy — device inserts that lose the race
   report ``ST_LOCKED`` and retry through the engine's bounded
   lock-retry/backoff budget (typed ``ST_LOCK_TIMEOUT`` at exhaustion,
   never a wrong answer); host writers spin exactly as they do against
   the scrubber's quarantine.  A word held by a LIVE foreign lease is
   skipped this batch (``migrate.lock_conflicts``) and retried later; a
   DEAD holder is revoked through the one revocation policy
   (``Tree._try_revoke_lease``).
2. **copy**: the locked pages are read in one batched step and staged
   host-side, verbatim — address rewriting is deferred to cutover so
   the staged bytes stay comparable with the live pool.
3. **journal**: the batch is persisted as a CRC-tagged artifact
   (``migbatch-<mid>-<seq>.npz``, atomic tmp+fsync+replace) BEFORE the
   locks release — a crash mid-migration keeps every completed batch,
   and :meth:`Migrator.resume` reloads them, folds every staged row
   into the re-verify queue (post-crash journal replay may have
   rewritten anything), and continues instead of restarting.
4. **release + invalidate**: the locks are freed in one step and the
   hot-key tier scatter-invalidates the batch's pages
   (``models/leaf_cache.py`` — the volatile-across-recovery contract
   extended to migration batches).

Writes AFTER a page's copy are caught by the DSM's dirty tracking: the
migrator folds ``dirty_rows()`` into a conservative re-copy set on
every batch, and a registered **dirty sink** (``DSM.add_dirty_sink``)
hands it the rows a delta checkpoint is about to consume-and-clear —
the migration epoch rides the delta-checkpoint chain instead of racing
it.  :meth:`Migrator.finish` then re-stages the dirtied pages under a
brief quiesced window, recomputes the live set + old→new address map
from the CURRENT allocator state, and feeds the staged image through
``utils.reshard.reshard_arrays`` — the SAME transform the offline CLI
runs, so the emitted M-node checkpoint is bit-identical to
``tools/reshard.py`` applied to the final logical state (the drill's
identity pin; ``tools/reshard_drill.py`` / ``bench.py
--reshard-drill``).

Observability: the ``migrate.`` pull collector (pages_moved, batches,
retries, lock_conflicts, resume_count, epoch, …) plus flight-recorder
events for begin/batch/resume/cutover and a debounced black-box dump
on abort.  Knob: ``SHERMAN_MIGRATE_BATCH_PAGES`` (pages locked+copied
per batch — the p99-spike vs migration-throughput dial).

Single-process meshes only, like the recovery plane (multihost
deployments resize via the offline checkpoint path).
"""

from __future__ import annotations

import glob
import os

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import (ConfigError, MultiprocessUnsupportedError,
                                ShermanError, StateError)
from sherman_tpu.obs import recorder as FR
from sherman_tpu.ops import bits
from sherman_tpu.parallel import dsm as D
from sherman_tpu.utils import checkpoint as CK
from sherman_tpu.utils import reshard as RS

# CAS attempts per lock word before deferring the word's pages to a
# later batch (same bound as the scrubber's quarantine: a legitimately
# held lock drains within a step or two).
_LOCK_TRIES = 8
# Quiesced-cutover convergence budget: finish() re-verifies the staged
# image against the live pool after each delta pass; mismatches still
# appearing after this many rounds mean a writer (or an unreleasable
# quarantine) is racing the cutover — abort typed, never emit a pool
# that silently lost writes.
_FINISH_VERIFY_ROUNDS = 3


def _batch_pages_default() -> int:
    """``SHERMAN_MIGRATE_BATCH_PAGES``: pages locked + copied per
    migration batch (default 256).  Smaller batches bound the per-batch
    lock-hold window (the read-path p99 spike); larger batches finish
    the copy in fewer lock/journal round trips."""
    v = os.environ.get("SHERMAN_MIGRATE_BATCH_PAGES", "").strip()
    if not v:
        return 256
    try:
        n = int(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_MIGRATE_BATCH_PAGES={v!r}: want a positive int")
    if n <= 0:
        raise ConfigError(f"SHERMAN_MIGRATE_BATCH_PAGES={n}: want > 0")
    return n


class MigrationAborted(ShermanError, RuntimeError):
    """Typed migration abort: the engine degraded mid-migration, the
    cutover could not quiesce, or the migration state was explicitly
    abandoned.  The SOURCE pool is untouched (the migrator only ever
    holds lock words and writes artifacts) — serving continues; the
    staged artifacts remain on disk for a later :meth:`Migrator.resume`
    or are swept by the next :meth:`Migrator.start`."""


class Migrator:
    """Background page migration of a live N-node pool toward M nodes.

    Lifecycle: construct → :meth:`start` → interleave :meth:`step` with
    traffic (the scrubber's ``tick`` shape — one bounded batch between
    engine steps) until :attr:`copied_all` → :meth:`finish` (brief
    quiesced cutover, emits the M-node checkpoint) → restore the
    emitted checkpoint on the M-node mesh.  After a crash:
    ``RecoveryPlane.recover`` the source, then :meth:`resume` and keep
    going — completed batches are re-verified, not re-done from
    scratch.
    """

    def __init__(self, cluster, tree, eng, target_nodes: int,
                 directory: str, *,
                 target_pages_per_node: int | None = None,
                 target_locks_per_node: int | None = None,
                 batch_pages: int | None = None):
        if cluster.dsm.multihost:
            # migration's lock-leased batch copies assume one driver
            # per POOL; the multihost service plane (PR 19) scopes a
            # migration to one host context at a time (each host's
            # chain namespace re-bases independently) — driving the
            # copy loop from N processes at once stays out of scope
            raise MultiprocessUnsupportedError(
                "online migration drives one process per pool: run it "
                "inside a single host context (the multihost service "
                "plane migrates per-host contexts one at a time)")
        if not 1 <= int(target_nodes) <= C.MAX_MACHINE:
            raise ConfigError(f"target_nodes={target_nodes} out of range")
        self.cluster = cluster
        self.tree = tree
        self.eng = eng
        self.dsm = cluster.host_dsm
        self.cfg = cluster.cfg
        self.target_nodes = int(target_nodes)
        self.target_pages_per_node = target_pages_per_node
        self.target_locks_per_node = target_locks_per_node
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.batch_pages = (batch_pages if batch_pages is not None
                            else _batch_pages_default())
        # the migrator's locks ride its OWN registered (live) lease, so
        # lock-lease recovery never revokes a mid-copy hold — the
        # scrubber's quarantine contract exactly
        self.ctx = cluster.register_client(replicated=True)
        self.mid: str | None = None
        self.seq = 0
        self.started = False
        self.finished = False
        self.aborted: str | None = None
        # Staging store: ONE flat pool-shaped array + a staged-row mask
        # (lazily allocated at start/resume).  A per-row dict of small
        # arrays would roughly double the host footprint in object
        # overhead and force Python-loop assembly/verification at
        # cutover — at the 100 M-key config (4.19 M pages) the flat
        # form IS the cutover image and verifies vectorized.  _dirt =
        # rows written since migration start (conservative: dirty polls
        # + the clear sink), re-staged by finish()'s delta passes.
        self._staged_arr: np.ndarray | None = None
        self._staged_mask: np.ndarray | None = None
        self._pending: list[int] = []
        self._dirt: set[int] = set()
        self._sink = self._on_dirty_clear
        # migrate.* accounting (plain int adds on the batch path; the
        # collector below materializes them at PULL time only)
        self.pages_moved = 0
        self.batches = 0
        self.retries = 0            # re-staged (dirtied-after-copy) pages
        self.lock_conflicts = 0     # words skipped: held by a live lease
        self.resume_count = 0
        self.resume_verified = 0    # staged pages proven clean on resume
        self.recopies_clean = 0     # non-resume re-copies proven clean
        #                             (conservative dirt that never
        #                             changed content) — kept separate
        #                             so resume_verified > 0 really
        #                             means a resume happened
        import weakref
        ref = weakref.ref(self)

        def _collect():
            m = ref()
            if m is None:
                return {}
            return {
                "pages_moved": m.pages_moved,
                "batches": m.batches,
                "retries": m.retries,
                "lock_conflicts": m.lock_conflicts,
                "resume_count": m.resume_count,
                "resume_verified": m.resume_verified,
                "recopies_clean": m.recopies_clean,
                "epoch": m.seq,
                "staged_pages": m.staged_pages,
                "dirt_backlog": len(m._dirt),
                "in_progress": int(m.started and not m.finished
                                   and m.aborted is None),
            }

        obs.register_collector("migrate", _collect)

    # -- artifact naming ------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "migrate-manifest.npz")

    def _batch_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"migbatch-{self.mid}-{seq:06d}.npz")

    def _sweep_stale(self) -> int:
        """Remove batch artifacts of a superseded migration id."""
        n = 0
        for f in glob.glob(os.path.join(self.dir, "migbatch-*.npz")):
            if self.mid is not None \
                    and f"-{self.mid}-" in os.path.basename(f):
                continue
            try:
                os.unlink(f)
                n += 1
            except OSError:
                pass
        return n

    # -- staging store --------------------------------------------------------

    def _ensure_staging(self) -> None:
        if self._staged_arr is None:
            rows = self.cfg.machine_nr * self.cfg.pages_per_node
            self._staged_arr = np.zeros((rows, C.PAGE_WORDS), np.int32)
            self._staged_mask = np.zeros(rows, bool)

    @property
    def staged_pages(self) -> int:
        """Pages with a staged copy (the ``migrate.staged_pages``
        gauge)."""
        return int(self._staged_mask.sum()) \
            if self._staged_mask is not None else 0

    def is_staged(self, row: int) -> bool:
        return bool(self._staged_mask is not None
                    and self._staged_mask[int(row)])

    # -- planning -------------------------------------------------------------

    def _live_rows_now(self) -> np.ndarray:
        """The CURRENT live-row set, by the same definition the offline
        transform uses (``utils.reshard.live_rows``) — allocator
        high-water marks, written pages only, free pool excluded."""
        cfg = self.cfg
        nxt = np.ones(cfg.machine_nr, np.int64)
        free = []
        for d in self.cluster.directories:
            nxt[d.node_id] = d.allocator.pages_used
            free += [bits.make_addr(d.node_id, p) & 0xFFFFFFFF
                     for p in d.allocator.free_pages_list]
        # only the W_FRONT_VER column crosses to the host (one narrow
        # materialization, not the whole pool)
        fv = np.asarray(self.dsm.pool[:, C.W_FRONT_VER])
        return RS.live_rows(fv, nxt, np.asarray(sorted(free), np.int64),
                            cfg.pages_per_node, cfg.machine_nr)

    def _refresh_plan(self) -> int:
        """Recompute the pending copy plan: live rows not yet staged
        (ascending — determinism across resumes).  Returns the pending
        count."""
        rows = self._live_rows_now()
        if self._staged_mask is not None and rows.size:
            rows = rows[~self._staged_mask[rows]]
        self._pending = rows.tolist()
        return len(self._pending)

    # -- dirty tracking -------------------------------------------------------

    def _on_dirty_clear(self, rows) -> None:
        """DSM dirty-sink hook: a checkpoint is about to consume-and-
        clear these rows — fold every staged one into the re-copy set
        so the clear cannot hide a post-copy write from the cutover.
        Runs inside every checkpoint save (registered obs-hot scope:
        plain loop, no per-call allocation)."""
        mask = self._staged_mask
        if mask is None:
            return
        dirt = self._dirt
        for r in rows:
            r = int(r)
            if mask[r]:
                dirt.add(r)

    def _poll_dirt(self) -> None:
        """Fold the DSM's cumulative dirty rows into the re-copy set
        (per-batch hot hook — same allocation-free shape as the sink)."""
        mask = self._staged_mask
        if mask is None:
            return
        dirt = self._dirt
        for r in self.dsm.dirty_rows():
            r = int(r)
            if mask[r]:
                dirt.add(r)

    # -- lifecycle ------------------------------------------------------------

    def _require_active(self) -> None:
        if not self.started:
            raise StateError("migration not started: call start() first")
        if self.finished:
            raise StateError("migration already finished")
        if self.aborted is not None:
            raise MigrationAborted(
                f"migration {self.mid} aborted: {self.aborted}")
        if self.eng.degraded:
            self.abort(f"engine degraded: {self.eng.degraded_reason}")
            raise MigrationAborted(
                f"migration {self.mid} aborted: engine degraded "
                f"({self.eng.degraded_reason})")

    def start(self) -> dict:
        """Begin a new migration: fresh mid, manifest persisted, stale
        artifacts of superseded migrations swept, copy plan computed."""
        if self.started:
            raise StateError("migration already started")
        if self.eng.degraded:
            raise MigrationAborted(
                "refusing to start a migration on a degraded engine")
        n = self._refresh_plan()
        # advisory capacity check (the live set can still grow, so the
        # authoritative check stays in reshard_arrays at cutover): an
        # OBVIOUSLY undersized target must fail BEFORE hours of
        # lock/copy/journal work — and before any state is persisted
        if self.target_pages_per_node is not None:
            cap = self.target_nodes * (self.target_pages_per_node - 1)
            if n > cap:
                raise ConfigError(
                    f"{n} live pages cannot fit {self.target_nodes} "
                    f"node(s) x {self.target_pages_per_node} pages "
                    "(page 0 per node reserved): raise "
                    "target_pages_per_node before migrating")
        self.mid = f"{int(np.frombuffer(os.urandom(4), np.uint32)[0]):08x}"
        self._sweep_stale()
        man = dict(
            mid=np.frombuffer(self.mid.encode(), np.uint8).copy(),
            target=np.asarray(
                [self.target_nodes, self.target_pages_per_node or 0,
                 self.target_locks_per_node or 0], np.int64),
            src_cfg=np.frombuffer(CK.cfg_to_json(self.cfg), np.uint8),
        )
        man["integrity"] = CK._integrity(man)
        CK._savez_atomic(self._manifest_path(), 0, **man)
        self._ensure_staging()
        self.started = True
        # register on the RAW DSM (host_dsm is the same object on the
        # single-process meshes migration supports)
        self.cluster.dsm.add_dirty_sink(self._sink)
        obs.record_event("migrate.begin", mid=self.mid,
                         src_nodes=self.cfg.machine_nr,
                         target_nodes=self.target_nodes, live_pages=n)
        return {"mid": self.mid, "live_pages": n}

    def abort(self, reason: str) -> None:
        """Abandon the migration (typed; serving is unaffected).  The
        black box dumps — an abort is exactly the moment a postmortem
        starts from."""
        if self.aborted is None:
            self.aborted = reason
            obs.counter("migrate.aborts").inc()
            FR.record_event("migrate.abort", mid=self.mid or "",
                            reason=reason)
            FR.auto_dump("migrate_abort")
            self.cluster.dsm.remove_dirty_sink(self._sink)

    @property
    def copied_all(self) -> bool:
        """True when every currently-live page has a staged copy (the
        signal to call :meth:`finish`; new allocations or post-copy
        writes after this flip are caught by finish's delta passes)."""
        return self.started and not self._pending

    # -- the batch protocol ---------------------------------------------------

    def _acquire_locks(self, addrs: list[int]) -> tuple[list[int], set[int]]:
        """CAS-acquire the lock words covering ``addrs`` under the
        migrator's lease.  -> (copyable addrs, held words).  Pages whose
        word stays held by a live foreign lease are deferred (counted in
        ``lock_conflicts``); dead holders are revoked."""
        by_word: dict[int, list[int]] = {}
        for a in addrs:
            by_word.setdefault(self.tree._lock_word_addr(a), []).append(a)
        held: set[int] = set()
        ok_addrs: list[int] = []
        for la, pages in by_word.items():
            got = False
            for _ in range(_LOCK_TRIES):
                old, won = self.dsm.cas(la, 0, 0, self.ctx.lease,
                                        space=D.SPACE_LOCK)
                if won or old == self.ctx.lease:
                    got = True
                    break
                # dead holder (e.g. wedged by the same fault storm the
                # drill injects): revoke through the one policy
                self.tree._try_revoke_lease(la, old)
            if got:
                held.add(la)
                ok_addrs.extend(pages)
            else:
                self.lock_conflicts += len(pages)
        return ok_addrs, held

    def _release_locks(self, held: set[int]) -> None:
        if held:
            self.dsm.write_rows([
                {"op": D.OP_WRITE_WORD, "addr": la, "woff": 0, "arg1": 0,
                 "space": D.SPACE_LOCK} for la in sorted(held)])

    def _stage_batch(self, rows: list[int], *, recopy: bool) -> dict:
        """One full batch protocol pass over ``rows``: lock → copy →
        journal → release → cache-invalidate.  Re-copies whose content
        is unchanged skip the artifact write (``resume_verified`` on
        resume passes, ``retries`` otherwise count the churn)."""
        P = self.cfg.pages_per_node
        addrs = [bits.make_addr(r // P, r % P) for r in rows]
        addrs, held = self._acquire_locks(addrs)
        if not addrs:
            return {"pages": 0, "deferred": len(rows)}
        try:
            got_rows = [bits.addr_node(a) * P + bits.addr_page(a)
                        for a in addrs]
            pages = self.dsm.read_pages(addrs)
            changed_rows, changed_pages = [], []
            arr, mask = self._staged_arr, self._staged_mask
            for r, pg in zip(got_rows, pages):
                if mask[r] and np.array_equal(arr[r], pg):
                    if recopy:
                        if self.resume_count:
                            self.resume_verified += 1
                        else:
                            self.recopies_clean += 1
                    continue
                arr[r] = pg
                mask[r] = True
                changed_rows.append(r)
                changed_pages.append(pg)
                if recopy:
                    self.retries += 1
            if changed_rows:
                # journal BEFORE the locks release: a crash after this
                # point keeps the batch; before it, the locks were never
                # released with an unjournaled copy outstanding
                self.seq += 1
                art = dict(
                    mid=np.frombuffer(self.mid.encode(), np.uint8).copy(),
                    seq=np.asarray([self.seq], np.int64),
                    rows=np.asarray(changed_rows, np.int64),
                    pages=np.asarray(changed_pages, np.int32),
                )
                art["integrity"] = CK._integrity(art)
                CK._savez_atomic(self._batch_path(self.seq), 0, **art)
        finally:
            self._release_locks(held)
        # the batch's rows are now clean as of this copy
        self._dirt.difference_update(got_rows)
        self.pages_moved += len(addrs)
        self.batches += 1
        # hot-key tier coherence: a migrating page's cached entries must
        # not outlive its batch (the volatile-across-recovery contract,
        # extended to migration — scatter-invalidate, not a flush)
        if self.eng.leaf_cache is not None:
            self.eng.leaf_cache.invalidate_pages(addrs)
        obs.record_event("migrate.batch", mid=self.mid, seq=self.seq,
                         pages=len(addrs), recopy=bool(recopy))
        return {"pages": len(addrs), "deferred": len(rows) - len(addrs)}

    def step(self, max_pages: int | None = None) -> dict:
        """One bounded migration batch between engine steps (the
        scrubber's ``tick`` shape).  Copies fresh pages from the plan;
        when the plan drains, reports idle (post-copy dirt is the
        cutover's job — re-staging it under traffic would churn).
        """
        self._require_active()
        n = max_pages or self.batch_pages
        if not self._pending:
            self._refresh_plan()  # splits allocate new live pages
        if not self._pending:
            return {"idle": True, "pages": 0,
                    "dirt_backlog": len(self._dirt)}
        batch, self._pending = self._pending[:n], self._pending[n:]
        # poll BEFORE the copy: dirt recorded up to here is captured by
        # the locked read below; dirt after it lands in a later poll.
        # (The poll is conservative bookkeeping, not load-bearing for
        # correctness — finish()'s own poll + the clear sink + the
        # row-by-row verify already close every hole — but keeping the
        # dirt set current per batch bounds the cutover's re-stage work
        # and keeps the dirt_backlog gauge honest.)
        self._poll_dirt()
        out = self._stage_batch(batch, recopy=False)
        if out["deferred"]:
            # deferred pages (live-held lock words) go back on the plan
            self._pending.extend(r for r in batch
                                 if not self._staged_mask[r])
        return out

    def run_to_copied(self, max_batches: int = 1_000_000) -> int:
        """Drive :meth:`step` until the plan drains (no traffic
        interleaving — tests and the drill's catch-up phases)."""
        n = 0
        while not self.copied_all and n < max_batches:
            r = self.step()
            n += 1
            if r.get("idle"):
                break
        return n

    # -- crash restart --------------------------------------------------------

    @classmethod
    def resume(cls, cluster, tree, eng, directory: str, *,
               batch_pages: int | None = None) -> "Migrator":
        """Rebuild a migrator from the on-disk migration state after a
        crash + source recovery: manifest + every readable batch
        artifact (CRC-verified; torn/corrupt ones are dropped — their
        pages just re-copy).  Every staged row is folded into the
        re-verify set: the crash's journal replay may have rewritten
        any page, so staged content is re-certified (clean rows count
        ``resume_verified``, rewritten ones re-stage) instead of
        trusted."""
        man = CK._load_arrays(os.path.join(directory,
                                           "migrate-manifest.npz"))
        mid = bytes(np.asarray(man["mid"])).decode()
        tgt = np.asarray(man["target"]).ravel()
        m = cls(cluster, tree, eng, int(tgt[0]), directory,
                target_pages_per_node=int(tgt[1]) or None,
                target_locks_per_node=int(tgt[2]) or None,
                batch_pages=batch_pages)
        m.mid = mid
        m._ensure_staging()
        m.started = True
        cluster.dsm.add_dirty_sink(m._sink)
        arts = sorted(glob.glob(os.path.join(directory,
                                             f"migbatch-{mid}-*.npz")))
        dropped = 0
        max_seq = 0
        for path in arts:
            try:
                z = CK._load_arrays(path)
            except CK.CheckpointCorruptError:
                dropped += 1
                continue
            max_seq = max(max_seq, int(np.asarray(z["seq"]).ravel()[0]))
            rows = np.asarray(z["rows"], np.int64)
            m._staged_arr[rows] = np.asarray(z["pages"], np.int32)
            m._staged_mask[rows] = True
        m.seq = max_seq
        # conservative: every staged page re-verifies against the
        # recovered pool (journal replay may have rewritten it)
        m._dirt.update(int(r) for r in np.nonzero(m._staged_mask)[0])
        m.resume_count += 1
        m._refresh_plan()
        obs.counter("migrate.resumes").inc()
        obs.record_event("migrate.resume", mid=mid,
                         staged=m.staged_pages, dropped_artifacts=dropped,
                         reverify=len(m._dirt))
        return m

    # -- cutover --------------------------------------------------------------

    def _verify_rows(self) -> list[int]:
        """Certification gather: every live row whose staged copy is
        absent or differs from the LIVE pool content right now.  One
        device-side gather of the live rows (O(live pages) — the
        cutover's one full sweep; the dirty tracking exists to make the
        RE-STAGE work proportional to writes, this check is what makes
        "zero lost writes" a measured property rather than a belief)."""
        import jax.numpy as jnp
        rows = self._live_rows_now()
        if not rows.size:
            return []
        live = np.asarray(self.dsm.pool[jnp.asarray(rows)])
        diff = ~self._staged_mask[rows] \
            | (self._staged_arr[rows] != live).any(axis=1)
        return [int(r) for r in rows[diff]]

    def finish(self, dst: str, *, hosts: int = 1) -> dict:
        """Quiesced cutover: flush deferred parents, re-stage the
        conservative dirt set (post-copy writes, resume re-verifies,
        late allocations), certify the staged image against the live
        pool row by row, then run the OFFLINE transform
        (``reshard_arrays``) over the staged image and emit the M-node
        checkpoint at ``dst``.

        The caller quiesces traffic for the duration (the single-driver
        serving shape makes this one call between batches); a writer
        racing the cutover — or a quarantine whose lock never frees —
        surfaces as verification mismatches past the convergence budget
        and aborts typed, never an emitted pool that silently lost
        writes."""
        self._require_active()
        import time
        t0 = time.perf_counter()
        self.eng.flush_parents()
        # value heap: stage the region FIRST, so the certification
        # against a fresh read at emit time below actually brackets
        # the whole cutover window (two adjacent reads would compare a
        # buffer to itself and could never catch a racing writer)
        heap_image = (self.dsm.heap_snapshot()
                      if self.dsm.heap is not None else None)
        # conservative delta pass: pre-cutover dirt + late allocations
        self._refresh_plan()
        self._poll_dirt()
        todo = sorted(set(self._pending) | self._dirt)
        self._pending = []
        for i in range(0, len(todo), self.batch_pages):
            self._stage_batch(todo[i:i + self.batch_pages], recopy=True)
        # certify (and repair) until the image IS the live pool
        for attempt in range(_FINISH_VERIFY_ROUNDS + 1):
            bad = self._verify_rows()
            if not bad:
                break
            if attempt == _FINISH_VERIFY_ROUNDS:
                self.abort("cutover could not quiesce: staged image "
                           f"kept diverging after {attempt} repair "
                           "rounds")
                raise MigrationAborted(
                    f"migration {self.mid}: cutover could not quiesce "
                    "(a writer or an unreleasable lock is racing "
                    "finish())")
            for i in range(0, len(bad), self.batch_pages):
                self._stage_batch(bad[i:i + self.batch_pages],
                                  recopy=True)
        self._dirt.clear()

        # the staged array IS the cutover image (no second pool-sized
        # copy): live rows hold their certified copies, everything else
        # is zero like a checkpoint's unwritten rows; the reserved meta
        # page (never in the live set) is read live into row 0
        cfg = self.cfg
        N = cfg.machine_nr
        image = self._staged_arr
        image[0] = self.dsm.read_page(bits.make_addr(0, 0))
        man = CK._manifest(self.cluster)
        # value heap certification: the image staged at cutover entry
        # must still BE the live region now that the pool has quiesced
        # (handles address the heap by global row, so the region copies
        # verbatim and the transform pads the node split — no handle
        # rewrite).  A heap writer racing the cutover lands between
        # the two reads and aborts typed, the pool verify's contract.
        if heap_image is not None:
            heap_live = self.dsm.heap_snapshot()
            if not np.array_equal(heap_image, heap_live):
                self.abort("cutover could not quiesce the value heap "
                           "(a heap writer is racing finish())")
                raise MigrationAborted(
                    f"migration {self.mid}: heap image diverged during "
                    "cutover (a writer is racing finish())")
        # counters LAST: nothing below issues another DSM op, so the
        # emitted totals equal a checkpoint taken right after finish —
        # the drill's offline-vs-online bit-identity pin needs that
        counters = np.asarray(self.dsm.counters)
        locks = np.zeros(N * cfg.locks_per_node, np.int32)
        arrays, new_cfg, summary = RS.reshard_arrays(
            man, image, locks, counters, self.target_nodes,
            pages_per_node=self.target_pages_per_node,
            locks_per_node=self.target_locks_per_node,
            heap=heap_image)
        RS.write_resharded(dst, arrays, new_cfg, hosts=hosts)
        self.finished = True
        self.cluster.dsm.remove_dirty_sink(self._sink)
        summary["mid"] = self.mid
        summary["heap_pages"] = (int(heap_image.shape[0])
                                 if heap_image is not None else 0)
        summary["pages_moved"] = self.pages_moved
        summary["batches"] = self.batches
        summary["retries"] = self.retries
        summary["lock_conflicts"] = self.lock_conflicts
        summary["resume_count"] = self.resume_count
        summary["resume_verified"] = self.resume_verified
        summary["recopies_clean"] = self.recopies_clean
        summary["cutover_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        summary["dst"] = dst
        obs.record_event("migrate.cutover", mid=self.mid,
                         live_pages=summary["live_pages"],
                         target_nodes=self.target_nodes,
                         cutover_ms=summary["cutover_ms"])
        return summary

    def close(self) -> None:
        """Detach from the DSM (idempotent); staged artifacts stay on
        disk for resume/sweep."""
        self.cluster.dsm.remove_dirty_sink(self._sink)
